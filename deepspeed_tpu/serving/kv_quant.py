"""Quantized KV-cache block storage (ISSUE 12).

Low-bit KV caches (KIVI, Liu et al. 2024) are near-lossless for decode
while doubling the tokens cached per HBM byte — and every serving layer
above the pool (radix prefix cache, preemption swap, the multi-replica
fabric) multiplies whatever capacity the KV layer provides. This module
owns the quantization math and the storage convention; the write/read
paths live in ops/attention.py (einsum) and ops/decode_step.py (fused
Pallas), and serving/kv_blocks.BlockKVPool allocates the pools.

Storage convention
------------------
A quantized pool is a PYTREE ``{"q": payload, "s": scales}`` instead of
one array:

  * ``payload`` keeps the exact unquantized pool shape
    ``[L, N+1, Hkv, bs/pair, Dh*pair]`` in the storage dtype (int8 or
    float8_e4m3fn) — the same token-pair packing, the same garbage
    sentinel row, the same block-table addressing;
  * ``scales`` is ``[L, N+1, Hkv, pair, bs/pair]`` bf16 — ONE symmetric
    scale per (layer, block, head, token), stored PAIR-GROUPED: token
    ``t`` of a block lives at ``[..., t % pair, t // pair]``, aligned
    with the packed payload's lane slices so the fused decode kernel
    indexes scales by SUBLANE (supported everywhere) instead of a
    strided lane slice (not portable across Mosaic versions).

Because the pool is a pytree, models never change: the cache dict rides
the layer-scan carry opaquely, jit programs take it as a normal operand
tree, and the zero-recompile invariant holds by construction — payloads
and scales are traced data exactly like the block table.

Scale granularity
-----------------
Per-token-per-head, NOT per-block: blocks are APPENDED to in place
(decode writes one token at a time into the tail block), and a
per-block scale fixed by earlier tokens would clip any later token with
a larger amplitude — or force an in-place requantization of the whole
block on every amax growth. A per-token scale is write-local: each
token's scale is computed from its own K/V row at store time and never
revised. Overhead: 2 bytes per (token, head, layer) against Dh payload
bytes — 3.1% at Dh=64, 1.6% at Dh=128.

Accuracy
--------
Symmetric round-to-nearest with the scale itself rounded to bf16 BEFORE
the payload divide (quantize and dequantize must share the identical
scale, or the rounding of the scale becomes a multiplicative bias).
Worst-case per-element relative error ~1/254 for int8; fp8 e4m3 carries
a ~2^-3 relative mantissa step at full scale. Greedy decode parity is
gated at >= 0.99 exact-match rate by tests/unit/serving/test_kv_quant.py
and the bench's ``serving_kv_quant`` mode.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from deepspeed_tpu.serving.errors import EngineConfigError

# storage dtype + symmetric quantization ceiling per kv_dtype name
_KV_DTYPES = {
    "int8": (jnp.int8, 127.0),
    "fp8": (jnp.float8_e4m3fn, 448.0),
}

SCALE_DTYPE = jnp.bfloat16

# floor on the stored scale: a zero K/V row must dequantize to zero
# without a 0/0 in the quantize divide. SHARED with the fused kernel's
# in-register quantizer (ops/decode_step._quantize_token) — the
# kernel-vs-einsum stored-byte bit-identity depends on both paths using
# the identical floor and qmax.
SCALE_FLOOR = 1e-8
_SCALE_FLOOR = SCALE_FLOOR


def normalize_kv_dtype(kv_dtype) -> Optional[str]:
    """Canonical kv_dtype name: ``None`` means unquantized (the pool
    stays in the engine's compute dtype)."""
    if kv_dtype in (None, "bf16", "bfloat16", "fp32", "float32"):
        return None
    if kv_dtype == "int8":
        return "int8"
    if kv_dtype in ("fp8", "float8", "float8_e4m3", "float8_e4m3fn"):
        return "fp8"
    raise EngineConfigError(
        f"kv_dtype must be one of None/'bf16'/'int8'/'fp8', got "
        f"{kv_dtype!r}")


def storage_dtype(kv_dtype: str):
    return _KV_DTYPES[kv_dtype][0]


def kv_qmax(kv_dtype: str) -> float:
    return _KV_DTYPES[kv_dtype][1]


def is_quantized_pool(pool) -> bool:
    """True for the ``{"q", "s"}`` pytree form (array pools are the
    unquantized mode)."""
    return isinstance(pool, dict) and "q" in pool


def pool_payload(pool):
    """The payload array of either pool form (shape/addressing queries
    never care about the scales)."""
    return pool["q"] if is_quantized_pool(pool) else pool


def kv_quantize_keepdims(x: jax.Array, kv_dtype: str
                         ) -> Tuple[jax.Array, jax.Array]:
    """Symmetric per-row quantization of ``x [..., Dh]`` →
    ``(payload [..., Dh] storage-dtype, scale [..., 1] bf16)``.

    The scale is rounded to its bf16 storage form BEFORE the divide so
    quantize and dequantize use bit-identical scales (an f32 quantize
    scale + bf16 stored scale would bias every element by the scale's
    own rounding error).

    This keepdims form is THE quantizer — the fused Pallas kernel calls
    it directly (ops/decode_step._quantize_token; keepdims because
    Mosaic cannot unit-dim-reshape bf16 vectors), so the
    kernel-vs-einsum stored-byte bit-identity holds by shared code, not
    by two hand-synchronized copies."""
    x32 = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(x32), axis=-1, keepdims=True)
    s = jnp.maximum(amax / kv_qmax(kv_dtype), _SCALE_FLOOR) \
        .astype(SCALE_DTYPE)
    y = x32 / s.astype(jnp.float32)
    if kv_dtype == "int8":
        payload = jnp.clip(jnp.round(y), -127.0, 127.0).astype(jnp.int8)
    else:
        payload = y.astype(jnp.float8_e4m3fn)
    return payload, s


def kv_quantize(x: jax.Array, kv_dtype: str) -> Tuple[jax.Array, jax.Array]:
    """:func:`kv_quantize_keepdims` with the scale's unit dim squeezed
    (the einsum write path's shape: scale ``[...]`` scatters into the
    pair-grouped scale array)."""
    payload, s = kv_quantize_keepdims(x, kv_dtype)
    return payload, s[..., 0]


def kv_dequantize(payload: jax.Array, scale: jax.Array, dtype) -> jax.Array:
    """``payload [..., Dh] * scale [...]`` → ``[..., Dh]`` in ``dtype``
    (f32 multiply — the storage upcast fuses into it)."""
    return (payload.astype(jnp.float32)
            * scale.astype(jnp.float32)[..., None]).astype(dtype)


def scales_token_order(s_rows: jax.Array) -> jax.Array:
    """Pair-grouped scales ``[..., pair, bs/pair]`` → token-ordered
    ``[..., bs]`` (token ``t = r * pair + h`` reads ``[..., h, r]``) —
    the einsum gather path's view; the fused kernel consumes the
    pair-grouped form directly."""
    pair, bsp = s_rows.shape[-2], s_rows.shape[-1]
    return jnp.moveaxis(s_rows, -2, -1).reshape(
        s_rows.shape[:-2] + (pair * bsp,))


def quantized_pool_like(base_pool: jax.Array, head_dim: int,
                        kv_dtype: str):
    """Allocate the ``{"q", "s"}`` pool matching an unquantized pool's
    shape (serving/kv_blocks.BlockKVPool sizes the base via
    ``model.init_cache``). Scales init to zero so NEVER-written rows
    dequantize to 0.0 at allocation; once serving runs, inactive
    slots' masked writes park real scales in the garbage row — from
    then on it holds finite junk exactly like the unquantized pool's
    garbage row, always dead behind the per-slot length mask."""
    l, n, hkv, bsp, dhp = base_pool.shape
    pair = dhp // head_dim
    return {"q": jnp.zeros(base_pool.shape, storage_dtype(kv_dtype)),
            "s": jnp.zeros((l, n, hkv, pair, bsp), SCALE_DTYPE)}


def tree_nbytes(tree) -> int:
    """Total bytes of a pool/blocks pytree (host or device arrays) —
    the swap buffer's and telemetry's byte accounting unit."""
    return sum(int(a.size) * jnp.dtype(a.dtype).itemsize
               for a in jax.tree_util.tree_leaves(tree))
