"""Host-side KV swap buffer for preempted serving requests (ISSUE 8).

Preemption with KV swap (the vLLM swap-space idea, in the spirit of this
framework's ``runtime/swap_tensor`` device<->host offload machinery but
scoped to serving): under resource pressure the scheduler swaps the
lowest-priority slot's KV OUT to host memory — freeing its slot/pool
blocks for a higher-priority request — and swaps it back IN when the
request resumes, bit-identical. The device halves live in
``ops/attention`` (extract/insert_slot_kv, gather/scatter_pool_blocks)
driven by the engine's jitted swap programs; this module owns the host
side: plain numpy arrays keyed by request id, with byte accounting so
telemetry (``serving/swap_buffer_bytes`` / peak) can watch host-memory
pressure.

Restore correctness does not depend on what happened on device while
the request was parked here: the buffer holds an exact copy of every KV
position the request had computed, so even total eviction of its blocks
(block-paged mode) or full slot reuse (slot-paged mode) cannot lose
state.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np

from deepspeed_tpu.serving.errors import (EngineConfigError,
                                          KVLifecycleError,
                                          SwapCapacityError)
from deepspeed_tpu.serving.kv_quant import tree_nbytes


class HostSwapBuffer:
    """Numpy parking lot for preempted requests' KV rows/blocks.

    One entry per preempted request id: ``put`` on swap-out, ``pop`` on
    swap-in (entries are single-use — a resumed request's KV lives on
    device again, and keeping the stale host copy around would invite
    restoring it twice). Byte accounting covers exactly what is stored;
    ``peak_bytes`` is the high-water mark a deployment sizes its host
    reservation against.

    ``max_bytes`` (ISSUE 9 satellite) caps the buffer: a ``put`` that
    would exceed it raises :class:`SwapCapacityError` BEFORE storing
    anything, so sustained preemption pressure degrades predictably
    (the engine declines the preemption and the candidate waits)
    instead of silently growing host memory until the OOM killer picks
    a victim. ``None`` keeps the historical unbounded behavior.
    """

    def __init__(self, max_bytes: Optional[int] = None):
        if max_bytes is not None and max_bytes <= 0:
            raise EngineConfigError(f"swap max_bytes must be positive or None, "
                             f"got {max_bytes}")
        self.max_bytes = max_bytes
        self._entries: Dict[int, Tuple[np.ndarray, np.ndarray]] = {}
        self.bytes_stored = 0
        self.peak_bytes = 0
        self.total_swaps_out = 0
        self.total_swaps_in = 0
        self.capacity_rejections = 0

    def fits(self, nbytes: int) -> bool:
        """Would ``nbytes`` more fit under the cap right now?"""
        return (self.max_bytes is None
                or self.bytes_stored + nbytes <= self.max_bytes)

    def put(self, rid: int, k, v) -> None:
        """Park a preempted request's KV. ``k``/``v`` are numpy arrays
        (slot rows / bf16 block stacks) or numpy PYTREES — the
        quantized pools' ``{"q", "s"}`` payload+scale trees (ISSUE 12),
        whose int8/fp8 payloads halve the bytes parked per block."""
        if rid in self._entries:
            raise KVLifecycleError(
                f"request {rid} is already swapped out (double preemption "
                f"without a resume)")
        nbytes = tree_nbytes(k) + tree_nbytes(v)
        if not self.fits(nbytes):
            self.capacity_rejections += 1
            raise SwapCapacityError(
                f"host swap buffer full: {self.bytes_stored} bytes stored "
                f"+ {nbytes} requested exceeds max_bytes "
                f"{self.max_bytes} ({len(self._entries)} parked requests)")
        self._entries[rid] = (k, v)
        self.bytes_stored += nbytes
        self.peak_bytes = max(self.peak_bytes, self.bytes_stored)
        self.total_swaps_out += 1

    def discard(self, rid: int) -> bool:
        """Drop a parked entry WITHOUT restoring it (request cancelled
        while swapped out): frees the bytes but does not count a
        swap-in — ``total_swaps_in`` keeps meaning 'KV actually
        restored to device'. Returns False when nothing was parked."""
        entry = self._entries.pop(rid, None)
        if entry is None:
            return False
        k, v = entry
        self.bytes_stored -= tree_nbytes(k) + tree_nbytes(v)
        return True

    def pop(self, rid: int) -> Tuple:
        if rid not in self._entries:
            raise KeyError(
                f"request {rid} has no swapped-out KV (resume without a "
                f"preemption, or a double resume)")
        k, v = self._entries.pop(rid)
        self.bytes_stored -= tree_nbytes(k) + tree_nbytes(v)
        self.total_swaps_in += 1
        return k, v

    def __contains__(self, rid: int) -> bool:
        return rid in self._entries

    def __len__(self) -> int:
        return len(self._entries)

    def __repr__(self):
        return (f"HostSwapBuffer(entries={len(self._entries)}, "
                f"bytes={self.bytes_stored}, peak={self.peak_bytes})")
