"""Host-side KV swap buffer for preempted serving requests (ISSUE 8).

Preemption with KV swap (the vLLM swap-space idea, in the spirit of this
framework's ``runtime/swap_tensor`` device<->host offload machinery but
scoped to serving): under resource pressure the scheduler swaps the
lowest-priority slot's KV OUT to host memory — freeing its slot/pool
blocks for a higher-priority request — and swaps it back IN when the
request resumes, bit-identical. The device halves live in
``ops/attention`` (extract/insert_slot_kv, gather/scatter_pool_blocks)
driven by the engine's jitted swap programs; this module owns the host
side: plain numpy arrays keyed by request id, with byte accounting so
telemetry (``serving/swap_buffer_bytes`` / peak) can watch host-memory
pressure.

Restore correctness does not depend on what happened on device while
the request was parked here: the buffer holds an exact copy of every KV
position the request had computed, so even total eviction of its blocks
(block-paged mode) or full slot reuse (slot-paged mode) cannot lose
state.
"""

from __future__ import annotations

from typing import Dict, Tuple

import numpy as np


class HostSwapBuffer:
    """Numpy parking lot for preempted requests' KV rows/blocks.

    One entry per preempted request id: ``put`` on swap-out, ``pop`` on
    swap-in (entries are single-use — a resumed request's KV lives on
    device again, and keeping the stale host copy around would invite
    restoring it twice). Byte accounting covers exactly what is stored;
    ``peak_bytes`` is the high-water mark a deployment sizes its host
    reservation against.
    """

    def __init__(self):
        self._entries: Dict[int, Tuple[np.ndarray, np.ndarray]] = {}
        self.bytes_stored = 0
        self.peak_bytes = 0
        self.total_swaps_out = 0
        self.total_swaps_in = 0

    def put(self, rid: int, k: np.ndarray, v: np.ndarray) -> None:
        if rid in self._entries:
            raise ValueError(
                f"request {rid} is already swapped out (double preemption "
                f"without a resume)")
        self._entries[rid] = (k, v)
        self.bytes_stored += k.nbytes + v.nbytes
        self.peak_bytes = max(self.peak_bytes, self.bytes_stored)
        self.total_swaps_out += 1

    def pop(self, rid: int) -> Tuple[np.ndarray, np.ndarray]:
        if rid not in self._entries:
            raise KeyError(
                f"request {rid} has no swapped-out KV (resume without a "
                f"preemption, or a double resume)")
        k, v = self._entries.pop(rid)
        self.bytes_stored -= k.nbytes + v.nbytes
        self.total_swaps_in += 1
        return k, v

    def __contains__(self, rid: int) -> bool:
        return rid in self._entries

    def __len__(self) -> int:
        return len(self._entries)

    def __repr__(self):
        return (f"HostSwapBuffer(entries={len(self._entries)}, "
                f"bytes={self.bytes_stored}, peak={self.peak_bytes})")
