"""Speculative decoding for the continuous-batching serving runtime
(ISSUE 4).

Decode steps emit one token per model invocation, so serving throughput
is bound by sequential decode latency (HBM-bandwidth-limited on TPU,
dispatch-limited on small models). Speculative decoding (Leviathan et
al., "Fast Inference from Transformers via Speculative Decoding") drafts
``k`` cheap candidate tokens, then scores all of them in ONE target-model
forward and keeps the longest accepted prefix plus one bonus token from
the target's own distribution — losslessly: the emitted stream is
token-identical to baseline decode under greedy, and distribution-exact
under sampling.

Pieces (the ServingEngine in serving/engine.py drives them):

  * **Drafting backends** —
    :class:`NgramDrafter`: draft-model-free prompt-lookup drafting. The
    slot's own token history (prompt + generated) is searched for the
    most recent earlier occurrence of its current suffix n-gram; the
    tokens that followed that occurrence are proposed as the
    continuation. Pure numpy, deterministic, zero extra FLOPs — it wins
    exactly when generation revisits its own context (templated/
    repetitive traffic, summarization, code).
    :class:`DraftModelDrafter`: a small draft model served through its
    own :class:`~deepspeed_tpu.inference.engine.InferenceEngine`. Drafts
    are generated greedily from a fixed trailing window of the slot's
    history re-prefilled each round (stateless — no persistent draft KV
    to roll back, at the cost of a window-length prefill per round; with
    a draft model orders of magnitude smaller than the target this is
    the verify FLOPs' rounding error, and the fixed window keeps the
    draft program's shapes static → zero recompiles).

  * **Acceptance** — :func:`speculative_acceptance`, the in-jit
    acceptance rule applied to the verify forward's logits. Both
    backends propose *deterministic* (point-mass) drafts, so the
    rejection-sampling rule collapses to: accept draft ``x_i`` with
    probability ``p_target(x_i)`` and on first rejection resample from
    the renormalized leftover ``p`` with ``x_i`` removed — exactly the
    Leviathan rule with ``q = delta(x_i)``, hence lossless for ANY draft
    choice. Greedy mode accepts while the draft matches the target
    argmax and emits the target's own argmax at the first mismatch, so
    the output is bit-identical to baseline greedy decode.

  * **KV rollback** — none needed, by construction: the verify forward
    writes all ``k + 1`` candidate positions' K/V into the slot-paged
    cache (ops/attention.write_kv_cache vector-idx block scatter), and
    the per-slot length vector advances only over the accepted prefix.
    Rejected entries stay DEAD behind the length mask and are
    overwritten in place by the next verify block, which starts exactly
    where the accepted prefix ended. Zero copies, zero extra programs.

  * **Adaptive k** — :class:`AdaptiveK`, a per-slot EMA of the
    acceptance fraction mapped onto the engine's FIXED ``k_buckets``
    set. Shrinking k when acceptance drops bounds wasted verify width;
    drawing k from a fixed bucket set (never free-varying) is what keeps
    the verify-program jit cache pinned after warmup.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.inference.engine import filter_logits
from deepspeed_tpu.serving.errors import (EngineConfigError,
                                          EngineTypeError)


# --------------------------------------------------------------- config
@dataclasses.dataclass
class SpeculativeConfig:
    """Speculative-decoding knobs for :class:`~deepspeed_tpu.serving.engine.ServingEngine`.

    mode: "ngram" (prompt-lookup, draft-model-free) or "draft" (small
        draft model; requires ``draft_engine``).
    k_buckets: ascending FIXED set of draft lengths the verify program
        may run at. Each bucket is one compiled verify program (exactly
        like prefill length buckets); adaptive k moves between buckets,
        never off them — the zero-recompile invariant.
    max_ngram/min_ngram: suffix n-gram sizes prompt-lookup tries,
        longest first (longer matches are more specific → higher
        acceptance).
    draft_engine: InferenceEngine serving the draft model ("draft" mode).
        Must share the target's tokenizer/vocab.
    draft_window: trailing-history window re-prefilled into the draft
        model each round. Bounded so the draft program's shapes are
        static; also bounds per-round draft prefill cost.
    adaptive: per-slot EMA acceptance tracking that shrinks/grows k
        within ``k_buckets``. Off = always draft ``k_buckets[-1]``.
    ema_decay: weight on the PAST in the acceptance EMA (higher = slower
        to move).
    """

    mode: str = "ngram"
    k_buckets: Sequence[int] = (2, 4, 8)
    max_ngram: int = 3
    min_ngram: int = 1
    draft_engine: Optional[object] = None
    draft_window: int = 64
    adaptive: bool = True
    ema_decay: float = 0.7

    def __post_init__(self):
        if self.mode not in ("ngram", "draft"):
            raise EngineConfigError(f"speculative mode must be 'ngram' or "
                             f"'draft', got {self.mode!r}")
        self.k_buckets = tuple(sorted({int(k) for k in self.k_buckets}))
        if not self.k_buckets or self.k_buckets[0] < 1:
            raise EngineConfigError(f"k_buckets must be >= 1: {self.k_buckets}")
        if self.mode == "draft" and self.draft_engine is None:
            raise EngineConfigError("speculative mode 'draft' needs a "
                             "draft_engine (an InferenceEngine over the "
                             "draft model)")
        if not (self.min_ngram >= 1 and self.max_ngram >= self.min_ngram):
            raise EngineConfigError(f"need 1 <= min_ngram <= max_ngram, got "
                             f"{self.min_ngram}..{self.max_ngram}")

    @property
    def k_max(self) -> int:
        return self.k_buckets[-1]


def normalize_speculative(spec) -> Optional[SpeculativeConfig]:
    """ServingEngine's ``speculative=`` kwarg: None/False/"off",
    a mode string, a dict of SpeculativeConfig fields, or a config."""
    if spec is None or spec is False or spec == "off":
        return None
    if isinstance(spec, SpeculativeConfig):
        return spec
    if isinstance(spec, str):
        return SpeculativeConfig(mode=spec)
    if isinstance(spec, dict):
        return SpeculativeConfig(**spec)
    raise EngineTypeError(f"speculative= takes None/'off'/mode str/dict/"
                          f"SpeculativeConfig, got {type(spec).__name__}")


def pick_k_bucket(k: int, k_buckets: Sequence[int]) -> int:
    """Smallest configured verify width holding ``k`` draft tokens
    (k_buckets ascending; k <= k_buckets[-1] is enforced at draft time)."""
    for b in k_buckets:
        if k <= b:
            return b
    return k_buckets[-1]


# --------------------------------------------------- in-jit acceptance
def speculative_acceptance(logits, tokens, draft_len, temp, rng, *,
                           do_sample: bool, top_k: int = 0,
                           top_p: float = 1.0, pad_token_id: int = 0):
    """Accept/reject ``k`` point-mass draft tokens against the target
    model's verify logits; traced inside the verify program.

    logits: [B, k+1, V] target logits — position i scored AFTER seeing
        ``tokens[:, i]`` (so it is the target's distribution for the
        token FOLLOWING tokens[:, i]).
    tokens: [B, k+1] int32 — column 0 the last committed token, columns
        1..k the drafts (pad past each row's ``draft_len``).
    draft_len: [B] int32 — real draft tokens per row (0 = plain decode).

    Returns ``(out_tokens [B, k+1], n_emit [B])``: row b emits
    ``out_tokens[b, :n_emit[b]]`` — its accepted draft prefix plus ONE
    token from the target distribution (bonus on full acceptance,
    correction on rejection). ``1 <= n_emit <= draft_len + 1`` always:
    every verify invocation makes progress.

    Greedy: accepted == draft matches target argmax, final token is the
    target argmax at the first mismatch — the emitted stream is exactly
    baseline greedy decode's. Sampling: Leviathan rejection sampling
    specialized to deterministic (point-mass) proposals — accept draft x
    w.p. ``p(x)``, on rejection resample from ``norm(p - p(x)·δ_x)`` —
    so emitted tokens are distributed exactly as sequential sampling
    from the target (pinned by the chi-squared test in
    tests/unit/serving/test_speculative.py)."""
    b, t, v = logits.shape
    k = t - 1
    cols = jnp.arange(t)[None, :]                                # [1, k+1]
    logits = logits.astype(jnp.float32)
    if not do_sample:
        tgt = jnp.argmax(logits, axis=-1).astype(jnp.int32)      # [B, k+1]
        match = (tokens[:, 1:] == tgt[:, :k]) & \
            (cols[:, :k] < draft_len[:, None])
        n_acc = jnp.sum(jnp.cumprod(match.astype(jnp.int32), axis=1),
                        axis=1)                                  # [B]
        out = jnp.where(cols <= n_acc[:, None], tgt, pad_token_id)
        return out, n_acc + 1

    probs = jax.nn.softmax(
        filter_logits(logits / temp, top_k=top_k, top_p=top_p), axis=-1)
    draft = tokens[:, 1:]                                        # [B, k]
    p_draft = jnp.take_along_axis(probs[:, :k], draft[..., None],
                                  axis=-1)[..., 0]               # [B, k]
    r_u, r_res = jax.random.split(rng)
    u = jax.random.uniform(r_u, (b, k))
    acc = (u < p_draft) & (cols[:, :k] < draft_len[:, None])
    n_acc = jnp.sum(jnp.cumprod(acc.astype(jnp.int32), axis=1), axis=1)
    # final token ~ target at the boundary position: on rejection the
    # leftover distribution with the rejected draft removed, on full
    # acceptance the target distribution itself (bonus token)
    row_p = jnp.take_along_axis(probs, n_acc[:, None, None],
                                axis=1)[:, 0]                    # [B, V]
    rejected = n_acc < draft_len                                 # [B]
    # the draft at the boundary (clipped gather is safe: where n_acc == k
    # there IS no draft and `rejected` is False there by construction)
    rej_tok = jnp.take_along_axis(
        draft, jnp.minimum(n_acc, k - 1)[:, None], axis=1)[:, 0]
    keep = 1.0 - jax.nn.one_hot(rej_tok, v, dtype=row_p.dtype)
    adj = jnp.where(rejected[:, None], row_p * keep, row_p)
    adj = adj / jnp.maximum(adj.sum(-1, keepdims=True), 1e-20)
    final = jax.random.categorical(
        r_res, jnp.log(jnp.maximum(adj, 1e-30)), axis=-1).astype(jnp.int32)
    draft_t = jnp.concatenate(
        [draft, jnp.full((b, 1), pad_token_id, jnp.int32)], axis=1)
    out = jnp.where(cols < n_acc[:, None], draft_t,
                    jnp.where(cols == n_acc[:, None], final[:, None],
                              pad_token_id))
    return out, n_acc + 1


# -------------------------------------------------------------- drafting
def ngram_propose(history: np.ndarray, k: int, *, max_ngram: int = 3,
                  min_ngram: int = 1) -> np.ndarray:
    """Prompt-lookup drafting (draft-model-free): propose the ``k``
    tokens that followed the MOST RECENT earlier occurrence of the
    current suffix n-gram in ``history``, trying the longest n-gram
    first. Returns an int32 array of length <= k (empty = no match, the
    engine degenerates to a plain decode step for this slot). Pure
    numpy, deterministic — acceptance then depends only on whether the
    target actually re-walks its own context."""
    h = np.asarray(history, np.int64)
    n_hi = min(max_ngram, len(h) - 1)
    for n in range(n_hi, min_ngram - 1, -1):
        suffix = h[len(h) - n:]
        windows = np.lib.stride_tricks.sliding_window_view(h, n)
        # exclude the suffix occurrence itself (the last window)
        starts = np.nonzero((windows[:-1] == suffix[None, :]).all(axis=1))[0]
        if len(starts):
            # recency bias, but never at the cost of draft LENGTH: in a
            # periodic stream the newest match sits one period from the
            # end and can only supply period-many tokens — prefer the
            # most recent match with a FULL k-token continuation, fall
            # back to the newest otherwise
            avail = len(h) - (starts + n)
            full = starts[avail >= k]
            s = int(full[-1] if len(full) else starts[-1]) + n
            cont = h[s:s + k]
            if len(cont):
                return cont.astype(np.int32)
    return np.zeros((0,), np.int32)


class NgramDrafter:
    """Per-slot prompt-lookup drafting over host-side token histories."""

    def __init__(self, config: SpeculativeConfig):
        self.config = config

    def propose(self, histories, want, kb: int) -> np.ndarray:
        """histories: per-slot token-history arrays (None = slot idle);
        want: [num_slots] per-slot draft-length caps; kb: verify bucket.
        Returns int32 [num_slots, kb] drafts + [num_slots] true lengths
        (the engine trims ``want`` already; this may return fewer)."""
        n = len(histories)
        drafts = np.zeros((n, kb), np.int32)
        lens = np.zeros((n,), np.int32)
        for i, hist in enumerate(histories):
            if hist is None or want[i] < 1:
                continue
            prop = ngram_propose(hist, int(want[i]),
                                 max_ngram=self.config.max_ngram,
                                 min_ngram=self.config.min_ngram)
            lens[i] = len(prop)
            drafts[i, :len(prop)] = prop
        return drafts, lens

    def program_cache_sizes(self):
        return {}          # host-side: nothing compiled, nothing to pin


class DraftModelDrafter:
    """Greedy draft-model drafting, batched over slots, through the
    draft model's own InferenceEngine.

    Stateless-window design: each round re-prefills the last
    ``draft_window`` history tokens into a FRESH draft cache inside one
    jitted program (InferenceEngine.slot_draft_program) and rolls k
    greedy tokens forward. No persistent draft KV: nothing to roll back
    on rejection, no draft/target length coupling, and the program's
    shapes — [slots, window] ids + [slots] lengths, one program per
    (window, k-bucket) — never vary, so the jit cache stays pinned. The
    price is a window-length draft prefill per verify step; with a draft
    model ~10-100x smaller than the target that is noise next to the
    verify forward, and ``draft_window`` caps it."""

    def __init__(self, config: SpeculativeConfig, num_slots: int,
                 pad_token_id: int = 0):
        self.config = config
        self.engine = config.draft_engine
        self.num_slots = num_slots
        self.pad_token_id = pad_token_id
        self.window = int(config.draft_window)
        mcfg = getattr(self.engine.module, "config", None)
        model_max = getattr(mcfg, "max_seq_len", None)
        need = self.window + config.k_max
        if model_max is not None and need > model_max:
            raise EngineConfigError(
                f"draft_window {self.window} + k_max {config.k_max} "
                f"exceeds the draft model's max_seq_len {model_max}")
        self._programs = {}

    def _program(self, kb: int):
        if kb not in self._programs:
            self._programs[kb] = self.engine.slot_draft_program(
                self.window, self.num_slots, kb)
        return self._programs[kb]

    def propose(self, histories, want, kb: int):
        ids = np.full((self.num_slots, self.window), self.pad_token_id,
                      np.int32)
        wlen = np.ones((self.num_slots,), np.int32)  # >=1: safe gather
        for i, hist in enumerate(histories):
            if hist is None:
                continue
            tail = np.asarray(hist[-self.window:], np.int32)
            ids[i, :len(tail)] = tail
            wlen[i] = len(tail)
        out = self._program(kb)(self.engine.params, jnp.asarray(ids),
                                jnp.asarray(wlen))
        drafts = np.asarray(jax.device_get(out))                # [B, kb]  # dstpu-lint: fence=draft tokens feed the host-side verify batch assembly
        lens = np.minimum(np.asarray(want, np.int32), kb)
        lens = np.where([h is not None for h in histories], lens, 0)
        return drafts.astype(np.int32), lens.astype(np.int32)

    def program_cache_sizes(self):
        return {f"draft_{kb}": fn._cache_size()
                for kb, fn in self._programs.items()}


# ------------------------------------------------------------ adaptive k
class AdaptiveK:
    """Per-slot acceptance-EMA -> draft-length controller over the FIXED
    ``k_buckets`` set (k never leaves the set: the verify program cache
    stays pinned through every adaptation).

    After each verify step the slot's acceptance fraction
    ``n_accepted / draft_len`` folds into an EMA; the desired k is the
    bucket indexed by the EMA's position in [0, 1]. Slots start
    optimistic (EMA 1.0 -> k_max) so high-acceptance traffic pays no
    ramp-up, and a run of rejections walks k down to ``k_buckets[0]``
    (one wasted verify column per step at worst, never a recompile)."""

    def __init__(self, config: SpeculativeConfig, num_slots: int):
        self.buckets = config.k_buckets
        self.decay = float(config.ema_decay)
        self.ema = np.ones((num_slots,), np.float64)

    def reset_slot(self, slot: int) -> None:
        self.ema[slot] = 1.0            # fresh request: optimistic start

    def update(self, slot: int, n_accepted: int, draft_len: int) -> None:
        if draft_len < 1:
            return                      # plain decode step: no signal
        frac = n_accepted / draft_len
        self.ema[slot] = self.decay * self.ema[slot] + \
            (1.0 - self.decay) * frac

    def desired_k(self, slot: int) -> int:
        i = min(int(self.ema[slot] * len(self.buckets)),
                len(self.buckets) - 1)
        return self.buckets[i]
