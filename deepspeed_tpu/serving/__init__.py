"""Continuous-batching serving runtime (ISSUE 2).

Iteration-level scheduling (Orca) over a slot-paged persistent KV cache
(vLLM's paging specialized to XLA static shapes) with recompile-free
prefill length buckets: the whole serving loop runs ``len(buckets) + 1``
compiled programs regardless of arrival pattern. See serving/engine.py.
"""

from deepspeed_tpu.serving.engine import ServingEngine
from deepspeed_tpu.serving.kv_slots import SlotKVCache
from deepspeed_tpu.serving.scheduler import (Request, RequestResult,
                                             SlotScheduler, pick_bucket,
                                             poisson_trace,
                                             templated_trace)
from deepspeed_tpu.serving.speculative import (SpeculativeConfig,
                                               ngram_propose)

__all__ = ["ServingEngine", "SlotKVCache", "SlotScheduler", "Request",
           "RequestResult", "SpeculativeConfig", "ngram_propose",
           "pick_bucket", "poisson_trace", "templated_trace"]
