"""Continuous-batching serving runtime (ISSUE 2).

Iteration-level scheduling (Orca) over a paged persistent KV cache with
recompile-free prefill length buckets: the whole serving loop runs
``len(buckets) + 1`` compiled programs regardless of arrival pattern.
Two cache layouts: the slot-paged default (vLLM's paging specialized to
XLA static shapes, serving/kv_slots.py), and the block-paged pool with
radix-tree prefix sharing + copy-on-write (ISSUE 6 — vLLM PagedAttention
block tables + SGLang RadixAttention, serving/kv_blocks.py +
serving/radix.py, ``ServingEngine(prefix_cache=True)``). SLO-aware
overload control (ISSUE 8): chunked prefill under a per-iteration token
budget, priority classes with aging, and preemption with host KV swap
(serving/swap.py). See serving/engine.py. The fault-tolerant
multi-replica fabric (ISSUE 9) — health-checked routing, failover,
load shedding, supervised restarts — lives in serving/fabric/ with its
typed error hierarchy in serving/errors.py.
"""

from deepspeed_tpu.serving.engine import ServingEngine
from deepspeed_tpu.serving.errors import (EmptyPromptError,
                                          EngineConfigError,
                                          EngineInvariantError,
                                          EngineTypeError, FabricError,
                                          InvalidMaxNewTokensError,
                                          InvalidRequestError,
                                          KVLifecycleError,
                                          LastReplicaError,
                                          NoHealthyReplicaError,
                                          PromptTooLongError,
                                          ReplicaAdmissionError,
                                          ReplicaCrashedError,
                                          RetriesExhaustedError,
                                          RouterOverloadedError, ServingError,
                                          SlotCapacityError,
                                          SwapCapacityError,
                                          TransientReplicaError,
                                          UnknownReplicaError)
from deepspeed_tpu.serving.fabric import (CircuitBreaker, ElasticAutoscaler,
                                          FabricRouter, InProcessReplica,
                                          Replica, ReplicaHealth,
                                          ReplicaSupervisor, ScaleDecision,
                                          TwinReport, run_twin,
                                          synthetic_tenant_trace)
from deepspeed_tpu.serving.kv_blocks import BlockKVPool
from deepspeed_tpu.serving.kv_slots import SlotKVCache
from deepspeed_tpu.serving.radix import PrefixCache
from deepspeed_tpu.serving.scheduler import (Request, RequestResult,
                                             SlotScheduler, bimodal_trace,
                                             bursty_poisson_trace,
                                             pick_bucket, poisson_trace,
                                             shared_prefix_trace,
                                             straggler_trace,
                                             templated_trace)
from deepspeed_tpu.serving.speculative import (SpeculativeConfig,
                                               ngram_propose)
from deepspeed_tpu.serving.swap import HostSwapBuffer

__all__ = ["ServingEngine", "SlotKVCache", "BlockKVPool", "PrefixCache",
           "SlotScheduler", "Request", "RequestResult", "SpeculativeConfig",
           "HostSwapBuffer", "ngram_propose", "pick_bucket",
           "poisson_trace", "shared_prefix_trace", "templated_trace",
           "bursty_poisson_trace", "bimodal_trace", "straggler_trace",
           # fabric (ISSUE 9)
           "CircuitBreaker", "FabricRouter", "InProcessReplica", "Replica",
           "ReplicaHealth", "ReplicaSupervisor",
           # elastic autoscaling + digital twin (ISSUE 16)
           "ElasticAutoscaler", "ScaleDecision", "TwinReport", "run_twin",
           "synthetic_tenant_trace",
           "ReplicaAdmissionError", "LastReplicaError",
           "UnknownReplicaError",
           # typed errors (ISSUE 9)
           "ServingError", "InvalidRequestError", "EmptyPromptError",
           "InvalidMaxNewTokensError", "PromptTooLongError",
           "SlotCapacityError", "SwapCapacityError", "FabricError",
           "RouterOverloadedError", "NoHealthyReplicaError",
           "RetriesExhaustedError", "ReplicaCrashedError",
           "TransientReplicaError",
           # typed errors (ISSUE 14 typed-error pass)
           "EngineConfigError", "KVLifecycleError", "EngineInvariantError",
           "EngineTypeError"]
