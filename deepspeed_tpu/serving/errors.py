"""Typed error hierarchy for the serving stack (ISSUE 9).

Every failure a caller can act on programmatically gets its own type:
admission-time request validation (bad prompt/budget shapes that used to
surface as downstream XLA shape or trace failures mid-step), host-swap
capacity pressure, and the fabric's traffic-layer conditions
(backpressure, deadlines, replica death). Two design rules:

  * **Compatibility** — request-validation errors subclass ``ValueError``
    and capacity errors subclass ``RuntimeError``, so pre-existing
    ``except ValueError`` call sites (and tests) keep working while new
    code can catch the precise type.
  * **Transient vs permanent** — the fabric router's retry policy keys
    on the TYPE, never on string matching: :class:`TransientReplicaError`
    is retryable (flaky step, failed probe), :class:`ReplicaCrashedError`
    means the replica is gone and in-flight work must fail over, and
    :class:`InvalidRequestError` is permanent (retrying the same request
    anywhere else would fail identically).
"""

from __future__ import annotations


class ServingError(Exception):
    """Base of every typed serving-stack error."""


# ----------------------------------------------- config / lifecycle / bugs
class EngineConfigError(ServingError, ValueError):
    """Construction-time misconfiguration of the engine, KV pools,
    scheduler, drafter, or fabric (bad buckets, dtypes, thresholds):
    permanent — no retry or admission order can serve it.  Subclasses
    ``ValueError`` so pre-typed ``except ValueError`` sites keep working
    (ISSUE 14 typed-error pass: every serving raise is typed)."""


class KVLifecycleError(ServingError, ValueError):
    """KV block/swap lifecycle misuse by a caller: unpinning an unpinned
    block, freeing a pinned one, evicting an interior radix node, double
    preemption without a resume.  A programming error at the call site,
    not capacity pressure (subclasses ``ValueError`` — these sites
    predate the typed hierarchy and tests pin that family)."""


class EngineTypeError(ServingError, TypeError):
    """A serving-config argument of the wrong TYPE (vs. a bad value):
    subclasses ``TypeError`` so the stdlib convention — and any
    pre-typed ``except TypeError`` site — keeps holding."""


class EngineInvariantError(ServingError, RuntimeError):
    """An internal serving invariant broke — pool exhausted past the
    admission gate, a clock that stops advancing: an engine bug, not an
    operator or caller error (subclasses ``RuntimeError`` for
    compatibility with pre-typed call sites)."""


# --------------------------------------------------------- submit validation
class InvalidRequestError(ServingError, ValueError):
    """The request itself is malformed — permanent, never retried
    (subclasses ``ValueError`` for backward compatibility with the
    pre-typed ``ServingEngine.submit`` checks)."""


class EmptyPromptError(InvalidRequestError):
    """Submitted prompt has no tokens."""


class InvalidMaxNewTokensError(InvalidRequestError):
    """``max_new_tokens`` is not a positive integer."""


class PromptTooLongError(InvalidRequestError):
    """Prompt exceeds the largest prefill bucket and chunked prefill is
    off (set ``prefill_token_budget`` to serve it in chunks)."""


class SlotCapacityError(InvalidRequestError):
    """prompt + max_new_tokens (+ speculative lookahead) exceeds the
    per-slot KV capacity — no admission order could ever serve it."""


# ------------------------------------------------------------- host KV swap
class SwapCapacityError(ServingError, RuntimeError):
    """The host swap buffer's ``max_bytes`` cap would be exceeded: the
    preemption that wanted the space is declined instead of silently
    growing host memory (ISSUE 9 satellite)."""


# ----------------------------------------------------------------- fabric
class FabricError(ServingError):
    """Base of the multi-replica fabric's traffic-layer errors."""


class RouterOverloadedError(FabricError):
    """Typed backpressure: the router's bounded queue is full and the
    submitted request is not higher-class than anything sheddable —
    the caller should slow down or retry later."""


class DeadlineExceededError(FabricError):
    """The request's deadline expired before it could be served (shed
    from the router queue before wasting prefill)."""


class NoHealthyReplicaError(FabricError):
    """Every replica is dead (or permanently abandoned by the
    supervisor's restart budget) — the fabric cannot make progress."""


class RetriesExhaustedError(FabricError):
    """The request failed more dispatch attempts than the router's
    retry budget allows."""


class ReplicaCrashedError(FabricError):
    """The replica died (process crash / preemption without grace).
    In-flight requests fail over to a survivor; the supervisor decides
    whether to resurrect the replica."""


class TransientReplicaError(FabricError):
    """A retryable replica-level hiccup (flaky step, failed health
    probe): the replica is still alive, the operation may be retried.
    Repeated transients trip the replica's circuit breaker."""


# ------------------------------------------------------- elastic pool (PR 16)
class ReplicaAdmissionError(FabricError):
    """A joining replica failed its warm admission probe (or its name
    collides with a pool member): it never entered the dispatch set, so
    no request can have been routed to it — the scale-out is refused,
    the pool is unchanged, and the caller (typically the autoscaler)
    may retry with a fresh replica."""


class LastReplicaError(FabricError):
    """Refusing to remove the LAST healthy replica: a scale-down that
    empties the serving set would strand the queue forever — the
    autoscaler's ``min_replicas`` floor should have prevented the ask,
    and a manual drain of the final replica needs a replacement added
    first."""


class UnknownReplicaError(FabricError):
    """The named replica is not a member of the pool (never added, or
    already drained out) — a caller-side bookkeeping error, not a
    health condition."""
