"""Continuous-batching serving engine: token-granularity scheduling on
top of the compiled prefill/decode programs.

The ROADMAP north star is serving heavy traffic "as fast as the hardware
allows"; ``InferenceEngine.generate`` runs one static batch to completion,
so every mixed-length batch idles finished slots on its stragglers and
every new (batch, prompt_len) shape pays an XLA recompile. This engine
closes both gaps (ISSUE 2):

  * **Iteration-level scheduling** (Orca): between decode steps the
    scheduler admits waiting requests into free slots of the persistent
    slot-paged KV cache (serving/kv_slots.py) — a finished request's
    slot decodes a NEW request on the very next iteration.
  * **Recompile-free shape bucketing**: prefill runs bucket-padded
    ([1, bucket] with the true length traced), decode runs at a fixed
    slot count with a per-slot valid-length vector — the entire serving
    loop executes exactly ``len(buckets) + 1`` compiled XLA programs
    (ONE prefill per configured bucket + ONE decode step), no matter the
    arrival pattern, admission order, or per-request lengths. With a
    single bucket that is the classic TWO-program serving loop.

Token identity: the decode step masks each slot to its own valid prefix
and bucket padding is causally invisible to the true last prompt
position, so a request's tokens are bit-identical whether it runs solo
or packed next to strangers (pinned by tests/unit/serving/).
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.serving.errors import (EmptyPromptError,
                                          EngineConfigError,
                                          EngineInvariantError,
                                          InvalidMaxNewTokensError,
                                          PromptTooLongError,
                                          SlotCapacityError,
                                          SwapCapacityError)
from deepspeed_tpu.serving.kv_blocks import BlockKVPool
from deepspeed_tpu.serving.kv_quant import normalize_kv_dtype
from deepspeed_tpu.serving.kv_slots import SlotKVCache
from deepspeed_tpu.serving.radix import PrefixCache
from deepspeed_tpu.serving.scheduler import (Request, RequestResult,
                                             SlotScheduler, pick_bucket)
from deepspeed_tpu.serving.speculative import (AdaptiveK, DraftModelDrafter,
                                               NgramDrafter,
                                               normalize_speculative,
                                               pick_k_bucket)
from deepspeed_tpu.serving.swap import HostSwapBuffer
from deepspeed_tpu.telemetry.registry import metric_label
from deepspeed_tpu.utils.logging import log_dist

# accepted-tokens-per-step / tokens-per-decode-call histograms count small
# integers (1 .. k+1), not latencies — unit-wide buckets keep the
# interpolated percentiles exact for the range any sane k reaches
_TOKENS_PER_STEP_BUCKETS = tuple(float(x) for x in range(1, 34))


def _host_blocks(tree, n_used: int):
    """device_get a swap-out gather and trim to the first ``n_used``
    blocks (axis 1 is block-major on every leaf — payloads AND the
    quantized pools' scale arrays), as host numpy."""
    return jax.tree_util.tree_map(
        lambda a: np.asarray(a)[:, :n_used], jax.device_get(tree))  # dstpu-lint: fence=swap-out gather lands host-side by definition


def _expand_blocks(tree, mb: int):
    """Zero-pad host block leaves back to the fixed [*, MB, ...] upload
    shape (swap-in programs never vary their operand shapes with how
    much actually uploads)."""
    def f(a):
        full = np.zeros((a.shape[0], mb) + a.shape[2:], a.dtype)
        full[:, :a.shape[1]] = a
        return full

    return jax.tree_util.tree_map(f, tree)


def _to_device(tree):
    return jax.tree_util.tree_map(jnp.asarray, tree)


class _SlotState:
    """Host-side state of one occupied slot. The speculative drafters'
    token-history view is DERIVED (request.prompt + result.tokens), not
    stored — a second copy could silently desynchronize from the
    emitted stream.

    A slot is in the PREFILL phase while ``prefill_pos <
    prefill_total`` (chunked prefill, ISSUE 8): it consumes prefill
    budget between decode iterations, emits no tokens, and is excluded
    from the decode batch. The first generated token (and TTFT) exists
    only once the last chunk lands. ``order`` is the engine's admission
    sequence — chunk continuations run priority-then-admission order,
    so earlier same-class prompts finish prefilling first. ``tenant``
    is the request's SANITIZED accounting tenant (ISSUE 13), resolved
    once at admission."""

    __slots__ = ("request", "result", "last_token", "prefill_pos",
                 "prefill_total", "order", "tenant")

    def __init__(self, request: Request, result: RequestResult,
                 last_token: int, prefill_pos: int, prefill_total: int,
                 order: int, tenant: str = "default"):
        self.request = request
        self.result = result
        self.last_token = last_token
        self.prefill_pos = prefill_pos
        self.prefill_total = prefill_total
        self.order = order
        self.tenant = tenant

    @property
    def prefilling(self) -> bool:
        return self.prefill_pos < self.prefill_total


class _Preempted:
    """Host-side state of one preempted (swapped-out) request: the slot
    state to reattach on resume, the KV length it had computed, and the
    engine-clock instant it left the slot set (the preempted interval
    is queue wait, not decode latency)."""

    __slots__ = ("state", "length", "since")

    def __init__(self, state: _SlotState, length: int, since: float):
        self.state = state
        self.length = length
        self.since = since


class _ReqTrace:
    """Engine-side span bookkeeping for one request (ISSUE 11): the
    trace id, the root span to hang lifecycle spans under (engine-owned
    when the request arrived without trace context; the fabric router's
    otherwise), and the currently-open decode-segment / swapped-out
    interval spans."""

    __slots__ = ("trace_id", "root", "root_span", "decode_span",
                 "swap_span", "submitted_t")

    def __init__(self, trace_id: str, root: Optional[str],
                 root_span=None, submitted_t: Optional[float] = None):
        self.trace_id = trace_id
        self.root = root               # parent span id for child spans
        self.root_span = root_span     # open root Span iff engine-owned
        self.decode_span = None
        self.swap_span = None
        # queue_wait start for CONTEXT-CARRYING requests (fabric
        # dispatch): the router's router_queue span already covers
        # [arrival, dispatch], so the engine-side wait must start at
        # the dispatch-time submit — starting at the original arrival
        # would double-count the router interval into the queue phase
        # (and, after a failover, swallow the whole first attempt).
        # Stamped by the engine's FIRST step() after submit (the same
        # clock instant the router dispatched at); None on an
        # engine-owned root, where arrival_time is correct.
        self.submitted_t = submitted_t


class ServingEngine:
    """Drives an :class:`InferenceEngine`'s slot programs with an
    iteration-level scheduler.

    Parameters
    ----------
    engine: InferenceEngine — owns params + the jitted slot programs.
    num_slots: fixed decode batch width (the slot-paged cache's batch dim).
    max_len: per-slot KV capacity in tokens; prompt + max_new_tokens of
        every admitted request must fit (rejected at submit otherwise).
    buckets: ascending prefill pad lengths (e.g. (128, 512, 2048)); a
        prompt prefills in the smallest bucket that holds it. One
        compiled prefill program per bucket.
    eos_token_id: finish a request early when it emits this token (the
        token is kept in the output, matching generate()'s EOS path).
    time_fn: clock used for arrival admission + latency metrics; defaults
        to time.monotonic. Tests inject a virtual clock so mixed arrival
        traces replay deterministically.
    telemetry: True (default) instruments the serving loop into the
        global metrics registry (queue-wait/TTFT/TPOT latency histograms,
        slot-occupancy and batch-fill gauges, recompile counter,
        finished-requests/sec — ISSUE 3); pass a MetricsRegistry to use a
        private one, or False/None to run bare (the bench.py
        ``observability_overhead`` baseline).
    speculative: speculative decoding (ISSUE 4): None/"off" (default),
        a mode string ("ngram" | "draft"), a dict of
        :class:`~deepspeed_tpu.serving.speculative.SpeculativeConfig`
        fields, or a config instance. When on, every decode iteration
        drafts up to k tokens per slot (prompt-lookup or draft model),
        verifies them ALL in one target forward, and emits each slot's
        accepted prefix + one bonus token — losslessly (greedy output is
        bit-identical to the plain decode path; sampling is
        distribution-exact). Verify programs are bucketed by k exactly
        like prefill is by length, so the zero-recompile guarantee
        holds; slot capacity reserves ``k_max`` lookahead rows for the
        pre-acceptance draft writes.
    prefix_cache: block-paged KV with radix prefix sharing (ISSUE 6).
        False (default) keeps the slot-paged cache. True switches the
        KV store to a :class:`~deepspeed_tpu.serving.kv_blocks.BlockKVPool`
        fronted by a :class:`~deepspeed_tpu.serving.radix.PrefixCache`:
        on admit the request's prompt is matched against the radix index
        and only the UNMATCHED suffix is prefilled (bucketed by suffix
        length); on finish the prompt's blocks are donated to the index
        instead of freed. Admission accounts in free pool BLOCKS (no
        fragmentation); ``block_size``/``num_blocks`` size the pool
        (defaults: 16-token blocks, worst-case slot parity). Outputs are
        bit-identical to the slot-paged engine (greedy, with and without
        speculation — pinned by tests), and the zero-recompile invariant
        holds: block tables are traced data, never shapes.
    kv_dtype: quantized KV-cache blocks (ISSUE 12; requires
        ``prefix_cache=True``). None/"bf16" (default) stores KV in the
        engine's compute dtype. "int8" / "fp8" switch the pool to
        int8 / float8_e4m3fn payloads with per-token-per-head bf16
        scales (serving/kv_quant.py): writes quantize on store, reads
        dequantize in-register (fused kernel) or in the gather (einsum
        path), and every downstream consumer — radix COW forks,
        preemption swap (byte-identical round trip at ~half the host
        bandwidth), speculation rollback — carries payload+scales as
        one opaque pytree, so zero recompiles hold by construction.
        int8 stores ~1.94x the blocks per HBM byte of bf16 (fp8 ~3.88x
        vs an fp32-serving pool); greedy output matches the bf16-KV
        engine at >= 0.99 exact-token rate on the test traces.
    prefill_token_budget: chunked prefill (ISSUE 8, Sarathi-style
        stall-free scheduling). None (default) keeps monolithic
        prefills. An int caps the BUCKET-PADDED prefill tokens (the
        compute actually dispatched) per serving iteration: long
        prompts prefill in fixed-bucket-sized chunks
        (at most the largest bucket <= budget per chunk) interleaved
        with decode steps, so a 2k-token prompt can no longer
        monopolize an iteration and spike every decoding tenant's
        TPOT. Chunk count is traced data — the zero-recompile
        invariant holds across chunk transitions — and prompts LONGER
        than the largest bucket become servable (submit's bucket
        rejection lifts; the slot capacity check remains). TTFT is
        stamped when the LAST chunk emits the first token.
    preemption: "swap" enables priority preemption with host KV swap
        (ISSUE 8): when an arrived request of a strictly higher class
        cannot be admitted (no free slot, or — block-paged — the pool
        doesn't fit it), the worst lower-class running slot is swapped
        OUT to a host-side numpy buffer (serving/swap.py), its
        slot/blocks freed, and the request re-queued at its original
        arrival position; it swaps back IN when resources free and
        finishes bit-identically to an uninterrupted run (pinned by
        tests). None (default) disables preemption.
    swap_max_bytes: byte cap on the host swap buffer (ISSUE 9): a
        preemption whose KV would push the buffer past the cap is
        declined (typed SwapCapacityError internally, surfaced as the
        ``serving/swap_capacity_rejections`` counter) so sustained
        preemption pressure cannot grow host memory without bound.
        None (default) leaves the buffer unbounded.
    priority_aging_sec: scheduler aging rate — a waiting request gains
        one full priority class per ``priority_aging_sec`` seconds
        waited, so the lowest class never starves under sustained
        high-priority load. None disables aging (raw classes only).
    tpot_slo_ms: decode-TPOT SLO guard for the admission side: when the
        EMA of inter-decode-invocation wall time exceeds this budget
        while decode-phase slots exist, the iteration's prefill budget
        drops to 0 (decode runs first, prefill defers) — for at most
        ``slo_max_defer`` consecutive iterations, so prefill always
        makes progress. Requires ``prefill_token_budget``.
    tracer: span-graph tracer (ISSUE 11), or None (default) to run
        untraced. When armed, every request's lifecycle is stamped
        host-side at fences that already exist — queue wait, each
        prefill chunk, decode segments, speculative draft/verify,
        preemption swap-out/swapped/swap-in, shed/cancel — under a root
        span the engine owns (or the fabric router's, when the request
        arrives with trace context), and per-program wall time is
        accumulated for :meth:`attribution_table`'s roofline. Arming
        adds no device work: greedy output stays bit-identical and the
        armed-vs-bare overhead is pinned <= 2% by bench.py
        ``tracing_overhead``.
    slo: an :class:`~deepspeed_tpu.telemetry.slo.SLOEngine` (ISSUE 13),
        or None (default). When armed, the engine calls
        ``slo.maybe_evaluate(now)`` once per serving iteration ON THE
        ENGINE'S OWN CLOCK — a FakeClock trace replays its alert
        timeline deterministically. Pure host work at the top of
        step(); greedy output stays bit-identical.
    tenants: per-tenant usage accounting (ISSUE 13). None (default)
        follows ``telemetry`` (accounting into the same registry);
        True forces a (possibly registry-less) ledger; False disables.
        Tracks per :attr:`Request.tenant_id`: prompt/decode tokens,
        prefill tokens computed vs saved by the prefix cache, KV
        block-seconds (pool occupancy integrated over engine-clock
        time; quantized pools billed at payload bytes), preemptions,
        deadline sheds, and TTFT/TPOT histograms — all at call sites
        the engine already owns (zero extra device syncs; the
        per-tenant token totals sum exactly to the engine counters,
        pinned by tests).
    """

    def __init__(self, engine, *, num_slots: int = 8, max_len: int = 1024,
                 buckets: Sequence[int] = (128, 512, 2048),
                 eos_token_id: Optional[int] = None, pad_token_id: int = 0,
                 do_sample: bool = False, temperature: float = 1.0,
                 top_k: int = 0, top_p: float = 1.0,
                 time_fn: Optional[Callable[[], float]] = None,
                 telemetry=True, speculative=None,
                 prefix_cache: bool = False, block_size: int = 16,
                 num_blocks: Optional[int] = None,
                 kv_dtype: Optional[str] = None,
                 prefill_token_budget: Optional[int] = None,
                 preemption: Optional[str] = None,
                 swap_max_bytes: Optional[int] = None,
                 priority_aging_sec: Optional[float] = None,
                 tpot_slo_ms: Optional[float] = None,
                 slo_max_defer: int = 4, tracer=None,
                 slo=None, tenants: Optional[bool] = None):
        self.engine = engine
        model = engine.module
        mcfg = getattr(model, "config", None)
        model_max = getattr(mcfg, "max_seq_len", None)
        if not getattr(mcfg, "has_position_table", True):
            model_max = None
        if model_max is not None and max_len > model_max:
            raise EngineConfigError(
                f"serving max_len {max_len} exceeds the model's max_seq_len "
                f"{model_max} (position table size)")
        self.kv_dtype = normalize_kv_dtype(kv_dtype)
        if self.kv_dtype is not None and not prefix_cache:
            raise EngineConfigError(
                f"kv_dtype={kv_dtype!r} needs prefix_cache=True: quantized "
                "KV lives in the block-paged pool (serving/kv_quant.py); "
                "the slot-paged cache stays in the compute dtype")
        if prefix_cache:
            self.cache = BlockKVPool(model, num_slots, max_len,
                                     block_size=block_size,
                                     num_blocks=num_blocks,
                                     dtype=engine.dtype,
                                     kv_dtype=self.kv_dtype)
        else:
            self.cache = SlotKVCache(model, num_slots, max_len,
                                     dtype=engine.dtype)
        # block-program jit-cache key component: one InferenceEngine may
        # back pools of DIFFERENT kv_dtypes (e.g. the kv-quant bench's
        # bf16-vs-int8 engines) — without the key the two pool pytree
        # structures would land in ONE jitted program's cache and break
        # the cache-size==1 zero-recompile pinning
        self._kv_key = self.kv_dtype or "compute"
        # canonical placement: freshly-allocated carry arrays are
        # uncommitted SingleDeviceSharding while jitted-program outputs
        # carry the mesh's NamedSharding — the jit cache keys on that, so
        # un-canonicalized resets would each cost one phantom recompile
        # (caught by the zero-recompile serving test)
        self._canon = lambda x: jax.device_put(
            x, NamedSharding(engine.mesh, P()))
        self.cache.update(*map(self._canon, self.cache.carry()))
        # clamp oversized buckets to the slot capacity (silently DROPPING
        # them would reject prompts that fit the slot: the default
        # buckets (128, 512, 2048) with max_len 1024 must yield a
        # 1024-token bucket, not a 512 ceiling)
        self.buckets = tuple(sorted({min(b, max_len) for b in buckets}))
        if not self.buckets:
            raise EngineConfigError(f"no prefill buckets given: {buckets}")
        for b in self.buckets:
            if b % max(self.cache.pair, 1):
                raise EngineConfigError(
                    f"prefill bucket {b} must be a multiple of the cache "
                    f"token-pair pack factor {self.cache.pair} "
                    "(ops/attention.kv_pack_factor)")
        self.num_slots = num_slots
        self.max_len = max_len
        self.eos_token_id = eos_token_id
        self.pad_token_id = pad_token_id
        self.do_sample = do_sample
        self._temp = jnp.asarray(max(temperature, 1e-6), jnp.float32)
        self._sample_kw = dict(do_sample=do_sample, top_k=top_k,
                               top_p=float(top_p))
        self._time = time_fn or time.monotonic
        # a wall clock only ADVANCES WITH real time, so idle gaps must
        # time.sleep (a tight poll would spin one core for the whole
        # gap); injected virtual clocks advance per CALL, so their idle
        # loops terminate by polling and must NOT sleep
        self._real_clock = self._time in (time.monotonic, time.time,
                                          time.perf_counter)
        self._rng = jax.random.PRNGKey(engine.config.seed + 1)
        self._zero_key = jax.random.PRNGKey(0)

        # ---- SLO-aware scheduling (ISSUE 8)
        if prefill_token_budget is not None:
            if prefill_token_budget < self.buckets[0]:
                raise EngineConfigError(
                    f"prefill_token_budget {prefill_token_budget} below the "
                    f"smallest prefill bucket {self.buckets[0]}: no chunk "
                    f"program could ever run under it")
            # chunks are fixed-bucket-sized: the largest bucket the
            # budget holds (chunk count is data, bucket set is fixed —
            # the recompile-free invariant)
            self._chunk_max: Optional[int] = max(
                b for b in self.buckets if b <= prefill_token_budget)
        else:
            self._chunk_max = None
        self.prefill_token_budget = prefill_token_budget
        if preemption not in (None, "swap"):
            raise EngineConfigError(f"preemption policy must be None or 'swap', "
                             f"got {preemption!r}")
        self.preemption = preemption
        # swap_max_bytes (ISSUE 9 satellite) caps the host swap buffer:
        # a preemption whose KV would not fit is DECLINED (typed
        # SwapCapacityError inside, counted outside) so sustained
        # preemption pressure degrades into "candidate waits" instead
        # of unbounded host-memory growth
        self.swap = HostSwapBuffer(max_bytes=swap_max_bytes) \
            if preemption else None
        self._preempted: Dict[int, _Preempted] = {}
        if tpot_slo_ms is not None and prefill_token_budget is None:
            raise EngineConfigError(
                "tpot_slo_ms needs prefill_token_budget: the SLO guard "
                "defers budgeted prefill work, and monolithic admission "
                "has no budget to defer")
        self.tpot_slo_ms = tpot_slo_ms
        self._slo_max_defer = slo_max_defer
        self._defer_streak = 0
        self._decode_gap_ema: Optional[float] = None
        self._last_decode_t: Optional[float] = None
        self._admit_seq = 0

        self.scheduler = SlotScheduler(num_slots,
                                       aging_sec=priority_aging_sec)
        self._slots: List[Optional[_SlotState]] = [None] * num_slots
        self._warm = False
        self._run_t0: Optional[float] = None
        # programs (built lazily, counted by tests): bucket -> prefill fn
        self._prefill: Dict[int, Callable] = {}
        # slot-paged chunk-prefill programs (chunked mode only; the
        # block-paged mode chunks through the same suffix-prefill
        # programs via their `start` operand)
        self._chunk_prefill: Dict[int, Callable] = {}
        self._swap_out_fn: Optional[Callable] = None
        self._swap_in_fn: Optional[Callable] = None
        self._copy_fn: Optional[Callable] = None
        if prefix_cache:
            self._decode = engine.block_decode_program(
                num_slots, self.cache.max_blocks_per_slot,
                pad_token_id=pad_token_id, kv_dtype=self._kv_key,
                **self._sample_kw)
            self._copy_fn = engine.block_copy_program(
                self.cache.num_blocks, block_size, kv_dtype=self._kv_key)
        else:
            self._decode = engine.slot_decode_program(
                num_slots, max_len, pad_token_id=pad_token_id,
                **self._sample_kw)
        # ---- speculative decoding (ISSUE 4)
        self.spec = normalize_speculative(speculative)
        self._verify: Dict[int, Callable] = {}     # k-bucket -> verify fn
        self._drafter = None
        self._adaptive = None
        self._lookahead = 0
        if self.spec is not None:
            # the verify step writes all k draft candidates' K/V BEFORE
            # acceptance — reserve the lookahead rows at admission
            self._lookahead = self.spec.k_max
            if max_len <= self._lookahead:
                raise EngineConfigError(
                    f"speculative k_max {self._lookahead} leaves no slot "
                    f"capacity at max_len {max_len}")
            if self.spec.mode == "draft":
                self._drafter = DraftModelDrafter(
                    self.spec, num_slots, pad_token_id=pad_token_id)
            else:
                self._drafter = NgramDrafter(self.spec)
            if self.spec.adaptive:
                self._adaptive = AdaptiveK(self.spec, num_slots)
        # metrics
        self.decode_steps = 0
        self.prefill_calls = 0
        # prompt tokens actually run through a prefill program (suffix
        # tokens in prefix-cache mode — the bench's "prefill tokens
        # computed" axis; radix-matched tokens never hit the device)
        self.prefill_tokens_computed = 0
        self.tokens_generated = 0
        # SLO-aware scheduling accounting (ISSUE 8; bench + telemetry)
        self.prefill_chunks = 0
        self.preemptions = 0
        # swap traffic in pool blocks (block-paged) / slot pages
        # (slot-paged: the whole slot row is the swap unit, 1 per trip)
        self.swapped_blocks_out = 0
        self.swapped_blocks_in = 0
        self.swap_capacity_rejections = 0
        self.slo_deferred_steps = 0
        self._active_slot_iterations = 0
        # speculative accounting (spec mode only; bench + telemetry)
        self.spec_drafted_tokens = 0
        self.spec_accepted_tokens = 0
        self._draft_wall = 0.0
        self._verify_wall = 0.0
        # decode-phase wall clock (plain decode + draft + verify calls,
        # host-observed): the denominator of the bench's decode
        # tokens/sec — run() wall would dilute the decode hot path with
        # prefill and idle time
        self.decode_wall = 0.0
        if telemetry is True:
            from deepspeed_tpu.telemetry import get_registry

            self.telemetry = get_registry()
        else:
            self.telemetry = telemetry or None
        # ---- SLO control plane + per-tenant accounting (ISSUE 13)
        self.slo = slo
        if tenants is None:
            tenants = self.telemetry is not None
        if tenants:
            from deepspeed_tpu.telemetry.tenants import TenantLedger

            self.tenants = TenantLedger(self.telemetry)
        else:
            self.tenants = None
        # KV occupancy billing unit: PAYLOAD bytes per pool block (a
        # quantized pool's blocks bill at what they actually cost in
        # HBM — the int8 capacity lever shows up on the tenant's bill);
        # slot-paged mode bills the whole slot row as one "block"
        if prefix_cache:
            from deepspeed_tpu.serving.kv_quant import pool_payload

            n_rows = self.cache.num_blocks + 1
            self._kv_bytes_per_block = (
                pool_payload(self.cache.k).nbytes
                + pool_payload(self.cache.v).nbytes) / n_rows
        else:
            self._kv_bytes_per_block = (
                self.cache.k.nbytes + self.cache.v.nbytes) / num_slots
        self._acct_last_t: Optional[float] = None
        # ---- span-graph tracing + roofline attribution (ISSUE 11)
        self.tracer = tracer
        self._rtraces: Dict[int, _ReqTrace] = {}
        self._engine_trace: Optional[str] = None  # iteration-span trace
        self._last_step_now = 0.0     # cancel() has no `now` argument
        # context-carrying records awaiting their submit-time stamp
        # (resolved by the next step(); see _ReqTrace.submitted_t)
        self._pending_submit_stamps: List[_ReqTrace] = []
        # program name -> abstract operand shapes, captured at warmup
        # (ShapeDtypeStructs — no live buffers retained); the lazy
        # cost_analysis probe in attribution_table() lowers with these
        self._program_shapes: Dict[str, tuple] = {}
        # program name -> [total host wall s, calls] (armed runs only —
        # the bare path must stay byte-identical to pre-tracing code)
        self._prog_wall: Dict[str, list] = {}
        self._attr_cache: Dict[str, dict] = {}
        # radix prefix index over the block pool (ISSUE 6) — created
        # after telemetry so its hit/miss/COW/eviction counters land in
        # the same registry as the serving histograms
        self.prefix = (PrefixCache(self.cache, registry=self.telemetry)
                       if prefix_cache else None)
        log_dist(f"ServingEngine: slots={num_slots} max_len={max_len} "
                 f"buckets={self.buckets} cache={self.cache!r}", ranks=[0])

    # -------------------------------------------------------------- programs
    def _prefill_fn(self, bucket: int):
        if bucket not in self._prefill:
            if self.prefix is not None:
                self._prefill[bucket] = self.engine.block_prefill_program(
                    bucket, self.num_slots, self.cache.max_blocks_per_slot,
                    kv_dtype=self._kv_key, **self._sample_kw)
            else:
                self._prefill[bucket] = self.engine.slot_prefill_program(
                    bucket, self.num_slots, self.max_len, **self._sample_kw)
        return self._prefill[bucket]

    def _chunk_fn(self, bucket: int):
        """Slot-paged mid-prompt chunk prefill (ISSUE 8) — the chunk
        attends over the slot's own already-written prefix, so unlike
        the monolithic bucket prefill it can start at a traced offset.
        Block-paged chunking needs no separate program (the suffix
        prefill's ``start`` operand is the chunk offset)."""
        if bucket not in self._chunk_prefill:
            self._chunk_prefill[bucket] = \
                self.engine.slot_chunk_prefill_program(
                    bucket, self.num_slots, self.max_len, **self._sample_kw)
        return self._chunk_prefill[bucket]

    def _build_swap_programs(self) -> None:
        """Preemption swap-out/in programs for the active cache mode
        (ISSUE 8) — compiled at warmup when the policy is on, so a
        preemption mid-trace never compiles."""
        if self._swap_out_fn is not None:
            return
        eng = self.engine
        if self.prefix is not None:
            mb = self.cache.max_blocks_per_slot
            self._swap_out_fn = eng.block_swap_out_program(
                self.cache.num_blocks, mb, kv_dtype=self._kv_key)
            self._swap_in_fn = eng.block_swap_in_program(
                self.cache.num_blocks, mb, kv_dtype=self._kv_key)
        else:
            self._swap_out_fn = eng.slot_swap_out_program(
                self.num_slots, self.max_len)
            self._swap_in_fn = eng.slot_swap_in_program(
                self.num_slots, self.max_len)

    def _verify_fn(self, kb: int):
        """Speculative verify program for draft-width bucket ``kb`` —
        one compiled program per bucket in the FIXED k_buckets set, so
        adaptive-k transitions never compile (the spec analog of the
        prefill length buckets)."""
        if kb not in self._verify:
            if self.prefix is not None:
                self._verify[kb] = self.engine.block_verify_program(
                    self.num_slots, self.cache.max_blocks_per_slot, kb,
                    pad_token_id=self.pad_token_id, kv_dtype=self._kv_key,
                    **self._sample_kw)
            else:
                self._verify[kb] = self.engine.slot_verify_program(
                    self.num_slots, self.max_len, kb,
                    pad_token_id=self.pad_token_id, **self._sample_kw)
        return self._verify[kb]

    @property
    def program_count(self) -> int:
        """Compiled serving programs built so far (== len(buckets) + 1
        after warmup without speculation — the no-recompile tests pin
        this; speculation adds one verify program per k-bucket plus the
        draft-model programs; the prefix cache adds exactly one COW
        block-copy program)."""
        n = len(self._prefill) + 1 + len(self._verify)
        n += len(self._chunk_prefill)
        if self._swap_out_fn is not None:
            n += 2
        if self._copy_fn is not None:
            n += 1
        if self._drafter is not None:
            n += len(self._drafter.program_cache_sizes())
        return n

    def program_cache_sizes(self) -> Dict[str, int]:
        """jit-cache entry count per serving program — every value must
        be 1 after any trace ("zero XLA recompiles after warmup"):
        a second entry would mean some argument's shape/dtype varied.
        Covers the speculative verify programs (one per k-bucket) and
        the draft-model programs when speculation is on."""
        out = {"decode": self._decode._cache_size()}
        for b, fn in self._prefill.items():
            out[f"prefill_{b}"] = fn._cache_size()
        for b, fn in self._chunk_prefill.items():
            out[f"chunk_prefill_{b}"] = fn._cache_size()
        for kb, fn in self._verify.items():
            out[f"verify_{kb}"] = fn._cache_size()
        if self._swap_out_fn is not None:
            out["swap_out"] = self._swap_out_fn._cache_size()
            out["swap_in"] = self._swap_in_fn._cache_size()
        if self._copy_fn is not None:
            out["block_copy"] = self._copy_fn._cache_size()
        if self._drafter is not None:
            out.update(self._drafter.program_cache_sizes())
        return out

    # ------------------------------------------------- attribution (ISSUE 11)
    def _cap(self, name: str, *args):
        """Capture a program's operand shapes (once, at warmup) for the
        lazy roofline cost probe; passes the args through unchanged."""
        if name not in self._program_shapes:
            from deepspeed_tpu.telemetry.attribution import abstract_args

            self._program_shapes[name] = abstract_args(args)
        return args

    def _prog_note(self, name: str, dt: float) -> None:
        """Accumulate host wall for one program call (armed runs)."""
        w = self._prog_wall.get(name)
        if w is None:
            self._prog_wall[name] = [dt, 1]
        else:
            w[0] += dt
            w[1] += 1

    def _program_map(self) -> Dict[str, Callable]:
        """name -> jitted program, names matching program_cache_sizes
        (the registry the attribution table covers)."""
        progs: Dict[str, Callable] = {"decode": self._decode}
        for b, fn in self._prefill.items():
            progs[f"prefill_{b}"] = fn
        for b, fn in self._chunk_prefill.items():
            progs[f"chunk_prefill_{b}"] = fn
        for kb, fn in self._verify.items():
            progs[f"verify_{kb}"] = fn
        if self._swap_out_fn is not None:
            progs["swap_out"] = self._swap_out_fn
            progs["swap_in"] = self._swap_in_fn
        if self._copy_fn is not None:
            progs["block_copy"] = self._copy_fn
        if isinstance(self._drafter, DraftModelDrafter):
            # the draft model's programs ride program_cache_sizes and
            # must ride the roofline table too (coverage is pinned by
            # bench.py's all_programs_covered)
            for kb, fn in self._drafter._programs.items():
                progs[f"draft_{kb}"] = fn
        return progs

    def attribution_table(self) -> Dict[str, dict]:
        """Per-program roofline attribution (ISSUE 11): XLA
        cost-analysis flops/bytes for every compiled serving program,
        joined with host-observed per-call wall (tracer-armed runs)
        and the accelerator's compute/bandwidth roofs —
        achieved-vs-attainable per program, and which roof binds it.
        Cost probes are one extra lower+compile each, memoized; never
        called from the serving hot path."""
        from deepspeed_tpu.telemetry.attribution import attribution_table

        progs = {n: (fn, self._program_shapes[n])
                 for n, fn in self._program_map().items()
                 if n in self._program_shapes}
        walls = {n: (w[0], w[1]) for n, w in self._prog_wall.items()}
        return attribution_table(progs, walls=walls,
                                 cache=self._attr_cache)

    def record_attribution(self) -> Dict[str, dict]:
        """Compute :meth:`attribution_table` and stream it to the
        telemetry JSONL sink as an ``{"kind": "attribution"}`` record
        (rendered by scripts/telemetry_report.py's ``attribution``
        section). Returns the table."""
        table = self.attribution_table()
        if self.telemetry is not None and self.telemetry.sink is not None:
            try:
                self.telemetry.sink.write({
                    "kind": "attribution", "scope": "serving",
                    "programs": table})
            except Exception:
                pass
        return table

    def warmup(self) -> None:
        """Compile every serving program (each bucket's prefill + the
        decode step + with speculation each k-bucket's verify and draft
        programs) on dummy data, then reset the slot lengths. Two
        passes, so both carry signatures — canonical (post-reset) and
        program-output — are cached for every program; after this, a
        trace of ANY shape mix (including adaptive-k transitions) runs
        zero compiles."""
        if self._warm:
            return
        eng = self.engine
        paged = self.prefix is not None
        for _ in range(2):
            for b in self.buckets:
                ids = jnp.zeros((1, b), jnp.int32)
                if paged:
                    # sentinel table row: the dummy prefill's writes land
                    # in the pool's garbage block, never a real one
                    out = self._prefill_fn(b)(*self._cap(
                        f"prefill_{b}",
                        eng.params, *self.cache.carry(), ids,
                        self.cache.table_row(0), np.int32(0), np.int32(0),
                        np.int32(1), self._temp, self._zero_key))
                else:
                    out = self._prefill_fn(b)(*self._cap(
                        f"prefill_{b}",
                        eng.params, *self.cache.carry(), ids, np.int32(0),
                        np.int32(1), self._temp, self._zero_key))
                self.cache.update(*out[:3])
                if (self._chunk_max is not None and not paged
                        and b <= self._chunk_max):
                    # slot-paged chunk programs: chunks never exceed
                    # _chunk_max, so only buckets up to it can run one
                    out = self._chunk_fn(b)(*self._cap(
                        f"chunk_prefill_{b}",
                        eng.params, *self.cache.carry(), ids, np.int32(0),
                        np.int32(0), np.int32(1), self._temp,
                        self._zero_key))
                    self.cache.update(*out[:3])
            if self.preemption is not None:
                # swap round trip through slot/garbage rows, with the
                # host upload in the loop so BOTH runtime operand
                # signatures (canonical carry + numpy-uploaded rows) are
                # cached — a first preemption mid-trace must not compile
                self._build_swap_programs()
                if paged:
                    sent = jnp.asarray(np.full(
                        (self.cache.max_blocks_per_slot,),
                        self.cache.sentinel, np.int32))
                    ko, vo = self._swap_out_fn(*self._cap(
                        "swap_out", self.cache.k, self.cache.v, sent))
                    args_in = (_to_device(jax.device_get(ko)),  # dstpu-lint: fence=warmup: pre-cache numpy-upload swap signature
                               _to_device(jax.device_get(vo)),
                               sent)
                else:
                    ko, vo = self._swap_out_fn(*self._cap(
                        "swap_out", self.cache.k, self.cache.v,
                        np.int32(0)))
                    args_in = (jnp.asarray(np.asarray(jax.device_get(ko))),  # dstpu-lint: fence=warmup: pre-cache numpy-upload swap signature
                               jnp.asarray(np.asarray(jax.device_get(vo))))
                out = self._swap_in_fn(*self._cap(
                    "swap_in", self.cache.k, self.cache.v,
                    *args_in, self.cache.lengths,
                    np.int32(0), np.int32(0)))
                self.cache.update(*out)
            toks = np.zeros((self.num_slots,), np.int32)
            active = np.zeros((self.num_slots,), bool)
            out = self._decode(*self._cap(
                "decode", eng.params, *self.cache.carry(),
                *self._table_args(),
                jnp.asarray(toks), jnp.asarray(active),
                self._temp, self._zero_key))
            self.cache.update(*out[:3])
            if paged:
                # COW copy program: garbage row onto itself is a no-op
                k, v = self._copy_fn(*self._cap(
                    "block_copy", self.cache.k, self.cache.v,
                    np.int32(self.cache.sentinel),
                    np.int32(self.cache.sentinel)))
                self.cache.update_kv(k, v)
            if self.spec is not None:
                zeros = jnp.zeros((self.num_slots,), jnp.int32)
                for kb in self.spec.k_buckets:
                    blk = jnp.zeros((self.num_slots, kb + 1), jnp.int32)
                    out = self._verify_fn(kb)(*self._cap(
                        f"verify_{kb}",
                        eng.params, *self.cache.carry(),
                        *self._table_args(), blk, zeros,
                        jnp.asarray(active), self._temp, self._zero_key))
                    self.cache.update(*out[:3])
                    if isinstance(self._drafter, DraftModelDrafter):
                        window = jnp.zeros(
                            (self.num_slots, self._drafter.window),
                            jnp.int32)
                        self._drafter._program(kb)(*self._cap(
                            f"draft_{kb}",
                            self._drafter.engine.params, window,
                            jnp.ones((self.num_slots,), jnp.int32)))
            self.cache.lengths = self._canon(
                jnp.zeros((self.num_slots,), jnp.int32))
        self._warm = True

    def _table_args(self) -> tuple:
        """Extra traced operand for the block-paged programs: the full
        [B, MB] block table from the host tables (empty in slot-paged
        mode). ``table_array()`` caches the device mirror and only
        re-uploads after ``PrefixCache.admit``/``finish`` call
        ``invalidate_tables()`` — any new code path that mutates
        ``pool.tables`` must invalidate too. Same shape/dtype every
        call — traced DATA, so remapping blocks between steps reuses
        the compiled programs."""
        if self.prefix is None:
            return ()
        return (self.cache.table_array(),)

    # ----------------------------------------------------------------- queue
    def submit(self, request: Request) -> None:
        """Queue a request, validating it up front (ISSUE 9 satellite):
        a malformed prompt/budget raises a TYPED error here — at submit
        time, where the caller can act on it — instead of surfacing as
        an XLA shape or trace failure several decode iterations later.
        All the types subclass ``ValueError`` (serving/errors.py), so
        pre-typed call sites keep working."""
        plen = len(request.prompt)
        if plen < 1:
            raise EmptyPromptError(f"request {request.rid}: empty prompt")
        if request.max_new_tokens < 1:
            raise InvalidMaxNewTokensError(
                f"request {request.rid}: max_new_tokens must be >= 1, "
                f"got {request.max_new_tokens}")
        if self._chunk_max is None and \
                pick_bucket(plen, self.buckets) is None:
            raise PromptTooLongError(
                f"request {request.rid}: prompt length {plen} exceeds the "
                f"largest prefill bucket {self.buckets[-1]} (set "
                f"prefill_token_budget to serve longer prompts via "
                f"chunked prefill)")
        if not self.cache.capacity_for(plen, request.max_new_tokens,
                                       self._lookahead):
            extra = (f" (speculation reserves {self._lookahead} lookahead "
                     f"rows for pre-acceptance draft writes)"
                     if self._lookahead else "")
            raise SlotCapacityError(
                f"request {request.rid}: prompt {plen} + max_new "
                f"{request.max_new_tokens} exceeds slot capacity "
                f"{self.max_len}{extra}")
        if self.tracer is not None:
            # trace context (ISSUE 11): a request arriving WITH context
            # (the fabric's re-dispatch, or any upstream caller) keeps
            # its trace — the engine's spans link under the caller's
            # root. Otherwise the engine owns the root span. The
            # incoming Request is never mutated: context lives in the
            # engine-side record, so replaying the same trace objects
            # (benches, tests) yields fresh traces per run.
            if request.trace_id is not None:
                rt = _ReqTrace(request.trace_id, request.parent_span)
                self._rtraces[request.rid] = rt
                self._pending_submit_stamps.append(rt)
            else:
                root = self.tracer.begin(
                    "request", t=request.arrival_time, rid=request.rid,
                    priority=request.priority, prompt_len=plen)
                self._rtraces[request.rid] = _ReqTrace(
                    root.trace_id, root.span_id, root_span=root)
        self.scheduler.submit(request)

    def cancel(self, rid: int) -> bool:
        """Withdraw a request wherever it currently lives (ISSUE 9 —
        the fabric router's failover/timeout path: a request being
        re-dispatched to another replica must not also finish here).
        Queued: removed from the scheduler (a preempted request's host
        KV is dropped too). Running: its slot is freed — in
        prefix-cache mode the blocks it COMPUTED are donated to the
        radix index (they are valid prefixes; unwritten tails are not)
        and the rest freed. Returns False when the rid is unknown or
        already finished; no result is ever emitted for a cancelled
        request."""
        if self.scheduler.remove(rid):
            if rid in self._preempted:
                self._preempted.pop(rid)
                # discard, not pop: nothing returns to the device, so
                # this must not count as a swap-in
                self.swap.discard(rid)
            self._trace_cancel(rid, "queued")
            return True
        for i, st in enumerate(self._slots):
            if st is not None and st.request.rid == rid:
                self._slots[i] = None
                self.scheduler.release(i)
                if self.prefix is not None:
                    length = int(jax.device_get(self.cache.lengths[i]))  # dstpu-lint: fence=cancel path (cold): computed length gates the radix donate
                    self.prefix.finish(i, donate_upto=length)
                self._trace_cancel(rid, "slot")
                return True
        return False

    def _trace_cancel(self, rid: int, where: str) -> None:
        """Close a cancelled request's open spans (ISSUE 11) — the
        fabric's failover/timeout path cancels here and re-dispatches
        the SAME trace to a survivor, so the cancelled attempt's spans
        must not dangle open. cancel() carries no clock argument; the
        last step() instant is the best engine-base stamp available."""
        if self.tracer is None:
            return
        rt = self._rtraces.pop(rid, None)
        if rt is None:
            return
        t = self._last_step_now
        self.tracer.end(rt.decode_span, t=t, reason="cancelled")
        self.tracer.end(rt.swap_span, t=t, reason="cancelled")
        self.tracer.record("cancel", t, t, trace_id=rt.trace_id,
                           parent_id=rt.root, where=where)
        self.tracer.end(rt.root_span, t=t, finish_reason="cancelled")

    @property
    def pending(self) -> int:
        """Requests not yet finished (queued + in flight)."""
        return self.scheduler.waiting + sum(
            s is not None for s in self._slots)

    # ------------------------------------------------------------ iteration
    def _next_rng(self):
        if not self.do_sample:
            return self._zero_key
        self._rng, sub = jax.random.split(self._rng)
        return sub

    def _now(self, fallback: float) -> float:
        """Fresh clock read in run()'s offset base — result timestamps
        include the device work that happened since step() entry (the
        admission-gating ``now`` would understate latency by one
        prefill/decode's compute)."""
        if self._run_t0 is None:
            return fallback
        return self._time() - self._run_t0

    def _finish(self, slot: int, now: float, reason: str) -> RequestResult:
        st = self._slots[slot]
        st.result.finish_time = self._now(now)
        st.result.finish_reason = reason
        self._slots[slot] = None
        self.scheduler.release(slot)
        if self.prefix is not None:
            # insert-on-finish: donate the prompt's full blocks to the
            # radix index (one cached prefill serves every future match),
            # free the rest, park the table row at the sentinel
            self.prefix.finish(slot)
        if self.tracer is not None:
            rt = self._rtraces.pop(st.request.rid, None)
            if rt is not None:
                t_fin = st.result.finish_time
                self.tracer.end(rt.decode_span, t=t_fin,
                                tokens=len(st.result.tokens),
                                decode_calls=st.result.decode_calls)
                self.tracer.end(rt.swap_span, t=t_fin)
                self.tracer.end(rt.root_span, t=t_fin,
                                finish_reason=reason,
                                tokens=len(st.result.tokens),
                                preemptions=st.result.preemptions)
        if self.telemetry is not None:
            res = st.result
            reg = self.telemetry
            reg.counter("serving/finished_requests").inc()
            reg.histogram("serving/latency_ms").observe(res.latency * 1e3)
            # Orca-style iteration accounting over the decode phase only
            # (TTFT covers the prefill). Divide by ACTUAL decode
            # invocations, not len(tokens) - 1: a speculative verify step
            # emits up to k+1 tokens per invocation, so the token count
            # would overstate the step count and understate TPOT. Time
            # spent PREEMPTED DURING DECODE is queue wait, not decode
            # latency — it is subtracted from the span (and decode_calls
            # never counted swapped-out iterations in the first place);
            # a mid-PREFILL park fell before first_token_time and is
            # already outside the span.
            n_dec = res.decode_calls
            if n_dec > 0:
                tpot = max(res.finish_time - res.first_token_time
                           - res.decode_preempted_wall, 0.0) / n_dec * 1e3
                reg.histogram("serving/tpot_ms").observe(tpot)
                reg.histogram(
                    f"serving/tpot_ms/p{metric_label(res.priority)}"
                ).observe(tpot)
                reg.histogram(
                    "serving/tokens_per_decode_call",
                    buckets=_TOKENS_PER_STEP_BUCKETS).observe(
                    (len(res.tokens) - 1) / n_dec)
                if self.tenants is not None:
                    self.tenants.note_tpot(st.tenant, tpot)
        return st.result

    def _maybe_finish(self, slot: int, now: float) -> Optional[RequestResult]:
        st = self._slots[slot]
        if (self.eos_token_id is not None
                and st.result.tokens
                and st.result.tokens[-1] == self.eos_token_id):
            return self._finish(slot, now, "eos")
        if len(st.result.tokens) >= st.request.max_new_tokens:
            return self._finish(slot, now, "length")
        return None

    def _admit_fits(self, req: Request) -> bool:
        """Admission predicate (scheduler ``fits`` hook). Slot-paged:
        the free-slot list is the only resource, always True.
        Block-paged: the request's UNMATCHED block demand — prompt +
        max_new + speculative lookahead, minus radix-matched full
        blocks — must be servable from free + evictable pool blocks
        (identical accounting for fresh admissions and preempted
        resumes: ``readmit`` re-pins exactly the blocks ``fits``
        credits)."""
        if self.prefix is None:
            return True
        return self.prefix.fits(
            req.prompt,
            len(req.prompt) + req.max_new_tokens + self._lookahead)

    def _stream(self, st: _SlotState, tokens) -> None:
        """Token-streaming callback (ISSUE 8 satellite): invoked once
        per COMMITTED token in emission order — under speculation only
        the accepted (post-EOS-truncation) block ever reaches it, so
        the streamed sequence is exactly ``RequestResult.tokens``."""
        cb = st.request.on_token
        if cb is not None:
            for t in tokens:
                cb(int(t))

    def _iteration_prefill_budget(self, now: float) -> Optional[int]:
        """Prefill tokens this iteration may spend. None = unlimited
        (monolithic mode). With ``tpot_slo_ms`` set, an iteration whose
        decode-gap EMA exceeds the budget while decode-phase slots
        exist defers ALL prefill work (returns 0) — decode runs
        untaxed — but never more than ``slo_max_defer`` times in a row,
        so prefilling requests always progress (deferral shapes WHEN
        prefill happens, never WHETHER). The streak counts only
        iterations that actually had prefill work to defer (an
        in-flight chunked prompt, or an arrived fresh head): idle
        at-risk iterations neither defer anything nor burn the streak —
        otherwise a long prompt arriving right after an idle at-risk
        stretch would prefill undeferred in the exact iteration the EMA
        flags a breach."""
        budget = self.prefill_token_budget
        if budget is None:
            return None
        at_risk = (self.tpot_slo_ms is not None
                   and self._decode_gap_ema is not None
                   and self._decode_gap_ema * 1e3 > self.tpot_slo_ms
                   and any(s is not None and not s.prefilling
                           for s in self._slots))
        if not at_risk:
            self._defer_streak = 0
            return budget
        head = self.scheduler.peek(now)
        work = (any(s is not None and s.prefilling for s in self._slots)
                or (head is not None and head.rid not in self._preempted))
        if not work:
            return budget       # nothing to defer; streak untouched
        if self._defer_streak >= self._slo_max_defer:
            self._defer_streak = 0
            return budget
        self._defer_streak += 1
        self.slo_deferred_steps += 1
        if self.telemetry is not None:
            self.telemetry.counter("serving/slo_deferred_steps").inc()
        return 0

    def _schedule(self, now: float, finished: List[RequestResult]) -> None:
        """One iteration of the admit/prefill side of the serving loop
        (ISSUE 8): continue in-flight chunked prefills (priority, then
        admission order), then admit — preempting lower-priority slots
        when the policy allows — all under this iteration's prefill
        token budget. Swap-ins ride free (a resume is an HBM copy, not
        prefill compute), so a preempted request never waits on budget.

        Prefix-cache mode admits ONE request per scheduler call (each
        admission consumes pool blocks the next ``fits`` check must
        see), matches the prompt against the radix index, pins + names
        the matched block chain in the slot's table, runs the COW fork
        copies, and prefills only the unmatched suffix — bucketed by
        SUFFIX length (and chunked under a prefill budget), so a long
        shared system prompt with a short unique tail prefills in the
        smallest bucket."""
        budget = self._iteration_prefill_budget(now)
        # (1) in-flight chunked prefills first: an admitted prompt
        # finishes prefilling before new admissions eat the budget
        # (Sarathi's stall-free ordering — decode-phase slots are
        # protected by the budget itself). EXCEPT when the queue head
        # strictly outranks a prefilling slot (same double guard as
        # preemption): its budget share is yielded so the admission
        # loop below can preempt and admit the head — otherwise a
        # lower-class long prompt's chunking would block an interactive
        # arrival for its whole prefill (priority inversion).
        spent = self._continue_prefills(now, budget, 0, finished,
                                        yield_to_head=True)
        # (2) admission (+ preemption to make room)
        while True:
            if budget is not None and spent >= budget:
                head = self.scheduler.peek(now)
                if head is None or head.rid not in self._preempted:
                    break
            pairs = self.scheduler.admit(now, fits=self._admit_fits,
                                         limit=1)
            if not pairs:
                if not self._try_preempt(now):
                    break
                continue
            (req, slot), = pairs
            if req.rid in self._preempted:
                self._resume(slot, req, now)
                continue
            spent += self._admit_one(
                slot, req, now, None if budget is None else budget - spent,
                finished)
        # (3) leftover budget back to whoever is still prefilling (the
        # head either got placed above or cannot be placed at all —
        # idling the budget would help nobody)
        self._continue_prefills(now, budget, spent, finished,
                                yield_to_head=False)

    def _continue_prefills(self, now: float, budget: Optional[int],
                           spent: int, finished: List[RequestResult],
                           yield_to_head: bool) -> int:
        """Advance in-flight chunked prefills in (priority, admission)
        order under the remaining budget. With ``yield_to_head``, a
        slot that the best arrived queue head strictly outranks (raw
        class AND aged effective priority — preemption's guard) is
        skipped: its budget share belongs to the head the admission
        loop is about to place — into a free slot, or (policy
        permitting) into this very slot after preempting it. If the
        head turns out unplaceable, the post-admission leftover pass
        returns the yielded budget to the skipped slot, so yielding
        never idles an iteration."""
        head = self.scheduler.peek(now) if yield_to_head else None
        eff = self.scheduler.effective_priority
        pre = sorted((i for i, s in enumerate(self._slots)
                      if s is not None and s.prefilling),
                     key=lambda i: (self._slots[i].request.priority,
                                    self._slots[i].order))
        for slot in pre:
            if budget is not None and spent >= budget:
                break
            st = self._slots[slot]
            if (head is not None
                    and head.priority < st.request.priority
                    and eff(head, now) < eff(st.request, now)):
                continue
            spent += self._run_prefill_chunks(
                slot, now, None if budget is None else budget - spent,
                finished)
        return spent

    def _admit_one(self, slot: int, req: Request, now: float,
                   budget_left: Optional[int],
                   finished: List[RequestResult]) -> int:
        """Admit one fresh request into ``slot``: radix match + COW
        forks (prefix-cache mode), then prefill as much of the prompt
        as the budget allows (the rest continues on later iterations).
        Returns prefill tokens spent.

        A request whose ``deadline`` already passed is SHED here —
        after it won its slot but BEFORE any prefill compute (ISSUE 9:
        an answer nobody is waiting for must not waste the iteration
        budget): it finishes immediately with ``finish_reason
        "shed_deadline"`` and the slot is released. Preempted resumes
        never pass through here, so sunk prefill work is never thrown
        away by the shed."""
        plen = len(req.prompt)
        if req.deadline is not None and now > req.deadline:
            self.scheduler.release(slot)
            res = RequestResult(rid=req.rid, prompt_len=plen,
                                arrival_time=req.arrival_time,
                                admitted_time=now, priority=req.priority)
            res.finish_time = self._now(now)
            res.finish_reason = "shed_deadline"
            finished.append(res)
            if self.telemetry is not None:
                self.telemetry.counter("serving/shed_deadline").inc()
            if self.tenants is not None:
                self.tenants.note_shed(self.tenants.resolve(req.tenant_id))
            if self.tracer is not None:
                rt = self._rtraces.pop(req.rid, None)
                if rt is not None:
                    start = req.arrival_time if rt.submitted_t is None \
                        else rt.submitted_t
                    self.tracer.record(
                        "queue_wait", min(start, now), now,
                        trace_id=rt.trace_id, parent_id=rt.root, slot=slot)
                    self.tracer.end(rt.root_span, t=res.finish_time,
                                    finish_reason="shed_deadline")
            return 0
        start = 0
        if self.prefix is not None:
            total = plen + req.max_new_tokens + self._lookahead
            start, copies = self.prefix.admit(slot, req.prompt, total)
            for src, dst in copies:
                w0 = time.perf_counter() if self.tracer is not None \
                    else 0.0
                k, v = self._copy_fn(self.cache.k, self.cache.v,
                                     np.int32(src), np.int32(dst))
                self.cache.update_kv(k, v)
                if self.tracer is not None:
                    self._prog_note("block_copy",
                                    time.perf_counter() - w0)
        res = RequestResult(rid=req.rid, prompt_len=plen,
                            arrival_time=req.arrival_time,
                            admitted_time=now, priority=req.priority)
        tenant = self.tenants.resolve(req.tenant_id) \
            if self.tenants is not None else "default"
        self._slots[slot] = _SlotState(req, res, last_token=0,
                                       prefill_pos=start,
                                       prefill_total=plen,
                                       order=self._admit_seq,
                                       tenant=tenant)
        self._admit_seq += 1
        if self.tenants is not None:
            # per-tenant usage (ISSUE 13): the prompt lands on the bill
            # at admission; radix-matched tokens are the prefix cache's
            # per-tenant dividend (prefill the tenant did NOT pay for)
            self.tenants.note_admitted(tenant, plen)
            if start:
                self.tenants.note_prefill(tenant, 0, saved=start)
        if self.telemetry is not None:
            reg = self.telemetry
            reg.counter("serving/prefills").inc()
            reg.histogram("serving/queue_wait_ms").observe(
                max(now - req.arrival_time, 0.0) * 1e3)
        if self.tracer is not None:
            rt = self._rtraces.get(req.rid)
            if rt is not None:
                t_q0 = req.arrival_time if rt.submitted_t is None \
                    else rt.submitted_t
                self.tracer.record(
                    "queue_wait", min(t_q0, now), now,
                    trace_id=rt.trace_id, parent_id=rt.root, slot=slot,
                    priority=req.priority, radix_matched_tokens=start)
        if self._adaptive is not None:
            self._adaptive.reset_slot(slot)
        return self._run_prefill_chunks(slot, now, budget_left, finished)

    def _run_prefill_chunks(self, slot: int, now: float,
                            budget_left: Optional[int],
                            finished: List[RequestResult]) -> int:
        """Advance slot ``slot``'s prefill by whole chunks until its
        prompt is done or the budget is spent. Monolithic mode
        (``budget_left`` None, no chunk cap) is the single-chunk
        degenerate case and runs the exact pre-ISSUE-8 program path.
        The first generated token is picked only by the LAST chunk —
        intermediate chunk picks are never device_get (discarded, still
        async) — and TTFT is stamped at that commit (ISSUE 8
        latency-accounting fix)."""
        st = self._slots[slot]
        req = st.request
        eng = self.engine
        spent = 0
        while st.prefilling and (budget_left is None or spent < budget_left):
            remaining = st.prefill_total - st.prefill_pos
            chunk = remaining if self._chunk_max is None \
                else min(remaining, self._chunk_max)
            last = st.prefill_pos + chunk == st.prefill_total
            bucket = pick_bucket(chunk, self.buckets)
            ids = np.full((1, bucket), self.pad_token_id, np.int32)
            ids[0, :chunk] = np.asarray(
                req.prompt[st.prefill_pos:st.prefill_pos + chunk], np.int32)
            armed = self.tracer is not None
            if armed:
                t_span0 = self._now(now)
                t_wall0 = time.perf_counter()
            with jax.profiler.TraceAnnotation("dstpu/serving_prefill"):
                if self.prefix is not None:
                    pname = f"prefill_{bucket}"
                    out = self._prefill_fn(bucket)(
                        eng.params, *self.cache.carry(), jnp.asarray(ids),
                        self.cache.table_row(slot), np.int32(slot),
                        np.int32(st.prefill_pos), np.int32(chunk),
                        self._temp, self._next_rng())
                elif st.prefill_pos == 0 and last:
                    # whole prompt in one chunk: the monolithic bucket
                    # program (fresh bucket-sized cache + slot insert)
                    pname = f"prefill_{bucket}"
                    out = self._prefill_fn(bucket)(
                        eng.params, *self.cache.carry(), jnp.asarray(ids),
                        np.int32(slot), np.int32(chunk), self._temp,
                        self._next_rng())
                else:
                    pname = f"chunk_prefill_{bucket}"
                    out = self._chunk_fn(bucket)(
                        eng.params, *self.cache.carry(), jnp.asarray(ids),
                        np.int32(slot), np.int32(st.prefill_pos),
                        np.int32(chunk), self._temp, self._next_rng())
                self.cache.update(*out[:3])
            if armed:
                # host-stamped at the instants the loop already holds:
                # no fence added (under async dispatch this brackets the
                # dispatch; the LAST chunk's token fetch below is the
                # same fence the untraced engine always paid)
                self._prog_note(pname, time.perf_counter() - t_wall0)
                rt = self._rtraces.get(req.rid)
                if rt is not None:
                    self.tracer.record(
                        "prefill_chunk", t_span0, self._now(now),
                        trace_id=rt.trace_id, parent_id=rt.root,
                        program=pname, bucket=bucket, tokens=chunk,
                        slot=slot)
            st.prefill_pos += chunk
            # the budget is charged in BUCKET-PADDED tokens — the
            # compute actually dispatched — so one iteration's prefill
            # work genuinely stays near the cap (true-token charging
            # would let padding push real work past it); chunks are
            # never clamped below their natural size, since a padded
            # bucket costs the same forward whether half full or full
            spent += bucket
            self.prefill_tokens_computed += chunk
            self.prefill_chunks += 1
            st.result.prefill_chunks += 1
            if self.telemetry is not None:
                self.telemetry.counter("serving/prefill_chunks").inc()
            if self.tenants is not None:
                # billed at the same increment as the engine counter, so
                # per-tenant computed tokens sum EXACTLY to it
                self.tenants.note_prefill(st.tenant, chunk)
            if last:
                tok = int(jax.device_get(out[3]))  # dstpu-lint: fence=token emission: the chunk's final pick must reach the host stream
                self.prefill_calls += 1
                self.tokens_generated += 1
                st.last_token = tok
                st.result.tokens.append(tok)
                t_emit = self._now(now)
                st.result.first_token_time = t_emit
                st.result.token_times.append(t_emit)
                self._stream(st, [tok])
                ttft = max(t_emit - req.arrival_time, 0.0) * 1e3
                if self.telemetry is not None:
                    self.telemetry.histogram("serving/ttft_ms").observe(ttft)
                    self.telemetry.histogram(
                        f"serving/ttft_ms/p{metric_label(req.priority)}"
                    ).observe(ttft)
                if self.tenants is not None:
                    self.tenants.note_tokens(st.tenant, 1)
                    self.tenants.note_ttft(st.tenant, ttft)
                if armed:
                    # decode-phase residency starts at the first-token
                    # commit; closed at finish/preemption/cancel
                    rt = self._rtraces.get(req.rid)
                    if rt is not None:
                        rt.decode_span = self.tracer.begin(
                            "decode_segment", trace_id=rt.trace_id,
                            parent_id=rt.root, t=t_emit, slot=slot)
                done = self._maybe_finish(slot, now)
                if done is not None:
                    finished.append(done)
        return spent

    # -------------------------------------------------------- preemption
    def _try_preempt(self, now: float) -> bool:
        """Make room for the best waiting request by swapping out one
        strictly-lower-priority running slot (ISSUE 8). Called only
        after admission came up empty, i.e. the candidate is blocked on
        a slot or (block-paged) on pool blocks. Two guards bound
        thrash: the victim's RAW class must be strictly worse (a
        resumed request can never be preempted by the class that
        displaced it), and its AGED effective priority must be worse
        too — a victim that waiting has promoted past the candidate
        would rank AHEAD of it in the queue after resubmit, so evicting
        it would only swap it straight back in (the resume→preempt
        ping-pong this guard exists to prevent). Victim choice: the
        worst class, and within it the most recently admitted (least
        sunk work). Returns True if a slot was freed (the caller
        retries admission)."""
        if self.preemption is None:
            return False
        cand = self.scheduler.peek(now)
        if cand is None:
            return False
        eff = self.scheduler.effective_priority
        cand_eff = eff(cand, now)
        victims = [i for i, s in enumerate(self._slots)
                   if s is not None and s.request.priority > cand.priority
                   and eff(s.request, now) > cand_eff]
        if not victims:
            return False
        victim = max(victims, key=lambda i: (self._slots[i].request.priority,
                                             self._slots[i].order))
        try:
            self._preempt(victim, now)
        except SwapCapacityError:
            # swap buffer at its max_bytes cap (ISSUE 9 satellite): the
            # preemption is declined BEFORE any engine state mutated
            # (put happens first in _preempt) — the candidate waits for
            # a natural slot release instead of the host growing
            # unboundedly; surfaced via counter + gauge so operators
            # see sustained pressure
            self.swap_capacity_rejections += 1
            if self.telemetry is not None:
                self.telemetry.counter(
                    "serving/swap_capacity_rejections").inc()
            return False
        return True

    def _preempt(self, slot: int, now: float) -> None:
        """Swap slot ``slot``'s KV out to the host buffer and return its
        request to the arrival queue (original position — resubmit is
        arrival-ordered). The preempted interval counts as queue wait;
        the slot state (emitted tokens, chunk progress, drafter
        history) is parked host-side and reattached verbatim on resume,
        so the finished stream is bit-identical to an uninterrupted run
        (pinned by tests)."""
        st = self._slots[slot]
        self._build_swap_programs()
        armed = self.tracer is not None
        rt = self._rtraces.get(st.request.rid) if armed else None
        if armed:
            t_sw0 = self._now(now)
            w0 = time.perf_counter()
        length = int(jax.device_get(self.cache.lengths[slot]))  # dstpu-lint: fence=preemption swap-out: computed length bounds the parked blocks
        if self.prefix is not None:
            n_used = self.cache.blocks_for(length)
            table = jnp.asarray(self.cache.tables[slot])
            ko, vo = self._swap_out_fn(self.cache.k, self.cache.v, table)
            # park only the blocks the request actually computed into
            # (garbage gathers past n_used are dropped here); quantized
            # pools park payload+scale trees — the exact stored bytes,
            # at half (int8/fp8) the bf16 swap bandwidth
            host_k = _host_blocks(ko, n_used)
            host_v = _host_blocks(vo, n_used)
            self.swap.put(st.request.rid, host_k, host_v)
            # donate fully-computed prompt blocks to the radix index
            # (they are valid cached prefixes — the resume's re-match
            # usually finds them again and skips their upload), free the
            # rest; donate_upto caps at the COMPUTED length so a
            # mid-prefill preemption never donates unwritten tails
            self.prefix.finish(slot, donate_upto=length)
            self.swapped_blocks_out += n_used
        else:
            ko, vo = self._swap_out_fn(self.cache.k, self.cache.v,
                                       np.int32(slot))
            self.swap.put(st.request.rid,
                          np.asarray(jax.device_get(ko)),  # dstpu-lint: fence=preemption swap-out parks KV host-side
                          np.asarray(jax.device_get(vo)))
            n_used = 1
            self.swapped_blocks_out += 1      # the slot page
        self._slots[slot] = None
        self.scheduler.release(slot)
        self.scheduler.resubmit(st.request)
        st.result.preemptions += 1
        since = self._now(now)
        self._preempted[st.request.rid] = _Preempted(st, length, since)
        if armed:
            self._prog_note("swap_out", time.perf_counter() - w0)
            if rt is not None:
                # the decode segment ends where the swap began; the
                # swapped interval opens at the park instant and closes
                # on resume — preempted time lands in its own phase
                self.tracer.end(rt.decode_span, t=t_sw0,
                                reason="preempted")
                rt.decode_span = None
                self.tracer.record("swap_out", t_sw0, since,
                                   trace_id=rt.trace_id,
                                   parent_id=rt.root, program="swap_out",
                                   blocks=n_used, slot=slot)
                rt.swap_span = self.tracer.begin(
                    "swapped", trace_id=rt.trace_id, parent_id=rt.root,
                    t=since, blocks=n_used)
        self.preemptions += 1
        if self.tenants is not None:
            self.tenants.note_preemption(st.tenant)
        if self.telemetry is not None:
            reg = self.telemetry
            reg.counter("serving/preemptions").inc()
            reg.counter("serving/swapped_blocks_out").inc(
                n_used if self.prefix is not None else 1)

    def _resume(self, slot: int, req: Request, now: float) -> None:
        """Swap a preempted request back into ``slot``: upload its host
        KV, restore its length, and reattach its slot state. Block-paged
        mode first re-matches the prompt against the radix index —
        still-cached full prefix blocks are re-pinned and skipped by the
        upload (and a trie that learned a LONGER prefix while the
        request was parked fast-forwards a mid-prefill resume past it).
        Decode continues exactly where it left off."""
        rec = self._preempted.pop(req.rid)
        st = rec.state
        armed = self.tracer is not None
        rt = self._rtraces.get(req.rid) if armed else None
        if armed:
            t_in0 = self._now(now)
            w0 = time.perf_counter()
        host_k, host_v = self.swap.pop(req.rid)
        length = rec.length
        if self.prefix is not None:
            total = len(req.prompt) + req.max_new_tokens + self._lookahead
            shared = self.prefix.readmit(slot, req.prompt, total)
            # the trie may now hold MORE of the prompt than this request
            # had computed (another tenant donated it meanwhile): skip
            # the prefill ahead over the re-pinned shared prefix
            length = max(length, min(shared * self.cache.block_size,
                                     st.prefill_total))
            st.prefill_pos = max(st.prefill_pos, length) \
                if st.prefilling else st.prefill_pos
            n_used = jax.tree_util.tree_leaves(host_k)[0].shape[1]
            mb = self.cache.max_blocks_per_slot
            dst = np.full((mb,), self.cache.sentinel, np.int32)
            row = self.cache.tables[slot]
            dst[shared:n_used] = row[shared:n_used]
            up_k = _expand_blocks(host_k, mb)
            up_v = _expand_blocks(host_v, mb)
            out = self._swap_in_fn(self.cache.k, self.cache.v,
                                   _to_device(up_k), _to_device(up_v),
                                   jnp.asarray(dst), self.cache.lengths,
                                   np.int32(slot), np.int32(length))
            swapped_in = max(n_used - shared, 0)
        else:
            out = self._swap_in_fn(self.cache.k, self.cache.v,
                                   jnp.asarray(host_k), jnp.asarray(host_v),
                                   self.cache.lengths, np.int32(slot),
                                   np.int32(length))
            swapped_in = 1
        self.cache.update(*out)
        t_res = self._now(now)
        if armed:
            self._prog_note("swap_in", time.perf_counter() - w0)
            if rt is not None:
                self.tracer.end(rt.swap_span, t=t_in0)
                rt.swap_span = None
                self.tracer.record("swap_in", t_in0, t_res,
                                   trace_id=rt.trace_id,
                                   parent_id=rt.root, program="swap_in",
                                   blocks=swapped_in, slot=slot)
                if st.result.tokens:
                    rt.decode_span = self.tracer.begin(
                        "decode_segment", trace_id=rt.trace_id,
                        parent_id=rt.root, t=t_res, slot=slot,
                        resumed=True)
        gap = max(t_res - rec.since, 0.0)
        st.result.preempted_wall += gap
        if st.result.tokens:
            # decode-phase preemption (first token already out): this
            # gap must be discounted from the TPOT span at finish. A
            # mid-prefill park fell before TTFT — discounting it would
            # deflate TPOT toward zero.
            st.result.decode_preempted_wall += gap
        st.order = self._admit_seq
        self._admit_seq += 1
        self._slots[slot] = st
        if self._adaptive is not None:
            self._adaptive.reset_slot(slot)
        self.swapped_blocks_in += swapped_in
        if self.telemetry is not None:
            reg = self.telemetry
            reg.counter("serving/swapped_blocks_in").inc(swapped_in)
            # the preempted interval is queue wait (ISSUE 8 accounting
            # fix): it lands in the same histogram the initial admission
            # wait did
            reg.histogram("serving/queue_wait_ms").observe(gap * 1e3)

    def step(self, now: Optional[float] = None) -> List[RequestResult]:
        """One serving iteration: run the budgeted admit/prefill side
        (chunk continuations, admissions, preemptions — ISSUE 8), then
        decode one step for every DECODE-PHASE slot (slots still
        prefilling their prompt sit the decode out). Returns requests
        finished this iteration."""
        if not self._warm:
            self.warmup()
        if now is None:
            now = self._time()
        self._last_step_now = now
        self._account_kv_occupancy(now)
        if self.slo is not None:
            # SLO judgment rides the serving clock (ISSUE 13): virtual
            # traces replay their alert timelines deterministically.
            # Pure host work — no device interaction, no output change.
            self.slo.maybe_evaluate(now)
        if self._pending_submit_stamps:
            # first step after a context-carrying submit: this instant
            # is where the dispatcher's router_queue span ends, so the
            # engine-side queue_wait begins exactly here (the phases
            # tile; stamping a since-cancelled record is harmless)
            for rt in self._pending_submit_stamps:
                rt.submitted_t = now
            self._pending_submit_stamps.clear()
        finished: List[RequestResult] = []
        with jax.profiler.TraceAnnotation("dstpu/serving_admit"):
            self._schedule(now, finished)
        active_slots = [i for i, s in enumerate(self._slots)
                        if s is not None and not s.prefilling]
        if self.telemetry is not None:
            # iteration-level gauges: slot occupancy after admission
            # (prefilling slots included) and the decode batch's fill
            # ratio (decode-phase slots only — they diverge under
            # chunked prefill)
            occupied = sum(s is not None for s in self._slots)
            self.telemetry.gauge("serving/slot_occupancy").set(
                occupied / self.num_slots)
            if active_slots:
                self.telemetry.gauge("serving/batch_fill_ratio").set(
                    len(active_slots) / self.num_slots)
        if not active_slots:
            # no decode ran: a later gap against _last_decode_t would
            # fold queue-idle time into the TPOT-SLO EMA
            self._last_decode_t = None
            return finished
        self._note_decode_gap()
        if self.spec is not None:
            return self._spec_step(now, active_slots, finished)
        return self._plain_step(now, active_slots, finished)

    def _account_kv_occupancy(self, now: float) -> None:
        """Integrate per-tenant KV occupancy over the interval since
        the last step (ISSUE 13): each occupied slot bills its tenant
        for the pool blocks its table names (block-paged — HOST numpy,
        no device read; shared radix blocks bill every tenant that
        depends on them) or its whole slot row (slot-paged). dt is
        engine-clock time, so virtual traces produce deterministic
        block-second bills."""
        if self.tenants is None:
            return
        last = self._acct_last_t
        self._acct_last_t = now
        if last is None:
            return
        dt = now - last
        if dt <= 0:
            return
        paged = self.prefix is not None
        for i, st in enumerate(self._slots):
            if st is None:
                continue
            if paged:
                blocks = int((self.cache.tables[i]
                              != self.cache.sentinel).sum())
            else:
                blocks = 1
            self.tenants.note_kv_occupancy(st.tenant, blocks, dt,
                                           self._kv_bytes_per_block)

    def _iter_trace(self) -> str:
        """Lazy engine-scope trace for iteration-level spans (decode
        steps, speculative draft/verify) — structural context that is
        not any single request's lifecycle."""
        if self._engine_trace is None:
            self._engine_trace = self.tracer.new_trace()
        return self._engine_trace

    def _note_decode_gap(self) -> None:
        """EMA of wall time between consecutive decode invocations —
        the signal the ``tpot_slo_ms`` admission guard watches. Host
        wall, not the injected clock: the guard protects real decode
        latency from real prefill compute."""
        t = time.perf_counter()
        if self._last_decode_t is not None:
            gap = t - self._last_decode_t
            self._decode_gap_ema = gap if self._decode_gap_ema is None \
                else 0.7 * self._decode_gap_ema + 0.3 * gap
        self._last_decode_t = t

    def _plain_step(self, now: float, active_slots: List[int],
                    finished: List[RequestResult]) -> List[RequestResult]:
        """One plain decode iteration: one token for every active slot.
        Also the speculative path's fallback when drafting proposes
        nothing anywhere (a 1-wide step beats an empty k-wide verify)."""
        toks = np.full((self.num_slots,), self.pad_token_id, np.int32)
        for i in active_slots:
            toks[i] = self._slots[i].last_token
        active = np.zeros((self.num_slots,), bool)
        active[active_slots] = True
        armed = self.tracer is not None
        if armed:
            t_dec0 = self._now(now)
        t0 = time.perf_counter()
        with jax.profiler.TraceAnnotation("dstpu/serving_decode"):
            out = self._decode(self.engine.params, *self.cache.carry(),
                               *self._table_args(),
                               jnp.asarray(toks), jnp.asarray(active),
                               self._temp, self._next_rng())
            self.cache.update(*out[:3])
            nxt = np.asarray(jax.device_get(out[3]))  # dstpu-lint: fence=token emission: decode's picks feed host continuations + streams
        dt = time.perf_counter() - t0
        self.decode_wall += dt
        if armed:
            # the token fetch above IS a fence, so this wall is honest
            # device-inclusive time — the attribution 'achieved' clock
            self._prog_note("decode", dt)
            self.tracer.record("decode_step", t_dec0, self._now(now),
                               trace_id=self._iter_trace(),
                               program="decode",
                               n_slots=len(active_slots))
        self.decode_steps += 1
        self._active_slot_iterations += len(active_slots)
        if self.telemetry is not None:
            self.telemetry.counter("serving/decode_steps").inc()
            self.telemetry.counter("serving/slot_iterations_active").inc(
                len(active_slots))
        t_emit = self._now(now)
        for i in active_slots:
            st = self._slots[i]
            tok = int(nxt[i])
            st.result.tokens.append(tok)
            st.result.token_times.append(t_emit)
            st.result.decode_calls += 1
            st.last_token = tok
            self.tokens_generated += 1
            if self.tenants is not None:
                self.tenants.note_tokens(st.tenant, 1)
            self._stream(st, [tok])
            done = self._maybe_finish(i, now)
            if done is not None:
                finished.append(done)
        return finished

    def _spec_step(self, now: float, active_slots: List[int],
                   finished: List[RequestResult]) -> List[RequestResult]:
        """One speculative decode iteration: draft up to k tokens per
        slot, verify them ALL in one target forward, emit each slot's
        accepted prefix + one bonus/correction token.

        Per-step variable emission: a slot commits between 1 and
        ``draft_len + 1`` tokens per invocation (never 0 — the
        correction token guarantees baseline-speed progress even at zero
        acceptance). The verify width is bucketed over the FIXED
        k_buckets set — the smallest bucket holding the longest draft
        actually PROPOSED this step — so adaptive-k transitions reuse
        compiled programs, and a step where drafting found nothing at
        all falls back to the (also warmed) 1-wide plain decode program
        instead of paying an empty k-wide verify. Per-slot draft length
        is additionally capped at ``remaining_budget - 1``: emission can
        then never overshoot max_new_tokens, so output truncation
        happens only at EOS (where the slot retires and its dead cache
        tail is reclaimed by the next prefill anyway)."""
        spec = self.spec
        nslots = self.num_slots
        want = np.zeros((nslots,), np.int32)
        for i in active_slots:
            st = self._slots[i]
            remaining = st.request.max_new_tokens - len(st.result.tokens)
            k_des = (self._adaptive.desired_k(i)
                     if self._adaptive is not None else spec.k_max)
            want[i] = max(0, min(k_des, remaining - 1))
        kb = pick_k_bucket(max(int(want.max()), 1), spec.k_buckets)
        # drafters read each slot's full token stream (prompt + emitted,
        # derived — result.tokens IS the emitted history; slots still
        # PREFILLING have no stream yet and sit speculation out)
        histories = [list(s.request.prompt) + s.result.tokens
                     if s is not None and not s.prefilling else None
                     for s in self._slots]
        armed = self.tracer is not None
        if armed:
            t_sp0 = self._now(now)
        t0 = time.perf_counter()
        with jax.profiler.TraceAnnotation("dstpu/serving_draft"):
            drafts, lens = self._drafter.propose(histories, want, kb)
        lens = np.minimum(np.asarray(lens, np.int32), want)
        dt = time.perf_counter() - t0
        self._draft_wall += dt
        self.decode_wall += dt
        if armed:
            self.tracer.record("spec_draft", t_sp0, self._now(now),
                               trace_id=self._iter_trace(), k_bucket=kb,
                               n_slots=len(active_slots))
        longest = int(lens.max())
        if longest == 0:
            # nothing proposed anywhere (e.g. prompt-lookup on novel
            # text): the plain decode step emits the identical token at
            # 1-token width
            return self._plain_step(now, active_slots, finished)
        # shrink the verify width to the drafts we actually have (a
        # partial match needs a narrower program than the full want)
        kb = pick_k_bucket(longest, spec.k_buckets)
        tokens = np.full((nslots, kb + 1), self.pad_token_id, np.int32)
        active = np.zeros((nslots,), bool)
        for i in active_slots:
            tokens[i, 0] = self._slots[i].last_token
            n = int(lens[i])
            tokens[i, 1:1 + n] = drafts[i, :n]
            active[i] = True
        if armed:
            t_vf0 = self._now(now)
        t0 = time.perf_counter()
        with jax.profiler.TraceAnnotation("dstpu/serving_verify"):
            out = self._verify_fn(kb)(
                self.engine.params, *self.cache.carry(),
                *self._table_args(),
                jnp.asarray(tokens), jnp.asarray(lens),
                jnp.asarray(active), self._temp, self._next_rng())
            self.cache.update(*out[:3])
            out_tokens = np.asarray(jax.device_get(out[3]))  # dstpu-lint: fence=token emission: accepted drafts reach host streams
            n_emit = np.asarray(jax.device_get(out[4]))  # dstpu-lint: fence=token emission: accepted drafts reach host streams
        dt = time.perf_counter() - t0
        self._verify_wall += dt
        self.decode_wall += dt
        if armed:
            self._prog_note(f"verify_{kb}", dt)
            self.tracer.record("spec_verify", t_vf0, self._now(now),
                               trace_id=self._iter_trace(),
                               program=f"verify_{kb}",
                               n_slots=len(active_slots))
        self.decode_steps += 1
        self._active_slot_iterations += len(active_slots)
        reg = self.telemetry
        if reg is not None:
            reg.counter("serving/decode_steps").inc()
            reg.counter("serving/spec_verify_steps").inc()
            reg.counter("serving/slot_iterations_active").inc(
                len(active_slots))
        t_emit = self._now(now)
        for i in active_slots:
            st = self._slots[i]
            n = int(n_emit[i])
            emitted = [int(t) for t in out_tokens[i, :n]]
            n_drafted, n_accepted = int(lens[i]), n - 1
            if (self.eos_token_id is not None
                    and self.eos_token_id in emitted):
                # EOS inside the accepted block: baseline decode stops
                # at its first EOS, so every token behind it is dropped
                # (the slot retires; its dead cache tail is overwritten
                # by the next prefill into the slot)
                emitted = emitted[:emitted.index(self.eos_token_id) + 1]
            st.result.tokens.extend(emitted)
            st.result.token_times.extend([t_emit] * len(emitted))
            st.result.decode_calls += 1
            st.last_token = emitted[-1]
            self.tokens_generated += len(emitted)
            if self.tenants is not None:
                self.tenants.note_tokens(st.tenant, len(emitted))
            # stream only the ACCEPTED (post-truncation) block — a
            # rejected draft token is never observable
            self._stream(st, emitted)
            self.spec_drafted_tokens += n_drafted
            self.spec_accepted_tokens += n_accepted
            if self._adaptive is not None:
                self._adaptive.update(i, n_accepted, n_drafted)
            if reg is not None:
                reg.counter("serving/spec_drafted_tokens").inc(n_drafted)
                reg.counter("serving/spec_accepted_tokens").inc(n_accepted)
                reg.histogram("serving/accepted_tokens_per_step",
                              buckets=_TOKENS_PER_STEP_BUCKETS).observe(n)
            done = self._maybe_finish(i, now)
            if done is not None:
                finished.append(done)
        return finished

    # ----------------------------------------------------------------- run
    def run(self, requests: Sequence[Request], *,
            warmup: bool = True) -> List[RequestResult]:
        """Serve a trace to completion. ``arrival_time``s are offsets from
        the moment run() starts; the engine idles (real clock: sleeps)
        until the next arrival when no slot is active."""
        for r in requests:
            self.submit(r)
        if warmup:
            self.warmup()
        t0 = self._time()
        self._run_t0 = t0
        tokens_before = self.tokens_generated
        results: List[RequestResult] = []
        stall = 0
        while self.pending:
            now = self._time() - t0
            if (not any(s is not None for s in self._slots)
                    and self.scheduler.waiting):
                nxt = self.scheduler.next_arrival()
                if nxt is not None and nxt > now:
                    if self._real_clock:
                        time.sleep(min(nxt - now, 0.05))
                    stall += 1
                    if stall > 10_000_000:
                        raise EngineInvariantError(
                            "serving clock is not advancing toward the "
                            "next arrival (non-monotonic time_fn?)")
                    continue
            stall = 0
            results.extend(self.step(now))
        if self.telemetry is not None:
            self._record_run_telemetry(
                len(results), self._time() - t0,
                self.tokens_generated - tokens_before)
        return results

    # ------------------------------------------------------------- telemetry
    def recompile_count(self) -> int:
        """Excess jit-cache entries across the serving programs — any
        value > 0 means some program recompiled after warmup (an
        argument's shape/dtype/sharding varied)."""
        return sum(max(0, v - 1) for v in self.program_cache_sizes().values())

    def _record_run_telemetry(self, n_finished: int, elapsed: float,
                              run_tokens: int) -> None:
        reg = self.telemetry
        reg.gauge("serving/run_elapsed_s").set(elapsed)
        if elapsed > 0:
            reg.gauge("serving/finished_requests_per_sec").set(
                n_finished / elapsed)
            # THIS run's tokens only — self.tokens_generated is cumulative
            # across runs while elapsed resets, so using it would inflate
            # the rate on every run() after the first
            reg.gauge("serving/tokens_per_sec").set(run_tokens / elapsed)
        reg.gauge("serving/peak_queue_depth").set(
            self.scheduler.peak_queue_depth)
        reg.gauge("serving/compiled_programs").set(self.program_count)
        reg.gauge("serving/jit_cache_entries").set(
            sum(self.program_cache_sizes().values()))
        reg.gauge("serving/recompiles").set(self.recompile_count())
        if self.decode_steps:
            reg.gauge("serving/mean_batch_fill_ratio").set(
                self._active_slot_iterations /
                (self.decode_steps * self.num_slots))
        if self.swap is not None:
            reg.gauge("serving/swap_buffer_bytes").set(
                self.swap.bytes_stored)
            reg.gauge("serving/swap_buffer_peak_bytes").set(
                self.swap.peak_bytes)
            if self.swap.max_bytes is not None:
                reg.gauge("serving/swap_buffer_max_bytes").set(
                    self.swap.max_bytes)
        if self.prefix is not None:
            # KV capacity gauges (ISSUE 12): pool bytes incl. quantized
            # scales, and the blocks-per-byte capacity lever kv_dtype
            # buys (int8 ~1.94x bf16, fp8 ~3.88x fp32)
            reg.gauge("serving/kv_pool_bytes").set(self.cache.hbm_bytes())
            reg.gauge("serving/kv_blocks_per_mib").set(
                self.cache.blocks_per_mib())
            # cumulative cache effectiveness (counters already streamed
            # per admit/evict/fork by PrefixCache); occupancy covers
            # running slots' blocks + radix-cached blocks
            reg.gauge("serving/prefix_hit_rate").set(self.prefix.hit_rate())
            reg.gauge("serving/prefix_pool_occupancy").set(
                self.cache.occupancy())
            reg.gauge("serving/prefix_cached_blocks").set(
                self.prefix.cached_blocks())
        if self.spec is not None:
            if self.spec_drafted_tokens:
                reg.gauge("serving/spec_acceptance_rate").set(
                    self.spec_accepted_tokens / self.spec_drafted_tokens)
            if self._active_slot_iterations:
                # decode-phase tokens per slot-step: 1.0 = baseline, the
                # spec speedup headroom is this number (verify cost aside)
                reg.gauge("serving/spec_tokens_per_slot_step").set(
                    (self.tokens_generated - self.prefill_calls)
                    / self._active_slot_iterations)
            wall = self._draft_wall + self._verify_wall
            if wall > 0:
                # drafting's share of the decode hot path (host wall):
                # n-gram drafting should be noise, a draft MODEL should
                # stay well under the verify forward
                reg.gauge("serving/spec_draft_overhead_frac").set(
                    self._draft_wall / wall)
        reg.flush()
