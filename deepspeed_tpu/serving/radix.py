"""Radix-tree prefix index with copy-on-write sharing (ISSUE 6).

The SGLang RadixAttention idea on top of the block pool
(serving/kv_blocks.py): a trie over token sequences at BLOCK granularity
— each node owns one full pool block of ``block_size`` tokens, keyed by
that block's token tuple, valid only beneath its ancestors (KV entries
depend on every preceding token AND on absolute position, so a cached
block is reusable exactly when the whole path from the root matches).

  * **match-on-admit**: walk the trie along the new prompt; every
    exact-block hit is pinned (refcount++) and named directly in the
    slot's table — its prefill is skipped entirely.  When the walk
    stops at a child sharing only a PARTIAL prefix of its block (or the
    prompt ends mid-block), that block is COW-FORKED: a fresh block is
    allocated, the shared block's contents are copied on device, and
    the slot's table names the fork — because the suffix prefill /
    decode steps will partially overwrite that block, and the shared
    original may be pinned by other running slots.  Fork only when a
    shared block would be partially overwritten; full-block hits are
    shared in place, read-only.
  * **insert-on-finish**: a finished request donates its prompt's full
    blocks to the trie (ownership moves from the slot to the index;
    refcount drops to 0 → evictable) instead of freeing them.  Blocks
    whose token key already exists in the trie are freed as redundant.
  * **LRU eviction**: when admission needs more blocks than the free
    list holds, unpinned LEAF nodes are evicted oldest-first (interior
    nodes are unevictable while children reference their context;
    evicting a pinned block is an error, pinned by tests).

Everything here is host-side policy over numpy/int bookkeeping — the
only device work COW generates is the one-block copy program the engine
runs per fork (inference/engine.block_copy_program).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from deepspeed_tpu.serving.errors import (EngineInvariantError,
                                          KVLifecycleError)
from deepspeed_tpu.serving.kv_blocks import BlockKVPool


class RadixNode:
    """One cached full block: ``key`` is its block_size-token tuple,
    ``block`` the pool block holding those tokens' KV."""

    __slots__ = ("key", "block", "parent", "children", "last_used")

    def __init__(self, key, block, parent):
        self.key = key
        self.block = block
        self.parent = parent
        self.children: Dict[tuple, "RadixNode"] = {}
        self.last_used = 0

    def __repr__(self):
        return (f"RadixNode(block={self.block}, depth_key={self.key!r:.40}, "
                f"children={len(self.children)})")


class _SlotRecord:
    """Host state of one admitted slot's block ownership."""

    __slots__ = ("prompt", "matched_nodes", "owned")

    def __init__(self, prompt, matched_nodes, owned):
        self.prompt = prompt
        self.matched_nodes = matched_nodes   # pinned full-block trie nodes
        self.owned = owned                   # private blocks, table order


class PrefixCache:
    """Couples the block pool and the radix trie into the serving
    engine's admit/finish protocol, and carries the prefix-cache
    telemetry counters (ISSUE 6 satellites)."""

    def __init__(self, pool: BlockKVPool, registry=None):
        self.pool = pool
        self.registry = registry
        self.root = RadixNode(None, None, None)
        self._records: Dict[int, _SlotRecord] = {}
        self._tick = 0
        # fits() -> admit() run the same match walk back-to-back per
        # admission (and fits re-fires every step while the queue head
        # waits on blocks): memoize the last match, guarded by a trie
        # structure counter so any insert/evict invalidates it
        self._mut = 0
        self._match_memo = None  # (prompt_key, full, partial, mut)
        # cumulative accounting (bench reads these even with telemetry off)
        self.hit_tokens = 0
        self.miss_tokens = 0
        self.blocks_cowed = 0
        self.blocks_evicted = 0

    # ------------------------------------------------------------- match
    def _touch(self, node: RadixNode) -> None:
        self._tick += 1
        while node is not None and node.parent is not None:
            node.last_used = self._tick
            node = node.parent

    def match(self, prompt: Sequence[int], cap: int
              ) -> Tuple[List[RadixNode], Optional[Tuple[RadixNode, int]]]:
        """Longest cached prefix of ``prompt[:cap]``: a chain of exact
        full-block nodes, plus at most one trailing (node, p) partial
        overlap of 1 <= p < block_size tokens (the COW-fork candidate).
        ``cap`` is prompt_len - 1 in practice: at least one prompt token
        must stay unmatched so the suffix prefill has a position to pick
        the first generated token from."""
        bs = self.pool.block_size
        node, full, t = self.root, [], 0
        while cap - t >= bs:
            child = node.children.get(tuple(prompt[t:t + bs]))
            if child is None:
                break
            full.append(child)
            node = child
            t += bs
        partial = None
        remaining = prompt[t:cap]
        if remaining:
            best_p = 0
            for key, child in node.children.items():
                p = 0
                for a, b in zip(key, remaining):
                    if a != b:
                        break
                    p += 1
                if p > best_p:
                    best_p, partial = p, (child, p)
        return full, partial

    def _match_memoized(self, prompt: Sequence[int]):
        """match(prompt, len-1) with the fits()->admit() memo. match()
        depends only on trie STRUCTURE (children keys), never on
        refcounts or LRU ticks, so the memo is valid exactly while
        ``_mut`` is unchanged."""
        key = tuple(prompt)
        memo = self._match_memo
        if memo is not None and memo[0] == key and memo[3] == self._mut:
            return memo[1], memo[2]
        full, partial = self.match(prompt, len(prompt) - 1)
        self._match_memo = (key, full, partial, self._mut)
        return full, partial

    # ------------------------------------------------------------ admit
    def evictable_count(self) -> int:
        return sum(1 for _ in self._iter_evictable())

    def _iter_evictable(self):
        stack = [self.root]
        while stack:
            node = stack.pop()
            stack.extend(node.children.values())
            if (node is not self.root and not node.children
                    and self.pool.ref[node.block] == 0):
                yield node

    def evict_node(self, node: RadixNode) -> None:
        """Remove one LEAF node from the trie and free its block.
        Errors on a pinned block (a running slot still names it) or an
        interior node (its children's KV depends on its context)."""
        if node.children:
            raise KVLifecycleError(
                f"evicting interior radix node {node!r}: its children's "
                f"cached KV is only valid beneath it")
        if self.pool.ref[node.block] != 0:
            raise KVLifecycleError(
                f"evicting pinned block {node.block} "
                f"(refcount {self.pool.ref[node.block]})")
        del node.parent.children[node.key]
        self._mut += 1
        self.pool.free_block(node.block)
        self.blocks_evicted += 1
        if self.registry is not None:
            self.registry.counter("serving/blocks_evicted").inc()

    def _evict_lru(self, n_needed: int) -> None:
        """Evict unpinned leaves oldest-first until the free list holds
        ``n_needed`` blocks (evicting a leaf may expose its parent as the
        next candidate, so re-scan per round)."""
        while self.pool.free_count < n_needed:
            victims = sorted(self._iter_evictable(),
                             key=lambda nd: nd.last_used)
            if not victims:
                raise EngineInvariantError(
                    f"need {n_needed} blocks, have {self.pool.free_count} "
                    f"free and nothing evictable (admission gating bug)")
            self.evict_node(victims[0])

    def _evictable_cascade(self, exclude=frozenset()) -> int:
        """Blocks the LRU pass could EVENTUALLY free: a node counts iff
        its whole subtree (itself included) is unpinned and outside
        ``exclude`` — evicting leaves exposes their parents, so a clean
        3-deep chain yields 3 blocks even though only its leaf is
        evictable right now. ``_iter_evictable`` (current leaves only)
        would under-count that cascade and deadlock admission on pools
        barely bigger than one request."""

        def walk(node):
            clean = node is self.root or (
                self.pool.ref[node.block] == 0
                and node.block not in exclude)
            n = 0
            for child in node.children.values():
                cn, cclean = walk(child)
                n += cn
                clean = clean and cclean
            if node is not self.root and clean:
                n += 1
            return n, clean

        return walk(self.root)[0]

    def fits(self, prompt: Sequence[int], total_tokens: int) -> bool:
        """Admission predicate: can ``blocks_for(total_tokens)`` minus the
        shared full-match blocks be served from free + eventually-
        evictable? Matched blocks are EXCLUDED from the evictable side —
        admit() pins them before evicting, so a matched unpinned leaf
        cannot be an LRU victim for the very request that wants to share
        it (a dry-run that counted it would overstate capacity and trip
        admit's eviction into a RuntimeError)."""
        full, partial = self._match_memoized(prompt)
        matched = {node.block for node in full}
        need = self.pool.blocks_for(total_tokens) - len(full)
        return need <= (self.pool.free_count
                        + self._evictable_cascade(matched))

    def admit(self, slot: int, prompt: Sequence[int], total_tokens: int
              ) -> Tuple[int, List[Tuple[int, int]]]:
        """Build slot ``slot``'s block table for a request needing
        ``total_tokens`` of KV (prompt + max_new + lookahead): share the
        matched prefix, allocate the rest.  Returns ``(matched_len,
        copies)`` where ``matched_len`` prompt tokens are already cached
        (prefill only the suffix) and ``copies`` is the [(src, dst)]
        block-copy list the engine must run BEFORE the suffix prefill
        (the COW forks)."""
        pool = self.pool
        bs = pool.block_size
        prompt = list(prompt)
        full, partial = self._match_memoized(prompt)
        self._match_memo = None
        n_total = pool.blocks_for(total_tokens)
        # (The partial COW source needs no pin: even if evicted and
        # reallocated, nothing can WRITE it on device before the copy
        # program the engine issues right after this call — device
        # programs execute in issue order.)
        table = self._pin_evict_build(slot, full, n_total)
        owned: List[int] = []
        copies: List[Tuple[int, int]] = []
        matched = len(full) * bs
        if partial is not None:
            node, p = partial
            fork = pool.alloc_block()
            copies.append((node.block, fork))
            table[len(full)] = fork
            owned.append(fork)
            matched += p
            self.blocks_cowed += 1
            self._touch(node)
            if self.registry is not None:
                self.registry.counter("serving/blocks_cowed").inc()
        owned.extend(self._alloc_rest(table, len(full) + len(owned),
                                      n_total))
        self._records[slot] = _SlotRecord(prompt, full, owned)
        pool.invalidate_tables()
        miss = len(prompt) - matched
        self.hit_tokens += matched
        self.miss_tokens += miss
        if self.registry is not None:
            self.registry.counter("serving/prefix_hit_tokens").inc(matched)
            self.registry.counter("serving/prefix_miss_tokens").inc(miss)
        return matched, copies

    def _pin_evict_build(self, slot: int, full: List[RadixNode],
                         n_total: int):
        """Shared admit/readmit table construction: pin the matched
        chain BEFORE evicting (a matched unpinned leaf must not become
        an LRU victim of its own admission), make room, rebuild the
        slot's table with the matched blocks leading, and touch the
        chain's LRU clock. Returns the (host numpy) table row."""
        pool = self.pool
        for node in full:
            pool.pin(node.block)
        self._evict_lru(n_total - len(full))
        table = pool.tables[slot]
        table[:] = pool.sentinel
        for j, node in enumerate(full):
            table[j] = node.block
        if full:
            self._touch(full[-1])
        return table

    def _alloc_rest(self, table, start_j: int, n_total: int) -> List[int]:
        """Allocate the slot's private blocks for table positions
        ``start_j .. n_total`` (shared admit/readmit tail)."""
        owned: List[int] = []
        for j in range(start_j, n_total):
            blk = self.pool.alloc_block()
            table[j] = blk
            owned.append(blk)
        return owned

    def readmit(self, slot: int, prompt: Sequence[int],
                total_tokens: int) -> int:
        """Rebuild a PREEMPTED request's block table on resume (ISSUE 8
        swap-in): re-pin whatever full prompt-prefix blocks the trie
        still holds — their KV is keyed by the same tokens at the same
        positions, so the host upload skips them — allocate private
        blocks for the rest, and register the slot record so a later
        ``finish``/preempt donates normally. Unlike :meth:`admit` there
        is no COW fork (a partially-overlapping block's content comes
        from the host swap copy, not a device fork) and no hit/miss
        token accounting (re-matched blocks avoid swap-in UPLOADS, not
        prefill compute — counting them as prefix hits would inflate
        the cache's effectiveness). Returns the number of re-pinned
        leading shared blocks; the caller uploads host KV only for
        block positions at or past that count."""
        pool = self.pool
        prompt = list(prompt)
        full, _partial = self._match_memoized(prompt)
        self._match_memo = None
        n_total = pool.blocks_for(total_tokens)
        table = self._pin_evict_build(slot, full, n_total)
        owned = self._alloc_rest(table, len(full), n_total)
        self._records[slot] = _SlotRecord(prompt, full, owned)
        pool.invalidate_tables()
        return len(full)

    # ----------------------------------------------------------- finish
    def finish(self, slot: int, donate_upto: Optional[int] = None) -> None:
        """Release slot ``slot``: unpin its shared prefix, donate its
        prompt's full private blocks to the trie (insert-on-finish), and
        free everything else (the partial prompt tail and every decode
        block — generated tokens are not indexed: matching happens
        against PROMPTS, and a prompt extending into another request's
        output is not the workload prefix caching targets).

        ``donate_upto`` (preemption swap-out, ISSUE 8) caps donation at
        the tokens the slot actually COMPUTED: a request preempted
        mid-chunked-prefill has only written ``donate_upto`` positions,
        and donating a block whose tail was never written would serve
        garbage KV to every future match. Mid-decode preemption passes
        its current length, which is >= the prompt length, so the cap
        is inert there and the whole prompt donates as on a normal
        finish."""
        rec = self._records.pop(slot, None)
        if rec is None:
            return
        pool = self.pool
        bs = pool.block_size
        for node in rec.matched_nodes:
            pool.unpin(node.block)
        parent = rec.matched_nodes[-1] if rec.matched_nodes else self.root
        j = len(rec.matched_nodes)
        owned = list(rec.owned)
        cap = len(rec.prompt) if donate_upto is None \
            else min(len(rec.prompt), donate_upto)
        while owned and (j + 1) * bs <= cap:
            blk = owned.pop(0)
            key = tuple(rec.prompt[j * bs:(j + 1) * bs])
            child = parent.children.get(key)
            if child is not None:
                pool.free_block(blk)       # an identical block is cached
            else:
                child = RadixNode(key, blk, parent)
                parent.children[key] = child
                self._mut += 1
            self._touch(child)
            parent = child
            j += 1
        for blk in owned:
            pool.free_block(blk)
        pool.tables[slot][:] = pool.sentinel
        pool.invalidate_tables()

    # -------------------------------------------------------- telemetry
    def hit_rate(self) -> float:
        total = self.hit_tokens + self.miss_tokens
        return self.hit_tokens / total if total else 0.0

    def cached_blocks(self) -> int:
        """Blocks currently owned by the trie (shared + evictable)."""
        n, stack = 0, [self.root]
        while stack:
            node = stack.pop()
            stack.extend(node.children.values())
            n += node is not self.root
        return n
