"""Iteration-level (continuous-batching) request scheduler.

Orca-style scheduling (PAPERS.md; the reference's serving path has no
analog — its InferenceEngine runs one static batch to completion): the
unit of scheduling is ONE DECODE ITERATION, not one batch. Between decode
steps the scheduler admits waiting requests into whatever slots are free,
so a drained slot is refilled immediately instead of idling until the
longest request in a static batch finishes — reclaiming the up-to
(B-1)/B of aggregate capacity a run-to-completion batch wastes on
stragglers.

Pure host-side policy: no jax here. The ServingEngine
(serving/engine.py) owns the compiled programs; this module decides WHO
runs in WHICH slot and in WHICH prefill bucket.
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import List, Optional, Sequence, Tuple


@dataclasses.dataclass
class Request:
    """One generation request in the serving queue."""

    rid: int
    prompt: Sequence[int]
    max_new_tokens: int
    arrival_time: float = 0.0


@dataclasses.dataclass
class RequestResult:
    """Completed request + latency accounting (times in the engine's
    clock, same base as Request.arrival_time)."""

    rid: int
    prompt_len: int
    tokens: List[int] = dataclasses.field(default_factory=list)
    arrival_time: float = 0.0
    admitted_time: float = 0.0
    first_token_time: float = 0.0
    finish_time: float = 0.0
    finish_reason: str = ""  # "eos" | "length"
    # decode-phase model invocations that included this request (0 for a
    # request finished at prefill). One invocation emits ONE token in
    # plain decode but up to k+1 under speculative decoding — TPOT and
    # tokens-per-step accounting divide by THIS, never len(tokens)-1.
    decode_calls: int = 0

    @property
    def latency(self) -> float:
        return self.finish_time - self.arrival_time

    @property
    def first_token_latency(self) -> float:
        return self.first_token_time - self.arrival_time


def pick_bucket(prompt_len: int, buckets: Sequence[int]) -> Optional[int]:
    """Smallest configured prefill bucket that fits the prompt (buckets
    ascending). None = no bucket fits (reject at submit)."""
    for b in buckets:
        if prompt_len <= b:
            return b
    return None


class SlotScheduler:
    """FIFO iteration-level scheduler over a fixed slot set.

    Invariants (pinned by tests/unit/serving/test_scheduler.py):
      * a slot is FREE or holds exactly one request; release() makes it
        admissible on the very next admit() call (slot reuse after EOS);
      * admission is FIFO over arrived requests — a later arrival never
        jumps an earlier one that a free slot could serve;
      * admit() never admits a request whose arrival_time is in the
        future, and never over-fills: len(admissions) <= free slots.
    """

    def __init__(self, num_slots: int):
        self.num_slots = num_slots
        self._free: deque = deque(range(num_slots))
        self._waiting: deque = deque()
        # accounting for tests / metrics
        self.admissions_per_slot = [0] * num_slots
        self.peak_queue_depth = 0

    # ------------------------------------------------------------ queue
    def submit(self, request: Request) -> None:
        self._waiting.append(request)
        self.peak_queue_depth = max(self.peak_queue_depth,
                                    len(self._waiting))

    @property
    def waiting(self) -> int:
        return len(self._waiting)

    @property
    def free_slots(self) -> int:
        return len(self._free)

    def next_arrival(self) -> Optional[float]:
        """Arrival time of the QUEUE HEAD — the next request admit() can
        actually take (admission is strict FIFO, so the engine must idle
        until the head arrives even if a later submission has an earlier
        timestamp)."""
        if not self._waiting:
            return None
        return self._waiting[0].arrival_time

    # -------------------------------------------------------- scheduling
    def admit(self, now: float, fits=None,
              limit: Optional[int] = None) -> List[Tuple[Request, int]]:
        """Pop (request, slot) pairs: arrived requests into free slots,
        FIFO order, called between decode iterations.

        ``fits(request) -> bool`` gates admission on a resource the
        scheduler does not own — the block-paged engine (ISSUE 6)
        accounts in free KV-pool BLOCKS rather than whole slots, so a
        free slot alone is not admissible. FIFO is preserved: a head
        that does not fit blocks everything behind it (no later arrival
        jumps the queue on block luck). ``limit`` caps admissions per
        call — the block engine admits one at a time because each
        admission consumes blocks the next ``fits`` check must see."""
        out: List[Tuple[Request, int]] = []
        while self._free and self._waiting \
                and self._waiting[0].arrival_time <= now \
                and (limit is None or len(out) < limit):
            if fits is not None and not fits(self._waiting[0]):
                break
            slot = self._free.popleft()
            req = self._waiting.popleft()
            self.admissions_per_slot[slot] += 1
            out.append((req, slot))
        return out

    def release(self, slot: int) -> None:
        assert slot not in self._free, f"slot {slot} double-released"
        self._free.append(slot)


def poisson_trace(rng, n_requests: int, *, rate: float,
                  prompt_lens: Sequence[int],
                  max_new_choices: Sequence[int],
                  vocab_size: int, start_rid: int = 0) -> List[Request]:
    """Synthetic mixed-length Poisson arrival trace (the ISSUE-2
    acceptance workload): exponential inter-arrival gaps at ``rate``
    requests/sec (CPU-simulatable — a virtual clock works too since only
    the arrival ORDER and gaps matter), prompts and output budgets drawn
    uniformly from the given choice sets. ``rng`` is a
    numpy.random.RandomState so traces are reproducible."""
    reqs: List[Request] = []
    t = 0.0
    for i in range(n_requests):
        t += float(rng.exponential(1.0 / rate)) if rate > 0 else 0.0
        plen = int(rng.choice(list(prompt_lens)))
        reqs.append(Request(
            rid=start_rid + i,
            prompt=rng.randint(0, vocab_size, size=plen).astype("int32")
                      .tolist(),
            max_new_tokens=int(rng.choice(list(max_new_choices))),
            arrival_time=t))
    return reqs


def templated_trace(rng, n_requests: int, *, rate: float,
                    pattern_len: int, repeats: int,
                    max_new_tokens: int, vocab_size: int,
                    n_templates: int = 4,
                    start_rid: int = 0) -> List[Request]:
    """Synthetic HIGH-ACCEPTANCE trace for speculative decoding (the
    ISSUE-4 bench workload): each prompt is a short random template
    n-gram repeated ``repeats`` times — the repetitive/templated traffic
    shape (form letters, code stubs, retrieval-stuffed prompts) where
    prompt-lookup drafting finds its continuations in the prompt itself
    and greedy decode tends to keep walking the loop. Poisson arrivals
    like :func:`poisson_trace`; a handful of shared templates (drawn per
    request) mimics a templated API's request mix."""
    patterns = [rng.randint(0, vocab_size, size=pattern_len).tolist()
                for _ in range(max(n_templates, 1))]
    reqs: List[Request] = []
    t = 0.0
    for i in range(n_requests):
        t += float(rng.exponential(1.0 / rate)) if rate > 0 else 0.0
        reqs.append(Request(
            rid=start_rid + i,
            prompt=patterns[int(rng.randint(len(patterns)))] * repeats,
            max_new_tokens=max_new_tokens,
            arrival_time=t))
    return reqs


def shared_prefix_trace(rng, n_requests: int, *, rate: float,
                        prefix_len: int, suffix_lens: Sequence[int],
                        max_new_tokens: int, vocab_size: int,
                        n_prefixes: int = 2,
                        start_rid: int = 0) -> List[Request]:
    """Synthetic MULTI-TENANT trace for prefix caching (the ISSUE-6
    bench + test workload): every prompt is one of ``n_prefixes`` long
    shared system prompts (drawn per request — N tenants hammering the
    same few templates) followed by a short UNIQUE user suffix drawn
    from ``suffix_lens``. The redundancy profile of a production
    few-shot / system-prompt API: the radix index should serve
    ``prefix_len``-ish tokens of every request after the first per
    template, leaving only the suffix to prefill. Poisson arrivals like
    :func:`poisson_trace`."""
    prefixes = [rng.randint(0, vocab_size, size=prefix_len).tolist()
                for _ in range(max(n_prefixes, 1))]
    reqs: List[Request] = []
    t = 0.0
    for i in range(n_requests):
        t += float(rng.exponential(1.0 / rate)) if rate > 0 else 0.0
        slen = int(rng.choice(list(suffix_lens)))
        suffix = rng.randint(0, vocab_size, size=slen).tolist()
        reqs.append(Request(
            rid=start_rid + i,
            prompt=prefixes[int(rng.randint(len(prefixes)))] + suffix,
            max_new_tokens=max_new_tokens,
            arrival_time=t))
    return reqs
