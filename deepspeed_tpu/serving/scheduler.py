"""Iteration-level (continuous-batching) request scheduler.

Orca-style scheduling (PAPERS.md; the reference's serving path has no
analog — its InferenceEngine runs one static batch to completion): the
unit of scheduling is ONE DECODE ITERATION, not one batch. Between decode
steps the scheduler admits waiting requests into whatever slots are free,
so a drained slot is refilled immediately instead of idling until the
longest request in a static batch finishes — reclaiming the up-to
(B-1)/B of aggregate capacity a run-to-completion batch wastes on
stragglers.

Priority classes (ISSUE 8): each request carries an integer ``priority``
(LOWER value = more latency-critical; 0 is the default and highest
class). Scheduling is FIFO *within* a class; *across* classes the
scheduler picks the best effective priority, where waiting time ages a
request toward the top (``aging_sec``) so the lowest class can never
starve under sustained high-priority load. With a single class the
policy degenerates to exactly the original strict FIFO. Preempted
requests re-enter their class in arrival order (:meth:`resubmit`), so a
swap-out never costs a request its queue position.

Pure host-side policy: no jax here. The ServingEngine
(serving/engine.py) owns the compiled programs; this module decides WHO
runs in WHICH slot and in WHICH prefill bucket.
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from deepspeed_tpu.serving.errors import EngineConfigError


@dataclasses.dataclass
class Request:
    """One generation request in the serving queue."""

    rid: int
    prompt: Sequence[int]
    max_new_tokens: int
    arrival_time: float = 0.0
    # SLO scheduling class (ISSUE 8): lower = more latency-critical
    # (0 = interactive default). FIFO within a class; the scheduler's
    # aging promotes long-waiting lower classes so none starves.
    priority: int = 0
    # token-streaming callback (ISSUE 8 satellite): invoked once per
    # COMMITTED token, in emission order, as the engine commits it —
    # under speculative decoding only ACCEPTED tokens stream (rejected
    # drafts are never visible). The streamed sequence is exactly
    # RequestResult.tokens (pinned by tests).
    on_token: Optional[Callable[[int], None]] = dataclasses.field(
        default=None, repr=False, compare=False)
    # absolute completion deadline in the engine clock (same base as
    # arrival_time), or None for no SLO. The fabric router (ISSUE 9)
    # sheds a request whose deadline expired while still queued —
    # before it wastes prefill compute it can no longer make use of.
    deadline: Optional[float] = None
    # usage-accounting tenant (ISSUE 13): which caller's bill this
    # request lands on — token counts, prefill saved-vs-computed, KV
    # block-seconds, preemptions/sheds, per-tenant TTFT/TPOT. None is
    # the default tenant (every pre-existing call site unchanged). The
    # id is sanitized (telemetry.metric_label) before it names metrics.
    tenant_id: Optional[str] = None
    # distributed trace context (ISSUE 11): set by whoever OWNS the
    # request's root span (the fabric router, or the engine at submit
    # when standalone). A failover re-dispatch carries the SAME
    # trace_id, so the survivor replica's spans link under the original
    # trace — these two fields are exactly what a cross-process wire
    # protocol would propagate. None + an armed tracer = the engine
    # allocates a fresh trace (and owns the root span).
    trace_id: Optional[str] = dataclasses.field(
        default=None, repr=False, compare=False)
    parent_span: Optional[str] = dataclasses.field(
        default=None, repr=False, compare=False)


@dataclasses.dataclass
class RequestResult:
    """Completed request + latency accounting (times in the engine's
    clock, same base as Request.arrival_time)."""

    rid: int
    prompt_len: int
    tokens: List[int] = dataclasses.field(default_factory=list)
    arrival_time: float = 0.0
    admitted_time: float = 0.0
    first_token_time: float = 0.0
    finish_time: float = 0.0
    finish_reason: str = ""  # "eos" | "length"
    # decode-phase model invocations that included this request (0 for a
    # request finished at prefill). One invocation emits ONE token in
    # plain decode but up to k+1 under speculative decoding — TPOT and
    # tokens-per-step accounting divide by THIS, never len(tokens)-1.
    # Iterations spent PREEMPTED (swapped out of the slot set) are not
    # invocations and never count here.
    decode_calls: int = 0
    # scheduling class the request ran under (Request.priority)
    priority: int = 0
    # engine-clock timestamp of every committed token, emission order
    # (token_times[0] == first_token_time). Under speculation the whole
    # accepted block of a verify step commits at one timestamp. The
    # bench's inter-token-latency (decode TPOT) tails read these.
    token_times: List[float] = dataclasses.field(default_factory=list)
    # chunked-prefill accounting (ISSUE 8): prefill program calls this
    # request's prompt took (1 = monolithic)
    prefill_chunks: int = 0
    # preemption accounting (ISSUE 8): times swapped out, and total wall
    # spent OFF the slot set (swap-out -> swap-in). Preempted time is
    # queueing, not decode latency: it counts in queue_wait, and the
    # portion that fell AFTER the first token (decode_preempted_wall —
    # a mid-prefill preemption parks before TTFT and must not discount
    # the decode span) is excluded from the engine's TPOT accounting.
    preemptions: int = 0
    preempted_wall: float = 0.0
    decode_preempted_wall: float = 0.0
    # fabric accounting (ISSUE 9): times the request failed over to a
    # surviving replica after a crash, and the replica that finished it
    # ("" outside the fabric). finish_reason grows the router's
    # terminal states: "shed_overload" | "shed_deadline" | "rejected" |
    # "failed" alongside the engine's "eos" | "length".
    failovers: int = 0
    replica: str = ""

    @property
    def latency(self) -> float:
        return self.finish_time - self.arrival_time

    @property
    def first_token_latency(self) -> float:
        return self.first_token_time - self.arrival_time

    @property
    def queue_wait(self) -> float:
        """Total time the request spent runnable but not running: the
        initial queue wait plus every preempted interval (ISSUE 8 —
        swap-out time is queueing, a preempted request is back in the
        arrival queue)."""
        return (max(self.admitted_time - self.arrival_time, 0.0)
                + self.preempted_wall)


def pick_bucket(prompt_len: int, buckets: Sequence[int]) -> Optional[int]:
    """Smallest configured prefill bucket that fits the prompt (buckets
    ascending). None = no bucket fits (reject at submit)."""
    for b in buckets:
        if prompt_len <= b:
            return b
    return None


class SlotScheduler:
    """Priority-class iteration-level scheduler over a fixed slot set.

    Invariants (pinned by tests/unit/serving/test_scheduler.py and
    test_slo.py):
      * a slot is FREE or holds exactly one request; release() makes it
        admissible on the very next admit() call (slot reuse after EOS);
      * admission is FIFO *within* a priority class — a later arrival
        never jumps an earlier same-class one that a free slot could
        serve; with a single class (every request at the default
        priority 0) the policy is exactly the original strict FIFO;
      * across classes the best EFFECTIVE priority wins:
        ``priority - waiting_time / aging_sec`` — waiting ages a request
        toward the top, so the lowest class cannot starve (with
        ``aging_sec=None`` the raw class always wins and starvation is
        the caller's problem);
      * admit() never admits a request whose arrival_time is in the
        future, and never over-fills: len(admissions) <= free slots.
    """

    def __init__(self, num_slots: int, *, aging_sec: Optional[float] = None):
        self.num_slots = num_slots
        self.aging_sec = aging_sec
        self._free: deque = deque(range(num_slots))
        # priority class -> deque[(submit_seq, Request)], FIFO per class
        self._queues: Dict[int, deque] = {}
        self._seq = 0
        # rid -> original submission seq, kept after admission so a
        # preempted resubmit restores the request's EXACT original
        # total order (equal-arrival bursts included) — a handful of
        # ints per request over the scheduler's lifetime
        self._seq_of: Dict[int, int] = {}
        # accounting for tests / metrics
        self.admissions_per_slot = [0] * num_slots
        self.peak_queue_depth = 0

    # ------------------------------------------------------------ queue
    def submit(self, request: Request) -> None:
        q = self._queues.setdefault(request.priority, deque())
        q.append((self._seq, request))
        self._seq_of[request.rid] = self._seq
        self._seq += 1
        self.peak_queue_depth = max(self.peak_queue_depth, self.waiting)

    def resubmit(self, request: Request) -> None:
        """Re-queue a PREEMPTED request (ISSUE 8): it re-enters its
        class under its ORIGINAL submission sequence, restoring its
        exact original position — ahead of every same-class entry that
        was originally behind it (equal-arrival bursts and other
        already-resubmitted preemptees included), so a swap-out costs
        compute, never queue position."""
        seq = self._seq_of.get(request.rid)
        if seq is None:          # resubmit of a never-submitted request
            seq = self._seq
            self._seq_of[request.rid] = seq
            self._seq += 1
        q = self._queues.setdefault(request.priority, deque())
        items = list(q)
        i = 0
        while i < len(items) and \
                (items[i][1].arrival_time, items[i][0]) < \
                (request.arrival_time, seq):
            i += 1
        items.insert(i, (seq, request))
        self._queues[request.priority] = deque(items)
        self.peak_queue_depth = max(self.peak_queue_depth, self.waiting)

    @property
    def waiting(self) -> int:
        return sum(len(q) for q in self._queues.values())

    @property
    def free_slots(self) -> int:
        return len(self._free)

    def next_arrival(self) -> Optional[float]:
        """Earliest arrival time over the CLASS HEADS — the next instant
        admit() could take anything (within a class admission is strict
        FIFO, so a later same-class submission with an earlier timestamp
        cannot be admitted first and must not defeat the idle sleep)."""
        heads = [q[0][1].arrival_time for q in self._queues.values() if q]
        return min(heads) if heads else None

    # -------------------------------------------------------- scheduling
    def effective_priority(self, req: Request, now: float) -> float:
        """Aged effective priority (lower = runs sooner): waiting time
        continuously promotes a request (one full class per
        ``aging_sec`` waited), so any request eventually outranks every
        fresher arrival — the no-starvation guarantee. The engine's
        preemption policy consults the same ordering: a victim whose
        aged priority outranks the candidate keeps its slot."""
        if not self.aging_sec:
            return float(req.priority)
        return req.priority - max(now - req.arrival_time, 0.0) / self.aging_sec

    def _best_head(self, now: float):
        """(class_queue, seq, request) of the best arrived class head,
        or None. Tie-break: effective priority, then raw class, then
        arrival, then submission order — total and deterministic."""
        best = None
        for q in self._queues.values():
            if not q:
                continue
            seq, req = q[0]
            if req.arrival_time > now:
                continue
            key = (self.effective_priority(req, now), req.priority,
                   req.arrival_time, seq)
            if best is None or key < best[0]:
                best = (key, q, seq, req)
        return best[1:] if best is not None else None

    def peek(self, now: float) -> Optional[Request]:
        """The request admit() would take next (arrived class heads
        only) — the engine's preemption logic compares its class against
        the running slots' before swapping anyone out."""
        head = self._best_head(now)
        return head[2] if head is not None else None

    def admit(self, now: float, fits=None,
              limit: Optional[int] = None) -> List[Tuple[Request, int]]:
        """Pop (request, slot) pairs: arrived requests into free slots,
        best-effective-priority-first (FIFO within a class), called
        between decode iterations.

        ``fits(request) -> bool`` gates admission on a resource the
        scheduler does not own — the block-paged engine (ISSUE 6)
        accounts in free KV-pool BLOCKS rather than whole slots, so a
        free slot alone is not admissible. Class order is preserved: a
        best head that does not fit blocks everything behind it (no
        lower-priority arrival jumps the queue on block luck — the
        engine's preemption path, not queue-jumping, resolves the
        shortage). ``limit`` caps admissions per call — the engines
        admit one at a time because each admission consumes resources
        the next ``fits``/budget check must see."""
        out: List[Tuple[Request, int]] = []
        while self._free and (limit is None or len(out) < limit):
            head = self._best_head(now)
            if head is None:
                break
            q, _seq, req = head
            if fits is not None and not fits(req):
                break
            q.popleft()
            if not q:
                del self._queues[req.priority]
            slot = self._free.popleft()
            self.admissions_per_slot[slot] += 1
            out.append((req, slot))
        return out

    def remove(self, rid: int) -> bool:
        """Withdraw a WAITING request (ISSUE 9 — the fabric router's
        cancel path: a timed-out or failed-over request must not run
        twice). Returns False when ``rid`` is not queued (already
        admitted, finished, or never submitted); slots are untouched —
        cancelling an admitted request is the engine's job."""
        for pri, q in list(self._queues.items()):
            for i, (_seq, req) in enumerate(q):
                if req.rid == rid:
                    del q[i]
                    if not q:
                        del self._queues[pri]
                    return True
        return False

    def release(self, slot: int) -> None:
        assert slot not in self._free, f"slot {slot} double-released"
        self._free.append(slot)


def poisson_trace(rng, n_requests: int, *, rate: float,
                  prompt_lens: Sequence[int],
                  max_new_choices: Sequence[int],
                  vocab_size: int, start_rid: int = 0) -> List[Request]:
    """Synthetic mixed-length Poisson arrival trace (the ISSUE-2
    acceptance workload): exponential inter-arrival gaps at ``rate``
    requests/sec (CPU-simulatable — a virtual clock works too since only
    the arrival ORDER and gaps matter), prompts and output budgets drawn
    uniformly from the given choice sets. ``rng`` is a
    numpy.random.RandomState so traces are reproducible."""
    reqs: List[Request] = []
    t = 0.0
    for i in range(n_requests):
        t += float(rng.exponential(1.0 / rate)) if rate > 0 else 0.0
        plen = int(rng.choice(list(prompt_lens)))
        reqs.append(Request(
            rid=start_rid + i,
            prompt=rng.randint(0, vocab_size, size=plen).astype("int32")
                      .tolist(),
            max_new_tokens=int(rng.choice(list(max_new_choices))),
            arrival_time=t))
    return reqs


def templated_trace(rng, n_requests: int, *, rate: float,
                    pattern_len: int, repeats: int,
                    max_new_tokens: int, vocab_size: int,
                    n_templates: int = 4,
                    start_rid: int = 0) -> List[Request]:
    """Synthetic HIGH-ACCEPTANCE trace for speculative decoding (the
    ISSUE-4 bench workload): each prompt is a short random template
    n-gram repeated ``repeats`` times — the repetitive/templated traffic
    shape (form letters, code stubs, retrieval-stuffed prompts) where
    prompt-lookup drafting finds its continuations in the prompt itself
    and greedy decode tends to keep walking the loop. Poisson arrivals
    like :func:`poisson_trace`; a handful of shared templates (drawn per
    request) mimics a templated API's request mix."""
    patterns = [rng.randint(0, vocab_size, size=pattern_len).tolist()
                for _ in range(max(n_templates, 1))]
    reqs: List[Request] = []
    t = 0.0
    for i in range(n_requests):
        t += float(rng.exponential(1.0 / rate)) if rate > 0 else 0.0
        reqs.append(Request(
            rid=start_rid + i,
            prompt=patterns[int(rng.randint(len(patterns)))] * repeats,
            max_new_tokens=max_new_tokens,
            arrival_time=t))
    return reqs


def shared_prefix_trace(rng, n_requests: int, *, rate: float,
                        prefix_len: int, suffix_lens: Sequence[int],
                        max_new_tokens: int, vocab_size: int,
                        n_prefixes: int = 2,
                        start_rid: int = 0) -> List[Request]:
    """Synthetic MULTI-TENANT trace for prefix caching (the ISSUE-6
    bench + test workload): every prompt is one of ``n_prefixes`` long
    shared system prompts (drawn per request — N tenants hammering the
    same few templates) followed by a short UNIQUE user suffix drawn
    from ``suffix_lens``. The redundancy profile of a production
    few-shot / system-prompt API: the radix index should serve
    ``prefix_len``-ish tokens of every request after the first per
    template, leaving only the suffix to prefill. Poisson arrivals like
    :func:`poisson_trace`."""
    prefixes = [rng.randint(0, vocab_size, size=prefix_len).tolist()
                for _ in range(max(n_prefixes, 1))]
    reqs: List[Request] = []
    t = 0.0
    for i in range(n_requests):
        t += float(rng.exponential(1.0 / rate)) if rate > 0 else 0.0
        slen = int(rng.choice(list(suffix_lens)))
        suffix = rng.randint(0, vocab_size, size=slen).tolist()
        reqs.append(Request(
            rid=start_rid + i,
            prompt=prefixes[int(rng.randint(len(prefixes)))] + suffix,
            max_new_tokens=max_new_tokens,
            arrival_time=t))
    return reqs


def _rand_prompt(rng, plen: int, vocab_size: int) -> List[int]:
    return rng.randint(0, vocab_size, size=int(plen)).astype("int32").tolist()


def bursty_poisson_trace(rng, n_requests: int, *, burst_size: int,
                         burst_rate: float, prompt_lens: Sequence[int],
                         max_new_choices: Sequence[int], vocab_size: int,
                         priorities: Sequence[int] = (0,),
                         start_rid: int = 0) -> List[Request]:
    """Synthetic ADVERSARIAL bursty arrival trace (ISSUE 8): burst START
    times are Poisson at ``burst_rate`` bursts/sec, and each burst lands
    ``burst_size`` requests at the same instant — the flash-crowd shape
    (cache stampedes, retry storms, fan-out backends) that overwhelms
    admission far beyond what the mean arrival rate suggests. Prompt
    lengths, output budgets, and priority classes are drawn uniformly
    from their choice sets per request."""
    reqs: List[Request] = []
    t = 0.0
    while len(reqs) < n_requests:
        t += float(rng.exponential(1.0 / burst_rate)) if burst_rate > 0 \
            else 0.0
        for _ in range(min(burst_size, n_requests - len(reqs))):
            reqs.append(Request(
                rid=start_rid + len(reqs),
                prompt=_rand_prompt(rng, rng.choice(list(prompt_lens)),
                                    vocab_size),
                max_new_tokens=int(rng.choice(list(max_new_choices))),
                arrival_time=t,
                priority=int(rng.choice(list(priorities)))))
    return reqs


def bimodal_trace(rng, n_requests: int, *, rate: float,
                  short_lens: Sequence[int], long_lens: Sequence[int],
                  long_frac: float, short_new: Sequence[int],
                  long_new: Sequence[int], vocab_size: int,
                  short_priority: int = 0, long_priority: int = 1,
                  start_rid: int = 0) -> List[Request]:
    """Synthetic BIMODAL prompt-length trace (the ISSUE-8 acceptance
    workload): mostly short interactive prompts at the latency-critical
    class, with a ``long_frac`` fraction of long-prompt requests at a
    lower class — the mix where one monolithic long prefill monopolizes
    an iteration and every decoding tenant's TPOT spikes (exactly the
    stall chunked prefill + priority scheduling eliminate). Poisson
    arrivals at ``rate`` requests/sec like :func:`poisson_trace`."""
    reqs: List[Request] = []
    t = 0.0
    for i in range(n_requests):
        t += float(rng.exponential(1.0 / rate)) if rate > 0 else 0.0
        long = bool(rng.rand() < long_frac)
        reqs.append(Request(
            rid=start_rid + i,
            prompt=_rand_prompt(
                rng, rng.choice(list(long_lens if long else short_lens)),
                vocab_size),
            max_new_tokens=int(rng.choice(
                list(long_new if long else short_new))),
            arrival_time=t,
            priority=long_priority if long else short_priority))
    return reqs


def straggler_trace(rng, n_requests: int, *, rate: float,
                    prompt_lens: Sequence[int],
                    max_new_choices: Sequence[int],
                    straggler_every: int, straggler_prompt_len: int,
                    straggler_max_new: int, vocab_size: int,
                    straggler_priority: int = 1,
                    start_rid: int = 0) -> List[Request]:
    """Short interactive traffic with periodic LONG-CONTEXT STRAGGLERS
    (ISSUE 8): every ``straggler_every``-th request carries a
    ``straggler_prompt_len`` prompt and a ``straggler_max_new`` output
    budget at a lower priority class — the document-summarization /
    batch-analytics tenant mixed into a chat workload, the canonical
    preemption + chunked-prefill stressor. Poisson arrivals at ``rate``
    like :func:`poisson_trace`."""
    if straggler_every < 1:
        raise EngineConfigError(f"straggler_every must be >= 1, "
                         f"got {straggler_every}")
    reqs: List[Request] = []
    t = 0.0
    for i in range(n_requests):
        t += float(rng.exponential(1.0 / rate)) if rate > 0 else 0.0
        if (i + 1) % straggler_every == 0:
            reqs.append(Request(
                rid=start_rid + i,
                prompt=_rand_prompt(rng, straggler_prompt_len, vocab_size),
                max_new_tokens=straggler_max_new,
                arrival_time=t, priority=straggler_priority))
        else:
            reqs.append(Request(
                rid=start_rid + i,
                prompt=_rand_prompt(rng, rng.choice(list(prompt_lens)),
                                    vocab_size),
                max_new_tokens=int(rng.choice(list(max_new_choices))),
                arrival_time=t))
    return reqs
