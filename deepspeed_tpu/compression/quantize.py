"""Quantization / pruning primitives — analog of the reference's
``csrc/quantization`` CUDA kernels (fake_quantizer.cu, quantize.cu; SURVEY
§2.4) and the ``compression/basic_layer.py`` QuantAct/LinearLayer_Compress
math. Pure jnp: XLA fuses quant/dequant into the surrounding matmuls on TPU
(the CUDA kernels exist to do exactly that fusion by hand).

All functions use the straight-through estimator (STE) for training: the
forward quantizes, the backward passes gradients through unchanged —
identical semantics to the reference's fake quantization.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


@jax.custom_vjp
def _ste_round(x):
    return jnp.round(x)


def _ste_round_fwd(x):
    return jnp.round(x), None


def _ste_round_bwd(_, g):
    return (g,)


_ste_round.defvjp(_ste_round_fwd, _ste_round_bwd)


def _round_op(v, rounding: str, rng):
    if rounding == "stochastic":
        assert rng is not None, "stochastic rounding needs an rng"
        return jnp.floor(v + jax.random.uniform(rng, v.shape))
    return _ste_round(v)


def fake_quantize(x: jax.Array, bits: int = 8, *, symmetric: bool = True,
                  per_channel_axis: Optional[int] = None,
                  rounding: str = "nearest", rng=None) -> jax.Array:
    """Quantize→dequantize with STE (reference fake_quantizer.cu sym/asym;
    ``rounding="stochastic"`` matches the reference's stochastic mode)."""
    if per_channel_axis is not None:
        axes = tuple(i for i in range(x.ndim) if i != per_channel_axis)
    else:
        axes = tuple(range(x.ndim))
    x32 = x.astype(jnp.float32)
    if symmetric:
        qmax = 2.0 ** (bits - 1) - 1
        scale = jnp.max(jnp.abs(x32), axis=axes, keepdims=True) / qmax
        scale = jnp.maximum(scale, 1e-10)
        q = jnp.clip(_round_op(x32 / scale, rounding, rng), -qmax - 1, qmax)
        return (q * scale).astype(x.dtype)
    qmax = 2.0 ** bits - 1
    lo = jnp.min(x32, axis=axes, keepdims=True)
    hi = jnp.max(x32, axis=axes, keepdims=True)
    scale = jnp.maximum(hi - lo, 1e-10) / qmax
    q = jnp.clip(_round_op((x32 - lo) / scale, rounding, rng), 0, qmax)
    return (q * scale + lo).astype(x.dtype)


def fake_quantize_grouped(x: jax.Array, bits: int = 8, groups: int = 1, *,
                          symmetric: bool = True, rounding: str = "nearest",
                          rng=None) -> jax.Array:
    """Group-wise fake quantization: the flattened tensor is split into
    ``groups`` equal ranges, each with its own scale (reference q_groups
    semantics in quantization_utils.h)."""
    if groups <= 1:
        return fake_quantize(x, bits, symmetric=symmetric, rounding=rounding,
                             rng=rng)
    n = x.size
    assert n % groups == 0, f"numel {n} not divisible by q_groups {groups}"
    flat = x.reshape(groups, n // groups)
    out = fake_quantize(flat, bits, symmetric=symmetric, per_channel_axis=0,
                        rounding=rounding, rng=rng)
    return out.reshape(x.shape)


def quantize_int8(x: jax.Array, *, per_channel_axis: Optional[int] = None
                  ) -> Tuple[jax.Array, jax.Array]:
    """Real int8 quantization → (int8 values, fp32 scales). Used by MoQ and
    int8 inference paths (reference quantize.cu)."""
    if per_channel_axis is not None:
        axes = tuple(i for i in range(x.ndim) if i != per_channel_axis)
    else:
        axes = tuple(range(x.ndim))
    x32 = x.astype(jnp.float32)
    scale = jnp.max(jnp.abs(x32), axis=axes, keepdims=True) / 127.0
    scale = jnp.maximum(scale, 1e-10)
    q = jnp.clip(jnp.round(x32 / scale), -128, 127).astype(jnp.int8)
    return q, scale


def dequantize_int8(q: jax.Array, scale: jax.Array, dtype=jnp.bfloat16) -> jax.Array:
    return (q.astype(jnp.float32) * scale).astype(dtype)


def magnitude_prune_mask(w: jax.Array, sparsity: float) -> jax.Array:
    """Unstructured magnitude pruning mask (reference sparse_pruning,
    compression/helper.py): keep the largest (1-sparsity) fraction."""
    flat = jnp.abs(w).reshape(-1)
    k = int(flat.size * (1.0 - sparsity))
    k = max(k, 1)
    threshold = jax.lax.top_k(flat, k)[0][-1]
    return (jnp.abs(w) >= threshold).astype(w.dtype)


def row_prune_mask(w: jax.Array, ratio: float, axis: int = 0) -> jax.Array:
    """Structured row/head pruning mask: zero whole slices along ``axis`` by
    L1 norm (reference row_pruning / head_pruning)."""
    other = tuple(i for i in range(w.ndim) if i != axis)
    norms = jnp.sum(jnp.abs(w), axis=other)
    keep = max(int(norms.size * (1.0 - ratio)), 1)
    threshold = jax.lax.top_k(norms, keep)[0][-1]
    mask1d = (norms >= threshold).astype(w.dtype)
    shape = [1] * w.ndim
    shape[axis] = norms.size
    return mask1d.reshape(shape)
