from deepspeed_tpu.compression.compress import (
    CompressionScheduler,
    init_compression,
    redundancy_clean,
)
from deepspeed_tpu.compression.quantize import (
    dequantize_int8,
    fake_quantize,
    magnitude_prune_mask,
    quantize_int8,
    row_prune_mask,
)

__all__ = ["init_compression", "redundancy_clean", "CompressionScheduler",
           "fake_quantize", "quantize_int8", "dequantize_int8",
           "magnitude_prune_mask", "row_prune_mask"]
