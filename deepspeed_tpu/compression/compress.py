"""Config-driven model compression — analog of reference
``deepspeed/compression/compress.py`` (init_compression:95,
redundancy_clean:123, scheduler.py; 2311 LoC).

The reference swaps nn.Modules for ``*_Compress`` layers that quantize/prune
inside forward. Functionally (JAX), compression is a *params transform*
applied inside the loss: ``init_compression`` returns a ``CompressedModel``
wrapper whose apply() fake-quantizes / masks the matched parameter groups
before calling the wrapped model — same training semantics (STE), no module
surgery. ``redundancy_clean`` bakes the transform into the weights for
export.

Config schema kept reference-shaped::

    {"compression_training": {
        "weight_quantization": {"shared_parameters": {"enabled": true, ...},
            "different_groups": {"wq1": {"params": {"target_bits": 8},
                                          "modules": ["blocks.*"]}}},
        "sparse_pruning": {...}, "row_pruning": {...}, "head_pruning": {...}
    }}
"""

from __future__ import annotations

import fnmatch
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from deepspeed_tpu.compression.quantize import (
    fake_quantize,
    magnitude_prune_mask,
    row_prune_mask,
)
from deepspeed_tpu.utils.logging import logger


def _match(path: str, patterns: List[str]) -> bool:
    dotted = path.replace("/", ".")
    return any(fnmatch.fnmatch(dotted, pat) or fnmatch.fnmatch(path, pat)
               for pat in patterns)


class CompressionScheduler:
    """Step-gated activation (reference compression/scheduler.py): each
    method has schedule_offset; the transform is identity before it."""

    def __init__(self, offsets: Dict[str, int]):
        self.offsets = offsets
        self.global_step = 0

    def step(self, global_step: Optional[int] = None):
        self.global_step = (self.global_step + 1 if global_step is None
                            else global_step)

    def active(self, method: str) -> bool:
        return self.global_step >= self.offsets.get(method, 0)


class CompressedModel:
    """ModelSpec wrapper applying compression transforms to matched params."""

    def __init__(self, model, config: Dict):
        self.model = model
        cc = config.get("compression_training", config)
        self._transforms: List[Tuple[str, List[str], Callable]] = []
        offsets: Dict[str, int] = {}

        wq = cc.get("weight_quantization", {})
        if wq.get("shared_parameters", {}).get("enabled", False):
            shared = wq.get("shared_parameters", {})
            offsets["weight_quantization"] = shared.get("schedule_offset", 0)
            sym = "symmetric" in str(shared.get("quantization_type", "symmetric"))
            for gname, group in wq.get("different_groups", {}).items():
                bits = group.get("params", {}).get("target_bits", 8)
                mods = group.get("modules", ["*"])
                self._transforms.append((
                    "weight_quantization", mods,
                    lambda w, b=bits, s=sym: fake_quantize(w, b, symmetric=s)))

        sp = cc.get("sparse_pruning", {})
        if sp.get("shared_parameters", {}).get("enabled", False):
            offsets["sparse_pruning"] = sp["shared_parameters"].get("schedule_offset", 0)
            for gname, group in sp.get("different_groups", {}).items():
                ratio = group.get("params", {}).get("dense_ratio", 0.5)
                mods = group.get("modules", ["*"])
                self._transforms.append((
                    "sparse_pruning", mods,
                    lambda w, r=ratio: w * magnitude_prune_mask(w, 1.0 - r)))

        rp = cc.get("row_pruning", {})
        if rp.get("shared_parameters", {}).get("enabled", False):
            offsets["row_pruning"] = rp["shared_parameters"].get("schedule_offset", 0)
            for gname, group in rp.get("different_groups", {}).items():
                ratio = group.get("params", {}).get("dense_ratio", 0.5)
                mods = group.get("modules", ["*"])
                self._transforms.append((
                    "row_pruning", mods,
                    lambda w, r=ratio: w * row_prune_mask(w, 1.0 - r, axis=w.ndim - 1)))

        hp = cc.get("head_pruning", {})
        if hp.get("shared_parameters", {}).get("enabled", False):
            offsets["head_pruning"] = hp["shared_parameters"].get("schedule_offset", 0)

        self.scheduler = CompressionScheduler(offsets)
        if not self._transforms:
            logger.warning("init_compression: no compression groups matched/enabled")

    # --------------------------------------------------------------- ModelSpec
    def compress_params(self, params):
        """Apply all active transforms to matched params (the *_Compress
        forward, functionally)."""
        leaves, treedef = jax.tree_util.tree_flatten_with_path(params)
        out = []
        for path, leaf in leaves:
            key = "/".join(str(getattr(p, "key", getattr(p, "idx", p)))
                           for p in path)
            for method, patterns, fn in self._transforms:
                if getattr(leaf, "ndim", 0) >= 2 and \
                        self.scheduler.active(method) and _match(key, patterns):
                    leaf = fn(leaf)
            out.append(leaf)
        return jax.tree_util.tree_unflatten(treedef, [l for l in out])

    def init(self, rng):
        return self.model.init(rng)

    def apply(self, params, batch, *, rngs=None, train: bool = False):
        return self.model.apply(self.compress_params(params), batch,
                                rngs=rngs, train=train)

    def logical_axes(self):
        return self.model.logical_axes() if hasattr(self.model, "logical_axes") else None

    def __getattr__(self, name):
        return getattr(self.model, name)


def init_compression(model, deepspeed_config: Dict, teacher_model=None, mpu=None):
    """reference compress.py:95 — returns the compression-wrapped model."""
    return CompressedModel(model, deepspeed_config)


def redundancy_clean(model_or_params, deepspeed_config: Dict):
    """reference compress.py:123 — bake transforms into the weights for
    export (quantized/pruned values become the stored values)."""
    if isinstance(model_or_params, CompressedModel):
        raise ValueError("pass (params, config); bake with the wrapper's "
                         "compress_params instead")
    wrapper = CompressedModel(_IdentityModel(), deepspeed_config)
    # activate everything regardless of schedule offsets
    wrapper.scheduler.global_step = max(
        list(wrapper.scheduler.offsets.values()) + [0])
    return wrapper.compress_params(model_or_params)


class _IdentityModel:
    def init(self, rng):
        return {}

    def apply(self, params, batch, *, rngs=None, train=False):
        return batch, {}
