"""Config-driven model compression — analog of reference
``deepspeed/compression/compress.py`` (init_compression:95,
redundancy_clean:123, scheduler.py; 2311 LoC).

The reference swaps nn.Modules for ``*_Compress`` layers that quantize/prune
inside forward. Functionally (JAX), compression is a *params transform*
applied inside the loss: ``init_compression`` returns a ``CompressedModel``
wrapper whose apply() fake-quantizes / masks the matched parameter groups
before calling the wrapped model — same training semantics (STE), no module
surgery. ``redundancy_clean`` bakes the transform into the weights for
export.

Config schema kept reference-shaped::

    {"compression_training": {
        "weight_quantization": {"shared_parameters": {"enabled": true, ...},
            "different_groups": {"wq1": {"params": {"target_bits": 8},
                                          "modules": ["blocks.*"]}}},
        "sparse_pruning": {...}, "row_pruning": {...}, "head_pruning": {...}
    }}
"""

from __future__ import annotations

import fnmatch
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from deepspeed_tpu.compression.quantize import (
    fake_quantize,
    magnitude_prune_mask,
    row_prune_mask,
)
from deepspeed_tpu.utils.logging import logger


def _match(path: str, patterns: List[str]) -> bool:
    dotted = path.replace("/", ".")
    return any(fnmatch.fnmatch(dotted, pat) or fnmatch.fnmatch(path, pat)
               for pat in patterns)


def _path_key(path) -> str:
    """jax key-path → 'a/b/c' (shared by transforms + layer reduction)."""
    return "/".join(str(getattr(p, "key", getattr(p, "idx", p))) for p in path)


class CompressionScheduler:
    """Step-gated activation (reference compression/scheduler.py): each
    method has schedule_offset; the transform is identity before it."""

    def __init__(self, offsets: Dict[str, int]):
        self.offsets = offsets
        self.global_step = 0

    def step(self, global_step: Optional[int] = None):
        self.global_step = (self.global_step + 1 if global_step is None
                            else global_step)

    def active(self, method: str) -> bool:
        return self.global_step >= self.offsets.get(method, 0)


class CompressedModel:
    """ModelSpec wrapper applying compression transforms to matched params."""

    def __init__(self, model, config: Dict):
        self.model = model
        self._teacher_params = None        # set by init_compression for KD
        self._layer_reduction_cfg = None
        cc = config.get("compression_training", config)
        self._transforms: List[Tuple[str, List[str], Callable]] = []
        offsets: Dict[str, int] = {}

        wq = cc.get("weight_quantization", {})
        if wq.get("shared_parameters", {}).get("enabled", False):
            shared = wq.get("shared_parameters", {})
            offsets["weight_quantization"] = shared.get("schedule_offset", 0)
            sym = "symmetric" in str(shared.get("quantization_type", "symmetric"))
            for gname, group in wq.get("different_groups", {}).items():
                bits = group.get("params", {}).get("target_bits", 8)
                mods = group.get("modules", ["*"])
                self._transforms.append((
                    "weight_quantization", mods,
                    lambda w, b=bits, s=sym: fake_quantize(w, b, symmetric=s)))

        sp = cc.get("sparse_pruning", {})
        if sp.get("shared_parameters", {}).get("enabled", False):
            offsets["sparse_pruning"] = sp["shared_parameters"].get("schedule_offset", 0)
            for gname, group in sp.get("different_groups", {}).items():
                ratio = group.get("params", {}).get("dense_ratio", 0.5)
                mods = group.get("modules", ["*"])
                self._transforms.append((
                    "sparse_pruning", mods,
                    lambda w, r=ratio: w * magnitude_prune_mask(w, 1.0 - r)))

        rp = cc.get("row_pruning", {})
        if rp.get("shared_parameters", {}).get("enabled", False):
            offsets["row_pruning"] = rp["shared_parameters"].get("schedule_offset", 0)
            for gname, group in rp.get("different_groups", {}).items():
                ratio = group.get("params", {}).get("dense_ratio", 0.5)
                mods = group.get("modules", ["*"])
                self._transforms.append((
                    "row_pruning", mods,
                    lambda w, r=ratio: w * row_prune_mask(w, 1.0 - r, axis=w.ndim - 1)))

        hp = cc.get("head_pruning", {})
        if hp.get("shared_parameters", {}).get("enabled", False):
            offsets["head_pruning"] = hp["shared_parameters"].get("schedule_offset", 0)

        self.scheduler = CompressionScheduler(offsets)
        if not self._transforms:
            logger.warning("init_compression: no compression groups matched/enabled")

    # --------------------------------------------------------------- ModelSpec
    def compress_params(self, params):
        """Apply all active transforms to matched params (the *_Compress
        forward, functionally)."""
        leaves, treedef = jax.tree_util.tree_flatten_with_path(params)
        out = []
        for path, leaf in leaves:
            key = _path_key(path)
            for method, patterns, fn in self._transforms:
                if getattr(leaf, "ndim", 0) >= 2 and \
                        self.scheduler.active(method) and _match(key, patterns):
                    leaf = fn(leaf)
            out.append(leaf)
        return jax.tree_util.tree_unflatten(treedef, [l for l in out])

    def init(self, rng):
        params = self.model.init(rng)
        if self._teacher_params is not None:
            params = student_initialization(
                params, self._teacher_params, self._layer_reduction_cfg)
        return params

    def apply(self, params, batch, *, rngs=None, train: bool = False):
        return self.model.apply(self.compress_params(params), batch,
                                rngs=rngs, train=train)

    def logical_axes(self):
        return self.model.logical_axes() if hasattr(self.model, "logical_axes") else None

    def __getattr__(self, name):
        return getattr(self.model, name)


def _flatten_with_keys(params) -> Dict[str, Any]:
    leaves = jax.tree_util.tree_flatten_with_path(params)[0]
    return {_path_key(path): leaf for path, leaf in leaves}


def student_initialization(student_params, teacher_params, deepspeed_config: Dict):
    """Knowledge-distillation student init via layer reduction (reference
    compress.py:167 student_initialization + helper.py): copy a chosen subset
    of teacher layers — plus named non-layer modules — into a shallower
    student.

    The reference walks ``module_name_prefix.{i}`` torch submodules; here the
    layer stack is the scanned ``blocks`` subtree with a leading layer dim,
    so layer selection is one gather: ``student_blocks = teacher_blocks[idx]``.

    Config (reference-shaped)::

        {"compression_training": {"layer_reduction": {
            "enabled": true,
            "keep_number_layer": 2,            # student depth (checked)
            "module_name_prefix": "blocks",    # stacked-layer subtree key
            "teacher_layer": [1, 3],           # teacher layers to inherit
            "other_module_name": ["wte", "wpe", "ln_f*"]  # copied verbatim
        }}}
    """
    cc = deepspeed_config.get("compression_training", deepspeed_config)
    lr = cc.get("layer_reduction", {})
    if not lr.get("enabled", False):
        return student_params
    teacher_layer = list(lr["teacher_layer"])
    keep = lr.get("keep_number_layer", len(teacher_layer))
    if keep != len(teacher_layer):
        raise ValueError(
            f"layer_reduction: keep_number_layer={keep} but teacher_layer has "
            f"{len(teacher_layer)} entries — they must match")
    prefix = lr.get("module_name_prefix", "blocks")
    other = lr.get("other_module_name", [])
    idx = jnp.asarray(teacher_layer, jnp.int32)

    t_flat = _flatten_with_keys(teacher_params)
    s_leaves, treedef = jax.tree_util.tree_flatten_with_path(student_params)
    out = []
    copied_layers = copied_other = 0
    for path, leaf in s_leaves:
        key = _path_key(path)
        if (key.startswith(prefix + "/") or key == prefix) and key in t_flat:
            t_leaf = t_flat[key]
            if leaf.shape[0] != len(teacher_layer):
                raise ValueError(
                    f"layer_reduction: student '{key}' has {leaf.shape[0]} "
                    f"layers but teacher_layer selects {len(teacher_layer)}")
            if max(teacher_layer) >= t_leaf.shape[0]:
                raise ValueError(
                    f"layer_reduction: teacher_layer {teacher_layer} out of "
                    f"range for teacher '{key}' with {t_leaf.shape[0]} layers")
            sel = jnp.take(jnp.asarray(t_leaf), idx, axis=0).astype(leaf.dtype)
            if sel.shape != leaf.shape:
                raise ValueError(
                    f"layer_reduction: '{key}' teacher slice {sel.shape} != "
                    f"student {leaf.shape} (hidden sizes must match)")
            out.append(sel)
            copied_layers += 1
        elif other and _match(key, other) and key in t_flat:
            t_leaf = jnp.asarray(t_flat[key])
            if t_leaf.shape != leaf.shape:
                raise ValueError(
                    f"layer_reduction: other module '{key}' teacher shape "
                    f"{t_leaf.shape} != student {leaf.shape}")
            out.append(t_leaf.astype(leaf.dtype))
            copied_other += 1
        else:
            out.append(leaf)
    if copied_layers == 0:
        raise ValueError(
            f"layer_reduction: no student param under prefix '{prefix}' "
            f"matched the teacher — check module_name_prefix and that "
            f"teacher_model carries a params pytree (got teacher keys "
            f"{sorted(t_flat)[:5]}...)")
    logger.info(f"student_initialization: inherited {copied_layers} layer "
                f"params (teacher layers {teacher_layer}) + {copied_other} "
                f"other params")
    return jax.tree_util.tree_unflatten(treedef, out)


def init_compression(model, deepspeed_config: Dict, teacher_model=None, mpu=None):
    """reference compress.py:95 — returns the compression-wrapped model.

    With ``layer_reduction`` enabled, ``teacher_model`` is required (reference
    :112 asserts the same) and must carry the teacher's *params*: pass the
    params pytree itself, or an object with ``.params`` (e.g. a training
    engine's state view). The student's ``init()`` then inherits the selected
    teacher layers (student_initialization)."""
    cc = deepspeed_config.get("compression_training", deepspeed_config)
    if cc.get("layer_reduction", {}).get("enabled", False):
        if teacher_model is None:
            raise ValueError(
                "Teacher model is required for layer reduction")  # ref :112
        teacher_params = getattr(teacher_model, "params", teacher_model)
        wrapped = CompressedModel(model, deepspeed_config)
        wrapped._teacher_params = teacher_params
        wrapped._layer_reduction_cfg = deepspeed_config
        return wrapped
    return CompressedModel(model, deepspeed_config)


def redundancy_clean(model_or_params, deepspeed_config: Dict):
    """reference compress.py:123 — bake transforms into the weights for
    export (quantized/pruned values become the stored values)."""
    if isinstance(model_or_params, CompressedModel):
        raise ValueError("pass (params, config); bake with the wrapper's "
                         "compress_params instead")
    wrapper = CompressedModel(_IdentityModel(), deepspeed_config)
    # activate everything regardless of schedule offsets
    wrapper.scheduler.global_step = max(
        list(wrapper.scheduler.offsets.values()) + [0])
    return wrapper.compress_params(model_or_params)


class _IdentityModel:
    def init(self, rng):
        return {}

    def apply(self, params, batch, *, rngs=None, train=False):
        return batch, {}
