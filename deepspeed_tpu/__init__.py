"""deepspeed_tpu — a TPU-native distributed training & inference framework
with the capability surface of DeepSpeed (reference v0.9.2), re-designed for
JAX/XLA/pjit/Pallas. See SURVEY.md at the repo root for the capability map.

Public API parity with reference ``deepspeed/__init__.py``:
    initialize()            (:58)  — engine selection + wiring
    init_inference()        (:260) — inference engine
    init_distributed        — comm facade init
    add_config_arguments    (:237) — argparse bootstrap
    comm, zero, ops, moe, PipelineModule re-exports
"""

from __future__ import annotations

import argparse
from typing import Any, Optional, Union

from deepspeed_tpu.accelerator import get_accelerator, set_accelerator
from deepspeed_tpu import comm
from deepspeed_tpu import ops
from deepspeed_tpu.comm.comm import init_distributed
from deepspeed_tpu.runtime.config import DeepSpeedConfig
from deepspeed_tpu.runtime.engine import DeepSpeedEngine
from deepspeed_tpu.utils.logging import log_dist, logger

__version__ = "0.1.0"
__git_branch__ = "main"


def initialize(args=None, model=None, optimizer=None, model_parameters=None,
               training_data=None, lr_scheduler=None, topology=None, mpu=None,
               dist_init_required: Optional[bool] = None, collate_fn=None,
               config: Union[dict, str, None] = None, config_params=None):
    """Initialize the engine (reference deepspeed/__init__.py:58).

    Returns ``(engine, optimizer, training_dataloader, lr_scheduler)`` exactly
    like the reference. ``model`` is a ModelSpec (see models/base.py) or a
    flax module wrapped in FlaxModelAdapter. Engine selection mirrors the
    reference (:150-190): PipelineModule → PipelineEngine, hybrid_engine
    section → HybridEngine, else DeepSpeedEngine.
    """
    assert model is not None, "deepspeed_tpu.initialize: model is required"
    if config is None:
        config = config_params
    if config is None and args is not None and hasattr(args, "deepspeed_config") \
            and args.deepspeed_config is not None:
        config = args.deepspeed_config
    assert config is not None, "a config dict/path is required"

    log_dist(f"deepspeed_tpu info: version={__version__}", ranks=[0])
    init_distributed(dist_init_required=dist_init_required)

    from deepspeed_tpu.runtime.pipe.module import PipelineModule

    ds_config = config if isinstance(config, DeepSpeedConfig) else DeepSpeedConfig(config)
    if isinstance(model, PipelineModule):
        from deepspeed_tpu.runtime.pipe.engine import PipelineEngine

        engine = PipelineEngine(model, ds_config, optimizer=optimizer,
                                lr_scheduler=lr_scheduler, training_data=training_data,
                                collate_fn=collate_fn, topology=topology)
    elif ds_config.hybrid_engine.enabled:
        from deepspeed_tpu.runtime.hybrid_engine import DeepSpeedHybridEngine

        engine = DeepSpeedHybridEngine(model, ds_config, optimizer=optimizer,
                                       lr_scheduler=lr_scheduler,
                                       training_data=training_data,
                                       collate_fn=collate_fn, topology=topology)
    else:
        engine = DeepSpeedEngine(model, ds_config, optimizer=optimizer,
                                 lr_scheduler=lr_scheduler, training_data=training_data,
                                 collate_fn=collate_fn, topology=topology)
    return engine, engine.optimizer, engine.training_dataloader, engine.lr_scheduler


def init_inference(model=None, config=None, **kwargs):
    """Inference engine factory (reference deepspeed/__init__.py:260)."""
    from deepspeed_tpu.inference.engine import InferenceEngine
    from deepspeed_tpu.inference.config import DeepSpeedInferenceConfig

    if config is None:
        config = kwargs
    elif kwargs:
        config = {**(config if isinstance(config, dict) else {}), **kwargs}
    if not isinstance(config, DeepSpeedInferenceConfig):
        config = DeepSpeedInferenceConfig(**config)
    return InferenceEngine(model, config)


def add_config_arguments(parser: argparse.ArgumentParser) -> argparse.ArgumentParser:
    """CLI bootstrap flags (reference deepspeed/__init__.py:237)."""
    group = parser.add_argument_group("DeepSpeed-TPU", "DeepSpeed-TPU configurations")
    group.add_argument("--deepspeed", default=False, action="store_true",
                       help="Enable DeepSpeed-TPU (helper flag)")
    group.add_argument("--deepspeed_config", default=None, type=str,
                       help="Path to the DeepSpeed-TPU json configuration file")
    group.add_argument("--deepscale", default=False, action="store_true",
                       help=argparse.SUPPRESS)
    group.add_argument("--local_rank", default=-1, type=int,
                       help="Reserved for launcher compatibility")
    return parser


def _lazy(name: str):
    import importlib

    return importlib.import_module(name)


# convenience namespaces (populated lazily to keep import light)
def __getattr__(name: str):
    if name == "zero":
        return _lazy("deepspeed_tpu.runtime.zero")
    if name == "serving":
        return _lazy("deepspeed_tpu.serving")
    if name == "telemetry":
        return _lazy("deepspeed_tpu.telemetry")
    if name == "PipelineModule":
        return _lazy("deepspeed_tpu.runtime.pipe.module").PipelineModule
    if name == "moe":
        return _lazy("deepspeed_tpu.moe")
    if name == "checkpointing":
        return _lazy("deepspeed_tpu.runtime.activation_checkpointing")
    raise AttributeError(f"module 'deepspeed_tpu' has no attribute '{name}'")
