"""`dstpu_report` — environment / op compatibility report.

Reference analog: ``deepspeed/env_report.py`` (the `ds_report` tool): print
framework versions, device inventory, and the op-builder compatibility
matrix so users can see at a glance what the installation supports.
"""

from __future__ import annotations

import importlib
import sys

GREEN_OK = "\033[92m[OKAY]\033[0m"
RED_NO = "\033[91m[NO]\033[0m"


def op_report(verbose: bool = False):
    from deepspeed_tpu.ops import all_ops

    lines = ["-" * 66,
             "op name " + "." * 40 + " compatible",
             "-" * 66]
    for name, builder_cls in sorted(all_ops().items()):
        try:
            builder = builder_cls()
            ok = builder.is_compatible(verbose=verbose)
            reason = "" if ok else f"  ({builder.compatibility_reason()})"
        except Exception as e:  # an op that cannot even probe is incompatible
            ok, reason = False, f"  ({e})"
        status = GREEN_OK if ok else RED_NO
        lines.append(f"{name} {'.' * max(1, 48 - len(name))} {status}{reason}")
    return "\n".join(lines)


def version_report():
    lines = ["-" * 66, "DeepSpeed-TPU general environment info:", "-" * 66]
    import deepspeed_tpu

    lines.append(f"deepspeed_tpu install path ... {deepspeed_tpu.__path__}")
    lines.append(f"deepspeed_tpu version ........ {deepspeed_tpu.__version__}")
    for mod in ("jax", "jaxlib", "flax", "optax", "numpy"):
        try:
            m = importlib.import_module(mod)
            lines.append(f"{mod} version {'.' * max(1, 15 - len(mod))} "
                         f"{getattr(m, '__version__', 'unknown')}")
        except ImportError:
            lines.append(f"{mod} ................ not installed")
    lines.append(f"python version ....... {sys.version.split()[0]}")
    return "\n".join(lines)


def device_report():
    lines = ["-" * 66, "Device / mesh info:", "-" * 66]
    try:
        import jax

        lines.append(f"platform ............. {jax.default_backend()}")
        lines.append(f"process count ........ {jax.process_count()}")
        lines.append(f"device count ......... {jax.device_count()}")
        for d in jax.devices()[:8]:
            lines.append(f"  {d.id}: {d.device_kind} ({d.platform})")
        if jax.device_count() > 8:
            lines.append(f"  ... and {jax.device_count() - 8} more")
    except Exception as e:
        lines.append(f"jax backend unavailable: {e}")
    return "\n".join(lines)


def main(hide_operator_status: bool = False, hide_errors_and_warnings: bool = False):
    if not hide_operator_status:
        print(op_report(verbose=not hide_errors_and_warnings))
    print(version_report())
    print(device_report())


def cli_main():
    import argparse

    parser = argparse.ArgumentParser(description="dstpu environment report")
    parser.add_argument("--hide_operator_status", action="store_true")
    parser.add_argument("--hide_errors_and_warnings", action="store_true")
    args = parser.parse_args()
    main(args.hide_operator_status, args.hide_errors_and_warnings)


if __name__ == "__main__":
    cli_main()
