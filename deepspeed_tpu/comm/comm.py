"""Communication facade (L2).

TPU-native re-design of ``deepspeed/comm/comm.py`` (the torch.distributed-
shaped API every upper layer programs against) with the same surface —
``init_distributed``, ``all_reduce``, ``all_gather``, ``reduce_scatter``,
``all_to_all``, ``broadcast``, ``send/recv`` (→ ppermute), ``barrier``,
rank/world-size queries — but two execution modes instead of a backend zoo:

1. **Traced** (the hot path): called inside ``jit``/``shard_map`` with a mesh
   axis name; lowers directly to XLA collectives over ICI/DCN
   (``lax.psum / all_gather / psum_scatter / all_to_all / ppermute``).
2. **Eager**: called outside jit on (possibly sharded) arrays; the facade jits
   a ``shard_map`` over the current topology's mesh so torch.dist-style
   imperative code (tests, checkpoint consolidation, overflow checks) works.

Both modes feed the CommsLogger (reference's ``timed_op`` decorator,
comm/comm.py:104): eager ops get real latencies, traced ops are recorded at
trace time (count/volume only — timing individual ops inside a compiled
program is meaningless on TPU).

Group arguments are mesh-axis names (str or tuple of str) — see
``deepspeed_tpu.utils.groups``.
"""

from __future__ import annotations

import functools
import os
import time
from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax

from deepspeed_tpu.accelerator import get_accelerator
from deepspeed_tpu.utils import groups as groups_mod
from deepspeed_tpu.utils.comms_logging import CommsLogger
from deepspeed_tpu.utils.logging import log_dist

Axis = Union[str, Sequence[str]]

comms_logger = CommsLogger()

_INITIALIZED = False


class ReduceOp:
    SUM = "sum"
    AVG = "avg"
    MAX = "max"
    MIN = "min"
    PRODUCT = "prod"


def _in_trace(x) -> bool:
    return isinstance(x, jax.core.Tracer)


def _nbytes(x) -> int:
    return int(x.size * x.dtype.itemsize) if hasattr(x, "size") else 0


def _axis_size(axis: Axis) -> int:
    topo = groups_mod.get_topology()
    axes = (axis,) if isinstance(axis, str) else tuple(axis)
    n = 1
    for a in axes:
        n *= topo.get_dim(a)
    return n


def _log_op(name: str, tensor, axis: Axis, latency: Optional[float], caller: str = ""):
    if not comms_logger.should_profile(name):
        return
    record = f"{name}" + (f" | [Caller Func: {caller}]" if caller else "")
    size = _nbytes(tensor)
    if latency is None:
        comms_logger.record_traced(name, record, size)
    else:
        comms_logger.append(name, record, latency, size, world_size=_axis_size(axis))


def configure(comms_config=None, enabled=None, prof_all=None, prof_ops=None, verbose=None, debug=None):
    """Configure comms logging (reference comm.configure, comm/comm.py:82)."""
    if comms_config is not None:
        comms_logger.configure(comms_config.comms_logger)
    if enabled is not None:
        comms_logger.enabled = enabled
    if prof_all is not None:
        comms_logger.prof_all = prof_all
    if prof_ops is not None:
        comms_logger.prof_ops = prof_ops
    if verbose is not None:
        comms_logger.verbose = verbose
    if debug is not None:
        comms_logger.debug = debug


def log_summary(show_straggler: bool = False):
    return comms_logger.log_all(print_log=True, show_straggler=show_straggler)


# --------------------------------------------------------------------- init
def init_distributed(dist_backend: Optional[str] = None, auto_mpi_discovery: bool = True,
                     verbose: bool = True, timeout=None, init_method=None,
                     dist_init_required: Optional[bool] = None, config=None,
                     rank: int = -1, world_size: int = -1) -> None:
    """Initialise multi-host JAX + the global topology
    (analog of reference init_distributed, comm/comm.py:526).

    On a single host this is a no-op beyond topology setup. On a pod, the
    launcher provides coordinator env vars and ``jax.distributed.initialize``
    performs the rendezvous (the NCCL init_process_group analog).
    """
    global _INITIALIZED
    if _INITIALIZED:
        return
    coord = os.environ.get("DSTPU_COORDINATOR_ADDRESS") or os.environ.get(
        "JAX_COORDINATOR_ADDRESS")

    def _env_int(default: int, *names: str) -> int:
        for n in names:
            v = os.environ.get(n)
            if v is not None:
                return int(v)
        return default

    # process id/world discovery: dstpu per-node launcher env first, then the
    # MPI/Slurm runtime's own vars (reference mpi_discovery, comm/comm.py:591)
    nprocs = _env_int(world_size if world_size > 0 else 1,
                      "DSTPU_NUM_PROCESSES", "OMPI_COMM_WORLD_SIZE",
                      "PMI_SIZE", "SLURM_NPROCS")
    pid = _env_int(rank if rank >= 0 else 0,
                   "DSTPU_PROCESS_ID", "OMPI_COMM_WORLD_RANK", "PMI_RANK",
                   "SLURM_PROCID")
    # single-process launches (dstpu --num_gpus 1) need no rendezvous, and
    # jax.distributed.initialize would fail if the backend is already up
    if coord and nprocs > 1:
        jax.distributed.initialize(
            coordinator_address=coord,
            num_processes=nprocs,
            process_id=pid,
        )
    backend = dist_backend or get_accelerator().communication_backend_name()
    if verbose:
        log_dist(f"Initializing distributed backend: {backend}, "
                 f"processes={jax.process_count()}, devices={jax.device_count()}", ranks=[0])
    if not groups_mod.is_initialized():
        groups_mod.initialize()
    _INITIALIZED = True


def is_initialized() -> bool:
    return _INITIALIZED


def get_rank(group: Optional[Axis] = None) -> int:
    return jax.process_index()


def get_world_size(group: Optional[Axis] = None) -> int:
    if group is None:
        return groups_mod.get_world_size()
    return _axis_size(group)


def get_local_rank() -> int:
    return jax.process_index()


def barrier(group: Optional[Axis] = None):
    jax.effects_barrier()
    x = jnp.zeros(())
    jax.block_until_ready(x + 0)


# ------------------------------------------------------- traced collectives
#
# Eager semantics note: outside jit, JAX is single-controller — a global array
# already holds every shard, so device-level collectives only have meaning
# inside traced code. The eager paths therefore operate at *process* level
# (rank == jax.process_index(), matching torch.distributed's mental model) via
# multihost_utils, and degenerate to identity on a single host.


def _process_reduce(tensor, op: str):
    import numpy as np

    if jax.process_count() == 1:
        return tensor
    from jax.experimental import multihost_utils

    gathered = multihost_utils.process_allgather(tensor)
    red = {ReduceOp.SUM: np.sum, ReduceOp.AVG: np.mean, ReduceOp.MAX: np.max,
           ReduceOp.MIN: np.min}[op]
    return jnp.asarray(red(np.asarray(gathered), axis=0))


def all_reduce(tensor, op: str = ReduceOp.SUM, group: Axis = None, async_op: bool = False,
               prof: bool = False, log_name: str = "all_reduce", comm_id: int = 0):
    axis = group or groups_mod.get_data_parallel_group()
    if _in_trace(tensor):
        reducer = {ReduceOp.SUM: lax.psum, ReduceOp.MAX: lax.pmax, ReduceOp.MIN: lax.pmin,
                   ReduceOp.AVG: lax.pmean}.get(op)
        if reducer is None:
            raise ValueError(f"unsupported reduce op {op}")
        _log_op(log_name, tensor, axis, None)
        return reducer(tensor, axis)
    t0 = time.perf_counter()
    out = _process_reduce(tensor, op)
    _log_op(log_name, tensor, axis, time.perf_counter() - t0)
    return out


def inference_all_reduce(tensor, op: str = ReduceOp.SUM, group: Axis = None):
    return all_reduce(tensor, op=op, group=group, log_name="inference_all_reduce")


def all_gather(tensor, group: Axis = None, axis_index: int = 0, tiled: bool = False,
               log_name: str = "all_gather"):
    """Gather shards along a mesh axis; concatenates on dim ``axis_index``.

    Traced analog of ``all_gather_into_tensor`` (reference comm.py:290).
    """
    axis = group or groups_mod.get_data_parallel_group()
    if _in_trace(tensor):
        _log_op(log_name, tensor, axis, None)
        return lax.all_gather(tensor, axis, axis=axis_index, tiled=True)
    t0 = time.perf_counter()
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        out = multihost_utils.process_allgather(tensor, tiled=tiled)
    else:
        out = tensor
    _log_op(log_name, tensor, axis, time.perf_counter() - t0)
    return out


# torch.dist-compatible aliases
all_gather_into_tensor = all_gather


def reduce_scatter(tensor, group: Axis = None, op: str = ReduceOp.SUM,
                   scatter_dim: int = 0, tiled: bool = True,
                   log_name: str = "reduce_scatter"):
    """psum_scatter along a mesh axis (reference reduce_scatter_tensor, comm.py:273)."""
    axis = group or groups_mod.get_data_parallel_group()
    if _in_trace(tensor):
        _log_op(log_name, tensor, axis, None)
        out = lax.psum_scatter(tensor, axis, scatter_dimension=scatter_dim, tiled=tiled)
        if op == ReduceOp.AVG:
            out = out / _axis_size(axis)
        elif op != ReduceOp.SUM:
            raise ValueError(f"unsupported reduce_scatter op {op}")
        return out
    # Eager process-level: reduce then return this process's slice.
    out = _process_reduce(tensor, ReduceOp.AVG if op == ReduceOp.AVG else ReduceOp.SUM)
    n, r = jax.process_count(), jax.process_index()
    if n > 1:
        out = jnp.split(out, n, axis=scatter_dim)[r]
    _log_op(log_name, tensor, axis, 0.0)
    return out


reduce_scatter_tensor = reduce_scatter


def all_to_all_single(tensor, group: Axis = None, split_dim: int = 0, concat_dim: int = 0,
                      log_name: str = "all_to_all_single"):
    """MoE dispatch primitive (reference all_to_all_single, comm.py:324) →
    ``lax.all_to_all`` over the expert axis."""
    axis = group or groups_mod.get_expert_parallel_group()
    if _in_trace(tensor):
        _log_op(log_name, tensor, axis, None)
        return lax.all_to_all(tensor, axis, split_axis=split_dim,
                              concat_axis=concat_dim, tiled=True)
    raise RuntimeError("all_to_all is only supported inside traced (jit) code; "
                       "wrap the call in jit/shard_map with the expert axis.")


all_to_all = all_to_all_single


def broadcast(tensor, src: int = 0, group: Axis = None, log_name: str = "broadcast"):
    """Broadcast from ``src`` coordinate along the axis. Inside jit arrays are
    already consistent; eager mode selects src's shard via gather."""
    axis = group or groups_mod.get_data_parallel_group()
    if _in_trace(tensor):
        _log_op(log_name, tensor, axis, None)
        # take src's value along the axis for every member
        gathered = lax.all_gather(tensor, axis)
        return gathered[src]
    return tensor  # single-controller JAX: host arrays are already consistent


def ppermute(tensor, perm, group: Axis = None, log_name: str = "ppermute"):
    """Point-to-point ring exchange — the PP send/recv analog
    (reference pipe p2p.py / comm send:343 recv:361)."""
    axis = group or groups_mod.get_pipe_parallel_group()
    _log_op(log_name, tensor, axis, None if _in_trace(tensor) else 0.0)
    return lax.ppermute(tensor, axis, perm)


def send_recv_next(tensor, group: Axis = None):
    """Send to rank+1 along the axis (last wraps to 0 discarded by caller)."""
    axis = group or groups_mod.get_pipe_parallel_group()
    n = _axis_size(axis)
    perm = [(i, (i + 1) % n) for i in range(n)]
    return ppermute(tensor, perm, group=axis, log_name="send_next")


def send_recv_prev(tensor, group: Axis = None):
    axis = group or groups_mod.get_pipe_parallel_group()
    n = _axis_size(axis)
    perm = [(i, (i - 1) % n) for i in range(n)]
    return ppermute(tensor, perm, group=axis, log_name="send_prev")


def pmean(tensor, group: Axis = None):
    return all_reduce(tensor, op=ReduceOp.AVG, group=group)


# -------------------------------------------------- axis index inside traces
def axis_index(group: Axis = None):
    axis = group or groups_mod.get_data_parallel_group()
    if isinstance(axis, str):
        return lax.axis_index(axis)
    # linearised index over multiple axes (outer-major)
    idx = lax.axis_index(axis[0])
    for a in axis[1:]:
        idx = idx * lax.axis_size(a) + lax.axis_index(a)
    return idx
