"""Comms-logger config — analog of reference ``deepspeed/comm/config.py``."""

from __future__ import annotations

from typing import List

from deepspeed_tpu.runtime.config_utils import DeepSpeedConfigModel


class CommsLoggerConfig(DeepSpeedConfigModel):
    enabled: bool = False
    verbose: bool = False
    prof_all: bool = True
    prof_ops: List[str] = []
    debug: bool = False


class CommsConfig(DeepSpeedConfigModel):
    comms_logger: CommsLoggerConfig = CommsLoggerConfig()
