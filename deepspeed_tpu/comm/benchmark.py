"""Collective micro-benchmark (`dstpu_bench`).

Reference analog: ``bin/ds_bench`` → deepspeed communication benchmarks —
sweep message sizes through the collectives and report algorithm/bus
bandwidth.  Here each collective is a jitted `shard_map` program over the
local mesh, so the numbers reflect the real XLA/ICI path the framework
trains with.
"""

from __future__ import annotations

import argparse
import time
from typing import List


def _human(nbytes: float) -> str:
    for unit in ("B", "KB", "MB", "GB"):
        if nbytes < 1024:
            return f"{nbytes:.1f}{unit}"
        nbytes /= 1024
    return f"{nbytes:.1f}TB"


def run_collective_bench(op: str = "all_reduce", sizes: List[int] = None,
                         trials: int = 10, dtype_str: str = "float32"):
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    from deepspeed_tpu.parallel.topology import DATA_AXIS
    from deepspeed_tpu.utils.jax_compat import shard_map

    devices = jax.devices()
    n = len(devices)
    mesh = Mesh(devices, (DATA_AXIS,))
    dtype = getattr(jnp, dtype_str)
    sizes = sizes or [2 ** p for p in range(12, 27, 2)]  # 4KB..512MB elems/4
    results = []
    # one local function + out_specs per collective, one shard_map site
    local_fns = {
        "all_reduce": (lambda a: jax.lax.psum(a, DATA_AXIS), P(DATA_AXIS)),
        "all_gather": (lambda a: jax.lax.all_gather(a, DATA_AXIS, tiled=True),
                       P()),
        "reduce_scatter": (lambda a: jax.lax.psum_scatter(a, DATA_AXIS,
                                                          tiled=True),
                           P(DATA_AXIS)),
        "all_to_all": (lambda a: jax.lax.all_to_all(
            a.reshape(n, -1), DATA_AXIS, 0, 0,
            tiled=False).reshape(a.shape), P(DATA_AXIS)),
    }
    if op not in local_fns:
        raise ValueError(f"unknown op '{op}'")
    local_fn, out_specs = local_fns[op]
    for numel in sizes:
        x = jnp.ones((n, numel // n if op != "all_gather" else numel), dtype)
        fn = shard_map(local_fn, mesh=mesh, in_specs=P(DATA_AXIS),
                       out_specs=out_specs)
        jfn = jax.jit(fn)
        jax.block_until_ready(jfn(x))  # compile + warm
        t0 = time.perf_counter()
        for _ in range(trials):
            out = jfn(x)
        jax.block_until_ready(out)
        dt = (time.perf_counter() - t0) / trials
        nbytes = numel * x.dtype.itemsize
        # bus bandwidth correction factors (NCCL-tests convention)
        factor = {"all_reduce": 2 * (n - 1) / n, "all_gather": (n - 1) / n,
                  "reduce_scatter": (n - 1) / n, "all_to_all": (n - 1) / n}[op]
        busbw = nbytes * factor / dt
        results.append((numel, nbytes, dt, busbw))
    return results


def main(argv=None):
    parser = argparse.ArgumentParser(description="dstpu collective benchmark")
    parser.add_argument("--op", default="all_reduce",
                        choices=["all_reduce", "all_gather", "reduce_scatter",
                                 "all_to_all"])
    parser.add_argument("--trials", type=int, default=10)
    parser.add_argument("--dtype", default="float32")
    parser.add_argument("--maxsize", type=int, default=24,
                        help="max message size as log2(elements)")
    args = parser.parse_args(argv)
    sizes = [2 ** p for p in range(12, args.maxsize + 1, 2)]
    print(f"{'size':>10} {'bytes':>10} {'time(us)':>12} {'busbw(GB/s)':>12}")
    for numel, nbytes, dt, busbw in run_collective_bench(
            args.op, sizes, args.trials, args.dtype):
        print(f"{numel:>10} {_human(nbytes):>10} {dt * 1e6:>12.1f} "
              f"{busbw / 1e9:>12.2f}")
    return 0


if __name__ == "__main__":
    main()
