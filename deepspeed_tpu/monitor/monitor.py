"""Experiment monitoring — analog of reference ``deepspeed/monitor/``
(MonitorMaster monitor.py:29 fanning out to tensorboard/wandb/csv writers).

Writers activate only on process rank 0 (matching the reference's
rank-0-only behaviour) and degrade gracefully when their backend package is
absent (tensorboard/wandb are optional; csv always works).
"""

from __future__ import annotations

import os
from typing import List, Tuple

from deepspeed_tpu.utils.logging import logger

Event = Tuple[str, float, int]  # (tag, value, global_step)


def _rank() -> int:
    try:
        import jax

        return jax.process_index()
    except Exception:
        return 0


class Monitor:
    def __init__(self, config):
        self.config = config
        self.enabled = False

    def write_events(self, event_list: List[Event]):
        raise NotImplementedError


class TensorBoardMonitor(Monitor):
    def __init__(self, config):
        super().__init__(config)
        self.summary_writer = None
        if config.enabled and _rank() == 0:
            try:
                from torch.utils.tensorboard import SummaryWriter

                log_dir = os.path.join(config.output_path or "./runs", config.job_name)
                self.summary_writer = SummaryWriter(log_dir=log_dir)
                self.enabled = True
            except Exception as e:  # tensorboard not installed
                logger.warning(f"TensorBoard monitor disabled: {e}")

    def write_events(self, event_list, flush: bool = True):
        if self.summary_writer is None:
            return
        for tag, value, step in event_list:
            self.summary_writer.add_scalar(tag, value, step)
        if flush:
            self.summary_writer.flush()


class WandbMonitor(Monitor):
    def __init__(self, config):
        super().__init__(config)
        self._wandb = None
        if config.enabled and _rank() == 0:
            try:
                import wandb

                wandb.init(project=config.project, group=config.group or None,
                           entity=config.team or None)
                self._wandb = wandb
                self.enabled = True
            except Exception as e:
                logger.warning(f"W&B monitor disabled: {e}")

    def write_events(self, event_list):
        if self._wandb is None:
            return
        for tag, value, step in event_list:
            self._wandb.log({tag: value}, step=step)


class csvMonitor(Monitor):
    def __init__(self, config):
        super().__init__(config)
        self.filenames: dict = {}
        if config.enabled and _rank() == 0:
            self.output_path = os.path.join(config.output_path or "./csv_logs",
                                            config.job_name)
            os.makedirs(self.output_path, exist_ok=True)
            self.enabled = True

    def write_events(self, event_list):
        if not self.enabled:
            return
        for tag, value, step in event_list:
            safe = tag.replace("/", "_")
            path = os.path.join(self.output_path, f"{safe}.csv")
            new = not os.path.exists(path)
            with open(path, "a") as f:
                if new:
                    f.write("step,value\n")
                f.write(f"{step},{value}\n")


class JsonlMonitor(Monitor):
    """Structured JSONL writer — the telemetry subsystem's fourth backend
    (deepspeed_tpu/telemetry/sink.py): scalar events append to
    ``<output_path>/<job_name>.jsonl`` as one record per line, readable by
    ``scripts/telemetry_report.py`` and any jq/pandas pipeline."""

    def __init__(self, config):
        super().__init__(config)
        self.sink = None
        if config.enabled and _rank() == 0:
            from deepspeed_tpu.telemetry.sink import JsonlSink

            path = os.path.join(config.output_path or "./telemetry",
                                f"{config.job_name}.jsonl")
            try:
                self.sink = JsonlSink(path)
                self.enabled = True
            except Exception as e:
                logger.warning(f"JSONL monitor disabled: {e}")

    def write_events(self, event_list):
        if self.sink is None:
            return
        for tag, value, step in event_list:
            self.sink.scalar(tag, float(value), int(step))
        self.sink.flush()


class MonitorMaster(Monitor):
    """Fans out write_events to every enabled writer (reference monitor.py:29)."""

    def __init__(self, config):
        super().__init__(config)
        self.tb_monitor = TensorBoardMonitor(config.tensorboard)
        self.wandb_monitor = WandbMonitor(config.wandb)
        self.csv_monitor = csvMonitor(config.csv_monitor)
        self.jsonl_monitor = JsonlMonitor(config.jsonl_monitor)
        self.enabled = (self.tb_monitor.enabled or self.wandb_monitor.enabled or
                        self.csv_monitor.enabled or self.jsonl_monitor.enabled)

    def write_events(self, event_list: List[Event]):
        if _rank() != 0:
            return
        for mon in (self.tb_monitor, self.wandb_monitor, self.csv_monitor,
                    self.jsonl_monitor):
            if mon.enabled:
                mon.write_events(event_list)
