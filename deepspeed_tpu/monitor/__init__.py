from .config import DeepSpeedMonitorConfig, get_monitor_config
from .monitor import MonitorMaster
