"""Monitor config — analog of reference ``deepspeed/monitor/config.py``."""

from __future__ import annotations

from deepspeed_tpu.runtime.config_utils import DeepSpeedConfigModel


class TensorBoardConfig(DeepSpeedConfigModel):
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedJobName"


class WandbConfig(DeepSpeedConfigModel):
    enabled: bool = False
    group: str = ""
    team: str = ""
    project: str = "deepspeed_tpu"


class CSVConfig(DeepSpeedConfigModel):
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedJobName"


class JsonlConfig(DeepSpeedConfigModel):
    """Structured JSONL writer (the telemetry subsystem's fourth monitor
    backend, no reference analog): every scalar event lands as one
    ``{"kind": "scalar", "tag", "value", "step", "ts"}`` line in
    ``<output_path>/<job_name>.jsonl`` — render with
    ``scripts/telemetry_report.py``."""

    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedJobName"


class DeepSpeedMonitorConfig(DeepSpeedConfigModel):
    tensorboard: TensorBoardConfig = TensorBoardConfig()
    wandb: WandbConfig = WandbConfig()
    csv_monitor: CSVConfig = CSVConfig()
    jsonl_monitor: JsonlConfig = JsonlConfig()

    @property
    def enabled(self) -> bool:
        return (self.tensorboard.enabled or self.wandb.enabled or
                self.csv_monitor.enabled or self.jsonl_monitor.enabled)


def get_monitor_config(param_dict: dict) -> DeepSpeedMonitorConfig:
    monitor_dict = {
        k: v for k, v in param_dict.items()
        if k in ("tensorboard", "wandb", "csv_monitor", "jsonl_monitor")
    }
    return DeepSpeedMonitorConfig(**monitor_dict)
