from .abstract_accelerator import DeepSpeedAccelerator
from .real_accelerator import get_accelerator, set_accelerator, is_current_accelerator_supported
from .tpu_accelerator import CPU_Accelerator, TPU_Accelerator

__all__ = [
    "DeepSpeedAccelerator",
    "get_accelerator",
    "set_accelerator",
    "is_current_accelerator_supported",
    "TPU_Accelerator",
    "CPU_Accelerator",
]
