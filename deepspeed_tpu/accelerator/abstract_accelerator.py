"""Accelerator abstraction (L0).

TPU-native re-design of the reference's ``accelerator/abstract_accelerator.py``
(DeepSpeedAccelerator, ~70 methods). Every other layer asks ``get_accelerator()``
for device facts instead of touching ``jax`` backends directly, which is what
makes the whole stack runnable on the CPU-emulated multi-device mesh used by the
test harness.

Differences from the reference surface, by design:
  * no streams/events — XLA owns scheduling; ``synchronize`` maps to
    ``block_until_ready`` on request.
  * tensor factory methods return jnp dtypes/arrays, not torch tensors.
  * ``communication_backend_name`` names the collective lowering ("xla-ici"),
    consumed by :mod:`deepspeed_tpu.comm`.
"""

from __future__ import annotations

import abc
from typing import Any, Dict, List, Optional


class DeepSpeedAccelerator(abc.ABC):
    _name: str = "abstract"
    _communication_backend_name: str = "undefined"

    # ------------------------------------------------------------------ device
    @abc.abstractmethod
    def device_name(self, device_index: Optional[int] = None) -> str:
        ...

    @abc.abstractmethod
    def device_count(self) -> int:
        """Global device count visible to this process group."""

    @abc.abstractmethod
    def local_device_count(self) -> int:
        ...

    @abc.abstractmethod
    def devices(self) -> List[Any]:
        ...

    def current_device(self) -> int:
        return 0

    def current_device_name(self) -> str:
        return self.device_name(self.current_device())

    def set_device(self, device_index: int) -> None:  # no-op: XLA places arrays
        pass

    @abc.abstractmethod
    def is_available(self) -> bool:
        ...

    def process_count(self) -> int:
        import jax

        return jax.process_count()

    def process_index(self) -> int:
        import jax

        return jax.process_index()

    # --------------------------------------------------------------- execution
    def synchronize(self, obj: Any = None) -> None:
        """Block until device work completes (analog of torch.cuda.synchronize)."""
        import jax

        if obj is not None:
            jax.block_until_ready(obj)
        else:
            # Barrier against all pending local computations.
            jax.effects_barrier()

    # ---------------------------------------------------------------------- RNG
    def default_rng(self, seed: int):
        import jax

        return jax.random.PRNGKey(seed)

    # ------------------------------------------------------------------- memory
    def memory_stats(self, device_index: int = 0) -> Dict[str, int]:
        try:
            d = self.devices()[device_index]
            return dict(d.memory_stats() or {})
        except Exception:
            return {}

    def memory_allocated(self, device_index: int = 0) -> int:
        return self.memory_stats(device_index).get("bytes_in_use", 0)

    def max_memory_allocated(self, device_index: int = 0) -> int:
        return self.memory_stats(device_index).get("peak_bytes_in_use", 0)

    def total_memory(self, device_index: int = 0) -> int:
        return self.memory_stats(device_index).get("bytes_limit", 0)

    def available_memory(self, device_index: int = 0) -> int:
        stats = self.memory_stats(device_index)
        return stats.get("bytes_limit", 0) - stats.get("bytes_in_use", 0)

    def reset_peak_memory_stats(self, device_index: int = 0) -> None:
        pass  # not supported by all backends; peak stats are advisory

    def empty_cache(self) -> None:
        pass

    # -------------------------------------------------------------------- dtype
    @abc.abstractmethod
    def preferred_dtype(self):
        """The fast matmul dtype on this accelerator (bf16 on TPU)."""

    def is_bf16_supported(self) -> bool:
        return True

    def is_fp16_supported(self) -> bool:
        return True

    def supported_dtypes(self):
        import jax.numpy as jnp

        return [jnp.float32, jnp.bfloat16, jnp.float16, jnp.int8]

    # ------------------------------------------------------------- peak flops
    def peak_tflops(self) -> Optional[float]:
        """Dense peak TFLOPs per chip in the fast matmul dtype
        (:meth:`preferred_dtype`) — the MFU denominator
        (telemetry/mfu.py). Concrete accelerators consult their
        device-kind table; ``DSTPU_PEAK_TFLOPS`` overrides everywhere
        (new silicon, derated quotas, CPU test runs). None = unknown, and
        MFU-vs-peak is simply not reported."""
        import os

        env = os.environ.get("DSTPU_PEAK_TFLOPS")
        if env:
            try:
                return float(env)
            except ValueError:
                pass
        return None

    def peak_hbm_gbps(self) -> Optional[float]:
        """Peak HBM bandwidth per chip in GB/s — the memory roof of the
        per-program roofline attribution (telemetry/attribution.py).
        Concrete accelerators consult their device-kind table;
        ``DSTPU_PEAK_HBM_GBPS`` overrides everywhere. None = unknown,
        and attainable-vs-achieved is simply not reported."""
        import os

        env = os.environ.get("DSTPU_PEAK_HBM_GBPS")
        if env:
            try:
                return float(env)
            except ValueError:
                pass
        return None

    # ------------------------------------------------------------ profiler hooks
    def range_push(self, msg: str):
        """NVTX analog: jax profiler trace annotation (used by instrument_w_scope)."""
        import jax

        ctx = jax.profiler.TraceAnnotation(msg)
        ctx.__enter__()
        self._range_stack = getattr(self, "_range_stack", [])
        self._range_stack.append(ctx)

    def range_pop(self):
        stack = getattr(self, "_range_stack", [])
        if stack:
            stack.pop().__exit__(None, None, None)

    # ------------------------------------------------------------- communication
    def communication_backend_name(self) -> str:
        return self._communication_backend_name

    # --------------------------------------------------------------- op builders
    def create_op_builder(self, op_name: str):
        from deepspeed_tpu.ops.registry import get_op_builder

        return get_op_builder(op_name)(accelerator=self)

    def get_op_builder(self, op_name: str):
        from deepspeed_tpu.ops.registry import get_op_builder

        return get_op_builder(op_name)

    # -------------------------------------------------------------------- naming
    def name(self) -> str:
        return self._name

    def platform(self) -> str:
        return self._name

    def is_synchronized_device(self) -> bool:
        return False

    def device_kind(self) -> str:
        try:
            return self.devices()[0].device_kind
        except Exception:
            return "unknown"
