"""Concrete accelerators: TPU and CPU-emulated.

Replaces the reference's ``accelerator/cuda_accelerator.py`` with a JAX-backed
implementation. The CPU accelerator exists so the entire framework (ZeRO, MoE,
PP meshes) runs on ``--xla_force_host_platform_device_count=N`` virtual devices
— something the reference's test harness could not do without GPUs
(tests/unit/common.py in the reference always needs real NCCL).
"""

from __future__ import annotations

from typing import List, Optional

from .abstract_accelerator import DeepSpeedAccelerator


class TPU_Accelerator(DeepSpeedAccelerator):
    _name = "tpu"
    _communication_backend_name = "xla-ici"

    def device_name(self, device_index: Optional[int] = None) -> str:
        if device_index is None:
            return "tpu"
        return f"tpu:{device_index}"

    def devices(self) -> List:
        import jax

        return jax.devices()

    def device_count(self) -> int:
        import jax

        return jax.device_count()

    def local_device_count(self) -> int:
        import jax

        return jax.local_device_count()

    def is_available(self) -> bool:
        import jax

        try:
            return any(d.platform in ("tpu", "axon") for d in jax.devices())
        except RuntimeError:
            return False

    def preferred_dtype(self):
        import jax.numpy as jnp

        return jnp.bfloat16

    def is_fp16_supported(self) -> bool:
        # fp16 compute works but bf16 is native; DynamicLossScaler stays optional.
        return True


class CPU_Accelerator(DeepSpeedAccelerator):
    """Host-platform accelerator for tests and CI (virtual multi-device mesh)."""

    _name = "cpu"
    _communication_backend_name = "xla-host"

    def device_name(self, device_index: Optional[int] = None) -> str:
        if device_index is None:
            return "cpu"
        return f"cpu:{device_index}"

    def devices(self) -> List:
        import jax

        return jax.devices("cpu")

    def device_count(self) -> int:
        return len(self.devices())

    def local_device_count(self) -> int:
        return len(self.devices())

    def is_available(self) -> bool:
        return True

    def preferred_dtype(self):
        import jax.numpy as jnp

        return jnp.float32
