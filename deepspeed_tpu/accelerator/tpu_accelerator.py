"""Concrete accelerators: TPU and CPU-emulated.

Replaces the reference's ``accelerator/cuda_accelerator.py`` with a JAX-backed
implementation. The CPU accelerator exists so the entire framework (ZeRO, MoE,
PP meshes) runs on ``--xla_force_host_platform_device_count=N`` virtual devices
— something the reference's test harness could not do without GPUs
(tests/unit/common.py in the reference always needs real NCCL).
"""

from __future__ import annotations

from typing import List, Optional

from .abstract_accelerator import DeepSpeedAccelerator


# Dense bf16 peak TFLOPs per CHIP (not per core) by device-kind substring,
# from the published TPU system specs. The MFU denominator
# (telemetry/mfu.py); lookup is case-insensitive longest-match so
# "TPU v5 lite"/"TPU v5e" both hit the v5e entry. DSTPU_PEAK_TFLOPS
# (abstract_accelerator.peak_tflops) overrides for unlisted silicon.
TPU_PEAK_TFLOPS = {
    "v2": 45.0,
    "v3": 123.0,
    "v4": 275.0,
    "v5 lite": 197.0,
    "v5litepod": 197.0,
    "v5e": 197.0,
    "v5p": 459.0,
    "v6 lite": 918.0,
    "v6e": 918.0,
}

# HBM bandwidth per CHIP in GB/s, same published specs + lookup rules —
# the memory roof of the per-program roofline attribution
# (telemetry/attribution.py); DSTPU_PEAK_HBM_GBPS overrides.
TPU_PEAK_HBM_GBPS = {
    "v2": 700.0,
    "v3": 900.0,
    "v4": 1228.0,
    "v5 lite": 819.0,
    "v5litepod": 819.0,
    "v5e": 819.0,
    "v5p": 2765.0,
    "v6 lite": 1640.0,
    "v6e": 1640.0,
}


class TPU_Accelerator(DeepSpeedAccelerator):
    _name = "tpu"
    _communication_backend_name = "xla-ici"

    def device_name(self, device_index: Optional[int] = None) -> str:
        if device_index is None:
            return "tpu"
        return f"tpu:{device_index}"

    def devices(self) -> List:
        import jax

        return jax.devices()

    def device_count(self) -> int:
        import jax

        return jax.device_count()

    def local_device_count(self) -> int:
        import jax

        return jax.local_device_count()

    def is_available(self) -> bool:
        import jax

        try:
            return any(d.platform in ("tpu", "axon") for d in jax.devices())
        except RuntimeError:
            return False

    def preferred_dtype(self):
        import jax.numpy as jnp

        return jnp.bfloat16

    def is_fp16_supported(self) -> bool:
        # fp16 compute works but bf16 is native; DynamicLossScaler stays optional.
        return True

    def peak_tflops(self):
        env = super().peak_tflops()
        if env is not None:
            return env
        return self._kind_lookup(TPU_PEAK_TFLOPS)

    def peak_hbm_gbps(self):
        env = super().peak_hbm_gbps()
        if env is not None:
            return env
        return self._kind_lookup(TPU_PEAK_HBM_GBPS)

    def _kind_lookup(self, table):
        kind = self.device_kind().lower()
        best = None
        for sub, v in table.items():
            if sub in kind and (best is None or len(sub) > best[0]):
                best = (len(sub), v)
        return best[1] if best else None


class CPU_Accelerator(DeepSpeedAccelerator):
    """Host-platform accelerator for tests and CI (virtual multi-device mesh)."""

    _name = "cpu"
    _communication_backend_name = "xla-host"

    def device_name(self, device_index: Optional[int] = None) -> str:
        if device_index is None:
            return "cpu"
        return f"cpu:{device_index}"

    def devices(self) -> List:
        import jax

        return jax.devices("cpu")

    def device_count(self) -> int:
        return len(self.devices())

    def local_device_count(self) -> int:
        return len(self.devices())

    def is_available(self) -> bool:
        return True

    def preferred_dtype(self):
        import jax.numpy as jnp

        return jnp.float32
