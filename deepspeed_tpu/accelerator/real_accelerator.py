"""Accelerator selection — analog of the reference's
``accelerator/real_accelerator.py`` (get_accelerator/set_accelerator).

Selection order:
  1. explicit ``set_accelerator()``
  2. ``DSTPU_ACCELERATOR`` env var ("tpu" | "cpu")
  3. auto-detect: TPU if the default jax backend exposes TPU-ish devices,
     else CPU.
"""

from __future__ import annotations

import os
from typing import Optional

from .abstract_accelerator import DeepSpeedAccelerator

_accelerator: Optional[DeepSpeedAccelerator] = None


def _detect() -> DeepSpeedAccelerator:
    from .tpu_accelerator import CPU_Accelerator, TPU_Accelerator

    env = os.environ.get("DSTPU_ACCELERATOR")
    if env == "cpu":
        return CPU_Accelerator()
    if env == "tpu":
        return TPU_Accelerator()
    tpu = TPU_Accelerator()
    if tpu.is_available():
        return tpu
    return CPU_Accelerator()


def get_accelerator() -> DeepSpeedAccelerator:
    global _accelerator
    if _accelerator is None:
        _accelerator = _detect()
    return _accelerator


def set_accelerator(accel: DeepSpeedAccelerator) -> None:
    global _accelerator
    _accelerator = accel


def is_current_accelerator_supported() -> bool:
    return get_accelerator().is_available()
