from deepspeed_tpu.testing.fault_injection import (
    FakeClock,
    FaultInjector,
    ScriptedWorkerGroup,
    SimulatedCrash,
)

__all__ = ["FakeClock", "FaultInjector", "ScriptedWorkerGroup",
           "SimulatedCrash"]
