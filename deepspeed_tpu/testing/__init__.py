from deepspeed_tpu.testing.fault_injection import (
    AlertStormPlan,
    FakeClock,
    FaultInjector,
    ReplicaFaultPlan,
    ScriptedWorkerGroup,
    SimulatedCrash,
)

__all__ = ["AlertStormPlan", "FakeClock", "FaultInjector",
           "ReplicaFaultPlan", "ScriptedWorkerGroup", "SimulatedCrash"]
