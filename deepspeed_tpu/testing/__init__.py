from deepspeed_tpu.testing.fault_injection import (
    FakeClock,
    FaultInjector,
    ReplicaFaultPlan,
    ScriptedWorkerGroup,
    SimulatedCrash,
)

__all__ = ["FakeClock", "FaultInjector", "ReplicaFaultPlan",
           "ScriptedWorkerGroup", "SimulatedCrash"]
