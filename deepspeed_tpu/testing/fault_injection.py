"""Reusable fault-injection harness for robustness tests.

All checkpoint bytes flow through the seam functions in
``deepspeed_tpu.utils.fs`` (``read_bytes`` / ``write_bytes`` / ``replace``),
so :class:`FaultInjector` can deterministically inject the failure modes
that matter for fault tolerance — truncated writes, I/O errors on the Nth
call, slow writes, and simulated worker crashes mid-operation — without
subprocesses, making the tests tier-1-safe.

For the elasticity layer, :class:`FakeClock` and :class:`ScriptedWorkerGroup`
drive :class:`~deepspeed_tpu.elasticity.elastic_agent.ElasticAgent` through
arbitrary failure/preemption schedules in virtual time.

For the SERVING fabric (ISSUE 9), the injector grows replica seams:
:meth:`FaultInjector.replica_plan` returns a per-replica
:class:`ReplicaFaultPlan` that an
:class:`~deepspeed_tpu.serving.fabric.replica.InProcessReplica` consults
on every step/probe — scripted crash on the Nth step, slow-replica
straggling (virtual-time stalls), flaky steps, and failing health
probes — so a 3-replica chaos suite runs entirely in-process, in
virtual time, tier-1-safe.

Usage::

    with FaultInjector() as inj:
        inj.truncate_write(nth=1, keep_bytes=64)   # crash mid state.npz
        with pytest.raises(SimulatedCrash):
            engine.save_checkpoint(ckpt_dir)
    # seam functions restored here
"""

from __future__ import annotations

import time as _time
from typing import Callable, Dict, List, Optional, Sequence

from deepspeed_tpu.utils import fs


class SimulatedCrash(BaseException):
    """Models a worker dying mid-operation (SIGKILL / preemption without
    grace). Derives from ``BaseException`` so generic ``except Exception``
    recovery paths cannot accidentally 'survive' the kill — exactly like a
    real dead process."""


class FaultInjector:
    """Patches ``deepspeed_tpu.utils.fs`` primitives; restores them on
    ``__exit__`` / ``restore()``. Call counters (``write_calls``,
    ``read_calls``, ``replace_calls``) count *entries*, including calls that
    fault, so Nth-call targeting is deterministic under retries."""

    def __init__(self, target=fs):
        self.target = target
        self.write_calls = 0
        self.read_calls = 0
        self.replace_calls = 0
        self._saved = {}
        # serving-fabric seams (ISSUE 9): replica name -> fault plan,
        # consulted by InProcessReplica on every step/probe. Plans are
        # plain scripted state, not monkey-patches, so restore() does
        # not apply — they die with the injector.
        self._replica_plans: Dict[str, "ReplicaFaultPlan"] = {}
        # alert-storm seams (ISSUE 16): scripted synthetic SLO alert
        # transitions, drained by the twin into SLOEngine.inject_alert
        self._alert_storms: List["AlertStormPlan"] = []

    # ------------------------------------------------------------ lifecycle
    def __enter__(self) -> "FaultInjector":
        return self

    def __exit__(self, *exc):
        self.restore()
        return False

    def _original(self, name: str):
        return self._saved.get(name, getattr(self.target, name))

    def _patch(self, name: str, value):
        if name not in self._saved:
            self._saved[name] = getattr(self.target, name)
        setattr(self.target, name, value)

    def restore(self):
        for name, value in self._saved.items():
            setattr(self.target, name, value)
        self._saved.clear()

    # ------------------------------------------------------------- helpers
    def fast_retries(self):
        """Zero out retry backoff so exhausting the retry budget is
        instant — keeps fault tests fast without changing retry counts."""
        self._patch("DEFAULT_BASE_DELAY_S", 0.0)
        self._patch("DEFAULT_MAX_DELAY_S", 0.0)

    def _buffer_stream(self, writer) -> bytes:
        """Materialize a stream_write payload so byte-level faults (e.g.
        truncation) can apply to streamed writers exactly as to byte writes."""
        import io as _io

        buf = _io.BytesIO()
        writer(buf)
        return buf.getvalue()

    # -------------------------------------------------------------- faults
    def fail_writes(self, nth: int = 1, count: int = 1,
                    exc_factory: Optional[Callable[[], BaseException]] = None):
        """Raise on write calls ``nth .. nth+count-1`` (1-based, counting
        byte AND streamed writes together); other calls pass through.
        Default exception is a retryable ``OSError`` — use ``count`` > the
        retry budget to defeat the retry wrapper."""
        exc_factory = exc_factory or (lambda: OSError("injected I/O error"))
        real_wb = self._original("write_bytes")
        real_sw = self._original("stream_write")

        def _faulted(go):
            self.write_calls += 1
            if nth <= self.write_calls < nth + count:
                raise exc_factory()
            return go()

        self._patch("write_bytes", lambda path, data: _faulted(
            lambda: real_wb(path, data)))
        self._patch("stream_write", lambda path, writer: _faulted(
            lambda: real_sw(path, writer)))

    def truncate_write(self, nth: int = 1, keep_bytes: int = 64,
                       crash: bool = True):
        """The ``nth`` write persists only ``keep_bytes``. ``crash=True``
        raises :class:`SimulatedCrash` after the partial write (process
        died mid-write); ``crash=False`` returns as if successful — a torn
        write the checksum manifest must catch at load time."""
        real_wb = self._original("write_bytes")
        real_sw = self._original("stream_write")

        def _truncated(path, data, go):
            self.write_calls += 1
            if self.write_calls == nth:
                real_wb(path, bytes(data()[:keep_bytes]))
                if crash:
                    raise SimulatedCrash(f"simulated crash mid-write of {path}")
                return
            return go()

        self._patch("write_bytes", lambda path, data: _truncated(
            path, lambda: data, lambda: real_wb(path, data)))
        self._patch("stream_write", lambda path, writer: _truncated(
            path, lambda: self._buffer_stream(writer),
            lambda: real_sw(path, writer)))

    def slow_writes(self, delay_s: float,
                    sleep_fn: Callable[[float], None] = _time.sleep):
        """Every write sleeps ``delay_s`` first (stalling filesystem)."""
        real_wb = self._original("write_bytes")
        real_sw = self._original("stream_write")

        def _slowed(go):
            self.write_calls += 1
            sleep_fn(delay_s)
            return go()

        self._patch("write_bytes", lambda path, data: _slowed(
            lambda: real_wb(path, data)))
        self._patch("stream_write", lambda path, writer: _slowed(
            lambda: real_sw(path, writer)))

    def fail_reads(self, nth: int = 1, count: int = 1,
                   exc_factory: Optional[Callable[[], BaseException]] = None):
        exc_factory = exc_factory or (lambda: OSError("injected read error"))
        real = self._original("read_bytes")

        def read_bytes(path):
            self.read_calls += 1
            if nth <= self.read_calls < nth + count:
                raise exc_factory()
            return real(path)

        self._patch("read_bytes", read_bytes)

    # ------------------------------------------------- serving seams (ISSUE 9)
    def replica_plan(self, name: str) -> "ReplicaFaultPlan":
        """Fault plan for replica ``name`` (created on first access).
        Hand it to ``InProcessReplica(chaos=...)``; the scripting
        helpers below mutate the same plan by name."""
        return self._replica_plans.setdefault(name, ReplicaFaultPlan(name))

    def crash_replica_step(self, name: str, nth: int):
        """Replica ``name`` dies entering its ``nth`` step (1-based,
        counting from when the plan attaches): models a replica process
        SIGKILLed mid-trace — the router must fail its in-flight
        requests over to a survivor."""
        self.replica_plan(name).crash_at_step = nth

    def flaky_replica_step(self, name: str, nth: int, count: int = 1):
        """Steps ``nth .. nth+count-1`` of replica ``name`` raise a
        retryable transient error (the replica stays alive): repeated
        transients should trip the router's circuit breaker."""
        plan = self.replica_plan(name)
        plan.flaky_steps.update(range(nth, nth + count))

    def straggle_replica(self, name: str, delay_s: float, *,
                         from_step: int = 1, until_step: Optional[int] = None):
        """Replica ``name`` becomes a straggler: every step in
        ``[from_step, until_step]`` stalls the (virtual) clock by
        ``delay_s`` — the slow-host shape that blows per-request
        deadlines without any crash."""
        plan = self.replica_plan(name)
        plan.slow_from, plan.slow_until = from_step, until_step
        plan.slow_delay_s = delay_s

    def fail_replica_probes(self, name: str, count: int = 1):
        """The next ``count`` health probes of replica ``name`` raise a
        transient error (probe timeout / connection refused) while
        steps keep working — health-check flap the breaker must absorb
        or act on."""
        self.replica_plan(name).failing_probes += count

    # ---------------------------------------------- alert seams (ISSUE 16)
    def alert_storm(self, *, start_s: float, count: int = 10,
                    period_s: float = 0.1, severity: str = "page",
                    rule: str = "injected:storm", sli: str = "availability",
                    flap: bool = True) -> "AlertStormPlan":
        """Script a storm of SYNTHETIC SLO alert transitions: ``count``
        fires starting at ``start_s``, one per ``period_s``; with
        ``flap=True`` each fire resolves half a period later — the
        pathological flapping shape an autoscaler's hysteresis and
        cooldowns must absorb without thrashing the pool. The twin
        drains :meth:`due_alerts` each iteration into
        ``SLOEngine.inject_alert``, which fans the alerts to every
        subscriber through the REAL emit path without perturbing the
        burn-rate state machine."""
        plan = AlertStormPlan(start_s=start_s, count=count,
                              period_s=period_s, severity=severity,
                              rule=rule, sli=sli, flap=flap)
        self._alert_storms.append(plan)
        return plan

    def due_alerts(self, now: float) -> List:
        """Pop every scripted alert transition due at/before ``now``
        (across all storms), in time order."""
        out = []
        for plan in self._alert_storms:
            out.extend(plan.pop_due(now))
        out.sort(key=lambda a: a.t)
        return out

    def crash_on_replace(self, nth: int = 1):
        """Process dies at the publish step: the tmp file is complete but
        the atomic rename never happens — the prior version must survive."""
        real = self._original("replace")

        def replace(src, dst):
            self.replace_calls += 1
            if self.replace_calls == nth:
                raise SimulatedCrash(f"simulated crash before publishing {dst}")
            return real(src, dst)

        self._patch("replace", replace)


# ------------------------------------------------ training seams (ISSUE 10)
def poison_sample(sample, mode: str):
    """Corrupt one dataset sample: ``"nan"`` fills float leaves with NaN
    (nonfinite loss/grads — what the finite-grad guard must catch);
    ``"huge"`` scales float leaves by 1e6 (finite but enormous loss — the
    robust z-score spike shape). Integer leaves (token ids) pass through."""
    import numpy as np

    def corrupt(node):
        if isinstance(node, dict):
            return {k: corrupt(v) for k, v in node.items()}
        if isinstance(node, (tuple, list)):
            return type(node)(corrupt(v) for v in node)
        arr = np.asarray(node)
        if arr.dtype.kind != "f":
            return node
        if mode == "nan":
            return np.full_like(arr, np.nan)
        if mode == "huge":
            return arr * np.asarray(1e6, arr.dtype)
        raise ValueError(f"unknown poison mode {mode!r}")

    return corrupt(sample)


class PoisonedDataset:
    """Indexable-dataset wrapper with per-index poison: models a corrupt
    data shard. ``poison`` maps dataset index -> mode ("nan" | "huge").
    The wrapped dataset is untouched, so the SAME underlying data drives
    the clean-run side of a bit-identity comparison."""

    def __init__(self, dataset, poison: Dict[int, str]):
        self.dataset = dataset
        self.poison = dict(poison)

    def __len__(self):
        return len(self.dataset)

    def __getitem__(self, i):
        sample = self.dataset[i]
        mode = self.poison.get(int(i))
        if mode is None:
            return sample
        return poison_sample(sample, mode)


def flip_param_bit(engine, device_index: int = 0, leaf_index: int = 0,
                   byte: int = 0, bit: int = 0):
    """Flip one bit in ONE device's copy of one parameter — the silent
    data corruption model (a host's HBM/SRAM bit-flip on a single
    data-parallel replica). Only the targeted device's shard changes;
    the cross-replica checksum audit must localize it to exactly
    ``device_index``. Returns the flipped leaf's flat index."""
    import jax
    import numpy as np

    leaves, treedef = jax.tree_util.tree_flatten(engine.state.params)
    leaf = leaves[leaf_index % len(leaves)]
    target = jax.devices()[device_index]
    singles = []
    for sh in leaf.addressable_shards:
        arr = np.array(sh.data, copy=True)
        if sh.device == target:
            flat = arr.view(np.uint8).reshape(-1)
            flat[byte % flat.size] ^= np.uint8(1 << (bit % 8))
        singles.append(jax.device_put(arr, sh.device))
    flipped = jax.make_array_from_single_device_arrays(
        leaf.shape, leaf.sharding, singles)
    leaves[leaf_index % len(leaves)] = flipped
    engine.state = engine.state._replace(
        params=jax.tree_util.tree_unflatten(treedef, leaves))
    return leaf_index % len(leaves)


def corrupt_file(path, keep_bytes: int = 64):
    """Truncate a file in place — bit-rot / torn-write damage to an
    already-published artifact (e.g. a checkpoint tag corrupted AFTER its
    save succeeded, the mid-recovery chaos case: the rewind walk-back
    must skip it and fall to an older valid tag). Fails loudly when the
    file is already smaller than ``keep_bytes`` — a chaos seam that
    injects nothing makes its test pass vacuously."""
    with open(path, "rb") as f:
        raw = f.read()
    if len(raw) <= keep_bytes:
        raise ValueError(
            f"corrupt_file({path!r}): file is {len(raw)} bytes <= "
            f"keep_bytes={keep_bytes}; truncation would be a no-op")
    with open(path, "wb") as f:
        f.write(raw[:keep_bytes])


class ReplicaFaultPlan:
    """Scripted fault schedule for ONE serving replica (ISSUE 9).

    ``InProcessReplica`` calls :meth:`on_step` entering every engine
    step and :meth:`on_probe` on every health probe; the plan raises
    the typed serving errors the fabric's failure model is written
    against — :class:`SimulatedCrash` for process death (the replica
    wrapper converts it to a terminal ``ReplicaCrashedError``) and
    ``TransientReplicaError`` for retryable flap. Slow-straggler steps
    stall the test's virtual clock (any object with ``advance``), so
    "this replica is 100x slower" is expressible without wall time.
    """

    def __init__(self, name: str):
        self.name = name
        self.crash_at_step: Optional[int] = None
        self.flaky_steps: set = set()
        self.slow_from: int = 0
        self.slow_until: Optional[int] = None
        self.slow_delay_s: float = 0.0
        self.failing_probes: int = 0
        self.step_calls = 0
        self.probe_calls = 0

    def on_step(self, clock=None) -> None:
        from deepspeed_tpu.serving.errors import TransientReplicaError

        self.step_calls += 1
        n = self.step_calls
        if self.crash_at_step is not None and n >= self.crash_at_step:
            # one-shot: a crash kills ONE process; a resurrected replica
            # re-attaching the same plan starts clean (script another
            # crash with crash_replica_step again if the schedule says so)
            self.crash_at_step = None
            raise SimulatedCrash(
                f"replica {self.name}: scripted crash at step {n}")
        if (self.slow_delay_s and n >= self.slow_from
                and (self.slow_until is None or n <= self.slow_until)):
            advance = getattr(clock, "advance", None)
            if advance is not None:
                advance(self.slow_delay_s)
        if n in self.flaky_steps:
            raise TransientReplicaError(
                f"replica {self.name}: scripted flaky step {n}")

    def on_probe(self) -> None:
        from deepspeed_tpu.serving.errors import TransientReplicaError

        self.probe_calls += 1
        if self.failing_probes > 0:
            self.failing_probes -= 1
            raise TransientReplicaError(
                f"replica {self.name}: scripted probe failure "
                f"#{self.probe_calls}")


class AlertStormPlan:
    """Scripted synthetic-alert schedule (ISSUE 16): a deterministic
    sequence of ``(t, "fired"/"resolved")`` transitions for one rule
    name. Builds real :class:`~deepspeed_tpu.telemetry.slo.SLOAlert`
    objects lazily (keeps this module import-light)."""

    def __init__(self, *, start_s: float, count: int, period_s: float,
                 severity: str, rule: str, sli: str, flap: bool):
        self.rule = rule
        self.sli = sli
        self.severity = severity
        self.delivered = 0
        self._schedule: List = []   # (t, transition) pending, time-ordered
        for i in range(count):
            t = start_s + i * period_s
            self._schedule.append((t, "fired"))
            if flap:
                self._schedule.append((t + period_s / 2.0, "resolved"))
        self._schedule.sort(key=lambda x: x[0])

    def pop_due(self, now: float) -> List:
        from deepspeed_tpu.telemetry.slo import SLOAlert

        out = []
        while self._schedule and self._schedule[0][0] <= now:
            t, transition = self._schedule.pop(0)
            self.delivered += 1
            out.append(SLOAlert(
                rule=self.rule, sli=self.sli, severity=self.severity,
                kind=transition, t=t, burn_short=99.0, burn_long=99.0,
                budget_consumed=1.0))
        return out


class FakeClock:
    """Deterministic virtual clock for ElasticAgent / serving-fabric
    tests: pass ``.time`` as ``time_fn`` and ``.sleep`` as ``sleep_fn``.
    ``auto_dt`` > 0 advances the clock by that much per ``time()`` READ
    — the serving engines poll the clock once per iteration, so an
    auto-advancing clock replays arrival traces deterministically
    without anyone calling ``advance`` (the fabric chaos suite's
    mode)."""

    def __init__(self, start: float = 0.0, auto_dt: float = 0.0):
        self.now = start
        self.auto_dt = auto_dt
        self.sleeps: List[float] = []

    def time(self) -> float:
        self.now += self.auto_dt
        return self.now

    def sleep(self, seconds: float):
        self.sleeps.append(seconds)
        self.now += seconds

    def advance(self, seconds: float):
        self.now += seconds


class ScriptedWorkerGroup:
    """A ``spawn_fn``/``monitor_fn`` pair whose worker groups exit with a
    scripted sequence of codes (the last one repeats). ``run_time_s``
    advances ``clock`` per monitored round, modelling how long the group
    lived — what the rolling restart-budget window keys on."""

    def __init__(self, exit_codes: Sequence[int],
                 clock: Optional[FakeClock] = None, run_time_s: float = 0.0):
        self.exit_codes = list(exit_codes)
        self.clock = clock
        self.run_time_s = run_time_s
        self.spawns = 0

    def spawn(self) -> List[str]:
        self.spawns += 1
        return [f"worker-group-{self.spawns}"]

    def monitor(self, procs) -> int:
        if self.clock is not None and self.run_time_s:
            self.clock.advance(self.run_time_s)
        return self.exit_codes[min(self.spawns - 1, len(self.exit_codes) - 1)]
