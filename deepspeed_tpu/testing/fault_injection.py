"""Reusable fault-injection harness for robustness tests.

All checkpoint bytes flow through the seam functions in
``deepspeed_tpu.utils.fs`` (``read_bytes`` / ``write_bytes`` / ``replace``),
so :class:`FaultInjector` can deterministically inject the failure modes
that matter for fault tolerance — truncated writes, I/O errors on the Nth
call, slow writes, and simulated worker crashes mid-operation — without
subprocesses, making the tests tier-1-safe.

For the elasticity layer, :class:`FakeClock` and :class:`ScriptedWorkerGroup`
drive :class:`~deepspeed_tpu.elasticity.elastic_agent.ElasticAgent` through
arbitrary failure/preemption schedules in virtual time.

Usage::

    with FaultInjector() as inj:
        inj.truncate_write(nth=1, keep_bytes=64)   # crash mid state.npz
        with pytest.raises(SimulatedCrash):
            engine.save_checkpoint(ckpt_dir)
    # seam functions restored here
"""

from __future__ import annotations

import time as _time
from typing import Callable, List, Optional, Sequence

from deepspeed_tpu.utils import fs


class SimulatedCrash(BaseException):
    """Models a worker dying mid-operation (SIGKILL / preemption without
    grace). Derives from ``BaseException`` so generic ``except Exception``
    recovery paths cannot accidentally 'survive' the kill — exactly like a
    real dead process."""


class FaultInjector:
    """Patches ``deepspeed_tpu.utils.fs`` primitives; restores them on
    ``__exit__`` / ``restore()``. Call counters (``write_calls``,
    ``read_calls``, ``replace_calls``) count *entries*, including calls that
    fault, so Nth-call targeting is deterministic under retries."""

    def __init__(self, target=fs):
        self.target = target
        self.write_calls = 0
        self.read_calls = 0
        self.replace_calls = 0
        self._saved = {}

    # ------------------------------------------------------------ lifecycle
    def __enter__(self) -> "FaultInjector":
        return self

    def __exit__(self, *exc):
        self.restore()
        return False

    def _original(self, name: str):
        return self._saved.get(name, getattr(self.target, name))

    def _patch(self, name: str, value):
        if name not in self._saved:
            self._saved[name] = getattr(self.target, name)
        setattr(self.target, name, value)

    def restore(self):
        for name, value in self._saved.items():
            setattr(self.target, name, value)
        self._saved.clear()

    # ------------------------------------------------------------- helpers
    def fast_retries(self):
        """Zero out retry backoff so exhausting the retry budget is
        instant — keeps fault tests fast without changing retry counts."""
        self._patch("DEFAULT_BASE_DELAY_S", 0.0)
        self._patch("DEFAULT_MAX_DELAY_S", 0.0)

    def _buffer_stream(self, writer) -> bytes:
        """Materialize a stream_write payload so byte-level faults (e.g.
        truncation) can apply to streamed writers exactly as to byte writes."""
        import io as _io

        buf = _io.BytesIO()
        writer(buf)
        return buf.getvalue()

    # -------------------------------------------------------------- faults
    def fail_writes(self, nth: int = 1, count: int = 1,
                    exc_factory: Optional[Callable[[], BaseException]] = None):
        """Raise on write calls ``nth .. nth+count-1`` (1-based, counting
        byte AND streamed writes together); other calls pass through.
        Default exception is a retryable ``OSError`` — use ``count`` > the
        retry budget to defeat the retry wrapper."""
        exc_factory = exc_factory or (lambda: OSError("injected I/O error"))
        real_wb = self._original("write_bytes")
        real_sw = self._original("stream_write")

        def _faulted(go):
            self.write_calls += 1
            if nth <= self.write_calls < nth + count:
                raise exc_factory()
            return go()

        self._patch("write_bytes", lambda path, data: _faulted(
            lambda: real_wb(path, data)))
        self._patch("stream_write", lambda path, writer: _faulted(
            lambda: real_sw(path, writer)))

    def truncate_write(self, nth: int = 1, keep_bytes: int = 64,
                       crash: bool = True):
        """The ``nth`` write persists only ``keep_bytes``. ``crash=True``
        raises :class:`SimulatedCrash` after the partial write (process
        died mid-write); ``crash=False`` returns as if successful — a torn
        write the checksum manifest must catch at load time."""
        real_wb = self._original("write_bytes")
        real_sw = self._original("stream_write")

        def _truncated(path, data, go):
            self.write_calls += 1
            if self.write_calls == nth:
                real_wb(path, bytes(data()[:keep_bytes]))
                if crash:
                    raise SimulatedCrash(f"simulated crash mid-write of {path}")
                return
            return go()

        self._patch("write_bytes", lambda path, data: _truncated(
            path, lambda: data, lambda: real_wb(path, data)))
        self._patch("stream_write", lambda path, writer: _truncated(
            path, lambda: self._buffer_stream(writer),
            lambda: real_sw(path, writer)))

    def slow_writes(self, delay_s: float,
                    sleep_fn: Callable[[float], None] = _time.sleep):
        """Every write sleeps ``delay_s`` first (stalling filesystem)."""
        real_wb = self._original("write_bytes")
        real_sw = self._original("stream_write")

        def _slowed(go):
            self.write_calls += 1
            sleep_fn(delay_s)
            return go()

        self._patch("write_bytes", lambda path, data: _slowed(
            lambda: real_wb(path, data)))
        self._patch("stream_write", lambda path, writer: _slowed(
            lambda: real_sw(path, writer)))

    def fail_reads(self, nth: int = 1, count: int = 1,
                   exc_factory: Optional[Callable[[], BaseException]] = None):
        exc_factory = exc_factory or (lambda: OSError("injected read error"))
        real = self._original("read_bytes")

        def read_bytes(path):
            self.read_calls += 1
            if nth <= self.read_calls < nth + count:
                raise exc_factory()
            return real(path)

        self._patch("read_bytes", read_bytes)

    def crash_on_replace(self, nth: int = 1):
        """Process dies at the publish step: the tmp file is complete but
        the atomic rename never happens — the prior version must survive."""
        real = self._original("replace")

        def replace(src, dst):
            self.replace_calls += 1
            if self.replace_calls == nth:
                raise SimulatedCrash(f"simulated crash before publishing {dst}")
            return real(src, dst)

        self._patch("replace", replace)


class FakeClock:
    """Deterministic virtual clock for ElasticAgent tests: pass ``.time``
    as ``time_fn`` and ``.sleep`` as ``sleep_fn``."""

    def __init__(self, start: float = 0.0):
        self.now = start
        self.sleeps: List[float] = []

    def time(self) -> float:
        return self.now

    def sleep(self, seconds: float):
        self.sleeps.append(seconds)
        self.now += seconds

    def advance(self, seconds: float):
        self.now += seconds


class ScriptedWorkerGroup:
    """A ``spawn_fn``/``monitor_fn`` pair whose worker groups exit with a
    scripted sequence of codes (the last one repeats). ``run_time_s``
    advances ``clock`` per monitored round, modelling how long the group
    lived — what the rolling restart-budget window keys on."""

    def __init__(self, exit_codes: Sequence[int],
                 clock: Optional[FakeClock] = None, run_time_s: float = 0.0):
        self.exit_codes = list(exit_codes)
        self.clock = clock
        self.run_time_s = run_time_s
        self.spawns = 0

    def spawn(self) -> List[str]:
        self.spawns += 1
        return [f"worker-group-{self.spawns}"]

    def monitor(self, procs) -> int:
        if self.clock is not None and self.run_time_s:
            self.clock.advance(self.run_time_s)
        return self.exit_codes[min(self.spawns - 1, len(self.exit_codes) - 1)]
