"""Inference engine (L5).

Parity target: reference ``deepspeed/inference/engine.py`` (InferenceEngine:89,
610 LoC) + the CUDA kernel set behind it (ds_attention.py, ds_mlp.py,
softmax_context w/ KV cache). TPU-native redesign:

  * kernel injection (`replace_transformer_layer`, module_inject) becomes
    *weight mapping*: HF torch modules are converted once into this
    framework's own model implementations via per-arch policies
    (inference/policies.py) — the containers/policies concept survives, the
    nn.Module surgery does not (SURVEY §7.12).
  * CUDA-graph capture/replay (engine.py:500,:519) is replaced by jit: the
    prefill and the decode step are each ONE compiled XLA program with a
    static-shape KV cache.
  * TP for serving (`_create_model_parallel_group`, :261) is the 'model'
    mesh axis; per-layer output allreduces are XLA collectives over ICI.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.accelerator import get_accelerator
from deepspeed_tpu.inference.config import DeepSpeedInferenceConfig
from deepspeed_tpu.runtime.zero.partition import PartitionPlan
from deepspeed_tpu.utils import groups as groups_mod
from deepspeed_tpu.utils.logging import log_dist, logger


def filter_logits(logits, *, top_k: int = 0, top_p: float = 1.0):
    """Sampling-filter parity with HF's TopKLogitsWarper + TopPLogitsWarper
    (the path the reference's serving takes through HF ``generate``,
    reference inference/engine.py:588): top-k first, then nucleus — keep the
    smallest prefix of the descending-sorted distribution whose cumulative
    probability reaches ``top_p`` (always >= 1 token), mask the rest to
    -inf. Value-ties at the nucleus boundary are all kept (HF cuts by
    sorted position; with distinct logits the support sets are identical).
    """
    if top_k > 0:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p < 1.0:
        sort = jnp.sort(logits, axis=-1)[..., ::-1]  # descending
        probs = jax.nn.softmax(sort, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        keep = (cum - probs) < top_p  # exclusive cumsum: keeps the crosser
        kth = jnp.min(jnp.where(keep, sort, jnp.inf), axis=-1, keepdims=True)
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    return logits


class InferenceEngine:
    """Serve a ModelSpec (or a converted HF torch model) with a compiled
    prefill + decode loop (reference InferenceEngine:89)."""

    def __init__(self, model, config: DeepSpeedInferenceConfig, *,
                 params=None, topology=None):
        if not isinstance(config, DeepSpeedInferenceConfig):
            config = DeepSpeedInferenceConfig(**(config or {}))
        self._config = config
        self.dtype = config.jax_dtype()
        # int8 = weight-only quantization (reference GroupQuantizer path,
        # module_inject/replace_module.py:140): HBM holds int8 weights +
        # per-column scales, compute runs in bf16 on per-layer dequantized
        # tiles (see models/base.qdot)
        self.weight_quant = bool(config.quant.enabled)
        if self.dtype == jnp.int8:
            self.weight_quant = True
            self.dtype = jnp.bfloat16
        if self.weight_quant:
            if config.quant.bits != 8:
                raise ValueError(
                    f"weight quantization supports bits=8 only "
                    f"(got {config.quant.bits})")
            log_dist("weight quantization uses per-layer per-output-column "
                     "scales; quant.group_size is ignored", ranks=[0])
        elif getattr(config.quant, "quantize_embedding", False):
            # same fail-loudly contract as the int8 weight check below:
            # silently leaving the ~77 MB tied table full-precision when
            # its quantization was explicitly requested would defeat the
            # sizing the flag exists for
            raise ValueError(
                "quant.quantize_embedding requires weight quantization "
                "(quant.enabled=true or dtype='int8'): the tied-embedding "
                "int8 path rides the weight-quant initialization")

        # HF torch module → (ModelSpec, params) via policy (module_inject analog)
        if _is_torch_module(model):
            from deepspeed_tpu.inference.policies import convert_hf_model

            model, hf_params = convert_hf_model(model, compute_dtype=self.dtype)
            if params is None:
                params = hf_params
        # align the model's compute dtype with the serving dtype — a bf16
        # model served with dtype="fp32" would otherwise mix dtypes in the
        # decode-loop carry (scan carries are dtype-strict)
        if hasattr(model, "compute_dtype") and model.compute_dtype != self.dtype:
            model.compute_dtype = self.dtype
        self.module = model

        # ---- topology: model axis = tp (reference _create_model_parallel_group)
        if topology is None:
            topology = groups_mod.initialize(tp_size=config.tp_size,
                                             ep_size=config.ep_size)
        else:
            groups_mod.initialize(topology)
        self.topology = topology
        self.mesh = topology.mesh
        self.plan = PartitionPlan(topology=topology, zero_stage=0)
        self.logical_axes = model.logical_axes() if hasattr(model, "logical_axes") else None

        # ---- parameters: explicit > checkpoint > fresh init
        if params is None and config.checkpoint is not None:
            params = self._load_checkpoint_params(config.checkpoint)
        if self.weight_quant and not getattr(self.module,
                                             "supports_weight_quant", False):
            # an explicit int8 request that cannot be honored must fail
            # loudly — silently serving bf16 would use ~4x the HBM the
            # deployment was sized for
            raise ValueError(
                f"int8 weight quantization requested but "
                f"{type(self.module).__name__} does not support dequant "
                "blocks (models must route weight matmuls through models/base.qdot in "
                "their block scan and set supports_weight_quant = True)")
        if (params is None and self.weight_quant
                and config.tp_size == 1 and config.ep_size == 1):
            # stream-init: each quantizable block leaf is initialized AND
            # quantized in its own fused program (XLA DCE reduces the jitted
            # init to just that leaf), so the full serving-dtype tree never
            # materializes — HBM peak is the int8 tree + ONE bf16 leaf
            # (~9.4 GB at 6.7B vs ~20 GB init-then-quantize). Values are
            # bit-identical to the one-shot init.
            self.params, n_q = self._stream_init_quantized(
                jax.random.PRNGKey(config.seed))
            log_dist(f"weight-only int8: stream-initialized {n_q} block "
                     "weight tensors (per-layer, per-output-column scales)",
                     ranks=[0])
            self.params = self._maybe_quantize_embedding(self.params)
        else:
            if params is None:
                # cast fused INTO the jitted init: XLA folds the astype into
                # the elementwise RNG sampling, so only serving-dtype params
                # ever materialize — initializing a 7B model in f32 and
                # casting after would transiently need 2x the weight HBM
                # (27 GB at 6.7B)
                # dstpu-lint: disable=recompile-hazard -- one-shot fused init+cast at engine construction
                params = jax.jit(self._init_cast)(
                    jax.random.PRNGKey(config.seed))
            self.params = self._shard_and_cast(params)
            params = None  # drop the caller-scope tree: the quantize walk
            # below frees each bf16 leaf as its int8 replacement is built
            if self.weight_quant:
                self.params, n_q = self._quantize_block_weights(self.params)
                log_dist(f"weight-only int8: quantized {n_q} block weight "
                         "tensors (per-layer, per-output-column scales)",
                         ranks=[0])
                self.params = self._maybe_quantize_embedding(self.params)

        self._compiled: Dict[Tuple, Any] = {}
        self._gen_rng = jax.random.PRNGKey(config.seed)
        log_dist(
            f"InferenceEngine: dtype={self.dtype.__name__} tp={config.tp_size} "
            f"ep={config.ep_size} max_tokens={config.max_tokens}", ranks=[0])

    # ----------------------------------------------------------------- params
    def _init_cast(self, key):
        """Fresh init with the serving-dtype cast fused into the jitted
        program (XLA folds the astype into the RNG sampling)."""
        return jax.tree_util.tree_map(
            lambda x: x.astype(self.dtype)
            if x.dtype == jnp.float32 else x, self.module.init(key))

    @staticmethod
    def _is_quantizable(leaf, in_blocks: bool) -> bool:
        """Same predicate as _quantize_block_weights: stacked [L, in, out]
        float matmul weights under a 'blocks' subtree."""
        return (in_blocks and hasattr(leaf, "ndim") and leaf.ndim == 3
                and leaf.dtype in (jnp.float32, jnp.bfloat16, jnp.float16)
                and min(leaf.shape[1:]) >= 16)

    def _stream_init_quantized(self, key):
        """Random-init int8 serving without ever materializing the full
        serving-dtype tree: each quantizable block leaf gets its own fused
        jitted program (init -> take leaf -> quantize) — XLA dead-code
        eliminates every other leaf's sampling, so the program's footprint
        is ONE bf16 leaf + its int8 image. Peak HBM = int8 tree + largest
        bf16 leaf (~9.4 GB at 6.7B) instead of full-bf16 + int8 (~20 GB),
        which is the difference between fitting and OOMing a 16 GB chip.
        Values are bit-identical to the one-shot init + quantize path
        (single-mesh tp=1/ep=1 only; larger meshes take the sharded
        two-phase path). Reference sizing analog: the deployment-sized
        GroupQuantizer load in module_inject/replace_module.py:140."""
        from deepspeed_tpu.compression.quantize import quantize_int8

        shapes = jax.eval_shape(self._init_cast, key)

        def find_qpaths(tree, in_blocks=False, prefix=()):
            out = []
            if isinstance(tree, dict):
                for k, v in tree.items():
                    if self._is_quantizable(v, in_blocks):
                        out.append(prefix + (k,))
                    else:
                        out.extend(find_qpaths(v, in_blocks or k == "blocks",
                                               prefix + (k,)))
            return out

        def get(tree, path):
            for k in path:
                tree = tree[k]
            return tree

        qpaths = find_qpaths(shapes)
        quantized = {}
        for path in qpaths:
            def leaf_q(key, _path=path):
                leaf = get(self._init_cast(key), _path)
                qv, scale = jax.vmap(
                    lambda w: quantize_int8(w, per_channel_axis=1))(leaf)
                return {"__q__": qv, "__scale__": scale}

            # block per leaf: overlapping two leaf programs would double the
            # transient bf16 footprint this path exists to avoid
            # dstpu-lint: disable=recompile-hazard -- init-time weight quantize: serial per-leaf programs bound the transient bf16 footprint
            quantized[path] = jax.block_until_ready(jax.jit(leaf_q)(key))

        def rest(key):
            tree = self._init_cast(key)
            for path in qpaths:
                del get(tree, path[:-1])[path[-1]]
            return tree

        # the non-quantized remainder honors the same placement/cast
        # contract as the init-then-quantize path (_shard_and_cast:
        # serving-dtype recast + device_put under the plan's
        # NamedSharding) — this path is gated to tp=1/ep=1, where the
        # specs are replicated, but the contract should not silently
        # diverge between init paths
        # dstpu-lint: disable=recompile-hazard -- one-shot init-time quantize of the non-block leaves
        params = self._shard_and_cast(jax.jit(rest)(key))
        for path, qleaf in quantized.items():
            get(params, path[:-1])[path[-1]] = qleaf
        return params, len(qpaths)

    def _shard_and_cast(self, params):
        axes = self.logical_axes

        missing = []

        def prune(ax, tree, path=""):
            """Logical-axes subtree matching ``tree`` (the stream-init
            path shards a PARTIAL tree whose quantized leaves were
            carved out). Param keys ABSENT from logical_axes are kept
            with None (replicated) specs — silently dropping them used
            to surface as an opaque tree-structure mismatch deep in
            compute_specs instead of naming the unannotated param."""
            if isinstance(ax, dict) and isinstance(tree, dict):
                out = {}
                for k, v in tree.items():
                    if k in ax:
                        out[k] = prune(ax[k], v, f"{path}/{k}")
                    else:
                        missing.append(f"{path}/{k}")
                        out[k] = jax.tree_util.tree_map(lambda _: None, v)
                return out
            return ax

        if axes is not None:
            axes = prune(axes, params)
            if missing:
                logger.warning(
                    "logical_axes is missing entries for %s — treating "
                    "them as replicated (no TP/ZeRO sharding); annotate "
                    "them in the model's logical_axes() to shard them",
                    ", ".join(missing))
        specs = self.plan.compute_specs(
            jax.eval_shape(lambda: params), axes)

        def put(p, spec):
            arr = jnp.asarray(p)
            if arr.dtype in (jnp.float32, jnp.float16, jnp.bfloat16):
                arr = arr.astype(self.dtype)
            return jax.device_put(arr, NamedSharding(self.mesh, spec))

        return jax.tree_util.tree_map(put, params, specs)

    def _quantize_block_weights(self, params):
        """Quantize scanned-block matmul weights ([L, in, out] float leaves
        under a 'blocks' subtree) to int8 with [L, 1, out] fp32 scales."""
        from deepspeed_tpu.compression.quantize import quantize_int8

        count = 0

        @jax.jit
        def q(leaf):
            # per-layer (vmap over L), per-output-column scales
            qv, scale = jax.vmap(
                lambda w: quantize_int8(w, per_channel_axis=1))(leaf)
            return {"__q__": qv, "__scale__": scale}

        def walk(tree, in_blocks=False):
            nonlocal count
            if isinstance(tree, dict):
                out = {}
                for k, v in list(tree.items()):
                    if self._is_quantizable(v, in_blocks):
                        # consume the source leaf BEFORE quantizing: at 7B
                        # scale holding the full bf16 tree alongside the
                        # int8 one would peak at ~3x the quantized
                        # footprint. Mutating `tree` is safe only because
                        # _shard_and_cast always returns fresh dict
                        # containers (never caller-owned ones); a failure
                        # mid-walk leaves the source tree with popped keys,
                        # and the caller must not reuse it.
                        leaf = tree.pop(k)
                        out[k] = q(leaf)
                        del leaf
                        count += 1
                    else:
                        out[k] = walk(v, in_blocks or k == "blocks")
                return out
            return tree

        return walk(params), count

    def _maybe_quantize_embedding(self, params):
        """int8 tied-embedding quantization (ISSUE 12 satellite,
        ``quant.quantize_embedding``): ONE per-vocab-row scale serves
        both consumers of the tied table — the embedding gather (exact
        per-row dequant, models/base.embed_tokens) and the lm-head
        matmul (scale on the output logit column, base.tied_logits).
        At 125M the tied table is ~77 MB of the 249 MB int8 weight
        stream (PROFILE_DECODE.md) — the last unquantized resident.
        Requires the model to route wte through the quant-aware helpers
        (``supports_embedding_quant``); fails loudly otherwise, exactly
        like the block-weight support check."""
        if not getattr(self._config.quant, "quantize_embedding", False):
            return params
        if not getattr(self.module, "supports_embedding_quant", False):
            raise ValueError(
                f"quant.quantize_embedding requested but "
                f"{type(self.module).__name__} does not route its tied "
                "embedding through models/base.embed_tokens/tied_logits "
                "(set supports_embedding_quant = True once it does)")
        mcfg = getattr(self.module, "config", None)
        if not getattr(mcfg, "tie_embeddings", True):
            raise ValueError(
                "quant.quantize_embedding targets the TIED embedding; "
                "this model unties wte from its lm_head")
        from deepspeed_tpu.compression.quantize import quantize_int8

        @jax.jit
        def q(leaf):
            qv, scale = quantize_int8(leaf, per_channel_axis=0)  # [V, 1]
            return {"__q__": qv, "__scale__": scale}

        leaf = params.pop("wte")
        params["wte"] = jax.block_until_ready(q(leaf))
        del leaf
        log_dist("weight-only int8: quantized tied embedding/lm-head "
                 "(per-vocab-row scales)", ranks=[0])
        return params

    def _load_checkpoint_params(self, checkpoint):
        """Load from this framework's sharding-agnostic engine checkpoint
        (reference loads mp-rank/meta-tensor checkpoints, load_checkpoint.py;
        here one global npz serves any mesh)."""
        from deepspeed_tpu.runtime.checkpoint_engine.engine import load_params_for_inference

        if isinstance(checkpoint, str):
            path = checkpoint
        else:
            path = checkpoint.get("checkpoint_dir") or checkpoint.get("base_dir")
            if path is None:
                raise ValueError(
                    "inference checkpoint dict must carry 'checkpoint_dir' (or "
                    f"'base_dir') pointing at an engine checkpoint; got keys "
                    f"{sorted(checkpoint)}")
        template = jax.eval_shape(self.module.init, jax.random.PRNGKey(0))
        return load_params_for_inference(path, template)

    # ---------------------------------------------------------------- forward
    def forward(self, input_ids):
        """Full no-cache forward → logits (reference forward:560)."""
        key = ("fwd", tuple(np.shape(input_ids)))
        if key not in self._compiled:
            def fwd(params, ids):
                hidden = self.module.forward_hidden(params, ids, train=False)
                return self.module.logits(params, hidden)

            self._compiled[key] = jax.jit(fwd)
        return self._compiled[key](self.params, jnp.asarray(input_ids))

    __call__ = forward

    # --------------------------------------------------------------- generate
    def generate(self, input_ids, max_new_tokens: int = 32, *,
                 do_sample: bool = False, temperature: float = 1.0,
                 top_k: int = 0, top_p: float = 1.0,
                 eos_token_id: Optional[int] = None,
                 pad_token_id: int = 0, seed: Optional[int] = None):
        """Autoregressive generation: one jitted prefill + one jitted decode
        step iterated ``max_new_tokens`` times (reference _generate:588 via HF
        model.generate over injected modules). Sampling supports greedy,
        top-k, and top-p/nucleus (HF TopPLogitsWarper semantics); with
        ``eos_token_id`` set, the decode loop is a ``while_loop`` that exits
        as soon as every batch row has emitted EOS (HF early-stopping analog)
        — remaining positions are ``pad_token_id``.

        input_ids: [B, T] — uniform prompt length per call (static shapes).
        Returns np.ndarray [B, T + max_new_tokens].
        """
        input_ids = np.asarray(input_ids)
        assert input_ids.ndim == 2, "generate expects [batch, seq]"
        if max_new_tokens < 1:
            raise ValueError(f"generate: max_new_tokens must be >= 1, got {max_new_tokens}")
        if max_new_tokens < self._config.min_out_tokens:
            raise RuntimeError(
                f"generate: max_new_tokens {max_new_tokens} below min_out_tokens "
                f"{self._config.min_out_tokens} (reference min_tokens semantics)")
        b, t = input_ids.shape
        total = t + max_new_tokens
        # token budget guard (reference engine.py:588 blocks > max_out_tokens)
        if total > self._config.max_tokens:
            raise RuntimeError(
                f"generate: input+new tokens {total} exceeds max_tokens "
                f"{self._config.max_tokens} (reference max_out_tokens semantics); "
                f"raise it in the inference config")
        # position-table guard: past max_seq_len the wpe/RoPE gathers clamp and
        # silently produce garbage — fail loudly instead
        mcfg = getattr(self.module, "config", None)
        model_max = getattr(mcfg, "max_seq_len", None)
        if not getattr(mcfg, "has_position_table", True):
            model_max = None  # pure-ALiBi models extrapolate freely
        if model_max is not None and total > model_max:
            raise RuntimeError(
                f"generate: input+new tokens {total} exceeds the model's "
                f"max_seq_len {model_max} (position table size)")
        vocab = getattr(getattr(self.module, "config", None), "vocab_size", None)
        if top_k and vocab is not None and top_k > vocab:
            raise ValueError(f"generate: top_k {top_k} > vocab_size {vocab}")
        if not (0.0 < top_p <= 1.0):
            raise ValueError(f"generate: top_p must be in (0, 1], got {top_p}")

        key = ("gen", b, t, max_new_tokens, do_sample, top_k, float(top_p),
               eos_token_id, pad_token_id)
        if key not in self._compiled:
            self._compiled[key] = self._build_generate(
                b, t, max_new_tokens, do_sample=do_sample, top_k=top_k,
                top_p=float(top_p), eos_token_id=eos_token_id,
                pad_token_id=pad_token_id)
        if seed is not None:
            rng = jax.random.PRNGKey(seed)
        else:
            self._gen_rng, rng = jax.random.split(self._gen_rng)
        temp = jnp.asarray(max(temperature, 1e-6), jnp.float32)
        out_tokens = self._compiled[key](self.params, jnp.asarray(input_ids), temp, rng)
        return np.concatenate([input_ids, np.asarray(jax.device_get(out_tokens))], axis=1)

    def _build_generate(self, b, t, max_new, *, do_sample, top_k, top_p,
                        eos_token_id, pad_token_id):
        """Two compiled programs — prefill (builds the cache, picks token 0)
        and decode (the token loop) — composed by a host-side driver.

        Why not one fused program: a single XLA program carrying BOTH the
        prefill graph and the decode loop over the full weight tree fails
        with ResourceExhausted on large models on this backend even though
        its compiled peak memory fits (measured at 6.7B int8: prefill-only
        and decode-only each run fine; the fusion of the two does not).
        Both programs still recompile per prompt length (the KV cache is
        shaped [*, t + max_new, *], so `total` is in both cache keys) —
        the split's benefit is the ResourceExhausted fix plus smaller
        individual executables. It mirrors the split the reference's
        inference engine makes between its prompt and token phases
        (csrc/transformer/inference pt_binding.cpp allocate_workspace
        prompt/token paths)."""
        model = self.module
        total = t + max_new
        pick = self._make_pick(do_sample, top_k, top_p)

        # pad the KV allocation to a multiple of 128 so the flash-decode
        # kernel's sequence blocks tile (ops/attention.decode_attention
        # routing); masking by cache_index keeps padded positions inert
        cache_len = (total + 127) // 128 * 128

        pf_key = ("pf", b, t, total, do_sample, top_k, top_p)
        if pf_key not in self._compiled:
            def prefill(params, ids, temp, rng):
                cache = model.init_cache(b, cache_len, dtype=self.dtype)
                logits, cache = model.forward_with_cache(params, ids, cache)
                rng, sub = jax.random.split(rng)
                return pick(logits[:, -1], temp, sub), cache, rng

            self._compiled[pf_key] = jax.jit(prefill)
        prefill_fn = self._compiled[pf_key]

        if eos_token_id is None:
            dec_key = ("dec", b, total, max_new, do_sample, top_k, top_p)
            if dec_key not in self._compiled:
                def decode(params, tok, cache, temp, rng):
                    def step(carry, _):
                        tok, cache, rng = carry
                        logits, cache = model.forward_with_cache(
                            params, tok[:, None], cache)
                        rng, sub = jax.random.split(rng)
                        nxt = pick(logits[:, -1], temp, sub)
                        return (nxt, cache, rng), tok

                    (last, _, _), toks = jax.lax.scan(
                        step, (tok, cache, rng), None, length=max_new - 1)
                    return jnp.concatenate([toks.T, last[:, None]], axis=1)

                # donate the cache: the decode loop must not double-buffer
                # the [L,B,H,S,Dh] KV tensors at 7B scale
                self._compiled[dec_key] = jax.jit(decode, donate_argnums=(2,))
            decode_fn = self._compiled[dec_key]

            def gen(params, ids, temp, rng):
                tok, cache, rng = prefill_fn(params, ids, temp, rng)
                if max_new == 1:
                    return tok[:, None]
                return decode_fn(params, tok, cache, temp, rng)

            return gen

        # EOS path: while_loop exits once every row has EMITTED its eos
        # (prev_done); pending-but-unwritten eos keeps the loop alive one
        # more tick so it lands in the buffer.
        dec_key = ("dec_eos", b, total, max_new, do_sample, top_k, top_p,
                   eos_token_id, pad_token_id)
        if dec_key not in self._compiled:
            def decode_eos(params, tok, cache, temp, rng):
                done = tok == eos_token_id
                buf = jnp.full((max_new, b), pad_token_id, jnp.int32)

                def cond(carry):
                    i, *_rest, prev_done, _buf = carry
                    return (i < max_new) & ~jnp.all(prev_done)

                def body(carry):
                    i, tok, cache, rng, done, prev_done, buf = carry
                    buf = buf.at[i].set(tok)

                    def do_step(args):
                        tok, cache, rng = args
                        logits, cache = model.forward_with_cache(
                            params, tok[:, None], cache)
                        rng, sub = jax.random.split(rng)
                        nxt = pick(logits[:, -1], temp, sub)
                        return jnp.where(done, pad_token_id, nxt), cache, rng

                    # skip the decode forward when this was the last token
                    # to emit (parity with the scan path's max_new - 1
                    # forwards)
                    need = (i + 1 < max_new) & ~jnp.all(done)
                    nxt, cache, rng = jax.lax.cond(
                        need, do_step, lambda args: args, (tok, cache, rng))
                    return (i + 1, nxt, cache, rng,
                            done | (nxt == eos_token_id), done, buf)

                prev_done = jnp.zeros((b,), bool)
                *_state, buf = jax.lax.while_loop(
                    cond, body, (0, tok, cache, rng, done, prev_done, buf))
                return buf.T

            self._compiled[dec_key] = jax.jit(decode_eos, donate_argnums=(2,))
        decode_eos_fn = self._compiled[dec_key]

        def gen(params, ids, temp, rng):
            tok, cache, rng = prefill_fn(params, ids, temp, rng)
            return decode_eos_fn(params, tok, cache, temp, rng)

        return gen

    def _make_pick(self, do_sample, top_k, top_p):
        """Token-selection closure shared by generate() and the serving
        programs: greedy argmax, or top-k/top-p filtered sampling."""
        def pick(logits, temp, rng):
            logits = logits.astype(jnp.float32)
            if not do_sample:
                return jnp.argmax(logits, axis=-1).astype(jnp.int32)
            logits = filter_logits(logits / temp, top_k=top_k, top_p=top_p)
            return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)

        return pick

    # ------------------------------------------- continuous-batching programs
    def slot_prefill_program(self, bucket_len: int, num_slots: int,
                             max_len: int, *, do_sample: bool = False,
                             top_k: int = 0, top_p: float = 1.0):
        """Jitted slot-insert prefill for the continuous-batching serving
        runtime (serving/engine.py): run ONE request's bucket-padded
        prompt through a fresh bucket-sized cache, copy the prefix KV
        into slot ``slot`` of the persistent slot-paged cache
        (ops/attention.write_slot_prefix), set the slot's valid length,
        and pick the first generated token from the logits at the TRUE
        last prompt position (pad tokens behind it are causally
        invisible, so bucket padding cannot change the pick). Slot index
        and true length are traced scalars — ONE compiled program per
        bucket serves every slot, length, and arrival pattern.

        Signature of the returned program:
        ``(params, k_slots, v_slots, lengths, ids[1, bucket], slot,
        length, temp, rng) -> (k_slots, v_slots, lengths, first_token)``
        (cache operands donated on TPU)."""
        from deepspeed_tpu.ops.attention import write_slot_prefix

        key = ("slot_pf", bucket_len, num_slots, max_len, do_sample,
               top_k, float(top_p))
        if key not in self._compiled:
            model = self.module
            pick = self._make_pick(do_sample, top_k, float(top_p))

            def prefill(params, k_slots, v_slots, lengths, ids, slot,
                        length, temp, rng):
                cache = model.init_cache(1, bucket_len, dtype=self.dtype)
                logits, cache = model.forward_with_cache(params, ids, cache)
                k_slots, v_slots = write_slot_prefix(
                    k_slots, v_slots, cache["k"], cache["v"], slot)
                lengths = jax.lax.dynamic_update_index_in_dim(
                    lengths, length, slot, 0)
                last = jax.lax.dynamic_index_in_dim(
                    logits, length - 1, 1, keepdims=False)       # [1, V]
                return k_slots, v_slots, lengths, pick(last, temp, rng)[0]

            donate = (1, 2, 3) if jax.default_backend() == "tpu" else ()
            self._compiled[key] = jax.jit(prefill, donate_argnums=donate)
        return self._compiled[key]

    def slot_decode_program(self, num_slots: int, max_len: int, *,
                            do_sample: bool = False, top_k: int = 0,
                            top_p: float = 1.0, pad_token_id: int = 0):
        """Jitted persistent-cache decode step for the continuous-batching
        serving runtime: ONE token for every slot against the slot-paged
        KV cache with a per-slot valid-length vector
        (models/base.cache_positions + ops/attention per-slot masking).
        Inactive slots (``active`` false) keep their length, emit
        ``pad_token_id``, and their masked garbage write is overwritten
        by the next prefill into that slot. Fixed slot count + fixed
        cache shape = exactly one compiled program for the entire decode
        side of the serving loop, regardless of arrival pattern.

        Signature: ``(params, k_slots, v_slots, lengths[B], tokens[B],
        active[B] bool, temp, rng) -> (k_slots, v_slots, lengths,
        next_tokens[B])`` (cache operands donated on TPU)."""
        key = ("slot_dec", num_slots, max_len, do_sample, top_k,
               float(top_p), pad_token_id)
        if key not in self._compiled:
            model = self.module
            pick = self._make_pick(do_sample, top_k, float(top_p))

            def decode(params, k_slots, v_slots, lengths, tokens, active,
                       temp, rng):
                cache = {"k": k_slots, "v": v_slots, "index": lengths}
                logits, cache = model.forward_with_cache(
                    params, tokens[:, None], cache)
                nxt = jnp.where(active, pick(logits[:, -1], temp, rng),
                                pad_token_id)
                lengths = jnp.where(active, lengths + 1, lengths)
                return cache["k"], cache["v"], lengths, nxt

            donate = (1, 2, 3) if jax.default_backend() == "tpu" else ()
            self._compiled[key] = jax.jit(decode, donate_argnums=donate)
        return self._compiled[key]

    def slot_verify_program(self, num_slots: int, max_len: int, k: int, *,
                            do_sample: bool = False, top_k: int = 0,
                            top_p: float = 1.0, pad_token_id: int = 0):
        """Jitted speculative-decoding verify step (ISSUE 4,
        serving/speculative.py): score ``k`` drafted tokens per slot in
        ONE target-model forward over the slot-paged cache and emit each
        slot's accepted prefix plus one bonus/correction token.

        The [B, k+1] block (last committed token + k drafts) runs through
        the SAME ``forward_with_cache`` the decode step uses: per-slot
        positions from the length vector (models/base.cache_positions),
        per-slot-prefix + intra-block-causal masks in
        ops/attention.decode_attention, and a vector-idx block scatter
        writing all k+1 candidate K/V entries
        (ops/attention.write_kv_cache). Rollback of rejected drafts is
        free: the returned length vector advances only over the accepted
        prefix, so rejected cache entries stay dead behind the mask and
        the NEXT verify block overwrites them in place. One compiled
        program per k-bucket — k comes from the engine's fixed bucket
        set, so the jit cache stays pinned after warmup.

        Signature: ``(params, k_slots, v_slots, lengths[B], tokens[B,k+1],
        draft_len[B], active[B] bool, temp, rng) -> (k_slots, v_slots,
        lengths, out_tokens[B,k+1], n_emit[B])``; row b emits
        ``out_tokens[b, :n_emit[b]]`` (cache operands donated on TPU)."""
        from deepspeed_tpu.serving.speculative import speculative_acceptance

        key = ("slot_ver", num_slots, max_len, k, do_sample, top_k,
               float(top_p), pad_token_id)
        if key not in self._compiled:
            model = self.module

            def verify(params, k_slots, v_slots, lengths, tokens,
                       draft_len, active, temp, rng):
                cache = {"k": k_slots, "v": v_slots, "index": lengths}
                logits, cache = model.forward_with_cache(
                    params, tokens, cache)
                out_tokens, n_emit = speculative_acceptance(
                    logits, tokens, draft_len, temp, rng,
                    do_sample=do_sample, top_k=top_k, top_p=float(top_p),
                    pad_token_id=pad_token_id)
                n_emit = jnp.where(active, n_emit, 0)
                out_tokens = jnp.where(active[:, None], out_tokens,
                                       pad_token_id)
                lengths = lengths + n_emit      # n_emit is 0 when inactive
                return (cache["k"], cache["v"], lengths, out_tokens,
                        n_emit)

            donate = (1, 2, 3) if jax.default_backend() == "tpu" else ()
            self._compiled[key] = jax.jit(verify, donate_argnums=donate)
        return self._compiled[key]

    def slot_draft_program(self, window_len: int, num_slots: int, k: int):
        """Jitted greedy drafting for the DRAFT model of a speculative-
        decoding pair (serving/speculative.DraftModelDrafter): re-prefill
        each slot's trailing ``window_len`` history tokens into a fresh
        in-program cache, then roll ``k`` greedy tokens forward —
        returning [B, k] draft proposals in one compiled program.

        Stateless by design: the draft cache lives and dies inside the
        program, so there is no persistent draft KV to roll back when the
        target rejects, and the program's shapes never vary (one program
        per (window, k) pair, both from fixed bucket sets). Right-padded
        windows with a per-slot true length reuse the slot machinery:
        positions/masks come from the per-slot index vector, and each
        decode write lands at ``wlen + j``, overwriting window padding
        before the mask ever exposes it.

        Signature: ``(params, window[B, window_len] int32, wlen[B] int32
        >= 1) -> drafts[B, k] int32`` (greedy argmax; point-mass
        proposals stay lossless under both acceptance modes)."""
        key = ("slot_draft", window_len, num_slots, k)
        if key not in self._compiled:
            model = self.module
            cache_len = window_len + k

            def draft(params, window, wlen):
                cache = model.init_cache(num_slots, cache_len,
                                         dtype=self.dtype)
                zeros = jnp.zeros((num_slots,), jnp.int32)
                logits, cache = model.forward_with_cache(
                    params, window, {"k": cache["k"], "v": cache["v"],
                                     "index": zeros})
                # first draft from each row's TRUE last window position
                tok = jnp.argmax(jnp.take_along_axis(
                    logits, (wlen - 1)[:, None, None], axis=1
                )[:, 0].astype(jnp.float32), axis=-1).astype(jnp.int32)
                out = [tok]
                idx = wlen
                for _ in range(k - 1):
                    logits, cache = model.forward_with_cache(
                        params, tok[:, None],
                        {"k": cache["k"], "v": cache["v"], "index": idx})
                    tok = jnp.argmax(logits[:, -1].astype(jnp.float32),
                                     axis=-1).astype(jnp.int32)
                    out.append(tok)
                    idx = idx + 1
                return jnp.stack(out, axis=1)

            self._compiled[key] = jax.jit(draft)
        return self._compiled[key]

    # --------------------------------------------- block-paged programs
    # (ISSUE 6, serving/kv_blocks.py + serving/radix.py): the slot
    # programs' prefix-sharing analogs. Same zero-recompile contract —
    # the block table is a TRACED int32 operand, never a shape, so one
    # compiled program per (bucket | k-bucket | step kind) serves every
    # block assignment the radix index produces.

    def block_prefill_program(self, bucket_len: int, num_slots: int,
                              max_blocks: int, *, do_sample: bool = False,
                              top_k: int = 0, top_p: float = 1.0,
                              kv_dtype: str = "compute"):
        """Jitted SUFFIX prefill against the block pool: run ONE
        request's bucket-padded UNMATCHED suffix through the pool with
        the slot's [1, MB] table row — the suffix tokens attend over the
        radix-matched prefix blocks already in the pool (start = matched
        length), and their K/V scatter through the table
        (ops/attention.write_kv_blocks). This is where the prefix-cache
        win lands: a matched prefix is never recomputed, and the bucket
        is picked by SUFFIX length, so a 2k-token shared system prompt
        with a 30-token user suffix prefills in the smallest bucket.

        Signature: ``(params, k_pool, v_pool, lengths, ids[1, bucket],
        table_row[1, MB], slot, start, suffix_len, temp, rng) ->
        (k_pool, v_pool, lengths, first_token)`` (pool operands donated
        on TPU). ``start`` is the matched prefix length; the slot's
        length becomes ``start + suffix_len``."""
        key = ("blk_pf", bucket_len, num_slots, max_blocks, do_sample,
               top_k, float(top_p), kv_dtype)
        if key not in self._compiled:
            model = self.module
            pick = self._make_pick(do_sample, top_k, float(top_p))

            def prefill(params, k_pool, v_pool, lengths, ids, table_row,
                        slot, start, length, temp, rng):
                idx = jnp.reshape(jnp.asarray(start, jnp.int32), (1,))
                cache = {"k": k_pool, "v": v_pool, "index": idx,
                         "block_table": table_row}
                logits, cache = model.forward_with_cache(params, ids, cache)
                lengths = jax.lax.dynamic_update_index_in_dim(
                    lengths, start + length, slot, 0)
                last = jax.lax.dynamic_index_in_dim(
                    logits, length - 1, 1, keepdims=False)       # [1, V]
                return (cache["k"], cache["v"], lengths,
                        pick(last, temp, rng)[0])

            donate = (1, 2, 3) if jax.default_backend() == "tpu" else ()
            self._compiled[key] = jax.jit(prefill, donate_argnums=donate)
        return self._compiled[key]

    def block_decode_program(self, num_slots: int, max_blocks: int, *,
                             do_sample: bool = False, top_k: int = 0,
                             top_p: float = 1.0, pad_token_id: int = 0,
                             kv_dtype: str = "compute"):
        """Jitted block-paged decode step: one token for every slot,
        KV addressed through the full [B, MB] block table (single-token
        decode on TPU routes to the fused Pallas block kernel,
        ops/decode_step.fused_block_decode_step). Inactive slots carry
        sentinel tables — their writes land in the pool's garbage row.

        Signature: ``(params, k_pool, v_pool, lengths[B], tables[B, MB],
        tokens[B], active[B] bool, temp, rng) -> (k_pool, v_pool,
        lengths, next_tokens[B])`` (pool operands donated on TPU)."""
        key = ("blk_dec", num_slots, max_blocks, do_sample, top_k,
               float(top_p), pad_token_id, kv_dtype)
        if key not in self._compiled:
            model = self.module
            pick = self._make_pick(do_sample, top_k, float(top_p))

            def decode(params, k_pool, v_pool, lengths, tables, tokens,
                       active, temp, rng):
                cache = {"k": k_pool, "v": v_pool, "index": lengths,
                         "block_table": tables}
                logits, cache = model.forward_with_cache(
                    params, tokens[:, None], cache)
                nxt = jnp.where(active, pick(logits[:, -1], temp, rng),
                                pad_token_id)
                lengths = jnp.where(active, lengths + 1, lengths)
                return cache["k"], cache["v"], lengths, nxt

            donate = (1, 2, 3) if jax.default_backend() == "tpu" else ()
            self._compiled[key] = jax.jit(decode, donate_argnums=donate)
        return self._compiled[key]

    def block_verify_program(self, num_slots: int, max_blocks: int, k: int,
                             *, do_sample: bool = False, top_k: int = 0,
                             top_p: float = 1.0, pad_token_id: int = 0,
                             kv_dtype: str = "compute"):
        """Jitted speculative verify step over the block pool — the
        block-table analog of :meth:`slot_verify_program`. Rollback
        stays free: rejected candidates' K/V stay dead behind the
        per-slot length in the slot's PRIVATE decode blocks (a shared
        prefix block is never written after admit — the radix COW fork
        happens at admit time, before any decode write could touch a
        shared block), and the next verify block overwrites them in
        place through the same table.

        Signature: ``(params, k_pool, v_pool, lengths[B], tables[B, MB],
        tokens[B, k+1], draft_len[B], active[B] bool, temp, rng) ->
        (k_pool, v_pool, lengths, out_tokens[B, k+1], n_emit[B])``."""
        from deepspeed_tpu.serving.speculative import speculative_acceptance

        key = ("blk_ver", num_slots, max_blocks, k, do_sample, top_k,
               float(top_p), pad_token_id, kv_dtype)
        if key not in self._compiled:
            model = self.module

            def verify(params, k_pool, v_pool, lengths, tables, tokens,
                       draft_len, active, temp, rng):
                cache = {"k": k_pool, "v": v_pool, "index": lengths,
                         "block_table": tables}
                logits, cache = model.forward_with_cache(
                    params, tokens, cache)
                out_tokens, n_emit = speculative_acceptance(
                    logits, tokens, draft_len, temp, rng,
                    do_sample=do_sample, top_k=top_k, top_p=float(top_p),
                    pad_token_id=pad_token_id)
                n_emit = jnp.where(active, n_emit, 0)
                out_tokens = jnp.where(active[:, None], out_tokens,
                                       pad_token_id)
                lengths = lengths + n_emit
                return (cache["k"], cache["v"], lengths, out_tokens,
                        n_emit)

            donate = (1, 2, 3) if jax.default_backend() == "tpu" else ()
            self._compiled[key] = jax.jit(verify, donate_argnums=donate)
        return self._compiled[key]

    def block_copy_program(self, num_blocks: int, block_size: int, *,
                           kv_dtype: str = "compute"):
        """Jitted one-block COW copy: duplicate pool block ``src`` into
        ``dst`` across both pools and every layer (the device half of a
        radix copy-on-write fork, serving/radix.PrefixCache.admit —
        issued BEFORE the suffix prefill that partially overwrites the
        fork). ``src``/``dst`` are traced scalars: one compiled program
        serves every fork. Quantized ``{"q", "s"}`` pools (ISSUE 12)
        copy leaf-wise — a fork carries the source block's payload AND
        its per-token scales, so the forked block dequantizes
        bit-identically to the shared original (pinned by tests).

        Signature: ``(k_pool, v_pool, src, dst) -> (k_pool, v_pool)``
        (pool operands donated on TPU)."""
        key = ("blk_copy", num_blocks, block_size, kv_dtype)
        if key not in self._compiled:
            def copy(k_pool, v_pool, src, dst):
                def copy_one(pool):
                    def f(leaf):
                        blk = jax.lax.dynamic_slice_in_dim(leaf, src, 1, 1)
                        return jax.lax.dynamic_update_slice_in_dim(
                            leaf, blk, dst, 1)

                    return jax.tree_util.tree_map(f, pool)

                return copy_one(k_pool), copy_one(v_pool)

            donate = (0, 1) if jax.default_backend() == "tpu" else ()
            self._compiled[key] = jax.jit(copy, donate_argnums=donate)
        return self._compiled[key]

    # ----------------------------------------- SLO-aware serving programs
    # (ISSUE 8, serving/engine.py): chunked prefill against the
    # slot-paged cache, and the device halves of preemption KV
    # swap-out/in for both cache modes. Same zero-recompile contract as
    # every serving program: slot / start / length / block lists are
    # traced DATA, so chunk counts and preemption patterns are invisible
    # to the jit cache.

    def slot_chunk_prefill_program(self, bucket_len: int, num_slots: int,
                                   max_len: int, *, do_sample: bool = False,
                                   top_k: int = 0, top_p: float = 1.0):
        """Jitted mid-prompt CHUNK prefill against the slot-paged cache
        (ISSUE 8): run ONE request's bucket-padded prompt chunk with the
        slot's own cache row — the chunk's queries attend over the
        slot's already-prefilled prefix (``start`` tokens, a traced
        scalar) plus the chunk's own causal block, and its K/V scatter
        in at ``start .. start+length`` through the per-slot vector
        write path (ops/attention.write_kv_cache). The slot's row pair
        is sliced out (ops/attention.extract_slot_kv), stepped as a
        batch-1 cache, and written back. Slot/start/length are all
        traced, so ONE compiled program per bucket serves every chunk of
        every prompt — chunk COUNT is data, which is what lets long
        prompts prefill in fixed-bucket-sized pieces interleaved with
        decode steps without a single recompile (the block-paged mode
        needs no new program: block_prefill_program's ``start`` operand
        already is the chunk offset).

        The returned token is the pick at the chunk's TRUE last
        position — meaningful only on the FINAL chunk (the engine
        discards it for intermediate chunks; the first generated token
        of a chunked prompt exists only after the last chunk, which is
        also when TTFT is stamped).

        Signature: ``(params, k_slots, v_slots, lengths, ids[1, bucket],
        slot, start, length, temp, rng) -> (k_slots, v_slots, lengths,
        token)`` (cache operands donated on TPU)."""
        from deepspeed_tpu.ops.attention import (extract_slot_kv,
                                                 insert_slot_kv)

        key = ("slot_chunk_pf", bucket_len, num_slots, max_len, do_sample,
               top_k, float(top_p))
        if key not in self._compiled:
            model = self.module
            pick = self._make_pick(do_sample, top_k, float(top_p))

            def chunk(params, k_slots, v_slots, lengths, ids, slot, start,
                      length, temp, rng):
                k_row, v_row = extract_slot_kv(k_slots, v_slots, slot)
                idx = jnp.reshape(jnp.asarray(start, jnp.int32), (1,))
                cache = {"k": k_row, "v": v_row, "index": idx}
                logits, cache = model.forward_with_cache(params, ids, cache)
                k_slots, v_slots = insert_slot_kv(
                    k_slots, v_slots, cache["k"], cache["v"], slot)
                lengths = jax.lax.dynamic_update_index_in_dim(
                    lengths, start + length, slot, 0)
                last = jax.lax.dynamic_index_in_dim(
                    logits, length - 1, 1, keepdims=False)       # [1, V]
                return k_slots, v_slots, lengths, pick(last, temp, rng)[0]

            donate = (1, 2, 3) if jax.default_backend() == "tpu" else ()
            self._compiled[key] = jax.jit(chunk, donate_argnums=donate)
        return self._compiled[key]

    def slot_swap_out_program(self, num_slots: int, max_len: int):
        """Jitted preemption swap-OUT for the slot-paged cache: slice
        slot ``slot``'s full row pair out (the engine device_gets it
        into the host swap buffer). Read-only — the cache operands are
        NOT donated, the caller keeps using them.

        Signature: ``(k_slots, v_slots, slot) -> (k_row, v_row)`` with
        rows ``[L, 1, Hkv, S(/pair), Dh(*pair)]``."""
        from deepspeed_tpu.ops.attention import extract_slot_kv

        key = ("slot_swap_out", num_slots, max_len)
        if key not in self._compiled:
            self._compiled[key] = jax.jit(
                lambda k, v, slot: extract_slot_kv(k, v, slot))
        return self._compiled[key]

    def slot_swap_in_program(self, num_slots: int, max_len: int):
        """Jitted preemption swap-IN for the slot-paged cache: write a
        host-uploaded row pair back into slot ``slot`` and restore its
        valid length — after this the slot decodes exactly as if it had
        never been preempted (bit-identical, pinned by tests).

        Signature: ``(k_slots, v_slots, k_row, v_row, lengths, slot,
        length) -> (k_slots, v_slots, lengths)`` (cache operands donated
        on TPU)."""
        from deepspeed_tpu.ops.attention import insert_slot_kv

        key = ("slot_swap_in", num_slots, max_len)
        if key not in self._compiled:
            def swap_in(k_slots, v_slots, k_row, v_row, lengths, slot,
                        length):
                k_slots, v_slots = insert_slot_kv(
                    k_slots, v_slots, k_row, v_row, slot)
                lengths = jax.lax.dynamic_update_index_in_dim(
                    lengths, jnp.asarray(length, jnp.int32), slot, 0)
                return k_slots, v_slots, lengths

            donate = (0, 1, 4) if jax.default_backend() == "tpu" else ()
            self._compiled[key] = jax.jit(swap_in, donate_argnums=donate)
        return self._compiled[key]

    def block_swap_out_program(self, num_blocks: int, max_blocks: int, *,
                               kv_dtype: str = "compute"):
        """Jitted preemption swap-OUT for the block pool: gather the
        contents of one slot's table-named blocks (sentinel entries
        gather the garbage row — the engine trims to the blocks the
        request actually used before parking them on host). Read-only.

        Signature: ``(k_pool, v_pool, table[MB]) -> (k_blocks, v_blocks)``
        with blocks ``[L, MB, Hkv, bs(/pair), Dh(*pair)]``."""
        from deepspeed_tpu.ops.attention import gather_pool_blocks

        key = ("blk_swap_out", num_blocks, max_blocks, kv_dtype)
        if key not in self._compiled:
            self._compiled[key] = jax.jit(
                lambda k, v, table: gather_pool_blocks(k, v, table))
        return self._compiled[key]

    def block_swap_in_program(self, num_blocks: int, max_blocks: int, *,
                              kv_dtype: str = "compute"):
        """Jitted preemption swap-IN for the block pool: scatter
        host-uploaded block contents into the pool rows named by
        ``dst`` and restore the slot's valid length. Entries the
        restore skips (radix re-matched shared prefix blocks, allocated
        but never-written tail blocks) name the garbage row, so the
        program's shapes never vary with how much actually uploads.

        Signature: ``(k_pool, v_pool, k_blocks, v_blocks, dst[MB],
        lengths, slot, length) -> (k_pool, v_pool, lengths)`` (pool
        operands donated on TPU)."""
        from deepspeed_tpu.ops.attention import scatter_pool_blocks

        key = ("blk_swap_in", num_blocks, max_blocks, kv_dtype)
        if key not in self._compiled:
            def swap_in(k_pool, v_pool, k_blocks, v_blocks, dst, lengths,
                        slot, length):
                k_pool, v_pool = scatter_pool_blocks(
                    k_pool, v_pool, k_blocks, v_blocks, dst)
                lengths = jax.lax.dynamic_update_index_in_dim(
                    lengths, jnp.asarray(length, jnp.int32), slot, 0)
                return k_pool, v_pool, lengths

            donate = (0, 1, 5) if jax.default_backend() == "tpu" else ()
            self._compiled[key] = jax.jit(swap_in, donate_argnums=donate)
        return self._compiled[key]

    # ------------------------------------------------------------- utilities
    def compiled_programs(self, batch: int, prompt_len: int, max_new: int,
                          *, do_sample: bool = False, top_k: int = 0,
                          top_p: float = 1.0):
        """The (prefill, decode) jitted programs generate() uses for this
        shape — built on demand. For benches that time the programs
        directly (PROFILE_DECODE.md methodology) without reconstructing
        the private cache keys. Greedy/eos-free only (decode is the scan
        program; the eos path's while-loop program is not exposed).

        NOTE: the decode program DONATES its cache argument
        (donate_argnums=(2,)) — a second dec() call on the same cache
        hits a deleted-buffer error; run the prefill program again per
        decode invocation, as bench.py does."""
        self._build_generate(batch, prompt_len, max_new,
                             do_sample=do_sample, top_k=top_k,
                             top_p=float(top_p), eos_token_id=None,
                             pad_token_id=0)
        total = prompt_len + max_new
        pf = self._compiled[("pf", batch, prompt_len, total, do_sample,
                             top_k, float(top_p))]
        dec = self._compiled.get(("dec", batch, total, max_new, do_sample,
                                  top_k, float(top_p)))
        return pf, dec

    def jit_cache_sizes(self) -> Dict[str, int]:
        """Compiled-entry count per jitted program this engine has built
        (recompile accounting for telemetry: a program whose count keeps
        growing is recompiling — some argument's shape/dtype varies).
        Composed host-side drivers (``generate``'s gen closures) carry no
        cache and are skipped."""
        out: Dict[str, int] = {}
        for key, fn in self._compiled.items():
            size_fn = getattr(fn, "_cache_size", None)
            if size_fn is None:
                continue
            try:
                out[str(key)] = int(size_fn())
            except Exception:
                continue
        return out

    @property
    def config(self):
        return self._config

    def eval(self):  # torch-API compat no-op
        return self

    def to(self, *a, **k):  # torch-API compat no-op
        return self


def _is_torch_module(model) -> bool:
    try:
        import torch.nn as nn

        return isinstance(model, nn.Module)
    except Exception:
        return False
