"""Per-architecture HF weight-mapping policies — the module_inject analog.

The reference surgically replaces HF nn.Modules with CUDA-kernel containers
(``deepspeed/module_inject/replace_module.py:279 replace_transformer_layer``,
per-arch policies in ``module_inject/containers/``). On TPU the model
implementations are this framework's own JAX models, so "injection" becomes a
one-time weight conversion: torch state_dict → params pytree. The policy
registry keyed by HF architecture class name mirrors the reference's
``replace_policies`` list (module_inject/replace_policy.py).

Conventions handled:
  * HF GPT-2 uses Conv1D ([in, out] weights) — matches our [d_in, d_out]
    einsum layout directly.
  * HF LLaMA Linear stores [out, in] — transposed on load.
  * HF LLaMA RoPE uses the half-split ("rotate_half") convention; our rotary
    op (ops/rotary.py) is interleaved (GPT-NeoX). q/k projection columns are
    permuted per-head on load so the two are numerically identical.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Tuple

import numpy as np

_POLICIES: Dict[str, Callable] = {}


def register_policy(arch: str):
    def deco(fn):
        _POLICIES[arch] = fn
        return fn
    return deco


def convert_hf_model(hf_model, compute_dtype=None) -> Tuple[Any, Any]:
    """HF torch model → (ModelSpec, params). Raises for unknown archs,
    listing supported ones (reference raises when no policy matches)."""
    arch = type(hf_model).__name__
    if arch not in _POLICIES:
        raise ValueError(
            f"no inference policy for HF architecture {arch!r}; "
            f"supported: {sorted(_POLICIES)}")
    import jax.numpy as jnp

    dtype = compute_dtype or jnp.bfloat16
    return _POLICIES[arch](hf_model, dtype)


def _np(t) -> np.ndarray:
    return t.detach().cpu().float().numpy()


def _interleave_rope_columns(w: np.ndarray, num_heads: int) -> np.ndarray:
    """Permute projection output columns from HF half-split RoPE layout to
    interleaved: per head, column order [0, dh/2, 1, dh/2+1, ...]."""
    d_in, d_out = w.shape
    dh = d_out // num_heads
    perm = np.empty(dh, dtype=np.int64)
    perm[0::2] = np.arange(dh // 2)
    perm[1::2] = np.arange(dh // 2) + dh // 2
    w = w.reshape(d_in, num_heads, dh)[:, :, perm]
    return w.reshape(d_in, d_out)


@register_policy("GPT2LMHeadModel")
def gpt2_policy(hf_model, dtype):
    """HF GPT2LMHeadModel → GPT2Model (reference containers/gpt2.py GPT2
    policy + HFGPT2LayerPolicy)."""
    import jax.numpy as jnp

    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model

    hf_cfg = hf_model.config
    cfg = GPT2Config(
        vocab_size=hf_cfg.vocab_size, max_seq_len=hf_cfg.n_positions,
        num_layers=hf_cfg.n_layer, hidden_size=hf_cfg.n_embd,
        num_heads=hf_cfg.n_head, eps=hf_cfg.layer_norm_epsilon,
        tie_embeddings=True)
    model = GPT2Model(cfg, compute_dtype=dtype)
    sd = hf_model.state_dict()

    def stack(fmt, post=lambda x: x):
        return jnp.asarray(np.stack([post(_np(sd[fmt.format(i=i)]))
                                     for i in range(cfg.num_layers)]))

    params = {
        "wte": jnp.asarray(_np(sd["transformer.wte.weight"])),
        "wpe": jnp.asarray(_np(sd["transformer.wpe.weight"])),
        "blocks": {
            "ln1_scale": stack("transformer.h.{i}.ln_1.weight"),
            "ln1_bias": stack("transformer.h.{i}.ln_1.bias"),
            "qkv_w": stack("transformer.h.{i}.attn.c_attn.weight"),   # Conv1D [in,out]
            "qkv_b": stack("transformer.h.{i}.attn.c_attn.bias"),
            "attn_out_w": stack("transformer.h.{i}.attn.c_proj.weight"),
            "attn_out_b": stack("transformer.h.{i}.attn.c_proj.bias"),
            "ln2_scale": stack("transformer.h.{i}.ln_2.weight"),
            "ln2_bias": stack("transformer.h.{i}.ln_2.bias"),
            "mlp_fc_w": stack("transformer.h.{i}.mlp.c_fc.weight"),
            "mlp_fc_b": stack("transformer.h.{i}.mlp.c_fc.bias"),
            "mlp_out_w": stack("transformer.h.{i}.mlp.c_proj.weight"),
            "mlp_out_b": stack("transformer.h.{i}.mlp.c_proj.bias"),
        },
        "ln_f_scale": jnp.asarray(_np(sd["transformer.ln_f.weight"])),
        "ln_f_bias": jnp.asarray(_np(sd["transformer.ln_f.bias"])),
    }
    return model, params


@register_policy("LlamaForCausalLM")
def llama_policy(hf_model, dtype):
    """HF LlamaForCausalLM → LlamaModel. The reference snapshot has no LLaMA
    container — serving went through AutoTP (module_inject/auto_tp.py:84);
    here LLaMA serving is first-class."""
    import jax.numpy as jnp

    from deepspeed_tpu.models.llama import LlamaConfig, LlamaModel

    hf_cfg = hf_model.config
    cfg = LlamaConfig(
        vocab_size=hf_cfg.vocab_size,
        max_seq_len=hf_cfg.max_position_embeddings,
        num_layers=hf_cfg.num_hidden_layers,
        hidden_size=hf_cfg.hidden_size,
        num_heads=hf_cfg.num_attention_heads,
        num_kv_heads=getattr(hf_cfg, "num_key_value_heads",
                             hf_cfg.num_attention_heads),
        intermediate_size=hf_cfg.intermediate_size,
        rope_theta=getattr(hf_cfg, "rope_theta", 10000.0),
        eps=hf_cfg.rms_norm_eps)
    model = LlamaModel(cfg, compute_dtype=dtype)
    sd = hf_model.state_dict()

    def stack(fmt, post=lambda x: x):
        return jnp.asarray(np.stack([post(_np(sd[fmt.format(i=i)]))
                                     for i in range(cfg.num_layers)]))

    def lin(x):          # HF Linear [out, in] → [in, out]
        return x.T

    def rope_q(x):
        return _interleave_rope_columns(lin(x), cfg.num_heads)

    def rope_k(x):
        return _interleave_rope_columns(lin(x), cfg.num_kv_heads)

    params = {
        "embed": jnp.asarray(_np(sd["model.embed_tokens.weight"])),
        "blocks": {
            "attn_norm": stack("model.layers.{i}.input_layernorm.weight"),
            "wq": stack("model.layers.{i}.self_attn.q_proj.weight", rope_q),
            "wk": stack("model.layers.{i}.self_attn.k_proj.weight", rope_k),
            "wv": stack("model.layers.{i}.self_attn.v_proj.weight", lin),
            "wo": stack("model.layers.{i}.self_attn.o_proj.weight", lin),
            "mlp_norm": stack("model.layers.{i}.post_attention_layernorm.weight"),
            "w_gate": stack("model.layers.{i}.mlp.gate_proj.weight", lin),
            "w_up": stack("model.layers.{i}.mlp.up_proj.weight", lin),
            "w_down": stack("model.layers.{i}.mlp.down_proj.weight", lin),
        },
        "final_norm": jnp.asarray(_np(sd["model.norm.weight"])),
        "lm_head": jnp.asarray(
            _np(sd.get("lm_head.weight", sd["model.embed_tokens.weight"])).T),
    }
    return model, params
