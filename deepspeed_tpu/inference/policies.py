"""Per-architecture HF weight-mapping policies — the module_inject analog.

The reference surgically replaces HF nn.Modules with CUDA-kernel containers
(``deepspeed/module_inject/replace_module.py:279 replace_transformer_layer``,
per-arch policies in ``module_inject/containers/``). On TPU the model
implementations are this framework's own JAX models, so "injection" becomes a
one-time weight conversion: torch state_dict → params pytree. The policy
registry keyed by HF architecture class name mirrors the reference's
``replace_policies`` list (module_inject/replace_policy.py).

Conventions handled:
  * HF GPT-2 uses Conv1D ([in, out] weights) — matches our [d_in, d_out]
    einsum layout directly.
  * HF LLaMA Linear stores [out, in] — transposed on load.
  * HF LLaMA RoPE uses the half-split ("rotate_half") convention; our rotary
    op (ops/rotary.py) is interleaved (GPT-NeoX). q/k projection columns are
    permuted per-head on load so the two are numerically identical.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Tuple

import numpy as np

_POLICIES: Dict[str, Callable] = {}


def register_policy(arch: str):
    def deco(fn):
        _POLICIES[arch] = fn
        return fn
    return deco


def convert_hf_model(hf_model, compute_dtype=None) -> Tuple[Any, Any]:
    """HF torch model → (ModelSpec, params). Raises for unknown archs,
    listing supported ones (reference raises when no policy matches)."""
    arch = type(hf_model).__name__
    if arch not in _POLICIES:
        raise ValueError(
            f"no inference policy for HF architecture {arch!r}; "
            f"supported: {sorted(_POLICIES)}")
    import jax.numpy as jnp

    dtype = compute_dtype or jnp.bfloat16
    return _POLICIES[arch](hf_model, dtype)


def _np(t) -> np.ndarray:
    return t.detach().cpu().float().numpy()


def _interleave_rope_columns(w: np.ndarray, num_heads: int,
                             rotary_dim: int = 0) -> np.ndarray:
    """Permute projection output columns from HF half-split (rotate_half)
    RoPE layout to interleaved: per head, [0, rd/2, 1, rd/2+1, ...].
    ``rotary_dim`` limits the permutation to each head's rotary slice
    (GPT-NeoX partial rotary); 0 = whole head."""
    d_in, d_out = w.shape
    dh = d_out // num_heads
    rd = rotary_dim or dh
    perm = np.arange(dh)
    perm[0:rd:2] = np.arange(rd // 2)
    perm[1:rd:2] = np.arange(rd // 2) + rd // 2
    w = w.reshape(d_in, num_heads, dh)[:, :, perm]
    return w.reshape(d_in, d_out)


def _dense_blocks(sd, num_layers, fmt_map, post_map=None):
    """Stack per-layer tensors into the scanned-blocks layout."""
    import jax.numpy as jnp

    post_map = post_map or {}
    out = {}
    for name, fmt in fmt_map.items():
        post = post_map.get(name, lambda x: x)
        out[name] = jnp.asarray(np.stack(
            [post(_np(sd[fmt.format(i=i)])) for i in range(num_layers)]))
    return out


def _fuse_headwise_qkv(w: np.ndarray, num_heads: int) -> np.ndarray:
    """HF BLOOM/GPT-NeoX fused qkv rows are laid out [h, 3, dh]; convert to
    [d_in, 3d_out] with output columns ordered q(all heads), k, v."""
    three_d, d_in = w.shape
    dh = three_d // (3 * num_heads)
    w = w.reshape(num_heads, 3, dh, d_in).transpose(1, 0, 2, 3)
    return w.reshape(three_d, d_in).T


def _fuse_headwise_qkv_bias(b: np.ndarray, num_heads: int) -> np.ndarray:
    dh = b.shape[0] // (3 * num_heads)
    return b.reshape(num_heads, 3, dh).transpose(1, 0, 2).reshape(-1)


@register_policy("GPT2LMHeadModel")
def gpt2_policy(hf_model, dtype):
    """HF GPT2LMHeadModel → GPT2Model (reference containers/gpt2.py GPT2
    policy + HFGPT2LayerPolicy)."""
    import jax.numpy as jnp

    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model

    hf_cfg = hf_model.config
    cfg = GPT2Config(
        vocab_size=hf_cfg.vocab_size, max_seq_len=hf_cfg.n_positions,
        num_layers=hf_cfg.n_layer, hidden_size=hf_cfg.n_embd,
        num_heads=hf_cfg.n_head, eps=hf_cfg.layer_norm_epsilon,
        tie_embeddings=True)
    model = GPT2Model(cfg, compute_dtype=dtype)
    sd = hf_model.state_dict()

    def stack(fmt, post=lambda x: x):
        return jnp.asarray(np.stack([post(_np(sd[fmt.format(i=i)]))
                                     for i in range(cfg.num_layers)]))

    params = {
        "wte": jnp.asarray(_np(sd["transformer.wte.weight"])),
        "wpe": jnp.asarray(_np(sd["transformer.wpe.weight"])),
        "blocks": {
            "ln1_scale": stack("transformer.h.{i}.ln_1.weight"),
            "ln1_bias": stack("transformer.h.{i}.ln_1.bias"),
            "qkv_w": stack("transformer.h.{i}.attn.c_attn.weight"),   # Conv1D [in,out]
            "qkv_b": stack("transformer.h.{i}.attn.c_attn.bias"),
            "attn_out_w": stack("transformer.h.{i}.attn.c_proj.weight"),
            "attn_out_b": stack("transformer.h.{i}.attn.c_proj.bias"),
            "ln2_scale": stack("transformer.h.{i}.ln_2.weight"),
            "ln2_bias": stack("transformer.h.{i}.ln_2.bias"),
            "mlp_fc_w": stack("transformer.h.{i}.mlp.c_fc.weight"),
            "mlp_fc_b": stack("transformer.h.{i}.mlp.c_fc.bias"),
            "mlp_out_w": stack("transformer.h.{i}.mlp.c_proj.weight"),
            "mlp_out_b": stack("transformer.h.{i}.mlp.c_proj.bias"),
        },
        "ln_f_scale": jnp.asarray(_np(sd["transformer.ln_f.weight"])),
        "ln_f_bias": jnp.asarray(_np(sd["transformer.ln_f.bias"])),
    }
    return model, params


@register_policy("LlamaForCausalLM")
def llama_policy(hf_model, dtype):
    """HF LlamaForCausalLM → LlamaModel. The reference snapshot has no LLaMA
    container — serving went through AutoTP (module_inject/auto_tp.py:84);
    here LLaMA serving is first-class."""
    import jax.numpy as jnp

    from deepspeed_tpu.models.llama import LlamaConfig, LlamaModel

    hf_cfg = hf_model.config
    cfg = LlamaConfig(
        vocab_size=hf_cfg.vocab_size,
        max_seq_len=hf_cfg.max_position_embeddings,
        num_layers=hf_cfg.num_hidden_layers,
        hidden_size=hf_cfg.hidden_size,
        num_heads=hf_cfg.num_attention_heads,
        num_kv_heads=getattr(hf_cfg, "num_key_value_heads",
                             hf_cfg.num_attention_heads),
        intermediate_size=hf_cfg.intermediate_size,
        rope_theta=getattr(hf_cfg, "rope_theta", 10000.0),
        eps=hf_cfg.rms_norm_eps)
    model = LlamaModel(cfg, compute_dtype=dtype)
    sd = hf_model.state_dict()

    def stack(fmt, post=lambda x: x):
        return jnp.asarray(np.stack([post(_np(sd[fmt.format(i=i)]))
                                     for i in range(cfg.num_layers)]))

    def lin(x):          # HF Linear [out, in] → [in, out]
        return x.T

    def rope_q(x):
        return _interleave_rope_columns(lin(x), cfg.num_heads)

    def rope_k(x):
        return _interleave_rope_columns(lin(x), cfg.num_kv_heads)

    params = {
        "embed": jnp.asarray(_np(sd["model.embed_tokens.weight"])),
        "blocks": {
            "attn_norm": stack("model.layers.{i}.input_layernorm.weight"),
            "wq": stack("model.layers.{i}.self_attn.q_proj.weight", rope_q),
            "wk": stack("model.layers.{i}.self_attn.k_proj.weight", rope_k),
            "wv": stack("model.layers.{i}.self_attn.v_proj.weight", lin),
            "wo": stack("model.layers.{i}.self_attn.o_proj.weight", lin),
            "mlp_norm": stack("model.layers.{i}.post_attention_layernorm.weight"),
            "w_gate": stack("model.layers.{i}.mlp.gate_proj.weight", lin),
            "w_up": stack("model.layers.{i}.mlp.up_proj.weight", lin),
            "w_down": stack("model.layers.{i}.mlp.down_proj.weight", lin),
        },
        "final_norm": jnp.asarray(_np(sd["model.norm.weight"])),
        "lm_head": jnp.asarray(
            _np(sd.get("lm_head.weight", sd["model.embed_tokens.weight"])).T),
    }
    return model, params


def _lin(x: np.ndarray) -> np.ndarray:
    """HF Linear [out, in] → [in, out]."""
    return x.T


@register_policy("OPTForCausalLM")
def opt_policy(hf_model, dtype):
    """HF OPTForCausalLM → DecoderModel.opt (reference
    module_inject/containers/opt.py HFOPTLayerPolicy)."""
    import jax.numpy as jnp

    from deepspeed_tpu.models.transformer import DecoderConfig, DecoderModel

    hc = hf_model.config
    sd = hf_model.state_dict()
    p = "model.decoder."
    we_dim = getattr(hc, "word_embed_proj_dim", hc.hidden_size)
    cfg = DecoderConfig.opt(
        vocab_size=hc.vocab_size, max_seq_len=hc.max_position_embeddings,
        num_layers=hc.num_hidden_layers, hidden_size=hc.hidden_size,
        num_heads=hc.num_attention_heads, mlp_dim=hc.ffn_dim,
        # opt-350m family: post-LN blocks, projected embeddings, no final LN
        post_ln=not getattr(hc, "do_layer_norm_before", True),
        final_ln=f"{p}final_layer_norm.weight" in sd,
        word_embed_dim=we_dim if we_dim != hc.hidden_size else 0)
    model = DecoderModel(cfg, compute_dtype=dtype)
    L = cfg.num_layers

    def qkv(i):
        return np.concatenate(
            [_lin(_np(sd[f"{p}layers.{i}.self_attn.{x}_proj.weight"]))
             for x in ("q", "k", "v")], axis=1)

    def qkv_b(i):
        return np.concatenate(
            [_np(sd[f"{p}layers.{i}.self_attn.{x}_proj.bias"])
             for x in ("q", "k", "v")])

    blocks = _dense_blocks(sd, L, {
        "ln1_scale": p + "layers.{i}.self_attn_layer_norm.weight",
        "ln1_bias": p + "layers.{i}.self_attn_layer_norm.bias",
        "attn_out_w": p + "layers.{i}.self_attn.out_proj.weight",
        "attn_out_b": p + "layers.{i}.self_attn.out_proj.bias",
        "ln2_scale": p + "layers.{i}.final_layer_norm.weight",
        "ln2_bias": p + "layers.{i}.final_layer_norm.bias",
        "mlp_fc_w": p + "layers.{i}.fc1.weight",
        "mlp_fc_b": p + "layers.{i}.fc1.bias",
        "mlp_out_w": p + "layers.{i}.fc2.weight",
        "mlp_out_b": p + "layers.{i}.fc2.bias",
    }, post_map={"attn_out_w": _lin, "mlp_fc_w": _lin, "mlp_out_w": _lin})
    blocks["qkv_w"] = jnp.asarray(np.stack([qkv(i) for i in range(L)]))
    blocks["qkv_b"] = jnp.asarray(np.stack([qkv_b(i) for i in range(L)]))
    params = {
        "wte": jnp.asarray(_np(sd[p + "embed_tokens.weight"])),
        "wpe": jnp.asarray(_np(sd[p + "embed_positions.weight"])),
        "blocks": blocks,
    }
    if cfg.final_ln:
        params["ln_f_scale"] = jnp.asarray(_np(sd[p + "final_layer_norm.weight"]))
        params["ln_f_bias"] = jnp.asarray(_np(sd[p + "final_layer_norm.bias"]))
    if cfg.word_embed_dim:
        params["project_in"] = jnp.asarray(_lin(_np(sd[p + "project_in.weight"])))
        params["project_out"] = jnp.asarray(_lin(_np(sd[p + "project_out.weight"])))
    return model, params


@register_policy("BloomForCausalLM")
def bloom_policy(hf_model, dtype):
    """HF BloomForCausalLM → DecoderModel.bloom (reference
    module_inject/containers/bloom.py BLOOMLayerPolicy): ALiBi attention,
    embedding LayerNorm, head-interleaved fused qkv de-interleaved on load."""
    import jax.numpy as jnp

    from deepspeed_tpu.models.transformer import DecoderConfig, DecoderModel

    hc = hf_model.config
    cfg = DecoderConfig.bloom(
        vocab_size=hc.vocab_size,
        max_seq_len=getattr(hc, "seq_length", 2048),
        num_layers=hc.n_layer, hidden_size=hc.hidden_size,
        num_heads=hc.n_head, mlp_dim=4 * hc.hidden_size,
        eps=hc.layer_norm_epsilon)
    model = DecoderModel(cfg, compute_dtype=dtype)
    sd = hf_model.state_dict()
    p = "transformer."
    L, H = cfg.num_layers, cfg.num_heads

    blocks = _dense_blocks(sd, L, {
        "ln1_scale": p + "h.{i}.input_layernorm.weight",
        "ln1_bias": p + "h.{i}.input_layernorm.bias",
        "attn_out_w": p + "h.{i}.self_attention.dense.weight",
        "attn_out_b": p + "h.{i}.self_attention.dense.bias",
        "ln2_scale": p + "h.{i}.post_attention_layernorm.weight",
        "ln2_bias": p + "h.{i}.post_attention_layernorm.bias",
        "mlp_fc_w": p + "h.{i}.mlp.dense_h_to_4h.weight",
        "mlp_fc_b": p + "h.{i}.mlp.dense_h_to_4h.bias",
        "mlp_out_w": p + "h.{i}.mlp.dense_4h_to_h.weight",
        "mlp_out_b": p + "h.{i}.mlp.dense_4h_to_h.bias",
    }, post_map={"attn_out_w": _lin, "mlp_fc_w": _lin, "mlp_out_w": _lin})
    blocks["qkv_w"] = jnp.asarray(np.stack(
        [_fuse_headwise_qkv(
            _np(sd[f"{p}h.{i}.self_attention.query_key_value.weight"]), H)
         for i in range(L)]))
    blocks["qkv_b"] = jnp.asarray(np.stack(
        [_fuse_headwise_qkv_bias(
            _np(sd[f"{p}h.{i}.self_attention.query_key_value.bias"]), H)
         for i in range(L)]))
    params = {
        "wte": jnp.asarray(_np(sd[p + "word_embeddings.weight"])),
        "emb_ln_scale": jnp.asarray(
            _np(sd[p + "word_embeddings_layernorm.weight"])),
        "emb_ln_bias": jnp.asarray(
            _np(sd[p + "word_embeddings_layernorm.bias"])),
        "blocks": blocks,
        "ln_f_scale": jnp.asarray(_np(sd[p + "ln_f.weight"])),
        "ln_f_bias": jnp.asarray(_np(sd[p + "ln_f.bias"])),
    }
    return model, params


@register_policy("GPTNeoXForCausalLM")
def gpt_neox_policy(hf_model, dtype):
    """HF GPTNeoXForCausalLM → DecoderModel.gpt_neox (reference
    module_inject/containers/gptneox.py): parallel residual, partial rotary
    (rotate_half checkpoint → interleaved columns on load)."""
    import jax.numpy as jnp

    from deepspeed_tpu.models.transformer import DecoderConfig, DecoderModel

    hc = hf_model.config
    head_dim = hc.hidden_size // hc.num_attention_heads
    rotary_dim = int(head_dim * hc.rotary_pct)
    cfg = DecoderConfig.gpt_neox(
        vocab_size=hc.vocab_size, max_seq_len=hc.max_position_embeddings,
        num_layers=hc.num_hidden_layers, hidden_size=hc.hidden_size,
        num_heads=hc.num_attention_heads, mlp_dim=hc.intermediate_size,
        rotary_dim=rotary_dim, eps=hc.layer_norm_eps,
        parallel_residual=getattr(hc, "use_parallel_residual", True),
        rope_theta=float(getattr(hc, "rotary_emb_base", 10000.0)))
    model = DecoderModel(cfg, compute_dtype=dtype)
    sd = hf_model.state_dict()
    p = "gpt_neox."
    L, H = cfg.num_layers, cfg.num_heads

    def qkv(i):
        w = _fuse_headwise_qkv(
            _np(sd[f"{p}layers.{i}.attention.query_key_value.weight"]), H)
        d = cfg.hidden_size
        # q and k columns carry rotary → de-rotate_half their rotary slice
        q = _interleave_rope_columns(w[:, :d], H, rotary_dim)
        k = _interleave_rope_columns(w[:, d:2 * d], H, rotary_dim)
        return np.concatenate([q, k, w[:, 2 * d:]], axis=1)

    def qkv_b(i):
        b = _fuse_headwise_qkv_bias(
            _np(sd[f"{p}layers.{i}.attention.query_key_value.bias"]), H)
        d = cfg.hidden_size
        q = _interleave_rope_columns(b[None, :d], H, rotary_dim)[0]
        k = _interleave_rope_columns(b[None, d:2 * d], H, rotary_dim)[0]
        return np.concatenate([q, k, b[2 * d:]])

    blocks = _dense_blocks(sd, L, {
        "ln1_scale": p + "layers.{i}.input_layernorm.weight",
        "ln1_bias": p + "layers.{i}.input_layernorm.bias",
        "ln2_scale": p + "layers.{i}.post_attention_layernorm.weight",
        "ln2_bias": p + "layers.{i}.post_attention_layernorm.bias",
        "attn_out_w": p + "layers.{i}.attention.dense.weight",
        "attn_out_b": p + "layers.{i}.attention.dense.bias",
        "mlp_fc_w": p + "layers.{i}.mlp.dense_h_to_4h.weight",
        "mlp_fc_b": p + "layers.{i}.mlp.dense_h_to_4h.bias",
        "mlp_out_w": p + "layers.{i}.mlp.dense_4h_to_h.weight",
        "mlp_out_b": p + "layers.{i}.mlp.dense_4h_to_h.bias",
    }, post_map={"attn_out_w": _lin, "mlp_fc_w": _lin, "mlp_out_w": _lin})
    blocks["qkv_w"] = jnp.asarray(np.stack([qkv(i) for i in range(L)]))
    blocks["qkv_b"] = jnp.asarray(np.stack([qkv_b(i) for i in range(L)]))
    params = {
        "wte": jnp.asarray(_np(sd[p + "embed_in.weight"])),
        "blocks": blocks,
        "ln_f_scale": jnp.asarray(_np(sd[p + "final_layer_norm.weight"])),
        "ln_f_bias": jnp.asarray(_np(sd[p + "final_layer_norm.bias"])),
        "lm_head": jnp.asarray(_lin(_np(sd["embed_out.weight"]))),
    }
    return model, params


@register_policy("GPTJForCausalLM")
def gptj_policy(hf_model, dtype):
    """HF GPTJForCausalLM → DecoderModel.gptj (reference
    module_inject/containers/gptj.py): parallel residual with single LN,
    partial interleaved rotary (native convention — no permutation)."""
    import jax.numpy as jnp

    from deepspeed_tpu.models.transformer import DecoderConfig, DecoderModel

    hc = hf_model.config
    cfg = DecoderConfig.gptj(
        vocab_size=hc.vocab_size, max_seq_len=hc.n_positions,
        num_layers=hc.n_layer, hidden_size=hc.n_embd,
        num_heads=hc.n_head, mlp_dim=4 * hc.n_embd,
        rotary_dim=hc.rotary_dim, eps=hc.layer_norm_epsilon)
    model = DecoderModel(cfg, compute_dtype=dtype)
    sd = hf_model.state_dict()
    p = "transformer."
    L = cfg.num_layers
    d = cfg.hidden_size

    def qkv(i):
        return np.concatenate(
            [_lin(_np(sd[f"{p}h.{i}.attn.{x}_proj.weight"]))
             for x in ("q", "k", "v")], axis=1)

    blocks = _dense_blocks(sd, L, {
        "ln1_scale": p + "h.{i}.ln_1.weight",
        "ln1_bias": p + "h.{i}.ln_1.bias",
        "attn_out_w": p + "h.{i}.attn.out_proj.weight",
        "mlp_fc_w": p + "h.{i}.mlp.fc_in.weight",
        "mlp_fc_b": p + "h.{i}.mlp.fc_in.bias",
        "mlp_out_w": p + "h.{i}.mlp.fc_out.weight",
        "mlp_out_b": p + "h.{i}.mlp.fc_out.bias",
    }, post_map={"attn_out_w": _lin, "mlp_fc_w": _lin, "mlp_out_w": _lin})
    blocks["qkv_w"] = jnp.asarray(np.stack([qkv(i) for i in range(L)]))
    blocks["qkv_b"] = jnp.zeros((L, 3 * d))        # GPT-J attn has no biases
    blocks["attn_out_b"] = jnp.zeros((L, d))
    params = {
        "wte": jnp.asarray(_np(sd[p + "wte.weight"])),
        "blocks": blocks,
        "ln_f_scale": jnp.asarray(_np(sd[p + "ln_f.weight"])),
        "ln_f_bias": jnp.asarray(_np(sd[p + "ln_f.bias"])),
        "lm_head": jnp.asarray(_lin(_np(sd["lm_head.weight"]))),
    }
    if "lm_head.bias" in sd:
        params["lm_head_bias"] = jnp.asarray(_np(sd["lm_head.bias"]))
    return model, params


def _bert_common(hf_model, dtype, head):
    """Shared BERT mapping (reference containers/bert.py HFBertLayerPolicy)."""
    import jax.numpy as jnp

    from deepspeed_tpu.models.bert import BertConfig, BertModel

    hc = hf_model.config
    cfg = BertConfig(
        vocab_size=hc.vocab_size, max_seq_len=hc.max_position_embeddings,
        type_vocab_size=hc.type_vocab_size, num_layers=hc.num_hidden_layers,
        hidden_size=hc.hidden_size, num_heads=hc.num_attention_heads,
        mlp_dim=hc.intermediate_size, eps=hc.layer_norm_eps,
        hidden_act=hc.hidden_act,
        num_labels=getattr(hc, "num_labels", 2))
    model = BertModel(cfg, compute_dtype=dtype, head=head)
    sd = hf_model.state_dict()
    p = "bert."
    L = cfg.num_layers
    d = cfg.hidden_size

    def qkv(i):
        return np.concatenate(
            [_lin(_np(sd[f"{p}encoder.layer.{i}.attention.self.{x}.weight"]))
             for x in ("query", "key", "value")], axis=1)

    def qkv_b(i):
        return np.concatenate(
            [_np(sd[f"{p}encoder.layer.{i}.attention.self.{x}.bias"])
             for x in ("query", "key", "value")])

    blocks = _dense_blocks(sd, L, {
        "attn_out_w": p + "encoder.layer.{i}.attention.output.dense.weight",
        "attn_out_b": p + "encoder.layer.{i}.attention.output.dense.bias",
        "attn_ln_scale": p + "encoder.layer.{i}.attention.output.LayerNorm.weight",
        "attn_ln_bias": p + "encoder.layer.{i}.attention.output.LayerNorm.bias",
        "mlp_fc_w": p + "encoder.layer.{i}.intermediate.dense.weight",
        "mlp_fc_b": p + "encoder.layer.{i}.intermediate.dense.bias",
        "mlp_out_w": p + "encoder.layer.{i}.output.dense.weight",
        "mlp_out_b": p + "encoder.layer.{i}.output.dense.bias",
        "mlp_ln_scale": p + "encoder.layer.{i}.output.LayerNorm.weight",
        "mlp_ln_bias": p + "encoder.layer.{i}.output.LayerNorm.bias",
    }, post_map={"attn_out_w": _lin, "mlp_fc_w": _lin, "mlp_out_w": _lin})
    blocks["qkv_w"] = jnp.asarray(np.stack([qkv(i) for i in range(L)]))
    blocks["qkv_b"] = jnp.asarray(np.stack([qkv_b(i) for i in range(L)]))
    params = {
        "wte": jnp.asarray(_np(sd[p + "embeddings.word_embeddings.weight"])),
        "wpe": jnp.asarray(_np(sd[p + "embeddings.position_embeddings.weight"])),
        "wtt": jnp.asarray(_np(sd[p + "embeddings.token_type_embeddings.weight"])),
        "emb_ln_scale": jnp.asarray(_np(sd[p + "embeddings.LayerNorm.weight"])),
        "emb_ln_bias": jnp.asarray(_np(sd[p + "embeddings.LayerNorm.bias"])),
        "blocks": blocks,
    }
    if f"{p}pooler.dense.weight" in sd:
        params["pooler_w"] = jnp.asarray(_lin(_np(sd[p + "pooler.dense.weight"])))
        params["pooler_b"] = jnp.asarray(_np(sd[p + "pooler.dense.bias"]))
    else:  # BertForMaskedLM omits the pooler
        params["pooler_w"] = jnp.zeros((d, d), jnp.float32)
        params["pooler_b"] = jnp.zeros((d,), jnp.float32)
    return model, params, sd


@register_policy("BertForMaskedLM")
def bert_mlm_policy(hf_model, dtype):
    import jax.numpy as jnp

    model, params, sd = _bert_common(hf_model, dtype, head="mlm")
    params["mlm"] = {
        "transform_w": jnp.asarray(_lin(_np(
            sd["cls.predictions.transform.dense.weight"]))),
        "transform_b": jnp.asarray(_np(
            sd["cls.predictions.transform.dense.bias"])),
        "ln_scale": jnp.asarray(_np(
            sd["cls.predictions.transform.LayerNorm.weight"])),
        "ln_bias": jnp.asarray(_np(
            sd["cls.predictions.transform.LayerNorm.bias"])),
        "decoder_bias": jnp.asarray(_np(sd["cls.predictions.bias"])),
    }
    # untied MLM decoder: keep the checkpoint's projection rather than wte
    dec_key = "cls.predictions.decoder.weight"
    if dec_key in sd and not getattr(hf_model.config, "tie_word_embeddings",
                                     True):
        params["mlm"]["decoder_w"] = jnp.asarray(_np(sd[dec_key]))
    return model, params


@register_policy("BertForSequenceClassification")
def bert_cls_policy(hf_model, dtype):
    import jax.numpy as jnp

    model, params, sd = _bert_common(hf_model, dtype, head="cls")
    params["cls"] = {
        "w": jnp.asarray(_lin(_np(sd["classifier.weight"]))),
        "b": jnp.asarray(_np(sd["classifier.bias"])),
    }
    return model, params


@register_policy("GPTNeoForCausalLM")
def gpt_neo_policy(hf_model, dtype):
    """HF GPTNeoForCausalLM → DecoderModel.gpt_neo (reference
    module_inject/containers/gptneo.py HFGPTNEOLayerPolicy): unscaled QK^T,
    alternating global/local (sliding-window) attention layers, bias-free
    q/k/v projections."""
    import jax.numpy as jnp

    from deepspeed_tpu.models.transformer import DecoderConfig, DecoderModel

    hc = hf_model.config
    act_map = {"gelu_new": "gelu", "gelu": "gelu_exact", "relu": "relu"}
    if hc.activation_function not in act_map:
        raise ValueError(
            f"gpt_neo_policy: unsupported activation_function "
            f"{hc.activation_function!r}; supported: {sorted(act_map)}")
    act = act_map[hc.activation_function]
    cfg = DecoderConfig.gpt_neo(
        vocab_size=hc.vocab_size, max_seq_len=hc.max_position_embeddings,
        num_layers=hc.num_layers, hidden_size=hc.hidden_size,
        num_heads=hc.num_heads,
        mlp_dim=hc.intermediate_size or 4 * hc.hidden_size,
        eps=hc.layer_norm_epsilon, activation=act,
        local_attn_window=hc.window_size,
        attn_layer_pattern=tuple(hc.attention_layers))
    model = DecoderModel(cfg, compute_dtype=dtype)
    sd = hf_model.state_dict()
    p = "transformer."
    L, d = cfg.num_layers, cfg.hidden_size

    def qkv(i):
        return np.concatenate(
            [_lin(_np(sd[f"{p}h.{i}.attn.attention.{x}_proj.weight"]))
             for x in ("q", "k", "v")], axis=1)

    blocks = _dense_blocks(sd, L, {
        "ln1_scale": p + "h.{i}.ln_1.weight",
        "ln1_bias": p + "h.{i}.ln_1.bias",
        "attn_out_w": p + "h.{i}.attn.attention.out_proj.weight",
        "attn_out_b": p + "h.{i}.attn.attention.out_proj.bias",
        "ln2_scale": p + "h.{i}.ln_2.weight",
        "ln2_bias": p + "h.{i}.ln_2.bias",
        "mlp_fc_w": p + "h.{i}.mlp.c_fc.weight",
        "mlp_fc_b": p + "h.{i}.mlp.c_fc.bias",
        "mlp_out_w": p + "h.{i}.mlp.c_proj.weight",
        "mlp_out_b": p + "h.{i}.mlp.c_proj.bias",
    }, post_map={"attn_out_w": _lin, "mlp_fc_w": _lin, "mlp_out_w": _lin})
    blocks["qkv_w"] = jnp.asarray(np.stack([qkv(i) for i in range(L)]))
    blocks["qkv_b"] = jnp.zeros((L, 3 * d))    # GPT-Neo q/k/v have no bias
    params = {
        "wte": jnp.asarray(_np(sd[p + "wte.weight"])),
        "wpe": jnp.asarray(_np(sd[p + "wpe.weight"])),
        "blocks": blocks,
        "ln_f_scale": jnp.asarray(_np(sd[p + "ln_f.weight"])),
        "ln_f_bias": jnp.asarray(_np(sd[p + "ln_f.bias"])),
    }
    return model, params


def _distilbert_common(hf_model, dtype, head):
    """Shared DistilBERT mapping (reference
    module_inject/containers/distil_bert.py HFDistilBertLayerPolicy): BERT
    post-LN encoder without token-type embeddings."""
    import jax.numpy as jnp

    from deepspeed_tpu.models.bert import BertConfig, BertModel

    hc = hf_model.config
    cfg = BertConfig(
        vocab_size=hc.vocab_size, max_seq_len=hc.max_position_embeddings,
        type_vocab_size=0, num_layers=hc.n_layers, hidden_size=hc.dim,
        num_heads=hc.n_heads, mlp_dim=hc.hidden_dim, eps=1e-12,
        hidden_act=hc.activation, pooler_act="relu",
        num_labels=getattr(hc, "num_labels", 2))
    model = BertModel(cfg, compute_dtype=dtype, head=head)
    sd = hf_model.state_dict()
    p = "distilbert."
    L, d = cfg.num_layers, cfg.hidden_size

    def qkv(i):
        return np.concatenate(
            [_lin(_np(sd[f"{p}transformer.layer.{i}.attention.{x}_lin.weight"]))
             for x in ("q", "k", "v")], axis=1)

    def qkv_b(i):
        return np.concatenate(
            [_np(sd[f"{p}transformer.layer.{i}.attention.{x}_lin.bias"])
             for x in ("q", "k", "v")])

    blocks = _dense_blocks(sd, L, {
        "attn_out_w": p + "transformer.layer.{i}.attention.out_lin.weight",
        "attn_out_b": p + "transformer.layer.{i}.attention.out_lin.bias",
        "attn_ln_scale": p + "transformer.layer.{i}.sa_layer_norm.weight",
        "attn_ln_bias": p + "transformer.layer.{i}.sa_layer_norm.bias",
        "mlp_fc_w": p + "transformer.layer.{i}.ffn.lin1.weight",
        "mlp_fc_b": p + "transformer.layer.{i}.ffn.lin1.bias",
        "mlp_out_w": p + "transformer.layer.{i}.ffn.lin2.weight",
        "mlp_out_b": p + "transformer.layer.{i}.ffn.lin2.bias",
        "mlp_ln_scale": p + "transformer.layer.{i}.output_layer_norm.weight",
        "mlp_ln_bias": p + "transformer.layer.{i}.output_layer_norm.bias",
    }, post_map={"attn_out_w": _lin, "mlp_fc_w": _lin, "mlp_out_w": _lin})
    blocks["qkv_w"] = jnp.asarray(np.stack([qkv(i) for i in range(L)]))
    blocks["qkv_b"] = jnp.asarray(np.stack([qkv_b(i) for i in range(L)]))
    params = {
        "wte": jnp.asarray(_np(sd[p + "embeddings.word_embeddings.weight"])),
        "wpe": jnp.asarray(_np(sd[p + "embeddings.position_embeddings.weight"])),
        "emb_ln_scale": jnp.asarray(_np(sd[p + "embeddings.LayerNorm.weight"])),
        "emb_ln_bias": jnp.asarray(_np(sd[p + "embeddings.LayerNorm.bias"])),
        "blocks": blocks,
        "pooler_w": jnp.zeros((d, d), jnp.float32),
        "pooler_b": jnp.zeros((d,), jnp.float32),
    }
    return model, params, sd


@register_policy("DistilBertForMaskedLM")
def distilbert_mlm_policy(hf_model, dtype):
    import jax.numpy as jnp

    model, params, sd = _distilbert_common(hf_model, dtype, head="mlm")
    params["mlm"] = {
        "transform_w": jnp.asarray(_lin(_np(sd["vocab_transform.weight"]))),
        "transform_b": jnp.asarray(_np(sd["vocab_transform.bias"])),
        "ln_scale": jnp.asarray(_np(sd["vocab_layer_norm.weight"])),
        "ln_bias": jnp.asarray(_np(sd["vocab_layer_norm.bias"])),
        "decoder_w": jnp.asarray(_np(sd["vocab_projector.weight"])),
        "decoder_bias": jnp.asarray(_np(sd["vocab_projector.bias"])),
    }
    return model, params


@register_policy("DistilBertForSequenceClassification")
def distilbert_cls_policy(hf_model, dtype):
    import jax.numpy as jnp

    model, params, sd = _distilbert_common(hf_model, dtype, head="cls")
    # relu pre_classifier plays the pooler's role; classifier on top
    params["pooler_w"] = jnp.asarray(_lin(_np(sd["pre_classifier.weight"])))
    params["pooler_b"] = jnp.asarray(_np(sd["pre_classifier.bias"]))
    params["cls"] = {
        "w": jnp.asarray(_lin(_np(sd["classifier.weight"]))),
        "b": jnp.asarray(_np(sd["classifier.bias"])),
    }
    return model, params


def _clip_text_common(hf_model, dtype, sd_prefix=""):
    """HF CLIPTextModel(-WithProjection) → models/clip.CLIPTextModel
    (reference module_inject/containers/clip.py HFCLIPLayerPolicy)."""
    import jax.numpy as jnp

    from deepspeed_tpu.models.clip import CLIPTextConfig, CLIPTextModel

    hc = hf_model.config
    sd = hf_model.state_dict()
    proj_key = sd_prefix + "text_projection.weight"
    cfg = CLIPTextConfig(
        vocab_size=hc.vocab_size, max_seq_len=hc.max_position_embeddings,
        num_layers=hc.num_hidden_layers, hidden_size=hc.hidden_size,
        num_heads=hc.num_attention_heads, mlp_dim=hc.intermediate_size,
        eps=hc.layer_norm_eps, hidden_act=hc.hidden_act,
        projection_dim=hc.projection_dim if proj_key in sd else 0)
    model = CLIPTextModel(cfg, compute_dtype=dtype)
    p = sd_prefix + "text_model."
    L = cfg.num_layers

    def qkv(i):
        return np.concatenate(
            [_lin(_np(sd[f"{p}encoder.layers.{i}.self_attn.{x}_proj.weight"]))
             for x in ("q", "k", "v")], axis=1)

    def qkv_b(i):
        return np.concatenate(
            [_np(sd[f"{p}encoder.layers.{i}.self_attn.{x}_proj.bias"])
             for x in ("q", "k", "v")])

    blocks = _dense_blocks(sd, L, {
        "ln1_scale": p + "encoder.layers.{i}.layer_norm1.weight",
        "ln1_bias": p + "encoder.layers.{i}.layer_norm1.bias",
        "attn_out_w": p + "encoder.layers.{i}.self_attn.out_proj.weight",
        "attn_out_b": p + "encoder.layers.{i}.self_attn.out_proj.bias",
        "ln2_scale": p + "encoder.layers.{i}.layer_norm2.weight",
        "ln2_bias": p + "encoder.layers.{i}.layer_norm2.bias",
        "mlp_fc_w": p + "encoder.layers.{i}.mlp.fc1.weight",
        "mlp_fc_b": p + "encoder.layers.{i}.mlp.fc1.bias",
        "mlp_out_w": p + "encoder.layers.{i}.mlp.fc2.weight",
        "mlp_out_b": p + "encoder.layers.{i}.mlp.fc2.bias",
    }, post_map={"attn_out_w": _lin, "mlp_fc_w": _lin, "mlp_out_w": _lin})
    blocks["qkv_w"] = jnp.asarray(np.stack([qkv(i) for i in range(L)]))
    blocks["qkv_b"] = jnp.asarray(np.stack([qkv_b(i) for i in range(L)]))
    params = {
        "wte": jnp.asarray(_np(sd[p + "embeddings.token_embedding.weight"])),
        "wpe": jnp.asarray(
            _np(sd[p + "embeddings.position_embedding.weight"])),
        "blocks": blocks,
        "ln_f_scale": jnp.asarray(_np(sd[p + "final_layer_norm.weight"])),
        "ln_f_bias": jnp.asarray(_np(sd[p + "final_layer_norm.bias"])),
    }
    if cfg.projection_dim:
        params["text_projection"] = jnp.asarray(_lin(_np(sd[proj_key])))
    return model, params


@register_policy("CLIPTextModel")
def clip_text_policy(hf_model, dtype):
    return _clip_text_common(hf_model, dtype)


@register_policy("CLIPTextModelWithProjection")
def clip_text_proj_policy(hf_model, dtype):
    return _clip_text_common(hf_model, dtype)


def _normalize_megatron_sd(sd):
    """Strip Megatron module-path prefixes to the flat layers.* namespace
    shared by the dense and MoE converters."""
    return {k.replace("language_model.", "").replace("encoder.", "transformer.")
             .replace("transformer.layers.", "layers.")
             .replace("embedding.", ""): v
            for k, v in sd.items()}


def _megatron_qkv_fns(num_heads, megatron_v2):
    """Fused-qkv row-layout handlers shared by the Megatron converters:
    v2 rows are (heads, 3, head_dim); v1 rows are already (3, heads, dh)."""
    def qkv_w(x):
        return _fuse_headwise_qkv(x, num_heads) if megatron_v2 else x.T

    def qkv_b(x):
        return _fuse_headwise_qkv_bias(x, num_heads) if megatron_v2 else x

    return qkv_w, qkv_b


def convert_megatron_gpt_checkpoint(sd, *, num_heads, megatron_v2=True,
                                    compute_dtype=None, eps=1e-5):
    """Megatron-LM GPT state dict → (GPT2Model, params).

    Reference analog: ``module_inject/containers/megatron_gpt.py``
    (MegatronLayerPolicy) + ``state_dict_factory.py`` — serving Megatron
    checkpoints through the same engine as HF ones.  Handles both fused-qkv
    row layouts: ``megatron_v2=True`` = rows ordered (heads, 3, head_dim)
    (Megatron ≥ 2.0 "version 2"), ``False`` = (3, heads, head_dim).
    Shapes are inferred from the checkpoint; padded vocab rows are kept
    (harmless: the extra logits are never sampled by HF tokenizers).
    """
    import jax.numpy as jnp

    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model

    sd = _normalize_megatron_sd(sd)
    wte = _np(sd["word_embeddings.weight"])
    wpe = _np(sd["position_embeddings.weight"])
    num_layers = 1 + max(int(k.split(".")[1]) for k in sd
                         if k.startswith("layers."))
    d = wte.shape[1]
    cfg = GPT2Config(vocab_size=wte.shape[0], max_seq_len=wpe.shape[0],
                     num_layers=num_layers, hidden_size=d,
                     num_heads=num_heads, eps=eps, tie_embeddings=True)
    model = GPT2Model(cfg, compute_dtype=compute_dtype or jnp.bfloat16)

    qkv_w, qkv_b = _megatron_qkv_fns(num_heads, megatron_v2)

    blocks = _dense_blocks(sd, num_layers, {
        "ln1_scale": "layers.{i}.input_layernorm.weight",
        "ln1_bias": "layers.{i}.input_layernorm.bias",
        "qkv_w": "layers.{i}.attention.query_key_value.weight",
        "qkv_b": "layers.{i}.attention.query_key_value.bias",
        "attn_out_w": "layers.{i}.attention.dense.weight",
        "attn_out_b": "layers.{i}.attention.dense.bias",
        "ln2_scale": "layers.{i}.post_attention_layernorm.weight",
        "ln2_bias": "layers.{i}.post_attention_layernorm.bias",
        "mlp_fc_w": "layers.{i}.mlp.dense_h_to_4h.weight",
        "mlp_fc_b": "layers.{i}.mlp.dense_h_to_4h.bias",
        "mlp_out_w": "layers.{i}.mlp.dense_4h_to_h.weight",
        "mlp_out_b": "layers.{i}.mlp.dense_4h_to_h.bias",
    }, post_map={"qkv_w": qkv_w, "qkv_b": qkv_b,
                 "attn_out_w": _lin, "mlp_fc_w": _lin, "mlp_out_w": _lin})
    params = {
        "wte": jnp.asarray(wte), "wpe": jnp.asarray(wpe), "blocks": blocks,
        "ln_f_scale": jnp.asarray(_np(sd["transformer.final_layernorm.weight"])),
        "ln_f_bias": jnp.asarray(_np(sd["transformer.final_layernorm.bias"])),
    }
    return model, params


def convert_megatron_moe_checkpoint(sd, *, num_heads, top_k=1,
                                    megatron_v2=True, compute_dtype=None,
                                    eps=1e-5):
    """Megatron-DeepSpeed GPT-MoE state dict → (GPTMoEModel, params).

    Reference analog: ``module_inject/containers/megatron_gpt_moe.py``
    (DS_MegatronGPTMoEContainer) — the expert stacks live under
    ``mlp.deepspeed_moe.experts.deepspeed_experts.{e}`` and the gate under
    ``mlp.deepspeed_moe.gate.wg`` (reference moe/experts.py:15,
    moe/layer.py:70); dense/MoE interleave is whatever the Megatron run
    used, detected per layer from the checkpoint keys. Expert Linear
    weights stack to this framework's [E, in, out] batched-einsum layout
    (moe/layer.py ExpertFFN), so serving shards them over the 'expert'
    mesh axis exactly like training.
    """
    import jax.numpy as jnp

    from deepspeed_tpu.models.gpt_moe import GPTMoEConfig, GPTMoEModel

    sd = _normalize_megatron_sd(sd)
    wte = _np(sd["word_embeddings.weight"])
    wpe = _np(sd["position_embeddings.weight"])
    num_layers = 1 + max(int(k.split(".")[1]) for k in sd
                         if k.startswith("layers."))
    d = wte.shape[1]

    def gate_key(i):
        return f"layers.{i}.mlp.deepspeed_moe.gate.wg.weight"

    moe_layers = tuple(i for i in range(num_layers) if gate_key(i) in sd)
    if not moe_layers:
        raise ValueError(
            "no deepspeed_moe gate weights found — use "
            "convert_megatron_gpt_checkpoint for dense Megatron checkpoints")
    num_experts = 1 + max(
        int(k.split("deepspeed_experts.")[1].split(".")[0])
        for k in sd if f"layers.{moe_layers[0]}.mlp.deepspeed_moe.experts." in k)

    cfg = GPTMoEConfig(vocab_size=wte.shape[0], max_seq_len=wpe.shape[0],
                       num_layers=num_layers, hidden_size=d,
                       num_heads=num_heads, num_experts=num_experts,
                       moe_layers=moe_layers, top_k=top_k, eps=eps)
    model = GPTMoEModel(cfg, compute_dtype=compute_dtype or jnp.bfloat16)

    qkv_w, qkv_b = _megatron_qkv_fns(num_heads, megatron_v2)

    blocks = []
    for i in range(num_layers):
        p = f"layers.{i}"
        blk = {
            "ln1_scale": jnp.asarray(_np(sd[f"{p}.input_layernorm.weight"])),
            "ln1_bias": jnp.asarray(_np(sd[f"{p}.input_layernorm.bias"])),
            "qkv_w": jnp.asarray(qkv_w(_np(
                sd[f"{p}.attention.query_key_value.weight"]))),
            "qkv_b": jnp.asarray(qkv_b(_np(
                sd[f"{p}.attention.query_key_value.bias"]))),
            "out_w": jnp.asarray(_lin(_np(sd[f"{p}.attention.dense.weight"]))),
            "out_b": jnp.asarray(_np(sd[f"{p}.attention.dense.bias"])),
            "ln2_scale": jnp.asarray(_np(
                sd[f"{p}.post_attention_layernorm.weight"])),
            "ln2_bias": jnp.asarray(_np(
                sd[f"{p}.post_attention_layernorm.bias"])),
        }
        if i in moe_layers:
            e = f"{p}.mlp.deepspeed_moe.experts.deepspeed_experts"
            blk["moe"] = {
                # reference TopKGate wg is Linear(d→E): weight [E, d] → [d, E]
                "gate": {"wg": jnp.asarray(_np(sd[gate_key(i)]).T)},
                "experts": {
                    "w1": jnp.asarray(np.stack(
                        [_lin(_np(sd[f"{e}.{j}.dense_h_to_4h.weight"]))
                         for j in range(num_experts)])),
                    "b1": jnp.asarray(np.stack(
                        [_np(sd[f"{e}.{j}.dense_h_to_4h.bias"])
                         for j in range(num_experts)])),
                    "w2": jnp.asarray(np.stack(
                        [_lin(_np(sd[f"{e}.{j}.dense_4h_to_h.weight"]))
                         for j in range(num_experts)])),
                    "b2": jnp.asarray(np.stack(
                        [_np(sd[f"{e}.{j}.dense_4h_to_h.bias"])
                         for j in range(num_experts)])),
                },
            }
        else:
            blk.update({
                "mlp_fc_w": jnp.asarray(_lin(_np(
                    sd[f"{p}.mlp.dense_h_to_4h.weight"]))),
                "mlp_fc_b": jnp.asarray(_np(sd[f"{p}.mlp.dense_h_to_4h.bias"])),
                "mlp_out_w": jnp.asarray(_lin(_np(
                    sd[f"{p}.mlp.dense_4h_to_h.weight"]))),
                "mlp_out_b": jnp.asarray(_np(sd[f"{p}.mlp.dense_4h_to_h.bias"])),
            })
        blocks.append(blk)
    params = {
        "wte": jnp.asarray(wte), "wpe": jnp.asarray(wpe), "blocks": blocks,
        "ln_f_scale": jnp.asarray(_np(sd["transformer.final_layernorm.weight"])),
        "ln_f_bias": jnp.asarray(_np(sd["transformer.final_layernorm.bias"])),
    }
    return model, params
