"""Inference configuration — parity with reference
``deepspeed/inference/config.py`` (DeepSpeedInferenceConfig:131-246).

Fields kept with reference semantics: dtype, tensor_parallel.tp_size (:55),
moe.ep_size (:71), max_out_tokens, min_out_tokens, checkpoint,
replace_with_kernel_inject (:131), enable_cuda_graph (:151). TPU notes:
``replace_with_kernel_inject``/``enable_cuda_graph`` are accepted for config
compatibility but are no-ops — every decode step is a jit-compiled XLA
program (the CUDA-graph equivalent), and kernel fusion is XLA/Pallas's job
rather than module surgery's.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Union

import jax.numpy as jnp
from pydantic import Field

from deepspeed_tpu.runtime.config_utils import DeepSpeedConfigModel

_DTYPES = {
    "fp32": jnp.float32, "float32": jnp.float32, "float": jnp.float32,
    "fp16": jnp.float16, "float16": jnp.float16, "half": jnp.float16,
    "bf16": jnp.bfloat16, "bfloat16": jnp.bfloat16,
    "int8": jnp.int8,
}


class DeepSpeedTPConfig(DeepSpeedConfigModel):
    """reference inference/config.py:50 DeepSpeedTPConfig."""

    enabled: bool = True
    tp_size: int = 1


class DeepSpeedMoEConfig(DeepSpeedConfigModel):
    """reference inference/config.py:64 DeepSpeedMoEConfig."""

    enabled: bool = True
    ep_size: int = 1
    moe_experts: list = Field(default_factory=lambda: [1])


class QuantizationConfig(DeepSpeedConfigModel):
    enabled: bool = False
    bits: int = 8
    group_size: int = 64
    # ISSUE 12 satellite: also quantize the TIED embedding / lm-head
    # (per-vocab-row scales) — at 125M the tied table is ~77 MB of the
    # 249 MB weight stream and was deliberately left unquantized until
    # the logit-parity gate existed (models/base.embed_tokens /
    # tied_logits; test_kv_quant pins argmax parity + logit error)
    quantize_embedding: bool = False


class DeepSpeedInferenceConfig(DeepSpeedConfigModel):
    """reference inference/config.py:131 DeepSpeedInferenceConfig."""

    dtype: Any = "bf16"                 # TPU-native default (reference: fp16)
    tensor_parallel: DeepSpeedTPConfig = Field(
        default_factory=DeepSpeedTPConfig, alias="tp")
    moe: Union[bool, DeepSpeedMoEConfig] = Field(default_factory=DeepSpeedMoEConfig)
    quant: QuantizationConfig = Field(default_factory=QuantizationConfig)
    checkpoint: Optional[Union[str, Dict]] = None
    max_tokens: int = Field(1024, alias="max_out_tokens")
    min_out_tokens: int = Field(1, alias="min_tokens")
    max_batch_size: int = 1
    replace_with_kernel_inject: bool = False  # accepted; no-op on TPU
    enable_cuda_graph: bool = False           # accepted; jit IS the graph
    triangular_masking: bool = True
    return_tuple: bool = True
    set_empty_params: bool = False
    seed: int = 0

    # convenience used by the engine
    def jax_dtype(self):
        d = self.dtype
        if isinstance(d, str):
            key = d.lower().replace("torch.", "")
            if key not in _DTYPES:
                raise ValueError(f"unknown inference dtype {d!r}; "
                                 f"one of {sorted(_DTYPES)}")
            return _DTYPES[key]
        return d

    @property
    def tp_size(self) -> int:
        return self.tensor_parallel.tp_size

    @property
    def ep_size(self) -> int:
        if isinstance(self.moe, bool):
            return 1
        return self.moe.ep_size
