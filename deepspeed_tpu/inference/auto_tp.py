"""AutoTP — automatic tensor-parallel sharding inference.

Reference analog: ``AutoTP.tp_parser`` (module_inject/auto_tp.py:84): for an
arbitrary HF model, discover which linear layers must be row-parallel (their
output feeds the residual stream, so TP requires an all-reduce there) vs
column-parallel, without a hand-written policy.  The reference returns a
"gem list" of modules to slice + allreduce; here the output is a
PartitionSpec pytree over the params — XLA inserts the psum when the row-
sharded matmul's output is required replicated, which is exactly the
all-reduce AutoTP hand-places.

Heuristic (same as the reference's name-based parser): a 2-D weight whose
name marks it as an output projection (attention out / MLP down) is
row-parallel ([model, None] over its [in, out] dims); every other 2-D
weight is column-parallel ([None, model]); biases follow their weight
(col-parallel bias is sharded, row-parallel bias is replicated — it is
added after the reduce); 1-D norms/embedding tables replicate.
Stacked-layer leading dims (our scanned blocks) are passed through as None.
"""

from __future__ import annotations

import re
from typing import Any, Dict, Optional, Tuple

import jax
from jax.sharding import PartitionSpec as P

# output-projection name fragments (reference auto_tp.py load-balanced names:
# o_proj/out_proj/down_proj/dense_4h_to_h/attention.dense/c_proj + this
# framework's own layouts)
ROW_PARALLEL_PATTERNS = (
    "attn_out_w", "mlp_out_w", "wo", "w_down",
    "o_proj", "out_proj", "down_proj", "dense_4h_to_h", "c_proj",
    "attention_dense", "attention.dense",
)
# embedding-style tables: replicate (vocab sharding is a separate choice)
EMBED_PATTERNS = ("wte", "wpe", "embed", "lm_head", "word_embeddings")


def classify(name: str, ndim: int) -> str:
    """'row' | 'col' | 'replicate' for one param (reference tp_parser's
    per-module decision)."""
    lname = name.lower()
    if ndim < 2 or any(p in lname for p in EMBED_PATTERNS):
        return "replicate"
    if any(p in lname for p in ROW_PARALLEL_PATTERNS):
        return "row"
    return "col"


def _bias_kind(name: str) -> Optional[str]:
    """A 1-D bias follows its weight's class: col-parallel bias is sharded,
    row-parallel bias replicated (added post-reduce)."""
    # keystr paths look like "['blocks']['qkv_b']" — strip punctuation tails
    lname = name.lower().rstrip("]'\"")
    if not re.search(r"(_b|bias)$", lname):
        return None
    wname = re.sub(r"_b$", "_w", lname)
    wname = re.sub(r"bias$", "weight", wname)
    if any(p in wname for p in ROW_PARALLEL_PATTERNS):
        return "replicate"
    if any(p in wname for p in EMBED_PATTERNS) or "ln" in lname or \
            "norm" in lname:
        return "replicate"
    return "col-bias"


def tp_parser(params) -> Dict[str, str]:
    """Param path → 'row' | 'col' | 'col-bias' | 'replicate'."""
    flat, _ = jax.tree_util.tree_flatten_with_path(params)
    out = {}
    for path, leaf in flat:
        name = jax.tree_util.keystr(path)
        bias = _bias_kind(name)
        if bias is not None:
            out[name] = bias
        else:
            out[name] = classify(name, getattr(leaf, "ndim", 0))
    return out


def tp_shard_specs(params, model_axis: str = "model"):
    """PartitionSpec pytree implementing the parsed plan: the TP sharding a
    hand-written policy would produce, inferred (reference AutoTP outcome)."""
    kinds = tp_parser(params)
    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    specs = []
    for path, leaf in flat:
        name = jax.tree_util.keystr(path)
        kind = kinds[name]
        nd = getattr(leaf, "ndim", 0)
        lead = (None,) * (nd - 2)  # stacked-layer dims stay unsharded
        if kind == "row" and nd >= 2:
            specs.append(P(*lead, model_axis, None))
        elif kind == "col" and nd >= 2:
            specs.append(P(*lead, None, model_axis))
        elif kind == "col-bias" and nd >= 1:
            specs.append(P(*((None,) * (nd - 1)), model_axis))
        else:
            specs.append(P())
    return jax.tree_util.tree_unflatten(treedef, specs)
