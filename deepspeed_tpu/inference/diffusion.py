"""Diffusion serving pipeline — the DeepSpeed-Diffusers analog.

Reference: ``deepspeed.init_inference`` on a diffusers pipeline routes
UNet/VAE/CLIP through ``module_inject/replace_module.py:184
generic_injection`` into CUDA-graphed channels-last wrappers
(``model_implementations/diffusers/{unet,vae}.py``, ``csrc/spatial`` ops).

TPU shape of the same capability:
  * ``convert_diffusers_unet/vae`` map a diffusers-format torch state dict
    (SD-1.x lineage) onto the NHWC JAX models in ``models/diffusion.py``
    (conv kernels OIHW→HWIO, linears [out,in]→[in,out]).
  * ``StableDiffusionEngine`` compiles ONE classifier-free-guidance
    denoise step (jit = the CUDA-graph analog) and drives the DDIM loop
    with a ``lax.scan`` — the whole sampler is a single XLA program.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.models.diffusion import (
    AutoencoderKL,
    UNet2DConditionModel,
)


def _np(t) -> np.ndarray:
    return t.detach().cpu().float().numpy()


def _conv(sd, name):
    """OIHW torch conv kernel → HWIO."""
    return np.transpose(_np(sd[name]), (2, 3, 1, 0))


def _lin_t(sd, name):
    return _np(sd[name]).T


# ------------------------------------------------------------- converters
def _convert_resnet(sd, p):
    out = {
        "norm1_scale": _np(sd[p + "norm1.weight"]),
        "norm1_bias": _np(sd[p + "norm1.bias"]),
        "conv1_w": _conv(sd, p + "conv1.weight"),
        "conv1_b": _np(sd[p + "conv1.bias"]),
        "norm2_scale": _np(sd[p + "norm2.weight"]),
        "norm2_bias": _np(sd[p + "norm2.bias"]),
        "conv2_w": _conv(sd, p + "conv2.weight"),
        "conv2_b": _np(sd[p + "conv2.bias"]),
    }
    if p + "time_emb_proj.weight" in sd:
        out["time_emb_w"] = _lin_t(sd, p + "time_emb_proj.weight")
        out["time_emb_b"] = _np(sd[p + "time_emb_proj.bias"])
    if p + "conv_shortcut.weight" in sd:
        out["shortcut_w"] = _conv(sd, p + "conv_shortcut.weight")
        out["shortcut_b"] = _np(sd[p + "conv_shortcut.bias"])
    return out


def _convert_tblock(sd, p):
    ln = lambda n: {"scale": _np(sd[p + n + ".weight"]),
                    "bias": _np(sd[p + n + ".bias"])}
    lin = lambda n: {"w": _lin_t(sd, p + n + ".weight"),
                     "b": _np(sd[p + n + ".bias"])}
    return {
        "norm1": ln("norm1"), "norm2": ln("norm2"), "norm3": ln("norm3"),
        "attn1_q": _lin_t(sd, p + "attn1.to_q.weight"),
        "attn1_k": _lin_t(sd, p + "attn1.to_k.weight"),
        "attn1_v": _lin_t(sd, p + "attn1.to_v.weight"),
        "attn1_out": lin("attn1.to_out.0"),
        "attn2_q": _lin_t(sd, p + "attn2.to_q.weight"),
        "attn2_k": _lin_t(sd, p + "attn2.to_k.weight"),
        "attn2_v": _lin_t(sd, p + "attn2.to_v.weight"),
        "attn2_out": lin("attn2.to_out.0"),
        "ff_in": {"w": _lin_t(sd, p + "ff.net.0.proj.weight"),
                  "b": _np(sd[p + "ff.net.0.proj.bias"])},
        "ff_out": {"w": _lin_t(sd, p + "ff.net.2.weight"),
                   "b": _np(sd[p + "ff.net.2.bias"])},
    }


def _convert_attn2d(sd, p, depth):
    return {
        "norm_scale": _np(sd[p + "norm.weight"]),
        "norm_bias": _np(sd[p + "norm.bias"]),
        "proj_in_w": _conv(sd, p + "proj_in.weight"),
        "proj_in_b": _np(sd[p + "proj_in.bias"]),
        "blocks": [_convert_tblock(sd, f"{p}transformer_blocks.{k}.")
                   for k in range(depth)],
        "proj_out_w": _conv(sd, p + "proj_out.weight"),
        "proj_out_b": _np(sd[p + "proj_out.bias"]),
    }


def convert_diffusers_unet(sd, config) -> Dict[str, Any]:
    """diffusers UNet2DConditionModel state dict → UNet2DConditionModel
    params (models/diffusion.py). SD-1.x layout: conv proj_in/out."""
    c = config
    params: Dict[str, Any] = {
        "time_mlp1": {"w": _lin_t(sd, "time_embedding.linear_1.weight"),
                      "b": _np(sd["time_embedding.linear_1.bias"])},
        "time_mlp2": {"w": _lin_t(sd, "time_embedding.linear_2.weight"),
                      "b": _np(sd["time_embedding.linear_2.bias"])},
        "conv_in_w": _conv(sd, "conv_in.weight"),
        "conv_in_b": _np(sd["conv_in.bias"]),
        "norm_out_scale": _np(sd["conv_norm_out.weight"]),
        "norm_out_bias": _np(sd["conv_norm_out.bias"]),
        "conv_out_w": _conv(sd, "conv_out.weight"),
        "conv_out_b": _np(sd["conv_out.bias"]),
    }
    down = []
    for i, btype in enumerate(c.down_block_types):
        pre = f"down_blocks.{i}."
        blk = {"resnets": [], "attns": []}
        for j in range(c.layers_per_block):
            blk["resnets"].append(_convert_resnet(sd, f"{pre}resnets.{j}."))
            if btype == "CrossAttnDownBlock2D":
                blk["attns"].append(_convert_attn2d(
                    sd, f"{pre}attentions.{j}.", c.transformer_depth))
        if f"{pre}downsamplers.0.conv.weight" in sd:
            blk["down_w"] = _conv(sd, f"{pre}downsamplers.0.conv.weight")
            blk["down_b"] = _np(sd[f"{pre}downsamplers.0.conv.bias"])
        down.append(blk)
    params["down"] = down
    params["mid"] = {
        "resnet1": _convert_resnet(sd, "mid_block.resnets.0."),
        "attn": _convert_attn2d(sd, "mid_block.attentions.0.",
                                c.transformer_depth),
        "resnet2": _convert_resnet(sd, "mid_block.resnets.1."),
    }
    up = []
    for i, btype in enumerate(c.up_block_types):
        pre = f"up_blocks.{i}."
        blk = {"resnets": [], "attns": []}
        for j in range(c.layers_per_block + 1):
            blk["resnets"].append(_convert_resnet(sd, f"{pre}resnets.{j}."))
            if btype == "CrossAttnUpBlock2D":
                blk["attns"].append(_convert_attn2d(
                    sd, f"{pre}attentions.{j}.", c.transformer_depth))
        if f"{pre}upsamplers.0.conv.weight" in sd:
            blk["up_w"] = _conv(sd, f"{pre}upsamplers.0.conv.weight")
            blk["up_b"] = _np(sd[f"{pre}upsamplers.0.conv.bias"])
        up.append(blk)
    params["up"] = up
    return jax.tree_util.tree_map(jnp.asarray, params)


def _convert_vae_attn(sd, p):
    # diffusers ≥0.15 names (to_q/...); legacy AttentionBlock (query/...)
    new = p + "to_q.weight" in sd
    n = lambda a, b: a if new else b
    lin = lambda nm: {"w": _lin_t(sd, p + nm + ".weight"),
                      "b": _np(sd[p + nm + ".bias"])}
    return {
        "norm_scale": _np(sd[p + "group_norm.weight"]),
        "norm_bias": _np(sd[p + "group_norm.bias"]),
        "q": lin(n("to_q", "query")), "k": lin(n("to_k", "key")),
        "v": lin(n("to_v", "value")),
        "out": lin(n("to_out.0", "proj_attn")),
    }


def convert_diffusers_vae(sd, config) -> Dict[str, Any]:
    """diffusers AutoencoderKL state dict → AutoencoderKL params."""
    c = config
    n_blocks = len(c.block_out_channels)
    enc: Dict[str, Any] = {
        "conv_in_w": _conv(sd, "encoder.conv_in.weight"),
        "conv_in_b": _np(sd["encoder.conv_in.bias"]),
        "down": [],
        "norm_out_scale": _np(sd["encoder.conv_norm_out.weight"]),
        "norm_out_bias": _np(sd["encoder.conv_norm_out.bias"]),
        "conv_out_w": _conv(sd, "encoder.conv_out.weight"),
        "conv_out_b": _np(sd["encoder.conv_out.bias"]),
    }
    for i in range(n_blocks):
        pre = f"encoder.down_blocks.{i}."
        blk = {"resnets": [_convert_resnet(sd, f"{pre}resnets.{j}.")
                           for j in range(c.layers_per_block)]}
        if f"{pre}downsamplers.0.conv.weight" in sd:
            blk["down_w"] = _conv(sd, f"{pre}downsamplers.0.conv.weight")
            blk["down_b"] = _np(sd[f"{pre}downsamplers.0.conv.bias"])
        enc["down"].append(blk)
    enc["mid"] = {
        "resnet1": _convert_resnet(sd, "encoder.mid_block.resnets.0."),
        "attn": _convert_vae_attn(sd, "encoder.mid_block.attentions.0."),
        "resnet2": _convert_resnet(sd, "encoder.mid_block.resnets.1."),
    }
    dec: Dict[str, Any] = {
        "conv_in_w": _conv(sd, "decoder.conv_in.weight"),
        "conv_in_b": _np(sd["decoder.conv_in.bias"]),
        "mid": {
            "resnet1": _convert_resnet(sd, "decoder.mid_block.resnets.0."),
            "attn": _convert_vae_attn(sd, "decoder.mid_block.attentions.0."),
            "resnet2": _convert_resnet(sd, "decoder.mid_block.resnets.1."),
        },
        "up": [],
        "norm_out_scale": _np(sd["decoder.conv_norm_out.weight"]),
        "norm_out_bias": _np(sd["decoder.conv_norm_out.bias"]),
        "conv_out_w": _conv(sd, "decoder.conv_out.weight"),
        "conv_out_b": _np(sd["decoder.conv_out.bias"]),
    }
    for i in range(n_blocks):
        pre = f"decoder.up_blocks.{i}."
        blk = {"resnets": [_convert_resnet(sd, f"{pre}resnets.{j}.")
                           for j in range(c.layers_per_block + 1)]}
        if f"{pre}upsamplers.0.conv.weight" in sd:
            blk["up_w"] = _conv(sd, f"{pre}upsamplers.0.conv.weight")
            blk["up_b"] = _np(sd[f"{pre}upsamplers.0.conv.bias"])
        dec["up"].append(blk)
    params = {
        "encoder": enc, "decoder": dec,
        "quant_w": _conv(sd, "quant_conv.weight"),
        "quant_b": _np(sd["quant_conv.bias"]),
        "post_quant_w": _conv(sd, "post_quant_conv.weight"),
        "post_quant_b": _np(sd["post_quant_conv.bias"]),
    }
    return jax.tree_util.tree_map(jnp.asarray, params)


# --------------------------------------------------------------- scheduler
@dataclasses.dataclass
class DDIMScheduler:
    """Deterministic DDIM (eta=0) with the SD scheduler config: the
    'scaled_linear' beta schedule, 'leading' timestep spacing with
    steps_offset=1, and set_alpha_to_one=False (final previous-alpha is
    alphas_cumprod[0]) — matching diffusers' StableDiffusionPipeline
    trajectory for the same seed."""

    num_train_timesteps: int = 1000
    beta_start: float = 0.00085
    beta_end: float = 0.012
    steps_offset: int = 1
    set_alpha_to_one: bool = False

    def __post_init__(self):
        betas = np.linspace(self.beta_start ** 0.5, self.beta_end ** 0.5,
                            self.num_train_timesteps,
                            dtype=np.float64) ** 2
        self.alphas_cumprod = jnp.asarray(
            np.cumprod(1.0 - betas), jnp.float32)
        self.final_alpha_cumprod = jnp.asarray(
            1.0 if self.set_alpha_to_one else float(self.alphas_cumprod[0]),
            jnp.float32)

    def timesteps(self, num_inference_steps: int) -> jnp.ndarray:
        step = self.num_train_timesteps // num_inference_steps
        ts = jnp.arange(0, num_inference_steps, dtype=jnp.int32)[::-1] * step
        return jnp.minimum(ts + self.steps_offset,
                           self.num_train_timesteps - 1)

    def step(self, eps, t, t_prev, sample):
        acp = self.alphas_cumprod[t]
        acp_prev = jnp.where(t_prev >= 0, self.alphas_cumprod[t_prev],
                             self.final_alpha_cumprod)
        x0 = (sample - jnp.sqrt(1.0 - acp) * eps) / jnp.sqrt(acp)
        return jnp.sqrt(acp_prev) * x0 + jnp.sqrt(1.0 - acp_prev) * eps


# ----------------------------------------------------------------- engine
class StableDiffusionEngine:
    """Text→image serving engine (DeepSpeed-Diffusers ``init_inference``
    analog). The denoise scan (CFG: one batched uncond+cond UNet call per
    step) and the VAE decode compile once."""

    def __init__(self, unet: UNet2DConditionModel, unet_params,
                 vae: AutoencoderKL, vae_params,
                 text_encoder=None, text_params=None,
                 scheduler: Optional[DDIMScheduler] = None):
        self.unet = unet
        self.unet_params = unet_params
        self.vae = vae
        self.vae_params = vae_params
        self.text_encoder = text_encoder
        self.text_params = text_params
        self.scheduler = scheduler or DDIMScheduler()
        self._samplers: Dict[int, Any] = {}   # compiled, keyed by num_steps

    def encode_prompt(self, input_ids):
        assert self.text_encoder is not None, "no text encoder configured"
        return self.text_encoder.forward_hidden(
            self.text_params, jnp.asarray(input_ids))

    def _build(self, num_steps: int):
        sched = self.scheduler
        ts = sched.timesteps(num_steps)
        ts_prev = jnp.concatenate([ts[1:], jnp.array([-1], jnp.int32)])

        def sample_fn(unet_params, vae_params, latents, ctx, uncond_ctx,
                      guidance):
            both_ctx = jnp.concatenate([uncond_ctx, ctx], axis=0)

            def denoise(lat, t_pair):
                t, t_prev = t_pair
                b = lat.shape[0]
                both = jnp.concatenate([lat, lat], axis=0)
                tt = jnp.full((2 * b,), t, jnp.int32)
                eps = self.unet(unet_params, both, tt, both_ctx)
                eps_u, eps_c = jnp.split(eps, 2, axis=0)
                eps = eps_u + guidance * (eps_c - eps_u)
                return sched.step(eps, t, t_prev, lat), None

            latents, _ = jax.lax.scan(denoise, latents, (ts, ts_prev))
            images = self.vae.decode(
                vae_params, latents / self.vae.config.scaling_factor)
            return jnp.clip(images / 2 + 0.5, 0.0, 1.0)

        self._samplers[num_steps] = jax.jit(sample_fn)
        return self._samplers[num_steps]

    def generate(self, prompt_ids, uncond_ids, *, num_steps: int = 50,
                 guidance_scale: float = 7.5, height: int = 512,
                 width: int = 512, rng=None):
        """[B, T] token ids (cond + uncond) → [B, H, W, 3] images in
        [0, 1]."""
        ctx = self.encode_prompt(prompt_ids)
        uncond = self.encode_prompt(uncond_ids)
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        lat_c = self.unet.config.in_channels
        # VAE spatial factor = one 2x per non-final block (8x for SD)
        f = 2 ** (len(self.vae.config.block_out_channels) - 1)
        latents = jax.random.normal(
            rng, (ctx.shape[0], height // f, width // f, lat_c), jnp.float32)
        sample = self._samplers.get(num_steps) or self._build(num_steps)
        return sample(self.unet_params, self.vae_params, latents, ctx,
                      uncond, jnp.asarray(guidance_scale, jnp.float32))
