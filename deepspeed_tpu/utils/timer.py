"""Wall-clock timers and throughput accounting.

TPU-native analog of the reference's ``deepspeed/utils/timer.py``
(SynchronizedWallClockTimer / ThroughputTimer). CUDA-event timing has no
equivalent on TPU: dispatch is async but ``jax.block_until_ready`` gives the
device-complete boundary, so synchronized timers call it on request.
"""

from __future__ import annotations

import time
from collections import OrderedDict
from typing import Dict, List, Optional

from .logging import log_dist

FORWARD_MICRO_TIMER = "fwd_microstep"
FORWARD_GLOBAL_TIMER = "fwd"
BACKWARD_MICRO_TIMER = "bwd_microstep"
BACKWARD_GLOBAL_TIMER = "bwd"
STEP_MICRO_TIMER = "step_microstep"
STEP_GLOBAL_TIMER = "step"
TRAIN_BATCH_TIMER = "train_batch"


class _Timer:
    def __init__(self, name: str):
        self.name = name
        self.started = False
        self._start = 0.0
        self._elapsed = 0.0
        self._record: List[float] = []

    def start(self):
        assert not self.started, f"timer {self.name} already started"
        self._start = time.perf_counter()
        self.started = True

    def stop(self, record: bool = False):
        assert self.started, f"timer {self.name} not started"
        dt = time.perf_counter() - self._start
        self._elapsed += dt
        self.started = False
        if record:
            self._record.append(dt)

    def reset(self):
        self.started = False
        self._elapsed = 0.0

    def elapsed(self, reset: bool = True) -> float:
        started = self.started
        if started:
            self.stop()
        out = self._elapsed
        if reset:
            self.reset()
        if started:
            self.start()
        return out

    def mean(self) -> float:
        return sum(self._record) / max(len(self._record), 1)


class SynchronizedWallClockTimer:
    """Named timer registry; ``sync_fn`` (e.g. block_until_ready on engine state)
    is invoked before reading when device-accurate numbers are requested."""

    def __init__(self, sync_fn=None):
        self.timers: "OrderedDict[str, _Timer]" = OrderedDict()
        self.sync_fn = sync_fn

    def __call__(self, name: str) -> _Timer:
        if name not in self.timers:
            self.timers[name] = _Timer(name)
        return self.timers[name]

    def has(self, name: str) -> bool:
        return name in self.timers

    @staticmethod
    def memory_usage() -> str:
        try:
            import jax

            stats = jax.local_devices()[0].memory_stats() or {}
            in_use = stats.get("bytes_in_use", 0) / (1024**3)
            peak = stats.get("peak_bytes_in_use", 0) / (1024**3)
            return f"Device mem: in_use {in_use:.2f} GB | peak {peak:.2f} GB"
        except Exception:
            return "Device mem: unavailable"

    def log(self, names: List[str], normalizer: float = 1.0, reset: bool = True,
            memory_breakdown: bool = False, ranks: Optional[List[int]] = None):
        assert normalizer > 0.0
        if self.sync_fn is not None:
            self.sync_fn()
        string = "time (ms)"
        for name in names:
            if name in self.timers:
                elapsed = self.timers[name].elapsed(reset=reset) * 1000.0 / normalizer
                string += f" | {name}: {elapsed:.2f}"
        if memory_breakdown:
            string += " | " + self.memory_usage()
        log_dist(string, ranks=ranks or [0])

    def get_mean(self, names: List[str], normalizer: float = 1.0) -> Dict[str, float]:
        return {n: self.timers[n].mean() * 1000.0 / normalizer for n in names if n in self.timers}


class ThroughputTimer:
    """Samples/sec + TFLOPs estimate, mirroring the reference ThroughputTimer
    (deepspeed/utils/timer.py:137)."""

    def __init__(self, batch_size: int, start_step: int = 2, steps_per_output: int = 50,
                 monitor_memory: bool = False, logging_fn=None):
        self.start_time = 0.0
        self.end_time = 0.0
        self.started = False
        self.batch_size = max(batch_size, 1)
        self.start_step = start_step
        self.epoch_count = 0
        self.micro_step_count = 0
        self.global_step_count = 0
        self.total_elapsed_time = 0.0
        self.step_elapsed_time = 0.0
        self.window_steps = 0  # timed global steps in the current window
        self.steps_per_output = steps_per_output
        self.monitor_memory = monitor_memory
        self.logging = logging_fn or (lambda msg: log_dist(msg, ranks=[0]))
        self.initialized = False
        self.flops_per_sample = None  # optionally set by the engine from model cost analysis

    def update_epoch_count(self):
        self.epoch_count += 1
        self.micro_step_count = 0

    def _init_timer(self):
        self.initialized = True

    def start(self):
        self._init_timer()
        self.started = True
        if self.global_step_count >= self.start_step:
            self.start_time = time.perf_counter()

    def stop(self, global_step: bool = False, report_speed: bool = True):
        if not self.started:
            return
        self.started = False
        self.micro_step_count += 1
        if global_step:
            self.global_step_count += 1
        if self.start_time > 0.0:
            self.end_time = time.perf_counter()
            duration = self.end_time - self.start_time
            self.total_elapsed_time += duration
            self.step_elapsed_time += duration
            if global_step:
                self.window_steps += 1
            self.start_time = 0.0
            if global_step and report_speed and \
                    self.global_step_count % self.steps_per_output == 0:
                # step_elapsed_time spans EVERY timed step since the last
                # report, so the current-rate numerator is the window's
                # sample count, not one batch (a single batch_size here
                # under-reported CurrSamplesPerSec by ~steps_per_output x)
                window_samples = self.batch_size * max(self.window_steps, 1)
                msg = (f"epoch={self.epoch_count}/micro_step={self.micro_step_count}/"
                       f"global_step={self.global_step_count}, "
                       f"RunningAvgSamplesPerSec={self.avg_samples_per_sec():.3f}, "
                       f"CurrSamplesPerSec={window_samples / self.step_elapsed_time:.3f}")
                if self.flops_per_sample:
                    tflops = self.flops_per_sample * window_samples / self.step_elapsed_time / 1e12
                    msg += f", TFLOPs={tflops:.2f}"
                self.logging(msg)
                self.step_elapsed_time = 0.0
                self.window_steps = 0

    def avg_samples_per_sec(self) -> float:
        if self.global_step_count > self.start_step and self.total_elapsed_time > 0:
            samples = self.batch_size * (self.global_step_count - self.start_step)
            return samples / self.total_elapsed_time
        return float("nan")


def trainable_parameters_numel(params) -> int:
    import jax

    return sum(x.size for x in jax.tree_util.tree_leaves(params))
