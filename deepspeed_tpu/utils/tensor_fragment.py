"""Flat-buffer ↔ per-parameter fragment mapping.

Reference analog: ``deepspeed/utils/tensor_fragment.py`` — maps each
parameter to its (offset, numel) slice of the flat fp32 optimizer partition
so universal checkpointing can reassemble full tensors from dp shards.
This framework's native checkpoints are already per-parameter global arrays
(no flat buffers), so these helpers exist to IMPORT reference-style ZeRO
checkpoints (zero_pp_rank_*_optim_states.pt flat partitions) and to export
flat layouts other tools expect.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


@dataclass
class Fragment:
    name: str
    offset: int     # element offset into the flat buffer
    numel: int
    shape: Tuple[int, ...]


def fragment_map(shapes: Dict[str, Tuple[int, ...]],
                 order: Optional[Sequence[str]] = None) -> List[Fragment]:
    """Flat layout of the given param shapes in ``order`` — defaulting to
    the dict's insertion order, which is how callers express the source's
    registration order (reference flat partitions are laid out in parameter
    registration order, NOT name order — a mismatched order reassembles
    silently-wrong tensors)."""
    names = list(order) if order is not None else list(shapes)
    assert set(names) == set(shapes), \
        f"order names {sorted(set(names) ^ set(shapes))} mismatch shapes"
    out, off = [], 0
    for name in names:
        n = int(np.prod(shapes[name])) if shapes[name] else 1
        out.append(Fragment(name, off, n, tuple(shapes[name])))
        off += n
    return out


def flatten_params(params: Dict[str, np.ndarray]) -> np.ndarray:
    frags = fragment_map({k: v.shape for k, v in params.items()})
    flat = np.empty(sum(f.numel for f in frags), np.float32)
    for f in frags:
        flat[f.offset:f.offset + f.numel] = \
            np.asarray(params[f.name], np.float32).reshape(-1)
    return flat


def unflatten_params(flat: np.ndarray,
                     shapes: Dict[str, Tuple[int, ...]]) -> Dict[str, np.ndarray]:
    frags = fragment_map(shapes)
    total = sum(f.numel for f in frags)
    assert flat.size >= total, \
        f"flat buffer has {flat.size} elements, layout needs {total}"
    return {f.name: flat[f.offset:f.offset + f.numel].reshape(f.shape)
            for f in frags}


def gather_dp_partitions(partitions: Sequence[np.ndarray],
                         shapes: Dict[str, Tuple[int, ...]]
                         ) -> Dict[str, np.ndarray]:
    """Reassemble per-param tensors from dp-sharded flat partitions
    (reference get_full_hp_param over zero shards: partitions are equal
    slices of the concatenated flat buffer, possibly padded at the end)."""
    flat = np.concatenate([np.asarray(p, np.float32).reshape(-1)
                           for p in partitions])
    return unflatten_params(flat, shapes)
