"""Compatibility shims over jax API drift.

``shard_map`` moved from ``jax.experimental.shard_map`` to the top-level
``jax`` namespace, and two kwargs were renamed on the way:

  * ``check_rep``  → ``check_vma``
  * partial-manual axes: old API takes ``auto`` (the complement set —
    mesh axes left OUT of manual mode), new API takes ``axis_names``
    (the manual set itself).

The codebase is written against the new surface (``axis_names``,
``check_vma``); this adapter translates per-installed-jax so the 1-bit
engine path, ring attention, and the pipeline executor run on both. On
jaxlibs where ``from jax import shard_map`` works, this is a pass-through.
"""

from __future__ import annotations

import inspect

import jax

try:  # jax >= 0.6 (top-level, check_vma/axis_names spelling)
    from jax import shard_map as _shard_map
except ImportError:  # older jax: experimental module, check_rep/auto
    from jax.experimental.shard_map import shard_map as _shard_map

_PARAMS = frozenset(inspect.signature(_shard_map).parameters)


def has_vma_typing() -> bool:
    """True when this jax tracks shard_map varying-manual-axes types
    (aval ``.vma``); same probe as ops.flash_attention.
    vma_typing_supported, duplicated here so L0 utils need not import the
    kernel layer."""
    try:
        import jax.numpy as jnp

        jax.ShapeDtypeStruct((1,), jnp.float32, vma=frozenset())
        return hasattr(jax.typeof(jnp.zeros(())), "vma")
    except Exception:
        return False


def pcast_varying(x, axis_names):
    """``lax.pcast(x, axes, to="varying")`` where vma typing exists;
    identity on older jax, whose shard_map rep machinery either inserts
    the casts itself (check_rep=True) or doesn't track reps at all
    (check_rep=False) — there is nothing to cast."""
    pcast = getattr(jax.lax, "pcast", None)
    if pcast is None:
        return x
    return pcast(x, axis_names, to="varying")


def shard_map(f, *, mesh, in_specs, out_specs, **kw):
    if "check_vma" in kw and "check_vma" not in _PARAMS:
        kw["check_rep"] = kw.pop("check_vma")
    elif "check_rep" in kw and "check_rep" not in _PARAMS:
        kw["check_vma"] = kw.pop("check_rep")
    if "axis_names" in kw and "axis_names" not in _PARAMS:
        manual = kw.pop("axis_names")
        if manual is not None and "auto" in _PARAMS:
            auto = frozenset(getattr(mesh, "axis_names", ())) - set(manual)
            if auto:
                kw["auto"] = auto
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **kw)
