"""Durable filesystem primitives for checkpointing.

Every checkpoint byte goes through this module so that (a) transient
filesystem errors (GCS fuse hiccups, NFS timeouts) are retried with
exponential backoff + jitter, (b) publication is atomic — a file is either
the complete old version or the complete new version, never a torn write —
and (c) tests can inject faults at one seam
(``deepspeed_tpu.testing.fault_injection`` patches the functions here).

Reference analog: the reference DeepSpeed delegates durability to Nebula /
torch.save; on TPU pods the filesystem (usually GCS-backed) is the only
persistence layer, so atomicity and retries live here.
"""

from __future__ import annotations

import os
import random
import time
from typing import Callable, Optional, Tuple, Type

from deepspeed_tpu.utils.logging import logger

# Module-level knobs (read at call time so tests / deployments can tune them
# without threading parameters through every caller).
DEFAULT_RETRIES = 4
DEFAULT_BASE_DELAY_S = 0.05
DEFAULT_MAX_DELAY_S = 2.0
DEFAULT_JITTER = 0.5

# Errors that signal a *permanent* condition — retrying cannot help and only
# delays the real traceback.
NON_RETRYABLE = (FileNotFoundError, IsADirectoryError, NotADirectoryError,
                 PermissionError)

TMP_SUFFIX = ".tmp"


def retry_io(fn: Callable, *, retries: Optional[int] = None,
             base_delay_s: Optional[float] = None,
             max_delay_s: Optional[float] = None,
             jitter: Optional[float] = None,
             retry_on: Tuple[Type[BaseException], ...] = (OSError,),
             description: str = ""):
    """Call ``fn()`` retrying transient I/O errors.

    Exponential backoff (``base * 2**attempt``) capped at ``max_delay_s``,
    with multiplicative jitter in ``[1-jitter, 1+jitter]`` so a pod's worth
    of workers retrying the same flaky filesystem don't stampede in sync.
    ``NON_RETRYABLE`` errors re-raise immediately.
    """
    retries = DEFAULT_RETRIES if retries is None else retries
    base_delay_s = DEFAULT_BASE_DELAY_S if base_delay_s is None else base_delay_s
    max_delay_s = DEFAULT_MAX_DELAY_S if max_delay_s is None else max_delay_s
    jitter = DEFAULT_JITTER if jitter is None else jitter
    attempt = 0
    while True:
        try:
            return fn()
        except NON_RETRYABLE:
            raise
        except retry_on as e:
            attempt += 1
            if attempt > retries:
                raise
            delay = min(max_delay_s, base_delay_s * (2 ** (attempt - 1)))
            delay *= 1.0 + jitter * random.uniform(-1.0, 1.0)
            delay = max(delay, 0.0)
            logger.warning(
                f"transient I/O error{' in ' + description if description else ''}"
                f" ({type(e).__name__}: {e}); retry {attempt}/{retries} "
                f"in {delay:.3f}s")
            time.sleep(delay)


def read_bytes(path: str) -> bytes:
    with open(path, "rb") as f:
        return f.read()


def write_bytes(path: str, data: bytes) -> None:
    """Write + flush + fsync. Reopening with 'wb' truncates, so a retry
    after a partial write starts clean."""
    with open(path, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())


def stream_write(path: str, writer: Callable) -> None:
    """``writer(fileobj)`` streams content to ``path``; flush + fsync before
    close. Lets large payloads (np.savez zips) go straight to disk without
    an in-memory copy of the serialized form."""
    with open(path, "wb") as f:
        writer(f)
        f.flush()
        os.fsync(f.fileno())


def replace(src: str, dst: str) -> None:
    os.replace(src, dst)


def fsync_dir(path: str) -> None:
    """Best-effort directory fsync so a rename survives power loss; some
    filesystems (and all object-store fuses) don't support it."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def atomic_write_bytes(path: str, data: bytes, **retry_kw) -> None:
    """Durably publish ``data`` at ``path``: write to ``path + '.tmp'``
    (retried), then ``os.replace`` onto the final name. Readers never
    observe a torn file; a crash mid-write leaves the previous version (or
    nothing) at ``path`` plus at most a stale ``.tmp``."""
    _atomic_publish(path, lambda tmp: retry_io(
        lambda: write_bytes(tmp, data), description=f"write {tmp}", **retry_kw),
        **retry_kw)


def atomic_stream_write(path: str, writer: Callable, **retry_kw) -> None:
    """Atomic publish for streamed payloads: ``writer(fileobj)`` runs
    against ``path + '.tmp'`` (retried — rewinding is the writer's job is
    NOT assumed, each retry reopens a truncated file and calls ``writer``
    afresh), then the tmp is renamed onto the final name."""
    _atomic_publish(path, lambda tmp: retry_io(
        lambda: stream_write(tmp, writer), description=f"write {tmp}",
        **retry_kw), **retry_kw)


def _atomic_publish(path: str, write_tmp: Callable, **retry_kw) -> None:
    tmp = path + TMP_SUFFIX
    try:
        write_tmp(tmp)
        retry_io(lambda: replace(tmp, path),
                 description=f"publish {path}", **retry_kw)
    except BaseException:
        try:
            if os.path.exists(tmp):
                os.remove(tmp)
        except OSError:
            pass
        raise
    fsync_dir(os.path.dirname(path) or ".")


def atomic_write_text(path: str, text: str, **retry_kw) -> None:
    atomic_write_bytes(path, text.encode("utf-8"), **retry_kw)


def read_bytes_with_retry(path: str, **retry_kw) -> bytes:
    return retry_io(lambda: read_bytes(path),
                    description=f"read {path}", **retry_kw)
