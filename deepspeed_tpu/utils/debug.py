"""Debug / safe-mode helpers.

Reference analogs: ``deepspeed/utils/debug.py`` (module/param debug
printers), ``runtime/utils.py see_memory_usage``, and the safe-mode asserts
sprinkled through ZeRO-3 (stage3.py:1045 ``safe_mode``, trace-invalidation
checks in partitioned_param_coordinator.py:138).

SURVEY §5.2 notes the reference has NO systematic race/invariant checking —
correctness of its async paths rests on stream synchronization.  The
functional JAX design can do better cheaply: every distributed invariant is
a PLACEMENT, so one walk over the engine state verifies that reality
matches the PartitionPlan.  Enable continuously with ``DSTPU_DEBUG=1``
(checked after init and every ``steps_per_print`` steps) or call
``assert_sharding_invariants(engine)`` directly in tests.
"""

from __future__ import annotations

import math
import os
from typing import List

import jax

from deepspeed_tpu.utils.logging import logger


def debug_mode_enabled() -> bool:
    return os.environ.get("DSTPU_DEBUG") == "1"


def check_sharding_invariants(engine) -> List[str]:
    """Compare the live placement of engine.state against the
    PartitionPlan's declared specs. Returns human-readable violations
    (empty = healthy)."""
    problems: List[str] = []
    n_mesh_devices = int(math.prod(engine.mesh.devices.shape)) \
        if hasattr(engine, "mesh") else 1

    def norm(t):
        """Strip only the TRAILING None suffix — interior Nones are real
        (they pin WHICH dim is sharded)."""
        t = tuple(t)
        while t and t[-1] is None:
            t = t[:-1]
        return t

    def walk(prefix, tree, spec_tree):
        if hasattr(tree, "_asdict"):          # NamedTuple state nodes
            tree = tree._asdict()
            if hasattr(spec_tree, "_asdict"):
                spec_tree = spec_tree._asdict()
        if isinstance(tree, dict):
            for k in tree:
                sub_spec = spec_tree.get(k) if isinstance(spec_tree, dict) \
                    else None
                walk(f"{prefix}.{k}", tree[k], sub_spec)
            return
        if not hasattr(tree, "sharding") or spec_tree is None:
            return
        actual = getattr(tree.sharding, "spec", None)
        if actual is None:
            # SingleDeviceSharding/GSPMDSharding: on a multi-device mesh
            # this IS the misplacement the checker exists for (the array
            # escaped the plan entirely)
            if n_mesh_devices > 1:
                problems.append(
                    f"{prefix}: non-mesh placement "
                    f"{type(tree.sharding).__name__} on a "
                    f"{n_mesh_devices}-device mesh")
            return
        want = tuple(spec_tree) if not isinstance(spec_tree, tuple) \
            else spec_tree
        got = tuple(actual)
        if norm(got) != norm(want):
            problems.append(
                f"{prefix}: placed {got}, plan says {want}")

    try:
        walk("params", engine.state.params, engine.master_specs)
        if getattr(engine, "opt_specs", None) is not None and \
                engine.state.opt_state:
            walk("opt_state", engine.state.opt_state, engine.opt_specs)
    except Exception as e:   # a checker must never take training down
        problems.append(f"invariant walk failed: {e!r}")
    return problems


def assert_sharding_invariants(engine) -> None:
    problems = check_sharding_invariants(engine)
    if problems:
        raise AssertionError(
            "sharding invariants violated:\n  " + "\n  ".join(problems))


def see_memory_usage(message: str, force: bool = False) -> None:
    """reference runtime/utils.py see_memory_usage: print allocator stats.
    On TPU backends reads per-device memory_stats(); always reports host
    RSS from /proc."""
    if not (force or debug_mode_enabled()):
        return
    lines = [message]
    for d in jax.local_devices():
        stats = d.memory_stats() or {}
        if stats:
            in_use = stats.get("bytes_in_use", 0) / 2**30
            peak = stats.get("peak_bytes_in_use", 0) / 2**30
            limit = stats.get("bytes_limit", 0) / 2**30
            lines.append(f"  {d}: in_use={in_use:.2f}GB "
                         f"peak={peak:.2f}GB limit={limit:.2f}GB")
    try:
        with open("/proc/self/status") as f:
            for ln in f:
                if ln.startswith("VmRSS"):
                    lines.append(f"  host RSS={int(ln.split()[1]) / 2**20:.2f}GB")
                    break
    except OSError:
        pass
    logger.info("\n".join(lines))
