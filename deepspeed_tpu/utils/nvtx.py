"""Profiler range instrumentation — reference ``deepspeed/utils/nvtx.py:9
instrument_w_nvtx`` (NVTX range push/pop around hot functions).

On TPU the ranges are ``jax.profiler.TraceAnnotation`` scopes: they appear
in Perfetto/XPlane traces captured with ``jax.profiler.start_trace`` the
way NVTX ranges appear in Nsight.  The decorator name is kept for source
compatibility; ``instrument_w_scope`` is the native-flavored alias.
"""

from __future__ import annotations

import functools

import jax


def instrument_w_nvtx(func):
    """Wrap ``func`` in a named profiler trace annotation."""
    name = getattr(func, "__qualname__", getattr(func, "__name__", "fn"))

    @functools.wraps(func)
    def wrapped(*args, **kwargs):
        with jax.profiler.TraceAnnotation(name):
            return func(*args, **kwargs)

    return wrapped


instrument_w_scope = instrument_w_nvtx


def range_push(msg: str):
    """Imperative form (reference accelerator range_push); prefer the
    decorator or ``jax.profiler.TraceAnnotation`` as a context manager."""
    from deepspeed_tpu.accelerator import get_accelerator

    return get_accelerator().range_push(msg)


def range_pop():
    from deepspeed_tpu.accelerator import get_accelerator

    return get_accelerator().range_pop()
