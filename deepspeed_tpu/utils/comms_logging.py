"""Communication operation logging — analog of the reference's
``deepspeed/utils/comms_logging.py`` (CommsLogger) and the ``timed_op``
decorator in ``deepspeed/comm/comm.py:104``.

Collectives on TPU execute inside compiled programs, so per-op wall-clock is
only measurable for the eager (outside-jit) paths; for traced collectives the
logger records op name, message size and axis at trace time and the summary
reports counts/volumes (algbw/busbw are reported for timed ops only).
"""

from __future__ import annotations

import math
from collections import defaultdict
from typing import Dict, List, Optional

from .logging import log_dist


def get_caller_func(frame_depth: int = 3) -> str:
    import sys

    frame = sys._getframe(frame_depth)
    return frame.f_code.co_name


def convert_size(size_bytes: int) -> str:
    if size_bytes == 0:
        return "0B"
    names = ("B", "KB", "MB", "GB", "TB", "PB")
    i = int(math.floor(math.log(size_bytes, 1024)))
    p = math.pow(1024, i)
    return f"{round(size_bytes / p, 2)} {names[i]}"


def calc_bw_log(comm_op: str, size_bytes: int, duration_s: float, n: int):
    """algbw/busbw formulae per collective (mirrors reference calc_bw_log)."""
    duration_s = max(duration_s, 1e-12)
    tput = size_bytes / duration_s
    if comm_op in ("all_to_all",):
        busbw = tput * ((n - 1) / max(n, 1))
    elif comm_op in ("all_gather", "reduce_scatter"):
        size_bytes = size_bytes * n
        tput = size_bytes / duration_s
        busbw = tput * ((n - 1) / max(n, 1))
    elif comm_op in ("all_reduce",):
        tput = size_bytes * 2 / duration_s
        busbw = (size_bytes / duration_s) * (2 * (n - 1) / max(n, 1))
    else:  # pt2pt / broadcast / barrier
        busbw = tput
    return tput / 1e9, busbw / 1e9, size_bytes


class CommsLogger:
    def __init__(self, enabled: bool = False, verbose: bool = False,
                 prof_all: bool = True, prof_ops: Optional[List[str]] = None,
                 debug: bool = False):
        self.enabled = enabled
        self.verbose = verbose
        self.prof_all = prof_all
        self.prof_ops = prof_ops or []
        self.debug = debug
        # op name -> msg size -> [count, total_time_s, [tputs], [busbws]]
        self.comms_dict: Dict[str, Dict[int, list]] = defaultdict(dict)

    def configure(self, config) -> None:
        self.enabled = config.enabled
        self.verbose = config.verbose
        self.prof_all = config.prof_all
        self.prof_ops = config.prof_ops
        self.debug = config.debug

    def should_profile(self, op_name: str) -> bool:
        return self.enabled and (self.prof_all or op_name in self.prof_ops)

    def append(self, raw_name: str, record_name: str, latency_s: float,
               msg_size: int, world_size: int = 1) -> None:
        algbw, busbw, msg_size = calc_bw_log(raw_name, msg_size, latency_s, world_size)
        if record_name in self.comms_dict:
            if msg_size in self.comms_dict[record_name]:
                entry = self.comms_dict[record_name][msg_size]
                entry[0] += 1
                entry[1] += latency_s
                entry[2].append(algbw)
                entry[3].append(busbw)
            else:
                self.comms_dict[record_name][msg_size] = [1, latency_s, [algbw], [busbw]]
        else:
            self.comms_dict[record_name] = {msg_size: [1, latency_s, [algbw], [busbw]]}
        if self.verbose:
            log_dist(
                f"comm op: {record_name} | time (ms): {latency_s * 1000:.2f} | "
                f"msg size: {convert_size(msg_size)} | algbw (Gbps): {algbw * 8:.2f} | "
                f"busbw (Gbps): {busbw * 8:.2f}", ranks=[0])

    def record_traced(self, raw_name: str, record_name: str, msg_size: int) -> None:
        """Trace-time record (no latency available inside jit)."""
        if record_name in self.comms_dict and msg_size in self.comms_dict[record_name]:
            self.comms_dict[record_name][msg_size][0] += 1
        else:
            self.comms_dict[record_name][msg_size] = [1, 0.0, [], []]

    def log_all(self, print_log: bool = True, show_straggler: bool = False):
        import numpy as np

        lines = [f"{'Comm. Op': <20}{'Message Size': <20}{'Count': <20}"
                 f"{'Total Latency(ms)': <20}{'Avg Latency(ms)': <20}"
                 f"{'tput_avg (Gbps)': <20}{'busbw_avg (Gbps)': <20}"]
        for record_name in self.comms_dict:
            lines.append(record_name)
            for msg_size, vals in sorted(self.comms_dict[record_name].items()):
                count, total_lat, tputs, busbws = vals
                avg_lat = total_lat / count * 1000 if count else 0.0
                avg_algbw = 8 * float(np.mean(tputs)) if tputs else 0.0
                avg_busbw = 8 * float(np.mean(busbws)) if busbws else 0.0
                lines.append(
                    f"{' ': <20}{convert_size(msg_size): <20}{count: <20}"
                    f"{total_lat * 1000:<20.2f}{avg_lat:<20.2f}"
                    f"{avg_algbw:<20.2f}{avg_busbw:<20.2f}")
        out = "\n".join(lines)
        if print_log:
            log_dist("\n" + out, ranks=[0])
        return out
