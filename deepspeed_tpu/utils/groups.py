"""Global parallel-group state — analog of the reference's
``deepspeed/utils/groups.py``.

The reference materialises torch ProcessGroups per axis; here a "group" is a
mesh axis name (str) usable directly in ``jax.lax`` collectives and
``PartitionSpec``s. A module-level current topology plays the role of the
reference's ``_WORLD_GROUP``/``_EXPERT_PARALLEL_GROUP`` dicts.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

from deepspeed_tpu.parallel.topology import (
    BATCH_AXES,
    DATA_AXIS,
    EXPERT_AXIS,
    MODEL_AXIS,
    PIPE_AXIS,
    SEQ_AXIS,
    MeshTopology,
    build_topology,
)

_TOPOLOGY: Optional[MeshTopology] = None


def initialize(topology: Optional[MeshTopology] = None, *, ep_size: int = 1,
               tp_size: int = 1, pp_size: int = 1, sp_size: int = 1) -> MeshTopology:
    """Initialise the global topology (reference groups.initialize, :46)."""
    global _TOPOLOGY
    if topology is None:
        topology = build_topology(tp=tp_size, pp=pp_size, ep=ep_size, sp=sp_size)
    _TOPOLOGY = topology
    return topology


def is_initialized() -> bool:
    return _TOPOLOGY is not None


def get_topology() -> MeshTopology:
    global _TOPOLOGY
    if _TOPOLOGY is None:
        _TOPOLOGY = build_topology()
    return _TOPOLOGY


def reset() -> None:
    global _TOPOLOGY
    _TOPOLOGY = None


def get_mesh():
    return get_topology().mesh


# --- group accessors: return mesh axis names (usable as lax collective axes) ---
def get_data_parallel_group() -> Tuple[str, ...]:
    """Dense-batch axis: ('data','expert') — expert axis folds into DP for
    non-expert params (reference _get_data_parallel_group, groups.py:319)."""
    return BATCH_AXES


def get_model_parallel_group() -> str:
    return MODEL_AXIS


def get_expert_parallel_group() -> str:
    return EXPERT_AXIS


def get_expert_data_parallel_group() -> Tuple[str, ...]:
    """Axis over which *expert* parameters are data-parallel (grad averaged):
    the plain data axis, since experts are sharded over 'expert'."""
    return (DATA_AXIS,)


def get_pipe_parallel_group() -> str:
    return PIPE_AXIS


def get_sequence_parallel_group() -> str:
    return SEQ_AXIS


def get_data_parallel_world_size() -> int:
    return get_topology().data_parallel_size


def get_model_parallel_world_size() -> int:
    return get_topology().model_parallel_size


def get_expert_parallel_world_size() -> int:
    return get_topology().expert_parallel_size


def get_pipe_parallel_world_size() -> int:
    return get_topology().pipe_parallel_size


def get_sequence_parallel_world_size() -> int:
    return get_topology().sequence_parallel_size


def get_world_size() -> int:
    return get_topology().world_size
