"""Rank-filtered logging.

TPU-native analog of the reference's ``deepspeed/utils/logging.py`` (log_dist /
logger setup). In JAX the "rank" is ``jax.process_index()`` for multi-host and
0 for single-process runs; device-level ranks do not exist as processes.
"""

from __future__ import annotations

import functools
import logging
import os
import sys

LOG_LEVELS = {
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "critical": logging.CRITICAL,
}


@functools.lru_cache(None)
def _create_logger(name: str = "deepspeed_tpu", level=logging.INFO) -> logging.Logger:
    lg = logging.getLogger(name)
    lg.setLevel(level)
    lg.propagate = False
    formatter = logging.Formatter(
        "[%(asctime)s] [%(levelname)s] [%(name)s:%(lineno)d] %(message)s"
    )
    handler = logging.StreamHandler(stream=sys.stdout)
    handler.setLevel(level)
    handler.setFormatter(formatter)
    lg.addHandler(handler)
    return lg


logger = _create_logger(
    "deepspeed_tpu", LOG_LEVELS.get(os.environ.get("DSTPU_LOG_LEVEL", "info"), logging.INFO)
)


def _process_index() -> int:
    try:
        import jax

        return jax.process_index()
    except Exception:  # jax not initialised yet / no backend
        return int(os.environ.get("DSTPU_PROCESS_INDEX", 0))


def log_dist(message: str, ranks=None, level=logging.INFO) -> None:
    """Log only on the given process ranks (None or [-1] => all ranks).

    Mirrors the contract of the reference ``log_dist`` (deepspeed/utils/logging.py).
    """
    my_rank = _process_index()
    if ranks is None or -1 in ranks or my_rank in ranks:
        logger.log(level, f"[Rank {my_rank}] {message}")


def print_rank_0(message: str) -> None:
    if _process_index() == 0:
        print(message, flush=True)


def warning_once(message: str, _seen=set()) -> None:
    if message not in _seen:
        _seen.add(message)
        logger.warning(message)
