from . import groups
from .logging import log_dist, logger, print_rank_0
from .timer import SynchronizedWallClockTimer, ThroughputTimer
from .comms_logging import CommsLogger

__all__ = [
    "groups",
    "log_dist",
    "logger",
    "print_rank_0",
    "SynchronizedWallClockTimer",
    "ThroughputTimer",
    "CommsLogger",
]
