"""Per-node launcher — spawns the node's worker processes.

Reference analog: ``deepspeed/launcher/launch.py:216 main``: decode world
info, spawn one child per local slot with rank env vars, poll children, and
kill the whole process tree if any rank fails (failure detection,
launch.py:119 terminate_process_tree).  Here the env contract is the JAX
rendezvous (DSTPU_COORDINATOR_ADDRESS / DSTPU_NUM_PROCESSES /
DSTPU_PROCESS_ID) plus RANK/LOCAL_RANK/WORLD_SIZE for torch-style user code.

``--elastic`` wraps the children in the restart-on-failure elastic agent
(reference DSElasticAgent, elasticity/elastic_agent.py:28).
"""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time
from typing import Dict, List

from deepspeed_tpu.launcher.constants import (
    COORDINATOR_ADDR_ENV,
    NUM_PROCESSES_ENV,
    PROCESS_ID_ENV,
)
from deepspeed_tpu.launcher.runner import decode_world_info
from deepspeed_tpu.utils.logging import logger


def parse_args(args=None):
    parser = argparse.ArgumentParser(description="dstpu per-node launcher")
    parser.add_argument("--world_info", type=str, required=True,
                        help="base64 {host: [slots]} map")
    parser.add_argument("--node_rank", type=int, required=True)
    parser.add_argument("--master_addr", type=str, required=True)
    parser.add_argument("--master_port", type=int, required=True)
    parser.add_argument("--elastic", action="store_true")
    parser.add_argument("--max_restarts", type=int, default=3)
    parser.add_argument("--restart_window", type=float, default=None,
                        help="rolling budget window in seconds: only restarts "
                             "inside the trailing window count against "
                             "--max_restarts (default: unbounded)")
    parser.add_argument("--preemption_grace", type=float, default=120.0,
                        help="seconds workers get after a SIGTERM (TPU "
                             "maintenance/preemption notice) to finish their "
                             "final checkpoint before being killed")
    parser.add_argument("user_script", type=str)
    parser.add_argument("user_args", nargs=argparse.REMAINDER)
    return parser.parse_args(args=args)


def build_rank_env(world_info: Dict[str, List[int]], node_rank: int,
                   local_index: int, master_addr: str,
                   master_port: int) -> Dict[str, str]:
    """Env block for one worker process (reference launch.py rank env).

    ``local_index`` is the position in the node's active slot list — ranks
    are dense 0..world-1 even under non-contiguous ``--include`` filters;
    the physical slot ids go to DSTPU_VISIBLE_SLOTS (the
    CUDA_VISIBLE_DEVICES analog).
    """
    hosts = list(world_info.keys())
    slots = world_info[hosts[node_rank]]
    global_rank = sum(len(world_info[h]) for h in hosts[:node_rank]) + local_index
    world_size = sum(len(s) for s in world_info.values())
    return {
        "RANK": str(global_rank),
        "LOCAL_RANK": str(local_index),
        "WORLD_SIZE": str(world_size),
        "LOCAL_SIZE": str(len(slots)),
        "NODE_RANK": str(node_rank),
        "MASTER_ADDR": master_addr,
        "MASTER_PORT": str(master_port),
        "DSTPU_VISIBLE_SLOTS": ",".join(map(str, slots)),
        COORDINATOR_ADDR_ENV: f"{master_addr}:{master_port}",
        NUM_PROCESSES_ENV: str(world_size),
        PROCESS_ID_ENV: str(global_rank),
    }


def terminate_process_tree(pid: int, timeout: float = 10.0):
    """SIGTERM the process group, escalate to SIGKILL (reference
    launch.py:119)."""
    try:
        pgid = os.getpgid(pid)
    except ProcessLookupError:
        return
    try:
        os.killpg(pgid, signal.SIGTERM)
    except ProcessLookupError:
        return
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            os.killpg(pgid, 0)
        except ProcessLookupError:
            return
        time.sleep(0.2)
    try:
        os.killpg(pgid, signal.SIGKILL)
    except ProcessLookupError:
        pass


def spawn_workers(args, world_info) -> List[subprocess.Popen]:
    hosts = list(world_info.keys())
    local_slots = world_info[hosts[args.node_rank]]
    procs = []
    for local_index, slot in enumerate(local_slots):
        env = os.environ.copy()
        env.update(build_rank_env(world_info, args.node_rank, local_index,
                                  args.master_addr, args.master_port))
        cmd = [sys.executable, "-u", args.user_script] + list(args.user_args)
        logger.info(f"launching rank {env['RANK']} (local {local_index}, "
                    f"slot {slot}): {' '.join(cmd)}")
        procs.append(subprocess.Popen(cmd, env=env,
                                      start_new_session=True))
    return procs


def monitor(procs: List[subprocess.Popen], poll_interval: float = 1.0) -> int:
    """Poll children; on any failure kill the remaining tree (reference
    launch.py main loop). Returns the first nonzero exit code, else 0."""
    alive = list(procs)
    while alive:
        time.sleep(poll_interval)
        for p in list(alive):
            rc = p.poll()
            if rc is None:
                continue
            alive.remove(p)
            if rc != 0:
                logger.error(f"worker pid {p.pid} failed with exit code {rc}; "
                             f"terminating remaining workers")
                for other in alive:
                    terminate_process_tree(other.pid)
                return rc
    return 0


def main(args=None):
    args = parse_args(args)
    world_info = decode_world_info(args.world_info)
    current: List[subprocess.Popen] = []

    def handle_int(sig, frame):
        """User abort: tear everything down immediately."""
        for p in current:
            terminate_process_tree(p.pid)
        sys.exit(128 + sig)

    def handle_term(sig, frame):
        """Preemption notice: forward SIGTERM to the workers so their
        PreemptionHandler writes a final checkpoint, wait out the grace
        window, then exit with the restartable preemption code if any
        worker finished its graceful shutdown — killing workers instantly
        here (the old behavior) truncated the final save mid-write and the
        supervising elastic agent never saw the restartable code."""
        from deepspeed_tpu.elasticity.preemption import PREEMPTION_EXIT_CODE

        for p in current:
            try:
                os.killpg(os.getpgid(p.pid), signal.SIGTERM)
            except (ProcessLookupError, PermissionError):
                pass
        deadline = time.time() + args.preemption_grace
        rcs = []
        for p in current:
            try:
                rcs.append(p.wait(timeout=max(0.0, deadline - time.time())))
            except subprocess.TimeoutExpired:
                logger.error(f"worker pid {p.pid} did not finish its final "
                             f"checkpoint within {args.preemption_grace}s; killing")
                terminate_process_tree(p.pid)
                rcs.append(128 + signal.SIGKILL)
        restartable = any(rc == PREEMPTION_EXIT_CODE for rc in rcs)
        sys.exit(PREEMPTION_EXIT_CODE if restartable else 128 + sig)

    signal.signal(signal.SIGINT, handle_int)
    signal.signal(signal.SIGTERM, handle_term)

    if args.elastic:
        from deepspeed_tpu.elasticity.elastic_agent import ElasticAgent
        from deepspeed_tpu.elasticity.preemption import PREEMPTION_EXIT_CODE

        def spawn_tracked():
            current[:] = spawn_workers(args, world_info)
            return current

        agent = ElasticAgent(spawn_fn=spawn_tracked, monitor_fn=monitor,
                             max_restarts=args.max_restarts,
                             restart_window_s=args.restart_window,
                             restartable_exit_codes=(PREEMPTION_EXIT_CODE,))
        rc = agent.run()
    else:
        current[:] = spawn_workers(args, world_info)
        rc = monitor(current)
    sys.exit(rc)


if __name__ == "__main__":
    main()
