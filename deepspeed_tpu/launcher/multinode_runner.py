"""Multinode launch backends (reference deepspeed/launcher/multinode_runner.py:
PDSHRunner:51, OpenMPIRunner:107, MPICHRunner:160, SlurmRunner:208) plus a
TPU-pod `gcloud` runner — command construction for fanning the per-node
launcher out to every host.
"""

from __future__ import annotations

import os
import shutil
import sys
from abc import ABC, abstractmethod
from shlex import quote
from typing import Dict, List

from deepspeed_tpu.launcher.constants import (
    GCLOUD_LAUNCHER,
    MPICH_LAUNCHER,
    OPENMPI_LAUNCHER,
    PDSH_LAUNCHER,
    SLURM_LAUNCHER,
)


class MultiNodeRunner(ABC):
    name = "abstract"

    def __init__(self, args, world_info_base64: str):
        self.args = args
        self.world_info_base64 = world_info_base64
        self.user_arguments = list(args.user_args)
        self.user_script = args.user_script

    @abstractmethod
    def backend_exists(self) -> bool:
        ...

    @abstractmethod
    def get_cmd(self, environment: Dict[str, str],
                active_resources: Dict[str, List[int]]) -> List[str]:
        ...

    def _launch_args(self, node_rank: int, master: str) -> List[str]:
        cmd = [sys.executable, "-u", "-m", "deepspeed_tpu.launcher.launch",
               f"--world_info={self.world_info_base64}",
               f"--node_rank={node_rank}",
               f"--master_addr={master}",
               f"--master_port={self.args.master_port}"]
        if getattr(self.args, "elastic_training", False):
            cmd += ["--elastic", f"--max_restarts={self.args.max_restarts}"]
        return cmd

    def _master(self, active_resources) -> str:
        return self.args.master_addr or next(iter(active_resources))

    def _rendezvous_env(self, active_resources) -> Dict[str, str]:
        """DSTPU_* rendezvous vars for launchers that exec the user script
        directly (no per-node launcher): the MPI/Slurm runtime provides the
        process id (comm.init_distributed's discovery), these provide the
        coordinator + world size."""
        master = self._master(active_resources)
        total = sum(len(v) for v in active_resources.values())
        from deepspeed_tpu.launcher.constants import (
            COORDINATOR_ADDR_ENV, NUM_PROCESSES_ENV)

        return {COORDINATOR_ADDR_ENV: f"{master}:{self.args.master_port}",
                NUM_PROCESSES_ENV: str(total)}


class PDSHRunner(MultiNodeRunner):
    name = PDSH_LAUNCHER

    def backend_exists(self) -> bool:
        return shutil.which("pdsh") is not None

    def get_cmd(self, environment, active_resources):
        environment["PDSH_RCMD_TYPE"] = "ssh"
        hosts = ",".join(active_resources.keys())
        master = self._master(active_resources)
        # %n expands to the pdsh node index == node rank (hosts are ordered)
        launch = [quote(a) for a in
                  self._launch_args(node_rank=0, master=master)]
        # node_rank must vary per host: pdsh runs the same command everywhere,
        # so the per-node launcher recovers its rank from the %h hostname
        launch = [a if not a.startswith("--node_rank=") else "--node_rank=%n"
                  for a in launch]
        extra = self.args.launcher_args.split() if self.args.launcher_args else []
        return (["pdsh", "-S", "-f", "1024", "-w", hosts] + extra + launch +
                [self.user_script] + [quote(a) for a in self.user_arguments])


class OpenMPIRunner(MultiNodeRunner):
    name = OPENMPI_LAUNCHER

    def backend_exists(self) -> bool:
        return shutil.which("ompi_info") is not None

    def get_cmd(self, environment, active_resources):
        total_process_count = sum(len(v) for v in active_resources.values())
        hosts = ",".join(f"{h}:{len(s)}" for h, s in active_resources.items())
        extra = self.args.launcher_args.split() if self.args.launcher_args else []
        # -x exports the rendezvous env; OMPI_COMM_WORLD_RANK supplies the
        # process id (comm.init_distributed discovery)
        export = []
        for k, v in self._rendezvous_env(active_resources).items():
            export += ["-x", f"{k}={v}"]
        return (["mpirun", "-n", f"{total_process_count}", "-host", hosts,
                 "--mca", "btl", "^openib"] + export + extra +
                [sys.executable, "-u", self.user_script] +
                [quote(a) for a in self.user_arguments])


class MPICHRunner(MultiNodeRunner):
    name = MPICH_LAUNCHER

    def backend_exists(self) -> bool:
        return shutil.which("mpirun") is not None

    def get_cmd(self, environment, active_resources):
        total = sum(len(v) for v in active_resources.values())
        per_host = len(next(iter(active_resources.values())))
        extra = self.args.launcher_args.split() if self.args.launcher_args else []
        export = []
        for k, v in self._rendezvous_env(active_resources).items():
            export += ["-genv", k, v]  # PMI_RANK supplies the process id
        return (["mpirun", "-n", f"{total}", "-ppn", f"{per_host}"] + export +
                extra + [sys.executable, "-u", self.user_script] +
                [quote(a) for a in self.user_arguments])


class SlurmRunner(MultiNodeRunner):
    name = SLURM_LAUNCHER

    def backend_exists(self) -> bool:
        return shutil.which("sinfo") is not None

    def get_cmd(self, environment, active_resources):
        if getattr(self.args, "include", "") or getattr(self.args, "exclude", ""):
            # srun has no slot-spec syntax (reference rejects these too)
            raise ValueError("--include/--exclude are not supported with the "
                             "slurm launcher; use srun --nodelist via "
                             "--launcher_args")
        total = sum(len(v) for v in active_resources.values())
        srun = ["srun", "-n", f"{total}"]
        env_kv = ",".join(f"{k}={v}" for k, v in
                          self._rendezvous_env(active_resources).items())
        srun += [f"--export=ALL,{env_kv}"]  # SLURM_PROCID supplies the rank
        if self.args.launcher_args:
            srun += self.args.launcher_args.split()
        return (srun + [sys.executable, "-u", self.user_script] +
                [quote(a) for a in self.user_arguments])


class GcloudTPURunner(MultiNodeRunner):
    """TPU-VM pods: `gcloud compute tpus tpu-vm ssh <pod> --worker=all`
    runs the same command on every pod worker; JAX discovers its process id
    from the TPU metadata, so no per-node rank plumbing is needed."""

    name = GCLOUD_LAUNCHER

    def backend_exists(self) -> bool:
        return shutil.which("gcloud") is not None

    def get_cmd(self, environment, active_resources):
        pod_name = next(iter(active_resources))
        inner = " ".join(
            [quote(sys.executable), "-u", quote(self.user_script)] +
            [quote(a) for a in self.user_arguments])
        extra = self.args.launcher_args.split() if self.args.launcher_args else []
        return (["gcloud", "compute", "tpus", "tpu-vm", "ssh", pod_name,
                 "--worker=all"] + extra + [f"--command={inner}"])


_RUNNERS = {
    PDSH_LAUNCHER: PDSHRunner,
    OPENMPI_LAUNCHER: OpenMPIRunner,
    MPICH_LAUNCHER: MPICHRunner,
    SLURM_LAUNCHER: SlurmRunner,
    GCLOUD_LAUNCHER: GcloudTPURunner,
}


def build_runner(args, world_info_base64: str, resource_pool) -> MultiNodeRunner:
    cls = _RUNNERS.get(args.launcher)
    if cls is None:
        raise ValueError(f"unknown launcher '{args.launcher}'; "
                         f"options: {sorted(_RUNNERS)}")
    return cls(args, world_info_base64)
