"""`dstpu` CLI — cluster launch entry point.

Reference analog: ``deepspeed/launcher/runner.py:377 main`` (the `deepspeed`
CLI): parse a hostfile, apply ``--include/--exclude`` node/slot filters,
pick a multinode runner (pdsh/mpi/slurm — plus a TPU-pod gcloud runner), and
exec the per-node launcher with the world info embedded in the environment.

TPU mapping: a "slot" is a worker process on a host (a TPU-VM worker drives
all of its local chips through one JAX process, so slots-per-host defaults
to 1); rendezvous is `jax.distributed.initialize` fed by
DSTPU_COORDINATOR_ADDRESS / DSTPU_NUM_PROCESSES / DSTPU_PROCESS_ID instead
of MASTER_ADDR + NCCL.
"""

from __future__ import annotations

import argparse
import base64
import json
import os
import re
import shlex
import subprocess
import sys
from collections import OrderedDict
from typing import Dict, List, Optional

from deepspeed_tpu.launcher.constants import (
    COORDINATOR_ADDR_ENV,
    DEFAULT_COORDINATOR_PORT,
    GCLOUD_LAUNCHER,
    MPICH_LAUNCHER,
    NUM_PROCESSES_ENV,
    OPENMPI_LAUNCHER,
    PDSH_LAUNCHER,
    SLURM_LAUNCHER,
)
from deepspeed_tpu.utils.logging import logger


def parse_args(args=None):
    parser = argparse.ArgumentParser(
        description="dstpu distributed launcher",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    parser.add_argument("-H", "--hostfile", type=str, default="/job/hostfile",
                        help="Hostfile path: lines of '<host> slots=<n>'")
    parser.add_argument("-i", "--include", type=str, default="",
                        help="Node/slot filter, e.g. 'host1@host2:0,2'")
    parser.add_argument("-e", "--exclude", type=str, default="",
                        help="Node/slot exclusion filter (mutually exclusive "
                             "with --include)")
    parser.add_argument("--num_nodes", type=int, default=-1,
                        help="Limit to first N nodes of the hostfile")
    parser.add_argument("--num_gpus", "--num_chips", type=int, default=-1,
                        dest="num_gpus", help="Worker processes per node")
    parser.add_argument("--master_addr", type=str, default="",
                        help="Coordinator address (default: first node)")
    parser.add_argument("--master_port", type=int,
                        default=DEFAULT_COORDINATOR_PORT,
                        help="Coordinator port")
    parser.add_argument("--launcher", type=str, default=PDSH_LAUNCHER,
                        choices=[PDSH_LAUNCHER, OPENMPI_LAUNCHER,
                                 MPICH_LAUNCHER, SLURM_LAUNCHER,
                                 GCLOUD_LAUNCHER],
                        help="Multinode launch backend")
    parser.add_argument("--launcher_args", type=str, default="",
                        help="Extra args for the launch backend")
    parser.add_argument("--force_multi", action="store_true",
                        help="Treat as multi-node even for one host")
    parser.add_argument("--autotuning", type=str, default="",
                        choices=["", "tune", "run"],
                        help="Run the autotuner before/instead of training")
    parser.add_argument("--autotuning_tuner", type=str, default="gridsearch",
                        choices=["gridsearch", "random", "model_based"],
                        help="Autotuning search algorithm")
    parser.add_argument("--autotuning_parallel", type=int, default=1,
                        help="Concurrent autotuning experiments")
    parser.add_argument("--elastic_training", action="store_true",
                        help="Supervise workers with restart-on-failure "
                             "(elastic agent)")
    parser.add_argument("--max_restarts", type=int, default=3,
                        help="Elastic: max worker restarts before giving up")
    parser.add_argument("user_script", type=str, help="User training script")
    parser.add_argument("user_args", nargs=argparse.REMAINDER,
                        help="Arguments for the user script")
    return parser.parse_args(args=args)


def fetch_hostfile(hostfile_path: str) -> Optional["OrderedDict[str, int]"]:
    """Parse '<hostname> slots=<n>' lines (reference fetch_hostfile:189).
    Returns None when the file does not exist (single-node mode)."""
    if not os.path.isfile(hostfile_path):
        return None
    resource_pool: "OrderedDict[str, int]" = OrderedDict()
    with open(hostfile_path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            try:
                hostname, slots = line.split()
                _, slot_count = slots.split("=")
                slot_count = int(slot_count)
            except ValueError:
                raise ValueError(f"Hostfile contains a bad entry: '{line}'")
            if hostname in resource_pool:
                raise ValueError(f"Hostfile contains multiple entries for "
                                 f"{hostname}")
            resource_pool[hostname] = slot_count
    if not resource_pool:
        raise ValueError(f"Hostfile '{hostfile_path}' is empty")
    return resource_pool


def _parse_hosts_string(spec: str) -> "OrderedDict[str, Optional[List[int]]]":
    """'h1@h2:0,2@h3:1-3' → {h1: None, h2: [0,2], h3: [1,2,3]}."""
    out: "OrderedDict[str, Optional[List[int]]]" = OrderedDict()
    for part in spec.split("@"):
        part = part.strip()
        if not part:
            continue
        if ":" in part:
            host, slots = part.split(":")
            slot_list: List[int] = []
            for piece in slots.split(","):
                if "-" in piece:
                    lo, hi = piece.split("-")
                    slot_list.extend(range(int(lo), int(hi) + 1))
                else:
                    slot_list.append(int(piece))
            out[host] = sorted(set(slot_list))
        else:
            out[part] = None
    return out


def parse_resource_filter(resource_pool: Dict[str, int], include_str: str = "",
                          exclude_str: str = "") -> "OrderedDict[str, List[int]]":
    """Apply --include/--exclude (reference parse_resource_filter:244).

    Returns {host: [slot ids]} of the active set.
    """
    if include_str and exclude_str:
        raise ValueError("--include and --exclude are mutually exclusive")
    full: "OrderedDict[str, List[int]]" = OrderedDict(
        (h, list(range(n))) for h, n in resource_pool.items())
    if not include_str and not exclude_str:
        return full
    if include_str:
        parsed = _parse_hosts_string(include_str)
        active: "OrderedDict[str, List[int]]" = OrderedDict()
        for host, slots in parsed.items():
            if host not in full:
                raise ValueError(f"--include host '{host}' not in hostfile")
            want = slots if slots is not None else full[host]
            bad = [s for s in want if s not in full[host]]
            if bad:
                raise ValueError(f"--include slots {bad} not available on "
                                 f"{host}")
            active[host] = want
        return active
    parsed = _parse_hosts_string(exclude_str)
    active = OrderedDict((h, list(s)) for h, s in full.items())
    for host, slots in parsed.items():
        if host not in active:
            raise ValueError(f"--exclude host '{host}' not in hostfile")
        if slots is None:
            del active[host]
        else:
            remaining = [s for s in active[host] if s not in slots]
            if remaining:
                active[host] = remaining
            else:
                del active[host]
    return active


def parse_inclusion_exclusion(resource_pool, inclusion, exclusion):
    """Reference-name alias."""
    return parse_resource_filter(resource_pool, include_str=inclusion or "",
                                 exclude_str=exclusion or "")


def encode_world_info(active_resources: Dict[str, List[int]]) -> str:
    """base64 world info handed to every node (reference runner.py world_info)."""
    return base64.urlsafe_b64encode(
        json.dumps(active_resources).encode()).decode()


def decode_world_info(encoded: str) -> Dict[str, List[int]]:
    return json.loads(base64.urlsafe_b64decode(encoded.encode()).decode())


def build_launch_command(args, active_resources: Dict[str, List[int]],
                         node_rank: int, host: str) -> List[str]:
    """Per-node `python -m deepspeed_tpu.launcher.launch ...` command."""
    world_info = encode_world_info(active_resources)
    master = args.master_addr or next(iter(active_resources))
    cmd = [sys.executable, "-u", "-m", "deepspeed_tpu.launcher.launch",
           f"--world_info={world_info}",
           f"--node_rank={node_rank}",
           f"--master_addr={master}",
           f"--master_port={args.master_port}"]
    if args.elastic_training:
        cmd += ["--elastic", f"--max_restarts={args.max_restarts}"]
    cmd += [args.user_script] + list(args.user_args)
    return cmd


def main(args=None):
    args = parse_args(args)
    resource_pool = fetch_hostfile(args.hostfile)

    if resource_pool is None:  # single node
        n = args.num_gpus if args.num_gpus > 0 else 1
        resource_pool = OrderedDict({"localhost": n})

    if args.num_nodes > 0:
        resource_pool = OrderedDict(
            list(resource_pool.items())[:args.num_nodes])
    if args.num_gpus > 0:
        resource_pool = OrderedDict(
            (h, args.num_gpus) for h in resource_pool)

    active = parse_resource_filter(resource_pool, args.include, args.exclude)

    if args.autotuning:
        from deepspeed_tpu.autotuning.cli import run_autotuning

        best_path = run_autotuning(args, active,
                                   tuner_type=args.autotuning_tuner,
                                   max_parallel=args.autotuning_parallel)
        if best_path is None:
            return 1
        if args.autotuning == "tune":
            return 0
        # --autotuning=run: launch the winning config on the FULL resource
        # pool through the normal path below
        os.environ["DSTPU_AUTOTUNING_CONFIG"] = best_path

    multi_node = args.force_multi or len(active) > 1
    if not multi_node:
        host = next(iter(active))
        cmd = build_launch_command(args, active, node_rank=0, host=host)
        logger.info(f"dstpu launch (single node): {' '.join(map(shlex.quote, cmd))}")
        result = subprocess.Popen(cmd, env=os.environ.copy())
        result.wait()
        if result.returncode != 0:
            sys.exit(result.returncode)
        return 0

    from deepspeed_tpu.launcher.multinode_runner import build_runner

    runner = build_runner(args, world_info_base64=encode_world_info(active),
                          resource_pool=active)
    if not runner.backend_exists():
        raise RuntimeError(f"launcher backend '{args.launcher}' is not "
                           f"installed on this system")
    env = os.environ.copy()
    cmd = runner.get_cmd(env, active)
    logger.info(f"dstpu launch ({args.launcher}): {' '.join(map(shlex.quote, cmd))}")
    result = subprocess.Popen(cmd, env=env)
    result.wait()
    if result.returncode != 0:
        sys.exit(result.returncode)
    return 0


if __name__ == "__main__":
    main()
