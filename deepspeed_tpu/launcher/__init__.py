from deepspeed_tpu.launcher.runner import (
    fetch_hostfile,
    parse_inclusion_exclusion,
    parse_resource_filter,
)
