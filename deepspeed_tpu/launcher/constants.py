"""Launcher constants (reference deepspeed/launcher/constants.py)."""

PDSH_LAUNCHER = "pdsh"
OPENMPI_LAUNCHER = "openmpi"
MPICH_LAUNCHER = "mpich"
SLURM_LAUNCHER = "slurm"
GCLOUD_LAUNCHER = "gcloud"  # TPU-VM pods: gcloud compute tpus tpu-vm ssh --worker=all

DSTPU_ENVIRONMENT_NAME = ".dstpu_env"
DSTPU_ENVIRONMENT_PATHS = [".", "~"]

# rendezvous env contract consumed by comm.init_distributed
COORDINATOR_ADDR_ENV = "DSTPU_COORDINATOR_ADDRESS"
NUM_PROCESSES_ENV = "DSTPU_NUM_PROCESSES"
PROCESS_ID_ENV = "DSTPU_PROCESS_ID"
DEFAULT_COORDINATOR_PORT = 7777
