"""Generalized causal decoder family — OPT / BLOOM / GPT-NeoX / GPT-J.

Reference analog: the per-architecture inference containers
(``deepspeed/module_inject/containers/{opt,bloom,gptneox,gptj}.py``) and
``model_implementations/``.  The reference keeps one fused CUDA transformer
and injects per-arch weight layouts into it; here the same economy comes
from ONE scanned decoder block parameterized by the architectural axes these
families actually differ on:

  * position encoding: learned table (OPT, with its +2 offset), ALiBi
    (BLOOM), rotary (GPT-NeoX partial / GPT-J partial-interleaved), or none
  * residual topology: sequential (GPT-2/OPT/BLOOM) vs parallel
    attention+MLP (GPT-NeoX dual-LN, GPT-J single-LN)
  * activation: gelu / relu
  * embedding LayerNorm (BLOOM)

Rotary always uses the interleaved convention of ``ops/rotary.py``; policies
that load rotate-half checkpoints (NeoX) permute projection columns at load
time (see inference/policies.py), so the compute path stays single-form.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.models.base import (cache_positions, cross_entropy_loss,
                                       gelu, layer_norm, layer_view, qdot)
from deepspeed_tpu.ops.attention import (alloc_kv_cache, cache_seq_len,
                                         cached_attention,
                                         multihead_attention,
                                         pool_block_size)
from deepspeed_tpu.ops.rotary import apply_rotary_pos_emb, rope_frequencies


def alibi_slopes(num_heads: int) -> np.ndarray:
    """Standard ALiBi slope schedule (power-of-two geometric; BLOOM paper)."""

    def pow2_slopes(n):
        start = 2.0 ** (-(2.0 ** -(np.log2(n) - 3)))
        return start * (start ** np.arange(n))

    if np.log2(num_heads).is_integer():
        return pow2_slopes(num_heads)
    closest = 2 ** int(np.floor(np.log2(num_heads)))
    extra = pow2_slopes(2 * closest)[0::2][:num_heads - closest]
    return np.concatenate([pow2_slopes(closest), extra])


@dataclasses.dataclass
class DecoderConfig:
    vocab_size: int = 50272
    max_seq_len: int = 2048
    num_layers: int = 12
    hidden_size: int = 768
    num_heads: int = 12
    mlp_dim: int = 3072
    eps: float = 1e-5
    # positional scheme
    pos_emb: str = "learned"          # "learned" | "none"
    pos_offset: int = 0               # OPT stores positions at index+2
    alibi: bool = False               # BLOOM
    rotary_dim: int = 0               # 0 = no rotary; NeoX/GPT-J partial
    rope_theta: float = 10000.0       # NeoX rotary_emb_base
    # block topology
    parallel_residual: bool = False   # NeoX / GPT-J
    dual_ln: bool = True              # NeoX two LNs; GPT-J single
    post_ln: bool = False             # OPT do_layer_norm_before=False
    final_ln: bool = True             # opt-350m has no final LayerNorm
    activation: str = "gelu"          # "gelu" (tanh) | "gelu_exact" | "relu"
    embedding_ln: bool = False        # BLOOM word_embeddings_layernorm
    tie_embeddings: bool = False
    # OPT word_embed_proj_dim != hidden (opt-350m): embeddings live in a
    # smaller space with project_in/project_out linears around the stack
    word_embed_dim: int = 0           # 0 = same as hidden_size
    # attention-score scale override (GPT-Neo scales by 1.0, not dh^-0.5)
    qk_scale: Optional[float] = None
    # GPT-Neo local (sliding-window causal) attention on marked layers
    local_attn_window: int = 0
    attn_layer_pattern: tuple = ()    # per-layer: "global" | "local"

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @property
    def has_position_table(self) -> bool:
        """False only for pure-ALiBi models (BLOOM): they extrapolate to any
        length.  Learned tables AND rotary cos/sin tables are sized to
        max_seq_len, so those keep the inference engine's guard."""
        return self.pos_emb == "learned" or self.rotary_dim > 0

    # ---- family presets (HF config names in parens)
    @classmethod
    def opt(cls, **kw):
        kw.setdefault("activation", "relu")
        kw.setdefault("pos_offset", 2)
        kw.setdefault("tie_embeddings", True)
        return cls(**kw)

    @classmethod
    def gpt_neo(cls, **kw):
        kw.setdefault("qk_scale", 1.0)        # HF GPTNeo never scales QK^T
        kw.setdefault("local_attn_window", 256)
        kw.setdefault("tie_embeddings", True)
        return cls(**kw)

    @classmethod
    def bloom(cls, **kw):
        kw.setdefault("pos_emb", "none")
        kw.setdefault("alibi", True)
        kw.setdefault("embedding_ln", True)
        kw.setdefault("tie_embeddings", True)
        return cls(**kw)

    @classmethod
    def gpt_neox(cls, **kw):
        kw.setdefault("pos_emb", "none")
        kw.setdefault("parallel_residual", True)
        kw.setdefault("dual_ln", True)
        return cls(**kw)

    @classmethod
    def gptj(cls, **kw):
        kw.setdefault("pos_emb", "none")
        kw.setdefault("parallel_residual", True)
        kw.setdefault("dual_ln", False)
        return cls(**kw)


class DecoderModel:
    """Causal-LM ModelSpec. batch = {"input_ids": [B,T], "labels": [B,T]}."""

    supports_weight_quant = True   # weight matmuls go through base.qdot

    def __init__(self, config: DecoderConfig, compute_dtype=jnp.bfloat16,
                 remat: bool = False, remat_policy: Optional[str] = None,
                 decode_unroll: int = 1):
        self.config = config
        self.compute_dtype = compute_dtype
        self.remat = remat
        self.remat_policy = remat_policy
        # see GPT2Model: layer-scan unroll for single-token decode steps
        self.decode_unroll = decode_unroll
        c = config
        assert c.activation in ("gelu", "gelu_exact", "relu"), c.activation
        assert c.pos_emb in ("learned", "none"), c.pos_emb
        assert not (c.post_ln and c.parallel_residual), \
            "post_ln is a sequential-residual (OPT) topology"
        if c.alibi:
            self._alibi = jnp.asarray(alibi_slopes(c.num_heads), jnp.float32)
        if c.rotary_dim > 0:
            self._rope_cos, self._rope_sin = rope_frequencies(
                c.rotary_dim, c.max_seq_len, theta=c.rope_theta)
        self._local_flags = None
        if c.attn_layer_pattern:
            assert c.local_attn_window > 0, \
                "attn_layer_pattern needs local_attn_window"
            assert len(c.attn_layer_pattern) == c.num_layers
            self._local_flags = jnp.asarray(
                [p == "local" for p in c.attn_layer_pattern], bool)

    def _act(self, x):
        if self.config.activation == "gelu":
            return gelu(x)                       # tanh approximation
        if self.config.activation == "gelu_exact":
            return jax.nn.gelu(x, approximate=False)
        return jax.nn.relu(x)

    # ------------------------------------------------------------------- init
    def init(self, rng):
        c = self.config
        k = jax.random.split(rng, 9)
        d, l, m, v = c.hidden_size, c.num_layers, c.mlp_dim, c.vocab_size
        init = jax.nn.initializers.normal(0.02)
        blocks = {
            "ln1_scale": jnp.ones((l, d)), "ln1_bias": jnp.zeros((l, d)),
            "qkv_w": init(k[2], (l, d, 3 * d), jnp.float32),
            "qkv_b": jnp.zeros((l, 3 * d)),
            "attn_out_w": init(k[3], (l, d, d), jnp.float32) / (2 * l) ** 0.5,
            "attn_out_b": jnp.zeros((l, d)),
            "mlp_fc_w": init(k[4], (l, d, m), jnp.float32),
            "mlp_fc_b": jnp.zeros((l, m)),
            "mlp_out_w": init(k[5], (l, m, d), jnp.float32) / (2 * l) ** 0.5,
            "mlp_out_b": jnp.zeros((l, d)),
        }
        if c.dual_ln or not c.parallel_residual:
            blocks["ln2_scale"] = jnp.ones((l, d))
            blocks["ln2_bias"] = jnp.zeros((l, d))
        we = c.word_embed_dim or d
        params = {
            "wte": init(k[0], (v, we), jnp.float32),
            "blocks": blocks,
        }
        if c.final_ln:
            params["ln_f_scale"] = jnp.ones((d,))
            params["ln_f_bias"] = jnp.zeros((d,))
        if we != d:
            params["project_in"] = init(k[7], (we, d), jnp.float32)
            params["project_out"] = init(k[8], (d, we), jnp.float32)
        if c.pos_emb == "learned":
            params["wpe"] = init(k[1], (c.max_seq_len + c.pos_offset, d),
                                 jnp.float32)
        if c.embedding_ln:
            params["emb_ln_scale"] = jnp.ones((d,))
            params["emb_ln_bias"] = jnp.zeros((d,))
        if not c.tie_embeddings:
            params["lm_head"] = init(k[6], (d, v), jnp.float32)
        return params

    def logical_axes(self):
        c = self.config
        blocks = {
            "ln1_scale": ("layer", "hidden"), "ln1_bias": ("layer", "hidden"),
            "qkv_w": ("layer", "hidden", "heads"),
            "qkv_b": ("layer", "heads"),
            "attn_out_w": ("layer", "heads", "hidden"),
            "attn_out_b": ("layer", "hidden"),
            "mlp_fc_w": ("layer", "hidden", "mlp"),
            "mlp_fc_b": ("layer", "mlp"),
            "mlp_out_w": ("layer", "mlp", "hidden"),
            "mlp_out_b": ("layer", "hidden"),
        }
        if c.dual_ln or not c.parallel_residual:
            blocks["ln2_scale"] = ("layer", "hidden")
            blocks["ln2_bias"] = ("layer", "hidden")
        axes = {"wte": ("vocab_in", "hidden"), "blocks": blocks}
        if c.final_ln:
            axes["ln_f_scale"] = ("hidden",)
            axes["ln_f_bias"] = ("hidden",)
        if (c.word_embed_dim or c.hidden_size) != c.hidden_size:
            axes["project_in"] = (None, "hidden")
            axes["project_out"] = ("hidden", None)
        if c.pos_emb == "learned":
            axes["wpe"] = ("seq", "hidden")
        if c.embedding_ln:
            axes["emb_ln_scale"] = ("hidden",)
            axes["emb_ln_bias"] = ("hidden",)
        if not c.tie_embeddings:
            axes["lm_head"] = ("hidden", "vocab")
        return axes

    # ------------------------------------------------------------------ block
    def _attn_bias(self, t, s):
        if not self.config.alibi:
            return None
        # slopes * key position; shift-invariant per softmax row
        return (self._alibi[:, None, None] *
                jnp.arange(s, dtype=jnp.float32)[None, None, :]) * \
            jnp.ones((1, t, 1), jnp.float32)

    def _qkv(self, x, blk, pos_offset):
        c = self.config
        b, t, d = x.shape
        h, dh = c.num_heads, c.head_dim
        qkv = qdot("btd,de->bte", x, blk["qkv_w"]) + \
            blk["qkv_b"].astype(x.dtype)
        q, k_, v_ = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(b, t, h, dh)
        k_ = k_.reshape(b, t, h, dh)
        v_ = v_.reshape(b, t, h, dh)
        if c.rotary_dim > 0:
            rq, pq = q[..., :c.rotary_dim], q[..., c.rotary_dim:]
            rk, pk = k_[..., :c.rotary_dim], k_[..., c.rotary_dim:]
            rq = apply_rotary_pos_emb(rq, self._rope_cos, self._rope_sin,
                                      position_offset=pos_offset)
            rk = apply_rotary_pos_emb(rk, self._rope_cos, self._rope_sin,
                                      position_offset=pos_offset)
            q = jnp.concatenate([rq, pq], axis=-1)
            k_ = jnp.concatenate([rk, pk], axis=-1)
        return q, k_, v_

    def _block_impl(self, x, blk, cache, local_flag=None):
        # cache = (k_full, v_full, layer, idx): full stacked head-major
        # [L,B,H,S,Dh] caches, updated with per-token slice writes only
        # (see ops/attention.decode_attention docstring). Weight matmuls go
        # through qdot: int8 weights stream into the matmul, scale on the
        # output.
        c = self.config
        b, t, d = x.shape
        idx = cache[3] if cache is not None else 0

        y1 = x if c.post_ln else layer_norm(x, blk["ln1_scale"],
                                            blk["ln1_bias"], c.eps)
        q, k_, v_ = self._qkv(y1, blk, idx)
        if cache is None:
            mask = None
            if local_flag is not None:
                # sliding-window causal: key allowed iff q_pos-k_pos < window
                # (on layers whose pattern says "local"; others stay global)
                delta = jnp.arange(t)[:, None] - jnp.arange(t)[None, :]
                mask = (jnp.logical_not(local_flag) |
                        (delta < c.local_attn_window))[None, None]
            attn = multihead_attention(q, k_, v_, causal=True, mask=mask,
                                       bias=self._attn_bias(t, t),
                                       scale=c.qk_scale)
            kc = vc = None
        else:
            kc, vc, layer, _, *rest = cache
            bt = rest[0] if rest else None
            if bt is not None:
                # block-paged pool (ISSUE 6): the attended view is the
                # gathered block chain [B, MB * bs, ...], not the pool's
                # physical row count
                s_max = bt.shape[1] * pool_block_size(kc, c.head_dim)
            else:
                s_max = cache_seq_len(kc, c.head_dim)
            dec_bias = None
            if c.alibi:
                dec_bias = self._alibi[:, None] * jnp.arange(
                    s_max, dtype=jnp.float32)[None, :]
            window = None
            if local_flag is not None:
                window = jnp.where(local_flag, c.local_attn_window, s_max + 1)
            attn, kc, vc = cached_attention(q, kc, vc, k_, v_, layer, idx,
                                            bias=dec_bias, scale=c.qk_scale,
                                            window=window, block_table=bt)
        attn = attn.reshape(b, t, d)
        attn_out = qdot("btd,de->bte", attn, blk["attn_out_w"]) + \
            blk["attn_out_b"].astype(x.dtype)

        if c.parallel_residual:
            y2 = layer_norm(x, blk["ln2_scale"], blk["ln2_bias"], c.eps) \
                if c.dual_ln else y1
            mid = self._act(qdot("btd,dm->btm", y2, blk["mlp_fc_w"]) +
                            blk["mlp_fc_b"].astype(x.dtype))
            mlp_out = qdot("btm,md->btd", mid, blk["mlp_out_w"]) + \
                blk["mlp_out_b"].astype(x.dtype)
            x = x + attn_out + mlp_out
        else:
            x = x + attn_out
            if c.post_ln:      # OPT do_layer_norm_before=False: LN after add
                x = layer_norm(x, blk["ln1_scale"], blk["ln1_bias"], c.eps)
            y2 = x if c.post_ln else layer_norm(x, blk["ln2_scale"],
                                                blk["ln2_bias"], c.eps)
            mid = self._act(qdot("btd,dm->btm", y2, blk["mlp_fc_w"]) +
                            blk["mlp_fc_b"].astype(x.dtype))
            x = x + qdot("btm,md->btd", mid, blk["mlp_out_w"]) + \
                blk["mlp_out_b"].astype(x.dtype)
            if c.post_ln:
                x = layer_norm(x, blk["ln2_scale"], blk["ln2_bias"], c.eps)
        return x, kc, vc

    # ---------------------------------------------------------------- forward
    def _embed(self, params, input_ids, idx):
        c = self.config
        b, t = input_ids.shape
        x = params["wte"].astype(self.compute_dtype)[input_ids]
        if "project_in" in params:
            x = x @ params["project_in"].astype(x.dtype)
        if c.pos_emb == "learned":
            # idx may be a per-slot [B] vector (continuous batching)
            pos = cache_positions(idx, t) + c.pos_offset
            pe = params["wpe"].astype(self.compute_dtype)[pos]
            x = x + (pe if pos.ndim == 2 else pe[None])
        if c.embedding_ln:
            x = layer_norm(x, params["emb_ln_scale"], params["emb_ln_bias"],
                           c.eps)
        return x

    def forward_hidden(self, params, input_ids, *, rngs=None, train=False):
        c = self.config
        x = self._embed(params, input_ids, jnp.zeros((), jnp.int32))

        def block_fn(x, blk, flag):
            return self._block_impl(x, blk, None, local_flag=flag)[0]

        if self.remat:
            from deepspeed_tpu.runtime.activation_checkpointing import (
                checkpoint_policy)

            block_fn = jax.checkpoint(block_fn,
                                      policy=checkpoint_policy(self.remat_policy))

        if self._local_flags is not None:
            def scan_body(x, layer_in):
                blk, flag = layer_in
                return block_fn(x, blk, flag), None

            x, _ = jax.lax.scan(scan_body, x,
                                (params["blocks"], self._local_flags))
        else:
            def scan_body(x, blk):
                return block_fn(x, blk, None), None

            x, _ = jax.lax.scan(scan_body, x, params["blocks"])
        if c.final_ln:
            x = layer_norm(x, params["ln_f_scale"], params["ln_f_bias"],
                           c.eps)
        return x

    def logits(self, params, hidden):
        if "project_out" in params:
            hidden = hidden @ params["project_out"].astype(hidden.dtype)
        if self.config.tie_embeddings:
            out = jnp.einsum("btd,vd->btv", hidden,
                             params["wte"].astype(hidden.dtype))
        else:
            out = jnp.einsum("btd,dv->btv", hidden,
                             params["lm_head"].astype(hidden.dtype))
        if "lm_head_bias" in params:   # GPT-J ships a biased lm head
            out = out + params["lm_head_bias"].astype(out.dtype)
        return out

    def apply(self, params, batch, *, rngs=None, train=False):
        hidden = self.forward_hidden(params, batch["input_ids"], rngs=rngs,
                                     train=train)
        logits = self.logits(params, hidden)
        loss, n = cross_entropy_loss(logits, batch["labels"])
        return loss, {"loss": loss, "ntokens": n}

    # --------------------------------------------------------- inference path
    def init_cache(self, batch_size: int, max_len: int, dtype=None):
        # head-major, token-pair packed for Dh < 128 — except for models
        # whose decode always needs the einsum path (ALiBi bias, per-layer
        # local windows), which keep the plain [L, B, H, S, Dh] form so
        # every step isn't paying an unpack view (ops/attention.kv_pack_factor)
        c = self.config
        dtype = dtype or self.compute_dtype
        packed = not (c.alibi or c.attn_layer_pattern)
        return {"k": alloc_kv_cache(c.num_layers, batch_size, c.num_heads,
                                    max_len, c.head_dim, dtype,
                                    packed=packed),
                "v": alloc_kv_cache(c.num_layers, batch_size, c.num_heads,
                                    max_len, c.head_dim, dtype,
                                    packed=packed),
                "index": jnp.zeros((), jnp.int32)}

    def forward_with_cache(self, params, input_ids, cache):
        c = self.config
        idx = cache["index"]
        bt = cache.get("block_table")
        x = self._embed(params, input_ids, idx)
        flags = self._local_flags
        if flags is None:
            flags = jnp.zeros((c.num_layers,), bool)
            use_flags = False
        else:
            use_flags = True

        def scan_body(carry, flag):
            x, kc, vc, layer = carry
            # counter-indexed blocks: layer_view keeps int8 weight dicts
            # whole so qdot's kernel DMA-slices the layer in-kernel (a
            # host-side int8 operand slice copies the weight every step)
            blk = layer_view(params["blocks"], layer)
            x, kc, vc = self._block_impl(
                x, blk, (kc, vc, layer, idx, bt),
                local_flag=flag if use_flags else None)
            return (x, kc, vc, layer + 1), None

        t = input_ids.shape[1]
        (x, k_new, v_new, _), _ = jax.lax.scan(
            scan_body,
            (x, cache["k"], cache["v"], jnp.zeros((), jnp.int32)),
            flags, unroll=self.decode_unroll if t == 1 else 1)
        if c.final_ln:
            x = layer_norm(x, params["ln_f_scale"], params["ln_f_bias"],
                           c.eps)
        out = {"k": k_new, "v": v_new, "index": idx + input_ids.shape[1]}
        if bt is not None:
            out["block_table"] = bt
        return self.logits(params, x), out

    def flops_per_token(self) -> float:
        c = self.config
        n_params = (c.vocab_size * c.hidden_size +
                    c.num_layers * (4 * c.hidden_size ** 2 +
                                    2 * c.hidden_size * c.mlp_dim))
        return 6.0 * n_params + 12 * c.num_layers * c.hidden_size * c.max_seq_len
