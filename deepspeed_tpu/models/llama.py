"""LLaMA model family, TPU-first.

The reference serves LLaMA through the AutoTP path (no dedicated container in
the v0.9.2 snapshot — SURVEY §2.5); here it is a first-class model: RMSNorm,
RoPE, SwiGLU, grouped-query attention, scan-stacked blocks, logical axes for
TP/EP, optional remat. Flagship config for the BASELINE ladder is llama_7b.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from deepspeed_tpu.models.base import ATTN_IMPLS, cross_entropy_loss, layer_view, qdot, rms_norm, sp_attention  # noqa: E501
from deepspeed_tpu.ops.attention import alloc_kv_cache, cached_attention, multihead_attention
from deepspeed_tpu.ops.rotary import apply_rotary_pos_emb, rope_frequencies


@dataclasses.dataclass
class LlamaConfig:
    vocab_size: int = 32000
    max_seq_len: int = 2048
    num_layers: int = 32
    hidden_size: int = 4096
    num_heads: int = 32
    num_kv_heads: Optional[int] = None  # GQA; None => MHA
    intermediate_size: Optional[int] = None
    rope_theta: float = 10000.0
    eps: float = 1e-5

    def __post_init__(self):
        if self.num_kv_heads is None:
            self.num_kv_heads = self.num_heads
        if self.intermediate_size is None:
            # LLaMA: 2/3 * 4h rounded to multiple of 256
            inter = int(2 * (4 * self.hidden_size) / 3)
            self.intermediate_size = 256 * ((inter + 255) // 256)

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @classmethod
    def llama_7b(cls, **kw):
        return cls(num_layers=32, hidden_size=4096, num_heads=32, **kw)

    @classmethod
    def llama_13b(cls, **kw):
        return cls(num_layers=40, hidden_size=5120, num_heads=40, **kw)

    @classmethod
    def tiny(cls, **kw):
        kw.setdefault("vocab_size", 512)
        kw.setdefault("max_seq_len", 128)
        kw.setdefault("num_kv_heads", 2)
        return cls(num_layers=2, hidden_size=64, num_heads=4,
                   intermediate_size=128, **kw)


class LlamaModel:
    """Causal-LM ModelSpec: batch = {"input_ids": [B,T], "labels": [B,T]}."""

    supports_weight_quant = True   # weight matmuls go through base.qdot

    def __init__(self, config: LlamaConfig, compute_dtype=jnp.bfloat16,
                 remat: bool = False, remat_policy: Optional[str] = None,
                 attn_impl: str = "dense", decode_unroll: int = 1):
        self.config = config
        self.compute_dtype = compute_dtype
        self.remat = remat
        self.remat_policy = remat_policy
        assert attn_impl in ATTN_IMPLS, attn_impl
        self.attn_impl = attn_impl
        # see GPT2Model: layer-scan unroll for single-token decode steps
        self.decode_unroll = decode_unroll

    def init(self, rng):
        c = self.config
        k = jax.random.split(rng, 8)
        d, l, m, v = c.hidden_size, c.num_layers, c.intermediate_size, c.vocab_size
        hq, hkv, dh = c.num_heads, c.num_kv_heads, c.head_dim
        init = jax.nn.initializers.normal(0.02)
        out_scale = (2 * l) ** -0.5
        return {
            "embed": init(k[0], (v, d), jnp.float32),
            "blocks": {
                "attn_norm": jnp.ones((l, d)),
                "wq": init(k[1], (l, d, hq * dh), jnp.float32),
                "wk": init(k[2], (l, d, hkv * dh), jnp.float32),
                "wv": init(k[3], (l, d, hkv * dh), jnp.float32),
                "wo": init(k[4], (l, hq * dh, d), jnp.float32) * out_scale,
                "mlp_norm": jnp.ones((l, d)),
                "w_gate": init(k[5], (l, d, m), jnp.float32),
                "w_up": init(k[6], (l, d, m), jnp.float32),
                "w_down": init(k[7], (l, m, d), jnp.float32) * out_scale,
            },
            "final_norm": jnp.ones((d,)),
            "lm_head": init(jax.random.fold_in(k[0], 1), (d, v), jnp.float32),
        }

    def logical_axes(self):
        return {
            "embed": ("vocab_in", "hidden"),
            "blocks": {
                "attn_norm": ("layer", "hidden"),
                "wq": ("layer", "hidden", "heads"),
                "wk": ("layer", "hidden", "kv_heads"),
                "wv": ("layer", "hidden", "kv_heads"),
                "wo": ("layer", "heads", "hidden"),
                "mlp_norm": ("layer", "hidden"),
                "w_gate": ("layer", "hidden", "mlp"),
                "w_up": ("layer", "hidden", "mlp"),
                "w_down": ("layer", "mlp", "hidden"),
            },
            "final_norm": ("hidden",),
            "lm_head": ("hidden", "vocab"),
        }

    def _block_impl(self, x, blk, cos, sin, train: bool, cache):
        """One LLaMA block; with ``cache=(k_full, v_full, layer, idx)``
        attention runs against the GQA KV cache (shared implementation for
        train + serving). Only the new token's slice of the full stacked
        head-major [L, B, Hkv, S, Dh] cache is written — see
        ops/attention.decode_attention."""
        c = self.config
        b, t, d = x.shape
        hq, hkv, dh = c.num_heads, c.num_kv_heads, c.head_dim
        idx = cache[3] if cache is not None else 0
        y = rms_norm(x, blk["attn_norm"], c.eps)
        # qdot streams int8 weights straight into the matmul (scale folded
        # into the output) — no dequantized bf16 tiles in HBM
        q = qdot("btd,de->bte", y, blk["wq"]).reshape(b, t, hq, dh)
        k_ = qdot("btd,de->bte", y, blk["wk"]).reshape(b, t, hkv, dh)
        v_ = qdot("btd,de->bte", y, blk["wv"]).reshape(b, t, hkv, dh)
        q = apply_rotary_pos_emb(q, cos, sin, position_offset=idx)
        k_ = apply_rotary_pos_emb(k_, cos, sin, position_offset=idx)
        if cache is None:
            if hkv != hq:  # GQA: repeat kv heads
                rep = hq // hkv
                k_ = jnp.repeat(k_, rep, axis=2)
                v_ = jnp.repeat(v_, rep, axis=2)
            if self.attn_impl != "dense":
                attn = sp_attention(self.attn_impl, q, k_, v_)
            else:
                attn = multihead_attention(q, k_, v_, causal=True)
            kc = vc = None
        else:
            kc, vc, layer, idx, *rest = cache
            attn, kc, vc = cached_attention(
                q, kc, vc, k_, v_, layer, idx,
                block_table=rest[0] if rest else None)
        x = x + qdot("bte,ed->btd", attn.reshape(b, t, hq * dh), blk["wo"])
        y = rms_norm(x, blk["mlp_norm"], c.eps)
        gate = jax.nn.silu(qdot("btd,dm->btm", y, blk["w_gate"]))
        up = qdot("btd,dm->btm", y, blk["w_up"])
        x = x + qdot("btm,md->btd", gate * up, blk["w_down"])
        return x, kc, vc

    def _block(self, x, blk, cos, sin, train: bool):
        return self._block_impl(x, blk, cos, sin, train, None)[0]

    def forward_hidden(self, params, input_ids, *, rngs=None, train: bool = False):
        c = self.config
        b, t = input_ids.shape
        x = params["embed"].astype(self.compute_dtype)[input_ids]
        cos, sin = rope_frequencies(c.head_dim, c.max_seq_len, c.rope_theta)

        block_fn = self._block
        if self.remat:
            from deepspeed_tpu.runtime.activation_checkpointing import checkpoint_policy

            block_fn = jax.checkpoint(block_fn, policy=checkpoint_policy(self.remat_policy),
                                      static_argnums=(4,))

        def scan_body(x, layer_params):
            return block_fn(x, layer_params, cos, sin, train), None

        x, _ = jax.lax.scan(scan_body, x, params["blocks"])
        return rms_norm(x, params["final_norm"], c.eps)

    def logits(self, params, hidden):
        return jnp.einsum("btd,dv->btv", hidden, params["lm_head"].astype(hidden.dtype))

    def apply(self, params, batch, *, rngs=None, train: bool = False):
        hidden = self.forward_hidden(params, batch["input_ids"], rngs=rngs, train=train)
        logits = self.logits(params, hidden)
        loss, n = cross_entropy_loss(logits, batch["labels"])
        return loss, {"loss": loss, "ntokens": n}

    # --------------------------------------------------------- inference path
    def init_cache(self, batch_size: int, max_len: int, dtype=None):
        """Static-shape GQA KV cache — stores num_kv_heads only (the grouped
        query repeat happens inside decode_attention). Head-major,
        token-pair packed for Dh < 128 — see ops/attention.kv_pack_factor."""
        c = self.config
        dtype = dtype or self.compute_dtype
        return {"k": alloc_kv_cache(c.num_layers, batch_size,
                                    c.num_kv_heads, max_len, c.head_dim,
                                    dtype),
                "v": alloc_kv_cache(c.num_layers, batch_size,
                                    c.num_kv_heads, max_len, c.head_dim,
                                    dtype),
                "index": jnp.zeros((), jnp.int32)}

    def _block_cached(self, x, blk, kc, vc, layer, idx, cos, sin, bt):
        return self._block_impl(x, blk, cos, sin, False,
                                (kc, vc, layer, idx, bt))

    def forward_with_cache(self, params, input_ids, cache):
        """Prefill (T>1) or decode (T=1) against the KV cache. Stacked caches
        ride the scan carry with per-layer slice writes (see GPT2Model).
        ``cache["index"]`` may be a scalar or a per-slot [B] vector
        (continuous batching): RoPE then rotates each row at its own
        position (ops/rotary vector offset) and cached_attention masks
        each row's own prefix."""
        c = self.config
        b, t = input_ids.shape
        idx = cache["index"]
        bt = cache.get("block_table")
        x = params["embed"].astype(self.compute_dtype)[input_ids]
        cos, sin = rope_frequencies(c.head_dim, c.max_seq_len, c.rope_theta)

        def scan_body(carry, _):
            x, kc, vc, layer = carry
            # blocks are indexed by the carried counter (not scan xs):
            # layer_view keeps int8 weight dicts WHOLE so qdot's kernel
            # DMA-slices the layer in-kernel instead of paying a full
            # per-step operand copy (models/base.layer_view)
            blk = layer_view(params["blocks"], layer)
            x, kc, vc = self._block_cached(x, blk, kc, vc, layer, idx,
                                           cos, sin, bt)
            return (x, kc, vc, layer + 1), None

        (x, k_new, v_new, _), _ = jax.lax.scan(
            scan_body,
            (x, cache["k"], cache["v"], jnp.zeros((), jnp.int32)),
            None, length=c.num_layers,
            unroll=self.decode_unroll if t == 1 else 1)
        hidden = rms_norm(x, params["final_norm"], c.eps)
        logits = self.logits(params, hidden)
        out = {"k": k_new, "v": v_new, "index": idx + t}
        if bt is not None:
            out["block_table"] = bt
        return logits, out

    def flops_per_token(self) -> float:
        c = self.config
        n_params = (c.vocab_size * c.hidden_size * 2 + c.num_layers * (
            c.hidden_size * c.head_dim * (c.num_heads + 2 * c.num_kv_heads) +
            c.num_heads * c.head_dim * c.hidden_size +
            3 * c.hidden_size * c.intermediate_size))
        attn = 12 * c.num_layers * c.hidden_size * c.max_seq_len
        return 6.0 * n_params + attn
