"""Diffusion model family (UNet2DCondition + AutoencoderKL), TPU-first.

Reference analog: the DeepSpeed-Diffusers serving pillar — ``csrc/spatial``
(fused bias-add / NHWC channels-last kernels for diffusion),
``module_inject/containers/{unet,vae}.py`` and
``model_implementations/diffusers/{unet,vae}.py`` (module wrappers whose
main job is CUDA-graph capture + channels-last).  On TPU:

  * NHWC is the native convolution layout (the reference's
    ``spatial_inference`` ops exist to coerce torch into channels-last;
    here every tensor is born [B, H, W, C] and conv kernels are HWIO).
  * bias+silu+groupnorm fusion is XLA's job; there is nothing to
    hand-fuse.
  * the CUDA-graph machinery maps to jit: the denoise step is one compiled
    program (see inference/diffusion.py).

Layouts follow diffusers' ``UNet2DConditionModel`` / ``AutoencoderKL``
(SD-1.x lineage: conv proj_in/out in attention blocks, GEGLU feed-forward,
bias-free q/k/v cross-attention) so checkpoints map 1:1 — see
``inference/diffusion.py convert_diffusers_unet/vae`` for the name map.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.models.base import layer_norm
from deepspeed_tpu.ops.attention import multihead_attention

# --------------------------------------------------------------- primitives


def conv2d(x, w, b=None, *, stride=1, padding=1):
    """NHWC conv with HWIO kernel (TPU-native layouts)."""
    out = jax.lax.conv_general_dilated(
        x, w.astype(x.dtype), window_strides=(stride, stride),
        padding=[(padding, padding), (padding, padding)],
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    if b is not None:
        out = out + b.astype(out.dtype)
    return out


def group_norm(x, scale, bias, *, groups=32, eps=1e-6):
    """GroupNorm over the channel (last) dim of an NHWC tensor."""
    b, h, w, c = x.shape
    g = min(groups, c)
    xf = x.astype(jnp.float32).reshape(b, h, w, g, c // g)
    mean = xf.mean(axis=(1, 2, 4), keepdims=True)
    var = xf.var(axis=(1, 2, 4), keepdims=True)
    xf = (xf - mean) * jax.lax.rsqrt(var + eps)
    xf = xf.reshape(b, h, w, c)
    return (xf * scale.astype(jnp.float32) +
            bias.astype(jnp.float32)).astype(x.dtype)


def timestep_embedding(t, dim, *, max_period=10000.0):
    """Sinusoidal timestep embedding (diffusers Timesteps with
    flip_sin_to_cos=True, downscale_freq_shift=0): [cos | sin]."""
    half = dim // 2
    freqs = jnp.exp(-math.log(max_period) *
                    jnp.arange(half, dtype=jnp.float32) / half)
    args = t.astype(jnp.float32)[:, None] * freqs[None]
    return jnp.concatenate([jnp.cos(args), jnp.sin(args)], axis=-1)


def _linear(x, p):
    return x @ p["w"].astype(x.dtype) + p["b"].astype(x.dtype)


def _attention(q, k, v, num_heads):
    """[B, N, C] x [B, M, C] attention via the shared op (routes through
    the registry's flash-attention fast path on TPU)."""
    b, n, c = q.shape
    m = k.shape[1]
    dh = c // num_heads
    out = multihead_attention(
        q.reshape(b, n, num_heads, dh), k.reshape(b, m, num_heads, dh),
        v.reshape(b, m, num_heads, dh), causal=False)
    return out.reshape(b, n, c)


def _layer_norm(x, p, eps=1e-5):
    return layer_norm(x, p["scale"], p["bias"], eps)


# ----------------------------------------------------------------- resnet


def resnet_block(x, temb, p, *, groups=32, eps=1e-6):
    """diffusers ResnetBlock2D: GN→silu→conv3x3 (+time proj) →GN→silu→
    conv3x3, learned 1x1 shortcut on channel change."""
    h = group_norm(x, p["norm1_scale"], p["norm1_bias"], groups=groups,
                   eps=eps)
    h = conv2d(jax.nn.silu(h), p["conv1_w"], p["conv1_b"])
    if temb is not None and "time_emb_w" in p:
        h = h + _linear(jax.nn.silu(temb),
                        {"w": p["time_emb_w"], "b": p["time_emb_b"]}
                        )[:, None, None, :]
    h = group_norm(h, p["norm2_scale"], p["norm2_bias"], groups=groups,
                   eps=eps)
    h = conv2d(jax.nn.silu(h), p["conv2_w"], p["conv2_b"])
    if "shortcut_w" in p:
        x = conv2d(x, p["shortcut_w"], p["shortcut_b"], padding=0)
    return x + h


def init_resnet_block(rng, c_in, c_out, temb_dim=None):
    k = jax.random.split(rng, 4)
    he = jax.nn.initializers.variance_scaling(1.0, "fan_in", "normal")
    p = {
        "norm1_scale": jnp.ones((c_in,)), "norm1_bias": jnp.zeros((c_in,)),
        "conv1_w": he(k[0], (3, 3, c_in, c_out), jnp.float32),
        "conv1_b": jnp.zeros((c_out,)),
        "norm2_scale": jnp.ones((c_out,)), "norm2_bias": jnp.zeros((c_out,)),
        "conv2_w": he(k[1], (3, 3, c_out, c_out), jnp.float32),
        "conv2_b": jnp.zeros((c_out,)),
    }
    if temb_dim:
        p["time_emb_w"] = he(k[2], (temb_dim, c_out), jnp.float32)
        p["time_emb_b"] = jnp.zeros((c_out,))
    if c_in != c_out:
        p["shortcut_w"] = he(k[3], (1, 1, c_in, c_out), jnp.float32)
        p["shortcut_b"] = jnp.zeros((c_out,))
    return p


# ------------------------------------------------- transformer (cross-attn)


def basic_transformer_block(x, ctx, p, num_heads):
    """diffusers BasicTransformerBlock: pre-LN self-attn → pre-LN
    cross-attn → pre-LN GEGLU feed-forward."""
    y = _layer_norm(x, p["norm1"])
    q = y @ p["attn1_q"].astype(y.dtype)
    k = y @ p["attn1_k"].astype(y.dtype)
    v = y @ p["attn1_v"].astype(y.dtype)
    x = x + _linear(_attention(q, k, v, num_heads), p["attn1_out"])
    y = _layer_norm(x, p["norm2"])
    q = y @ p["attn2_q"].astype(y.dtype)
    k = ctx @ p["attn2_k"].astype(ctx.dtype)
    v = ctx @ p["attn2_v"].astype(ctx.dtype)
    x = x + _linear(_attention(q, k, v, num_heads), p["attn2_out"])
    y = _layer_norm(x, p["norm3"])
    h = _linear(y, p["ff_in"])               # [.., 2*inner] GEGLU
    h, gate = jnp.split(h, 2, axis=-1)
    h = h * jax.nn.gelu(gate, approximate=False)
    return x + _linear(h, p["ff_out"])


def init_transformer_block(rng, dim, ctx_dim, ff_mult=4):
    k = jax.random.split(rng, 8)
    he = jax.nn.initializers.variance_scaling(1.0, "fan_in", "normal")
    ln = lambda: {"scale": jnp.ones((dim,)), "bias": jnp.zeros((dim,))}
    lin = lambda kk, i, o: {"w": he(kk, (i, o), jnp.float32),
                            "b": jnp.zeros((o,))}
    inner = ff_mult * dim
    return {
        "norm1": ln(), "norm2": ln(), "norm3": ln(),
        "attn1_q": he(k[0], (dim, dim), jnp.float32),
        "attn1_k": he(k[1], (dim, dim), jnp.float32),
        "attn1_v": he(k[2], (dim, dim), jnp.float32),
        "attn1_out": lin(k[3], dim, dim),
        "attn2_q": he(k[4], (dim, dim), jnp.float32),
        "attn2_k": he(k[5], (ctx_dim, dim), jnp.float32),
        "attn2_v": he(k[6], (ctx_dim, dim), jnp.float32),
        "attn2_out": lin(k[7], dim, dim),
        "ff_in": lin(k[3], dim, 2 * inner),
        "ff_out": lin(k[4], inner, dim),
    }


def transformer_2d(x, ctx, p, num_heads):
    """diffusers Transformer2DModel (conv projections, SD-1.x): GN →
    conv1x1 proj_in → [B, HW, C] blocks → conv1x1 proj_out, residual."""
    b, h, w, c = x.shape
    res = x
    y = group_norm(x, p["norm_scale"], p["norm_bias"], eps=1e-6)
    y = conv2d(y, p["proj_in_w"], p["proj_in_b"], padding=0)
    y = y.reshape(b, h * w, c)
    for blk in p["blocks"]:
        y = basic_transformer_block(y, ctx, blk, num_heads)
    y = y.reshape(b, h, w, c)
    return conv2d(y, p["proj_out_w"], p["proj_out_b"], padding=0) + res


def init_transformer_2d(rng, dim, ctx_dim, depth=1):
    k = jax.random.split(rng, depth + 2)
    he = jax.nn.initializers.variance_scaling(1.0, "fan_in", "normal")
    return {
        "norm_scale": jnp.ones((dim,)), "norm_bias": jnp.zeros((dim,)),
        "proj_in_w": he(k[0], (1, 1, dim, dim), jnp.float32),
        "proj_in_b": jnp.zeros((dim,)),
        "blocks": [init_transformer_block(k[2 + i], dim, ctx_dim)
                   for i in range(depth)],
        "proj_out_w": he(k[1], (1, 1, dim, dim), jnp.float32),
        "proj_out_b": jnp.zeros((dim,)),
    }


# ---------------------------------------------------------------- UNet


@dataclasses.dataclass
class UNetConfig:
    in_channels: int = 4
    out_channels: int = 4
    block_out_channels: Tuple[int, ...] = (320, 640, 1280, 1280)
    layers_per_block: int = 2
    down_block_types: Tuple[str, ...] = (
        "CrossAttnDownBlock2D", "CrossAttnDownBlock2D",
        "CrossAttnDownBlock2D", "DownBlock2D")
    up_block_types: Tuple[str, ...] = (
        "UpBlock2D", "CrossAttnUpBlock2D", "CrossAttnUpBlock2D",
        "CrossAttnUpBlock2D")
    cross_attention_dim: int = 768
    attention_head_dim: int = 8      # heads per attention (SD-1.x semantics)
    norm_groups: int = 32
    transformer_depth: int = 1

    @classmethod
    def tiny(cls, **kw):
        kw.setdefault("block_out_channels", (32, 64))
        kw.setdefault("down_block_types",
                      ("CrossAttnDownBlock2D", "DownBlock2D"))
        kw.setdefault("up_block_types",
                      ("UpBlock2D", "CrossAttnUpBlock2D"))
        kw.setdefault("layers_per_block", 1)
        kw.setdefault("cross_attention_dim", 32)
        kw.setdefault("attention_head_dim", 4)
        kw.setdefault("norm_groups", 8)
        return cls(**kw)


class UNet2DConditionModel:
    """Conditional denoising UNet. __call__(params, sample [B,H,W,C_in],
    timesteps [B], encoder_hidden_states [B,S,ctx]) → eps [B,H,W,C_out]."""

    def __init__(self, config: UNetConfig, compute_dtype=jnp.float32):
        self.config = config
        self.compute_dtype = compute_dtype

    # ------------------------------------------------------------- init
    def init(self, rng):
        c = self.config
        ch = c.block_out_channels
        temb = 4 * ch[0]
        heads = c.attention_head_dim
        keys = iter(jax.random.split(rng, 256))
        he = jax.nn.initializers.variance_scaling(1.0, "fan_in", "normal")
        nk = lambda: next(keys)
        params: Dict[str, Any] = {
            "time_mlp1": {"w": he(nk(), (ch[0], temb), jnp.float32),
                          "b": jnp.zeros((temb,))},
            "time_mlp2": {"w": he(nk(), (temb, temb), jnp.float32),
                          "b": jnp.zeros((temb,))},
            "conv_in_w": he(nk(), (3, 3, c.in_channels, ch[0]), jnp.float32),
            "conv_in_b": jnp.zeros((ch[0],)),
        }
        # down
        down = []
        c_prev = ch[0]
        for i, btype in enumerate(c.down_block_types):
            c_out = ch[i]
            blk = {"resnets": [], "attns": []}
            for j in range(c.layers_per_block):
                blk["resnets"].append(init_resnet_block(
                    nk(), c_prev if j == 0 else c_out, c_out, temb))
                if btype == "CrossAttnDownBlock2D":
                    blk["attns"].append(init_transformer_2d(
                        nk(), c_out, c.cross_attention_dim,
                        c.transformer_depth))
            if i < len(ch) - 1:
                blk["down_w"] = he(nk(), (3, 3, c_out, c_out), jnp.float32)
                blk["down_b"] = jnp.zeros((c_out,))
            down.append(blk)
            c_prev = c_out
        params["down"] = down
        # mid
        params["mid"] = {
            "resnet1": init_resnet_block(nk(), ch[-1], ch[-1], temb),
            "attn": init_transformer_2d(nk(), ch[-1], c.cross_attention_dim,
                                        c.transformer_depth),
            "resnet2": init_resnet_block(nk(), ch[-1], ch[-1], temb),
        }
        # up (reversed channels, layers_per_block+1 resnets w/ skip concat)
        up = []
        rev = list(reversed(ch))
        for i, btype in enumerate(c.up_block_types):
            c_out = rev[i]
            c_skip_prev = rev[min(i + 1, len(rev) - 1)]
            blk = {"resnets": [], "attns": []}
            for j in range(c.layers_per_block + 1):
                res_skip = c_out if j < c.layers_per_block else c_skip_prev
                res_in = (rev[max(i - 1, 0)] if i > 0 else rev[0]) \
                    if j == 0 else c_out
                blk["resnets"].append(init_resnet_block(
                    nk(), res_in + res_skip, c_out, temb))
                if btype == "CrossAttnUpBlock2D":
                    blk["attns"].append(init_transformer_2d(
                        nk(), c_out, c.cross_attention_dim,
                        c.transformer_depth))
            if i < len(ch) - 1:
                blk["up_w"] = he(nk(), (3, 3, c_out, c_out), jnp.float32)
                blk["up_b"] = jnp.zeros((c_out,))
            up.append(blk)
        params["up"] = up
        params["norm_out_scale"] = jnp.ones((ch[0],))
        params["norm_out_bias"] = jnp.zeros((ch[0],))
        params["conv_out_w"] = he(nk(), (3, 3, ch[0], c.out_channels),
                                  jnp.float32)
        params["conv_out_b"] = jnp.zeros((c.out_channels,))
        return params

    # ---------------------------------------------------------- forward
    def __call__(self, params, sample, timesteps, encoder_hidden_states):
        c = self.config
        heads = c.attention_head_dim
        g = c.norm_groups
        temb = timestep_embedding(timesteps, c.block_out_channels[0])
        temb = _linear(jax.nn.silu(_linear(temb, params["time_mlp1"])),
                       params["time_mlp2"])

        x = conv2d(sample.astype(self.compute_dtype), params["conv_in_w"],
                   params["conv_in_b"])
        skips = [x]
        for i, blk in enumerate(params["down"]):
            has_attn = len(blk["attns"]) > 0
            for j, rp in enumerate(blk["resnets"]):
                x = resnet_block(x, temb, rp, groups=g)
                if has_attn:
                    x = transformer_2d(x, encoder_hidden_states,
                                       blk["attns"][j], heads)
                skips.append(x)
            if "down_w" in blk:
                x = conv2d(x, blk["down_w"], blk["down_b"], stride=2)
                skips.append(x)

        m = params["mid"]
        x = resnet_block(x, temb, m["resnet1"], groups=g)
        x = transformer_2d(x, encoder_hidden_states, m["attn"], heads)
        x = resnet_block(x, temb, m["resnet2"], groups=g)

        for i, blk in enumerate(params["up"]):
            has_attn = len(blk["attns"]) > 0
            for j, rp in enumerate(blk["resnets"]):
                skip = skips.pop()
                x = jnp.concatenate([x, skip], axis=-1)
                x = resnet_block(x, temb, rp, groups=g)
                if has_attn:
                    x = transformer_2d(x, encoder_hidden_states,
                                       blk["attns"][j], heads)
            if "up_w" in blk:
                b, h, w, cc = x.shape
                x = jax.image.resize(x, (b, 2 * h, 2 * w, cc), "nearest")
                x = conv2d(x, blk["up_w"], blk["up_b"])

        x = group_norm(x, params["norm_out_scale"], params["norm_out_bias"],
                       groups=g)
        return conv2d(jax.nn.silu(x), params["conv_out_w"],
                      params["conv_out_b"])


# ----------------------------------------------------------------- VAE


@dataclasses.dataclass
class VAEConfig:
    in_channels: int = 3
    out_channels: int = 3
    latent_channels: int = 4
    block_out_channels: Tuple[int, ...] = (128, 256, 512, 512)
    layers_per_block: int = 2
    norm_groups: int = 32
    scaling_factor: float = 0.18215

    @classmethod
    def tiny(cls, **kw):
        kw.setdefault("block_out_channels", (32, 64))
        kw.setdefault("layers_per_block", 1)
        kw.setdefault("norm_groups", 8)
        return cls(**kw)


def _init_vae_attn(rng, dim):
    k = jax.random.split(rng, 4)
    he = jax.nn.initializers.variance_scaling(1.0, "fan_in", "normal")
    lin = lambda kk: {"w": he(kk, (dim, dim), jnp.float32),
                      "b": jnp.zeros((dim,))}
    return {"norm_scale": jnp.ones((dim,)), "norm_bias": jnp.zeros((dim,)),
            "q": lin(k[0]), "k": lin(k[1]), "v": lin(k[2]),
            "out": lin(k[3])}


def _vae_attn(x, p, groups):
    """Single-head spatial self-attention (diffusers VAE mid attention)."""
    b, h, w, c = x.shape
    y = group_norm(x, p["norm_scale"], p["norm_bias"], groups=groups)
    y = y.reshape(b, h * w, c)
    out = _attention(_linear(y, p["q"]), _linear(y, p["k"]),
                     _linear(y, p["v"]), num_heads=1)
    return x + _linear(out, p["out"]).reshape(b, h, w, c)


class AutoencoderKL:
    """VAE with KL latent (diffusers AutoencoderKL layout).

    encode(params, images [B,H,W,3]) → (mean, logvar) [B,H/8,W/8,latent]
    decode(params, latents) → images [B,H,W,3]
    """

    def __init__(self, config: VAEConfig, compute_dtype=jnp.float32):
        self.config = config
        self.compute_dtype = compute_dtype

    def init(self, rng):
        c = self.config
        ch = c.block_out_channels
        keys = iter(jax.random.split(rng, 128))
        nk = lambda: next(keys)
        he = jax.nn.initializers.variance_scaling(1.0, "fan_in", "normal")
        enc: Dict[str, Any] = {
            "conv_in_w": he(nk(), (3, 3, c.in_channels, ch[0]), jnp.float32),
            "conv_in_b": jnp.zeros((ch[0],)),
            "down": [],
        }
        c_prev = ch[0]
        for i, c_out in enumerate(ch):
            blk = {"resnets": [init_resnet_block(
                nk(), c_prev if j == 0 else c_out, c_out)
                for j in range(c.layers_per_block)]}
            if i < len(ch) - 1:
                blk["down_w"] = he(nk(), (3, 3, c_out, c_out), jnp.float32)
                blk["down_b"] = jnp.zeros((c_out,))
            enc["down"].append(blk)
            c_prev = c_out
        enc["mid"] = {
            "resnet1": init_resnet_block(nk(), ch[-1], ch[-1]),
            "attn": _init_vae_attn(nk(), ch[-1]),
            "resnet2": init_resnet_block(nk(), ch[-1], ch[-1]),
        }
        enc["norm_out_scale"] = jnp.ones((ch[-1],))
        enc["norm_out_bias"] = jnp.zeros((ch[-1],))
        enc["conv_out_w"] = he(nk(), (3, 3, ch[-1], 2 * c.latent_channels),
                               jnp.float32)
        enc["conv_out_b"] = jnp.zeros((2 * c.latent_channels,))

        dec: Dict[str, Any] = {
            "conv_in_w": he(nk(), (3, 3, c.latent_channels, ch[-1]),
                            jnp.float32),
            "conv_in_b": jnp.zeros((ch[-1],)),
            "mid": {
                "resnet1": init_resnet_block(nk(), ch[-1], ch[-1]),
                "attn": _init_vae_attn(nk(), ch[-1]),
                "resnet2": init_resnet_block(nk(), ch[-1], ch[-1]),
            },
            "up": [],
        }
        rev = list(reversed(ch))
        c_prev = rev[0]
        for i, c_out in enumerate(rev):
            blk = {"resnets": [init_resnet_block(
                nk(), c_prev if j == 0 else c_out, c_out)
                for j in range(c.layers_per_block + 1)]}
            if i < len(ch) - 1:
                blk["up_w"] = he(nk(), (3, 3, c_out, c_out), jnp.float32)
                blk["up_b"] = jnp.zeros((c_out,))
            dec["up"].append(blk)
            c_prev = c_out
        dec["norm_out_scale"] = jnp.ones((ch[0],))
        dec["norm_out_bias"] = jnp.zeros((ch[0],))
        dec["conv_out_w"] = he(nk(), (3, 3, ch[0], c.out_channels),
                               jnp.float32)
        dec["conv_out_b"] = jnp.zeros((c.out_channels,))
        return {
            "encoder": enc, "decoder": dec,
            "quant_w": he(nk(), (1, 1, 2 * c.latent_channels,
                                 2 * c.latent_channels), jnp.float32),
            "quant_b": jnp.zeros((2 * c.latent_channels,)),
            "post_quant_w": he(nk(), (1, 1, c.latent_channels,
                                      c.latent_channels), jnp.float32),
            "post_quant_b": jnp.zeros((c.latent_channels,)),
        }

    def encode(self, params, images):
        c = self.config
        g = c.norm_groups
        e = params["encoder"]
        x = conv2d(images.astype(self.compute_dtype), e["conv_in_w"],
                   e["conv_in_b"])
        for blk in e["down"]:
            for rp in blk["resnets"]:
                x = resnet_block(x, None, rp, groups=g)
            if "down_w" in blk:
                # diffusers encoder downsample pads (0,1,0,1) asymmetrically
                x = jnp.pad(x, ((0, 0), (0, 1), (0, 1), (0, 0)))
                x = jax.lax.conv_general_dilated(
                    x, blk["down_w"].astype(x.dtype), (2, 2), "VALID",
                    dimension_numbers=("NHWC", "HWIO", "NHWC")) + \
                    blk["down_b"].astype(x.dtype)
        m = e["mid"]
        x = resnet_block(x, None, m["resnet1"], groups=g)
        x = _vae_attn(x, m["attn"], g)
        x = resnet_block(x, None, m["resnet2"], groups=g)
        x = group_norm(x, e["norm_out_scale"], e["norm_out_bias"], groups=g)
        x = conv2d(jax.nn.silu(x), e["conv_out_w"], e["conv_out_b"])
        moments = conv2d(x, params["quant_w"], params["quant_b"], padding=0)
        mean, logvar = jnp.split(moments, 2, axis=-1)
        return mean, jnp.clip(logvar, -30.0, 20.0)

    def decode(self, params, latents):
        c = self.config
        g = c.norm_groups
        d = params["decoder"]
        x = conv2d(latents.astype(self.compute_dtype), params["post_quant_w"],
                   params["post_quant_b"], padding=0)
        x = conv2d(x, d["conv_in_w"], d["conv_in_b"])
        m = d["mid"]
        x = resnet_block(x, None, m["resnet1"], groups=g)
        x = _vae_attn(x, m["attn"], g)
        x = resnet_block(x, None, m["resnet2"], groups=g)
        for blk in d["up"]:
            for rp in blk["resnets"]:
                x = resnet_block(x, None, rp, groups=g)
            if "up_w" in blk:
                b, h, w, cc = x.shape
                x = jax.image.resize(x, (b, 2 * h, 2 * w, cc), "nearest")
                x = conv2d(x, blk["up_w"], blk["up_b"])
        x = group_norm(x, d["norm_out_scale"], d["norm_out_bias"], groups=g)
        return conv2d(jax.nn.silu(x), d["conv_out_w"], d["conv_out_b"])
