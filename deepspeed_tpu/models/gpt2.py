"""GPT-2 model family (125M default), TPU-first.

Design notes (vs. the reference's per-module torch GPT-2 used in its tests
and the fused ``csrc/transformer`` training kernel, SURVEY §2.4):
  * all transformer blocks are *stacked* on a leading 'layer' dimension and
    executed with ``lax.scan`` — one compiled block, L iterations; this is
    the XLA-idiomatic form that keeps compile time flat in depth and lets
    ZeRO-3 shard the layer dimension.
  * activations/matmuls run in the engine's compute dtype (bf16); softmax,
    layernorm statistics and the CE loss run in fp32.
  * logical axis names per param dim feed the PartitionPlan (TP over 'heads'/
    'mlp'/'vocab', ZeRO over 'layer' or the largest free dim).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from deepspeed_tpu.models.base import ATTN_IMPLS, cache_positions, cross_entropy_loss, embed_tokens, gelu, layer_norm, layer_view, qdot, sp_attention, tied_logits
from deepspeed_tpu.ops.attention import alloc_kv_cache, cached_attention, multihead_attention


@dataclasses.dataclass
class GPT2Config:
    vocab_size: int = 50257
    max_seq_len: int = 1024
    num_layers: int = 12
    hidden_size: int = 768
    num_heads: int = 12
    mlp_ratio: int = 4
    dropout: float = 0.0
    tie_embeddings: bool = True
    eps: float = 1e-5
    # >0: compute the LM loss in sequence chunks of this size without ever
    # materializing [B, T, V] logits (runtime/zero/tiling.py — the memory
    # win matters from ~50k vocab; requires tie_embeddings)
    loss_chunk: int = 0

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @property
    def mlp_dim(self) -> int:
        return self.hidden_size * self.mlp_ratio

    @classmethod
    def gpt2_125m(cls, **kw):
        return cls(num_layers=12, hidden_size=768, num_heads=12, **kw)

    @classmethod
    def gpt2_350m(cls, **kw):
        return cls(num_layers=24, hidden_size=1024, num_heads=16, **kw)

    @classmethod
    def gpt2_774m(cls, **kw):
        return cls(num_layers=36, hidden_size=1280, num_heads=20, **kw)

    @classmethod
    def gpt2_1b3(cls, **kw):
        return cls(num_layers=24, hidden_size=2048, num_heads=16, **kw)

    @classmethod
    def tiny(cls, **kw):
        kw.setdefault("vocab_size", 512)
        kw.setdefault("max_seq_len", 128)
        return cls(num_layers=2, hidden_size=64, num_heads=4, **kw)


class GPT2Model:
    """Causal-LM ModelSpec. batch = {"input_ids": [B,T] int32, "labels": [B,T]}."""

    supports_weight_quant = True   # weight matmuls go through base.qdot
    # the tied embedding/lm-head may ALSO quantize (per-vocab-row scales,
    # quant.quantize_embedding): embed gathers + tied logits route
    # through base.embed_tokens / base.tied_logits
    supports_embedding_quant = True

    def __init__(self, config: GPT2Config, compute_dtype=jnp.bfloat16,
                 remat: bool = False, remat_policy: Optional[str] = None,
                 attn_impl: str = "dense", decode_unroll: int = 1):
        self.config = config
        self.compute_dtype = compute_dtype
        # layer-scan unroll factor for single-token decode steps: unrolling
        # lets XLA overlap consecutive layers' weight DMAs with compute
        # (per-layer matmuls are tiny at decode, so HBM latency dominates)
        self.decode_unroll = decode_unroll
        self.remat = remat
        self.remat_policy = remat_policy
        assert attn_impl in ATTN_IMPLS, attn_impl
        if attn_impl != "dense" and config.dropout > 0.0:
            raise ValueError(
                f"attn_impl={attn_impl!r} does not implement attention dropout; "
                f"set dropout=0.0 or use attn_impl='dense'")
        if config.loss_chunk and not config.tie_embeddings:
            raise ValueError("loss_chunk requires tie_embeddings (the "
                             "chunked LM loss projects through wte)")
        self.attn_impl = attn_impl

    # ------------------------------------------------------------------- init
    def init(self, rng):
        c = self.config
        k = jax.random.split(rng, 8)
        d, l, m, v = c.hidden_size, c.num_layers, c.mlp_dim, c.vocab_size
        std = 0.02
        init = jax.nn.initializers.normal(std)
        params = {
            "wte": init(k[0], (v, d), jnp.float32),
            "wpe": init(k[1], (c.max_seq_len, d), jnp.float32),
            "blocks": {
                "ln1_scale": jnp.ones((l, d)), "ln1_bias": jnp.zeros((l, d)),
                "qkv_w": init(k[2], (l, d, 3 * d), jnp.float32),
                "qkv_b": jnp.zeros((l, 3 * d)),
                "attn_out_w": init(k[3], (l, d, d), jnp.float32) / (2 * l) ** 0.5,
                "attn_out_b": jnp.zeros((l, d)),
                "ln2_scale": jnp.ones((l, d)), "ln2_bias": jnp.zeros((l, d)),
                "mlp_fc_w": init(k[4], (l, d, m), jnp.float32),
                "mlp_fc_b": jnp.zeros((l, m)),
                "mlp_out_w": init(k[5], (l, m, d), jnp.float32) / (2 * l) ** 0.5,
                "mlp_out_b": jnp.zeros((l, d)),
            },
            "ln_f_scale": jnp.ones((d,)), "ln_f_bias": jnp.zeros((d,)),
        }
        if not c.tie_embeddings:
            params["lm_head"] = init(k[6], (d, v), jnp.float32)
        return params

    def logical_axes(self):
        c = self.config
        axes = {
            "wte": ("vocab_in", "hidden"),
            "wpe": ("seq", "hidden"),
            "blocks": {
                "ln1_scale": ("layer", "hidden"), "ln1_bias": ("layer", "hidden"),
                "qkv_w": ("layer", "hidden", "heads"),
                "qkv_b": ("layer", "heads"),
                "attn_out_w": ("layer", "heads", "hidden"),
                "attn_out_b": ("layer", "hidden"),
                "ln2_scale": ("layer", "hidden"), "ln2_bias": ("layer", "hidden"),
                "mlp_fc_w": ("layer", "hidden", "mlp"),
                "mlp_fc_b": ("layer", "mlp"),
                "mlp_out_w": ("layer", "mlp", "hidden"),
                "mlp_out_b": ("layer", "hidden"),
            },
            "ln_f_scale": ("hidden",), "ln_f_bias": ("hidden",),
        }
        if not c.tie_embeddings:
            axes["lm_head"] = ("hidden", "vocab")
        return axes

    # ------------------------------------------------------------------ layers
    def _block_impl(self, x, blk, rng, train: bool, cache):
        """One transformer block; with ``cache=(k_full, v_full, layer, idx)``
        the attention runs against the KV cache (one shared implementation so
        training and serving can never diverge numerically). ``k_full`` /
        ``v_full`` are the FULL stacked head-major [L, B, H, S, Dh] caches:
        only the new token's slice is written (in place, as a loop-carry
        dynamic update) — never the whole cache (see
        ops/attention.decode_attention)."""
        c = self.config
        b, t, d = x.shape
        h, dh = c.num_heads, c.head_dim
        y = layer_norm(x, blk["ln1_scale"], blk["ln1_bias"], c.eps)
        # qdot streams int8 weights straight into the matmul (scale folded
        # into the output) — no dequantized bf16 tiles in HBM
        qkv = qdot("btd,de->bte", y, blk["qkv_w"]) + \
            blk["qkv_b"].astype(y.dtype)
        q, k_, v_ = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(b, t, h, dh)
        k_ = k_.reshape(b, t, h, dh)
        v_ = v_.reshape(b, t, h, dh)
        if cache is None:
            if self.attn_impl != "dense":
                attn = sp_attention(self.attn_impl, q, k_, v_)
            else:
                drop_rng = None
                if train and c.dropout > 0.0 and rng is not None:
                    rng, drop_rng = jax.random.split(rng)
                attn = multihead_attention(q, k_, v_, causal=True,
                                           dropout_rate=c.dropout if train else 0.0,
                                           dropout_rng=drop_rng)
            kc = vc = None
        else:
            kc, vc, layer, idx, *rest = cache
            attn, kc, vc = cached_attention(
                q, kc, vc, k_, v_, layer, idx,
                block_table=rest[0] if rest else None)
        attn = attn.reshape(b, t, d)
        x = x + qdot("btd,de->bte", attn, blk["attn_out_w"]) + \
            blk["attn_out_b"].astype(x.dtype)
        y = layer_norm(x, blk["ln2_scale"], blk["ln2_bias"], c.eps)
        hmid = gelu(qdot("btd,dm->btm", y, blk["mlp_fc_w"]) +
                    blk["mlp_fc_b"].astype(y.dtype))
        x = x + qdot("btm,md->btd", hmid, blk["mlp_out_w"]) + \
            blk["mlp_out_b"].astype(x.dtype)
        return x, kc, vc

    def _block(self, x, blk, rng, train: bool):
        return self._block_impl(x, blk, rng, train, None)[0]

    def forward_hidden(self, params, input_ids, *, rngs=None, train: bool = False,
                       pld_theta=None, ltd_keep=None):
        c = self.config
        b, t = input_ids.shape
        x = embed_tokens(params["wte"], input_ids, self.compute_dtype)
        x = x + params["wpe"].astype(self.compute_dtype)[:t][None]

        block_fn = self._block
        if self.remat:
            from deepspeed_tpu.runtime.activation_checkpointing import checkpoint_policy

            block_fn = jax.checkpoint(block_fn, policy=checkpoint_policy(self.remat_policy),
                                      static_argnums=(3,))

        rng0 = rngs.get("dropout") if isinstance(rngs, dict) else rngs
        if (ltd_keep is not None and train and ltd_keep < t
                and c.num_layers >= 3):
            # random-LTD token routing (reference data_routing/
            # basic_layer.py RandomLayerTokenDrop): every layer except the
            # first and last runs on a per-layer random SORTED subset of
            # ``ltd_keep`` tokens — gather -> block -> scatter, with the
            # dropped tokens' hidden states passing through unchanged.
            # Sorted indices keep the reduced sequence causal w.r.t. the
            # original token order, so the block's causal mask is exact.
            assert rng0 is not None, "random-LTD needs a dropout rng"
            assert pld_theta is None, \
                "random-LTD and progressive_layer_drop are exclusive"
            from deepspeed_tpu.runtime.data_pipeline.random_ltd import (
                gather_tokens, sample_token_indices, scatter_tokens)

            first = jax.tree_util.tree_map(lambda p: p[0], params["blocks"])
            last = jax.tree_util.tree_map(lambda p: p[-1], params["blocks"])
            mid = jax.tree_util.tree_map(lambda p: p[1:-1], params["blocks"])
            rng0, sub = jax.random.split(rng0)
            x = block_fn(x, first, sub, train)

            def ltd_body(carry, blk):
                x, rng = carry
                rng, r_idx, r_blk = jax.random.split(rng, 3)
                idx = sample_token_indices(r_idx, b, t, ltd_keep)
                kept = block_fn(gather_tokens(x, idx), blk, r_blk, train)
                return (scatter_tokens(x, kept, idx), rng), None

            (x, rng0), _ = jax.lax.scan(ltd_body, (x, rng0), mid)
            rng0, sub = jax.random.split(rng0)
            x = block_fn(x, last, sub, train)
            return layer_norm(x, params["ln_f_scale"], params["ln_f_bias"],
                              c.eps)

        use_pld = pld_theta is not None and train
        layer_idx = jnp.arange(c.num_layers)

        def scan_body(carry, layer_in):
            x, rng = carry
            layer_params, i = layer_in
            if rng is not None:
                rng, sub = jax.random.split(rng)
            else:
                sub = None
            x_new = block_fn(x, layer_params, sub, train)
            if use_pld:
                # stochastic depth (progressive layer drop): keep prob anneals
                # linearly in depth from 1 to theta; expectation-preserving
                # residual scaling keeps activations calibrated
                assert rng is not None, "pld needs a dropout rng"
                rng, pld_rng = jax.random.split(rng)
                frac = i / max(c.num_layers - 1, 1)
                p_keep = 1.0 - frac * (1.0 - pld_theta)
                keep = jax.random.bernoulli(pld_rng, p_keep)
                gate = jnp.where(keep, 1.0 / p_keep, 0.0).astype(x.dtype)
                x = x + gate * (x_new - x)
            else:
                x = x_new
            return (x, rng), None

        rng = rngs.get("dropout") if isinstance(rngs, dict) else rngs
        (x, _), _ = jax.lax.scan(scan_body, (x, rng),
                                 (params["blocks"], layer_idx))
        return layer_norm(x, params["ln_f_scale"], params["ln_f_bias"], c.eps)

    def logits(self, params, hidden):
        if self.config.tie_embeddings:
            return tied_logits(hidden, params["wte"])
        return jnp.einsum("btd,dv->btv", hidden, params["lm_head"].astype(hidden.dtype))

    def apply(self, params, batch, *, rngs=None, train: bool = False,
              pld_theta=None, ltd_keep=None):
        hidden = self.forward_hidden(params, batch["input_ids"], rngs=rngs,
                                     train=train, pld_theta=pld_theta,
                                     ltd_keep=ltd_keep)
        c = self.config
        if c.loss_chunk:
            from deepspeed_tpu.runtime.zero.tiling import (
                chunked_cross_entropy)

            loss, n = chunked_cross_entropy(hidden, params["wte"],
                                            batch["labels"],
                                            chunk=c.loss_chunk)
        else:
            loss, n = cross_entropy_loss(self.logits(params, hidden),
                                         batch["labels"])
        return loss, {"loss": loss, "ntokens": n}

    # --------------------------------------------------------- inference path
    def init_cache(self, batch_size: int, max_len: int, dtype=None):
        """Static-shape KV cache (the inference_context.h workspace analog —
        reference csrc/transformer/inference/includes/inference_context.h).
        Head-major, token-pair packed for Dh < 128 — see
        ops/attention.kv_pack_factor / decode_attention."""
        c = self.config
        dtype = dtype or self.compute_dtype
        return {"k": alloc_kv_cache(c.num_layers, batch_size, c.num_heads,
                                    max_len, c.head_dim, dtype),
                "v": alloc_kv_cache(c.num_layers, batch_size, c.num_heads,
                                    max_len, c.head_dim, dtype),
                "index": jnp.zeros((), jnp.int32)}

    def _block_cached(self, x, blk, kc, vc, layer, idx, bt):
        return self._block_impl(x, blk, None, False, (kc, vc, layer, idx, bt))

    def forward_with_cache(self, params, input_ids, cache):
        """Prefill (T>1) or decode (T=1) step against the KV cache.
        Returns (logits [B,T,V], new_cache).

        ``cache["index"]`` may be a scalar (uniform batch) or a per-slot
        [B] vector (continuous batching — models/base.cache_positions).
        ``cache["block_table"]`` (optional, int32 [B, max_blocks])
        switches the cache arrays to the block-paged pool addressing of
        ops/attention.write_kv_blocks (prefix-sharing serving, ISSUE 6).

        The stacked caches ride the layer-scan CARRY (per-layer slice writes
        XLA keeps in place), not xs/ys — the ys form copied the entire cache
        every step, which dominated decode latency (round-2 weak #2)."""
        c = self.config
        b, t = input_ids.shape
        idx = cache["index"]
        bt = cache.get("block_table")
        x = embed_tokens(params["wte"], input_ids, self.compute_dtype)
        pos = cache_positions(idx, t)
        pe = params["wpe"].astype(self.compute_dtype)[pos]
        x = x + (pe if pos.ndim == 2 else pe[None])

        def scan_body(carry, _):
            x, kc, vc, layer = carry
            # counter-indexed blocks: layer_view keeps int8 weight dicts
            # whole so qdot's kernel DMA-slices the layer in-kernel (a
            # host-side int8 operand slice copies the weight every step)
            blk = layer_view(params["blocks"], layer)
            x, kc, vc = self._block_cached(x, blk, kc, vc, layer, idx, bt)
            return (x, kc, vc, layer + 1), None

        (x, k_new, v_new, _), _ = jax.lax.scan(
            scan_body,
            (x, cache["k"], cache["v"], jnp.zeros((), jnp.int32)),
            None, length=c.num_layers,
            unroll=self.decode_unroll if t == 1 else 1)
        hidden = layer_norm(x, params["ln_f_scale"], params["ln_f_bias"], c.eps)
        logits = self.logits(params, hidden)
        out = {"k": k_new, "v": v_new, "index": idx + t}
        if bt is not None:
            out["block_table"] = bt
        return logits, out

    # ------------------------------------------------------------------- cost
    def flops_per_token(self) -> float:
        """6*N approximation + attention quadratic term (training fwd+bwd)."""
        c = self.config
        n_params = (c.vocab_size * c.hidden_size + c.max_seq_len * c.hidden_size +
                    c.num_layers * (4 * c.hidden_size ** 2 + 2 * c.hidden_size * c.mlp_dim))
        attn = 12 * c.num_layers * c.hidden_size * c.max_seq_len
        return 6.0 * n_params + attn
