"""BERT encoder family, TPU-first.

Reference analog: the BERT training/inference pillar — fused
``DeepSpeedTransformerLayer`` trained in the fastest-BERT-training claim
(csrc/transformer, docs/_posts/2020-05-28-fastest-bert-training.md) and the
inference containers (module_inject/containers/{bert,distil_bert}.py).
Same scanned-stack design as the decoders: one compiled post-LN encoder
block, L scan iterations; bidirectional attention with an additive padding
mask; MLM and sequence-classification heads.

batch = {"input_ids" [B,T], "attention_mask" [B,T] (1=real, 0=pad),
         "token_type_ids" [B,T] (optional), "labels"}.
For MLM, label -100 marks unscored positions (HF convention).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from deepspeed_tpu.models.base import cross_entropy_loss, layer_norm, qdot
from deepspeed_tpu.ops.attention import multihead_attention

_ACTS = {
    # HF BERT's default is the EXACT (erf) gelu — the repo-wide tanh
    # approximation would drift per layer across deep post-LN stacks
    "gelu": lambda x: jax.nn.gelu(x, approximate=False),
    "gelu_new": lambda x: jax.nn.gelu(x, approximate=True),
    "relu": jax.nn.relu,
}


@dataclasses.dataclass
class BertConfig:
    vocab_size: int = 30522
    max_seq_len: int = 512
    type_vocab_size: int = 2
    num_layers: int = 12
    hidden_size: int = 768
    num_heads: int = 12
    mlp_dim: int = 3072
    eps: float = 1e-12
    num_labels: int = 2          # sequence classification head width
    hidden_act: str = "gelu"     # exact erf gelu (HF BERT default)
    tie_mlm_decoder: bool = True
    # DistilBERT: no token-type embeddings (type_vocab_size=0) and a
    # relu pre-classifier instead of BERT's tanh pooler
    pooler_act: str = "tanh"     # "tanh" | "relu"

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @classmethod
    def bert_base(cls, **kw):
        return cls(**kw)

    @classmethod
    def bert_large(cls, **kw):
        kw.setdefault("num_layers", 24)
        kw.setdefault("hidden_size", 1024)
        kw.setdefault("num_heads", 16)
        kw.setdefault("mlp_dim", 4096)
        return cls(**kw)

    @classmethod
    def tiny(cls, **kw):
        kw.setdefault("vocab_size", 512)
        kw.setdefault("max_seq_len", 128)
        return cls(num_layers=2, hidden_size=64, num_heads=4, mlp_dim=128,
                   **kw)


class BertModel:
    """Encoder ModelSpec with MLM ("mlm") or classification ("cls") head."""

    supports_weight_quant = True   # weight matmuls go through base.qdot

    def __init__(self, config: BertConfig, compute_dtype=jnp.bfloat16,
                 head: str = "mlm", remat: bool = False):
        assert head in ("mlm", "cls", "none"), head
        self.config = config
        self.compute_dtype = compute_dtype
        self.head = head
        self.remat = remat
        assert config.hidden_act in _ACTS, config.hidden_act
        self._act = _ACTS[config.hidden_act]

    # ------------------------------------------------------------------- init
    def init(self, rng):
        c = self.config
        k = jax.random.split(rng, 12)
        d, l, m, v = c.hidden_size, c.num_layers, c.mlp_dim, c.vocab_size
        init = jax.nn.initializers.normal(0.02)
        params = {
            "wte": init(k[0], (v, d), jnp.float32),
            "wpe": init(k[1], (c.max_seq_len, d), jnp.float32),
            "emb_ln_scale": jnp.ones((d,)), "emb_ln_bias": jnp.zeros((d,)),
            "blocks": {
                "qkv_w": init(k[3], (l, d, 3 * d), jnp.float32),
                "qkv_b": jnp.zeros((l, 3 * d)),
                "attn_out_w": init(k[4], (l, d, d), jnp.float32),
                "attn_out_b": jnp.zeros((l, d)),
                "attn_ln_scale": jnp.ones((l, d)),
                "attn_ln_bias": jnp.zeros((l, d)),
                "mlp_fc_w": init(k[5], (l, d, m), jnp.float32),
                "mlp_fc_b": jnp.zeros((l, m)),
                "mlp_out_w": init(k[6], (l, m, d), jnp.float32),
                "mlp_out_b": jnp.zeros((l, d)),
                "mlp_ln_scale": jnp.ones((l, d)),
                "mlp_ln_bias": jnp.zeros((l, d)),
            },
            "pooler_w": init(k[7], (d, d), jnp.float32),
            "pooler_b": jnp.zeros((d,)),
        }
        if c.type_vocab_size > 0:
            params["wtt"] = init(k[2], (c.type_vocab_size, d), jnp.float32)
        if self.head == "mlm":
            params["mlm"] = {
                "transform_w": init(k[8], (d, d), jnp.float32),
                "transform_b": jnp.zeros((d,)),
                "ln_scale": jnp.ones((d,)), "ln_bias": jnp.zeros((d,)),
                "decoder_bias": jnp.zeros((v,)),   # decoder weight ties wte
            }
        elif self.head == "cls":
            params["cls"] = {
                "w": init(k[9], (d, c.num_labels), jnp.float32),
                "b": jnp.zeros((c.num_labels,)),
            }
        return params

    def logical_axes(self):
        c = self.config
        axes = {
            "wte": ("vocab_in", "hidden"), "wpe": ("seq", "hidden"),
            "emb_ln_scale": ("hidden",), "emb_ln_bias": ("hidden",),
            "blocks": {
                "qkv_w": ("layer", "hidden", "heads"),
                "qkv_b": ("layer", "heads"),
                "attn_out_w": ("layer", "heads", "hidden"),
                "attn_out_b": ("layer", "hidden"),
                "attn_ln_scale": ("layer", "hidden"),
                "attn_ln_bias": ("layer", "hidden"),
                "mlp_fc_w": ("layer", "hidden", "mlp"),
                "mlp_fc_b": ("layer", "mlp"),
                "mlp_out_w": ("layer", "mlp", "hidden"),
                "mlp_out_b": ("layer", "hidden"),
                "mlp_ln_scale": ("layer", "hidden"),
                "mlp_ln_bias": ("layer", "hidden"),
            },
            "pooler_w": ("hidden", "hidden"), "pooler_b": ("hidden",),
        }
        if c.type_vocab_size > 0:
            axes["wtt"] = (None, "hidden")
        if self.head == "mlm":
            axes["mlm"] = {"transform_w": ("hidden", "hidden"),
                           "transform_b": ("hidden",),
                           "ln_scale": ("hidden",), "ln_bias": ("hidden",),
                           "decoder_bias": ("vocab",)}
        elif self.head == "cls":
            axes["cls"] = {"w": ("hidden", None), "b": (None,)}
        return axes

    # ------------------------------------------------------------------ block
    def _block(self, x, blk, mask_bias):
        c = self.config
        b, t, d = x.shape
        h, dh = c.num_heads, c.head_dim
        # qdot: int8 weights stream into the matmul, scale on the output
        qkv = qdot("btd,de->bte", x, blk["qkv_w"]) + \
            blk["qkv_b"].astype(x.dtype)
        q, k_, v_ = (z.reshape(b, t, h, dh) for z in jnp.split(qkv, 3, -1))
        attn = multihead_attention(q, k_, v_, causal=False, mask=mask_bias)
        attn = attn.reshape(b, t, d)
        a_out = qdot("btd,de->bte", attn, blk["attn_out_w"]) + \
            blk["attn_out_b"].astype(x.dtype)
        x = layer_norm(x + a_out, blk["attn_ln_scale"], blk["attn_ln_bias"],
                       c.eps)                                  # post-LN
        mid = self._act(qdot("btd,dm->btm", x, blk["mlp_fc_w"]) +
                        blk["mlp_fc_b"].astype(x.dtype))
        m_out = qdot("btm,md->btd", mid, blk["mlp_out_w"]) + \
            blk["mlp_out_b"].astype(x.dtype)
        return layer_norm(x + m_out, blk["mlp_ln_scale"], blk["mlp_ln_bias"],
                          c.eps)

    # ---------------------------------------------------------------- forward
    def forward_hidden(self, params, input_ids, attention_mask=None,
                       token_type_ids=None, *, rngs=None, train=False):
        c = self.config
        b, t = input_ids.shape
        x = params["wte"].astype(self.compute_dtype)[input_ids]
        x = x + params["wpe"].astype(self.compute_dtype)[:t][None]
        if c.type_vocab_size > 0:
            if token_type_ids is None:
                token_type_ids = jnp.zeros_like(input_ids)
            x = x + params["wtt"].astype(self.compute_dtype)[token_type_ids]
        x = layer_norm(x, params["emb_ln_scale"], params["emb_ln_bias"], c.eps)

        mask_bias = None
        if attention_mask is not None:
            # [B, 1, 1, T] boolean: key positions that may be attended
            mask_bias = attention_mask[:, None, None, :].astype(bool)

        block_fn = self._block
        if self.remat:
            block_fn = jax.checkpoint(block_fn)

        def scan_body(x, blk):
            return block_fn(x, blk, mask_bias), None

        x, _ = jax.lax.scan(scan_body, x, params["blocks"])
        return x

    def pooled(self, params, hidden):
        """act(dense(CLS)) — tanh (reference BertPooler) or relu
        (DistilBERT pre_classifier)."""
        cls = hidden[:, 0]
        act = jnp.tanh if self.config.pooler_act == "tanh" else jax.nn.relu
        return act(cls @ params["pooler_w"].astype(cls.dtype) +
                   params["pooler_b"].astype(cls.dtype))

    def logits(self, params, hidden):
        c = self.config
        if self.head == "mlm":
            m = params["mlm"]
            h = self._act(hidden @ m["transform_w"].astype(hidden.dtype) +
                          m["transform_b"].astype(hidden.dtype))
            h = layer_norm(h, m["ln_scale"], m["ln_bias"], c.eps)
            dec = m["decoder_w"] if "decoder_w" in m else params["wte"]
            return jnp.einsum("btd,vd->btv", h, dec.astype(h.dtype)) + \
                m["decoder_bias"].astype(h.dtype)
        if self.head == "cls":
            p = self.pooled(params, hidden)
            return p @ params["cls"]["w"].astype(p.dtype) + \
                params["cls"]["b"].astype(p.dtype)
        return hidden

    def apply(self, params, batch, *, rngs=None, train=False):
        assert self.head in ("mlm", "cls"), \
            "head='none' is a feature extractor — use forward_hidden()"
        hidden = self.forward_hidden(
            params, batch["input_ids"], batch.get("attention_mask"),
            batch.get("token_type_ids"), rngs=rngs, train=train)
        logits = self.logits(params, hidden)
        labels = batch["labels"]
        if self.head == "mlm":
            loss, n = cross_entropy_loss(logits, labels)
        else:
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
            loss = -jnp.take_along_axis(logp, labels[:, None], -1).mean()
            n = labels.shape[0]
        return loss, {"loss": loss, "ntokens": n}

    def flops_per_token(self) -> float:
        c = self.config
        n = c.num_layers * (4 * c.hidden_size ** 2 + 2 * c.hidden_size * c.mlp_dim)
        return 6.0 * n
