"""GPT with MoE FFN layers — the BASELINE ladder's "GPT-MoE" config
(reference analog: Megatron-DeepSpeed MoE models driven through
``deepspeed.moe.layer.MoE``; test fixture analog SimpleMoEModel,
reference tests/unit/simple_model.py:70).

Interleaves dense and MoE transformer blocks (every other layer MoE, the
standard GShard/DeepSpeed-MoE pattern). Blocks are unrolled (not scanned)
because MoE and dense layers alternate structurally.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from deepspeed_tpu.models.base import (cache_positions, cross_entropy_loss,
                                       gelu, layer_norm)
from deepspeed_tpu.moe.layer import MoE
from deepspeed_tpu.ops.attention import alloc_kv_cache, cached_attention, multihead_attention


@dataclasses.dataclass
class GPTMoEConfig:
    vocab_size: int = 50257
    max_seq_len: int = 1024
    num_layers: int = 12
    hidden_size: int = 768
    num_heads: int = 12
    num_experts: int = 8
    moe_every: int = 2          # every Nth layer is MoE
    # explicit MoE layer indices (overrides moe_every) — checkpoints decide
    # their own dense/MoE interleave (ref containers/megatron_gpt_moe.py
    # converts whatever pattern the Megatron run used)
    moe_layers: Optional[tuple] = None
    top_k: int = 1
    capacity_factor: float = 1.25
    eval_capacity_factor: float = 2.0
    aux_loss_weight: float = 0.01
    use_residual: bool = False  # PR-MoE
    eps: float = 1e-5

    @property
    def head_dim(self):
        return self.hidden_size // self.num_heads

    @classmethod
    def tiny(cls, **kw):
        kw.setdefault("vocab_size", 512)
        kw.setdefault("max_seq_len", 128)
        kw.setdefault("num_experts", 4)
        return cls(num_layers=2, hidden_size=64, num_heads=4, **kw)


class GPTMoEModel:
    def __init__(self, config: GPTMoEConfig, compute_dtype=jnp.bfloat16):
        self.config = config
        self.compute_dtype = compute_dtype
        c = config
        if c.moe_layers is not None:
            self.moe_layers = sorted(int(i) for i in c.moe_layers)
        else:
            self.moe_layers = [i for i in range(c.num_layers)
                               if (i + 1) % c.moe_every == 0]
        self.moe = MoE(c.hidden_size, c.num_experts, k=c.top_k,
                       capacity_factor=c.capacity_factor,
                       eval_capacity_factor=c.eval_capacity_factor,
                       use_residual=c.use_residual)

    def init(self, rng):
        c = self.config
        d = c.hidden_size
        keys = jax.random.split(rng, 2 * c.num_layers + 3)
        init = jax.nn.initializers.normal(0.02)
        blocks = []
        for i in range(c.num_layers):
            k1, k2 = keys[2 * i], keys[2 * i + 1]
            blk = {
                "ln1_scale": jnp.ones((d,)), "ln1_bias": jnp.zeros((d,)),
                "qkv_w": init(k1, (d, 3 * d), jnp.float32),
                "qkv_b": jnp.zeros((3 * d,)),
                "out_w": init(k2, (d, d), jnp.float32) / (2 * c.num_layers) ** 0.5,
                "out_b": jnp.zeros((d,)),
                "ln2_scale": jnp.ones((d,)), "ln2_bias": jnp.zeros((d,)),
            }
            if i in self.moe_layers:
                blk["moe"] = self.moe.init(jax.random.fold_in(k2, 7))
            else:
                k3 = jax.random.fold_in(k1, 13)
                blk["mlp_fc_w"] = init(k3, (d, 4 * d), jnp.float32)
                blk["mlp_fc_b"] = jnp.zeros((4 * d,))
                blk["mlp_out_w"] = init(jax.random.fold_in(k3, 1), (4 * d, d),
                                        jnp.float32) / (2 * c.num_layers) ** 0.5
                blk["mlp_out_b"] = jnp.zeros((d,))
            blocks.append(blk)
        return {
            "wte": init(keys[-3], (c.vocab_size, d), jnp.float32),
            "wpe": init(keys[-2], (c.max_seq_len, d), jnp.float32),
            "blocks": blocks,
            "ln_f_scale": jnp.ones((d,)), "ln_f_bias": jnp.zeros((d,)),
        }

    def logical_axes(self):
        c = self.config
        d_axes = {
            "ln1_scale": ("hidden",), "ln1_bias": ("hidden",),
            "qkv_w": ("hidden", "heads"), "qkv_b": ("heads",),
            "out_w": ("heads", "hidden"), "out_b": ("hidden",),
            "ln2_scale": ("hidden",), "ln2_bias": ("hidden",),
        }
        blocks = []
        for i in range(c.num_layers):
            blk = dict(d_axes)
            if i in self.moe_layers:
                blk["moe"] = self.moe.logical_axes()
            else:
                blk.update({"mlp_fc_w": ("hidden", "mlp"), "mlp_fc_b": ("mlp",),
                            "mlp_out_w": ("mlp", "hidden"), "mlp_out_b": ("hidden",)})
            blocks.append(blk)
        return {"wte": ("vocab_in", "hidden"), "wpe": ("seq", "hidden"),
                "blocks": blocks, "ln_f_scale": ("hidden",), "ln_f_bias": ("hidden",)}

    def _attn(self, x, blk, cache=None):
        """Attention sub-block; ``cache=(k_full, v_full, layer, idx)`` runs
        against the stacked head-major [L, B, H, S, Dh] KV cache (same
        write/read ops as the dense families — ops/attention.py)."""
        c = self.config
        b, t, d = x.shape
        y = layer_norm(x, blk["ln1_scale"], blk["ln1_bias"], c.eps)
        qkv = y @ blk["qkv_w"].astype(y.dtype) + blk["qkv_b"].astype(y.dtype)
        q, k_, v_ = jnp.split(qkv, 3, axis=-1)
        shape = (b, t, c.num_heads, c.head_dim)
        q, k_, v_ = q.reshape(shape), k_.reshape(shape), v_.reshape(shape)
        if cache is None:
            attn = multihead_attention(q, k_, v_, causal=True)
            kc = vc = None
        else:
            kc, vc, layer, idx, *rest = cache
            attn, kc, vc = cached_attention(
                q, kc, vc, k_, v_, layer, idx,
                block_table=rest[0] if rest else None)
        x = x + attn.reshape(b, t, d) @ blk["out_w"].astype(x.dtype) + \
            blk["out_b"].astype(x.dtype)
        return x, kc, vc

    def _ffn(self, x, blk, i, *, train: bool, rng):
        """Dense MLP or MoE FFN for layer ``i`` → (x, aux_loss)."""
        c = self.config
        y = layer_norm(x, blk["ln2_scale"], blk["ln2_bias"], c.eps)
        if i in self.moe_layers:
            sub = jax.random.fold_in(rng, i) if rng is not None else None
            moe_out, l_aux, _ = self.moe.apply(blk["moe"], y, train=train, rng=sub)
            return x + moe_out, l_aux
        h = gelu(y @ blk["mlp_fc_w"].astype(y.dtype) +
                 blk["mlp_fc_b"].astype(y.dtype))
        x = x + h @ blk["mlp_out_w"].astype(x.dtype) + \
            blk["mlp_out_b"].astype(x.dtype)
        return x, jnp.zeros((), jnp.float32)

    def _embed(self, params, input_ids, start_pos=0):
        x = params["wte"].astype(self.compute_dtype)[input_ids]
        # start_pos may be a per-slot [B] vector (continuous batching)
        pos = cache_positions(start_pos, input_ids.shape[1])
        pe = params["wpe"].astype(self.compute_dtype)[pos]
        return x + (pe if pos.ndim == 2 else pe[None])

    def _forward_blocks(self, params, x, *, rng=None, train: bool = False):
        total_aux = jnp.zeros((), jnp.float32)
        for i, blk in enumerate(params["blocks"]):
            x, _, _ = self._attn(x, blk)
            x, l_aux = self._ffn(x, blk, i, train=train, rng=rng)
            total_aux = total_aux + l_aux
        c = self.config
        return layer_norm(x, params["ln_f_scale"], params["ln_f_bias"],
                          c.eps), total_aux

    def forward_hidden(self, params, input_ids, *, rngs=None,
                       train: bool = False):
        rng = rngs.get("dropout") if isinstance(rngs, dict) else rngs
        x = self._embed(params, input_ids)
        hidden, _ = self._forward_blocks(params, x, rng=rng, train=train)
        return hidden

    def logits(self, params, hidden):
        return jnp.einsum("btd,vd->btv", hidden,
                          params["wte"].astype(hidden.dtype))

    def apply(self, params, batch, *, rngs=None, train: bool = False):
        c = self.config
        rng = rngs.get("dropout") if isinstance(rngs, dict) else rngs
        x = self._embed(params, batch["input_ids"])
        hidden, total_aux = self._forward_blocks(params, x, rng=rng, train=train)
        logits = self.logits(params, hidden)
        ce, n = cross_entropy_loss(logits, batch["labels"])
        loss = ce + c.aux_loss_weight * total_aux / max(len(self.moe_layers), 1)
        return loss, {"loss": loss, "ce_loss": ce, "aux_loss": total_aux, "ntokens": n}

    # --------------------------------------------------------- inference path
    def init_cache(self, batch_size: int, max_len: int, dtype=None):
        """Static-shape stacked KV cache, head-major, token-pair packed for
        Dh < 128 (same layout as the dense families;
        ops/attention.kv_pack_factor)."""
        c = self.config
        dtype = dtype or self.compute_dtype
        return {"k": alloc_kv_cache(c.num_layers, batch_size, c.num_heads,
                                    max_len, c.head_dim, dtype),
                "v": alloc_kv_cache(c.num_layers, batch_size, c.num_heads,
                                    max_len, c.head_dim, dtype),
                "index": jnp.zeros((), jnp.int32)}

    def forward_with_cache(self, params, input_ids, cache):
        """Prefill (T>1) or decode (T=1) step against the KV cache →
        (logits [B,T,V], new_cache). MoE layers gate in eval mode
        (eval_capacity_factor, no gate noise) so decode is deterministic;
        with experts sharded over the 'expert' mesh axis the dispatch and
        combine einsums lower to the same all-to-alls as training (ref
        inference/engine.py:274 expert groups at serve time)."""
        c = self.config
        idx = cache["index"]
        bt = cache.get("block_table")
        x = self._embed(params, input_ids, start_pos=idx)
        kc, vc = cache["k"], cache["v"]
        for i, blk in enumerate(params["blocks"]):
            x, kc, vc = self._attn(x, blk, cache=(kc, vc, i, idx, bt))
            x, _ = self._ffn(x, blk, i, train=False, rng=None)
        hidden = layer_norm(x, params["ln_f_scale"], params["ln_f_bias"], c.eps)
        out = {"k": kc, "v": vc, "index": idx + input_ids.shape[1]}
        if bt is not None:
            out["block_table"] = bt
        return self.logits(params, hidden), out
