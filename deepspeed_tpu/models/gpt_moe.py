"""GPT with MoE FFN layers — the BASELINE ladder's "GPT-MoE" config
(reference analog: Megatron-DeepSpeed MoE models driven through
``deepspeed.moe.layer.MoE``; test fixture analog SimpleMoEModel,
reference tests/unit/simple_model.py:70).

Interleaves dense and MoE transformer blocks (every other layer MoE, the
standard GShard/DeepSpeed-MoE pattern). Blocks are unrolled (not scanned)
because MoE and dense layers alternate structurally.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from deepspeed_tpu.models.base import cross_entropy_loss, gelu, layer_norm
from deepspeed_tpu.moe.layer import MoE
from deepspeed_tpu.ops.attention import multihead_attention


@dataclasses.dataclass
class GPTMoEConfig:
    vocab_size: int = 50257
    max_seq_len: int = 1024
    num_layers: int = 12
    hidden_size: int = 768
    num_heads: int = 12
    num_experts: int = 8
    moe_every: int = 2          # every Nth layer is MoE
    top_k: int = 1
    capacity_factor: float = 1.25
    aux_loss_weight: float = 0.01
    use_residual: bool = False  # PR-MoE
    eps: float = 1e-5

    @property
    def head_dim(self):
        return self.hidden_size // self.num_heads

    @classmethod
    def tiny(cls, **kw):
        kw.setdefault("vocab_size", 512)
        kw.setdefault("max_seq_len", 128)
        kw.setdefault("num_experts", 4)
        return cls(num_layers=2, hidden_size=64, num_heads=4, **kw)


class GPTMoEModel:
    def __init__(self, config: GPTMoEConfig, compute_dtype=jnp.bfloat16):
        self.config = config
        self.compute_dtype = compute_dtype
        c = config
        self.moe_layers = [i for i in range(c.num_layers) if (i + 1) % c.moe_every == 0]
        self.moe = MoE(c.hidden_size, c.num_experts, k=c.top_k,
                       capacity_factor=c.capacity_factor,
                       use_residual=c.use_residual)

    def init(self, rng):
        c = self.config
        d = c.hidden_size
        keys = jax.random.split(rng, 2 * c.num_layers + 3)
        init = jax.nn.initializers.normal(0.02)
        blocks = []
        for i in range(c.num_layers):
            k1, k2 = keys[2 * i], keys[2 * i + 1]
            blk = {
                "ln1_scale": jnp.ones((d,)), "ln1_bias": jnp.zeros((d,)),
                "qkv_w": init(k1, (d, 3 * d), jnp.float32),
                "qkv_b": jnp.zeros((3 * d,)),
                "out_w": init(k2, (d, d), jnp.float32) / (2 * c.num_layers) ** 0.5,
                "out_b": jnp.zeros((d,)),
                "ln2_scale": jnp.ones((d,)), "ln2_bias": jnp.zeros((d,)),
            }
            if i in self.moe_layers:
                blk["moe"] = self.moe.init(jax.random.fold_in(k2, 7))
            else:
                k3 = jax.random.fold_in(k1, 13)
                blk["mlp_fc_w"] = init(k3, (d, 4 * d), jnp.float32)
                blk["mlp_fc_b"] = jnp.zeros((4 * d,))
                blk["mlp_out_w"] = init(jax.random.fold_in(k3, 1), (4 * d, d),
                                        jnp.float32) / (2 * c.num_layers) ** 0.5
                blk["mlp_out_b"] = jnp.zeros((d,))
            blocks.append(blk)
        return {
            "wte": init(keys[-3], (c.vocab_size, d), jnp.float32),
            "wpe": init(keys[-2], (c.max_seq_len, d), jnp.float32),
            "blocks": blocks,
            "ln_f_scale": jnp.ones((d,)), "ln_f_bias": jnp.zeros((d,)),
        }

    def logical_axes(self):
        c = self.config
        d_axes = {
            "ln1_scale": ("hidden",), "ln1_bias": ("hidden",),
            "qkv_w": ("hidden", "heads"), "qkv_b": ("heads",),
            "out_w": ("heads", "hidden"), "out_b": ("hidden",),
            "ln2_scale": ("hidden",), "ln2_bias": ("hidden",),
        }
        blocks = []
        for i in range(c.num_layers):
            blk = dict(d_axes)
            if i in self.moe_layers:
                blk["moe"] = self.moe.logical_axes()
            else:
                blk.update({"mlp_fc_w": ("hidden", "mlp"), "mlp_fc_b": ("mlp",),
                            "mlp_out_w": ("mlp", "hidden"), "mlp_out_b": ("hidden",)})
            blocks.append(blk)
        return {"wte": ("vocab_in", "hidden"), "wpe": ("seq", "hidden"),
                "blocks": blocks, "ln_f_scale": ("hidden",), "ln_f_bias": ("hidden",)}

    def _attn(self, x, blk):
        c = self.config
        b, t, d = x.shape
        y = layer_norm(x, blk["ln1_scale"], blk["ln1_bias"], c.eps)
        qkv = y @ blk["qkv_w"].astype(y.dtype) + blk["qkv_b"].astype(y.dtype)
        q, k_, v_ = jnp.split(qkv, 3, axis=-1)
        shape = (b, t, c.num_heads, c.head_dim)
        attn = multihead_attention(q.reshape(shape), k_.reshape(shape),
                                   v_.reshape(shape), causal=True)
        return x + attn.reshape(b, t, d) @ blk["out_w"].astype(x.dtype) + \
            blk["out_b"].astype(x.dtype)

    def apply(self, params, batch, *, rngs=None, train: bool = False):
        c = self.config
        ids = batch["input_ids"]
        b, t = ids.shape
        x = params["wte"].astype(self.compute_dtype)[ids]
        x = x + params["wpe"].astype(self.compute_dtype)[:t][None]
        rng = rngs.get("dropout") if isinstance(rngs, dict) else rngs
        total_aux = jnp.zeros((), jnp.float32)
        for i, blk in enumerate(params["blocks"]):
            x = self._attn(x, blk)
            y = layer_norm(x, blk["ln2_scale"], blk["ln2_bias"], c.eps)
            if i in self.moe_layers:
                sub = jax.random.fold_in(rng, i) if rng is not None else None
                moe_out, l_aux, _ = self.moe.apply(blk["moe"], y, train=train, rng=sub)
                x = x + moe_out
                total_aux = total_aux + l_aux
            else:
                h = gelu(y @ blk["mlp_fc_w"].astype(y.dtype) +
                         blk["mlp_fc_b"].astype(y.dtype))
                x = x + h @ blk["mlp_out_w"].astype(x.dtype) + \
                    blk["mlp_out_b"].astype(x.dtype)
        x = layer_norm(x, params["ln_f_scale"], params["ln_f_bias"], c.eps)
        logits = jnp.einsum("btd,vd->btv", x, params["wte"].astype(x.dtype))
        ce, n = cross_entropy_loss(logits, batch["labels"])
        loss = ce + c.aux_loss_weight * total_aux / max(len(self.moe_layers), 1)
        return loss, {"loss": loss, "ce_loss": ce, "aux_loss": total_aux, "ntokens": n}
