"""CLIP text encoder, TPU-first.

Reference analog: the CLIP serving path of the diffusers pillar —
``module_inject/containers/clip.py`` (HFCLIPLayerPolicy routes
CLIPEncoderLayer into the fused GPT inference kernels) and the text-encoder
half of DeepSpeed-Diffusers. Same scanned-stack design as the other model
families: one compiled pre-LN encoder block, causal text mask (CLIP text
towers are autoregressive), quick-gelu activation, final LN, pooled output
at the EOS position.

batch = {"input_ids" [B, T]}; ``forward_hidden`` returns [B, T, D] and
``pooled`` the EOS-token embedding (HF convention: position of the largest
token id, which is EOS for CLIP vocabularies).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from deepspeed_tpu.models.base import layer_norm
from deepspeed_tpu.ops.attention import multihead_attention

_ACTS = {
    "quick_gelu": lambda x: x * jax.nn.sigmoid(1.702 * x),
    "gelu": lambda x: jax.nn.gelu(x, approximate=False),
    "gelu_new": lambda x: jax.nn.gelu(x, approximate=True),
}


@dataclasses.dataclass
class CLIPTextConfig:
    vocab_size: int = 49408
    max_seq_len: int = 77
    num_layers: int = 12
    hidden_size: int = 512
    num_heads: int = 8
    mlp_dim: int = 2048
    eps: float = 1e-5
    hidden_act: str = "quick_gelu"
    projection_dim: int = 0        # 0 = no text projection head

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads


class CLIPTextModel:
    """Text-tower ModelSpec (feature extractor; no loss head)."""

    def __init__(self, config: CLIPTextConfig, compute_dtype=jnp.float32):
        assert config.hidden_act in _ACTS, config.hidden_act
        self.config = config
        self.compute_dtype = compute_dtype
        self._act = _ACTS[config.hidden_act]

    def init(self, rng):
        c = self.config
        k = jax.random.split(rng, 8)
        d, l, m = c.hidden_size, c.num_layers, c.mlp_dim
        init = jax.nn.initializers.normal(0.02)
        params = {
            "wte": init(k[0], (c.vocab_size, d), jnp.float32),
            "wpe": init(k[1], (c.max_seq_len, d), jnp.float32),
            "blocks": {
                "ln1_scale": jnp.ones((l, d)), "ln1_bias": jnp.zeros((l, d)),
                "qkv_w": init(k[2], (l, d, 3 * d), jnp.float32),
                "qkv_b": jnp.zeros((l, 3 * d)),
                "attn_out_w": init(k[3], (l, d, d), jnp.float32),
                "attn_out_b": jnp.zeros((l, d)),
                "ln2_scale": jnp.ones((l, d)), "ln2_bias": jnp.zeros((l, d)),
                "mlp_fc_w": init(k[4], (l, d, m), jnp.float32),
                "mlp_fc_b": jnp.zeros((l, m)),
                "mlp_out_w": init(k[5], (l, m, d), jnp.float32),
                "mlp_out_b": jnp.zeros((l, d)),
            },
            "ln_f_scale": jnp.ones((d,)), "ln_f_bias": jnp.zeros((d,)),
        }
        if c.projection_dim:
            params["text_projection"] = init(k[6], (d, c.projection_dim),
                                             jnp.float32)
        return params

    def logical_axes(self):
        c = self.config
        axes = {
            "wte": ("vocab_in", "hidden"), "wpe": ("seq", "hidden"),
            "blocks": {
                "ln1_scale": ("layer", "hidden"),
                "ln1_bias": ("layer", "hidden"),
                "qkv_w": ("layer", "hidden", "heads"),
                "qkv_b": ("layer", "heads"),
                "attn_out_w": ("layer", "heads", "hidden"),
                "attn_out_b": ("layer", "hidden"),
                "ln2_scale": ("layer", "hidden"),
                "ln2_bias": ("layer", "hidden"),
                "mlp_fc_w": ("layer", "hidden", "mlp"),
                "mlp_fc_b": ("layer", "mlp"),
                "mlp_out_w": ("layer", "mlp", "hidden"),
                "mlp_out_b": ("layer", "hidden"),
            },
            "ln_f_scale": ("hidden",), "ln_f_bias": ("hidden",),
        }
        if c.projection_dim:
            axes["text_projection"] = ("hidden", None)
        return axes

    def _block(self, x, blk):
        c = self.config
        b, t, d = x.shape
        h, dh = c.num_heads, c.head_dim
        y = layer_norm(x, blk["ln1_scale"], blk["ln1_bias"], c.eps)
        qkv = jnp.einsum("btd,de->bte", y, blk["qkv_w"].astype(y.dtype)) + \
            blk["qkv_b"].astype(y.dtype)
        q, k_, v_ = (z.reshape(b, t, h, dh) for z in jnp.split(qkv, 3, -1))
        attn = multihead_attention(q, k_, v_, causal=True).reshape(b, t, d)
        x = x + jnp.einsum("btd,de->bte", attn,
                           blk["attn_out_w"].astype(x.dtype)) + \
            blk["attn_out_b"].astype(x.dtype)
        y = layer_norm(x, blk["ln2_scale"], blk["ln2_bias"], c.eps)
        mid = self._act(jnp.einsum("btd,dm->btm", y,
                                   blk["mlp_fc_w"].astype(y.dtype)) +
                        blk["mlp_fc_b"].astype(y.dtype))
        return x + jnp.einsum("btm,md->btd", mid,
                              blk["mlp_out_w"].astype(x.dtype)) + \
            blk["mlp_out_b"].astype(x.dtype)

    def forward_hidden(self, params, input_ids, *, rngs=None, train=False):
        c = self.config
        t = input_ids.shape[1]
        x = params["wte"].astype(self.compute_dtype)[input_ids]
        x = x + params["wpe"].astype(self.compute_dtype)[:t][None]

        def scan_body(x, blk):
            return self._block(x, blk), None

        x, _ = jax.lax.scan(scan_body, x, params["blocks"])
        return layer_norm(x, params["ln_f_scale"], params["ln_f_bias"], c.eps)

    def pooled(self, params, hidden, input_ids):
        """EOS-position embedding (HF: argmax of token ids), optionally
        projected."""
        eos = jnp.argmax(input_ids, axis=-1)
        p = jnp.take_along_axis(hidden, eos[:, None, None].repeat(
            hidden.shape[-1], axis=-1), axis=1)[:, 0]
        if "text_projection" in params:
            p = p @ params["text_projection"].astype(p.dtype)
        return p

    def apply(self, params, batch, *, rngs=None, train=False):
        hidden = self.forward_hidden(params, batch["input_ids"])
        return hidden, {"pooled": self.pooled(params, hidden,
                                              batch["input_ids"])}
