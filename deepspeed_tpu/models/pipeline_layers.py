"""Per-layer building blocks for PipelineModule models.

Analog of the reference's Megatron-style ``GPT2ModelPipe`` (built from
LayerSpecs over EmbeddingPipe / ParallelTransformerLayerPipe / the tied lm
head — the pattern PipelineModule was designed for, reference
runtime/pipe/module.py:85). Math matches ``models/gpt2.py`` exactly so a
pipelined run is numerically comparable to the fused scan model.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from deepspeed_tpu.models.base import cross_entropy_loss, gelu, layer_norm
from deepspeed_tpu.models.gpt2 import GPT2Config
from deepspeed_tpu.ops.attention import multihead_attention
from deepspeed_tpu.runtime.pipe.module import LayerSpec, PipelineModule, TiedLayerSpec


class GPT2EmbedLayer:
    """Token + position embedding (first pipeline stage input layer)."""

    def __init__(self, config: GPT2Config, compute_dtype=jnp.bfloat16):
        self.config = config
        self.compute_dtype = compute_dtype

    def init(self, rng):
        c = self.config
        k1, k2 = jax.random.split(rng)
        init = jax.nn.initializers.normal(0.02)
        return {"wte": init(k1, (c.vocab_size, c.hidden_size), jnp.float32),
                "wpe": init(k2, (c.max_seq_len, c.hidden_size), jnp.float32)}

    def apply(self, params, input_ids, *, rngs=None, train: bool = False):
        t = input_ids.shape[-1]
        x = params["wte"].astype(self.compute_dtype)[input_ids]
        return x + params["wpe"].astype(self.compute_dtype)[:t][None]

    def logical_axes(self):
        return {"wte": ("vocab_in", "hidden"), "wpe": ("seq", "hidden")}


def tied_lm_head(params, hidden):
    """Tied-head forward_fn: project through the embedding table
    (TiedLayerSpec re-use site; grads sum into the embed owner's params)."""
    w = params["wte"].astype(hidden.dtype)
    return jnp.einsum("btd,vd->btv", hidden, w)


class GPT2BlockLayer:
    """One transformer block — unstacked params (the pipeline engine stacks
    homogeneous runs of these into [stages, layers_per_stage, ...])."""

    def __init__(self, config: GPT2Config):
        self.config = config

    def init(self, rng):
        c = self.config
        d, m = c.hidden_size, c.mlp_dim
        k = jax.random.split(rng, 4)
        init = jax.nn.initializers.normal(0.02)
        depth_scale = (2 * c.num_layers) ** 0.5
        return {
            "ln1_scale": jnp.ones((d,)), "ln1_bias": jnp.zeros((d,)),
            "qkv_w": init(k[0], (d, 3 * d), jnp.float32),
            "qkv_b": jnp.zeros((3 * d,)),
            "attn_out_w": init(k[1], (d, d), jnp.float32) / depth_scale,
            "attn_out_b": jnp.zeros((d,)),
            "ln2_scale": jnp.ones((d,)), "ln2_bias": jnp.zeros((d,)),
            "mlp_fc_w": init(k[2], (d, m), jnp.float32),
            "mlp_fc_b": jnp.zeros((m,)),
            "mlp_out_w": init(k[3], (m, d), jnp.float32) / depth_scale,
            "mlp_out_b": jnp.zeros((d,)),
        }

    def logical_axes(self):
        """Per-param TP axes (unstacked; the pipeline adapter prepends the
        stage/layer dims). Mirrors GPT2Model.logical_axes' 'blocks' entry."""
        return {
            "ln1_scale": ("norm",), "ln1_bias": ("norm",),
            "qkv_w": ("hidden", "heads"),
            "qkv_b": ("heads",),
            "attn_out_w": ("heads", "hidden"),
            "attn_out_b": ("hidden",),
            "ln2_scale": ("norm",), "ln2_bias": ("norm",),
            "mlp_fc_w": ("hidden", "mlp"),
            "mlp_fc_b": ("mlp",),
            "mlp_out_w": ("mlp", "hidden"),
            "mlp_out_b": ("hidden",),
        }

    def apply(self, blk, x, *, rngs=None, train: bool = False):
        c = self.config
        b, t, d = x.shape
        h, dh = c.num_heads, c.head_dim
        y = layer_norm(x, blk["ln1_scale"], blk["ln1_bias"], c.eps)
        qkv = jnp.einsum("btd,de->bte", y, blk["qkv_w"].astype(y.dtype)) + \
            blk["qkv_b"].astype(y.dtype)
        q, k_, v_ = jnp.split(qkv, 3, axis=-1)
        # attention dropout matches the fused model (gpt2.py _block_impl):
        # applied only when training AND an rng key is threaded in — the
        # pipeline executors derive the key per (microbatch, global layer)
        # via PipelinedModelAdapter.layer_key
        rng = rngs.get("dropout") if isinstance(rngs, dict) else rngs
        drop_rng = None
        if train and c.dropout > 0.0 and rng is not None:
            rng, drop_rng = jax.random.split(rng)
        attn = multihead_attention(
            q.reshape(b, t, h, dh), k_.reshape(b, t, h, dh), v_.reshape(b, t, h, dh),
            causal=True,
            dropout_rate=c.dropout if (train and drop_rng is not None) else 0.0,
            dropout_rng=drop_rng)
        x = x + jnp.einsum("btd,de->bte", attn.reshape(b, t, d),
                           blk["attn_out_w"].astype(x.dtype)) + \
            blk["attn_out_b"].astype(x.dtype)
        y = layer_norm(x, blk["ln2_scale"], blk["ln2_bias"], c.eps)
        hmid = gelu(jnp.einsum("btd,dm->btm", y, blk["mlp_fc_w"].astype(y.dtype)) +
                    blk["mlp_fc_b"].astype(y.dtype))
        return x + jnp.einsum("btm,md->btd", hmid, blk["mlp_out_w"].astype(x.dtype)) + \
            blk["mlp_out_b"].astype(x.dtype)


class GPT2FinalNorm:
    def __init__(self, config: GPT2Config):
        self.config = config

    def init(self, rng):
        d = self.config.hidden_size
        return {"scale": jnp.ones((d,)), "bias": jnp.zeros((d,))}

    def apply(self, params, x, *, rngs=None, train: bool = False):
        return layer_norm(x, params["scale"], params["bias"], self.config.eps)

    def logical_axes(self):
        return {"scale": ("norm",), "bias": ("norm",)}


class GPT2LMHead:
    """Untied output projection (when tie_embeddings=False)."""

    def __init__(self, config: GPT2Config):
        self.config = config

    def init(self, rng):
        c = self.config
        return {"w": jax.nn.initializers.normal(0.02)(
            rng, (c.hidden_size, c.vocab_size), jnp.float32)}

    def apply(self, params, x, *, rngs=None, train: bool = False):
        return jnp.einsum("btd,dv->btv", x, params["w"].astype(x.dtype))

    def logical_axes(self):
        return {"w": ("hidden", "vocab")}


def lm_loss(logits, labels):
    return cross_entropy_loss(logits, labels)[0]


def gpt2_pipe(config: GPT2Config, num_stages: int = 2,
              compute_dtype=jnp.bfloat16,
              activation_checkpoint_interval: int = 0) -> PipelineModule:
    """GPT-2 as a PipelineModule (GPT2ModelPipe analog)."""
    layers = []
    if config.tie_embeddings:
        layers.append(TiedLayerSpec("embed", GPT2EmbedLayer, config, compute_dtype))
    else:
        layers.append(LayerSpec(GPT2EmbedLayer, config, compute_dtype))
    layers += [LayerSpec(GPT2BlockLayer, config) for _ in range(config.num_layers)]
    layers.append(LayerSpec(GPT2FinalNorm, config))
    if config.tie_embeddings:
        layers.append(TiedLayerSpec("embed", GPT2EmbedLayer, config, compute_dtype,
                                    forward_fn=tied_lm_head))
    else:
        layers.append(LayerSpec(GPT2LMHead, config))
    return PipelineModule(
        layers, num_stages=num_stages, loss_fn=lm_loss,
        activation_checkpoint_interval=activation_checkpoint_interval)
