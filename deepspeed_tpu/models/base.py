"""Model protocol + shared layers.

The engine (like the reference ``DeepSpeedEngine`` wrapping any nn.Module,
engine.py:181) accepts anything satisfying :class:`ModelSpec`:

    params        = model.init(rng)
    loss, metrics = model.apply(params, batch, rngs=..., train=True)
    axes          = model.logical_axes()   # pytree matching params, or None

``logical_axes`` names each parameter dimension ('hidden', 'mlp', 'heads',
'vocab', 'expert', 'layer', ...) — the PartitionPlan maps names to mesh axes
for TP/EP while ZeRO picks up the rest. Flax linen modules are adapted via
:class:`FlaxModelAdapter`.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Protocol, Tuple, runtime_checkable

import jax
import jax.numpy as jnp


@runtime_checkable
class ModelSpec(Protocol):
    def init(self, rng) -> Any: ...

    def apply(self, params, batch, *, rngs=None, train: bool = False): ...

    def logical_axes(self) -> Optional[Any]: ...


ATTN_IMPLS = ("dense", "flash", "ring", "ring_flash", "ulysses")


def sp_attention(attn_impl: str, q, k, v, *, causal: bool = True):
    """Dispatch to the non-dense attention ops: Pallas flash kernel, or the
    sequence-parallel ring / Ulysses forms (models stay topology-agnostic —
    the mesh comes from the globally-initialized topology)."""
    if attn_impl == "flash":
        from deepspeed_tpu.ops.flash_attention import flash_attention

        return flash_attention(q, k, v, causal)
    from deepspeed_tpu.ops.ring_attention import (
        ring_attention, ring_flash_attention, ulysses_attention)
    from deepspeed_tpu.utils import groups

    mesh = groups.get_mesh()
    if attn_impl == "ring":
        return ring_attention(q, k, v, mesh=mesh, causal=causal)
    if attn_impl == "ring_flash":
        return ring_flash_attention(q, k, v, mesh, causal)
    if attn_impl == "ulysses":
        return ulysses_attention(q, k, v, mesh=mesh, causal=causal)
    raise ValueError(f"unknown attn_impl {attn_impl!r}")


# ------------------------------------------------------------- shared layers
def layer_norm(x, scale, bias, eps: float = 1e-5):
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x32 - mean), axis=-1, keepdims=True)
    y = (x32 - mean) * jax.lax.rsqrt(var + eps)
    return (y * scale.astype(jnp.float32) + bias.astype(jnp.float32)).astype(x.dtype)


def rms_norm(x, scale, eps: float = 1e-6):
    x32 = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    return (x32 * jax.lax.rsqrt(var + eps) * scale.astype(jnp.float32)).astype(x.dtype)


def gelu(x):
    return jax.nn.gelu(x, approximate=True)


def qdot(eq, x, w):
    """einsum whose weight may be weight-only-int8 ``{"__q__", "__scale__"}``.

    The int8 tensor feeds the matmul directly — its int8→dtype convert
    fuses into the operand stream, so HBM reads stay 1 byte/weight — and
    the per-output-column scale multiplies the matmul OUTPUT
    (``sum_d x_d q_de * s_e == s_e * sum_d x_d q_de``). Materializing a
    dequantized bf16 weight first (round-3 ``dequant_block``) paid
    int8-read + bf16-write + bf16-read per tile, which is why int8 decode
    measured only ~1.4× bf16 instead of the ~2× that half the bytes
    should buy (round-4 VERDICT weak #3). Reference counterpart: the
    dequant-fused GEMMs in csrc/transformer/inference/csrc/gelu.cu +
    pt_binding.cpp (vector_matmul_int8 path)."""
    if isinstance(w, dict) and "__q__" in w:
        q, s = w["__q__"], w["__scale__"]
        layer = w.get("__layer__")
        stacked = layer is not None and q.ndim == 3
        d_in, e_out = (q.shape[1], q.shape[2]) if stacked \
            else (q.shape[0], q.shape[-1])
        # decode fast path: tiny activations, weight-streaming-bound — the
        # Pallas kernel keeps HBM reads at 1 byte/weight (int8 upcast
        # in-register on the way into the MXU). Every model's qdot call
        # contracts x's last dim against q's axis 0 with the output on
        # q's axis 1, so the flat [N, D] @ [D, E] form is general here.
        # Stacked weights (``__layer__`` views from models.base.layer_view)
        # reach the kernel WHOLE: it DMA-slices the layer itself, because
        # a host-side slice of an int8 custom-call operand materializes a
        # full per-step copy of the weight.
        lhs, rhs = eq.replace(" ", "").split("->")
        xs, ws = lhs.split(",")
        std_form = (len(ws) == 2 and ws[0] == xs[-1] and rhs == xs[:-1] + ws[1])
        n_rows = 1
        for dim in x.shape[:-1]:
            n_rows *= dim
        if (std_form and (q.ndim == 2 or stacked) and n_rows <= 32
                and d_in % 128 == 0 and e_out % 128 == 0
                and jax.default_backend() == "tpu"):
            from deepspeed_tpu.ops.int8_matmul import (_dma_plan,
                                                       int8_matmul_dma)

            # single-invocation manual-DMA kernel: divisor tiles over
            # arbitrary (128-aligned) dims with no per-grid-cell cost, so
            # divisor-hostile shapes (LLaMA's 11008) stay on the kernel
            # path instead of falling back to einsum-dequant (round-4
            # VERDICT #2)
            if _dma_plan(d_in, e_out) is not None:
                out2d = int8_matmul_dma(x.reshape(n_rows, x.shape[-1]),
                                        q, s, layer if stacked else None)
                return out2d.reshape(x.shape[:-1] + (e_out,))
        if stacked:  # einsum fallback: the dynamic layer slice fuses here
            q = jax.lax.dynamic_index_in_dim(q, layer, 0, keepdims=False)
            s = jax.lax.dynamic_index_in_dim(s, layer, 0, keepdims=False)
        out = jnp.einsum(eq, x, q.astype(x.dtype))
        return out * s.reshape((1,) * (out.ndim - 1) + (-1,)).astype(x.dtype)
    return jnp.einsum(eq, x, w.astype(x.dtype))


def embed_tokens(wte, input_ids, dtype):
    """Token-embedding gather whose table may be weight-only-int8
    ``{"__q__", "__scale__"}`` with PER-VOCAB-ROW scales (ISSUE 12
    satellite — the tied embedding was the deliberately-unquantized 77
    MB of the 125M int8 stream, PROFILE_DECODE.md). The row gather
    stays int8 (1 byte/element of HBM traffic) and each row's single
    scale multiplies after the gather — an EXACT dequantization per
    row, so embedding lookups carry no extra error beyond the row's
    quantization itself."""
    if isinstance(wte, dict) and "__q__" in wte:
        q, s = wte["__q__"], wte["__scale__"]
        return (q[input_ids].astype(dtype)
                * s.reshape(-1)[input_ids][..., None].astype(dtype))
    return wte.astype(dtype)[input_ids]


def tied_logits(hidden, wte):
    """Tied LM-head matmul ``[.., D] @ [V, D]^T -> [.., V]`` whose
    weight may be int8 with per-vocab-row scales: the scale is
    per OUTPUT column of the logits, so it multiplies the matmul
    result (``sum_d h_d q_vd * s_v == s_v * sum_d h_d q_vd``) — the
    same scale-on-output contract as :func:`qdot`. Logit parity vs the
    unquantized head is pinned by tests (argmax agreement + bounded
    max logit error)."""
    if isinstance(wte, dict) and "__q__" in wte:
        q, s = wte["__q__"], wte["__scale__"]
        out = jnp.einsum("btd,vd->btv", hidden, q.astype(hidden.dtype))
        return out * s.reshape(-1).astype(hidden.dtype)
    return jnp.einsum("btd,vd->btv", hidden, wte.astype(hidden.dtype))


def cache_positions(index, t: int):
    """Query positions for a KV-cache step — the cache carry API's single
    point of index polymorphism. ``index`` is the cache dict's ``"index"``
    entry: a SCALAR (uniform batch — generate()) yields ``[t]`` positions
    shared by every row; a PER-SLOT ``[B]`` vector (continuous batching —
    serving/engine.py) yields ``[B, t]`` so every slot is embedded at its
    own valid length. Models add the returned positions to their position
    tables (wpe gather / RoPE offset) and pass the raw ``index`` through
    to ops/attention.cached_attention, which masks each row's prefix."""
    if jnp.ndim(index) == 1:
        return index[:, None] + jnp.arange(t)[None, :]
    return index + jnp.arange(t)


def layer_view(blocks, i):
    """Per-layer view of a layer-stacked block tree for a scan body that
    indexes with its own counter: normal ``[L, ...]`` leaves are
    dynamic-indexed (XLA fuses the slice into the consuming einsum), but
    weight-quantized ``{"__q__", "__scale__"}`` dicts stay WHOLE with the
    layer recorded as ``__layer__`` — qdot's int8 kernel DMA-slices the
    layer in-kernel, because a host-side slice of an int8 custom-call
    operand materializes a full per-step copy of the weight (measured as
    the '66% of streaming bound' int8 serving ceiling at 6.7B)."""

    def walk(node):
        if isinstance(node, dict):
            if "__q__" in node:
                return {"__q__": node["__q__"],
                        "__scale__": node["__scale__"], "__layer__": i}
            return {k: walk(v) for k, v in node.items()}
        return jax.lax.dynamic_index_in_dim(node, i, 0, keepdims=False)

    return walk(blocks)


def cross_entropy_loss(logits, labels, ignore_index: int = -100):
    """Token-level CE in fp32 with masking; returns (mean_loss, n_valid)."""
    logits = logits.astype(jnp.float32)
    valid = labels != ignore_index
    safe_labels = jnp.where(valid, labels, 0)
    logz = jax.nn.logsumexp(logits, axis=-1)
    ll = jnp.take_along_axis(logits, safe_labels[..., None], axis=-1)[..., 0]
    nll = (logz - ll) * valid.astype(jnp.float32)
    n = jnp.maximum(valid.sum(), 1)
    return nll.sum() / n, n


def make_causal_lm_batch(input_ids):
    """inputs/labels from one token stream: predict token t+1 from <=t."""
    return {"input_ids": input_ids[:, :-1], "labels": input_ids[:, 1:]}


# ---------------------------------------------------------------- flax bridge
class FlaxModelAdapter:
    """Wraps a flax.linen module + loss_fn into the ModelSpec protocol."""

    def __init__(self, module, sample_batch, loss_fn: Callable, train_kwarg: str = "train"):
        self.module = module
        self.sample_batch = sample_batch
        self.loss_fn = loss_fn
        self.train_kwarg = train_kwarg

    def init(self, rng):
        variables = self.module.init(rng, self.sample_batch)
        return variables["params"]

    def apply(self, params, batch, *, rngs=None, train: bool = False):
        outputs = self.module.apply({"params": params}, batch,
                                    rngs=rngs if train else None)
        return self.loss_fn(outputs, batch)

    def logical_axes(self):
        return None
