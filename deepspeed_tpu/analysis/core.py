"""Shared static-analysis framework: walker, registry, findings, baseline.

One :class:`FileContext` per source file (source + AST + enclosing-symbol
map + suppression directives, parsed ONCE); a :class:`Corpus` over the
tree; :class:`LintPass` subclasses registered by id.  A pass sees each
in-scope file (:meth:`LintPass.check_file`) and, for whole-corpus
contracts like metric-name coverage, the assembled corpus
(:meth:`LintPass.finalize`).

Suppression directives (comments, parsed from the token stream so a
``#`` inside a string never counts):

  * ``# dstpu-lint: disable=<pass>[,<pass>...] -- <justification>``
    silences the named passes.  The justification is REQUIRED — a
    directive without one is itself a finding.
  * ``# dstpu-lint: fence=<reason>`` is the host-sync allowlist form:
    it marks a *sanctioned* device→host synchronization point (sentinel
    drain, telemetry fence, token emission) rather than a grandfathered
    sin, and only silences the ``host-sync`` pass.

A directive trailing code applies to the whole (possibly multi-line)
statement it sits on; a directive on a comment-only line applies to
the next code line's statement (stacked standalone directives all
target the same statement).  Directives that silence nothing are
reported (burn-down: stale suppressions must go).

Baseline: ``LINT_BASELINE.json`` at the repo root grandfathers findings
by (pass, path, symbol, message) with a required justification and a
``budget`` that the entry count may never exceed — entries that no
longer match anything are reported as stale so the file only shrinks.

Typed exit codes for every CLI built on this framework:
``EXIT_CLEAN`` (0) nothing unsuppressed; ``EXIT_FINDINGS`` (1)
unsuppressed findings / stale baseline / budget exceeded;
``EXIT_USAGE`` (2) unreadable input or bad arguments;
``EXIT_INTERNAL`` (3) a pass crashed (a lint bug, never a tree bug).
"""

from __future__ import annotations

import ast
import io
import json
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

EXIT_CLEAN = 0
EXIT_FINDINGS = 1
EXIT_USAGE = 2
EXIT_INTERNAL = 3


class UnknownPassError(KeyError):
    """An unknown pass id was requested (a usage error, EXIT_USAGE) —
    distinct from a KeyError raised by a buggy pass mid-run, which is an
    internal error (EXIT_INTERNAL)."""

DEFAULT_BASELINE_NAME = "LINT_BASELINE.json"

# directive grammar: "dstpu-lint:" then disable=<ids> -- <why>, or
# fence=<why> (spelled indirectly here so this comment is not itself one)
_DIRECTIVE_RE = re.compile(
    r"#\s*dstpu-lint:\s*(?P<kind>disable|fence)\s*=\s*(?P<rest>.*)$")


# --------------------------------------------------------------- findings
@dataclass(frozen=True)
class Finding:
    """One contract violation at one site."""

    pass_id: str
    path: str            # repo-relative, forward slashes
    line: int
    col: int
    message: str
    severity: str = "error"          # "error" | "warning"
    symbol: str = ""                 # enclosing Class.function qualname
    suggestion: str = ""             # the exact fix/shim to use

    def format(self) -> str:
        loc = f"{self.path}:{self.line}:{self.col}"
        sym = f" [{self.symbol}]" if self.symbol else ""
        out = f"{loc}: {self.severity}: [{self.pass_id}]{sym} {self.message}"
        if self.suggestion:
            out += f"\n    fix: {self.suggestion}"
        return out

    def to_json(self) -> dict:
        return {"pass": self.pass_id, "path": self.path, "line": self.line,
                "col": self.col, "severity": self.severity,
                "symbol": self.symbol, "message": self.message,
                "suggestion": self.suggestion}

    @classmethod
    def from_json(cls, d: dict) -> "Finding":
        """Inverse of :meth:`to_json` (the incremental cache round-trips
        findings through JSON; the pair is pinned by a test)."""
        return cls(pass_id=d["pass"], path=d["path"], line=int(d["line"]),
                   col=int(d["col"]), message=d["message"],
                   severity=d.get("severity", "error"),
                   symbol=d.get("symbol", ""),
                   suggestion=d.get("suggestion", ""))


# -------------------------------------------------------------- directives
@dataclass
class Directive:
    """One inline suppression comment."""

    line: int                  # line the directive SILENCES
    kind: str                  # "disable" | "fence"
    passes: Tuple[str, ...]    # empty for fence (host-sync only)
    reason: str
    src_line: int = 0          # line the COMMENT itself is on
    used: int = 0

    def silences(self, finding: Finding) -> bool:
        if self.kind == "fence":
            return finding.pass_id == "host-sync"
        return finding.pass_id in self.passes


def _next_code_line(lines: List[str], lineno: int) -> int:
    """First line after ``lineno`` that carries code (skips blank and
    comment-only lines, so stacked standalone directives all target the
    same statement)."""
    j = lineno + 1
    while j <= len(lines):
        s = lines[j - 1].strip()
        if s and not s.startswith("#"):
            return j
        j += 1
    return lineno + 1


def parse_directives(source: str, path: str = "<src>",
                     ) -> Tuple[Dict[int, List[Directive]], List[Finding]]:
    """Extract suppression directives from the comment tokens.

    Returns ``({line: [Directive, ...]}, [malformed-directive findings])``.
    A trailing comment's directive silences its own line's statement; a
    comment-only line's directive silences the next code line's.
    """
    directives: Dict[int, List[Directive]] = {}
    errors: List[Finding] = []
    lines = source.splitlines()
    try:
        tokens = [(t.start, t.string) for t in tokenize.generate_tokens(
            io.StringIO(source).readline) if t.type == tokenize.COMMENT]
    except (tokenize.TokenError, SyntaxError, IndentationError):
        # tolerate half-written files: fall back to a line regex (a '#'
        # inside a string could false-positive here, acceptable for the
        # degraded path)
        tokens = [((i, line.index("#")), line[line.index("#"):])
                  for i, line in enumerate(lines, 1) if "#" in line]
    for (lineno, col), text in tokens:
        m = _DIRECTIVE_RE.search(text)
        if not m:
            continue
        kind, rest = m.group("kind"), m.group("rest").strip()
        standalone = lineno <= len(lines) and \
            lines[lineno - 1][:col].strip() == ""
        target = _next_code_line(lines, lineno) if standalone else lineno
        if kind == "fence":
            if not rest:
                errors.append(Finding(
                    "lint-directive", path, lineno, col,
                    "fence directive without a reason: write "
                    "`# dstpu-lint: fence=<why this sync is sanctioned>`"))
                continue
            d = Directive(target, "fence", (), rest, src_line=lineno)
        else:
            left, sep, just = rest.partition("--")
            pass_ids = tuple(p.strip() for p in left.split(",") if p.strip())
            just = just.strip()
            if not pass_ids or not sep or not just:
                errors.append(Finding(
                    "lint-directive", path, lineno, col,
                    "disable directive needs pass ids AND a justification: "
                    "`# dstpu-lint: disable=<pass>[,<pass>] -- <why>`"))
                continue
            d = Directive(target, "disable", pass_ids, just,
                          src_line=lineno)
        directives.setdefault(target, []).append(d)
    return directives, errors


# ------------------------------------------------------------ file context
class FileContext:
    """One parsed source file: AST, enclosing-symbol map, directives."""

    def __init__(self, root: str, path: str):
        self.root = root
        self.path = path
        self.relpath = os.path.relpath(path, root).replace(os.sep, "/")
        with open(path, "r", encoding="utf-8") as f:
            self.source = f.read()
        self.tree: Optional[ast.AST] = None
        self.parse_error: Optional[str] = None
        try:
            self.tree = ast.parse(self.source, filename=path)
        except SyntaxError as e:
            self.parse_error = f"{type(e).__name__}: {e}"
        self.directives, self.directive_errors = parse_directives(
            self.source, self.relpath)
        self._symbols: Dict[int, str] = {}
        # smallest statement span covering each line (for compound
        # statements only the header lines count — a directive deep in
        # an `if` body must not silence a finding on its test)
        self._stmt_span: Dict[int, Tuple[int, int]] = {}
        if self.tree is not None:
            self._map_symbols(self.tree, ())
            for node in ast.walk(self.tree):
                if not isinstance(node, ast.stmt):
                    continue
                start = node.lineno
                end = getattr(node, "end_lineno", start)
                body = getattr(node, "body", None)
                if isinstance(body, list) and body \
                        and hasattr(body[0], "lineno"):
                    end = max(start, body[0].lineno - 1)
                for ln in range(start, end + 1):
                    prev = self._stmt_span.get(ln)
                    if prev is None or end - start < prev[1] - prev[0]:
                        self._stmt_span[ln] = (start, end)

    def _map_symbols(self, node: ast.AST, stack: Tuple[str, ...]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                sub = stack + (child.name,)
                for n in ast.walk(child):
                    if hasattr(n, "lineno"):
                        # innermost scope wins: overwrite as we descend
                        self._symbols[id(n)] = ".".join(sub)
                self._map_symbols(child, sub)
            else:
                self._map_symbols(child, stack)

    def symbol(self, node: ast.AST) -> str:
        return self._symbols.get(id(node), "")

    def stmt_span(self, line: int) -> Tuple[int, int]:
        """Line range of the smallest statement covering ``line`` —
        suppression directives apply statement-wide, so a fence trailing
        ANY line of a wrapped call silences the whole call."""
        return self._stmt_span.get(line, (line, line))

    def finding(self, pass_id: str, node: ast.AST, message: str, *,
                severity: str = "error", suggestion: str = "") -> Finding:
        return Finding(pass_id, self.relpath, getattr(node, "lineno", 1),
                       getattr(node, "col_offset", 0), message,
                       severity=severity, symbol=self.symbol(node),
                       suggestion=suggestion)


@dataclass
class Corpus:
    """Every parsed file plus the repo root (for README.md etc.)."""

    root: str
    files: List[FileContext] = field(default_factory=list)

    def by_relpath(self, relpath: str) -> Optional[FileContext]:
        for ctx in self.files:
            if ctx.relpath == relpath:
                return ctx
        return None


# ------------------------------------------------------------------ passes
class LintPass:
    """Base pass. Subclasses set ``id``/``title``/``scope`` and override
    :meth:`check_file` (per-file) and/or :meth:`finalize` (whole corpus,
    runs after every file was visited).  Passes that need phase-1
    interprocedural context (ISSUE 15) override :meth:`begin`, which
    runs once per lint with the assembled corpus BEFORE any file is
    visited — the place to grab the shared
    :func:`~deepspeed_tpu.analysis.index.ensure_index`."""

    id: str = ""
    title: str = ""
    #: relpath prefixes this pass cares about; empty = every file
    scope: Tuple[str, ...] = ()
    #: relpaths never visited (e.g. the shim a pass routes callers to)
    exempt: Tuple[str, ...] = ()

    def in_scope(self, relpath: str) -> bool:
        if any(relpath == e or relpath.startswith(e) for e in self.exempt):
            return False
        if not self.scope:
            return True
        return any(relpath == s or relpath.startswith(s)
                   for s in self.scope)

    def begin(self, corpus: Corpus) -> None:
        """Phase-1 hook: runs once with the whole corpus before any
        :meth:`check_file` call (build/borrow the shared index here)."""

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        return ()

    def finalize(self, corpus: Corpus) -> Iterable[Finding]:
        return ()


_REGISTRY: Dict[str, LintPass] = {}


def register(cls):
    """Class decorator: instantiate and register a pass by its id."""
    inst = cls()
    if not inst.id:
        raise ValueError(f"pass {cls.__name__} has no id")
    if inst.id in _REGISTRY:
        raise ValueError(f"duplicate pass id {inst.id!r}")
    _REGISTRY[inst.id] = inst
    return cls


def load_passes() -> Dict[str, LintPass]:
    """Import the pass modules (populating the registry) and return it."""
    from deepspeed_tpu.analysis import passes  # noqa: F401

    return dict(_REGISTRY)


def registered_passes() -> Dict[str, LintPass]:
    return dict(_REGISTRY)


# ---------------------------------------------------------------- baseline
@dataclass
class BaselineEntry:
    pass_id: str
    path: str
    symbol: str
    message: str
    justification: str
    count: int = 1
    matched: int = 0

    def matches(self, f: Finding) -> bool:
        return (self.pass_id == f.pass_id and self.path == f.path
                and self.symbol == f.symbol and self.message == f.message)

    def to_json(self) -> dict:
        out = {"pass": self.pass_id, "path": self.path,
               "symbol": self.symbol, "message": self.message,
               "justification": self.justification}
        if self.count != 1:
            out["count"] = self.count
        return out


@dataclass
class Baseline:
    budget: int = 0
    entries: List[BaselineEntry] = field(default_factory=list)

    @property
    def total(self) -> int:
        return sum(e.count for e in self.entries)

    @classmethod
    def load(cls, path: str) -> "Baseline":
        """Parse a baseline file; raises ValueError on malformed input
        (mapped to EXIT_USAGE by CLIs)."""
        with open(path, "r", encoding="utf-8") as f:
            raw = json.load(f)
        if not isinstance(raw, dict):
            raise ValueError("baseline must be a JSON object")
        entries = []
        for i, e in enumerate(raw.get("entries", [])):
            just = str(e.get("justification", "")).strip()
            if not just:
                raise ValueError(
                    f"baseline entry {i} has no justification — every "
                    "grandfathered finding must say why it is allowed")
            entries.append(BaselineEntry(
                pass_id=e["pass"], path=e["path"],
                symbol=e.get("symbol", ""), message=e["message"],
                justification=just, count=int(e.get("count", 1))))
        return cls(budget=int(raw.get("budget",
                                      sum(e.count for e in entries))),
                   entries=entries)

    def dump(self, path: str) -> None:
        with open(path, "w", encoding="utf-8") as f:
            json.dump({"budget": self.budget,
                       "entries": [e.to_json() for e in self.entries]},
                      f, indent=2, sort_keys=True)
            f.write("\n")


# ------------------------------------------------------------------ runner
@dataclass
class LintResult:
    findings: List[Finding] = field(default_factory=list)       # unsuppressed
    suppressed: List[Tuple[Finding, Directive]] = field(default_factory=list)
    baselined: List[Tuple[Finding, BaselineEntry]] = field(default_factory=list)
    stale_baseline: List[BaselineEntry] = field(default_factory=list)
    over_budget: int = 0            # baseline entries past the budget
    files_scanned: int = 0
    passes_run: Tuple[str, ...] = ()

    @property
    def clean(self) -> bool:
        return not self.findings and not self.stale_baseline \
            and self.over_budget == 0

    def to_json(self) -> dict:
        per_pass: Dict[str, int] = {}
        for f in self.findings:
            per_pass[f.pass_id] = per_pass.get(f.pass_id, 0) + 1
        return {
            "version": 1,
            "files_scanned": self.files_scanned,
            "passes_run": list(self.passes_run),
            "findings": [f.to_json() for f in self.findings],
            "findings_per_pass": per_pass,
            "suppressed": [
                {**f.to_json(), "directive": d.kind, "reason": d.reason}
                for f, d in self.suppressed],
            "baselined": [
                {**f.to_json(), "justification": e.justification}
                for f, e in self.baselined],
            "stale_baseline": [e.to_json() for e in self.stale_baseline],
            "over_budget": self.over_budget,
            "clean": self.clean,
        }


def iter_py_files(root: str,
                  subdirs: Sequence[str] = ("deepspeed_tpu",)) -> List[str]:
    out = []
    for sub in subdirs:
        base = os.path.join(root, sub)
        if os.path.isfile(base):
            out.append(base)
            continue
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    out.append(os.path.join(dirpath, fn))
    return out


def build_corpus(root: str,
                 subdirs: Sequence[str] = ("deepspeed_tpu",)) -> Corpus:
    corpus = Corpus(root=root)
    for path in iter_py_files(root, subdirs):
        corpus.files.append(FileContext(root, path))
    return corpus


def run_lint(root: str, *, pass_ids: Optional[Sequence[str]] = None,
             baseline: Optional[Baseline] = None,
             subdirs: Sequence[str] = ("deepspeed_tpu",),
             report_unused_directives: Optional[bool] = None,
             corpus: Optional[Corpus] = None,
             file_cache=None) -> LintResult:
    """Run the registered passes over ``root`` and fold in suppressions
    and the baseline.  ``pass_ids=None`` runs every registered pass;
    unused-directive reporting defaults to on only for full runs (a
    directive for a pass that was not selected is not stale).  Pass a
    pre-built ``corpus`` to reuse already-parsed files (the CLI shares
    one corpus between the lint and the jax-compat inventory).

    ``file_cache`` (incremental mode, ISSUE 15): any object with
    ``lookup(ctx) -> Optional[List[Finding]]`` and ``store(ctx,
    findings)``.  A hit replaces the per-file pass execution for that
    file; finalize passes, directive folding and the baseline always
    run fresh, so a cached and a cold run report identical findings by
    construction (pinned by test).  The cache provider is responsible
    for invalidating entries whose INTERPROCEDURAL inputs changed (see
    :mod:`deepspeed_tpu.analysis.incremental`).
    """
    all_passes = load_passes()
    if pass_ids is None:
        selected = list(all_passes.values())
    else:
        unknown = [p for p in pass_ids if p not in all_passes]
        if unknown:
            raise UnknownPassError(
                f"unknown pass id(s): {', '.join(unknown)} "
                f"(have: {', '.join(sorted(all_passes))})")
        selected = [all_passes[p] for p in pass_ids]
    if report_unused_directives is None:
        report_unused_directives = pass_ids is None

    if corpus is None:
        corpus = build_corpus(root, subdirs)
    for p in selected:
        p.begin(corpus)
    raw: List[Finding] = []
    for ctx in corpus.files:
        for fnd in ctx.directive_errors:
            raw.append(fnd)
        if ctx.parse_error is not None:
            raw.append(Finding("lint-parse", ctx.relpath, 1, 0,
                               f"file does not parse: {ctx.parse_error}"))
            continue
        cached = file_cache.lookup(ctx) if file_cache is not None else None
        if cached is not None:
            raw.extend(cached)
            continue
        file_findings: List[Finding] = []
        for p in selected:
            if p.in_scope(ctx.relpath):
                file_findings.extend(p.check_file(ctx))
        if file_cache is not None:
            file_cache.store(ctx, file_findings)
        raw.extend(file_findings)
    for p in selected:
        raw.extend(p.finalize(corpus))

    result = LintResult(files_scanned=len(corpus.files),
                        passes_run=tuple(p.id for p in selected))
    ctx_by_relpath = {c.relpath: c for c in corpus.files}

    # 1. inline suppressions
    survivors: List[Finding] = []
    for f in sorted(raw, key=lambda f: (f.path, f.line, f.col, f.pass_id)):
        ctx = ctx_by_relpath.get(f.path)
        directive = None
        if ctx is not None and f.pass_id not in ("lint-directive",
                                                 "lint-parse"):
            start, end = ctx.stmt_span(f.line)
            for ln in range(start, end + 1):
                for d in ctx.directives.get(ln, ()):
                    if d.silences(f):
                        directive = d
                        break
                if directive is not None:
                    break
        if directive is not None:
            directive.used += 1
            result.suppressed.append((f, directive))
        else:
            survivors.append(f)

    # 2. stale (unused) directives — suppressions must silence something
    if report_unused_directives:
        for ctx in corpus.files:
            for ds in ctx.directives.values():
                for d in ds:
                    if d.used == 0:
                        survivors.append(Finding(
                            "lint-directive", ctx.relpath,
                            d.src_line or d.line, 0,
                            f"unused {d.kind} directive (nothing on line "
                            f"{d.line} triggers the suppressed pass) — "
                            "remove it",
                            symbol=""))

    # 3. baseline
    if baseline is not None:
        for e in baseline.entries:
            e.matched = 0
        still: List[Finding] = []
        for f in survivors:
            entry = next((e for e in baseline.entries
                          if e.matched < e.count and e.matches(f)), None)
            if entry is not None:
                entry.matched += 1
                result.baselined.append((f, entry))
            else:
                still.append(f)
        survivors = still
        # stale entries only mean something when the pass that produced
        # them actually ran — never report them on --passes subset runs
        ran = set(result.passes_run)
        result.stale_baseline = [
            e for e in baseline.entries
            if e.matched < e.count and e.pass_id in ran]
        if baseline.total > baseline.budget:
            result.over_budget = baseline.total - baseline.budget

    result.findings = survivors
    return result
