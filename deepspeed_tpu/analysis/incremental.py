"""Incremental lint: per-file finding cache keyed on content hashes
(ISSUE 15).

Full-corpus and incremental runs MUST report byte-identical findings
(pinned by test).  The mechanism:

  * the cache (``.dstpu_lint_cache.json`` at the repo root, gitignored)
    stores, per file, the sha256 of its source and the per-file
    findings the passes produced for it — plus a header binding the
    cache to the PASS SET and a fingerprint of the analysis sources
    themselves (editing a pass invalidates every entry, so a stale
    cache can never mask a lint change);
  * on an incremental run, files whose hash matches reuse their cached
    findings and skip per-file pass execution.  Finalize passes,
    suppression folding and the baseline always run fresh;
  * **interprocedural invalidation**: a cached file's findings may
    depend on ANOTHER file's function summaries (the sharding-contract
    pass follows donations through the call graph).  Changed files
    therefore invalidate their whole dependent region — the reverse
    import closure from the phase-1 index, a conservative superset of
    the changed files' strongly-connected call-graph region — and the
    corpus-global inputs in ``GLOBAL_INPUTS`` (the axis registry and
    the VMEM capacity table's home, ``ops/autotune.py``) invalidate
    everything.  The kernel-plan ARTIFACT (AUTOTUNE_KERNELS_MEASURED
    .json) needs no cache edge only because it is consumed exclusively
    in ``finalize()``, which always runs fresh — a per-file pass that
    reads it must add it here first.

``scripts/dstpu_lint.py --changed-only`` wires this up.  ``git diff
--name-only`` (plus untracked files) feeds the CLI's changed-set
diagnostics and degrades gracefully to a hash-only run when git is
unavailable; the content hashes are ALWAYS the invalidation authority
— git is never trusted over content in either direction.
"""

from __future__ import annotations

import hashlib
import json
import os
import subprocess
from typing import Dict, List, Optional, Set

from deepspeed_tpu.analysis.core import Corpus, Finding
from deepspeed_tpu.analysis.index import ensure_index

CACHE_VERSION = 1
DEFAULT_CACHE_NAME = ".dstpu_lint_cache.json"

#: corpus-global lint inputs: a change here can move findings in ANY
#: file, so it invalidates the whole cache
GLOBAL_INPUTS = (
    "deepspeed_tpu/parallel/topology.py",    # sharding axis registry
    # vmem-budget parses its capacity table (DEFAULT_VMEM_MB /
    # SCOPED_VMEM_MAX_MB) from this file but applies it to KERNEL files
    # that never import it — no import edge reaches them, so a budget
    # change must drop everything
    "deepspeed_tpu/ops/autotune.py",
)


def source_digest(text: str) -> str:
    return hashlib.sha256(text.encode("utf-8")).hexdigest()


def lint_fingerprint(root: str) -> str:
    """Digest of the analysis framework itself (passes included) and
    the CLI — cached findings are only as current as the code that
    produced them."""
    h = hashlib.sha256()
    paths: List[str] = []
    adir = os.path.join(root, "deepspeed_tpu", "analysis")
    for dirpath, dirnames, filenames in os.walk(adir):
        dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                paths.append(os.path.join(dirpath, fn))
    paths.append(os.path.join(root, "scripts", "dstpu_lint.py"))
    for p in paths:
        try:
            with open(p, "rb") as f:
                h.update(p.encode())
                h.update(f.read())
        except OSError:
            continue
    return h.hexdigest()


class LintCache:
    """Per-file finding cache.  ``prepare`` must run before the lint
    (it drops every entry the current tree invalidates); ``lookup`` /
    ``store`` are the :func:`~deepspeed_tpu.analysis.core.run_lint`
    ``file_cache`` protocol."""

    def __init__(self, path: str, fingerprint: str,
                 pass_ids: Optional[List[str]] = None):
        self.path = path
        self.fingerprint = fingerprint
        self.pass_ids = sorted(pass_ids) if pass_ids is not None else None
        self.entries: Dict[str, dict] = {}
        self._digests: Dict[str, str] = {}   # relpath -> sha256 (prepare)
        self.hits = 0
        self.misses = 0

    # ------------------------------------------------------------- io
    @classmethod
    def load(cls, path: str, root: str,
             pass_ids: Optional[List[str]] = None) -> "LintCache":
        cache = cls(path, lint_fingerprint(root), pass_ids)
        try:
            with open(path, "r", encoding="utf-8") as f:
                raw = json.load(f)
            if (isinstance(raw, dict)
                    and raw.get("version") == CACHE_VERSION
                    and raw.get("fingerprint") == cache.fingerprint
                    and raw.get("passes") == cache.pass_ids
                    and isinstance(raw.get("files"), dict)):
                cache.entries = raw["files"]
        except (OSError, ValueError):
            pass     # cold cache
        return cache

    def save(self) -> None:
        try:
            with open(self.path, "w", encoding="utf-8") as f:
                json.dump({"version": CACHE_VERSION,
                           "fingerprint": self.fingerprint,
                           "passes": self.pass_ids,
                           "files": self.entries}, f, sort_keys=True)
                f.write("\n")
        except OSError:
            pass     # cache is an accelerator, never a failure mode

    # ---------------------------------------------------- invalidation
    def prepare(self, corpus: Corpus) -> Set[str]:
        """Drop every entry the current tree invalidates; returns the
        invalidated relpaths.  Content hashes are the sole authority
        (``git diff`` feeds only the CLI's stderr diagnostics): a file
        git reports touched whose content matches its cache entry
        stays cached (worktree-vs-HEAD drift is the common case right
        after a cache-populating run), and a change git cannot see
        (non-git root) is still caught by its hash."""
        changed: Set[str] = set()
        self._digests = {ctx.relpath: source_digest(ctx.source)
                         for ctx in corpus.files}
        for relpath, digest in self._digests.items():
            ent = self.entries.get(relpath)
            if ent is None or ent.get("hash") != digest:
                changed.add(relpath)
        # deleted files leave stale entries; their importers must rescan
        changed.update(set(self.entries) - set(self._digests))
        if not changed:
            return set()
        if any(c in GLOBAL_INPUTS for c in changed):
            region = set(self.entries)       # global input: drop all
        else:
            idx = ensure_index(corpus)
            region = changed | idx.dependents_of(changed)
        for relpath in region:
            self.entries.pop(relpath, None)
        return region

    # ------------------------------------------------- run_lint hooks
    def lookup(self, ctx) -> Optional[List[Finding]]:
        ent = self.entries.get(ctx.relpath)
        digest = self._digests.get(ctx.relpath) \
            or source_digest(ctx.source)
        if ent is None or ent.get("hash") != digest:
            self.misses += 1
            return None
        self.hits += 1
        return [Finding.from_json(d) for d in ent.get("findings", ())]

    def store(self, ctx, findings: List[Finding]) -> None:
        self.entries[ctx.relpath] = {
            "hash": self._digests.get(ctx.relpath)
            or source_digest(ctx.source),
            "findings": [f.to_json() for f in findings]}


def git_changed_files(root: str) -> Optional[Set[str]]:
    """Repo-relative changed + untracked files per git, or None when
    git is unavailable (callers fall back to hash-only / full runs)."""
    try:
        diff = subprocess.run(
            ["git", "diff", "--name-only", "HEAD", "--"], cwd=root,
            capture_output=True, text=True, timeout=30)
        if diff.returncode != 0:
            return None
        untracked = subprocess.run(
            ["git", "ls-files", "--others", "--exclude-standard"],
            cwd=root, capture_output=True, text=True, timeout=30)
        if untracked.returncode != 0:
            return None
    except (OSError, subprocess.SubprocessError):
        return None
    # one path per LINE (paths may contain spaces); git quotes unusual
    # paths with surrounding double quotes — strip them so the .py
    # suffix test still applies
    names = {line.strip().strip('"')
             for out in (diff.stdout, untracked.stdout)
             for line in out.splitlines() if line.strip()}
    return {n for n in names if n.endswith(".py")}
