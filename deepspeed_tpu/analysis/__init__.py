"""dstpu-lint — AST invariant checker for the repo's machine-enforceable
contracts (ISSUE 14; corpus-level dataflow + Pallas/TPU passes:
ISSUE 15 "dstpu-prove").

ISSUE 15 upgraded the per-file scanner to a two-phase corpus analysis:
phase 1 (:mod:`~deepspeed_tpu.analysis.index`) builds the module/symbol
index, import-resolved call graph, and per-function donation/aliasing
summaries; phase 2 passes receive the corpus through
:meth:`LintPass.begin` and check interprocedural contracts — donated
buffers followed through helpers (:mod:`~deepspeed_tpu.analysis.taint`
+ the ``sharding-contract`` pass), Pallas tile quanta and DMA pairing
(``pallas-tile``/``pallas-dma``), and VMEM budgets shared with
ops/autotune.py (``vmem-budget``).  Incremental runs
(:mod:`~deepspeed_tpu.analysis.incremental`) cache per-file findings
by content hash with dependent-region invalidation;
:mod:`~deepspeed_tpu.analysis.sarif` emits SARIF 2.1.0 for CI.

Every perf/robustness win since PR 2 rests on invariants the test suite
can only probe dynamically and per-site: zero recompiles after warmup,
no host synchronization inside engine hot loops except at declared
fences, typed errors in the serving paths, and metric-name / jax_compat
discipline.  This package makes those contracts *static*: one shared AST
walk over ``deepspeed_tpu/``, a registry of passes that each encode one
contract, inline suppressions that require a written justification, and
a committed baseline for grandfathered findings that may only burn down.

Entry points:

  * :func:`run_lint` — programmatic (used by tests and the CLI);
  * ``scripts/dstpu_lint.py`` — the CLI, wired into run_tier1.sh;
  * ``scripts/check_metric_names.py`` / ``check_slo_rules.py`` — thin
    shims over the :mod:`~deepspeed_tpu.analysis.passes.metric_names`
    and :mod:`~deepspeed_tpu.analysis.passes.slo_rules` passes (their
    CLIs and exit-code contracts predate the framework and are pinned
    by tests).

See the README "Static analysis" section for the pass catalog, the
suppression syntax, and the baseline burn-down workflow.
"""

from __future__ import annotations

from deepspeed_tpu.analysis.core import (  # noqa: F401
    EXIT_CLEAN, EXIT_FINDINGS, EXIT_INTERNAL, EXIT_USAGE,
    Baseline, BaselineEntry, Corpus, Directive, FileContext, Finding,
    LintPass, LintResult, load_passes, registered_passes, run_lint)
