"""dstpu-lint — AST invariant checker for the repo's machine-enforceable
contracts (ISSUE 14).

Every perf/robustness win since PR 2 rests on invariants the test suite
can only probe dynamically and per-site: zero recompiles after warmup,
no host synchronization inside engine hot loops except at declared
fences, typed errors in the serving paths, and metric-name / jax_compat
discipline.  This package makes those contracts *static*: one shared AST
walk over ``deepspeed_tpu/``, a registry of passes that each encode one
contract, inline suppressions that require a written justification, and
a committed baseline for grandfathered findings that may only burn down.

Entry points:

  * :func:`run_lint` — programmatic (used by tests and the CLI);
  * ``scripts/dstpu_lint.py`` — the CLI, wired into run_tier1.sh;
  * ``scripts/check_metric_names.py`` / ``check_slo_rules.py`` — thin
    shims over the :mod:`~deepspeed_tpu.analysis.passes.metric_names`
    and :mod:`~deepspeed_tpu.analysis.passes.slo_rules` passes (their
    CLIs and exit-code contracts predate the framework and are pinned
    by tests).

See the README "Static analysis" section for the pass catalog, the
suppression syntax, and the baseline burn-down workflow.
"""

from __future__ import annotations

from deepspeed_tpu.analysis.core import (  # noqa: F401
    EXIT_CLEAN, EXIT_FINDINGS, EXIT_INTERNAL, EXIT_USAGE,
    Baseline, BaselineEntry, Corpus, Directive, FileContext, Finding,
    LintPass, LintResult, load_passes, registered_passes, run_lint)
