"""slo-rules — the built-in DEFAULT_SLO_CONFIG must validate.

Migrated from ``scripts/check_slo_rules.py`` (ISSUE 13 satellite) onto
the pass framework; the script stays as the CLI for validating
arbitrary config files (exit 0/1/2 contract pinned by
tests/unit/telemetry/test_slo_plane.py).  As a pass it pins the config
every engine runs when none is supplied: unknown SLI names, malformed
windows, and burn thresholds that can NEVER fire (a rule that looks
armed but is dead) fail the lint before they ship.
"""

from __future__ import annotations

from deepspeed_tpu.analysis.core import Corpus, Finding, LintPass, register

_SLO_PATH = "deepspeed_tpu/telemetry/slo.py"


@register
class SloRulesPass(LintPass):
    id = "slo-rules"
    title = "the built-in DEFAULT_SLO_CONFIG validates"

    #: test seam: swap in a known-bad config to prove the pass fires
    config_override = None

    def finalize(self, corpus: Corpus):
        # the default config only matters on trees that ship it (the
        # fixture corpora in tests/unit/analysis don't)
        if corpus.by_relpath(_SLO_PATH) is None:
            return
        from deepspeed_tpu.telemetry.slo import (DEFAULT_SLO_CONFIG,
                                                 validate_slo_config)

        cfg = self.config_override or DEFAULT_SLO_CONFIG
        for err in validate_slo_config(cfg):
            yield Finding(
                self.id, _SLO_PATH, 1, 0,
                f"built-in DEFAULT_SLO_CONFIG invalid: {err}",
                suggestion="fix the shipped default (every engine runs "
                "it when no SLO config is supplied)")
