"""Small shared AST helpers for the lint passes (stdlib only)."""

from __future__ import annotations

import ast
from typing import Iterator, Optional, Tuple

# canonical home is the (cycle-free) phase-1 index module — this side
# of the package re-exports so every pass shares ONE implementation
from deepspeed_tpu.analysis.index import (attr_chain,   # noqa: F401
                                          is_jit_call)


def call_name(node: ast.Call) -> str:
    """Trailing name of the called object: ``jax.device_get(...)`` ->
    'device_get', ``device_get(...)`` -> 'device_get', else ''."""
    if isinstance(node.func, ast.Attribute):
        return node.func.attr
    if isinstance(node.func, ast.Name):
        return node.func.id
    return ""


def expr_root(node: ast.AST) -> Tuple[str, ...]:
    """Leading names of an Attribute/Subscript chain:
    ``self.cache.lengths[i]`` -> ('self', 'cache', 'lengths')."""
    parts = []
    while True:
        if isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        elif isinstance(node, ast.Subscript):
            node = node.value
        elif isinstance(node, ast.Name):
            parts.append(node.id)
            return tuple(reversed(parts))
        else:
            return ()


def walk_with_parents(tree: ast.AST) -> Iterator[Tuple[ast.AST, list]]:
    """Yield ``(node, ancestors)`` for every node, ancestors outermost
    first (one shared, mutated list — copy if you keep it)."""
    stack: list = []

    def rec(node):
        yield node, stack
        stack.append(node)
        for child in ast.iter_child_nodes(node):
            yield from rec(child)
        stack.pop()

    yield from rec(tree)


def enclosing_function(ancestors) -> Optional[ast.AST]:
    for a in reversed(ancestors):
        if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return a
    return None


def in_loop(ancestors, *, stop_at: ast.AST = None) -> bool:
    """True when any ancestor below ``stop_at`` is a For/While."""
    for a in reversed(ancestors):
        if a is stop_at:
            return False
        if isinstance(a, (ast.For, ast.AsyncFor, ast.While)):
            return True
    return False
