"""sharding-contract — interprocedural donation taint + one mesh-axis
registry (ISSUE 15).

Two contracts, both invisible to per-file scans:

**Donation across call boundaries.**  The per-scope ``donation-safety``
pass goes blind the moment a donated array crosses a call: a helper
that donates its argument (``def consume(state): return _step(state)``
with ``_step = jax.jit(..., donate_argnums=(0,))``), or a donating
callable bound on ``self`` in ``__init__`` and invoked from another
method.  Phase 1 (:mod:`deepspeed_tpu.analysis.index`) summarizes every
function — params donated directly, via ``self``-attribute donating
callables, via module-level jit binds, or TRANSITIVELY through calls —
and this pass replays the same linearized read-after-donate scan
(:mod:`deepspeed_tpu.analysis.taint`) with those summaries as the
taint sources.  The two source sets are disjoint (local binds belong
to donation-safety), so one read is never double-reported.  The
acceptance fixture: fn A passes a buffer to helper B whose summary
donates it, then A reads the buffer → flagged; the safe twin (helper
consumes and returns fresh, caller rebinds) stays silent.

**Mesh axis names.**  ``P("dta")`` inside a 4-D mesh program shards
onto a nonexistent axis and fails at trace time — on the LAST
machine-size config you test, not the first.  The repo declares ONE
axis registry (``parallel/topology.py``'s ``MESH_AXES``); every string
literal used as a mesh axis — in ``P(...)``/``PartitionSpec``,
``shard_map``'s ``axis_names``, ``Mesh(devices, (...))``, an
``axis_name=`` kwarg, or a collective's axis argument — must name a
registered axis.  Variables pass through unchecked (ring attention
takes its axis as a parameter); only provable literals are held to the
registry, which is parsed from the corpus so the lint tracks the code,
not a copy of it.
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional, Set, Tuple

from deepspeed_tpu.analysis.core import Corpus, FileContext, LintPass, \
    register
from deepspeed_tpu.analysis.index import CorpusIndex, ensure_index, \
    module_name
from deepspeed_tpu.analysis.passes._ast_util import call_name
from deepspeed_tpu.analysis.passes.donation import SCOPES as DONATION_SCOPES
from deepspeed_tpu.analysis.taint import scan_function

AXIS_REGISTRY_PATH = "deepspeed_tpu/parallel/topology.py"

#: fallback when a (synthetic) tree ships no topology module — mirrors
#: parallel/topology.py's MESH_AXES and is pinned against it by test
DEFAULT_AXES = ("pipe", "data", "expert", "seq", "model")

_SPEC_CALLS = ("P", "PartitionSpec")
_COLLECTIVES = ("psum", "pmean", "pmax", "pmin", "all_gather",
                "psum_scatter", "all_to_all", "axis_index", "pswapaxes",
                "pcast_varying", "ppermute")
_AXIS_KWARGS = ("axis_name", "axis_names")


def _axis_literals(node: ast.AST) -> Iterable[Tuple[str, ast.AST]]:
    """String literals inside an axis-bearing expression (tuples/sets/
    lists recursed; anything else skipped)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        yield node.value, node
    elif isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        for e in node.elts:
            yield from _axis_literals(e)


@register
class ShardingContractPass(LintPass):
    id = "sharding-contract"
    title = "donations hold across call boundaries; mesh axes come " \
            "from the declared registry"
    scope = ()            # axis literals are checked corpus-wide

    def __init__(self) -> None:
        self._index: Optional[CorpusIndex] = None
        self._axes: Set[str] = set(DEFAULT_AXES)

    # ------------------------------------------------------- phase 1
    def begin(self, corpus: Corpus) -> None:
        self._index = ensure_index(corpus)
        self._axes = self._load_registry(corpus)

    @staticmethod
    def _load_registry(corpus: Corpus) -> Set[str]:
        ctx = corpus.by_relpath(AXIS_REGISTRY_PATH)
        if ctx is None or ctx.tree is None:
            return set(DEFAULT_AXES)
        axes: Set[str] = set()
        for node in ctx.tree.body:
            if not isinstance(node, ast.Assign):
                continue
            for tgt in node.targets:
                if not isinstance(tgt, ast.Name):
                    continue
                if tgt.id.endswith("_AXIS") \
                        and isinstance(node.value, ast.Constant) \
                        and isinstance(node.value.value, str):
                    axes.add(node.value.value)
                elif tgt.id == "MESH_AXES":
                    axes.update(v for v, _ in _axis_literals(node.value))
        return axes or set(DEFAULT_AXES)

    # ------------------------------------------------------- phase 2
    def check_file(self, ctx: FileContext) -> Iterable:
        yield from self._check_axes(ctx)
        if any(ctx.relpath.startswith(s) for s in DONATION_SCOPES):
            yield from self._check_donation(ctx)

    def _check_donation(self, ctx: FileContext) -> Iterable:
        idx = self._index
        if idx is None:
            return
        module = module_name(ctx.relpath)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            qual = ctx.symbol(node) or node.name

            def resolve(call: ast.Call, _qual=qual):
                return idx.summary_for_call(module, _qual, call)

            def resolve_alias(call: ast.Call, _qual=qual):
                return idx.alias_positions_for_call(module, _qual, call)

            yield from scan_function(
                ctx, node, pass_id=self.id, resolve_call=resolve,
                resolve_alias=resolve_alias,
                track_local_binds=False,
                suggestion="use the callee's outputs (rebind the "
                "reference), read before the donating call, or make "
                "the helper consume-and-return-fresh")

    def _check_axes(self, ctx: FileContext) -> Iterable:
        if not self._axes:
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            sites = []
            if name in _SPEC_CALLS:
                for a in node.args:
                    sites.extend(_axis_literals(a))
            elif name == "Mesh" and len(node.args) >= 2:
                sites.extend(_axis_literals(node.args[1]))
            elif name in _COLLECTIVES:
                # axis position: `axis_index(axis)` takes it first,
                # every other collective takes (value, axis)
                p = 0 if name == "axis_index" else 1
                for a in node.args[p:p + 1]:
                    sites.extend(_axis_literals(a))
            for kw in node.keywords:
                if kw.arg in _AXIS_KWARGS:
                    sites.extend(_axis_literals(kw.value))
            for axis, site in sites:
                if axis not in self._axes:
                    yield ctx.finding(
                        self.id, site,
                        f"mesh axis `{axis}` is not in the declared "
                        "axis registry "
                        f"({', '.join(sorted(self._axes))}) — sharding "
                        "onto an undeclared axis fails at trace time "
                        "on the first multi-axis mesh",
                        suggestion="use a registered axis from "
                        "parallel/topology.py MESH_AXES (or register "
                        "the new axis there, once, with its meaning)")
