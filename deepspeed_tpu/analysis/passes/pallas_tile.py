"""pallas-tile — dtype-dependent TPU tile quanta on constant kernel
shapes (ISSUE 15).

The TPU stores arrays in HBM/VMEM tiles whose minor dim is ALWAYS 128
lanes and whose sublane count depends on itemsize: 8×128 for fp32,
16×128 for bf16, 32×128 for int8/fp8.  The repo has paid for this class
of bug at runtime twice — PR 11's int8 path had to "sidestep int8's
32-row HBM tile quantum" with whole-block windows, and PR 2's decode
kernel RMWs "the 8-aligned pair-row window" because HBM tiling forbids
single-row writes.  This pass proves the statically-provable half:

  * **T1 — VMEM scratch tiling**: a ``pltpu.VMEM(shape, dtype)``
    scratch whose minor dim folds to a constant must tile to the
    128-lane quantum (1 is sanctioned — flash keeps rank-2 ``(bq, 1)``
    online-softmax state); a 1-byte scratch (int8/fp8) whose sublane
    dim folds must cover whole 32-row tiles.
  * **T2 — DMA window alignment**: a ``pl.ds(start, n)`` slice in the
    sublane position of a ``make_async_copy`` ref with constant ``n``
    must be a multiple of the buffer dtype's window quantum (8 rows for
    >=2-byte dtypes, 32 for int8/fp8 — resolved through the kernel's
    positional param map when provable, the universal 8 otherwise); a
    constant ``pl.ds`` in the MINOR position must move whole 128-lane
    groups.
  * **T3 — BlockSpec block shapes**: constant block dims must respect
    the same quanta (minor: None/1/128-multiple; sublane: 8-multiple).

Everything is evaluated from constant BlockSpec/slice arithmetic
(module constants and single-assignment locals folded); data-dependent
shapes fold to "unknown" and stay silent — the pass can miss, never
hallucinate.  The seeded-mutation tier-1 tests pin the teeth: shrinking
the int8 weight-tile DMA window in ops/int8_matmul.py to 8 rows fails
this pass, and therefore tier-1.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable

from deepspeed_tpu.analysis.core import FileContext, LintPass, register
from deepspeed_tpu.analysis.passes._pallas_util import (
    DTYPES, LANES, UNIVERSAL_SUBLANE, Env, PallasCallInfo, buffer_root,
    collect_assigns, is_call_named as _is_call_named, iter_pallas_calls)

SCOPES = ("deepspeed_tpu/ops/",)

_TILE_HINT = ("tile to the dtype quantum (8x128 fp32, 16x128 bf16, "
              "32x128 int8/fp8) or keep the dim data-dependent and "
              "validated by the plan resolver")


def _is_ds(node: ast.AST) -> bool:
    return (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "ds")


@register
class PallasTilePass(LintPass):
    id = "pallas-tile"
    title = "constant kernel shapes respect dtype-dependent TPU tile " \
            "quanta"
    scope = SCOPES

    def check_file(self, ctx: FileContext) -> Iterable:
        if "pallas" not in ctx.source:
            return
        module_assigns = collect_assigns(ctx.tree)
        calls = list(iter_pallas_calls(ctx.tree, module_assigns))
        for info, env in calls:
            yield from self._check_scratch(ctx, info, env)
            yield from self._check_blockspecs(ctx, info, env)
        # windows once per kernel FUNCTION, with buffer dtypes merged
        # across all of its call sites — a param whose callers disagree
        # folds to unknown (silent), so no single caller is ever
        # authoritative for a shared kernel
        by_kernel: Dict[int, list] = {}
        for info, _ in calls:
            if info.kernel is not None:
                by_kernel.setdefault(id(info.kernel), []).append(info)
        for infos in by_kernel.values():
            primary = infos[0]
            for other in infos[1:]:
                for name, bi in primary.params.items():
                    ob = other.params.get(name)
                    if ob is None or ob.dtype != bi.dtype:
                        bi.dtype = None
            yield from self._check_windows(ctx, primary, module_assigns)

    # ------------------------------------------------- T1 VMEM scratch
    def _check_scratch(self, ctx, info: PallasCallInfo, env: Env):
        for s in info.scratch:
            if not _is_call_named(s, "VMEM") or not s.args:
                continue
            dims = env.fold_dims(s.args[0])
            if not dims:
                continue
            dtype = env.resolve_dtype(s.args[1]) if len(s.args) > 1 \
                else None
            minor = dims[-1]
            if isinstance(minor, int) and minor != 1 and minor % LANES:
                yield ctx.finding(
                    self.id, s,
                    f"VMEM scratch minor dim {minor} is not 128-lane "
                    "tiled (every TPU tile is <sublanes>x128; "
                    "off-quantum scratch pads to a full tile per row)",
                    suggestion=_TILE_HINT)
            if len(dims) >= 2 and dtype in DTYPES \
                    and DTYPES[dtype][0] == 1:
                sub = dims[-2]
                if isinstance(sub, int) and sub != 1 \
                        and sub % DTYPES[dtype][1]:
                    yield ctx.finding(
                        self.id, s,
                        f"{dtype} VMEM scratch sublane dim {sub} does "
                        f"not cover whole {DTYPES[dtype][1]}-row tiles "
                        "(1-byte dtypes tile 32x128; partial tiles "
                        "corrupt neighboring rows on write-back)",
                        suggestion=_TILE_HINT)

    # -------------------------------------------------- T3 block specs
    def _check_blockspecs(self, ctx, info: PallasCallInfo, env: Env):
        # out_specs come straight off the call site — unlike the param
        # map they need no flat-signature kernel to be checkable
        for spec in info.in_specs + info.out_specs:
            if not _is_call_named(spec, "BlockSpec") or not spec.args:
                continue
            dims = env.fold_dims(spec.args[0])
            if not dims or len(dims) < 2:
                continue
            minor, sub = dims[-1], dims[-2]
            if isinstance(minor, int) and minor != 1 and minor % LANES:
                yield ctx.finding(
                    self.id, spec,
                    f"BlockSpec minor block dim {minor} is not 128-lane "
                    "tiled — each grid step moves partial lane groups",
                    suggestion=_TILE_HINT)
            if isinstance(sub, int) and sub != 1 \
                    and sub % UNIVERSAL_SUBLANE:
                yield ctx.finding(
                    self.id, spec,
                    f"BlockSpec sublane block dim {sub} is not a "
                    "multiple of 8 (the weakest sublane tile quantum)",
                    suggestion=_TILE_HINT)

    # ------------------------------------------------- T2 DMA windows
    def _check_windows(self, ctx, info: PallasCallInfo, module_assigns):
        kernel = info.kernel
        deep = collect_assigns(kernel, deep=True)
        env = Env([deep, module_assigns])
        for node in ast.walk(kernel):
            if not _is_call_named(node, "make_async_copy"):
                continue
            for operand in node.args[:2]:
                yield from self._check_ref_slices(ctx, info, env, deep,
                                                 operand)

    def _check_ref_slices(self, ctx, info: PallasCallInfo, env: Env,
                          deep, operand: ast.AST):
        sub = operand
        # peel `X.at[...]` / plain subscripts down to the slice tuple
        if not isinstance(sub, ast.Subscript):
            return
        idx = sub.slice
        elems = list(idx.elts) if isinstance(idx, ast.Tuple) else [idx]
        if len(elems) < 2:
            return       # leading-dim picks only: no tile-edge motion
        root = buffer_root(operand, deep)
        dtype = None
        if root is not None and root in info.params:
            dtype = info.params[root].dtype
        wq = DTYPES[dtype][2] if dtype in DTYPES else UNIVERSAL_SUBLANE
        minor_e, sub_e = elems[-1], elems[-2]
        if _is_ds(minor_e) and len(minor_e.args) >= 2:
            size = env.fold(minor_e.args[1])
            if isinstance(size, int) and size % LANES:
                yield ctx.finding(
                    self.id, minor_e,
                    f"DMA slice of the minor dim moves {size} lanes — "
                    "Mosaic requires 128-aligned minor-dim slices "
                    f"(buffer `{root or '?'}`)",
                    suggestion=_TILE_HINT)
        if _is_ds(sub_e) and len(sub_e.args) >= 2:
            size = env.fold(sub_e.args[1])
            if isinstance(size, int) and size % wq:
                what = f"{dtype} " if dtype else ""
                yield ctx.finding(
                    self.id, sub_e,
                    f"DMA window covers {size} sublane rows of "
                    f"{what}buffer `{root or '?'}` — HBM tiling "
                    f"requires whole {wq}-row windows (a partial-tile "
                    "RMW corrupts the neighboring rows)",
                    suggestion="widen the window to the "
                    f"{wq}-row quantum (whole-block windows for 1-byte "
                    "payloads — the PR 11 idiom) or realign the start")
