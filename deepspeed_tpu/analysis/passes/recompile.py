"""recompile-hazard — jit program construction whose cache key can vary
per call.

The zero-recompile contract (pinned dynamically by every serving suite
via compile counters) has a static shadow: a ``jax.jit`` whose compiled
object is discarded, rebuilt per loop iteration, or keyed by a raw
length/shape re-traces on data the engine cannot bucket.  Three shapes
of the same bug:

  * **R1 immediate invocation** — ``jax.jit(f)(x)`` inside a function:
    the compiled callable is dropped on the floor, so every call of the
    enclosing function pays a fresh trace+compile.
  * **R2 construction in a loop** — ``jax.jit(...)`` in a For/While
    body compiles per iteration.  Exempt when the result is stored into
    a subscript (``cache[key] = jax.jit(...)``) — that is the repo's
    keyed-memoization idiom (inference/engine.py ``self._compiled``).
  * **R3 unbucketed cache key** — ``cache[<key with len()/.shape>] =
    jax.jit(...)``: the key takes a distinct value per prompt length,
    so the "cache" is a compile-per-request log.  Keys must be bucket
    ids (the ``self.buckets`` discipline serving/engine.py pins with
    compile-counter tests).
"""

from __future__ import annotations

import ast

from deepspeed_tpu.analysis.core import FileContext, LintPass, register
from deepspeed_tpu.analysis.passes._ast_util import (
    enclosing_function, in_loop, is_jit_call, walk_with_parents)

SCOPES = (
    "deepspeed_tpu/serving/",
    "deepspeed_tpu/inference/",
    "deepspeed_tpu/runtime/",
    "deepspeed_tpu/moe/",
)


def _key_varies(key: ast.AST) -> str:
    """Non-empty reason when a cache-key expression derives from a raw
    length or shape (compiles per distinct value)."""
    for n in ast.walk(key):
        if (isinstance(n, ast.Call) and isinstance(n.func, ast.Name)
                and n.func.id == "len"):
            return "len(...)"
        if isinstance(n, ast.Attribute) and n.attr == "shape":
            return ".shape"
    return ""


@register
class RecompileHazardPass(LintPass):
    id = "recompile-hazard"
    title = "jit construction whose cache key can vary per call"
    scope = SCOPES

    def check_file(self, ctx: FileContext):
        reported_inner = set()   # jit calls already covered by an R1 site
        for node, ancestors in walk_with_parents(ctx.tree):
            # R1: jax.jit(f)(...) — compiled object discarded
            if (isinstance(node, ast.Call) and is_jit_call(node.func)
                    and enclosing_function(ancestors) is not None):
                reported_inner.add(id(node.func))
                yield ctx.finding(
                    self.id, node,
                    "jit program invoked immediately: the compiled "
                    "callable is discarded, so every call re-traces and "
                    "re-compiles",
                    suggestion="build once (module scope or keyed cache) "
                    "and call the cached program")
                continue
            if not is_jit_call(node) or id(node) in reported_inner:
                continue
            fn = enclosing_function(ancestors)
            if fn is None:
                continue  # module-scope construction compiles once
            parent = ancestors[-1] if ancestors else None
            grand = ancestors[-2] if len(ancestors) >= 2 else None
            # the memoization idiom: cache[key] = jax.jit(...)
            memo_target = None
            if isinstance(parent, ast.Assign) and node is parent.value:
                tgt = parent.targets[0]
                if isinstance(tgt, ast.Subscript):
                    memo_target = tgt
            elif (isinstance(grand, ast.Assign)
                  and isinstance(grand.targets[0], ast.Subscript)):
                memo_target = grand.targets[0]
            # R3: keyed memoization with an unbucketed key
            if memo_target is not None:
                varies = _key_varies(memo_target.slice)
                if varies:
                    yield ctx.finding(
                        self.id, node,
                        f"jit cache key derives from {varies}: one "
                        "compile per distinct runtime value — the cache "
                        "is a compile-per-request log",
                        suggestion="key by bucket id (round the length "
                        "up to a fixed bucket set first)")
                continue
            # R2: un-memoized construction inside a loop
            if in_loop(ancestors, stop_at=fn):
                yield ctx.finding(
                    self.id, node,
                    "jax.jit constructed inside a loop compiles per "
                    "iteration",
                    suggestion="hoist out of the loop, or memoize into a "
                    "keyed cache (cache[key] = jax.jit(...))")
