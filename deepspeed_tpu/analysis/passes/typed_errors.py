"""typed-error — the serving stack raises its typed hierarchy, never
bare stdlib exceptions.

The fabric router's retry policy keys on exception TYPE (serving/
errors.py: ``TransientReplicaError`` retries, ``ReplicaCrashedError``
fails over, ``InvalidRequestError`` never retries) — a bare
``ValueError`` raised anywhere in ``deepspeed_tpu/serving/`` is
invisible to that machinery and to callers who catch the typed bases.
Every raise in the serving tree must use (a subclass of) the hierarchy;
the compat rule from ISSUE 9 still holds, so typed config/invariant
errors subclass ``ValueError``/``RuntimeError`` and pre-existing
``except ValueError`` call sites keep working.
"""

from __future__ import annotations

import ast

from deepspeed_tpu.analysis.core import FileContext, LintPass, register

SCOPES = ("deepspeed_tpu/serving/",)

#: bare type -> the typed replacement to suggest
_BARE = {
    "ValueError": "EngineConfigError (or an InvalidRequestError subclass "
                  "for per-request validation)",
    "RuntimeError": "EngineInvariantError (or SwapCapacityError / a "
                    "FabricError subclass)",
    "Exception": "a ServingError subclass",
    "TypeError": "EngineTypeError (keeps the TypeError lineage)",
}


@register
class TypedErrorPass(LintPass):
    id = "typed-error"
    title = "serving paths raise the typed hierarchy from serving/errors.py"
    scope = SCOPES
    exempt = ("deepspeed_tpu/serving/errors.py",)

    def check_file(self, ctx: FileContext):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Raise) or node.exc is None:
                continue
            exc = node.exc
            name = None
            if isinstance(exc, ast.Call) and isinstance(exc.func, ast.Name):
                name = exc.func.id
            elif isinstance(exc, ast.Name):
                name = exc.id
            if name in _BARE:
                yield ctx.finding(
                    self.id, node,
                    f"bare `raise {name}` in the serving stack: the "
                    "fabric's retry policy and typed `except` sites key "
                    "on serving/errors.py types and cannot see this",
                    suggestion=f"raise {_BARE[name]} from "
                    "deepspeed_tpu/serving/errors.py")
