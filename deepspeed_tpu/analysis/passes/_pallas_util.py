"""Shared Pallas/TPU AST helpers for the kernel-safety passes (ISSUE 15).

The pallas-tile and vmem-budget passes reason about kernels WITHOUT
executing them, so everything here is a conservative constant-evaluator
over the kernel modules:

  * :class:`Env` — fold integer/tuple expressions through module-level
    and function-local single-assignment bindings (``_CHUNK_BUDGET``,
    ``bs = csp * pair``...).  Anything data-dependent folds to ``None``
    and the passes stay silent — they can miss, never hallucinate;
  * dtype resolution — ``jnp.int8`` attr chains, names bound to them,
    and ``x.astype(jnp.int8)`` operand wrappers, mapped to the
    TPU-physical facts the paper's kernel layer lives by: itemsize, the
    min HBM tile's sublane count (8 fp32 / 16 bf16 / 32 int8+fp8 — the
    minor dim is always 128 lanes), and the window-RMW row quantum the
    repo's kernels honor (8 rows for >=2-byte dtypes, whole 32-row
    tiles for 1-byte payloads — PR 11 sidestepped exactly this with
    whole-block windows);
  * :class:`PallasCallInfo` — one ``pl.pallas_call(...)`` site with its
    specs resolved (through ``grid_spec=PrefetchScalarGridSpec(...)``
    indirection too) and, when the kernel is a plain flat-signature
    function in the same module, the POSITIONAL mapping from kernel ref
    params to in_specs / outputs / scratch entries — which is how a
    ``pl.ds(..., 8)`` window over a ref can be traced back to an int8
    scratch buffer or an ``.astype(jnp.int8)`` operand.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from deepspeed_tpu.analysis.passes._ast_util import attr_chain, \
    call_name as _call_tail

# dtype -> (itemsize, min-tile sublane count, window-RMW row quantum).
# The minor dim of every tile is 128 lanes regardless of dtype.
DTYPES: Dict[str, Tuple[int, int, int]] = {
    "float32": (4, 8, 8),
    "int32": (4, 8, 8),
    "uint32": (4, 8, 8),
    "bfloat16": (2, 16, 8),
    "float16": (2, 16, 8),
    "int8": (1, 32, 32),
    "uint8": (1, 32, 32),
    "float8_e4m3fn": (1, 32, 32),
    "float8_e4m3": (1, 32, 32),
    "float8_e5m2": (1, 32, 32),
}

LANES = 128          # minor-dim tile width, every dtype
UNIVERSAL_SUBLANE = 8    # weakest sublane quantum (fp32); used when the
                         # dtype cannot be proven


def is_call_named(node: ast.AST, name: str) -> bool:
    """``name(...)`` or ``<anything>.name(...)`` — THE one predicate
    every pallas pass keys call spellings on (tile's BlockSpec/VMEM,
    dma's make_async_copy, vmem's scratch entries), so they can never
    diverge on which calls they see."""
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    return (isinstance(f, ast.Attribute) and f.attr == name) or \
        (isinstance(f, ast.Name) and f.id == name)


def collect_assigns(scope: ast.AST,
                    deep: bool = False) -> Dict[str, Optional[ast.AST]]:
    """``name -> value-expr`` for single-target assigns in ``scope``'s
    own body (nested function/class scopes excluded unless ``deep`` —
    Pallas kernels use nested closures as macros, so window analysis
    folds through them).  A name assigned more than once maps to
    ``None`` — the folder then refuses it."""
    out: Dict[str, Optional[ast.AST]] = {}

    def walk(node):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda, ast.ClassDef)):
                if deep and isinstance(child, (ast.FunctionDef,
                                               ast.AsyncFunctionDef)):
                    walk(child)
                continue
            if isinstance(child, ast.Assign) and len(child.targets) == 1 \
                    and isinstance(child.targets[0], ast.Name):
                name = child.targets[0].id
                out[name] = None if name in out else child.value
            elif isinstance(child, ast.AnnAssign):
                if isinstance(child.target, ast.Name):
                    name = child.target.id
                    out[name] = None if (name in out
                                         or child.value is None) \
                        else child.value
            elif isinstance(child, (ast.AugAssign, ast.For, ast.AsyncFor)):
                # EVERY name in the target is mutated — tuple for-
                # targets (`for rows, v in ...`) too, not just bare
                # names; a stale "constant" must fold to unknown
                for n in ast.walk(child.target):
                    if isinstance(n, ast.Name):
                        out[n.id] = None
                walk(child)
                continue
            walk(child)

    walk(scope)
    return out


class Env:
    """Layered constant environment (function locals over module
    globals).  ``fold`` returns an int/float/str or None."""

    def __init__(self, layers: List[Dict[str, Optional[ast.AST]]]):
        self.layers = layers

    def lookup(self, name: str) -> Optional[ast.AST]:
        for layer in self.layers:
            if name in layer:
                return layer[name]
        return None

    def fold(self, node: Optional[ast.AST], _seen: frozenset = frozenset()):
        if node is None:
            return None
        if isinstance(node, ast.Constant):
            return node.value if isinstance(node.value,
                                            (int, float, str)) else None
        if isinstance(node, ast.Name):
            if node.id in _seen:
                return None
            expr = self.lookup(node.id)
            if expr is None:
                return None
            return self.fold(expr, _seen | {node.id})
        if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
            v = self.fold(node.operand, _seen)
            return -v if isinstance(v, (int, float)) else None
        if isinstance(node, ast.BinOp):
            a = self.fold(node.left, _seen)
            b = self.fold(node.right, _seen)
            if not (isinstance(a, (int, float))
                    and isinstance(b, (int, float))):
                return None
            try:
                if isinstance(node.op, ast.Add):
                    return a + b
                if isinstance(node.op, ast.Sub):
                    return a - b
                if isinstance(node.op, ast.Mult):
                    return a * b
                if isinstance(node.op, ast.FloorDiv):
                    return a // b
                if isinstance(node.op, ast.Mod):
                    return a % b
                if isinstance(node.op, ast.Pow):
                    return a ** b
                if isinstance(node.op, ast.LShift):
                    return a << b
                if isinstance(node.op, ast.RShift):
                    return a >> b
            except (ZeroDivisionError, TypeError, ValueError):
                return None
        return None

    def fold_dims(self, node: ast.AST) -> Optional[List[Optional[int]]]:
        """Per-element fold of a literal shape tuple/list; ``None``
        elements mark unprovable dims, ``None`` result a non-literal
        shape.  ``None`` literals (BlockSpec squeezed dims) stay None
        but the element count is preserved."""
        if not isinstance(node, (ast.Tuple, ast.List)):
            return None
        out: List[Optional[int]] = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and e.value is None:
                out.append(None)
                continue
            v = self.fold(e)
            out.append(v if isinstance(v, int) else None)
        return out

    # ------------------------------------------------------------ dtype
    def resolve_dtype(self, node: Optional[ast.AST],
                      _seen: frozenset = frozenset()) -> Optional[str]:
        """Dtype NAME for an expression: ``jnp.int8``, a name bound to
        one, ``jnp.dtype("int8")``, or a string literal."""
        if node is None:
            return None
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return node.value if node.value in DTYPES else None
        if isinstance(node, ast.Attribute):
            tail = attr_chain(node).rsplit(".", 1)[-1] if attr_chain(node) \
                else node.attr
            return tail if tail in DTYPES else None
        if isinstance(node, ast.Name):
            if node.id in DTYPES:
                return node.id
            if node.id in _seen:
                return None
            expr = self.lookup(node.id)
            return self.resolve_dtype(expr, _seen | {node.id}) \
                if expr is not None else None
        if isinstance(node, ast.Call):
            # jnp.dtype("int8") / jnp.dtype(jnp.int8)
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr == "dtype" \
                    and node.args:
                return self.resolve_dtype(node.args[0], _seen)
        return None

    def operand_dtype(self, node: Optional[ast.AST]) -> Optional[str]:
        """Dtype of a pallas operand expression when provable:
        ``q.astype(jnp.int8)``, ``jnp.zeros(shp, jnp.float32)``,
        ``x.reshape(...)`` chains peeled down to those."""
        while isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute):
            attr = node.func.attr
            if attr == "astype" and node.args:
                return self.resolve_dtype(node.args[0])
            if attr in ("zeros", "ones", "full", "empty", "asarray"):
                for kw in node.keywords:
                    if kw.arg == "dtype":
                        return self.resolve_dtype(kw.value)
                if len(node.args) >= 2:
                    return self.resolve_dtype(node.args[-1])
                return None
            if attr in ("reshape", "transpose", "at"):
                node = node.func.value
                continue
            return None
        return None


@dataclass
class BufferInfo:
    """One kernel ref param's statically-known facts."""

    kind: str                     # "prefetch" | "in" | "out" | "scratch"
    dtype: Optional[str] = None   # DTYPES key, when provable
    shape_node: Optional[ast.AST] = None      # scratch shape expr
    spec_node: Optional[ast.AST] = None       # BlockSpec / VMEM / ... call


@dataclass
class PallasCallInfo:
    """One ``pl.pallas_call`` site, specs resolved."""

    node: ast.Call
    enclosing: Optional[ast.AST]             # enclosing FunctionDef
    kernel: Optional[ast.FunctionDef] = None
    in_specs: List[ast.AST] = field(default_factory=list)
    out_specs: List[ast.AST] = field(default_factory=list)
    scratch: List[ast.AST] = field(default_factory=list)
    out_count: int = 0
    out_dtypes: List[Optional[ast.AST]] = field(default_factory=list)
    num_prefetch: int = 0
    operands: List[ast.AST] = field(default_factory=list)
    vmem_limit_node: Optional[ast.AST] = None
    params: Dict[str, BufferInfo] = field(default_factory=dict)


def _kwarg(call: ast.Call, name: str) -> Optional[ast.AST]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _list_elts(node: Optional[ast.AST]) -> Optional[List[ast.AST]]:
    if isinstance(node, (ast.List, ast.Tuple)):
        return list(node.elts)
    return None


def _resolve_kernel(expr: ast.AST, fn_assigns, module_defs
                    ) -> Optional[ast.FunctionDef]:
    """``kernel`` argument → the module-level FunctionDef it names,
    through at most one local ``kernel = functools.partial(_k, ...)``
    hop.  Returns None (no param mapping) for anything fancier."""
    for _ in range(3):
        if isinstance(expr, ast.Name):
            if expr.id in module_defs:
                return module_defs[expr.id]
            nxt = fn_assigns.get(expr.id)
            if nxt is None:
                return None
            expr = nxt
            continue
        if isinstance(expr, ast.Call) and _call_tail(expr) == "partial" \
                and expr.args:
            expr = expr.args[0]
            continue
        return None
    return None


def iter_pallas_calls(tree: ast.Module, env_module: Dict[str,
                                                         Optional[ast.AST]]
                      ) -> List[Tuple[PallasCallInfo, Env]]:
    """Every ``pl.pallas_call`` site in a module, with its per-site Env
    (function locals layered over module globals) and — when provable —
    the kernel param → buffer mapping."""
    module_defs = {n.name: n for n in tree.body
                   if isinstance(n, ast.FunctionDef)}
    out: List[Tuple[PallasCallInfo, Env]] = []
    # parent map for (pallas_call(...))(operands) detection
    parents: Dict[int, ast.AST] = {}
    enclosing_fn: Dict[int, ast.AST] = {}

    def walk(node, fn):
        for child in ast.iter_child_nodes(node):
            parents[id(child)] = node
            f = fn
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                f = child
            enclosing_fn[id(child)] = f
            walk(child, f)

    walk(tree, None)

    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and _call_tail(node) == "pallas_call"):
            continue
        fn = enclosing_fn.get(id(node))
        fn_assigns = collect_assigns(fn) if fn is not None else {}
        env = Env([fn_assigns, env_module])
        info = PallasCallInfo(node=node, enclosing=fn)

        parent = parents.get(id(node))
        if isinstance(parent, ast.Call) and parent.func is node:
            info.operands = list(parent.args)

        in_specs = _kwarg(node, "in_specs")
        scratch = _kwarg(node, "scratch_shapes")
        out_specs = _kwarg(node, "out_specs")
        num_prefetch = None
        grid_spec = _kwarg(node, "grid_spec")
        if grid_spec is not None:
            gs = grid_spec
            if isinstance(gs, ast.Name):
                gs = fn_assigns.get(gs.id)
            if isinstance(gs, ast.Call):
                in_specs = in_specs or _kwarg(gs, "in_specs")
                scratch = scratch or _kwarg(gs, "scratch_shapes")
                out_specs = out_specs or _kwarg(gs, "out_specs")
                num_prefetch = _kwarg(gs, "num_scalar_prefetch")
        info.in_specs = _list_elts(in_specs) or []
        info.out_specs = _list_elts(out_specs) or (
            [out_specs] if out_specs is not None else [])
        info.scratch = _list_elts(scratch) or []
        npf = env.fold(num_prefetch) if num_prefetch is not None else 0
        info.num_prefetch = npf if isinstance(npf, int) else 0

        out_shape = _kwarg(node, "out_shape")
        outs = _list_elts(out_shape)
        if outs is not None:
            info.out_count = len(outs)
            info.out_dtypes = [
                (o.args[1] if isinstance(o, ast.Call)
                 and len(o.args) >= 2 else _kwarg(o, "dtype")
                 if isinstance(o, ast.Call) else None) for o in outs]
        elif out_shape is not None:
            info.out_count = 1
            info.out_dtypes = [
                out_shape.args[1] if isinstance(out_shape, ast.Call)
                and len(out_shape.args) >= 2 else None]
        else:
            specs = _list_elts(out_specs)
            info.out_count = len(specs) if specs is not None else 1
            info.out_dtypes = [None] * info.out_count

        cp = _kwarg(node, "compiler_params")
        if isinstance(cp, ast.Call):
            info.vmem_limit_node = _kwarg(cp, "vmem_limit_bytes")

        info.kernel = _resolve_kernel(node.args[0], fn_assigns,
                                      module_defs) if node.args else None
        _map_params(info, env)
        out.append((info, env))
    return out


def _map_params(info: PallasCallInfo, env: Env) -> None:
    """Positional kernel-param → buffer mapping.  Only attempted when
    the kernel has a flat signature (no *args) and the param count
    matches prefetch + inputs + outputs + scratch exactly — anything
    else leaves ``params`` empty (no mapping beats a wrong mapping)."""
    k = info.kernel
    if k is None or k.args.vararg is not None:
        return
    names = [a.arg for a in (k.args.posonlyargs + k.args.args)]
    n_expected = (info.num_prefetch + len(info.in_specs)
                  + info.out_count + len(info.scratch))
    if not info.in_specs or len(names) != n_expected:
        return
    i = 0
    for _ in range(info.num_prefetch):
        info.params[names[i]] = BufferInfo(kind="prefetch")
        i += 1
    for j, spec in enumerate(info.in_specs):
        # operands align with prefetch + inputs at the outer call
        op = info.operands[info.num_prefetch + j] \
            if len(info.operands) == info.num_prefetch \
            + len(info.in_specs) else None
        dt = env.operand_dtype(op) if op is not None else None
        info.params[names[i]] = BufferInfo(kind="in", dtype=dt,
                                           spec_node=spec)
        i += 1
    for j in range(info.out_count):
        dnode = info.out_dtypes[j] if j < len(info.out_dtypes) else None
        spec = info.out_specs[j] if j < len(info.out_specs) else None
        info.params[names[i]] = BufferInfo(
            kind="out", dtype=env.resolve_dtype(dnode), spec_node=spec)
        i += 1
    for s in info.scratch:
        bi = BufferInfo(kind="scratch", spec_node=s)
        if isinstance(s, ast.Call) and _call_tail(s) == "VMEM" \
                and len(s.args) >= 2:
            bi.shape_node = s.args[0]
            bi.dtype = env.resolve_dtype(s.args[1])
        info.params[names[i]] = bi
        i += 1


def buffer_root(node: ast.AST, fn_assigns: Dict[str, Optional[ast.AST]],
                _depth: int = 0) -> Optional[str]:
    """Root buffer NAME of a ref expression: ``k_ref.at[...]`` → k_ref,
    ``src`` where ``src = w_any.at[layer] if stacked else w_any`` →
    w_any (both branches must agree).  None when untraceable."""
    if _depth > 8:
        return None
    if isinstance(node, ast.Name):
        expr = fn_assigns.get(node.id)
        if expr is not None:
            r = buffer_root(expr, fn_assigns, _depth + 1)
            if r is not None:
                return r
        return node.id if expr is None else None
    if isinstance(node, ast.Attribute):
        return buffer_root(node.value, fn_assigns, _depth + 1)
    if isinstance(node, ast.Subscript):
        return buffer_root(node.value, fn_assigns, _depth + 1)
    if isinstance(node, ast.IfExp):
        a = buffer_root(node.body, fn_assigns, _depth + 1)
        b = buffer_root(node.orelse, fn_assigns, _depth + 1)
        return a if a == b else None
    return None
