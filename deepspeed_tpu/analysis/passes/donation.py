"""donation-safety — donated buffers are dead after the donating call.

``donate_argnums`` hands a buffer to XLA for in-place reuse: reading the
Python reference afterwards returns garbage (or, on the CPU backend,
*sometimes* the old value — the worst kind of bug, green locally and
corrupt on the TPU; runtime/sentinel.py:284 and checkpoint_engine grew
defensive copies for exactly this).  The dynamic suites can only catch a
read-after-donate that a test happens to execute; this pass catches the
shape statically:

  within one function scope,
  1. ``f = jax.jit(g, donate_argnums=(0,))``  (or ``self._f = ...``)
  2. ``y = f(x, ...)``                         — ``x`` is now donated
  3. any later read of ``x``                   — flagged,

with taint cleared on any rebinding of ``x`` (the canonical
``self.state, m = step(self.state, ...)`` pattern never taints).
Cross-function donation (a jitted callable stored in ``__init__`` and
called elsewhere) is out of static reach here; the dynamic
bit-identity suites keep owning that half.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from deepspeed_tpu.analysis.core import FileContext, LintPass, register
from deepspeed_tpu.analysis.passes._ast_util import (attr_chain, is_jit_call)

SCOPES = (
    "deepspeed_tpu/serving/",
    "deepspeed_tpu/inference/",
    "deepspeed_tpu/runtime/",
    "deepspeed_tpu/ops/",
)


def _donated_positions(call: ast.Call) -> Tuple[int, ...]:
    for kw in call.keywords:
        if kw.arg != "donate_argnums":
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, int):
            return (v.value,)
        if isinstance(v, (ast.Tuple, ast.List)):
            out = []
            for e in v.elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, int):
                    out.append(e.value)
            return tuple(out)
    return ()


def _walk_scope(fn: ast.AST, _path: Tuple = ()):
    """Walk one function's OWN body — never descending into nested
    function/class scopes (each FunctionDef is analyzed exactly once by
    check_file; descending here would double-report nested violations).

    Yields ``(node, branch_path)`` where branch_path identifies the
    chain of conditional arms the node sits in (``(id(if_node), arm),
    ...``) — so a Return inside one arm can be scoped to clear only the
    donations made in that same arm (see the exit handling below)."""
    for field_name, value in ast.iter_fields(fn):
        branches = ()
        if isinstance(fn, (ast.If, ast.For, ast.AsyncFor, ast.While,
                           ast.Try)) and field_name in (
                "body", "orelse", "handlers", "finalbody"):
            branches = ((id(fn), field_name),)
        for child in (value if isinstance(value, list) else [value]):
            if not isinstance(child, ast.AST):
                continue
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda, ast.ClassDef)):
                continue
            path = _path + branches
            yield child, path
            yield from _walk_scope(child, path)


def _ref(node: ast.AST) -> str:
    """Canonical dotted name for a Name / self-attribute chain ('' when
    the expression is not a trackable reference)."""
    chain = attr_chain(node)
    if chain and (chain.count(".") == 0 or chain.startswith("self.")):
        return chain
    return ""


class _Event:
    __slots__ = ("pos", "kind", "name", "node", "path")

    def __init__(self, pos, kind, name, node, path=()):
        self.pos, self.kind, self.name = pos, kind, name
        self.node, self.path = node, path


@register
class DonationSafetyPass(LintPass):
    id = "donation-safety"
    title = "no reads of donated buffers after the donating call"
    scope = SCOPES

    def check_file(self, ctx: FileContext):
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from self._check_function(ctx, node)

    def _check_function(self, ctx: FileContext, fn: ast.AST):
        # donating-callable BINDINGS in this scope (position-aware: a
        # call through the name before the binding — or after it is
        # rebound to something else — must not taint)
        binds = []          # (pos, name, donated positions)
        for node, _ in _walk_scope(fn):
            if (isinstance(node, ast.Assign) and is_jit_call(node.value)):
                pos = _donated_positions(node.value)
                if not pos:
                    continue
                for tgt in node.targets:
                    name = _ref(tgt)
                    if name:
                        # 2.5: after the plain store event at the same
                        # spot (which unbinds), so the bind wins
                        binds.append(((node.lineno, 2.5,
                                       tgt.col_offset), name, pos))
        if not binds:
            return

        # Linearize loads / stores / donating calls by source position.
        # Priority orders same-line events the way evaluation does:
        # loads (RHS) -> the donating call -> stores (LHS binds last) ->
        # function exits; `x = f(x)` therefore never taints x.
        bindable = {name for _, name, _ in binds}
        events: List[_Event] = [
            _Event(pos, "bind", name, positions)
            for pos, name, positions in binds]
        for node, path in _walk_scope(fn):
            if isinstance(node, ast.Call):
                cname = _ref(node.func)
                if cname in bindable:
                    events.append(_Event(
                        (node.lineno, 1, node.col_offset), "call",
                        cname, node, path))
            elif isinstance(node, (ast.Return, ast.Raise)):
                # control leaves the function: code later in source order
                # on the SAME branch never runs after this, so donations
                # made in this exit's own branch subtree are dead — but a
                # conditional early return must NOT launder a donation
                # made on the fallthrough path
                events.append(_Event(
                    (getattr(node, "end_lineno", node.lineno), 3, 0),
                    "exit", "", node, path))
            elif isinstance(node, (ast.Name, ast.Attribute)):
                name = _ref(node)
                if not name:
                    continue
                if isinstance(node.ctx, ast.Store):
                    events.append(_Event(
                        (node.lineno, 2, node.col_offset), "store",
                        name, node))
                elif isinstance(node.ctx, ast.Load):
                    events.append(_Event(
                        (node.lineno, 0, node.col_offset), "load",
                        name, node))
        events.sort(key=lambda e: e.pos)

        bound: Dict[str, Tuple[int, ...]] = {}   # name -> donated argnums
        tainted: Dict[str, tuple] = {}   # ref -> (donating call, branch path)
        reported: Set[Tuple[str, int]] = set()
        for ev in events:
            if ev.kind == "exit":
                # clear only donations made in this exit's branch subtree
                # (exit path is a prefix of the donor's path)
                for name in [n for n, (_, dpath) in tainted.items()
                             if dpath[:len(ev.path)] == ev.path]:
                    tainted.pop(name)
            elif ev.kind == "bind":
                bound[ev.name] = ev.node   # node slot carries positions
            elif ev.kind == "call" and ev.name in bound:
                call = ev.node
                for p in bound[ev.name]:
                    if p < len(call.args):
                        ref = _ref(call.args[p])
                        if ref:
                            tainted[ref] = (call, ev.path)
            elif ev.kind == "store":
                tainted.pop(ev.name, None)
                bound.pop(ev.name, None)   # rebound to something else
                # rebinding `self.state` also revives `self.state.params`
                for t in [t for t in tainted if t.startswith(ev.name + ".")]:
                    tainted.pop(t, None)
            elif ev.kind == "load" and ev.name in tainted:
                donor, _ = tainted[ev.name]
                if ev.node.lineno <= getattr(donor, "end_lineno",
                                             donor.lineno):
                    continue   # load inside/before the donating call
                               # statement (evaluated pre-donation)
                key = (ev.name, ev.node.lineno)
                if key in reported:
                    continue
                reported.add(key)
                yield ctx.finding(
                    self.id, ev.node,
                    f"`{ev.name}` was donated to the jit call on line "
                    f"{donor.lineno} (donate_argnums) and read here: the "
                    "buffer may already be reused in place",
                    suggestion="read the value BEFORE the donating call, "
                    "use the call's outputs, or drop the donation")
