"""donation-safety — donated buffers are dead after the donating call.

``donate_argnums`` hands a buffer to XLA for in-place reuse: reading the
Python reference afterwards returns garbage (or, on the CPU backend,
*sometimes* the old value — the worst kind of bug, green locally and
corrupt on the TPU; runtime/sentinel.py:284 and checkpoint_engine grew
defensive copies for exactly this).  The dynamic suites can only catch a
read-after-donate that a test happens to execute; this pass catches the
shape statically:

  within one function scope,
  1. ``f = jax.jit(g, donate_argnums=(0,))``  (or ``self._f = ...``)
  2. ``y = f(x, ...)``                         — ``x`` is now donated
  3. any later read of ``x``                   — flagged,

with taint cleared on any rebinding of ``x`` (the canonical
``self.state, m = step(self.state, ...)`` pattern never taints).

The linearized scan itself lives in
:mod:`deepspeed_tpu.analysis.taint` (shared with the interprocedural
``sharding-contract`` pass, which follows donations ACROSS call
boundaries via the phase-1 summaries — the half this per-scope pass
cannot see).  ISSUE 15 fixed three false-negative shapes here, each
pinned by a regression fixture: augmented-assignment reads after
donate, reads in a ``finally`` body after a donating ``try`` returned,
and donating callables bound through tuple unpacking.
"""

from __future__ import annotations

import ast

from deepspeed_tpu.analysis.core import FileContext, LintPass, register
from deepspeed_tpu.analysis.taint import scan_function

SCOPES = (
    "deepspeed_tpu/serving/",
    "deepspeed_tpu/inference/",
    "deepspeed_tpu/runtime/",
    "deepspeed_tpu/ops/",
)


@register
class DonationSafetyPass(LintPass):
    id = "donation-safety"
    title = "no reads of donated buffers after the donating call"
    scope = SCOPES

    def check_file(self, ctx: FileContext):
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from scan_function(ctx, node, pass_id=self.id,
                                         track_local_binds=True)
