"""host-sync — no device→host synchronization in hot paths except at
declared fences.

The serving decode loop and the training step path live or die by async
dispatch: one stray ``jax.device_get`` / ``.item()`` /
``block_until_ready`` serializes the pipeline and the TPU idles for a
host round-trip per step (PR 3 measured the telemetry fence at 1.4%
precisely because every OTHER read stays on-device).  The legitimate
sync points — token emission, swap-out gathers, the periodic telemetry
fence, sentinel drains — are *declared*: each carries a
``# dstpu-lint: fence=<why>`` comment naming its reason, so a new
unfenced sync in these files is a lint error, not a perf regression
found three PRs later.

Flags, inside the hot-path scopes:

  * ``jax.device_get(...)`` / ``device_get(...)``;
  * ``jax.block_until_ready(...)``;
  * ``<expr>.item()``;
  * ``float()/int()/bool()/np.asarray()`` directly on ``self.state.*``
    or ``self.cache.*`` — this repo's conventions put live device
    arrays there, so the cast is an *implicit* transfer (the honest
    spelling is an explicit ``jax.device_get`` under a fence comment).
"""

from __future__ import annotations

import ast

from deepspeed_tpu.analysis.core import FileContext, LintPass, register
from deepspeed_tpu.analysis.passes._ast_util import (call_name, expr_root)

#: the engine hot loops this contract protects (serving decode/prefill,
#: training step paths).  Cold paths — checkpointing, ZeRO offload
#: consolidation, eigenvalue probes — sync by design and stay out.
HOT_PATH_SCOPES = (
    "deepspeed_tpu/serving/",
    "deepspeed_tpu/runtime/engine.py",
    "deepspeed_tpu/runtime/pipe/engine.py",
    "deepspeed_tpu/runtime/hybrid_engine.py",
)

_SYNC_CALLS = ("device_get", "block_until_ready")
_CAST_CALLS = ("float", "int", "bool", "asarray")
_DEVICE_STATE_ROOTS = (("self", "state"), ("self", "cache"))

_FENCE_HINT = ("declare the sync: `# dstpu-lint: fence=<why>` on this "
               "line, or batch the read into an existing fence")


@register
class HostSyncPass(LintPass):
    id = "host-sync"
    title = "no host synchronization in hot paths except declared fences"
    scope = HOT_PATH_SCOPES

    def check_file(self, ctx: FileContext):
        # `asarray(...)` resolves through the file's imports: only
        # numpy's is a device->host transfer (jnp's is an upload).
        # Track both from-imports of the function and aliases of the
        # module itself (`import numpy as onp`).
        np_asarray_names = set()
        np_quals = {"np", "numpy"}
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ImportFrom) \
                    and node.module == "numpy":
                for a in node.names:
                    if a.name == "asarray":
                        np_asarray_names.add(a.asname or a.name)
            elif isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "numpy":
                        np_quals.add(a.asname or a.name)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if name in _SYNC_CALLS:
                what = ("jax.device_get" if name == "device_get"
                        else "jax.block_until_ready")
                yield ctx.finding(
                    self.id, node,
                    f"{what} in a hot path forces a device->host sync "
                    "(async dispatch stalls for the round-trip)",
                    suggestion=_FENCE_HINT)
            elif (isinstance(node.func, ast.Attribute)
                  and node.func.attr == "item" and not node.args
                  and not node.keywords):
                yield ctx.finding(
                    self.id, node,
                    ".item() in a hot path is a hidden device->host sync",
                    suggestion=_FENCE_HINT)
            elif name in _CAST_CALLS and len(node.args) == 1:
                if name == "asarray":
                    # np.asarray on a device array is an implicit
                    # transfer; jnp.asarray is an upload (host->device),
                    # fine in a hot path
                    if isinstance(node.func, ast.Attribute):
                        qual = node.func.value.id \
                            if isinstance(node.func.value, ast.Name) \
                            else ""
                        if qual not in np_quals:
                            continue
                    elif node.func.id not in np_asarray_names:
                        continue   # bare asarray not from numpy
                root = expr_root(node.args[0])
                if any(root[:2] == r for r in _DEVICE_STATE_ROOTS):
                    yield ctx.finding(
                        self.id, node,
                        f"{name}() on device state "
                        f"({'.'.join(root)}) is an implicit "
                        "device->host transfer in a hot path",
                        suggestion="spell the sync explicitly "
                        "(jax.device_get) and " + _FENCE_HINT)
