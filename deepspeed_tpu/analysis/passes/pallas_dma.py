"""pallas-dma — every started async copy is awaited; no orphan waits
(ISSUE 15).

The manual-DMA kernels (ops/decode_step.py's double-buffered cache walk,
ops/int8_matmul.py's weight streaming) are exactly as correct as their
start/wait pairing: a ``make_async_copy(...).start()`` whose semaphore
is never awaited lets the kernel return (or reuse the buffer slot)
while the copy is in flight — silent corruption that only reproduces on
real hardware timing — and an orphan ``.wait()`` deadlocks on a
semaphore nobody signals.

The repo spells DMA handles three ways, and the pass keys start/wait
events so all three pair up across the whole kernel (nested closures
are macros here, so the match domain is the outer kernel function with
its closures flattened):

  * **bound handles** — ``fk = pltpu.make_async_copy(...)`` then
    ``fk.start()`` / ``fk.wait()``: keyed by name; a name bound to a
    FACTORY result (``h = chunk_dma(0)``) keys like the call, so
    ``h.start()`` pairs with ``chunk_dma(0).wait()`` (a name rebound
    ambiguously — different streams on one name — goes untracked:
    can miss, never hallucinate);
  * **factory helpers** — ``def kdma(i): return pltpu.make_async_copy
    (...)`` then ``kdma(i).start()`` / ``kdma(i).wait()``: keyed by the
    factory name, refined by the trailing literal stream index when
    EVERY call spells one (``chunk_dma(..., 0)`` K-stream vs
    ``chunk_dma(..., 1)`` V-stream — dropping only the V wait is
    caught);
  * **inline** — ``pltpu.make_async_copy(a, b, sem).start()``: keyed by
    the normalized semaphore expression, so the write-back started in
    ``finish_write`` pairs with the drain ``.wait()`` at kernel exit.

Matching is whole-function (not path-sensitive): a start with no wait
ANYWHERE is flagged, which catches the dropped-wait mutation class the
tier-1 seeds pin; per-path gaps stay owned by the dynamic suites.
``.start()``/``.wait()`` on anything not traceable to a
``make_async_copy`` (threads, timers) is ignored.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from deepspeed_tpu.analysis.core import FileContext, LintPass, register
from deepspeed_tpu.analysis.passes._pallas_util import is_call_named

SCOPES = ("deepspeed_tpu/ops/",)


def _is_make_async_copy(node: ast.AST) -> bool:
    return is_call_named(node, "make_async_copy")


def _flat_walk(fn: ast.AST):
    """Every node under ``fn`` INCLUDING nested function bodies (the
    kernels' closure-as-macro idiom), excluding nested classes."""
    for child in ast.iter_child_nodes(fn):
        if isinstance(child, ast.ClassDef):
            continue
        yield child
        yield from _flat_walk(child)


def _norm(node: ast.AST) -> str:
    """Position-independent structural dump for semaphore matching."""
    return ast.dump(node, annotate_fields=False)


class _Events:
    def __init__(self) -> None:
        self.starts: Dict[tuple, List[ast.AST]] = {}
        self.waits: Dict[tuple, List[ast.AST]] = {}

    def add(self, kind: str, key: tuple, node: ast.AST) -> None:
        side = self.starts if kind == "start" else self.waits
        side.setdefault(key, []).append(node)


@register
class PallasDmaPass(LintPass):
    id = "pallas-dma"
    title = "every async-copy start has a wait; no orphan waits"
    scope = SCOPES

    def check_file(self, ctx: FileContext) -> Iterable:
        if "make_async_copy" not in ctx.source:
            return
        # module-level defs AND class methods are kernel roots; nested
        # defs are NOT re-scanned (the flattened walk already covers
        # them inside their root, so they would double-report)
        roots = [n for n in ctx.tree.body
                 if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        roots += [m for n in ctx.tree.body if isinstance(n, ast.ClassDef)
                  for m in n.body
                  if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef))]
        for node in roots:
            yield from self._check_kernel(ctx, node)

    def _check_kernel(self, ctx, fn: ast.AST) -> Iterable:
        nodes = list(_flat_walk(fn))
        if not any(_is_make_async_copy(n) for n in nodes):
            return
        # DMA-handle provenance inside this kernel: factories first, so
        # a name bound BEFORE the factory's def in the flat walk still
        # resolves
        factories: Set[str] = set()      # local defs returning a copy
        for n in nodes:
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for sub in ast.walk(n):
                    if isinstance(sub, ast.Return) \
                            and sub.value is not None \
                            and _is_make_async_copy(sub.value):
                        factories.add(n.name)

        # name -> every handle-producing value bound to it: None for a
        # direct ``make_async_copy(...)`` (keyed by name), or the
        # factory ``ast.Call`` (keyed like the call, so ``h.start()``
        # pairs with ``chunk_dma(0).wait()``)
        bound: Dict[str, List[Optional[ast.Call]]] = {}
        for n in nodes:
            if not isinstance(n, ast.Assign):
                continue
            val = n.value
            if _is_make_async_copy(val):
                entry: Optional[ast.Call] = None
            elif isinstance(val, ast.Call) \
                    and isinstance(val.func, ast.Name) \
                    and val.func.id in factories:
                entry = val
            else:
                continue
            for tgt in n.targets:
                if isinstance(tgt, ast.Name):
                    bound.setdefault(tgt.id, []).append(entry)

        # factory stream refinement: use the trailing literal arg as a
        # sub-key only when EVERY call of that factory (as a
        # start/wait receiver OR a handle bind) spells one
        const_last: Dict[str, bool] = {}

        def note(call: ast.Call) -> None:
            f = call.func
            if isinstance(f, ast.Name) and f.id in factories:
                is_const = bool(call.args) and isinstance(
                    call.args[-1], ast.Constant)
                const_last[f.id] = const_last.get(f.id, True) and is_const

        for n in nodes:
            call = self._handle_call(n)
            if call is not None:
                note(call)
            if isinstance(n, ast.Assign) and isinstance(n.value, ast.Call):
                note(n.value)

        ev = _Events()
        for n in nodes:
            if not (isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and n.func.attr in ("start", "wait")
                    and not n.args and not n.keywords):
                continue
            key = self._key(n.func.value, bound, factories, const_last)
            if key is not None:
                ev.add(n.func.attr, key, n)

        for key, sites in sorted(ev.starts.items(),
                                 key=lambda kv: kv[1][0].lineno):
            if key not in ev.waits:
                yield ctx.finding(
                    self.id, sites[0],
                    f"async copy {self._describe(key)} is started but "
                    "never awaited in this kernel: the DMA may still be "
                    "in flight when its buffer slot is reused or the "
                    "kernel returns",
                    suggestion="await the same handle/semaphore "
                    "(`.wait()`) on every path before buffer reuse and "
                    "before the kernel exits")
        for key, sites in sorted(ev.waits.items(),
                                 key=lambda kv: kv[1][0].lineno):
            if key not in ev.starts:
                yield ctx.finding(
                    self.id, sites[0],
                    f"unpaired wait: {self._describe(key)} is awaited "
                    "but no matching start exists in this kernel — the "
                    "semaphore is never signaled (deadlock on device)",
                    suggestion="start the copy on every path that "
                    "reaches this wait, or delete the stale wait")

    @staticmethod
    def _handle_call(n: ast.AST) -> Optional[ast.Call]:
        """The ``factory(...)`` receiver of a ``.start()``/``.wait()``."""
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute) \
                and n.func.attr in ("start", "wait") \
                and isinstance(n.func.value, ast.Call):
            return n.func.value
        return None

    @staticmethod
    def _factory_key(call: ast.Call, factories: Set[str],
                     const_last: Dict[str, bool]) -> Optional[tuple]:
        name = call.func.id
        if name not in factories:
            return None
        if const_last.get(name) and call.args:
            return ("call", name, repr(call.args[-1].value))
        return ("call", name, None)

    @classmethod
    def _key(cls, recv: ast.AST, bound: Dict[str, list],
             factories: Set[str],
             const_last: Dict[str, bool]) -> Optional[tuple]:
        if isinstance(recv, ast.Name):
            binds = bound.get(recv.id)
            if not binds:
                return None
            keys = {("name", recv.id) if b is None
                    else cls._factory_key(b, factories, const_last)
                    for b in binds}
            # ambiguous rebinds (different streams / mixed spellings
            # on one name) go untracked: can miss, never hallucinate
            return keys.pop() if len(keys) == 1 else None
        if _is_make_async_copy(recv):
            sem = recv.args[2] if len(recv.args) >= 3 else None
            return ("sem", _norm(sem) if sem is not None
                    else _norm(recv))
        if isinstance(recv, ast.Call) and isinstance(recv.func, ast.Name):
            return cls._factory_key(recv, factories, const_last)
        return None

    @staticmethod
    def _describe(key: tuple) -> str:
        if key[0] == "name":
            return f"handle `{key[1]}`"
        if key[0] == "call":
            stream = f" (stream {key[2]})" if key[2] is not None else ""
            return f"`{key[1]}(...)`{stream}"
        return "with this semaphore"
