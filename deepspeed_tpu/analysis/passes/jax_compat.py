"""jax-compat — version-gated jax APIs route through utils/jax_compat.

``shard_map`` moved namespaces and renamed kwargs across jax releases
(``check_rep`` -> ``check_vma``, ``auto`` -> ``axis_names``), vma typing
appeared, ``lax.pcast`` appeared, and ``PartitionId``-era symbols died.
``utils/jax_compat.py`` shims all of it — but only for call sites that
go THROUGH the shim.  A direct import compiles on one jax and breaks on
the next; the 37 still-failing seed tests (ROADMAP item 4) are exactly
the sites that didn't.  This pass finds every direct use and names the
shim to use; ``scripts/dstpu_lint.py --jaxcompat-report`` additionally
emits the full call-site inventory (shim-internal sites included, as
status ``shim``) — the migration work-list artifact LINT_JAXCOMPAT.md.
"""

from __future__ import annotations

import ast
from typing import List

from deepspeed_tpu.analysis.core import Corpus, FileContext, LintPass, register
from deepspeed_tpu.analysis.passes._ast_util import attr_chain

#: sanctioned shim layers: utils/jax_compat owns the API translation;
#: ops/flash_attention owns the vma-typing probe/out-struct factory the
#: kernel callers (ring_attention) route through
SHIM_FILES = ("deepspeed_tpu/utils/jax_compat.py",
              "deepspeed_tpu/ops/flash_attention.py")

_SHARD_MAP_FIX = ("from deepspeed_tpu.utils.jax_compat import shard_map "
                  "(translates check_rep/check_vma and auto/axis_names "
                  "per installed jax)")
_PCAST_FIX = ("deepspeed_tpu.utils.jax_compat.pcast_varying "
              "(identity on jax without lax.pcast)")
_VMA_FIX = ("deepspeed_tpu.ops.flash_attention.vma_typing_supported / "
            "out_struct, or utils.jax_compat.has_vma_typing")
_PARTITION_FIX = ("gate behind utils.jax_compat.has_vma_typing() or "
                  "migrate off PartitionId-era symbols (ROADMAP item 4)")


def gated_sites(ctx: FileContext) -> List[dict]:
    """Every version-gated jax API reference in one file."""
    out: List[dict] = []

    def site(node, api, fix):
        out.append({"path": ctx.relpath, "line": node.lineno,
                    "api": api, "fix": fix, "symbol": ctx.symbol(node)})

    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name.startswith("jax.experimental.shard_map"):
                    site(node, "import jax.experimental.shard_map",
                         _SHARD_MAP_FIX)
                elif a.name.startswith("jax.experimental.maps"):
                    site(node, "jax.experimental.maps (removed xmap era)",
                         _PARTITION_FIX)
        elif isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            if mod.startswith("jax.experimental.shard_map"):
                site(node, "jax.experimental.shard_map import",
                     _SHARD_MAP_FIX)
            elif mod == "jax.experimental" and any(
                    a.name in ("shard_map", "maps") for a in node.names):
                site(node, "from jax.experimental import shard_map/maps",
                     _SHARD_MAP_FIX)
            elif mod == "jax" and any(a.name == "shard_map"
                                      for a in node.names):
                site(node, "from jax import shard_map (new-jax only)",
                     _SHARD_MAP_FIX)
            elif mod.startswith("jax.experimental.maps"):
                site(node, "jax.experimental.maps (removed xmap era)",
                     _PARTITION_FIX)
        elif isinstance(node, ast.Attribute):
            chain = attr_chain(node)
            if chain.startswith("jax.experimental.shard_map"):
                site(node, chain, _SHARD_MAP_FIX)
            elif chain.endswith("lax.pcast"):
                site(node, chain + " (absent on older jax)", _PCAST_FIX)
            elif node.attr == "PartitionId":
                site(node, chain or node.attr, _PARTITION_FIX)
        elif isinstance(node, ast.Name) and node.id == "PartitionId":
            site(node, "PartitionId (pre-vma jax only)", _PARTITION_FIX)
        elif isinstance(node, ast.Call):
            # kwarg checks are scoped to the APIs that own them — a
            # generic `check_rep=`/`vma=` on an unrelated call is not a
            # jax-version hazard
            callee = node.func.attr \
                if isinstance(node.func, ast.Attribute) else (
                    node.func.id if isinstance(node.func, ast.Name)
                    else "")
            for kw in node.keywords:
                if kw.arg == "check_rep" and callee == "shard_map":
                    site(kw.value,
                         "check_rep= kwarg (renamed check_vma)",
                         _SHARD_MAP_FIX)
                elif kw.arg == "vma" and callee == "ShapeDtypeStruct":
                    site(kw.value, "vma= kwarg (vma-typing jax only)",
                         _VMA_FIX)
    return out


@register
class JaxCompatPass(LintPass):
    id = "jax-compat"
    title = "version-gated jax APIs must route through utils/jax_compat"
    scope = ()          # whole tree
    exempt = SHIM_FILES

    def check_file(self, ctx: FileContext):
        from deepspeed_tpu.analysis.core import Finding

        for s in gated_sites(ctx):
            yield Finding(
                self.id, ctx.relpath, s["line"], 0,
                f"direct use of version-gated jax API: {s['api']}",
                symbol=s["symbol"], suggestion=s["fix"])

    # ---------------------------------------------------------- inventory
    def inventory(self, corpus: Corpus) -> List[dict]:
        """Every version-gated call site in the tree — the ROADMAP item 4
        migration work-list: 'direct' (violations), 'shim' (the
        translation layers' own uses), and 'routed' (call sites that go
        through a shim entry point — the surface the migration PR must
        revisit when the compat layer changes shape)."""
        shim_names = ("shard_map", "pcast_varying", "has_vma_typing",
                      "vma_typing_supported", "out_struct")
        rows: List[dict] = []
        for ctx in corpus.files:
            if ctx.tree is None:
                continue
            status = "shim" if ctx.relpath in SHIM_FILES else "direct"
            for s in gated_sites(ctx):
                s["status"] = status
                rows.append(s)
            if status == "shim":
                continue
            # names this file imports FROM the shims
            routed: dict = {}
            for node in ast.walk(ctx.tree):
                if isinstance(node, ast.ImportFrom):
                    mod = node.module or ""
                    if mod.endswith("jax_compat") \
                            or mod.endswith("ops.flash_attention"):
                        for a in node.names:
                            if a.name in shim_names:
                                routed[a.asname or a.name] = a.name
            if not routed:
                continue
            for node in ast.walk(ctx.tree):
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Name)
                        and node.func.id in routed):
                    rows.append({
                        "path": ctx.relpath, "line": node.lineno,
                        "api": f"via shim: {routed[node.func.id]}",
                        "fix": "", "symbol": ctx.symbol(node),
                        "status": "routed"})
        order = {"direct": 0, "shim": 1, "routed": 2}
        rows.sort(key=lambda r: (order[r["status"]], r["path"], r["line"]))
        return rows
