"""metric-names — README metric docs exactly cover telemetry call sites.

Migrated from ``scripts/check_metric_names.py`` (ISSUE 11 satellite)
onto the pass framework; the script is now a thin shim over this module
and its CLI/exit-code contract is unchanged (pinned by
tests/unit/telemetry/test_spans.py).  The contract: every counter /
gauge / histogram / event name the code emits appears in README.md
(operators grep the README, not the source), and nothing documented is
emitted by nothing.  f-strings become wildcard patterns
(``f"serving/ttft_ms/p{c}"`` -> ``serving/ttft_ms/p*``); README
``<placeholder>`` segments normalize to ``*``; coverage matches either
direction.
"""

from __future__ import annotations

import ast
import fnmatch
import os
import re
from typing import Dict, List

from deepspeed_tpu.analysis.core import (Corpus, Finding, LintPass,
                                         register)

PREFIXES = ("train", "serving", "fabric", "resilience", "device",
            "checkpoint", "elastic", "slo", "telemetry")
_NAME_RE = re.compile(
    r"^(?:%s)/[A-Za-z0-9_][A-Za-z0-9_/<>*-]*$" % "|".join(PREFIXES))
# methods whose first string argument is a metric/event name
_METHODS = {"counter", "gauge", "histogram", "event", "record_event",
            "_count", "_gauge", "_observe"}


def _pattern_of(node) -> "str | None":
    """Metric-name pattern of a str/f-string AST node (formatted pieces
    become '*'), or None for non-strings."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        parts = []
        for v in node.values:
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                parts.append(v.value)
            else:
                parts.append("*")
        return "".join(parts)
    return None


def _names_in_tree(tree, relpath: str, out: Dict[str, List[str]]) -> None:
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        func = node.func
        name = (func.attr if isinstance(func, ast.Attribute)
                else func.id if isinstance(func, ast.Name)
                else None)
        if name not in _METHODS:
            continue
        pat = _pattern_of(node.args[0])
        if pat is None or not _NAME_RE.match(pat):
            continue
        out.setdefault(pat, []).append(f"{relpath}:{node.lineno}")


def code_names(root: str) -> dict:
    """{pattern: [file:line, ...]} over every telemetry call site under
    the directory ``root`` (path-based, kept for the shim CLI and the
    tests that drive it on synthetic trees)."""
    out: Dict[str, List[str]] = {}
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            try:
                with open(path, "r", encoding="utf-8") as f:
                    tree = ast.parse(f.read(), filename=path)
            except SyntaxError:
                continue
            _names_in_tree(
                tree, os.path.relpath(path, os.path.dirname(root)), out)
    return out


def readme_names(readme_path: str) -> dict:
    """{pattern: [line_no, ...]} over backticked metric-like tokens,
    ``<placeholder>`` segments normalized to ``*``."""
    out: Dict[str, List[int]] = {}
    with open(readme_path, "r", encoding="utf-8") as f:
        for i, line in enumerate(f, 1):
            for tok in re.findall(r"`([^`]+)`", line):
                if not _NAME_RE.match(tok):
                    continue
                pat = re.sub(r"<[^>]*>", "*", tok)
                out.setdefault(pat, []).append(i)
    return out


def _covered(name: str, patterns) -> bool:
    """A name (possibly itself a wildcard pattern) is covered when any
    pattern on the other side matches it — either direction, so
    ``serving/ttft_ms/p*`` (code f-string) pairs with
    ``serving/ttft_ms/p<class>`` (doc placeholder)."""
    for p in patterns:
        if p == name or fnmatch.fnmatchcase(name, p) \
                or fnmatch.fnmatchcase(p, name):
            return True
    return False


def drift(code: dict, docs: dict):
    """(undocumented, stale) between the two sides."""
    undocumented = {n: sites for n, sites in code.items()
                    if not _covered(n, docs)}
    stale = {n: lines for n, lines in docs.items()
             if not _covered(n, code)}
    return undocumented, stale


@register
class MetricNamesPass(LintPass):
    id = "metric-names"
    title = "README metric docs exactly cover telemetry call sites"

    def finalize(self, corpus: Corpus):
        code: Dict[str, List[str]] = {}
        for ctx in corpus.files:
            if ctx.tree is not None:
                _names_in_tree(ctx.tree, ctx.relpath, code)
        readme = os.path.join(corpus.root, "README.md")
        if not os.path.exists(readme):
            yield Finding(self.id, "README.md", 1, 0,
                          "README.md missing: metric names cannot be "
                          "checked against the operator docs")
            return
        docs = readme_names(readme)
        undocumented, stale = drift(code, docs)
        for n in sorted(undocumented):
            path, _, line = undocumented[n][0].rpartition(":")
            yield Finding(
                self.id, path, int(line), 0,
                f"metric `{n}` is emitted by code but not documented in "
                "README.md",
                suggestion="add it to the README metric tables "
                "(operators grep the README, not the source)")
        for n in sorted(stale):
            yield Finding(
                self.id, "README.md", stale[n][0], 0,
                f"metric `{n}` is documented in README.md but emitted "
                "by nothing",
                suggestion="remove the stale doc row (or restore the "
                "emitting call site)")
