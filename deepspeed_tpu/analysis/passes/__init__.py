"""Pass registry population: importing this package registers every
built-in pass.  To add a pass: new module here, subclass
:class:`~deepspeed_tpu.analysis.core.LintPass`, decorate with
``@register``, import it below, seed a bad/good fixture twin under
``tests/unit/analysis/fixtures/`` (README "how to add a pass")."""

from deepspeed_tpu.analysis.passes import (  # noqa: F401
    donation, host_sync, jax_compat, metric_names, pallas_dma,
    pallas_tile, recompile, sharding_contract, slo_rules, typed_errors,
    vmem_budget)
