"""vmem-budget — kernels and committed kernel plans fit VMEM (ISSUE 15).

A Pallas kernel that over-subscribes VMEM fails at Mosaic lowering — on
the TPU, at serving-rollout time, long after the plan that caused it
was committed.  This pass moves that failure into the lint, sharing ONE
capacity table with the kernels themselves
(``ops/autotune.py``: ``DEFAULT_VMEM_MB`` per generation,
``SCOPED_VMEM_MAX_MB`` for kernels that raise Mosaic's scoped limit —
the same constants ``decode_step._entry_vmem_mha`` clamps with):

  * **per-kernel scratch audit**: for every ``pl.pallas_call``, the
    constant-foldable ``pltpu.VMEM(shape, dtype)`` scratch entries are
    summed (a PARTIAL sum is a lower bound, so exceeding the budget on
    provable entries alone is already a certain violation).  The budget
    is the call's own ``vmem_limit_bytes`` when it folds (clamped to
    the scoped max), else the per-generation default.  A declared
    ``vmem_limit_bytes`` above the scoped max is flagged outright.
  * **committed-plan audit** (finalize): every entry in
    ``AUTOTUNE_KERNELS_MEASURED.json`` must fit — ``vmem_mb`` within
    the scoped clamp, and the plan's own resident footprint (4 chunk
    double-buffers for ``decode_step``'s ``bg``/``cs``, 2 int8 weight
    slots for ``int8_matmul_dma``'s ``bd``/``be``) inside the VMEM it
    declares.  A hand-edited or stale plan that cannot fit fails the
    LINT instead of the first TPU run.

Data-dependent scratch shapes fold to unknown and stay silent — the
dynamic plan resolvers (``_resolve_plan`` re-validation) own those.
"""

from __future__ import annotations

import ast
import json
import os
import re
from typing import Iterable, Optional

from deepspeed_tpu.analysis.core import Corpus, FileContext, Finding, \
    LintPass, register
from deepspeed_tpu.analysis.passes._pallas_util import (
    DTYPES, Env, collect_assigns, is_call_named, iter_pallas_calls)

SCOPES = ("deepspeed_tpu/ops/",)

ARTIFACT_NAME = "AUTOTUNE_KERNELS_MEASURED.json"

_DECODE_KEY = re.compile(
    r"^b(?P<b>\d+)_hkv(?P<hkv>\d+)_s(?P<s>\d+)_dh(?P<dh>\d+)_e(?P<e>\d+)$")
_MATMUL_KEY = re.compile(r"^d(?P<d>\d+)_e(?P<e>\d+)$")


AUTOTUNE_PATH = "deepspeed_tpu/ops/autotune.py"


def _budget_constants(corpus: Optional[Corpus] = None):
    """The one shared capacity table, read from the ANALYZED corpus's
    ``ops/autotune.py`` when it ships one (the lint tracks the code
    under ``--root``, not the installed copy — same convention as the
    sharding-contract axis registry, and the reason ``autotune.py`` is
    a cache ``GLOBAL_INPUT``); synthetic trees without the file fall
    back to the installed constants."""
    if corpus is not None:
        vals = {}
        for ctx in corpus.files:
            if ctx.relpath != AUTOTUNE_PATH or ctx.tree is None:
                continue
            for node in ctx.tree.body:
                if isinstance(node, ast.Assign) \
                        and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name) \
                        and isinstance(node.value, ast.Constant) \
                        and isinstance(node.value.value, int):
                    vals[node.targets[0].id] = node.value.value
        if "DEFAULT_VMEM_MB" in vals and "SCOPED_VMEM_MAX_MB" in vals:
            return vals["DEFAULT_VMEM_MB"], vals["SCOPED_VMEM_MAX_MB"]
    from deepspeed_tpu.ops import autotune

    return autotune.DEFAULT_VMEM_MB, autotune.SCOPED_VMEM_MAX_MB


def _is_vmem(node: ast.AST) -> bool:
    return is_call_named(node, "VMEM")


@register
class VmemBudgetPass(LintPass):
    id = "vmem-budget"
    title = "kernel scratch and committed kernel plans fit the VMEM " \
            "table"
    scope = SCOPES

    def begin(self, corpus: Corpus) -> None:
        self._table = _budget_constants(corpus)

    # ----------------------------------------------- per-kernel audit
    def check_file(self, ctx: FileContext) -> Iterable:
        if "pallas" not in ctx.source:
            return
        default_mb, max_mb = getattr(self, "_table", None) \
            or _budget_constants()
        module_assigns = collect_assigns(ctx.tree)
        for info, env in iter_pallas_calls(ctx.tree, module_assigns):
            budget = default_mb << 20
            declared = env.fold(info.vmem_limit_node) \
                if info.vmem_limit_node is not None else None
            if isinstance(declared, int):
                if declared > (max_mb << 20):
                    yield ctx.finding(
                        self.id, info.vmem_limit_node,
                        f"vmem_limit_bytes {declared} exceeds the "
                        f"scoped-VMEM max ({max_mb} MB) from the "
                        "ops/autotune.py capacity table",
                        suggestion="lower the scoped limit or split "
                        "the kernel's residency")
                budget = min(declared, max_mb << 20)
            elif info.vmem_limit_node is not None:
                # a declared-but-unfoldable limit (plan-resolved, e.g.
                # `plan.vmem_mb << 20`) may legitimately raise the
                # scope: budget at the scoped MAX, never the default —
                # the pass can miss, never hallucinate
                budget = max_mb << 20
            provable = 0
            for s in info.scratch:
                if not _is_vmem(s) or len(s.args) < 2:
                    continue
                dims = env.fold_dims(s.args[0])
                dtype = env.resolve_dtype(s.args[1])
                if not dims or dtype not in DTYPES \
                        or any(not isinstance(d, int) for d in dims):
                    continue
                n = DTYPES[dtype][0]
                for d in dims:
                    n *= d
                provable += n
            if provable > budget:
                yield ctx.finding(
                    self.id, info.node,
                    f"constant-foldable VMEM scratch alone totals "
                    f"{provable} bytes against a "
                    f"{budget >> 20} MB budget — this kernel cannot "
                    "lower on any generation in the table",
                    suggestion="shrink the scratch tiles or raise "
                    "vmem_limit_bytes within the scoped max "
                    "(ops/autotune.py SCOPED_VMEM_MAX_MB)")

    # -------------------------------------------- committed-plan audit
    def finalize(self, corpus: Corpus) -> Iterable:
        path = os.path.join(corpus.root, ARTIFACT_NAME)
        if not os.path.exists(path):
            return
        default_mb, max_mb = _budget_constants(corpus)
        try:
            with open(path, "r", encoding="utf-8") as f:
                art = json.load(f)
            plans = art.get("plans", {})
            if not isinstance(plans, dict):
                raise ValueError("plans is not an object")
        except (OSError, ValueError) as e:
            yield Finding(self.id, ARTIFACT_NAME, 1, 0,
                          f"unreadable kernel-plan artifact: {e}",
                          suggestion="regenerate with "
                          "scripts/autotune_kernels.py")
            return
        for kind, entries in sorted(plans.items()):
            if not isinstance(entries, dict):
                continue
            for key, ent in sorted(entries.items()):
                if not isinstance(ent, dict):
                    continue
                yield from self._check_entry(kind, key, ent,
                                             default_mb, max_mb)

    def _check_entry(self, kind: str, key: str, ent: dict,
                     default_mb: int, max_mb: int) -> Iterable:
        loc = f"plans.{kind}.{key}"
        vmem_mb = ent.get("vmem_mb")
        # the clamp must MATCH decode_step._entry_vmem_mha:
        # max(DEFAULT_VMEM_MB, min(vmem_mb, SCOPED_VMEM_MAX_MB)) — a
        # plan below the floor is re-clamped UP on device just as one
        # above the ceiling is re-clamped down
        if isinstance(vmem_mb, (int, float)) \
                and not default_mb <= vmem_mb <= max_mb:
            yield Finding(
                self.id, ARTIFACT_NAME, 1, 0,
                f"{loc}: vmem_mb={vmem_mb} outside the scoped clamp "
                f"[{default_mb}, {max_mb}] (ops/autotune.py) — the "
                "kernel would silently re-clamp and the measurement "
                "lies",
                symbol=loc,
                suggestion="re-measure with a plan inside the clamp")
            return
        if kind == "decode_step":
            m = _DECODE_KEY.match(key)
            if not m:
                yield Finding(self.id, ARTIFACT_NAME, 1, 0,
                              f"{loc}: malformed decode_step shape key",
                              severity="warning", symbol=loc,
                              suggestion="keys come from "
                              "autotune.decode_key(...)")
                return
            bg, cs = ent.get("bg"), ent.get("cs")
            if not (isinstance(bg, int) and isinstance(cs, int)):
                return
            hkv = int(m.group("hkv"))
            dh = int(m.group("dh"))
            itemsize = int(m.group("e"))
            # 2 slots x {K, V} chunk double-buffers resident at once
            resident = 4 * bg * hkv * cs * dh * itemsize
            budget_mb = vmem_mb if isinstance(vmem_mb, (int, float)) \
                else max_mb
            if resident > int(budget_mb) << 20:
                yield Finding(
                    self.id, ARTIFACT_NAME, 1, 0,
                    f"{loc}: committed plan (bg={bg}, cs={cs}) needs "
                    f"{resident} bytes of chunk double-buffers but "
                    f"declares only {budget_mb} MB of scoped VMEM — "
                    "this plan cannot fit; it would fail Mosaic "
                    "lowering on the first TPU run",
                    symbol=loc,
                    suggestion="re-measure; the harness must reject "
                    "candidates whose chunks outgrow vmem_mb")
        elif kind == "int8_matmul_dma":
            if not _MATMUL_KEY.match(key):
                yield Finding(self.id, ARTIFACT_NAME, 1, 0,
                              f"{loc}: malformed int8_matmul_dma key",
                              severity="warning", symbol=loc,
                              suggestion="keys come from "
                              "autotune.matmul_key(d, e)")
                return
            bd, be = ent.get("bd"), ent.get("be")
            if isinstance(bd, int) and isinstance(be, int):
                resident = 2 * bd * be       # two int8 weight slots
                if resident > default_mb << 20:
                    yield Finding(
                        self.id, ARTIFACT_NAME, 1, 0,
                        f"{loc}: committed tile plan (bd={bd}, "
                        f"be={be}) streams {resident} bytes of weight "
                        f"slots against the {default_mb} MB default "
                        "VMEM scope (int8_matmul_dma raises no scoped "
                        "limit) — this plan cannot fit",
                        symbol=loc,
                        suggestion="re-measure under the tile cap "
                        "(_hand_dma_plan's VMEM budget)")
