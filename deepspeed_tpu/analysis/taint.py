"""Shared donated-buffer taint engine (ISSUE 15).

One linearized read-after-donate scan used by BOTH donation passes:

  * ``donation-safety`` (per-scope): taint sources are donating
    callables BOUND IN THE SAME FUNCTION (``f = jax.jit(g,
    donate_argnums=...)``) — PR 13's pass, now with the known
    false-negative shapes fixed (below);
  * ``sharding-contract`` (interprocedural): taint sources are resolved
    through the phase-1 index — a call to a helper whose summary says
    it donates, or to a donating callable stored on ``self`` in another
    method / bound at module level.  The two source sets are disjoint
    by construction, so the passes never double-report one read.

Semantics (ported from PR 13's donation pass, behavior-pinned by its
tests): events (loads, donating calls, stores, function exits) are
linearized by source position with same-line priority ordering loads →
call → stores → exits, so ``x = f(x)`` never taints; stores clear taint
(and a store of ``self.state`` revives ``self.state.params``); a
Return/Raise clears only donations made in its own branch subtree, so a
conditional early return cannot launder the fallthrough path.

ISSUE 15 regression fixes (each pinned by a fixture):

  * **augmented assignment reads** — ``x += 1`` after donating ``x`` is
    a READ of the stale buffer before the store; the old pass saw only
    the Store ctx and silently cleared the taint;
  * **try/finally** — a ``return`` inside a ``try`` that has a
    ``finally`` defers its taint-clear until AFTER the last finally
    line: the finally body still runs (a donated read there must flag)
    but the post-try fallthrough of the returning branch is dead and
    must not false-positive;
  * **tuple-bound donating callables** — ``f, g = jax.jit(a,
    donate_argnums=(0,)), jax.jit(b)`` now registers ``f`` as a donor
    (the old pass only looked at single-target assigns).
"""

from __future__ import annotations

import ast
from typing import Callable, Dict, Iterable, List, Optional, Set, Tuple

# one canonical copy of the jit/donate-argnums parsing (index.py is the
# cycle-free home; a drift between the per-scope pass and the
# interprocedural summaries would silently desynchronize the two)
from deepspeed_tpu.analysis.index import (attr_chain,   # noqa: F401
                                          donated_positions, is_jit_call)

#: resolve a call node to (donated call-arg positions, provenance text);
#: return ((), "") when the call is not a known donor
CallResolver = Callable[[ast.Call], Tuple[Tuple[int, ...], str]]

#: resolve a call node to the call-arg positions its return value
#: aliases (returns-alias-of-arg); () when unknown
AliasResolver = Callable[[ast.Call], Tuple[int, ...]]


def walk_scope(fn: ast.AST, _path: Tuple = (),
               _trys: Optional[Dict[int, ast.Try]] = None):
    """Walk one function's OWN body — never descending into nested
    function/class scopes.  Yields ``(node, branch_path)`` where
    branch_path identifies the chain of conditional arms the node sits
    in (``(id(stmt), arm), ...``).  ``_trys`` (shared dict) collects
    Try nodes so exit handling can see ``finalbody``."""
    for field_name, value in ast.iter_fields(fn):
        branches = ()
        if isinstance(fn, (ast.If, ast.For, ast.AsyncFor, ast.While,
                           ast.Try)) and field_name in (
                "body", "orelse", "handlers", "finalbody"):
            branches = ((id(fn), field_name),)
            if isinstance(fn, ast.Try) and _trys is not None:
                _trys[id(fn)] = fn
        for child in (value if isinstance(value, list) else [value]):
            if not isinstance(child, ast.AST):
                continue
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda, ast.ClassDef)):
                continue
            path = _path + branches
            yield child, path
            yield from walk_scope(child, path, _trys)


def ref_of(node: ast.AST) -> str:
    """Canonical dotted name for a Name / self-attribute chain ('' when
    the expression is not a trackable reference)."""
    chain = attr_chain(node)
    if chain and (chain.count(".") == 0 or chain.startswith("self.")):
        return chain
    return ""


class _Event:
    __slots__ = ("pos", "kind", "name", "node", "path", "extra")

    def __init__(self, pos, kind, name, node, path=(), extra=None):
        self.pos, self.kind, self.name = pos, kind, name
        self.node, self.path, self.extra = node, path, extra


def scan_function(ctx, fn: ast.AST, *, pass_id: str,
                  resolve_call: Optional[CallResolver] = None,
                  resolve_alias: Optional[AliasResolver] = None,
                  track_local_binds: bool = True,
                  suggestion: str = "read the value BEFORE the donating "
                  "call, use the call's outputs, or drop the donation",
                  ) -> Iterable:
    """Yield read-after-donate findings for one function scope.

    ``resolve_alias`` (interprocedural only) consumes the phase-1
    ``returns_args`` summaries: ``y = view(x)`` where ``view`` returns
    its argument links ``y`` and ``x`` to ONE buffer, so a later
    donation of either taints both — the alias-laundering shape no
    per-name scan can see."""
    trys: Dict[int, ast.Try] = {}
    events: List[_Event] = []
    binds: List[Tuple[tuple, str, Tuple[int, ...]]] = []

    if track_local_binds:
        for node, _ in walk_scope(fn, _trys={}):
            if not isinstance(node, ast.Assign):
                continue
            pairs: List[Tuple[ast.AST, ast.AST]] = []
            for tgt in node.targets:
                if isinstance(tgt, (ast.Tuple, ast.List)) \
                        and isinstance(node.value, (ast.Tuple, ast.List)) \
                        and len(tgt.elts) == len(node.value.elts):
                    pairs += list(zip(tgt.elts, node.value.elts))
                else:
                    pairs.append((tgt, node.value))
            for tgt, val in pairs:
                if not is_jit_call(val):
                    continue
                pos = donated_positions(val)
                if not pos:
                    continue
                name = ref_of(tgt)
                if name:
                    # 2.5: after the plain store event at the same spot
                    # (which unbinds), so the bind wins
                    binds.append(((tgt.lineno, 2.5, tgt.col_offset),
                                  name, pos))
    bindable = {name for _, name, _ in binds}
    if track_local_binds and not binds and resolve_call is None:
        return
    for pos, name, positions in binds:
        events.append(_Event(pos, "bind", name, positions))

    # Linearize loads / stores / donating calls by source position.
    # Priority orders same-line events the way evaluation does: loads
    # (RHS) -> the donating call -> stores (LHS binds last) -> exits;
    # `x = f(x)` therefore never taints x.
    for node, path in walk_scope(fn, _trys=trys):
        if isinstance(node, ast.Call):
            cname = ref_of(node.func)
            if cname and cname in bindable:
                events.append(_Event((node.lineno, 1, node.col_offset),
                                     "call", cname, node, path))
            elif resolve_call is not None:
                positions, via = resolve_call(node)
                if positions:
                    events.append(_Event(
                        (node.lineno, 1, node.col_offset), "xcall", "",
                        node, path, extra=(positions, via)))
        elif isinstance(node, (ast.Return, ast.Raise)):
            # control leaves the function: donations made in this exit's
            # own branch subtree are dead for later source lines — but a
            # conditional early return must NOT launder the fallthrough
            # path.  A return inside try-with-finally must not launder
            # the finally body (it still runs), yet it DOES kill the
            # post-try fallthrough of its own branch — so the clear is
            # DEFERRED to just after the last finally line instead of
            # dropped entirely.
            finals = [trys[id_] for id_, fld in path
                      if fld != "finalbody" and id_ in trys
                      and trys[id_].finalbody]
            if finals:
                end = max(getattr(stmt, "end_lineno", stmt.lineno)
                          for t in finals for stmt in t.finalbody)
                pos = (end, 3.5, 0)
            else:
                pos = (getattr(node, "end_lineno", node.lineno), 3, 0)
            events.append(_Event(pos, "exit", "", node, path))
        elif isinstance(node, ast.Assign) and resolve_alias is not None \
                and isinstance(node.value, ast.Call):
            srcs = ()
            positions = resolve_alias(node.value)
            if positions:
                srcs = {ref_of(node.value.args[p]) for p in positions
                        if p < len(node.value.args)} - {""}
            if srcs:
                for tgt in node.targets:
                    name = ref_of(tgt)
                    # 2.25: after the store event (which unbinds the
                    # target), so the alias link wins for later lines;
                    # `x = view(x)` stays the canonical clean rebind
                    if name and name not in srcs:
                        events.append(_Event(
                            (node.lineno, 2.25, tgt.col_offset),
                            "alias", name, node, path, extra=srcs))
        elif isinstance(node, ast.AugAssign):
            # `x += 1` READS x before rebinding it: the read of a
            # donated buffer must flag even though the ctx is Store
            name = ref_of(node.target)
            if name:
                events.append(_Event(
                    (node.lineno, 0, node.target.col_offset), "load",
                    name, node.target))
        elif isinstance(node, (ast.Name, ast.Attribute)):
            name = ref_of(node)
            if not name:
                continue
            if isinstance(node.ctx, ast.Store):
                events.append(_Event((node.lineno, 2, node.col_offset),
                                     "store", name, node))
            elif isinstance(node.ctx, ast.Load):
                events.append(_Event((node.lineno, 0, node.col_offset),
                                     "load", name, node))
    events.sort(key=lambda e: e.pos)

    bound: Dict[str, Tuple[int, ...]] = {}   # name -> donated argnums
    tainted: Dict[str, tuple] = {}   # ref -> (donor call, branch path, via)
    aliases: Dict[str, Set[str]] = {}   # ref -> SHARED alias group set
    reported: Set[Tuple[str, int]] = set()

    def _taint(ref: str, info: tuple) -> None:
        # donating one name stales every alias of the same buffer
        for n in aliases.get(ref, {ref}):
            tainted[n] = info

    for ev in events:
        if ev.kind == "exit":
            for name in [n for n, (_, dpath, _) in tainted.items()
                         if dpath[:len(ev.path)] == ev.path]:
                tainted.pop(name)
        elif ev.kind == "bind":
            bound[ev.name] = ev.node   # node slot carries positions
        elif ev.kind == "call" and ev.name in bound:
            call = ev.node
            for p in bound[ev.name]:
                if p < len(call.args):
                    ref = ref_of(call.args[p])
                    if ref:
                        _taint(ref, (
                            call, ev.path,
                            f"donated to the jit call on line "
                            f"{call.lineno} (donate_argnums)"))
        elif ev.kind == "xcall":
            positions, via = ev.extra
            call = ev.node
            for p in positions:
                if p < len(call.args):
                    ref = ref_of(call.args[p])
                    if ref:
                        _taint(ref, (
                            call, ev.path,
                            f"donated by the call on line {call.lineno} "
                            f"— {via}"))
        elif ev.kind == "alias":
            group: Set[str] = {ev.name}
            for m in {ev.name} | set(ev.extra):
                group |= aliases.get(m, {m})
            for m in group:
                aliases[m] = group
            for m in ev.extra:      # alias OF a donated buffer is stale
                if m in tainted:
                    tainted[ev.name] = tainted[m]
                    break
        elif ev.kind == "store":
            tainted.pop(ev.name, None)
            bound.pop(ev.name, None)   # rebound to something else
            grp = aliases.pop(ev.name, None)
            if grp is not None:        # rebinding detaches from the group
                grp.discard(ev.name)
            # rebinding `self.state` also revives `self.state.params`
            for t in [t for t in tainted if t.startswith(ev.name + ".")]:
                tainted.pop(t, None)
        elif ev.kind == "load" and ev.name in tainted:
            donor, _, via = tainted[ev.name]
            if ev.node.lineno <= getattr(donor, "end_lineno",
                                         donor.lineno):
                continue   # load inside/before the donating call
                           # statement (evaluated pre-donation)
            key = (ev.name, ev.node.lineno)
            if key in reported:
                continue
            reported.add(key)
            yield ctx.finding(
                pass_id, ev.node,
                f"`{ev.name}` was {via} and read here: the buffer may "
                "already be reused in place",
                suggestion=suggestion)
