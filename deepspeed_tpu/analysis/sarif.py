"""SARIF 2.1.0 output for dstpu-lint (ISSUE 15).

One run object, one result per unsuppressed finding, pass id → ruleId,
severity → level — the shape CI annotators (GitHub code scanning et
al.) ingest to pin findings onto diff lines.  Baseline drift is
reported too (stale entries / over-budget as ``baseline`` rule
results), so a SARIF consumer sees exactly what makes the CLI exit
non-zero.

:func:`validate_sarif` is a structural validator for the subset of the
SARIF 2.1.0 schema this emitter uses; the unit tests run every emitted
document through it (and through ``jsonschema`` against the embedded
subset schema when the library is available — the full 2.1.0 schema is
not vendored).
"""

from __future__ import annotations

from typing import Dict, List

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/"
                "sarif-spec/master/Schemata/sarif-schema-2.1.0.json")

#: synthetic rule ids the framework itself can emit (no LintPass object)
_FRAMEWORK_RULES = {
    "lint-directive": "suppression directives are well-formed and live",
    "lint-parse": "every in-scope file parses",
    "baseline": "the committed baseline matches the tree and its budget",
}

_LEVELS = {"error": "error", "warning": "warning"}


def to_sarif(result, passes: Dict[str, object], tool_version: str = "15"
             ) -> dict:
    """``LintResult`` → SARIF 2.1.0 document (one run)."""
    rule_ids: List[str] = []
    rules = []
    for pid in result.passes_run:
        p = passes.get(pid)
        rule_ids.append(pid)
        rules.append({
            "id": pid,
            "shortDescription": {
                "text": getattr(p, "title", "") or pid},
        })
    for pid, text in _FRAMEWORK_RULES.items():
        rule_ids.append(pid)
        rules.append({"id": pid, "shortDescription": {"text": text}})
    rule_index = {pid: i for i, pid in enumerate(rule_ids)}

    results = []
    for f in result.findings:
        msg = f.message + (f"\nfix: {f.suggestion}" if f.suggestion
                           else "")
        results.append({
            "ruleId": f.pass_id,
            "ruleIndex": rule_index.get(f.pass_id, -1),
            "level": _LEVELS.get(f.severity, "error"),
            "message": {"text": msg},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path,
                                         "uriBaseId": "SRCROOT"},
                    "region": {"startLine": max(f.line, 1),
                               "startColumn": f.col + 1},
                },
            }],
        })
    for e in result.stale_baseline:
        results.append({
            "ruleId": "baseline",
            "ruleIndex": rule_index["baseline"],
            "level": "error",
            "message": {"text": f"stale baseline entry [{e.pass_id}] "
                                f"{e.message!r} matches nothing — "
                                "remove it (burn-down)"},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": e.path or
                                         "LINT_BASELINE.json",
                                         "uriBaseId": "SRCROOT"},
                    "region": {"startLine": 1, "startColumn": 1},
                },
            }],
        })
    if result.over_budget:
        results.append({
            "ruleId": "baseline",
            "ruleIndex": rule_index["baseline"],
            "level": "error",
            "message": {"text": f"{result.over_budget} baseline "
                                "entr(ies) over the committed budget — "
                                "the baseline only burns down"},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": "LINT_BASELINE.json",
                                         "uriBaseId": "SRCROOT"},
                    "region": {"startLine": 1, "startColumn": 1},
                },
            }],
        })

    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {"driver": {
                "name": "dstpu-lint",
                "informationUri":
                    "README.md#static-analysis-dstpu-lint",
                "version": tool_version,
                "rules": rules,
            }},
            "columnKind": "utf16CodeUnits",
            "results": results,
        }],
    }


#: JSON-Schema for the emitted subset — used with ``jsonschema`` in the
#: unit tests when available, mirrored by :func:`validate_sarif` below
SARIF_SUBSET_SCHEMA = {
    "type": "object",
    "required": ["$schema", "version", "runs"],
    "properties": {
        "version": {"const": "2.1.0"},
        "$schema": {"type": "string"},
        "runs": {
            "type": "array", "minItems": 1,
            "items": {
                "type": "object",
                "required": ["tool", "results"],
                "properties": {
                    "tool": {
                        "type": "object", "required": ["driver"],
                        "properties": {"driver": {
                            "type": "object", "required": ["name"],
                            "properties": {
                                "name": {"type": "string"},
                                "rules": {
                                    "type": "array",
                                    "items": {
                                        "type": "object",
                                        "required": ["id"],
                                        "properties": {
                                            "id": {"type": "string"}},
                                    }},
                            }}},
                    },
                    "results": {
                        "type": "array",
                        "items": {
                            "type": "object",
                            "required": ["ruleId", "level", "message",
                                         "locations"],
                            "properties": {
                                "ruleId": {"type": "string"},
                                "level": {"enum": ["none", "note",
                                                   "warning", "error"]},
                                "message": {
                                    "type": "object",
                                    "required": ["text"],
                                    "properties": {"text": {
                                        "type": "string"}}},
                                "locations": {
                                    "type": "array", "minItems": 1,
                                    "items": {
                                        "type": "object",
                                        "required": ["physicalLocation"],
                                        "properties": {
                                            "physicalLocation": {
                                                "type": "object",
                                                "required": [
                                                    "artifactLocation"],
                                                "properties": {
                                                    "artifactLocation": {
                                                        "type": "object",
                                                        "required": [
                                                            "uri"],
                                                    },
                                                    "region": {
                                                        "type": "object",
                                                        "properties": {
                                                            "startLine": {
                                                                "type":
                                                                "integer",
                                                                "minimum":
                                                                1},
                                                            "startColumn":
                                                            {"type":
                                                             "integer",
                                                             "minimum":
                                                             1},
                                                        }},
                                                }},
                                        }},
                                },
                            }},
                    },
                }},
        },
    },
}


def validate_sarif(doc) -> List[str]:
    """Structural problems in ``doc`` against the SARIF 2.1.0 subset
    this tool emits (empty list == valid).  Dependency-free mirror of
    :data:`SARIF_SUBSET_SCHEMA` for environments without jsonschema."""
    probs: List[str] = []

    def need(obj, key, typ, where):
        if not isinstance(obj, dict) or key not in obj:
            probs.append(f"{where}: missing {key!r}")
            return None
        if typ is not None and not isinstance(obj[key], typ):
            probs.append(f"{where}.{key}: expected {typ.__name__}")
            return None
        return obj[key]

    if not isinstance(doc, dict):
        return ["document is not an object"]
    if doc.get("version") != SARIF_VERSION:
        probs.append(f"version: expected {SARIF_VERSION!r}")
    need(doc, "$schema", str, "$")
    runs = need(doc, "runs", list, "$") or []
    if not runs:
        probs.append("runs: must have at least one run")
    for i, run in enumerate(runs):
        where = f"runs[{i}]"
        tool = need(run, "tool", dict, where) or {}
        driver = need(tool, "driver", dict, f"{where}.tool") or {}
        need(driver, "name", str, f"{where}.tool.driver")
        for j, rule in enumerate(driver.get("rules", []) or []):
            need(rule, "id", str, f"{where}...rules[{j}]")
        results = need(run, "results", list, where) or []
        for j, r in enumerate(results):
            rw = f"{where}.results[{j}]"
            need(r, "ruleId", str, rw)
            lvl = need(r, "level", str, rw)
            if lvl is not None and lvl not in ("none", "note", "warning",
                                               "error"):
                probs.append(f"{rw}.level: invalid {lvl!r}")
            msg = need(r, "message", dict, rw) or {}
            need(msg, "text", str, f"{rw}.message")
            locs = need(r, "locations", list, rw) or []
            if not locs:
                probs.append(f"{rw}.locations: empty")
            for k, loc in enumerate(locs):
                pl = need(loc, "physicalLocation", dict,
                          f"{rw}.locations[{k}]") or {}
                al = need(pl, "artifactLocation", dict,
                          f"{rw}.locations[{k}].physicalLocation") or {}
                need(al, "uri", str,
                     f"{rw}.locations[{k}]...artifactLocation")
                region = pl.get("region")
                if isinstance(region, dict):
                    for fld in ("startLine", "startColumn"):
                        v = region.get(fld)
                        if v is not None and (not isinstance(v, int)
                                              or v < 1):
                            probs.append(
                                f"{rw}...region.{fld}: must be a "
                                f"positive integer, got {v!r}")
    return probs
