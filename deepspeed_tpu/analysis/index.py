"""Phase-1 corpus index: modules, imports, call graph, summaries (ISSUE 15).

PR 13's passes are single-file AST scans — none can see a donated buffer
flow into a helper, and none knows that ``self._compiled_train_step``
(bound in one method) donates its first argument when called from
another.  This module is the corpus-level upgrade the interprocedural
passes run on:

  * **ModuleTable** — relpath ↔ dotted module name, top-level symbols
    (functions, classes, methods), per-module import map (local name →
    fully-qualified target, relative imports resolved);
  * **FunctionSummary** — per function/method: positional params,
    ``donates`` (param positions handed to XLA for in-place reuse when
    the function is called — directly via a local ``jax.jit(...,
    donate_argnums=...)`` bind, via a donating callable stored on
    ``self`` in ANY method of the same class, via a module-level
    donating callable, or TRANSITIVELY via a call to another summarized
    function), ``returns_args`` (params returned directly —
    returns-alias-of-arg), and resolved call edges;
  * **call graph** — edges resolved through the import maps and class
    method tables, plus Tarjan SCCs (summary soundness over mutual
    recursion is pinned through them; the incremental cache invalidates
    the reverse IMPORT closure, a conservative file-level superset of
    any changed SCC region);
  * **import graph** — module → imported modules (repo-internal), with
    a reverse closure used by the incremental cache to invalidate every
    file whose findings could depend on a changed file's summaries.

Everything here is pure-AST and stdlib-only (the lint must run without
jax installed, and must never import the code it analyzes).  Resolution
is CONSERVATIVE: an unresolvable callee is simply an absent edge —
passes built on the index can miss, never hallucinate.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

# package root all corpus modules live under; imports outside it are
# third-party and never indexed
_PKG = "deepspeed_tpu"


def attr_chain(node: ast.AST) -> str:
    """Dotted name of an Attribute/Name chain ('' when the chain roots
    in a call or subscript).  CANONICAL home: passes/_ast_util.py and
    taint.py re-export from here (this module cannot import the passes
    package — its __init__ imports the passes, which import this
    index)."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def module_name(relpath: str) -> str:
    """Dotted module name of a repo-relative path
    (``deepspeed_tpu/ops/decode_step.py`` → ``deepspeed_tpu.ops.
    decode_step``; package ``__init__.py`` maps to the package)."""
    mod = relpath[:-3] if relpath.endswith(".py") else relpath
    mod = mod.replace("/", ".")
    if mod.endswith(".__init__"):
        mod = mod[: -len(".__init__")]
    return mod


def donated_positions(call: ast.Call) -> Tuple[int, ...]:
    """Constant ``donate_argnums`` of a jit/pjit construction (``()``
    when absent or non-constant — conservative).  Canonical home —
    taint.py re-exports from here."""
    for kw in call.keywords:
        if kw.arg != "donate_argnums":
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, int):
            return (v.value,)
        if isinstance(v, (ast.Tuple, ast.List)):
            out = []
            for e in v.elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, int):
                    out.append(e.value)
            return tuple(out)
    return ()


def _walk_scope(fn: ast.AST):
    """Own-scope walk for summary building: yields nested function/
    class nodes themselves (they BIND a local name) but never descends
    into them — a closure's body does not execute when the enclosing
    function is called, so its donations/returns must not pollute the
    enclosing summary (mirrors taint.walk_scope's scope rule)."""
    for child in ast.iter_child_nodes(fn):
        yield child
        if not isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda, ast.ClassDef)):
            yield from _walk_scope(child)


def is_self_call(call: ast.Call) -> bool:
    """``self.method(...)`` — the only Attribute-call form that is
    provably BOUND (arg 0 is the first real param).  An unbound
    class-attribute call like ``Engine.step(eng, state)`` passes
    ``self`` explicitly, so its args line up with the params 1:1."""
    f = call.func
    return (isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name)
            and f.value.id == "self")


def is_jit_call(node: ast.AST) -> bool:
    """``jax.jit(...)`` / ``jit(...)`` / ``pjit(...)`` construction.
    Canonical home — passes/_ast_util.py and taint.py re-export."""
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    name = f.attr if isinstance(f, ast.Attribute) else (
        f.id if isinstance(f, ast.Name) else "")
    return name in ("jit", "pjit")


@dataclass
class CallEdge:
    """One resolved call site inside a function."""

    node: ast.Call
    callee: str                      # FQN ("deepspeed_tpu.x.f" / ".C.m")
    #: callee param position -> caller param position, for args that are
    #: plain reads of the caller's own positional params
    param_args: Dict[int, int] = field(default_factory=dict)


@dataclass
class FunctionSummary:
    """What a caller needs to know about a function without its body."""

    fqn: str                         # module + "." + qualname
    module: str
    qualname: str                    # "fn" or "Class.method"
    relpath: str
    node: ast.AST
    params: Tuple[str, ...] = ()
    #: param positions donated (directly or transitively) when called
    donates: Set[int] = field(default_factory=set)
    #: human-readable provenance per donated position (for findings)
    donates_via: Dict[int, str] = field(default_factory=dict)
    #: param positions returned directly (returns-alias-of-arg)
    returns_args: Set[int] = field(default_factory=set)
    calls: List[CallEdge] = field(default_factory=list)
    #: every name bound in the function's own scope (params, assigns,
    #: nested defs): a call through such a name must NOT resolve to a
    #: same-named module-level function — local shadowing wins
    local_binds: Set[str] = field(default_factory=set)

    @property
    def is_method(self) -> bool:
        return "." in self.qualname


@dataclass
class ClassInfo:
    fqn: str
    module: str
    name: str
    relpath: str
    methods: Dict[str, str] = field(default_factory=dict)  # name -> FQN
    #: self-attribute -> (donated positions — the INTERSECTION across
    #: every binding method, so only provably-donated-under-any-live-
    #: bind positions survive, "bound in <method>[/..]", frozenset of
    #: binding method names) for donating callables stored on the
    #: instance in ANY method
    donating_attrs: Dict[str, Tuple[Tuple[int, ...], str,
                                    FrozenSet[str]]] = \
        field(default_factory=dict)


class CorpusIndex:
    """The phase-1 artifact every interprocedural pass shares."""

    def __init__(self) -> None:
        self.modules: Dict[str, str] = {}          # module -> relpath
        self.relpaths: Dict[str, str] = {}         # relpath -> module
        self.imports: Dict[str, Dict[str, str]] = {}   # module -> local->FQN
        self.functions: Dict[str, FunctionSummary] = {}    # FQN -> summary
        self.classes: Dict[str, ClassInfo] = {}            # FQN -> class
        #: module-level donating callables: FQN -> donated positions
        self.donating_globals: Dict[str, Tuple[int, ...]] = {}
        self.import_graph: Dict[str, Set[str]] = {}    # module -> imports

    # ------------------------------------------------------------ build
    @classmethod
    def build(cls, corpus) -> "CorpusIndex":
        idx = cls()
        for ctx in corpus.files:
            if ctx.tree is None:
                continue
            idx._index_file(ctx)
        idx._resolve_calls()
        idx._donation_fixpoint()
        return idx

    def _index_file(self, ctx) -> None:
        mod = module_name(ctx.relpath)
        self.modules[mod] = ctx.relpath
        self.relpaths[ctx.relpath] = mod
        imap: Dict[str, str] = {}
        igraph: Set[str] = set()
        # base package for relative imports: `module_name` strips
        # `.__init__`, so a package __init__'s `mod` IS its package —
        # level 1 anchors there, not one level higher
        pkg_parts = mod.split(".") if ctx.relpath.endswith("__init__.py") \
            else mod.split(".")[:-1]
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    local = a.asname or a.name.split(".")[0]
                    target = a.name if a.asname else a.name.split(".")[0]
                    imap[local] = target
                    if a.name.startswith(_PKG):
                        igraph.add(a.name)
            elif isinstance(node, ast.ImportFrom):
                base = node.module or ""
                if node.level:      # relative: resolve against the package
                    anchor = pkg_parts[: len(pkg_parts) - (node.level - 1)]
                    base = ".".join(anchor + ([base] if base else []))
                for a in node.names:
                    if a.name == "*":
                        continue
                    imap[a.asname or a.name] = f"{base}.{a.name}" \
                        if base else a.name
                    if base.startswith(_PKG):
                        igraph.add(base)
        self.imports[mod] = imap
        self.import_graph[mod] = igraph

        for node in ctx.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._add_function(ctx, mod, node.name, node)
            elif isinstance(node, ast.ClassDef):
                cinfo = ClassInfo(fqn=f"{mod}.{node.name}", module=mod,
                                  name=node.name, relpath=ctx.relpath)
                self.classes[cinfo.fqn] = cinfo
                # donating attrs FIRST: each method's summary scan
                # consults them to mark params donated through
                # `self.<attr>(param, ...)` calls
                self._scan_donating_attrs(cinfo, node)
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        qual = f"{node.name}.{item.name}"
                        self._add_function(ctx, mod, qual, item)
                        cinfo.methods[item.name] = f"{mod}.{qual}"
            elif isinstance(node, ast.Assign) and is_jit_call(node.value):
                pos = donated_positions(node.value)
                if pos:
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            self.donating_globals[f"{mod}.{tgt.id}"] = pos

    def _add_function(self, ctx, mod: str, qual: str, node) -> None:
        params = tuple(a.arg for a in (node.args.posonlyargs
                                       + node.args.args))
        s = FunctionSummary(fqn=f"{mod}.{qual}", module=mod, qualname=qual,
                            relpath=ctx.relpath, node=node, params=params)
        self.functions[s.fqn] = s
        self._scan_function(s)

    def _scan_donating_attrs(self, cinfo: ClassInfo,
                             cnode: ast.ClassDef) -> None:
        """``self.X = jax.jit(..., donate_argnums=...)`` in any method of
        the class registers ``X`` as a donating instance attribute —
        the cross-method donation channel (bound in __init__, called in
        step) the per-scope pass is blind to.  EVERY assign to the same
        attribute participates: a rebind to a plain callable (or a
        non-donating jit) contributes an empty position set, and only
        positions EVERY live bind provably donates survive the
        intersection — a maybe-donating attr goes silent rather than
        hallucinating."""
        binds: Dict[str, List[Tuple[str, Set[int]]]] = {}
        for method in cnode.body:
            if not isinstance(method, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                continue
            for node in ast.walk(method):
                if not isinstance(node, ast.Assign):
                    continue
                pos = set(donated_positions(node.value)) \
                    if is_jit_call(node.value) else set()
                for tgt in node.targets:
                    if (isinstance(tgt, ast.Attribute)
                            and isinstance(tgt.value, ast.Name)
                            and tgt.value.id == "self"):
                        binds.setdefault(tgt.attr, []).append(
                            (method.name, pos))
        for attr, evs in binds.items():
            if not any(p for _, p in evs):
                continue         # never donating: not a channel at all
            inter = set.intersection(*(p for _, p in evs))
            methods = frozenset(m for m, _ in evs)
            where = "bound in " + "/".join(
                f"{cinfo.name}.{m}" for m in sorted(methods))
            cinfo.donating_attrs[attr] = (tuple(sorted(inter)), where,
                                          methods)

    # ----------------------------------------------- per-function scan
    def _scan_function(self, s: FunctionSummary) -> None:
        """Direct summary facts: local donating binds, donating calls on
        own params, returns-alias-of-arg, raw call list (resolved
        later, once every module is indexed)."""
        param_pos = {p: i for i, p in enumerate(s.params)}
        local_donors: Dict[str, Tuple[int, ...]] = {}
        a = s.node.args
        s.local_binds.update(s.params)
        s.local_binds.update(x.arg for x in a.kwonlyargs)
        for x in (a.vararg, a.kwarg):
            if x is not None:
                s.local_binds.add(x.arg)
        for node in _walk_scope(s.node):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                s.local_binds.add(node.name)
            elif isinstance(node, ast.Name) \
                    and isinstance(node.ctx, ast.Store):
                s.local_binds.add(node.id)
            if isinstance(node, ast.Assign) and is_jit_call(node.value):
                pos = donated_positions(node.value)
                if not pos:
                    continue
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        local_donors[tgt.id] = pos
            elif isinstance(node, ast.Return) and node.value is not None:
                vals = node.value.elts \
                    if isinstance(node.value, ast.Tuple) else [node.value]
                for v in vals:
                    if isinstance(v, ast.Name) and v.id in param_pos:
                        s.returns_args.add(param_pos[v.id])
        for node in _walk_scope(s.node):
            if not isinstance(node, ast.Call):
                continue
            s.calls.append(CallEdge(node=node, callee=""))
            # direct donation of own params through local / self-attr /
            # (later) global donating callables
            donor_pos: Tuple[int, ...] = ()
            via = ""
            f = node.func
            if isinstance(f, ast.Name) and f.id in local_donors:
                donor_pos = local_donors[f.id]
                via = f"local jit bind `{f.id}`"
            elif (isinstance(f, ast.Attribute)
                  and isinstance(f.value, ast.Name)
                  and f.value.id == "self" and s.is_method):
                cls_fqn = f"{s.module}.{s.qualname.rsplit('.', 1)[0]}"
                cinfo = self.classes.get(cls_fqn)
                if cinfo and f.attr in cinfo.donating_attrs:
                    donor_pos, where, _ = cinfo.donating_attrs[f.attr]
                    via = f"donating callable `self.{f.attr}` ({where})"
            for p in donor_pos:
                if p < len(node.args):
                    a = node.args[p]
                    if isinstance(a, ast.Name) and a.id in param_pos:
                        i = param_pos[a.id]
                        s.donates.add(i)
                        s.donates_via.setdefault(i, via)

    # ------------------------------------------------------ resolution
    def resolve_call(self, module: str, qualname: str,
                     call: ast.Call) -> str:
        """FQN of a call's target, or '' when it cannot be proven.
        Handles plain names (locals to the module, imported names),
        dotted module chains, and ``self.method(...)``."""
        f = call.func
        imap = self.imports.get(module, {})
        if isinstance(f, ast.Name):
            caller = self.functions.get(f"{module}.{qualname}")
            if caller is not None and f.id in caller.local_binds:
                return ""       # locally rebound name shadows the
                                # module-level / imported target
            if f.id in imap:
                fqn = imap[f.id]
                return fqn if (fqn in self.functions
                               or fqn in self.donating_globals) else ""
            for cand in (f"{module}.{f.id}",):
                if cand in self.functions or cand in self.donating_globals:
                    return cand
            return ""
        if isinstance(f, ast.Attribute):
            if isinstance(f.value, ast.Name) and f.value.id == "self" \
                    and "." in qualname:
                cls_fqn = f"{module}.{qualname.rsplit('.', 1)[0]}"
                cinfo = self.classes.get(cls_fqn)
                if cinfo and f.attr in cinfo.methods:
                    return cinfo.methods[f.attr]
                return ""
            chain = attr_chain(f)
            if not chain:
                return ""
            root = chain.split(".")[0]
            caller = self.functions.get(f"{module}.{qualname}")
            if caller is not None and root in caller.local_binds:
                return ""       # locally rebound root shadows the chain
            if root in imap:
                chain = imap[root] + chain[len(root):]
            # the bare chain matches import-resolved targets; the
            # module-prefixed one matches same-module `Class.method`
            for cand in (chain, f"{module}.{chain}"):
                if cand in self.functions or cand in self.donating_globals:
                    return cand
        return ""

    def _resolve_calls(self) -> None:
        for s in self.functions.values():
            param_pos = {p: i for i, p in enumerate(s.params)}
            for edge in s.calls:
                edge.callee = self.resolve_call(s.module, s.qualname,
                                                edge.node)
                if not edge.callee:
                    continue
                callee = self.functions.get(edge.callee)
                # a BOUND method call consumes the caller's args from
                # param 1; unbound Class.method(obj, ...) does not
                shift = 1 if (callee is not None and callee.is_method
                              and is_self_call(edge.node)) else 0
                for j, a in enumerate(edge.node.args):
                    if isinstance(a, ast.Name) and a.id in param_pos:
                        edge.param_args[j + shift] = param_pos[a.id]

    def _donation_fixpoint(self) -> None:
        """Propagate donation through the call graph: if f passes its
        param i to g's donated position j, calling f donates i.  The
        corpus call graph is small; a simple iterate-to-stable loop
        (bounded by function count) beats SCC bookkeeping here."""
        for _ in range(len(self.functions) + 1):
            changed = False
            for s in self.functions.values():
                for edge in s.calls:
                    target = self.functions.get(edge.callee)
                    if target is not None:
                        donated = target.donates
                        via_map = target.donates_via
                    elif edge.callee in self.donating_globals:
                        donated = set(self.donating_globals[edge.callee])
                        via_map = {}
                    else:
                        continue
                    for j in donated:
                        i = edge.param_args.get(j)
                        if i is not None and i not in s.donates:
                            s.donates.add(i)
                            tail = via_map.get(j, "")
                            s.donates_via[i] = (
                                f"call to `{edge.callee}`"
                                + (f" ({tail})" if tail else ""))
                            changed = True
            if not changed:
                break

    # --------------------------------------------------------- queries
    def summary_for_call(self, module: str, qualname: str, call: ast.Call,
                         ) -> Tuple[Tuple[int, ...], str]:
        """(donated positions IN CALL-ARG numbering, provenance) for a
        call site — the phase-2 entry point.  Method calls have the
        implicit ``self`` stripped, so positions index ``call.args``."""
        f = call.func
        if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name) \
                and f.value.id == "self" and "." in qualname:
            # donating callable stored on the instance (bound in another
            # method, typically __init__) — donate_argnums index the
            # jitted function's args, i.e. this call's args directly
            cls_fqn = f"{module}.{qualname.rsplit('.', 1)[0]}"
            cinfo = self.classes.get(cls_fqn)
            if cinfo and f.attr in cinfo.donating_attrs:
                pos, where, bound_in = cinfo.donating_attrs[f.attr]
                if qualname.rsplit(".", 1)[-1] in bound_in or not pos:
                    # bound in THIS very method (even if ALSO rebound
                    # elsewhere): the per-scope donation-safety pass
                    # already owns that shape — keeping the source
                    # sets disjoint means one defect is never reported
                    # (and suppressed) twice; an empty provable-
                    # position intersection likewise has nothing to say
                    return (), ""
                return pos, f"donating callable `self.{f.attr}` ({where})"
        fqn = self.resolve_call(module, qualname, call)
        if not fqn:
            return (), ""
        if fqn in self.donating_globals:
            return (self.donating_globals[fqn],
                    f"module-level donating callable `{fqn}`")
        s = self.functions.get(fqn)
        if s is None or not s.donates:
            return (), ""
        shift = 1 if (s.is_method and is_self_call(call)) else 0
        pos = tuple(sorted(p - shift for p in s.donates if p >= shift))
        via = "; ".join(s.donates_via.get(p + shift, "")
                        for p in pos if s.donates_via.get(p + shift))
        return pos, f"`{fqn}` donates it ({via})" if via else f"`{fqn}`"

    def alias_positions_for_call(self, module: str, qualname: str,
                                 call: ast.Call) -> Tuple[int, ...]:
        """Call-arg positions whose buffers the call's RETURN value
        aliases (the ``returns_args`` summaries): ``y = view(x)`` with
        ``def view(a): return a`` makes ``y`` and ``x`` one buffer, so
        donating either later stales the other.  Positions index
        ``call.args`` (implicit ``self`` stripped for method calls)."""
        fqn = self.resolve_call(module, qualname, call)
        if not fqn:
            return ()
        s = self.functions.get(fqn)
        if s is None or not s.returns_args:
            return ()
        shift = 1 if (s.is_method and is_self_call(call)) else 0
        return tuple(sorted(p - shift for p in s.returns_args
                            if p >= shift))

    def sccs(self) -> List[Set[str]]:
        """Strongly-connected components of the call graph (Tarjan,
        iterative).  Mutually-recursive summaries converge in the
        donation fixpoint; the SCCs make that soundness observable (and
        test-pinned) and bound any future finer-than-import-closure
        incremental invalidation."""
        graph: Dict[str, List[str]] = {
            fqn: [e.callee for e in s.calls
                  if e.callee in self.functions]
            for fqn, s in self.functions.items()}
        index: Dict[str, int] = {}
        low: Dict[str, int] = {}
        onstack: Set[str] = set()
        stack: List[str] = []
        out: List[Set[str]] = []
        counter = [0]

        for root in graph:
            if root in index:
                continue
            work = [(root, 0)]
            while work:
                node, pi = work[-1]
                if pi == 0:
                    index[node] = low[node] = counter[0]
                    counter[0] += 1
                    stack.append(node)
                    onstack.add(node)
                recurse = False
                for j in range(pi, len(graph[node])):
                    nxt = graph[node][j]
                    if nxt not in index:
                        work[-1] = (node, j + 1)
                        work.append((nxt, 0))
                        recurse = True
                        break
                    if nxt in onstack:
                        low[node] = min(low[node], index[nxt])
                if recurse:
                    continue
                if low[node] == index[node]:
                    comp: Set[str] = set()
                    while True:
                        w = stack.pop()
                        onstack.discard(w)
                        comp.add(w)
                        if w == node:
                            break
                    out.append(comp)
                work.pop()
                if work:
                    parent = work[-1][0]
                    low[parent] = min(low[parent], low[node])
        return out

    def dependents_of(self, relpaths: Set[str]) -> Set[str]:
        """Every relpath whose findings may depend on summaries from any
        of ``relpaths``: the reverse import closure (a module that
        imports a changed module — transitively — may resolve a call
        into it).  Conservative superset of the call-graph SCC region.
        A DELETED file is absent from this (fresh) index but its
        importers' import edges still point at it — fall back to the
        path-derived module name so the closure reaches them."""
        changed_mods = {self.relpaths.get(r, module_name(r))
                        for r in relpaths}
        # reverse edges: imported -> importers (prefix-aware: importing
        # a package pulls every submodule's file into the closure)
        rev: Dict[str, Set[str]] = {}
        for mod, deps in self.import_graph.items():
            for d in deps:
                rev.setdefault(d, set()).add(mod)
        frontier = set(changed_mods)
        seen = set(changed_mods)
        while frontier:
            nxt: Set[str] = set()
            for mod in frontier:
                # importers of this exact module or of any prefix target
                # that resolves into it (from pkg import name)
                for target, importers in rev.items():
                    if target == mod or target.startswith(mod + ".") \
                            or mod.startswith(target + "."):
                        nxt |= importers - seen
            seen |= nxt
            frontier = nxt
        return {self.modules[m] for m in seen if m in self.modules}


def ensure_index(corpus) -> CorpusIndex:
    """Build (once) and memoize the phase-1 index on the corpus — every
    phase-2 pass and the incremental cache share one instance."""
    idx = getattr(corpus, "_dstpu_index", None)
    if idx is None:
        idx = CorpusIndex.build(corpus)
        corpus._dstpu_index = idx
    return idx
