"""CLI autotuning driver (`dstpu --autotuning=tune ...`).

Reference analog: ``launcher/runner.py:348 run_autotuning`` →
``Autotuner.tune`` (autotuning/autotuner.py:404): generate candidate configs
(ZeRO stage × micro-batch), run the user script once per experiment as a
subprocess, collect each run's reported metric, pick the best config.

Experiment contract: the child runs with
  DSTPU_AUTOTUNING_CONFIG=<path>  — config overrides (json) to merge
  DSTPU_AUTOTUNING_RESULT=<path>  — child writes {"metric": float} here
(the engine writes the result automatically when it sees the env var; user
scripts can also write it directly).  Results land in
``autotuning_results/`` with the winning config in ``autotuning_results/
best_config.json`` (reference autotuner output layout).
"""

from __future__ import annotations

import itertools
import json
import os
import subprocess
import sys
import time
from typing import Dict, List, Optional

from deepspeed_tpu.utils.logging import logger

DEFAULT_MICRO_BATCHES = (1, 2, 4, 8)
DEFAULT_ZERO_STAGES = (0, 1, 2, 3)


def build_experiment_space(micro_batches=DEFAULT_MICRO_BATCHES,
                           zero_stages=DEFAULT_ZERO_STAGES) -> List[Dict]:
    """Candidate config overrides (the reference's tuning-space templates,
    autotuning/config_templates/)."""
    return [{"zero_optimization": {"stage": stage},
             "train_micro_batch_size_per_gpu": mb}
            for stage, mb in itertools.product(zero_stages, micro_batches)]


def run_experiment(cmd: List[str], overrides: Dict, exp_dir: str,
                   timeout_s: float = 600.0) -> Optional[float]:
    """Run one candidate; returns its metric (higher is better) or None."""
    os.makedirs(exp_dir, exist_ok=True)
    cfg_path = os.path.join(exp_dir, "overrides.json")
    result_path = os.path.join(exp_dir, "result.json")
    with open(cfg_path, "w") as f:
        json.dump(overrides, f)
    env = os.environ.copy()
    env["DSTPU_AUTOTUNING_CONFIG"] = cfg_path
    env["DSTPU_AUTOTUNING_RESULT"] = result_path
    try:
        proc = subprocess.run(cmd, env=env, capture_output=True, text=True,
                              timeout=timeout_s)
    except subprocess.TimeoutExpired:
        logger.warning(f"experiment {exp_dir}: timed out")
        return None
    with open(os.path.join(exp_dir, "stdout.log"), "w") as f:
        f.write(proc.stdout)
    with open(os.path.join(exp_dir, "stderr.log"), "w") as f:
        f.write(proc.stderr)
    if proc.returncode != 0:
        logger.warning(f"experiment {exp_dir}: exit {proc.returncode} "
                       f"(often OOM/invalid combo — pruned)")
        return None
    if not os.path.exists(result_path):
        logger.warning(f"experiment {exp_dir}: no result file written")
        return None
    with open(result_path) as f:
        return float(json.load(f)["metric"])


def run_autotuning(args, active_resources, experiments: Optional[List[Dict]] = None,
                   results_dir: str = "autotuning_results",
                   tuner_type: Optional[str] = None,
                   max_parallel: int = 1) -> Optional[str]:
    """Drive the experiment sweep (reference Autotuner.tune:404) through a
    tuner algorithm + the ResourceManager scheduler.

    Experiments run on the LOCAL node through the per-node launcher (all
    local slots), which is how throughput-representative profiling works on
    a TPU host; the caller (launcher/runner.py) then launches the best
    config on the full resource pool when ``--autotuning=run``.

    Returns the path to the winning overrides file, or None if every
    experiment failed.
    """
    from deepspeed_tpu.autotuning.scheduler import ResourceManager
    from deepspeed_tpu.autotuning.tuner import build_tuner
    from deepspeed_tpu.launcher.runner import build_launch_command

    experiments = experiments or build_experiment_space()
    # route through the per-node launcher so experiments see the same rank
    # env/world as a real single-node run
    local_host = next(iter(active_resources))
    local = {local_host: active_resources[local_host]}
    cmd = build_launch_command(args, local, node_rank=0, host=local_host)
    os.makedirs(results_dir, exist_ok=True)
    records = []

    def run_fn(overrides, exp_id):
        exp_dir = os.path.join(results_dir, f"exp_{exp_id}")
        t0 = time.time()
        metric = run_experiment(cmd, overrides, exp_dir)
        records.append({"exp": exp_id, "overrides": overrides,
                        "metric": metric,
                        "wall_s": round(time.time() - t0, 2)})
        logger.info(f"autotuning exp {exp_id}: {overrides} -> {metric}")
        return metric

    tuner = build_tuner(
        tuner_type or getattr(args, "autotuning_tuner", "gridsearch"),
        experiments)
    best_cfg, best_metric = ResourceManager(
        run_fn, max_parallel=max_parallel).schedule(tuner)
    with open(os.path.join(results_dir, "summary.json"), "w") as f:
        json.dump(records, f, indent=2)
    if best_cfg is None:
        logger.error("autotuning: no experiment produced a metric")
        return None
    with open(os.path.join(results_dir, "best_config.json"), "w") as f:
        json.dump({"metric": best_metric, "config": best_cfg}, f, indent=2)
    best_path = os.path.join(results_dir, "best_overrides.json")
    with open(best_path, "w") as f:
        json.dump(best_cfg, f)
    logger.info(f"autotuning: best {best_metric} with {best_cfg}")
    return best_path
