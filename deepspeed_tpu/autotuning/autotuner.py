"""Autotuner — analog of reference ``deepspeed/autotuning/autotuner.py``
(Autotuner:42, tune:404; 2718 LoC with a launcher-driven experiment
scheduler, XGBoost cost model and throwaway profile runs).

TPU-native redesign: the reference must *launch jobs* to learn each config's
memory/throughput because CUDA allocators only tell you at runtime. XLA
tells you at COMPILE time: ``jit(step).lower(...).compile()`` yields
``memory_analysis()`` (exact buffer plan) and ``cost_analysis()`` (flops /
bytes). The search over (ZeRO stage × micro-batch) therefore runs in-process
in seconds — compile, read the plan, roofline-score, pick:

    score = tokens_per_step / max(flops/peak_flops, bytes/hbm_bw)

No experiment scheduler, no cost-model training, no exit-and-relaunch
(reference engine.py:1687 kills the run after profiling). Same knobs
searched: ZeRO stage (reference tune_space z0-z3), micro-batch size.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from deepspeed_tpu.runtime.config_utils import DeepSpeedConfigModel
from deepspeed_tpu.utils.logging import log_dist, logger

# v4/v5-class defaults; overridable per call
DEFAULT_PEAK_FLOPS = 275e12     # bf16 matmul per chip
DEFAULT_HBM_BW = 1.2e12         # bytes/sec
DEFAULT_HBM_BYTES = 32e9        # per-chip HBM


class AutotuningConfig(DeepSpeedConfigModel):
    """'autotuning' config section — field parity with reference
    autotuning/config.py (enabled, metric, start_step, fast mode)."""

    enabled: bool = False
    fast: bool = True
    metric: str = "throughput"
    start_step: int = 3
    end_step: int = 5
    micro_batch_sizes: Optional[List[int]] = None
    zero_stages: Optional[List[int]] = None
    max_train_batch_size: Optional[int] = None


@dataclasses.dataclass
class TrialResult:
    zero_stage: int
    micro_batch: int
    peak_bytes: float
    flops: float
    bytes_accessed: float
    est_step_time: float
    tokens_per_sec: float
    fits: bool
    error: Optional[str] = None


class Autotuner:
    """Compile-time config search (reference Autotuner:42)."""

    def __init__(self, model, base_config: Dict, *, seq_len: int,
                 vocab_size: int, hbm_bytes: float = DEFAULT_HBM_BYTES,
                 peak_flops: float = DEFAULT_PEAK_FLOPS,
                 hbm_bw: float = DEFAULT_HBM_BW):
        self.model = model
        self.base_config = dict(base_config)
        self.seq_len = seq_len
        self.vocab_size = vocab_size
        self.hbm_bytes = hbm_bytes
        self.peak_flops = peak_flops
        self.hbm_bw = hbm_bw
        self.results: List[TrialResult] = []

    # ------------------------------------------------------------------ trial
    def _trial(self, zero_stage: int, micro_batch: int) -> TrialResult:
        import jax

        import deepspeed_tpu
        from deepspeed_tpu.utils import groups

        groups.reset()
        cfg = dict(self.base_config)
        dp = None
        try:
            from deepspeed_tpu.parallel.topology import build_topology

            topo = build_topology()
            dp = topo.data_parallel_size
            cfg.update({
                "train_batch_size": micro_batch * dp,
                "train_micro_batch_size_per_gpu": micro_batch,
                "gradient_accumulation_steps": 1,
                "zero_optimization": {"stage": zero_stage},
                "steps_per_print": 0,
            })
            engine, *_ = deepspeed_tpu.initialize(model=self.model, config=cfg,
                                                  topology=topo)
            step_fn = engine._build_train_step()
            batch = {
                "input_ids": jax.ShapeDtypeStruct(
                    (1, micro_batch * dp, self.seq_len), np.int32),
                "labels": jax.ShapeDtypeStruct(
                    (1, micro_batch * dp, self.seq_len), np.int32),
            }
            lr = jax.ShapeDtypeStruct((), np.float32)
            rng = jax.eval_shape(lambda: jax.random.PRNGKey(0))
            compiled = step_fn.lower(engine.state, batch, lr, rng).compile()
            peak, flops, bytes_ = self._read_compiled(compiled)
            per_chip_peak = peak / max(topo.world_size, 1)
            est = max(flops / self.peak_flops / max(topo.world_size, 1),
                      bytes_ / self.hbm_bw / max(topo.world_size, 1))
            est = max(est, 1e-9)
            tokens = micro_batch * dp * self.seq_len
            return TrialResult(zero_stage, micro_batch, per_chip_peak, flops,
                               bytes_, est, tokens / est,
                               fits=per_chip_peak <= self.hbm_bytes)
        except Exception as e:  # OOM at compile, bad divisibility, ...
            return TrialResult(zero_stage, micro_batch, float("inf"), 0, 0,
                               float("inf"), 0.0, fits=False, error=str(e)[:200])

    @staticmethod
    def _read_compiled(compiled) -> Tuple[float, float, float]:
        peak = flops = bytes_ = 0.0
        try:
            ma = compiled.memory_analysis()
            if ma is not None:
                peak = float(getattr(ma, "temp_size_in_bytes", 0) +
                             getattr(ma, "argument_size_in_bytes", 0) +
                             getattr(ma, "output_size_in_bytes", 0) -
                             getattr(ma, "alias_size_in_bytes", 0))
        except Exception:
            pass
        try:
            ca = compiled.cost_analysis()
            if isinstance(ca, list):
                ca = ca[0]
            flops = float(ca.get("flops", 0.0))
            bytes_ = float(ca.get("bytes accessed", 0.0))
        except Exception:
            pass
        return peak, flops, bytes_

    # ------------------------------------------------------------------- tune
    def tune(self, micro_batch_candidates: Sequence[int] = (1, 2, 4, 8),
             zero_stages: Sequence[int] = (0, 1, 2, 3),
             fast: bool = False) -> Dict[str, Any]:
        """Search → best config dict (reference tune:404 returns the best
        exp dir; here the resolved DS config section is returned directly)."""
        self.results = []
        best: Optional[TrialResult] = None
        for stage in zero_stages:
            stage_ok = False
            for mb in micro_batch_candidates:
                r = self._trial(stage, mb)
                self.results.append(r)
                log_dist(
                    f"autotune z{r.zero_stage} mb{r.micro_batch}: "
                    f"peak={r.peak_bytes/1e9:.2f}GB fits={r.fits} "
                    f"est_tok/s={r.tokens_per_sec:.0f}"
                    + (f" err={r.error}" if r.error else ""), ranks=[0])
                if r.fits:
                    stage_ok = True
                    if best is None or r.tokens_per_sec > best.tokens_per_sec:
                        best = r
                elif r.error is None and stage_ok and fast:
                    break  # monotone memory growth: larger mb won't fit either
        if best is None:
            raise RuntimeError(
                "autotuning found no (zero_stage, micro_batch) that fits; "
                f"tried stages {list(zero_stages)} x mb {list(micro_batch_candidates)}")
        return {
            "zero_optimization": {"stage": best.zero_stage},
            "train_micro_batch_size_per_gpu": best.micro_batch,
            "estimated_tokens_per_sec": best.tokens_per_sec,
            "peak_bytes_per_chip": best.peak_bytes,
        }
