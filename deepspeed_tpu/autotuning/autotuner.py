"""Autotuner — analog of reference ``deepspeed/autotuning/autotuner.py``
(Autotuner:42, tune:404; 2718 LoC with a launcher-driven experiment
scheduler, XGBoost cost model and throwaway profile runs).

TPU-native redesign: the reference must *launch jobs* to learn each config's
memory/throughput because CUDA allocators only tell you at runtime. XLA
tells you at COMPILE time: ``jit(step).lower(...).compile()`` yields
``memory_analysis()`` (exact buffer plan) and ``cost_analysis()`` (flops /
bytes). The search over (ZeRO stage × micro-batch) therefore runs in-process
in seconds — compile, read the plan, roofline-score, pick:

    score = tokens_per_step / max(flops/peak_flops, bytes/hbm_bw)

No experiment scheduler, no cost-model training, no exit-and-relaunch
(reference engine.py:1687 kills the run after profiling). Same knobs
searched: ZeRO stage (reference tune_space z0-z3), micro-batch size.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from deepspeed_tpu.runtime.config_utils import DeepSpeedConfigModel
from deepspeed_tpu.utils.logging import log_dist, logger

# v4/v5-class defaults; overridable per call
DEFAULT_PEAK_FLOPS = 275e12     # bf16 matmul per chip
DEFAULT_HBM_BW = 1.2e12         # bytes/sec
DEFAULT_HBM_BYTES = 32e9        # per-chip HBM
DEFAULT_HOST_BW = 5e10          # host<->device for offloaded optimizer state


class AutotuningConfig(DeepSpeedConfigModel):
    """'autotuning' config section — field parity with reference
    autotuning/config.py (enabled, metric, start_step, fast mode)."""

    enabled: bool = False
    fast: bool = True
    metric: str = "throughput"
    start_step: int = 3
    end_step: int = 5
    micro_batch_sizes: Optional[List[int]] = None
    zero_stages: Optional[List[int]] = None
    max_train_batch_size: Optional[int] = None


@dataclasses.dataclass
class TrialResult:
    zero_stage: int
    micro_batch: int
    peak_bytes: float
    flops: float
    bytes_accessed: float
    est_step_time: float
    tokens_per_sec: float
    fits: bool
    error: Optional[str] = None
    gas: int = 1
    offload: bool = False
    remat: Optional[str] = None
    pruned: bool = False  # rejected by the model-info pass, never compiled


class Autotuner:
    """Compile-time config search (reference Autotuner:42)."""

    def __init__(self, model, base_config: Dict, *, seq_len: int,
                 vocab_size: int, hbm_bytes: float = DEFAULT_HBM_BYTES,
                 peak_flops: float = DEFAULT_PEAK_FLOPS,
                 hbm_bw: float = DEFAULT_HBM_BW,
                 host_bw: float = DEFAULT_HOST_BW):
        self.model = model
        self.base_config = dict(base_config)
        self.seq_len = seq_len
        self.vocab_size = vocab_size
        self.hbm_bytes = hbm_bytes
        self.peak_flops = peak_flops
        self.hbm_bw = hbm_bw
        self.host_bw = host_bw
        self.results: List[TrialResult] = []
        self._model_info: Optional[Dict[str, float]] = None

    # ------------------------------------------------------- model-info pass
    def model_info(self) -> Dict[str, float]:
        """Profile-run analog (reference autotuner.py:664 model_info /
        ``--model_info_path``): parameter count + flops/token, computed from
        shapes — no throwaway training job needed. Cached."""
        if getattr(self, "_model_info", None) is not None:
            return self._model_info
        import jax

        shapes = jax.eval_shape(self.model.init, jax.random.PRNGKey(0))
        n_params = sum(int(np.prod(l.shape))
                       for l in jax.tree_util.tree_leaves(shapes))
        mcfg = getattr(self.model, "config", None)
        hidden = getattr(mcfg, "hidden_size", 0)
        layers = getattr(mcfg, "num_layers", 0)
        attn = 12 * layers * hidden * self.seq_len if hidden else 0
        self._model_info = {
            "num_params": float(n_params),
            "flops_per_token": 6.0 * n_params + attn,
            "hidden_size": float(hidden),
            "num_layers": float(layers),
        }
        return self._model_info

    def _estimate_device_bytes(self, zero_stage: int, micro_batch: int,
                               offload: bool, remat: Optional[str],
                               dp: int) -> float:
        """Analytic lower bound on per-chip HBM for (stage, mb, offload,
        remat) — used to PRUNE infeasible points before paying a compile
        (the reference prunes with its model-info profiling run the same
        way, autotuner.py:664 → _get_min_gpus)."""
        info = self.model_info()
        n = info["num_params"]
        shard = dp if zero_stage >= 1 else 1
        compute_shard = dp if zero_stage >= 3 else 1
        # fp32 master + 2 Adam moments (sharded from stage 1, host if
        # offload) — offload only credits HBM at the stages the runtime
        # exercises it (>= 1; _trial prunes stage-0 offload candidates)
        opt_bytes = 0.0 if (offload and zero_stage >= 1) else 12.0 * n / shard
        param_bytes = 2.0 * n / compute_shard          # bf16 compute copy
        grad_bytes = 4.0 * n / (dp if zero_stage >= 2 else 1)
        act = 0.0
        if info["hidden_size"]:
            h, L = info["hidden_size"], info["num_layers"]
            # bf16 residual-stream activations the backward must see; saved
            # tensors per layer: ~14x the [mb, seq, hidden] stream without
            # remat (qkv, probs excluded — attention T^2 dominates separately),
            # ~2x with a remat policy
            per_layer = (2.0 if remat else 14.0) * micro_batch * self.seq_len * h * 2
            act = per_layer * L
            if not remat and getattr(self.model, "attn_impl", "dense") == "dense":
                # T x T attention weights saved for backward, all layers
                # (flash/ring/ulysses never materialize them)
                act += L * micro_batch * self.seq_len ** 2 * \
                    getattr(getattr(self.model, "config", None), "num_heads", 1) * 2
        return opt_bytes + param_bytes + grad_bytes + act

    def _apply_remat(self, remat: Optional[str]):
        """Rebuild the model with the candidate remat policy when its
        constructor supports it; None return = the knob cannot be expressed
        for this model (the caller must SKIP the point, not silently compile
        a program that doesn't match the candidate)."""
        if not hasattr(self.model, "remat"):
            return self.model if remat is None else None
        if bool(remat) == bool(self.model.remat) and \
                remat == getattr(self.model, "remat_policy", None):
            return self.model
        try:
            return type(self.model)(
                self.model.config,
                compute_dtype=getattr(self.model, "compute_dtype", None),
                remat=bool(remat), remat_policy=remat,
                attn_impl=getattr(self.model, "attn_impl", "dense"))
        except TypeError:
            return None

    # ------------------------------------------------------------------ trial
    def _trial(self, zero_stage: int, micro_batch: int, gas: int = 1,
               offload: bool = False,
               remat: Optional[str] = None) -> TrialResult:
        import jax

        import deepspeed_tpu
        from deepspeed_tpu.utils import groups

        groups.reset()
        cfg = dict(self.base_config)
        try:
            from deepspeed_tpu.parallel.topology import build_topology

            topo = build_topology()
            dp = topo.data_parallel_size

            if offload and zero_stage < 1:
                # user-supplied spaces can pair offload with stage 0; the
                # sharded host-master path is only exercised from stage 1 —
                # reject rather than estimate a config the runtime may not
                # honor (ADVICE r2)
                return TrialResult(
                    zero_stage, micro_batch, 0, 0, 0, float("inf"), 0.0,
                    fits=False, gas=gas, offload=offload, remat=remat,
                    pruned=True,
                    error="pruned: optimizer offload requires ZeRO stage >= 1")

            est_bytes = self._estimate_device_bytes(
                zero_stage, micro_batch, offload, remat, dp)
            if est_bytes > self.hbm_bytes:
                return TrialResult(
                    zero_stage, micro_batch, est_bytes, 0, 0, float("inf"),
                    0.0, fits=False, gas=gas, offload=offload, remat=remat,
                    pruned=True,
                    error=f"pruned: analytic estimate {est_bytes/1e9:.1f}GB "
                          f"> HBM {self.hbm_bytes/1e9:.1f}GB")

            model = self._apply_remat(remat)
            if model is None:
                return TrialResult(
                    zero_stage, micro_batch, 0, 0, 0, float("inf"), 0.0,
                    fits=False, gas=gas, offload=offload, remat=remat,
                    pruned=True,
                    error="pruned: model cannot express this remat policy")
            zero_cfg: Dict[str, Any] = {"stage": zero_stage}
            if offload:
                zero_cfg["offload_optimizer"] = {"device": "cpu"}
            cfg.update({
                "train_batch_size": micro_batch * gas * dp,
                "train_micro_batch_size_per_gpu": micro_batch,
                "gradient_accumulation_steps": gas,
                "zero_optimization": zero_cfg,
                "steps_per_print": 0,
            })
            engine, *_ = deepspeed_tpu.initialize(model=model, config=cfg,
                                                  topology=topo)
            step_fn = engine._build_train_step()
            batch = {
                "input_ids": jax.ShapeDtypeStruct(
                    (gas, micro_batch * dp, self.seq_len), np.int32),
                "labels": jax.ShapeDtypeStruct(
                    (gas, micro_batch * dp, self.seq_len), np.int32),
            }
            lr = jax.ShapeDtypeStruct((), np.float32)
            rng = jax.eval_shape(lambda: jax.random.PRNGKey(0))
            compiled = step_fn.lower(engine.state, batch, lr, rng).compile()
            peak, flops, bytes_ = self._read_compiled(compiled)
            per_chip_peak = peak / max(topo.world_size, 1)
            est = max(flops / self.peak_flops / max(topo.world_size, 1),
                      bytes_ / self.hbm_bw / max(topo.world_size, 1))
            if offload:
                # optimizer shard round-trips the host each step
                est += 12.0 * self.model_info()["num_params"] / max(
                    topo.world_size, 1) / self.host_bw
            est = max(est, 1e-9)
            tokens = micro_batch * gas * dp * self.seq_len
            return TrialResult(zero_stage, micro_batch, per_chip_peak, flops,
                               bytes_, est, tokens / est,
                               fits=per_chip_peak <= self.hbm_bytes,
                               gas=gas, offload=offload, remat=remat)
        except Exception as e:  # OOM at compile, bad divisibility, ...
            return TrialResult(zero_stage, micro_batch, float("inf"), 0, 0,
                               float("inf"), 0.0, fits=False, gas=gas,
                               offload=offload, remat=remat,
                               error=str(e)[:200])

    @staticmethod
    def _read_compiled(compiled) -> Tuple[float, float, float]:
        peak = flops = bytes_ = 0.0
        try:
            ma = compiled.memory_analysis()
            if ma is not None:
                peak = float(getattr(ma, "temp_size_in_bytes", 0) +
                             getattr(ma, "argument_size_in_bytes", 0) +
                             getattr(ma, "output_size_in_bytes", 0) -
                             getattr(ma, "alias_size_in_bytes", 0))
        except Exception:
            pass
        try:
            ca = compiled.cost_analysis()
            if isinstance(ca, list):
                ca = ca[0]
            flops = float(ca.get("flops", 0.0))
            bytes_ = float(ca.get("bytes accessed", 0.0))
        except Exception:
            pass
        return peak, flops, bytes_

    # ------------------------------------------------------------------- tune
    def tune(self, micro_batch_candidates: Sequence[int] = (1, 2, 4, 8),
             zero_stages: Sequence[int] = (0, 1, 2, 3),
             fast: bool = False,
             space: Optional[Dict[str, Sequence]] = None) -> Dict[str, Any]:
        """Search → best config dict (reference tune:404 returns the best
        exp dir; here the resolved DS config section is returned directly).

        ``space`` widens the per-stage search beyond (stage x micro_batch)
        with the template dimensions (config_templates.py — the reference's
        config_templates/ analog): gas, offload on/off, remat policy.
        Omitted → the legacy 2-D sweep. Analytically infeasible points are
        pruned by the model-info pass without compiling."""
        from deepspeed_tpu.autotuning.config_templates import enumerate_space

        self.results = []
        best: Optional[TrialResult] = None
        for stage in zero_stages:
            if space is not None:
                overrides = dict(space)
                overrides.setdefault("micro_batch", list(micro_batch_candidates))
                candidates = enumerate_space(stage, overrides)
            else:
                candidates = [{"micro_batch": mb, "gas": 1, "offload": False,
                               "remat": None} for mb in micro_batch_candidates]
            stage_ok = False
            for cand in candidates:
                r = self._trial(stage, cand["micro_batch"], cand.get("gas", 1),
                                cand.get("offload", False), cand.get("remat"))
                self.results.append(r)
                log_dist(
                    f"autotune z{r.zero_stage} mb{r.micro_batch} gas{r.gas}"
                    f"{' offload' if r.offload else ''}"
                    f"{f' remat={r.remat}' if r.remat else ''}: "
                    f"peak={r.peak_bytes/1e9:.2f}GB fits={r.fits}"
                    f"{' PRUNED' if r.pruned else ''} "
                    f"est_tok/s={r.tokens_per_sec:.0f}"
                    + (f" err={r.error}" if r.error else ""), ranks=[0])
                if r.fits:
                    stage_ok = True
                    if best is None or r.tokens_per_sec > best.tokens_per_sec:
                        best = r
                elif r.error is None and stage_ok and fast and space is None:
                    # legacy 1-D sweep only: memory grows monotonically in mb,
                    # so larger mb can't fit either. The multi-dim template
                    # walk is NOT monotone in iteration order — never break.
                    break
        if best is None:
            raise RuntimeError(
                "autotuning found no (zero_stage, micro_batch) that fits; "
                f"tried stages {list(zero_stages)} x mb {list(micro_batch_candidates)}")
        out = {
            "zero_optimization": {"stage": best.zero_stage},
            "train_micro_batch_size_per_gpu": best.micro_batch,
            "gradient_accumulation_steps": best.gas,
            "estimated_tokens_per_sec": best.tokens_per_sec,
            "peak_bytes_per_chip": best.peak_bytes,
        }
        if best.offload:
            out["zero_optimization"]["offload_optimizer"] = {"device": "cpu"}
        if best.remat is not None:
            out["activation_checkpointing"] = {"policy": best.remat}
        return out
