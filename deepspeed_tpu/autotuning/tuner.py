"""Tuner algorithms + cost model.

Reference analogs: ``RandomTuner``/``GridSearchTuner``
(autotuning/tuner/index_based_tuner.py:11,27) and ``ModelBasedTuner`` with
``XGBoostCostModel`` (tuner/model_based_tuner.py:19, tuner/cost_model.py:14).
The model-based tuner here uses a ridge-regression cost model over one-hot
encoded config features — numpy-only (no xgboost dependency) with the same
role: rank untried configs by predicted throughput and evaluate the most
promising first (epsilon-greedy exploration).
"""

from __future__ import annotations

import random
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np


def _flatten_config(cfg: Dict, prefix: str = "") -> Dict[str, Any]:
    out = {}
    for k, v in cfg.items():
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            out.update(_flatten_config(v, key + "."))
        else:
            out[key] = v
    return out


class FeatureEncoder:
    """One-hot encode experiment configs over the observed value vocabulary
    (the reference feeds similar flattened features to xgboost)."""

    def __init__(self, experiments: Sequence[Dict]):
        flat = [_flatten_config(e) for e in experiments]
        self.keys = sorted({k for f in flat for k in f})
        self.vocab: Dict[str, List] = {
            k: sorted({str(f.get(k)) for f in flat}) for k in self.keys}

    def encode(self, cfg: Dict) -> np.ndarray:
        flat = _flatten_config(cfg)
        vec = []
        for k in self.keys:
            onehot = [0.0] * len(self.vocab[k])
            val = str(flat.get(k))
            if val in self.vocab[k]:
                onehot[self.vocab[k].index(val)] = 1.0
            vec.extend(onehot)
        return np.asarray(vec, np.float32)


class CostModel:
    """Ridge regression metric predictor (reference XGBoostCostModel.fit/
    predict surface)."""

    def __init__(self, l2: float = 1e-2):
        self.l2 = l2
        self._w: Optional[np.ndarray] = None

    def fit(self, feats: np.ndarray, metrics: np.ndarray) -> None:
        x = np.concatenate([feats, np.ones((len(feats), 1), np.float32)], 1)
        a = x.T @ x + self.l2 * np.eye(x.shape[1], dtype=np.float32)
        self._w = np.linalg.solve(a, x.T @ metrics.astype(np.float32))

    def predict(self, feats: np.ndarray) -> np.ndarray:
        assert self._w is not None, "fit() first"
        x = np.concatenate([feats, np.ones((len(feats), 1), np.float32)], 1)
        return x @ self._w


class BaseTuner:
    """Iteration protocol shared by all tuners (reference BaseTuner):
    ``next_batch(n)`` proposes experiments, ``update(exp, metric)`` records
    results (None = failed/pruned), ``best`` tracks the winner."""

    def __init__(self, experiments: Sequence[Dict]):
        self.all_experiments = list(experiments)
        self._untried = list(range(len(self.all_experiments)))
        self.results: List[Tuple[Dict, Optional[float]]] = []
        self.best_metric: Optional[float] = None
        self.best_config: Optional[Dict] = None

    def has_next(self) -> bool:
        return bool(self._untried)

    def next_batch(self, n: int = 1) -> List[Dict]:
        idxs = self._select(min(n, len(self._untried)))
        for i in idxs:
            self._untried.remove(i)
        return [self.all_experiments[i] for i in idxs]

    def _select(self, n: int) -> List[int]:
        raise NotImplementedError

    def update(self, experiment: Dict, metric: Optional[float]) -> None:
        self.results.append((experiment, metric))
        if metric is not None and (self.best_metric is None or
                                   metric > self.best_metric):
            self.best_metric, self.best_config = metric, experiment


class GridSearchTuner(BaseTuner):
    """In-order exhaustive sweep (reference GridSearchTuner:27)."""

    def _select(self, n: int) -> List[int]:
        return self._untried[:n]


class RandomTuner(BaseTuner):
    """Uniform without replacement (reference RandomTuner:11)."""

    def __init__(self, experiments: Sequence[Dict], seed: int = 0):
        super().__init__(experiments)
        self._rng = random.Random(seed)

    def _select(self, n: int) -> List[int]:
        return self._rng.sample(self._untried, n)


class ModelBasedTuner(BaseTuner):
    """Cost-model guided search (reference ModelBasedTuner:19): after
    ``warmup`` random evaluations, fit the cost model on observed results
    and propose the untried configs with the highest predicted metric
    (epsilon-greedy random exploration keeps the model honest)."""

    def __init__(self, experiments: Sequence[Dict], seed: int = 0,
                 warmup: int = 3, epsilon: float = 0.2):
        super().__init__(experiments)
        self.encoder = FeatureEncoder(experiments)
        self.model = CostModel()
        self.warmup = warmup
        self.epsilon = epsilon
        self._rng = random.Random(seed)

    def _observed(self):
        pairs = [(self.encoder.encode(e), m) for e, m in self.results
                 if m is not None]
        if not pairs:
            return None, None
        feats = np.stack([f for f, _ in pairs])
        metrics = np.asarray([m for _, m in pairs], np.float32)
        return feats, metrics

    def _select(self, n: int) -> List[int]:
        feats, metrics = self._observed()
        if feats is None or len(feats) < self.warmup:
            return self._rng.sample(self._untried, n)
        self.model.fit(feats, metrics)
        preds = self.model.predict(np.stack(
            [self.encoder.encode(self.all_experiments[i])
             for i in self._untried]))
        ranked = [i for _, i in sorted(zip(-preds, self._untried))]
        out = []
        for _ in range(n):
            if self._rng.random() < self.epsilon and len(ranked) > 1:
                pick = self._rng.choice(ranked)
            else:
                pick = ranked[0]
            ranked.remove(pick)
            out.append(pick)
        return out


TUNER_REGISTRY = {
    "gridsearch": GridSearchTuner,
    "random": RandomTuner,
    "model_based": ModelBasedTuner,
}


def build_tuner(name: str, experiments: Sequence[Dict], **kw) -> BaseTuner:
    key = name.lower().replace("-", "_")
    if key not in TUNER_REGISTRY:
        raise ValueError(f"unknown tuner '{name}'; options: "
                         f"{sorted(TUNER_REGISTRY)}")
    return TUNER_REGISTRY[key](experiments, **kw)
