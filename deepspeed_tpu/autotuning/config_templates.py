"""Per-ZeRO-stage tuning-space templates — analog of the reference's
``autotuning/config_templates/template_zero{0..3}.json`` (consumed by
``Autotuner._generate_experiments``, reference autotuning/autotuner.py:664).

The reference templates sweep CUDA-side knobs (reduce_bucket_size,
allgather_bucket_size, overlap_comm, ...) that XLA makes moot — the compiler
schedules collectives. The knobs that matter on TPU are the ones that change
the compiled program: micro-batch, gradient accumulation, host offload of
the optimizer shard, and the remat (activation-checkpoint) policy.
"""

from __future__ import annotations

import itertools
from typing import Any, Dict, List, Optional

# remat candidates: None = save-all (fastest when it fits), "dots_no_batch" =
# recompute matmul outputs (the usual HBM/compute sweet spot), "nothing" =
# save only layer boundaries (tightest memory)
DEFAULT_TUNING_SPACES: Dict[int, Dict[str, List[Any]]] = {
    0: {"micro_batch": [1, 2, 4, 8, 16], "gas": [1, 2, 4],
        "offload": [False], "remat": [None, "dots_no_batch"]},
    1: {"micro_batch": [1, 2, 4, 8, 16], "gas": [1, 2, 4],
        "offload": [False], "remat": [None, "dots_no_batch"]},
    2: {"micro_batch": [1, 2, 4, 8, 16], "gas": [1, 2, 4],
        "offload": [False, True], "remat": [None, "dots_no_batch", "nothing"]},
    3: {"micro_batch": [1, 2, 4, 8, 16], "gas": [1, 2, 4],
        "offload": [False, True], "remat": [None, "dots_no_batch", "nothing"]},
}


def tuning_space_for_stage(stage: int,
                           overrides: Optional[Dict[str, List[Any]]] = None
                           ) -> Dict[str, List[Any]]:
    space = {k: list(v) for k, v in DEFAULT_TUNING_SPACES[stage].items()}
    if overrides:
        space.update({k: list(v) for k, v in overrides.items()})
    return space


def enumerate_space(stage: int,
                    overrides: Optional[Dict[str, List[Any]]] = None
                    ) -> List[Dict[str, Any]]:
    """Cartesian candidate list for one stage, reference-template style."""
    space = tuning_space_for_stage(stage, overrides)
    keys = sorted(space)
    return [dict(zip(keys, combo))
            for combo in itertools.product(*(space[k] for k in keys))]
