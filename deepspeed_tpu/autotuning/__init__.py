from deepspeed_tpu.autotuning.autotuner import Autotuner, AutotuningConfig

__all__ = ["Autotuner", "AutotuningConfig"]
