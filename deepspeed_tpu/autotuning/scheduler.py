"""Experiment scheduler (reference ``autotuning/scheduler.py``
ResourceManager): run tuner-proposed experiments over a bounded pool of
parallel worker slots, feeding results back to the tuner until the space or
the experiment budget is exhausted.
"""

from __future__ import annotations

import concurrent.futures as cf
from typing import Callable, Dict, List, Optional, Tuple

from deepspeed_tpu.autotuning.tuner import BaseTuner
from deepspeed_tpu.utils.logging import logger


class ResourceManager:
    def __init__(self, run_fn: Callable[[Dict, int], Optional[float]],
                 max_parallel: int = 1, max_experiments: int = 0):
        """``run_fn(experiment_config, exp_id) -> metric or None``."""
        self.run_fn = run_fn
        self.max_parallel = max(1, max_parallel)
        self.max_experiments = max_experiments  # 0 = unlimited

    def schedule(self, tuner: BaseTuner) -> Tuple[Optional[Dict], Optional[float]]:
        """Drive the tuner to completion; returns (best_config, best_metric).

        Slot-refill scheduling (reference ResourceManager): each completed
        experiment immediately frees its slot for the tuner's next proposal —
        no batch barrier, so one slow experiment never idles the pool."""
        launched = 0
        budget = self.max_experiments or len(tuner.all_experiments)
        with cf.ThreadPoolExecutor(max_workers=self.max_parallel) as pool:
            inflight: Dict = {}

            def refill():
                nonlocal launched
                while len(inflight) < self.max_parallel and \
                        launched < budget and tuner.has_next():
                    for exp in tuner.next_batch(1):
                        inflight[pool.submit(self.run_fn, exp, launched)] = exp
                        launched += 1

            refill()
            while inflight:
                done, _ = cf.wait(inflight, return_when=cf.FIRST_COMPLETED)
                for fut in done:
                    exp = inflight.pop(fut)
                    try:
                        metric = fut.result()
                    except Exception as e:
                        logger.warning(f"experiment {exp} crashed: {e}")
                        metric = None
                    tuner.update(exp, metric)
                refill()
        return tuner.best_config, tuner.best_metric
