"""Crash-safe flight recorder: a bounded pre-incident window that dumps
one self-contained postmortem JSON on trigger (ISSUE 13).

When a chaos run dies today, the JSONL stream is all history and no
focus: the on-call greps thousands of records to reconstruct the 30
seconds that mattered. The flight recorder is the aircraft-style
alternative — bounded rings of the most recent spans, events, metric
snapshots and SLO alert evaluations, continuously teed off the streams
the telemetry stack already writes, so that at the moment something
breaks a single :meth:`dump` freezes the pre-incident window into one
artifact ``scripts/telemetry_report.py``'s postmortem section renders.

Triggers wired by this PR: fabric replica crash/quarantine, router
overload shed bursts, training sentinel anomalies, and SLO
page-severity alerts. Each trigger writes
``<dump_dir>/flight_<NNN>_<reason>.json`` (deterministic numbering —
no wall-clock in the name, so FakeClock chaos runs produce stable
artifact paths) and fires a ``telemetry/flight_dump`` event.

The recorder observes records through :meth:`tee`, a sink wrapper that
records-then-forwards — arming it changes no write sites and costs one
deque append per record. Ring evictions are EXPECTED (that is what
"bounded pre-incident window" means) and counted separately from
upstream drops: the dump's ``complete`` flag reports whether the
telemetry pipeline itself dropped anything (``telemetry/spans_dropped``
/ ``telemetry/events_dropped`` — ISSUE 13 satellite), so a postmortem
can state whether its own record is trustworthy.
"""

from __future__ import annotations

import json
import os
import threading
from collections import deque
from typing import Dict, List, Optional

from deepspeed_tpu.telemetry.registry import MetricsRegistry, get_registry


class _TeeSink:
    """Records every write into the recorder's rings, then forwards to
    the wrapped sink (which may be None — recorder-only capture)."""

    def __init__(self, recorder: "FlightRecorder", inner=None):
        self.recorder = recorder
        self.inner = inner

    def write(self, record: dict) -> None:
        try:
            self.recorder.observe(record)
        except Exception:   # the recorder must never take down the job
            pass
        if self.inner is not None:
            self.inner.write(record)

    def flush(self) -> None:
        if self.inner is not None:
            self.inner.flush()

    def close(self) -> None:
        if self.inner is not None:
            self.inner.close()

    def __getattr__(self, name):
        # sink-protocol extras (scalar(), records_written...) pass through
        if self.inner is None:
            raise AttributeError(name)
        return getattr(self.inner, name)


class FlightRecorder:
    """Bounded rings of recent telemetry + one-call postmortem dumps.

    Parameters
    ----------
    dump_dir: where :meth:`trigger` writes its JSON artifacts; None
        records triggers (ring + counter + event) without writing —
        :meth:`dump` with an explicit path still works.
    max_spans / max_events / max_snapshots / max_alerts: ring bounds.
        Evictions are counted in ``ring_evicted`` (expected, not data
        loss).
    registry: the registry whose snapshot rides in every dump and whose
        ``telemetry/flight_dumps`` counter/event fire per trigger.
        Defaults to the process-global registry.
    trigger_cooldown: minimum number of OBSERVED records between two
        auto-triggers of the same reason — a crash loop must not write
        a thousand identical dumps. 0 disables the gate.
    """

    def __init__(self, *, dump_dir: Optional[str] = None,
                 max_spans: int = 4096, max_events: int = 2048,
                 max_snapshots: int = 32, max_alerts: int = 256,
                 registry: Optional[MetricsRegistry] = None,
                 trigger_cooldown: int = 0):
        self.dump_dir = dump_dir
        self.registry = registry if registry is not None else get_registry()
        self._lock = threading.Lock()
        self.spans: deque = deque(maxlen=max_spans)
        self.events: deque = deque(maxlen=max_events)
        self.snapshots: deque = deque(maxlen=max_snapshots)
        self.alerts: deque = deque(maxlen=max_alerts)
        self.ring_evicted: Dict[str, int] = {
            "spans": 0, "events": 0, "snapshots": 0, "alerts": 0}
        self.observed = 0
        self.dumps: List[dict] = []          # trigger summaries, in order
        self._n_dumps = 0
        self.trigger_cooldown = int(trigger_cooldown)
        self._last_trigger_obs: Dict[str, int] = {}
        # completeness baseline: the pipeline drop counters live on the
        # PROCESS-GLOBAL registry (JsonlSink/SpanTracer count there no
        # matter which registry their records feed), so the verdict
        # must read them there — and as a DELTA since this recorder was
        # armed, so drops from an earlier unrelated run cannot taint a
        # fresh recorder's dumps
        self._drop_baseline = self._upstream_drop_counts()

    @staticmethod
    def _upstream_drop_counts() -> Dict[str, int]:
        counters = get_registry()._counters
        return {
            "spans": counters["telemetry/spans_dropped"].value
            if "telemetry/spans_dropped" in counters else 0,
            "events": counters["telemetry/events_dropped"].value
            if "telemetry/events_dropped" in counters else 0,
        }

    # ------------------------------------------------------------- capture
    def tee(self, inner=None) -> _TeeSink:
        """A sink that records-then-forwards — attach it wherever a
        JsonlSink goes (``registry.attach_sink(rec.tee(sink))``,
        ``SpanTracer(sink=rec.tee(sink))``)."""
        return _TeeSink(self, inner)

    def _push(self, ring_name: str, ring: deque, record: dict) -> None:
        if ring.maxlen is not None and len(ring) == ring.maxlen:
            self.ring_evicted[ring_name] += 1
        ring.append(record)

    def observe(self, record: dict) -> None:
        """Classify one telemetry record into its ring. Unknown kinds
        land in the events ring — a postmortem prefers noise over a
        blind spot."""
        kind = record.get("kind")
        with self._lock:
            self.observed += 1
            if kind == "span":
                self._push("spans", self.spans, record)
            elif kind == "snapshot":
                self._push("snapshots", self.snapshots, record)
            elif kind in ("slo_eval",):
                self._push("alerts", self.alerts, record)
            else:
                self._push("events", self.events, record)

    def note_alert(self, record: dict) -> None:
        """Direct entry into the alert ring (the SLO engine pushes its
        per-evaluation records here even when no sink is attached)."""
        with self._lock:
            self.observed += 1
            self._push("alerts", self.alerts, record)

    # -------------------------------------------------------------- dumps
    def _payload(self, reason: str, context: dict) -> dict:
        drops = self._upstream_drop_counts()
        spans_dropped = drops["spans"] - self._drop_baseline["spans"]
        events_dropped = drops["events"] - self._drop_baseline["events"]
        with self._lock:
            payload = {
                "kind": "flight_dump",
                "reason": reason,
                "context": context,
                "spans": list(self.spans),
                "events": list(self.events),
                "snapshots": list(self.snapshots),
                "alerts": list(self.alerts),
                "ring_evicted": dict(self.ring_evicted),
                "observed": self.observed,
            }
        payload["metrics"] = self.registry.snapshot()
        # completeness: ring evictions are the recorder doing its
        # bounded-window job; upstream drops mean the record itself has
        # holes — the postmortem must say so
        payload["upstream_dropped"] = {"spans": spans_dropped,
                                       "events": events_dropped}
        payload["complete"] = spans_dropped == 0 and events_dropped == 0
        return payload

    def dump(self, path: Optional[str], reason: str, **context) -> dict:
        """Freeze the current pre-incident window as one self-contained
        JSON object; returns the payload (``path`` key always present —
        the written file, or None with ``write_error`` / when no path
        was given). Never raises on I/O failure (the incident being
        dumped may BE a disk problem) — the payload is still returned,
        counted, and evented."""
        payload = self._payload(reason, context)
        payload["path"] = None
        if path is not None:
            try:
                parent = os.path.dirname(os.path.abspath(path))
                os.makedirs(parent, exist_ok=True)
                with open(path, "w") as f:
                    json.dump(payload, f, default=str)
                payload["path"] = path
            except Exception as e:
                payload["write_error"] = f"{type(e).__name__}: {e}"
        self._n_dumps += 1
        self.dumps.append({"reason": reason, "path": payload["path"],
                           "context": context})
        self.registry.event("telemetry/flight_dump", reason=reason,
                            path=payload["path"], **context)
        return payload

    def trigger(self, reason: str, **context) -> Optional[dict]:
        """Auto-trigger seam for the wired incident paths (replica
        crash/quarantine, shed burst, training anomaly, SLO page).
        Writes ``<dump_dir>/flight_<NNN>_<reason>.json`` when a
        ``dump_dir`` is configured; otherwise records the trigger
        without an artifact. Cooldown-gated per reason so an incident
        storm produces a bounded number of dumps. Returns the payload,
        or None when cooldown-suppressed."""
        if self.trigger_cooldown:
            last = self._last_trigger_obs.get(reason)
            if last is not None \
                    and self.observed - last < self.trigger_cooldown:
                return None
            self._last_trigger_obs[reason] = self.observed
        path = os.path.join(self.dump_dir,
                            f"flight_{self._n_dumps:03d}_{reason}.json") \
            if self.dump_dir is not None else None
        return self.dump(path, reason, **context)

    def __repr__(self):
        return (f"FlightRecorder(spans={len(self.spans)}, "
                f"events={len(self.events)}, alerts={len(self.alerts)}, "
                f"snapshots={len(self.snapshots)}, dumps={self._n_dumps}, "
                f"dir={self.dump_dir!r})")
