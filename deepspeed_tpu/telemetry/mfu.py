"""Model-flops-utilization accounting (PaLM appendix B sense).

MFU = achieved model flops/sec ÷ peak chip flops/sec: the fraction of the
hardware's matmul ceiling the training loop actually sustains, with model
flops counted analytically or from XLA's own ``cost_analysis`` of the
compiled step (post-fusion, what actually hits the MXU) — NOT
hardware-counter flops, so recomputation (remat) is charged against MFU
exactly as PaLM defines it when using cost_analysis of the remat program.

The peak table itself lives in the accelerator layer
(``accelerator.peak_tflops()``: per-chip dense bf16 peak by device kind,
``DSTPU_PEAK_TFLOPS`` env override for new silicon); this module only does
the division.
"""

from __future__ import annotations

from typing import Optional


def peak_flops_per_sec(n_chips: Optional[int] = None) -> Optional[float]:
    """Aggregate peak (flops/sec) across ``n_chips`` (default: every device
    in the process's world). None when the accelerator has no peak entry
    (e.g. the CPU test backend without DSTPU_PEAK_TFLOPS set)."""
    from deepspeed_tpu.accelerator import get_accelerator

    acc = get_accelerator()
    per_chip = acc.peak_tflops()
    if per_chip is None or per_chip <= 0:
        return None
    if n_chips is None:
        try:
            n_chips = acc.device_count()
        except Exception:
            n_chips = 1
    return per_chip * 1e12 * max(n_chips, 1)


def mfu(flops_per_step: float, step_time_s: float,
        n_chips: Optional[int] = None) -> Optional[float]:
    """Achieved-vs-peak utilization in [0, ~1]; None when peak is unknown
    or inputs are degenerate."""
    if not flops_per_step or not step_time_s or step_time_s <= 0:
        return None
    peak = peak_flops_per_sec(n_chips)
    if peak is None:
        return None
    return (flops_per_step / step_time_s) / peak
