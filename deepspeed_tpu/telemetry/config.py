"""Telemetry config section (``"telemetry": {...}`` in the DeepSpeed JSON).

Keys:
  enabled        — master switch for engine/serving instrumentation
                   (default true; the registry ops it gates cost ~1us/step,
                   see bench.py observability_overhead).
  jsonl_path     — when non-empty, a JsonlSink is attached to the global
                   registry and periodic snapshots + events stream there
                   (render with scripts/telemetry_report.py).
  sync_interval  — every N global steps the engine fences device work
                   (block_until_ready) to read honest device-time step
                   latency, memory gauges, grad-norm/overflow/MFU. 0
                   disables fencing (async dispatch never perturbed;
                   device-time metrics then unavailable).
  cost_analysis  — allow a one-time XLA cost_analysis of the compiled
                   train step for MFU flops (an extra lower+compile at the
                   first fence; analytic model flops are the fallback).
  spans          — arm the span-graph tracer (ISSUE 11): step-window,
                   sentinel-check, recovery and checkpoint spans stamped
                   host-side at the fences that already exist (zero extra
                   device syncs; default off).
  spans_path     — JSONL file for span records; empty reuses jsonl_path's
                   sink (spans interleave with snapshots/events in one
                   file — telemetry_report.py renders both).
  flight_recorder — arm the crash-safe flight recorder (ISSUE 13): a
                   bounded ring of recent spans/events/snapshots teed
                   off the JSONL stream, dumped as one postmortem JSON
                   when the training sentinel hits an actionable
                   anomaly (default off).
  flight_dir     — directory for flight-recorder dump artifacts
                   (``flight_<NNN>_<reason>.json``); empty records
                   triggers without writing files.
"""

from __future__ import annotations

from deepspeed_tpu.runtime.config_utils import DeepSpeedConfigModel


class TelemetryConfig(DeepSpeedConfigModel):
    enabled: bool = True
    jsonl_path: str = ""
    sync_interval: int = 50
    cost_analysis: bool = True
    spans: bool = False
    spans_path: str = ""
    flight_recorder: bool = False
    flight_dir: str = ""


def get_telemetry_config(param_dict: dict) -> TelemetryConfig:
    return TelemetryConfig(**param_dict.get("telemetry", {}))
