"""Span-graph request/step tracer (ISSUE 11).

The Dapper span model (Sigelman et al., 2010) applied to an Orca-style
iteration-level serving loop and a rewind-capable training loop: every
request (and every training-step window) is a TRACE — a tree of SPANS
linked by ``(trace, span, parent)`` ids — so "TPOT p99 regressed" and
"MFU is 46.6%" decompose into *named phases of named programs* instead
of one opaque aggregate. The aggregate counters/histograms from PR 3
answer "how much"; the span graph answers "where".

Design constraints, in order:

1. **Zero extra device syncs.** Spans are stamped HOST-SIDE at fences
   that already exist (token commits, telemetry fences, swap
   round-trips) with timestamps the caller already computed — the
   tracer never forces a device_get and, given an explicit ``t``,
   never even reads a clock. The serving/fabric integrations pass the
   engine-clock instants they were already holding, so an armed run
   issues the same device work as a bare one (greedy output
   bit-identical, pinned by tests; armed-vs-bare overhead <= 2%,
   pinned by bench.py ``tracing_overhead``).
2. **Virtual-clock compatible.** All times are plain floats in the
   CALLER's clock base (``time.monotonic`` offsets in production, a
   :class:`~deepspeed_tpu.testing.fault_injection.FakeClock` in the
   chaos suites) — the 3-replica crash/failover chaos tests replay
   deterministically, span graph included.
3. **Cross-process ready.** Trace context is two small fields
   (``trace_id``, ``parent_span``) riding on
   :class:`~deepspeed_tpu.serving.scheduler.Request` — exactly what a
   wire protocol would carry — so a request hopping replicas (failover,
   ROADMAP item 2's cross-process fabric) keeps ONE trace id and the
   survivor's spans link under the original root.

Outputs: every finished span goes to the bounded in-memory buffer and,
when a sink is attached, to telemetry JSONL as ``{"kind": "span", ...}``
records (rendered by ``scripts/telemetry_report.py``'s ``spans``
section); :meth:`SpanTracer.to_chrome_trace` exports the Chrome
trace-event JSON Perfetto loads directly (one track per trace).
"""

from __future__ import annotations

import json
import threading
import time
from typing import Dict, Iterable, List, Optional, Sequence

# span name -> lifecycle phase for per-request critical-path accounting
# (names outside this map — roots, engine-scope iteration spans — carry
# structure, not phase time, and are skipped by the breakdown)
PHASE_OF_SPAN = {
    "queue_wait": "queue",          # arrival -> admission (engine)
    "router_queue": "queue",        # submit/requeue -> dispatch (fabric)
    "prefill_chunk": "prefill",     # one prefill program call (per chunk)
    "decode_segment": "decode",     # decode-phase residency in a slot
    "swap_out": "swapped",          # preemption KV extract -> host
    "swapped": "swapped",           # parked off the slot set
    "swap_in": "swapped",           # host KV -> HBM on resume
    "failover": "failover",         # replica death -> re-dispatched
}

PHASES = ("queue", "prefill", "decode", "swapped", "failover")


class Span:
    """One closed (or still-open) span. Times are caller-clock floats;
    ``end`` is None while open. ``attrs`` is a flat dict of small JSON
    values (slot, bucket, program, reason...)."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "start",
                 "end", "attrs")

    def __init__(self, trace_id: str, span_id: str,
                 parent_id: Optional[str], name: str, start: float,
                 end: Optional[float] = None,
                 attrs: Optional[dict] = None):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.start = float(start)
        self.end = None if end is None else float(end)
        self.attrs = attrs or {}

    @property
    def duration(self) -> Optional[float]:
        return None if self.end is None else max(self.end - self.start, 0.0)

    def as_dict(self) -> dict:
        d = {"kind": "span", "trace": self.trace_id, "span": self.span_id,
             "parent": self.parent_id, "name": self.name,
             "start": self.start, "end": self.end}
        if self.end is not None:
            d["dur_ms"] = (self.end - self.start) * 1e3
        if self.attrs:
            d["attrs"] = self.attrs
        return d

    def __repr__(self):
        return (f"Span({self.name}, trace={self.trace_id}, "
                f"span={self.span_id}, parent={self.parent_id}, "
                f"start={self.start:.6f}, end={self.end})")


class SpanTracer:
    """Allocates trace/span ids, buffers finished spans, streams them to
    a JSONL sink, and exports Chrome-trace JSON.

    Ids are DETERMINISTIC per tracer (monotonic counters, not random):
    two runs of the same virtual-clock trace produce the same span
    graph, which is what lets the chaos suites pin graph shape.

    ``time_fn`` is only a fallback — every integration point passes
    explicit ``t`` values it already computed, so arming the tracer
    against a :class:`FakeClock` never perturbs the virtual timeline.

    Thread-safety: id allocation and buffer appends take a lock (the
    async checkpoint thread and the serving loop may both record).
    """

    def __init__(self, *, time_fn=None, sink=None, max_spans: int = 200_000):
        self._time = time_fn or time.monotonic
        self.sink = sink
        self.max_spans = int(max_spans)
        self._lock = threading.Lock()
        self._next_trace = 0
        self._next_span = 0
        self.spans: List[Span] = []        # finished spans, append order
        self.dropped = 0                   # finished spans past max_spans
        self._drop_warned = False

    # ------------------------------------------------------------------ ids
    def new_trace(self) -> str:
        with self._lock:
            tid = self._next_trace
            self._next_trace += 1
        return f"t{tid:08x}"

    def _new_span_id(self) -> str:
        with self._lock:
            sid = self._next_span
            self._next_span += 1
        return f"s{sid:08x}"

    def now(self) -> float:
        """Fallback clock read — prefer passing explicit ``t``."""
        return self._time()

    # ---------------------------------------------------------------- spans
    def begin(self, name: str, *, trace_id: Optional[str] = None,
              parent_id: Optional[str] = None, t: Optional[float] = None,
              **attrs) -> Span:
        """Open a span (allocating a fresh trace when ``trace_id`` is
        None). The span is not in :attr:`spans` until :meth:`end`."""
        if trace_id is None:
            trace_id = self.new_trace()
        return Span(trace_id, self._new_span_id(), parent_id, name,
                    self.now() if t is None else t, attrs=attrs)

    def end(self, span: Optional[Span], t: Optional[float] = None,
            **attrs) -> Optional[Span]:
        """Close an open span and commit it to the buffer/sink. None-safe
        (callers end whatever handle they hold without re-checking the
        armed state). A span already ended is left untouched."""
        if span is None or span.end is not None:
            return span
        span.end = self.now() if t is None else float(t)
        if span.end < span.start:          # out-of-order virtual stamps
            span.end = span.start
        if attrs:
            span.attrs.update(attrs)
        self._commit(span)
        return span

    def record(self, name: str, start: float, end: float, *,
               trace_id: Optional[str] = None,
               parent_id: Optional[str] = None, **attrs) -> Span:
        """Stamp an already-elapsed interval as one closed span — the
        fence-friendly primitive: both instants were observed at fences
        that already existed, nothing blocks here."""
        if trace_id is None:
            trace_id = self.new_trace()
        span = Span(trace_id, self._new_span_id(), parent_id, name,
                    start, max(end, start), attrs=attrs)
        self._commit(span)
        return span

    def _commit(self, span: Span) -> None:
        dropped = False
        with self._lock:
            if len(self.spans) < self.max_spans:
                self.spans.append(span)
            else:
                self.dropped += 1
                dropped = True
        if dropped:
            # dropped-data accounting (ISSUE 13 satellite): a silent
            # drop would let a postmortem claim completeness it does
            # not have — count every drop, warn once
            try:
                from deepspeed_tpu.telemetry.registry import get_registry

                get_registry().counter("telemetry/spans_dropped").inc()
            except Exception:
                pass
            if not self._drop_warned:
                self._drop_warned = True
                try:
                    from deepspeed_tpu.utils.logging import logger

                    logger.warning(
                        f"SpanTracer buffer full ({self.max_spans} spans): "
                        f"further spans are dropped from the in-memory "
                        f"buffer (counted in telemetry/spans_dropped; "
                        f"JSONL streaming, if armed, continues)")
                except Exception:
                    pass
        if self.sink is not None:
            try:
                self.sink.write(span.as_dict())
            except Exception:   # tracing must never take down the job
                pass

    # -------------------------------------------------------------- queries
    def spans_for(self, trace_id: str) -> List[Span]:
        with self._lock:
            return [s for s in self.spans if s.trace_id == trace_id]

    def trace_ids(self) -> List[str]:
        seen, out = set(), []
        with self._lock:
            for s in self.spans:
                if s.trace_id not in seen:
                    seen.add(s.trace_id)
                    out.append(s.trace_id)
        return out

    def clear(self) -> None:
        with self._lock:
            self.spans.clear()
            self.dropped = 0

    # -------------------------------------------------------------- exports
    def to_chrome_trace(self) -> dict:
        """Chrome trace-event JSON (the ``{"traceEvents": [...]}`` form
        Perfetto/chrome://tracing load directly): one complete ("X")
        event per finished span, one tid TRACK per trace so a request's
        lifecycle reads left-to-right on its own row. Times are mapped
        caller-clock seconds -> microseconds."""
        with self._lock:
            spans = list(self.spans)
        tids: Dict[str, int] = {}
        events = []
        for s in spans:
            if s.end is None:
                continue
            tid = tids.setdefault(s.trace_id, len(tids))
            args = {"trace": s.trace_id, "span": s.span_id}
            if s.parent_id:
                args["parent"] = s.parent_id
            args.update(s.attrs)
            events.append({
                "name": s.name,
                "cat": PHASE_OF_SPAN.get(s.name, "span"),
                "ph": "X",
                "ts": round(s.start * 1e6, 3),
                "dur": round((s.end - s.start) * 1e6, 3),
                "pid": 0,
                "tid": tid,
                "args": args,
            })
        meta = [{"name": "thread_name", "ph": "M", "pid": 0, "tid": tid,
                 "args": {"name": f"trace {trace}"}}
                for trace, tid in tids.items()]
        return {"traceEvents": meta + events, "displayTimeUnit": "ms"}

    def export_chrome_trace(self, path: str) -> str:
        """Write :meth:`to_chrome_trace` to ``path``; load the file at
        https://ui.perfetto.dev (or chrome://tracing)."""
        with open(path, "w") as f:
            json.dump(self.to_chrome_trace(), f)
        return path

    def __repr__(self):
        return (f"SpanTracer(spans={len(self.spans)}, "
                f"traces={self._next_trace}, dropped={self.dropped})")


# ------------------------------------------------------- span-graph analysis
def _get(rec, key, default=None):
    """Field access over either Span objects or JSONL span dicts."""
    if isinstance(rec, Span):
        return {"trace": rec.trace_id, "span": rec.span_id,
                "parent": rec.parent_id, "name": rec.name,
                "start": rec.start, "end": rec.end,
                "attrs": rec.attrs}.get(key, default)
    return rec.get(key, default)


def phase_breakdown(spans: Iterable) -> Dict[str, float]:
    """Seconds spent per lifecycle phase over one trace's spans (Span
    objects or JSONL dicts). Only closed spans whose name maps to a
    phase count; structural spans (roots, engine iteration spans) are
    skipped — for a single-slot request the phases are sequential, so
    the sum approximates the root span's duration."""
    out = {p: 0.0 for p in PHASES}
    for s in spans:
        phase = PHASE_OF_SPAN.get(_get(s, "name"))
        end = _get(s, "end")
        if phase is None or end is None:
            continue
        out[phase] += max(end - _get(s, "start", 0.0), 0.0)
    return out


def trace_summaries(spans: Iterable,
                    root_name: str = "request") -> List[dict]:
    """Per-trace lifecycle summary over a mixed span stream: one dict
    per trace that has a closed ``root_name`` span, with total seconds,
    per-phase seconds, and per-phase FRACTIONS of the root duration —
    the critical-path view ("this request spent 60% of its life in
    queue, 5% prefilling, 30% decoding, 5% swapped out")."""
    by_trace: Dict[str, List] = {}
    for s in spans:
        by_trace.setdefault(_get(s, "trace"), []).append(s)
    out = []
    for trace, group in by_trace.items():
        roots = [s for s in group
                 if _get(s, "name") == root_name and _get(s, "end")
                 is not None]
        if not roots:
            continue
        root = roots[0]
        total = max(_get(root, "end") - _get(root, "start"), 0.0)
        phases = phase_breakdown(group)
        fractions = {p: (phases[p] / total if total > 0 else 0.0)
                     for p in PHASES}
        out.append({
            "trace": trace,
            "root_span": _get(root, "span"),
            "total_s": total,
            "phases_s": phases,
            "fractions": fractions,
            "n_spans": len(group),
            "attrs": dict(_get(root, "attrs") or {}),
        })
    return out


def aggregate_phase_stats(summaries: Sequence[dict]) -> dict:
    """p50/p95 of per-request phase fractions and absolute times across
    a run's traces — the report's ``spans`` section payload."""
    if not summaries:
        return {}

    def pct(xs: List[float], p: float) -> float:
        xs = sorted(xs)
        return xs[min(int(len(xs) * p), len(xs) - 1)]

    out: Dict[str, dict] = {"n_requests": len(summaries)}
    totals = [s["total_s"] for s in summaries]
    out["total_ms"] = {"p50": pct(totals, 0.5) * 1e3,
                       "p95": pct(totals, 0.95) * 1e3}
    for phase in PHASES:
        fr = [s["fractions"][phase] for s in summaries]
        ab = [s["phases_s"][phase] for s in summaries]
        if not any(ab):
            continue
        out[phase] = {
            "frac_p50": round(pct(fr, 0.5), 4),
            "frac_p95": round(pct(fr, 0.95), 4),
            "ms_p50": round(pct(ab, 0.5) * 1e3, 3),
            "ms_p95": round(pct(ab, 0.95) * 1e3, 3),
        }
    return out
