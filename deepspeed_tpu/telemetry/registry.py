"""In-process metrics registry: counters, gauges, fixed-bucket histograms.

The reference DeepSpeed scatters observability across ``monitor/``,
``utils/timer.py``, the flops profiler and the comms logger; this module is
the missing spine that unifies them (ISSUE 3): one registry every hot loop
writes into with near-zero cost, snapshotted on demand.

Design constraints, in order:

1. **Overhead.** A hot-loop update is a dict lookup + an int add (counters),
   a float store (gauges) or a ``bisect`` + int add (histograms) — no
   locks on the update path, no allocation, no syscalls. bench.py's
   ``observability_overhead`` section holds instrumented train and decode
   steps to a 2% budget against bare runs.
2. **Fixed memory.** Histograms are fixed-bucket (default: log-spaced
   latency buckets, ~1.25x ratio) so a week-long serving run costs the
   same bytes as a unit test. Percentiles (p50/p95/p99) are estimated by
   linear interpolation inside the bracketing bucket — error is bounded
   by the bucket ratio, and min/max/sum/mean are exact.
3. **Pure host Python.** No jax imports: the registry must be usable from
   the checkpoint writer thread, the elastic agent supervisor and test
   code that never touches a device.

Threading: creation (``counter()/gauge()/histogram()`` first call) takes a
lock; updates are GIL-atomic single bytecode-ish operations — adequate for
the one-writer-per-metric usage here (the async checkpoint thread owns the
checkpoint counters, the train loop owns the train metrics).
"""

from __future__ import annotations

import re
import threading
from bisect import bisect_left
from typing import Dict, List, Optional, Sequence

# the one character class every scraped metric name must reduce to —
# shared by to_prometheus() and metric_label() so a name that is valid
# in-process is valid (and collision-stable) after Prometheus
# sanitization too
_PROM_INVALID = re.compile(r"[^a-zA-Z0-9_:]")
# labels reduce to the [a-zA-Z0-9_] subset: any character the
# Prometheus sanitizer would fold to "_" is folded HERE, so two
# distinct in-process names can never collide only at scrape time
_LABEL_INVALID = re.compile(r"[^a-zA-Z0-9_]")


def sanitize_metric_name(name: str, prefix: str = "dstpu") -> str:
    """Prometheus-exposition name for an in-process metric name
    (``serving/ttft_ms`` -> ``dstpu_serving_ttft_ms``)."""
    out = _PROM_INVALID.sub("_", name)
    return f"{prefix}_{out}" if prefix else out


def metric_label(value) -> str:
    """Sanitize a CALLER-SUPPLIED label value (tenant id, priority
    class) for embedding into a metric name segment (ISSUE 13
    satellite): arbitrary strings must neither break the ``/``-separated
    name paths the report sections parse nor collide after
    :func:`sanitize_metric_name`. Invalid characters (including ``/``)
    become ``_``; empty values become ``_``; length is clamped so a
    hostile tenant id cannot balloon the registry keys."""
    s = str(value)
    s = _LABEL_INVALID.sub("_", s)[:64]
    return s or "_"


def _default_latency_buckets_ms() -> List[float]:
    """Log-spaced (ratio 1.25) upper bounds from 10us to ~2min, in ms.
    The ratio bounds histogram-percentile quantization error to ~25%
    worst-case (a few % typical after interpolation) — tight enough that
    telemetry p50/p95 agree with direct measurement (bench.py
    ``observability_overhead.histogram_agreement``)."""
    out, v = [], 0.01
    while v < 120_000.0:
        out.append(round(v, 6))
        v *= 1.25
    return out


DEFAULT_LATENCY_BUCKETS_MS: Sequence[float] = tuple(_default_latency_buckets_ms())


class Counter:
    """Monotonic event counter."""

    __slots__ = ("name", "value")

    def __init__(self, name: str):
        self.name = name
        self.value = 0

    def inc(self, n: int = 1) -> None:
        self.value += n

    def snapshot(self):
        return self.value


class Gauge:
    """Last-write-wins instantaneous value (None until first set)."""

    __slots__ = ("name", "value")

    def __init__(self, name: str):
        self.name = name
        self.value: Optional[float] = None

    def set(self, v: float) -> None:
        self.value = v

    def snapshot(self):
        return self.value


class Histogram:
    """Fixed-bucket histogram with percentile estimation.

    ``buckets`` are ascending upper bounds; observations above the last
    bound land in an overflow bucket whose percentile estimate is the
    observed max (exact). min/max/sum/count are tracked exactly.
    """

    __slots__ = ("name", "buckets", "counts", "count", "sum", "min", "max")

    def __init__(self, name: str, buckets: Optional[Sequence[float]] = None):
        self.name = name
        self.buckets = tuple(buckets if buckets is not None
                             else DEFAULT_LATENCY_BUCKETS_MS)
        assert list(self.buckets) == sorted(self.buckets), \
            f"histogram {name}: buckets must be ascending"
        self.counts = [0] * (len(self.buckets) + 1)  # +1 overflow
        self.count = 0
        self.sum = 0.0
        self.min = float("inf")
        self.max = float("-inf")

    def observe(self, v: float) -> None:
        self.counts[bisect_left(self.buckets, v)] += 1
        self.count += 1
        self.sum += v
        if v < self.min:
            self.min = v
        if v > self.max:
            self.max = v

    def percentile(self, p: float) -> Optional[float]:
        """Estimated value at quantile ``p`` in [0, 1]: linear
        interpolation inside the bracketing bucket (lower bound = previous
        bucket's upper bound, 0 or observed min for the first)."""
        if self.count == 0:
            return None
        target = p * self.count
        cum = 0
        for i, c in enumerate(self.counts):
            if c == 0:
                continue
            if cum + c >= target:
                if i == len(self.buckets):   # overflow bucket
                    return self.max
                hi = self.buckets[i]
                lo = self.buckets[i - 1] if i > 0 else min(self.min, hi)
                frac = (target - cum) / c
                est = lo + (hi - lo) * frac
                # exact bounds beat bucket edges at the extremes
                return min(max(est, self.min), self.max)
            cum += c
        return self.max

    def snapshot(self) -> Dict[str, float]:
        if self.count == 0:
            return {"count": 0}
        return {
            "count": self.count,
            "sum": self.sum,
            "mean": self.sum / self.count,
            "min": self.min,
            "max": self.max,
            "p50": self.percentile(0.50),
            "p95": self.percentile(0.95),
            "p99": self.percentile(0.99),
        }


class MetricsRegistry:
    """Named metric store + optional structured sink.

    ``event()`` both counts and (when a sink is attached) appends a
    structured JSONL record — the checkpoint/elasticity layers use it for
    discrete occurrences (saves, corruption fallbacks, restarts).
    """

    def __init__(self, sink=None):
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}
        self._sink = sink

    # ------------------------------------------------------------- factories
    def counter(self, name: str) -> Counter:
        c = self._counters.get(name)
        if c is None:
            with self._lock:
                c = self._counters.setdefault(name, Counter(name))
        return c

    def gauge(self, name: str) -> Gauge:
        g = self._gauges.get(name)
        if g is None:
            with self._lock:
                g = self._gauges.setdefault(name, Gauge(name))
        return g

    def histogram(self, name: str,
                  buckets: Optional[Sequence[float]] = None) -> Histogram:
        h = self._histograms.get(name)
        if h is None:
            with self._lock:
                h = self._histograms.setdefault(name, Histogram(name, buckets))
        return h

    # ----------------------------------------------------------------- sink
    def attach_sink(self, sink) -> None:
        self._sink = sink

    @property
    def sink(self):
        return self._sink

    def event(self, name: str, **fields) -> None:
        """Count a discrete occurrence; stream it when a sink is attached."""
        self.counter(name).inc()
        if self._sink is not None:
            try:
                self._sink.write({"kind": "event", "name": name, **fields})
            except Exception:  # telemetry must never take down the job
                pass

    # ------------------------------------------------------------- snapshot
    def snapshot(self) -> Dict[str, dict]:
        with self._lock:
            return {
                "counters": {k: c.snapshot() for k, c in self._counters.items()},
                "gauges": {k: g.snapshot() for k, g in self._gauges.items()
                           if g.value is not None},
                "histograms": {k: h.snapshot()
                               for k, h in self._histograms.items()},
            }

    def flush(self, step: Optional[int] = None) -> None:
        """Write a full snapshot record to the sink (no-op without one)."""
        if self._sink is None:
            return
        rec = {"kind": "snapshot", "metrics": self.snapshot()}
        if step is not None:
            rec["step"] = step
        try:
            self._sink.write(rec)
            self._sink.flush()
        except Exception:
            pass

    # ---------------------------------------------------------- prometheus
    def to_prometheus(self, prefix: str = "dstpu") -> str:
        """Render the live registry in the Prometheus text exposition
        format (ISSUE 11 satellite) — the seam the cross-process
        fabric's scrape endpoint will serve. Metric names are sanitized
        (``serving/ttft_ms`` -> ``dstpu_serving_ttft_ms``); counters
        gain the conventional ``_total`` suffix; histograms emit the
        full CUMULATIVE bucket series (+Inf included) plus ``_sum`` and
        ``_count``, so Prometheus-side ``histogram_quantile`` sees the
        same fixed buckets the in-process percentiles use. Name
        sanitization is the module-level :func:`sanitize_metric_name`,
        shared with :func:`metric_label` (the per-tenant / per-class
        name segments), so any name the engines can emit scrapes
        cleanly."""
        def san(name: str) -> str:
            return sanitize_metric_name(name, prefix)

        lines: List[str] = []
        with self._lock:
            counters = list(self._counters.values())
            gauges = list(self._gauges.values())
            hists = list(self._histograms.values())
        for c in sorted(counters, key=lambda m: m.name):
            n = san(c.name) + "_total"
            lines.append(f"# TYPE {n} counter")
            lines.append(f"{n} {c.value}")
        for g in sorted(gauges, key=lambda m: m.name):
            if g.value is None:
                continue
            n = san(g.name)
            lines.append(f"# TYPE {n} gauge")
            lines.append(f"{n} {g.value}")
        for h in sorted(hists, key=lambda m: m.name):
            n = san(h.name)
            lines.append(f"# TYPE {n} histogram")
            cum = 0
            for bound, cnt in zip(h.buckets, h.counts):
                cum += cnt
                lines.append(f'{n}_bucket{{le="{bound}"}} {cum}')
            lines.append(f'{n}_bucket{{le="+Inf"}} {h.count}')
            lines.append(f"{n}_sum {h.sum}")
            lines.append(f"{n}_count {h.count}")
        return "\n".join(lines) + ("\n" if lines else "")

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._histograms.clear()


# ---------------------------------------------------------------- global
_default_registry = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    """The process-global registry: engines default to it, and the
    checkpoint/elasticity event counters always use it."""
    return _default_registry


def reset_registry() -> None:
    """Clear the global registry (tests / benchmark isolation). The
    attached sink, if any, is kept."""
    _default_registry.reset()


def record_event(name: str, **fields) -> None:
    """Fire-and-forget event into the global registry; exception-proof so
    instrumented subsystems (checkpoint writer thread, signal handlers)
    can call it unconditionally."""
    try:
        _default_registry.event(name, **fields)
    except Exception:
        pass
