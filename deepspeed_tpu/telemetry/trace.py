"""Profiler trace capture — ``telemetry.trace(path)``.

Context-manager wrapper over ``jax.profiler.start_trace``/``stop_trace``
producing a Perfetto/XPlane trace directory viewable at ui.perfetto.dev
(or TensorBoard's profile plugin). The named scopes the hot loops already
emit (``utils/nvtx.py`` TraceAnnotations around prefill/decode/admit and
fwd/bwd/step) appear as ranges inside it, the way NVTX ranges appear in
Nsight for the reference.

Degrades to a no-op with a warning when the installed jax/backend cannot
start a trace (some stripped jaxlib builds lack the profiler server) —
capturing a trace is never worth crashing the run being traced.
"""

from __future__ import annotations

import contextlib
from typing import Iterator, Optional


def annotate(name: str):
    """Named profiler scope (``with telemetry.annotate("decode"): ...``).
    Same TraceAnnotation the nvtx shim uses; reusable as a decorator via
    :func:`deepspeed_tpu.utils.nvtx.instrument_w_nvtx`."""
    import jax

    return jax.profiler.TraceAnnotation(name)


@contextlib.contextmanager
def trace(path: str, *, host_tracer_level: Optional[int] = None
          ) -> Iterator[str]:
    """Capture a device+host profiler trace of the enclosed block into
    ``path`` (a directory; created if needed).

    Example — trace one serving burst::

        with telemetry.trace("/tmp/serve_trace"):
            serving_engine.run(requests)

    then load ``path``'s ``plugins/profile/.../*.trace.json.gz`` in
    Perfetto. ``host_tracer_level`` forwards to jax when supported
    (higher = more host annotations)."""
    import jax

    from deepspeed_tpu.utils.logging import logger

    started = False
    try:
        kwargs = {}
        if host_tracer_level is not None:
            try:
                from jax.profiler import ProfileOptions  # jax >= 0.4.31

                opts = ProfileOptions()
                opts.host_tracer_level = host_tracer_level
                kwargs["profiler_options"] = opts
            except Exception:
                pass  # older jax: no per-trace options; default level
        jax.profiler.start_trace(str(path), **kwargs)
        started = True
    except Exception as e:
        logger.warning(f"telemetry.trace: cannot start profiler trace "
                       f"({type(e).__name__}: {e}); running untraced")
    try:
        yield str(path)
    finally:
        if started:
            try:
                jax.profiler.stop_trace()
            except Exception as e:
                logger.warning(f"telemetry.trace: stop_trace failed "
                               f"({type(e).__name__}: {e})")
