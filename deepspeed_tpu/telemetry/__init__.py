"""Unified telemetry subsystem (ISSUE 3).

The cross-cutting observability layer the reference spreads over
``deepspeed/monitor``, ``utils/timer.py``, the flops profiler and the
comms logger, redesigned for JAX's async-dispatch execution model:

  * :mod:`registry`  — counters / gauges / fixed-bucket latency histograms
    with p50/p95/p99 snapshots; process-global default registry plus
    :func:`record_event` for discrete occurrences (checkpoint saves,
    corruption fallbacks, elastic restarts).
  * :mod:`sink`      — structured JSONL sink (one record per line) that
    also plugs into :class:`~deepspeed_tpu.monitor.monitor.MonitorMaster`
    as its fourth writer; render with ``scripts/telemetry_report.py``.
  * :mod:`trace`     — ``telemetry.trace(path)`` Perfetto/XPlane capture
    around any block, with the hot loops' named scopes inside.
  * :mod:`mfu`       — PaLM-sense model-flops-utilization against the
    accelerator layer's per-chip peak table.

Instrumentation points: ``runtime/engine.py`` (per-step wall/device time,
tokens/sec, MFU, grad-norm, fp16 skip counters, device memory) and
``serving/engine.py`` (queue-wait/TTFT/TPOT histograms, slot occupancy,
recompile counter, finished-requests/sec). Overhead is budgeted at 2% and
measured by ``bench.py``'s ``observability_overhead`` section.
"""

from deepspeed_tpu.telemetry.attribution import (abstract_args,
                                                 attribution_table,
                                                 program_cost, roofline_row)
from deepspeed_tpu.telemetry.config import TelemetryConfig, get_telemetry_config
from deepspeed_tpu.telemetry.flight_recorder import FlightRecorder
from deepspeed_tpu.telemetry.mfu import mfu, peak_flops_per_sec
from deepspeed_tpu.telemetry.registry import (
    DEFAULT_LATENCY_BUCKETS_MS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    get_registry,
    metric_label,
    record_event,
    reset_registry,
    sanitize_metric_name,
)
from deepspeed_tpu.telemetry.sink import JsonlSink, read_jsonl
from deepspeed_tpu.telemetry.slo import (DEFAULT_SLO_CONFIG, SLI, BurnRateRule,
                                         SLOAlert, SLOConfigError, SLOEngine,
                                         parse_slo_config, validate_slo_config)
from deepspeed_tpu.telemetry.tenants import DEFAULT_TENANT, TenantLedger
from deepspeed_tpu.telemetry.spans import (PHASE_OF_SPAN, PHASES, Span,
                                           SpanTracer, aggregate_phase_stats,
                                           phase_breakdown, trace_summaries)
from deepspeed_tpu.telemetry.trace import annotate, trace

__all__ = [
    "BurnRateRule",
    "DEFAULT_LATENCY_BUCKETS_MS",
    "DEFAULT_SLO_CONFIG",
    "DEFAULT_TENANT",
    "Counter",
    "FlightRecorder",
    "Gauge",
    "Histogram",
    "JsonlSink",
    "MetricsRegistry",
    "PHASES",
    "PHASE_OF_SPAN",
    "SLI",
    "SLOAlert",
    "SLOConfigError",
    "SLOEngine",
    "Span",
    "SpanTracer",
    "TelemetryConfig",
    "TenantLedger",
    "abstract_args",
    "aggregate_phase_stats",
    "annotate",
    "attribution_table",
    "get_registry",
    "get_telemetry_config",
    "metric_label",
    "mfu",
    "parse_slo_config",
    "peak_flops_per_sec",
    "phase_breakdown",
    "program_cost",
    "read_jsonl",
    "record_event",
    "reset_registry",
    "roofline_row",
    "sanitize_metric_name",
    "trace",
    "trace_summaries",
    "validate_slo_config",
]
