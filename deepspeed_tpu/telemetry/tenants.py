"""Per-tenant usage & cost accounting (ISSUE 13).

The multi-tenant fabric shares one KV pool, one prefix cache and one
prefill budget across every caller, but until now nothing attributed
that consumption: "who is eating the pool?" had no answer, so neither
fairness decisions nor cost attribution were possible. This module is
the ledger the request path writes into:

  * **token usage** — prompt tokens, decode (generated) tokens;
  * **prefill economics** — prefill tokens actually COMPUTED vs tokens
    SAVED by the radix prefix cache (the cache's per-tenant dividend);
  * **KV occupancy** — block-seconds: pool-block occupancy integrated
    over engine-clock time (the scarce resource a long-idle tenant
    holds), plus byte-seconds at PAYLOAD bytes so a quantized pool's
    cheaper blocks bill at what they actually cost in HBM;
  * **QoS suffered** — preemptions and sheds, and per-tenant TTFT/TPOT
    histograms (the per-tenant SLI substrate).

Everything is host-side dict arithmetic at call sites the engine
already owns (admission, chunk loop, token commit, finish, preemption)
— zero extra device syncs, and the engine-level counters remain the
ground truth: the per-tenant token totals sum EXACTLY to them (pinned
by tests).

Tenant ids are caller-supplied strings
(:attr:`~deepspeed_tpu.serving.scheduler.Request.tenant_id`, default
:data:`DEFAULT_TENANT`), sanitized through
:func:`~deepspeed_tpu.telemetry.registry.metric_label` before they
name registry metrics — an arbitrary tenant string can neither break
the ``/``-separated name paths nor produce an invalid Prometheus name.
"""

from __future__ import annotations

from typing import Dict, Optional

from deepspeed_tpu.telemetry.registry import (MetricsRegistry, metric_label)

DEFAULT_TENANT = "default"

# TTFT/TPOT per tenant reuse the registry's default latency buckets


class _TenantMetrics:
    """One tenant's registry handles + exact local accumulators. The
    registry handles are resolved ONCE per tenant (hot-path updates are
    then a bound-method call), and the metric names are literal
    f-strings so scripts/check_metric_names.py sees them."""

    __slots__ = ("tenant", "requests", "prompt_tokens", "decode_tokens",
                 "prefill_tokens_computed", "prefill_tokens_saved",
                 "kv_block_seconds", "kv_byte_seconds", "preemptions",
                 "sheds", "ttft_ms", "tpot_ms")

    def __init__(self, tenant: str, reg: Optional[MetricsRegistry]):
        self.tenant = tenant
        t = tenant
        if reg is not None:
            self.requests = reg.counter(f"serving/tenant/{t}/requests")
            self.prompt_tokens = reg.counter(
                f"serving/tenant/{t}/prompt_tokens")
            self.decode_tokens = reg.counter(
                f"serving/tenant/{t}/decode_tokens")
            self.prefill_tokens_computed = reg.counter(
                f"serving/tenant/{t}/prefill_tokens_computed")
            self.prefill_tokens_saved = reg.counter(
                f"serving/tenant/{t}/prefill_tokens_saved")
            self.kv_block_seconds = reg.counter(
                f"serving/tenant/{t}/kv_block_seconds")
            self.kv_byte_seconds = reg.counter(
                f"serving/tenant/{t}/kv_byte_seconds")
            self.preemptions = reg.counter(
                f"serving/tenant/{t}/preemptions")
            self.sheds = reg.counter(f"serving/tenant/{t}/sheds")
            self.ttft_ms = reg.histogram(f"serving/tenant/{t}/ttft_ms")
            self.tpot_ms = reg.histogram(f"serving/tenant/{t}/tpot_ms")
        else:
            from deepspeed_tpu.telemetry.registry import Counter, Histogram

            self.requests = Counter("requests")
            self.prompt_tokens = Counter("prompt_tokens")
            self.decode_tokens = Counter("decode_tokens")
            self.prefill_tokens_computed = Counter("prefill_tokens_computed")
            self.prefill_tokens_saved = Counter("prefill_tokens_saved")
            self.kv_block_seconds = Counter("kv_block_seconds")
            self.kv_byte_seconds = Counter("kv_byte_seconds")
            self.preemptions = Counter("preemptions")
            self.sheds = Counter("sheds")
            self.ttft_ms = Histogram("ttft_ms")
            self.tpot_ms = Histogram("tpot_ms")


class TenantLedger:
    """Per-tenant accounting over a metrics registry (or standalone,
    with private metric objects, when ``registry`` is None).

    The engine resolves a request's tenant ONCE at submit/admit
    (:meth:`resolve`) and hands the sanitized label to every later
    note; two raw ids that sanitize identically share a ledger row by
    design (the registry could not tell them apart either)."""

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        self.registry = registry
        self._tenants: Dict[str, _TenantMetrics] = {}

    # ------------------------------------------------------------- lookup
    @staticmethod
    def resolve(tenant_id) -> str:
        """Sanitized ledger key for a caller-supplied tenant id (None ->
        the default tenant)."""
        if tenant_id is None:
            return DEFAULT_TENANT
        return metric_label(tenant_id)

    def _m(self, tenant: str) -> _TenantMetrics:
        tm = self._tenants.get(tenant)
        if tm is None:
            tm = _TenantMetrics(tenant, self.registry)
            self._tenants[tenant] = tm
        return tm

    def tenants(self):
        return sorted(self._tenants)

    # -------------------------------------------------------------- notes
    def note_admitted(self, tenant: str, prompt_tokens: int) -> None:
        m = self._m(tenant)
        m.requests.inc()
        m.prompt_tokens.inc(int(prompt_tokens))

    def note_prefill(self, tenant: str, computed: int,
                     saved: int = 0) -> None:
        m = self._m(tenant)
        if computed:
            m.prefill_tokens_computed.inc(int(computed))
        if saved:
            m.prefill_tokens_saved.inc(int(saved))

    def note_tokens(self, tenant: str, n: int) -> None:
        if n:
            self._m(tenant).decode_tokens.inc(int(n))

    def note_kv_occupancy(self, tenant: str, blocks: int, dt: float,
                          payload_bytes_per_block: float) -> None:
        """Integrate pool occupancy: ``blocks`` held for ``dt`` seconds
        of engine-clock time. Byte-seconds bill at PAYLOAD bytes per
        block (scales included), so an int8 pool's blocks cost ~half a
        bf16 pool's — the capacity lever shows up in the bill."""
        if blocks <= 0 or dt <= 0:
            return
        m = self._m(tenant)
        m.kv_block_seconds.inc(blocks * dt)
        m.kv_byte_seconds.inc(blocks * dt * payload_bytes_per_block)

    def note_preemption(self, tenant: str) -> None:
        self._m(tenant).preemptions.inc()

    def note_shed(self, tenant: str) -> None:
        self._m(tenant).sheds.inc()

    def note_ttft(self, tenant: str, ms: float) -> None:
        self._m(tenant).ttft_ms.observe(ms)

    def note_tpot(self, tenant: str, ms: float) -> None:
        self._m(tenant).tpot_ms.observe(ms)

    # ------------------------------------------------------------- totals
    def totals(self) -> Dict[str, dict]:
        """Per-tenant usage snapshot (the report's ``tenants`` table
        source when no registry snapshot is available)."""
        out: Dict[str, dict] = {}
        for t in self.tenants():
            m = self._tenants[t]
            out[t] = {
                "requests": m.requests.value,
                "prompt_tokens": m.prompt_tokens.value,
                "decode_tokens": m.decode_tokens.value,
                "prefill_tokens_computed": m.prefill_tokens_computed.value,
                "prefill_tokens_saved": m.prefill_tokens_saved.value,
                "kv_block_seconds": round(float(m.kv_block_seconds.value), 6),
                "kv_byte_seconds": round(float(m.kv_byte_seconds.value), 3),
                "preemptions": m.preemptions.value,
                "sheds": m.sheds.value,
                "ttft_ms_p50": m.ttft_ms.percentile(0.5),
                "tpot_ms_p50": m.tpot_ms.percentile(0.5),
            }
        return out

    def __repr__(self):
        return f"TenantLedger(tenants={self.tenants()})"
