"""Per-program roofline attribution (ISSUE 11).

The PR 3 MFU gauge says "46.6% of peak"; this module says WHICH compiled
program is responsible and whether it is compute- or memory-bound. For
every jitted program a serving engine registers (prefill buckets,
decode, speculative verify, swap, block-copy) plus the fused train
step, it extracts XLA's own post-fusion cost model — flops and bytes
accessed — via ``lower().compile().cost_analysis()`` (the PR 3 MFU
numerator, generalized), joins it with host-observed per-program wall
time, and places each program on the classic roofline
(Williams et al., 2009):

    attainable_flops/s = min(peak_flops/s, intensity * peak_bytes/s)

so ``achieved_vs_attainable`` is per-program MFU against the bound that
actually binds it — a decode step at intensity 2 flops/byte is judged
against the HBM roof, not the matmul peak.

Cost probing reuses the PR 3 discipline: one extra lower+compile per
program, shapes captured as ``jax.ShapeDtypeStruct`` abstractions at
warmup (no live buffers retained), probed lazily and cached — never on
the serving hot path. Peaks come from the accelerator layer
(``peak_tflops()`` / ``peak_hbm_gbps()``, ``DSTPU_PEAK_TFLOPS`` /
``DSTPU_PEAK_HBM_GBPS`` overrides); where a peak is unknown (CPU test
runs) the table still reports flops/bytes/intensity/achieved and leaves
the attainable columns None.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple


def abstract_args(args) -> Tuple:
    """Shape/dtype abstraction of a program's runtime operands —
    retainable without keeping device buffers alive, and accepted by
    ``jit_fn.lower`` for AOT cost probing."""
    import jax
    import numpy as np

    def absify(x):
        a = np.asarray(x) if not hasattr(x, "dtype") or not hasattr(
            x, "shape") else x
        return jax.ShapeDtypeStruct(tuple(a.shape), a.dtype)

    return tuple(jax.tree_util.tree_map(absify, a) for a in args)


def program_cost(fn, args) -> Optional[Dict[str, float]]:
    """XLA cost_analysis of ``fn`` lowered at ``args`` (ShapeDtypeStructs
    or concrete arrays): ``{"flops": ..., "bytes_accessed": ...}``.
    None when the backend cannot answer (stripped builds) — attribution
    is diagnostics and must never take down the run."""
    try:
        lowered = fn.lower(*args)
        ca = lowered.compile().cost_analysis()
        if isinstance(ca, list):
            ca = ca[0] if ca else {}
        ca = ca or {}
        return {"flops": float(ca.get("flops", 0.0) or 0.0),
                "bytes_accessed": float(ca.get("bytes accessed", 0.0)
                                        or 0.0)}
    except Exception:
        return None


def roofline_row(flops: float, bytes_accessed: float, *,
                 wall_s: Optional[float] = None, calls: int = 0,
                 peak_flops: Optional[float] = None,
                 peak_bytes_per_sec: Optional[float] = None) -> dict:
    """One attribution-table row. ``wall_s`` is the mean host-observed
    wall per call (None = program never timed); peaks in flops/s and
    bytes/s. ``bound`` names the binding roof at this intensity."""
    intensity = (flops / bytes_accessed) if bytes_accessed else None
    row = {
        "flops": flops,
        "bytes_accessed": bytes_accessed,
        "intensity_flops_per_byte": (round(intensity, 3)
                                     if intensity is not None else None),
        "calls": int(calls),
        "mean_wall_ms": (round(wall_s * 1e3, 4)
                         if wall_s is not None else None),
        "achieved_tflops": (round(flops / wall_s / 1e12, 4)
                            if wall_s else None),
        "achieved_gbps": (round(bytes_accessed / wall_s / 1e9, 3)
                          if wall_s else None),
        "attainable_tflops": None,
        "achieved_vs_attainable": None,
        "bound": None,
    }
    if intensity is not None and peak_flops and peak_bytes_per_sec:
        attainable = min(peak_flops, intensity * peak_bytes_per_sec)
        row["attainable_tflops"] = round(attainable / 1e12, 4)
        row["bound"] = ("compute" if attainable >= peak_flops
                        else "memory")
        if wall_s:
            row["achieved_vs_attainable"] = round(
                (flops / wall_s) / attainable, 4)
    elif intensity is not None and peak_flops and wall_s:
        # no bandwidth table (e.g. override-only setups): fall back to
        # plain MFU against the compute roof
        row["attainable_tflops"] = round(peak_flops / 1e12, 4)
        row["bound"] = "compute"
        row["achieved_vs_attainable"] = round(
            (flops / wall_s) / peak_flops, 4)
    return row


def accelerator_peaks() -> Tuple[Optional[float], Optional[float]]:
    """(peak flops/s, peak bytes/s) of the current accelerator, either
    None when unknown."""
    from deepspeed_tpu.accelerator import get_accelerator

    acc = get_accelerator()
    tf = acc.peak_tflops()
    bw = acc.peak_hbm_gbps()
    return (tf * 1e12 if tf else None), (bw * 1e9 if bw else None)


def attribution_table(programs: Dict[str, Tuple], *,
                      walls: Optional[Dict[str, Tuple[float, int]]] = None,
                      cache: Optional[Dict[str, dict]] = None) -> dict:
    """Roofline table over named programs.

    ``programs``: name -> (jit_fn, abstract_arg_tuple) — the registry a
    serving engine captured at warmup. ``walls``: name -> (total wall
    seconds, calls) host-observed. ``cache``: optional dict the caller
    owns; cost probes (one lower+compile each) are memoized into it so
    repeated reports are free."""
    peak_flops, peak_bw = accelerator_peaks()
    walls = walls or {}
    out: Dict[str, dict] = {}
    for name in sorted(programs):
        fn, args = programs[name]
        cost = None
        if cache is not None and name in cache:
            cost = cache[name]
        if cost is None:
            cost = program_cost(fn, args)
            if cache is not None and cost is not None:
                cache[name] = cost
        if cost is None:
            out[name] = {"error": "cost_analysis unavailable"}
            continue
        total_s, calls = walls.get(name, (0.0, 0))
        out[name] = roofline_row(
            cost["flops"], cost["bytes_accessed"],
            wall_s=(total_s / calls) if calls else None, calls=calls,
            peak_flops=peak_flops, peak_bytes_per_sec=peak_bw)
    return out
