"""Structured JSONL sink — the durable half of the telemetry subsystem.

One record per line, append-only, buffered host-side: a run produces a
single ``telemetry.jsonl`` that ``scripts/telemetry_report.py`` renders
into a human summary and downstream tooling can grep/stream. Record kinds:

  {"ts": ..., "kind": "scalar",   "tag": ..., "value": ..., "step": ...}
  {"ts": ..., "kind": "event",    "name": ..., **fields}
  {"ts": ..., "kind": "snapshot", "step": ..., "metrics": {...}}

``ts`` is wall-clock epoch seconds, stamped at write. Writes are buffered
(``flush_every`` records) so the hot loop pays a dict+list append, not a
syscall; ``flush()``/``close()`` drain. All I/O errors are swallowed after
a one-time warning — telemetry must never take down the job it observes.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import List, Optional


class JsonlSink:
    def __init__(self, path: str, flush_every: int = 64):
        self.path = path
        self.flush_every = max(int(flush_every), 1)
        self._buf: List[str] = []
        self._lock = threading.Lock()
        self._fh = None
        self._warned = False
        self.records_written = 0
        # dropped-data accounting (ISSUE 13 satellite): every record
        # this sink failed to durably write — serialization errors and
        # failed drains both — so a postmortem can state whether its
        # JSONL record is complete
        self.records_dropped = 0
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)

    def _warn_once(self, e: Exception) -> None:
        if not self._warned:
            self._warned = True
            from deepspeed_tpu.utils.logging import logger

            logger.warning(f"telemetry sink {self.path}: {type(e).__name__}: "
                           f"{e}; further records dropped (counted in "
                           f"telemetry/events_dropped)")

    def _note_dropped(self, n: int) -> None:
        self.records_dropped += n
        try:
            from deepspeed_tpu.telemetry.registry import get_registry

            get_registry().counter("telemetry/events_dropped").inc(n)
        except Exception:
            pass

    def write(self, record: dict) -> None:
        rec = dict(record)
        rec.setdefault("ts", time.time())
        try:
            line = json.dumps(rec, default=str)
        except Exception as e:
            self._warn_once(e)
            self._note_dropped(1)
            return
        with self._lock:
            self._buf.append(line)
            if len(self._buf) >= self.flush_every:
                self._drain_locked()

    def scalar(self, tag: str, value: float, step: int) -> None:
        """Monitor-event shape (the JSONL fourth writer goes through this)."""
        self.write({"kind": "scalar", "tag": tag, "value": value, "step": step})

    def _drain_locked(self) -> None:
        if not self._buf:
            return
        try:
            if self._fh is None:
                self._fh = open(self.path, "a")
            self._fh.write("\n".join(self._buf) + "\n")
            self._fh.flush()
            self.records_written += len(self._buf)
        except Exception as e:
            self._warn_once(e)
            self._note_dropped(len(self._buf))
        finally:
            self._buf.clear()

    def flush(self) -> None:
        with self._lock:
            self._drain_locked()

    def close(self) -> None:
        with self._lock:
            self._drain_locked()
            if self._fh is not None:
                try:
                    self._fh.close()
                except Exception:
                    pass
                self._fh = None

    def __enter__(self) -> "JsonlSink":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def read_jsonl(path: str, *, return_bad: bool = False):
    """Parse a telemetry JSONL file, tolerating a crash mid-write
    (ISSUE 9 satellite): a truncated or corrupt trailing line — torn
    JSON, undecodable bytes, a non-object value — is SKIPPED AND
    COUNTED, never raised, because the post-crash report must not fail
    on the very artifact needed to debug the crash. The file is read as
    bytes (a write torn inside a multi-byte UTF-8 sequence would make
    text-mode iteration itself raise) and each line decoded leniently.

    Returns the parsed records; with ``return_bad=True`` returns
    ``(records, n_bad_lines)`` so callers can surface the damage."""
    out: List[dict] = []
    bad = 0
    with open(path, "rb") as f:
        for raw in f:
            line = raw.decode("utf-8", errors="replace").strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                bad += 1
                continue
            if not isinstance(rec, dict):
                bad += 1
                continue
            out.append(rec)
    return (out, bad) if return_bad else out
