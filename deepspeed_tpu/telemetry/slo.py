"""SLO engine: declarative SLIs + multi-window multi-burn-rate alerting
(ISSUE 13).

The telemetry stack so far *describes* the system — counters, latency
histograms, span graphs, rooflines — but nothing *judges* it: there is
no notion of an objective being violated, so neither the on-call nor a
future autoscaler has a signal worth acting on. This module is that
judgment layer, built on the Google SRE Workbook's alerting discipline:

  * an **SLI** is a good-events / total-events ratio derived from the
    EXISTING metric stream (no new instrumentation on the hot path):
    a latency SLI counts histogram observations under a threshold
    ("TTFT <= 500ms"), an availability SLI divides two counters
    ("non-failed finishes / finishes"), a gauge SLI counts evaluation
    samples meeting a floor/ceiling ("MFU >= 0.4");
  * an **objective** turns the SLI into an error budget:
    ``budget = 1 - objective`` is the tolerable bad fraction;
  * a **burn rate** is how fast the budget is being spent:
    ``burn = bad_fraction(window) / budget`` — burn 1.0 exactly
    exhausts the budget over the SLO period, burn 14.4 exhausts a
    30-day budget in ~2 days;
  * an **alert rule** pages only when the burn exceeds its threshold
    over BOTH a short and a long window (multi-window multi-burn-rate:
    the short window gives fast detection and fast reset, the long
    window suppresses one-sample blips), e.g. 14.4x over 5m AND 1h ->
    page; 3x over 1h AND 6h -> warn.

Everything is evaluated HOST-SIDE on a caller-supplied clock: the
engines pass the same virtual ``now`` their serving loops run on, so a
FakeClock chaos run replays its alert timeline bit-for-bit — the
acceptance suite pins the fired/resolved sequence, not just counts.
Alerts emit typed events into the telemetry JSONL stream and a
subscriber-list seam (:meth:`SLOEngine.add_alert_callback`; the older
``set_alert_callback`` remains as a replace-all shim) that
``ReplicaSupervisor`` and the ISSUE 16 ``ElasticAutoscaler`` both
subscribe to — each subscriber individually immune to the others'
exceptions.

Window math: each :meth:`SLOEngine.evaluate` samples every SLI's
CUMULATIVE (good, total) counts from the registry and keeps a bounded
ring of ``(t, good, total)`` samples; the windowed bad fraction is the
difference against the newest sample at least ``window`` old (the
oldest sample when history is shorter — a young window is simply
shorter, never a fabricated zero). No locks, no device work, O(ring)
per evaluation.
"""

from __future__ import annotations

import dataclasses
import time
from bisect import bisect_right
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from deepspeed_tpu.telemetry.registry import MetricsRegistry, get_registry

SEVERITIES = ("page", "warn")
SLI_KINDS = ("latency", "availability", "gauge_floor", "gauge_ceiling")


class SLOConfigError(ValueError):
    """A malformed SLI/rule config — raised with EVERY problem listed
    (scripts/check_slo_rules.py renders them one per line), so a config
    author fixes the file in one round trip."""

    def __init__(self, errors: Sequence[str]):
        self.errors = list(errors)
        super().__init__("invalid SLO config:\n  " + "\n  ".join(self.errors))


@dataclasses.dataclass(frozen=True)
class SLI:
    """One service-level indicator over the existing metric stream.

    kind "latency": ``metric`` names a latency HISTOGRAM; an
        observation is good when it is <= ``threshold_ms`` (bucket
        upper bounds are the resolution — pick a threshold on or near
        a bucket edge for exact counting).
    kind "availability": ``good``/``bad`` name COUNTERS (``bad`` may be
        a list, summed — e.g. every ``fabric/shed_*`` class);
        total = good + bad.
    kind "gauge_floor"/"gauge_ceiling": ``metric`` names a GAUGE; each
        SLO evaluation contributes ONE sample, good when the gauge is
        >= ``floor`` (resp. <= ``ceiling``). An unset gauge contributes
        nothing.

    ``objective`` is the target good fraction in (0, 1);
    ``1 - objective`` is the error budget every burn rate divides by.
    """

    name: str
    kind: str
    objective: float
    metric: Optional[str] = None
    threshold_ms: Optional[float] = None
    good: Optional[str] = None
    bad: Optional[Tuple[str, ...]] = None
    floor: Optional[float] = None
    ceiling: Optional[float] = None
    description: str = ""


@dataclasses.dataclass(frozen=True)
class BurnRateRule:
    """Fire when ``sli``'s burn rate exceeds ``burn`` over BOTH windows.
    ``min_events`` gates on the long window's total event count so a
    near-empty service cannot page off its first bad request."""

    sli: str
    short_s: float
    long_s: float
    burn: float
    severity: str = "page"
    min_events: int = 10

    @property
    def name(self) -> str:
        return f"{self.sli}:{self.severity}:{self.burn:g}x"


@dataclasses.dataclass(frozen=True)
class SLOAlert:
    """One alert-state transition, delivered to the callback seam and
    (as an event) to the JSONL stream."""

    rule: str
    sli: str
    severity: str
    kind: str            # "fired" | "resolved"
    t: float
    burn_short: float
    burn_long: float
    budget_consumed: float


# --------------------------------------------------------------- defaults
# The serving-fabric SLO surface (README documents the semantics). The
# thresholds are deliberately loose: the standard bench traces must run
# alert-free (zero false alerts — pinned by tests), while a replica
# crash or overload burst blows well past them.
DEFAULT_SLO_CONFIG = {
    "slis": [
        {"name": "ttft_interactive", "kind": "latency",
         "metric": "serving/ttft_ms/p0", "threshold_ms": 1000.0,
         "objective": 0.99,
         "description": "interactive-class time-to-first-token"},
        {"name": "tpot", "kind": "latency", "metric": "serving/tpot_ms",
         "threshold_ms": 200.0, "objective": 0.99,
         "description": "per-output-token latency, all classes"},
        {"name": "queue_wait", "kind": "latency",
         "metric": "serving/queue_wait_ms", "threshold_ms": 2000.0,
         "objective": 0.95,
         "description": "admission queue wait incl. preempted time"},
        {"name": "availability", "kind": "availability",
         "good": "fabric/completed_requests",
         "bad": ["fabric/failed_requests", "fabric/rejected_requests"],
         "objective": 0.999,
         "description": "non-failed finishes across the fabric"},
        {"name": "train_mfu", "kind": "gauge_floor", "metric": "train/mfu",
         "floor": 0.30, "objective": 0.90,
         "description": "model-flops-utilization floor"},
        {"name": "train_anomaly_rate", "kind": "availability",
         "good": "train/steps",
         "bad": ["resilience/anomalies_nonfinite",
                 "resilience/anomalies_spike",
                 "resilience/anomalies_divergence",
                 "resilience/anomalies_sdc",
                 "resilience/anomalies_replay"],
         "objective": 0.99,
         "description": "training steps without an actionable anomaly"},
    ],
    "rules": [
        # the SRE Workbook ladder: fast-burn page, slow-burn warn
        {"sli": "ttft_interactive", "short_s": 300.0, "long_s": 3600.0,
         "burn": 14.4, "severity": "page"},
        {"sli": "ttft_interactive", "short_s": 3600.0, "long_s": 21600.0,
         "burn": 3.0, "severity": "warn"},
        {"sli": "tpot", "short_s": 300.0, "long_s": 3600.0,
         "burn": 14.4, "severity": "page"},
        {"sli": "queue_wait", "short_s": 3600.0, "long_s": 21600.0,
         "burn": 3.0, "severity": "warn"},
        {"sli": "availability", "short_s": 300.0, "long_s": 3600.0,
         "burn": 14.4, "severity": "page"},
        {"sli": "train_mfu", "short_s": 3600.0, "long_s": 21600.0,
         "burn": 3.0, "severity": "warn"},
        {"sli": "train_anomaly_rate", "short_s": 300.0, "long_s": 3600.0,
         "burn": 14.4, "severity": "page"},
    ],
}


# ------------------------------------------------------------- validation
def validate_slo_config(cfg: dict) -> List[str]:
    """Every problem in ``cfg``, as human-readable strings (empty list =
    valid). The classes scripts/check_slo_rules.py gates CI on:

      * unknown/duplicate SLI names, unknown kinds/severities;
      * missing per-kind fields (latency without a metric/threshold,
        availability without good/bad counters, gauge without a bound);
      * objectives outside (0, 1);
      * malformed windows (non-positive, or short >= long);
      * burn thresholds that can NEVER fire: the windowed bad fraction
        is at most 1.0, so any ``burn > 1 / (1 - objective)`` is
        structurally unreachable — a rule that looks armed but is dead.
    """
    errors: List[str] = []
    if not isinstance(cfg, dict):
        return [f"config must be a dict, got {type(cfg).__name__}"]
    slis = cfg.get("slis", [])
    rules = cfg.get("rules", [])
    if not isinstance(slis, list) or not isinstance(rules, list):
        return ["'slis' and 'rules' must be lists"]
    by_name: Dict[str, dict] = {}
    for i, s in enumerate(slis):
        where = f"slis[{i}]"
        if not isinstance(s, dict):
            errors.append(f"{where}: must be a dict")
            continue
        name = s.get("name")
        if not name or not isinstance(name, str):
            errors.append(f"{where}: missing 'name'")
            continue
        if name in by_name:
            errors.append(f"{where}: duplicate SLI name {name!r}")
        by_name[name] = s
        obj = s.get("objective")
        if not isinstance(obj, (int, float)) or not 0.0 < obj < 1.0:
            errors.append(f"{where} ({name}): objective must be in (0, 1), "
                          f"got {obj!r}")
        kind = s.get("kind")
        if kind not in SLI_KINDS:
            errors.append(f"{where} ({name}): unknown kind {kind!r} "
                          f"(one of {SLI_KINDS})")
            continue
        if kind == "latency":
            if not s.get("metric"):
                errors.append(f"{where} ({name}): latency SLI needs "
                              f"'metric' (a histogram name)")
            th = s.get("threshold_ms")
            if not isinstance(th, (int, float)) or th <= 0:
                errors.append(f"{where} ({name}): latency SLI needs a "
                              f"positive 'threshold_ms', got {th!r}")
        elif kind == "availability":
            if not s.get("good"):
                errors.append(f"{where} ({name}): availability SLI needs "
                              f"'good' (a counter name)")
            bad = s.get("bad")
            if not bad or not (isinstance(bad, str)
                               or (isinstance(bad, (list, tuple))
                                   and all(isinstance(b, str)
                                           for b in bad))):
                errors.append(f"{where} ({name}): availability SLI needs "
                              f"'bad' (a counter name or list of them)")
        else:  # gauge_floor / gauge_ceiling
            if not s.get("metric"):
                errors.append(f"{where} ({name}): gauge SLI needs "
                              f"'metric' (a gauge name)")
            bound = "floor" if kind == "gauge_floor" else "ceiling"
            if not isinstance(s.get(bound), (int, float)):
                errors.append(f"{where} ({name}): {kind} SLI needs a "
                              f"numeric '{bound}'")
    for i, r in enumerate(rules):
        where = f"rules[{i}]"
        if not isinstance(r, dict):
            errors.append(f"{where}: must be a dict")
            continue
        sli = r.get("sli")
        if sli not in by_name:
            errors.append(f"{where}: unknown SLI name {sli!r} "
                          f"(defined: {sorted(by_name) or 'none'})")
        sev = r.get("severity", "page")
        if sev not in SEVERITIES:
            errors.append(f"{where} ({sli}): unknown severity {sev!r} "
                          f"(one of {SEVERITIES})")
        short_s, long_s = r.get("short_s"), r.get("long_s")
        for fld, v in (("short_s", short_s), ("long_s", long_s)):
            if not isinstance(v, (int, float)) or v <= 0:
                errors.append(f"{where} ({sli}): {fld} must be a positive "
                              f"number, got {v!r}")
        if (isinstance(short_s, (int, float))
                and isinstance(long_s, (int, float))
                and 0 < long_s <= short_s):
            errors.append(f"{where} ({sli}): short window {short_s}s must "
                          f"be strictly inside the long window {long_s}s")
        burn = r.get("burn")
        if not isinstance(burn, (int, float)) or burn <= 0:
            errors.append(f"{where} ({sli}): burn must be a positive "
                          f"number, got {burn!r}")
        elif sli in by_name:
            obj = by_name[sli].get("objective")
            if isinstance(obj, (int, float)) and 0.0 < obj < 1.0:
                max_burn = 1.0 / (1.0 - obj)
                if burn > max_burn:
                    errors.append(
                        f"{where} ({sli}): burn {burn}x can never fire — "
                        f"bad fraction caps at 1.0, so the max reachable "
                        f"burn at objective {obj} is {max_burn:.4g}x")
        me = r.get("min_events", 10)
        if not isinstance(me, int) or me < 0:
            errors.append(f"{where} ({sli}): min_events must be a "
                          f"non-negative int, got {me!r}")
    return errors


def parse_slo_config(cfg: dict) -> Tuple[List[SLI], List[BurnRateRule]]:
    """Validate + materialize a config dict; raises
    :class:`SLOConfigError` listing EVERY problem on failure."""
    errors = validate_slo_config(cfg)
    if errors:
        raise SLOConfigError(errors)
    slis = []
    for s in cfg.get("slis", []):
        bad = s.get("bad")
        if isinstance(bad, str):
            bad = (bad,)
        elif bad is not None:
            bad = tuple(bad)
        slis.append(SLI(name=s["name"], kind=s["kind"],
                        objective=float(s["objective"]),
                        metric=s.get("metric"),
                        threshold_ms=s.get("threshold_ms"),
                        good=s.get("good"), bad=bad,
                        floor=s.get("floor"), ceiling=s.get("ceiling"),
                        description=s.get("description", "")))
    rules = [BurnRateRule(sli=r["sli"], short_s=float(r["short_s"]),
                          long_s=float(r["long_s"]), burn=float(r["burn"]),
                          severity=r.get("severity", "page"),
                          min_events=int(r.get("min_events", 10)))
             for r in cfg.get("rules", [])]
    return slis, rules


# ----------------------------------------------------------------- engine
class _SliState:
    """Per-SLI sample ring + lifetime accumulators. The ring is
    retained by AGE (every sample younger than the rules' longest
    window, plus the one older anchor the window diff needs), not by a
    fixed count — a count bound silently shortened the 6h windows to
    however long the ring happened to cover. ``cap`` is a hard safety
    bound against a pathological evaluation storm; past it the oldest
    samples go and the longest windows degrade toward the ring's span
    (documented, never silent truncation of the math itself)."""

    __slots__ = ("sli", "samples", "cap", "gauge_good", "gauge_total")

    def __init__(self, sli: SLI, cap: int):
        self.sli = sli
        # (t, cumulative_good, cumulative_total)
        self.samples: deque = deque()
        self.cap = max(int(cap), 4)
        # gauge SLIs synthesize their own cumulative counts (one
        # observation per evaluation)
        self.gauge_good = 0
        self.gauge_total = 0

    def prune(self, now: float, max_window: float) -> None:
        """Drop samples no window can anchor on: everything older than
        ``max_window`` EXCEPT the newest such sample (the long window's
        anchor must be the newest sample at least window old)."""
        cutoff = now - max_window
        samples = self.samples
        while len(samples) >= 2 and samples[1][0] <= cutoff:
            samples.popleft()
        while len(samples) > self.cap:
            samples.popleft()


class SLOEngine:
    """Evaluates SLIs + burn-rate rules against a metrics registry.

    Parameters
    ----------
    config: the declarative dict (see :data:`DEFAULT_SLO_CONFIG`);
        validated up front with typed errors.
    registry: the MetricsRegistry to read SLI inputs from (and emit
        alert events into). Defaults to the process-global registry.
    time_fn: fallback clock for :meth:`evaluate` / ``maybe_evaluate``
        called without an explicit ``now`` — the engines always pass
        their own (possibly virtual) clock instants, so chaos runs
        replay alert timelines deterministically.
    eval_interval_s: ``maybe_evaluate`` cadence gate (evaluations are
        cheap — a handful of dict reads — but sub-interval calls are
        pointless).
    max_samples_per_sli: HARD memory cap on each SLI's sample ring.
        Samples are normally retained by age — everything inside the
        rules' longest window (so the default 6h windows stay honest
        at any evaluation cadence); the cap only binds under an
        evaluation storm, where the oldest samples go and the longest
        windows degrade toward the ring's span.
    flight_recorder: optional
        :class:`~deepspeed_tpu.telemetry.flight_recorder.FlightRecorder`;
        every evaluation record lands in its alert ring, and a
        page-severity FIRE triggers a dump.
    """

    def __init__(self, config: Optional[dict] = None, *,
                 registry: Optional[MetricsRegistry] = None,
                 time_fn: Optional[Callable[[], float]] = None,
                 eval_interval_s: float = 1.0,
                 max_samples_per_sli: int = 100_000,
                 flight_recorder=None):
        slis, rules = parse_slo_config(
            DEFAULT_SLO_CONFIG if config is None else config)
        self.registry = registry if registry is not None else get_registry()
        self._time = time_fn or time.monotonic
        self.eval_interval_s = float(eval_interval_s)
        self.flight_recorder = flight_recorder
        self.slis: Dict[str, _SliState] = {
            s.name: _SliState(s, max_samples_per_sli) for s in slis}
        self.rules: List[BurnRateRule] = rules
        # age-based ring retention horizon: the longest rule window
        # (+5% slack so an anchor never ages out mid-evaluation)
        self._max_window = max((r.long_s for r in rules),
                               default=0.0) * 1.05
        self._firing: Dict[str, bool] = {r.name: False for r in rules}
        self._callbacks: List[Callable[[SLOAlert], None]] = []
        self._last_eval: Optional[float] = None
        self.evaluations = 0
        self.alerts: List[SLOAlert] = []     # full fired/resolved history

    # -------------------------------------------------------------- seams
    def add_alert_callback(self,
                           cb: Callable[[SLOAlert], None]) -> None:
        """Subscribe ``cb`` to every alert transition (ISSUE 16: the
        supervisor AND the autoscaler both listen — fan-out lives here,
        not in the callers). Delivery order is subscription order;
        duplicate subscriptions are idempotent. Each subscriber's
        exceptions are swallowed INDIVIDUALLY: one broken pager must
        neither take down the serving loop nor starve the subscribers
        behind it."""
        if cb not in self._callbacks:
            self._callbacks.append(cb)

    def remove_alert_callback(self,
                              cb: Callable[[SLOAlert], None]) -> None:
        """Unsubscribe; unknown callbacks are ignored."""
        if cb in self._callbacks:
            self._callbacks.remove(cb)

    def set_alert_callback(self,
                           cb: Optional[Callable[[SLOAlert], None]]) -> None:
        """Pre-ISSUE-16 single-subscriber shim: REPLACES the whole
        subscriber list (``None`` clears it), preserving the original
        set-and-overwrite semantics for existing call sites. New code
        uses :meth:`add_alert_callback`."""
        self._callbacks = [] if cb is None else [cb]

    def inject_alert(self, alert: SLOAlert) -> None:
        """Chaos seam (ISSUE 16): deliver a SYNTHETIC alert transition
        through the same emit path real evaluations use — events,
        subscriber fan-out, flight-recorder trigger — without touching
        the burn-rate state machine (``firing()`` is unaffected, and a
        later real evaluation is not confused by the injection). The
        twin's alert-storm injector drives this to prove autoscaler
        hysteresis/cooldown survive pathological alert flapping."""
        self.alerts.append(alert)
        self._emit(alert)

    # ----------------------------------------------------------- sampling
    def _cumulative(self, st: _SliState) -> Tuple[float, float]:
        """This instant's lifetime (good, total) event counts for one
        SLI, read from the registry (gauge SLIs: the synthesized
        per-evaluation sample counters)."""
        s = st.sli
        if s.kind == "latency":
            h = self.registry._histograms.get(s.metric)
            if h is None or h.count == 0:
                return 0.0, 0.0
            n_good_buckets = bisect_right(h.buckets, s.threshold_ms)
            good = float(sum(h.counts[:n_good_buckets]))
            return good, float(h.count)
        if s.kind == "availability":
            cs = self.registry._counters
            good = float(cs[s.good].value) if s.good in cs else 0.0
            bad = float(sum(cs[b].value for b in s.bad if b in cs))
            return good, good + bad
        # gauge_floor / gauge_ceiling: one observation per evaluation
        g = self.registry._gauges.get(s.metric)
        if g is not None and g.value is not None:
            v = float(g.value)
            ok = (v >= s.floor) if s.kind == "gauge_floor" \
                else (v <= s.ceiling)
            st.gauge_total += 1
            if ok:
                st.gauge_good += 1
        return float(st.gauge_good), float(st.gauge_total)

    def _window(self, st: _SliState, now: float,
                window_s: float) -> Tuple[Optional[float], float]:
        """(bad_fraction, total_events) over the trailing window: the
        newest sample at least ``window_s`` old anchors the diff (the
        oldest sample when history is shorter). None = no events in
        the window — distinct from a clean 0.0."""
        if not st.samples:
            return None, 0.0
        samples = st.samples
        newest = samples[-1]
        anchor = samples[0]
        cutoff = now - window_s
        if anchor[0] <= cutoff:
            # the window starts inside the ring: find the newest sample
            # at least window_s old, scanning from whichever end the
            # cutoff is nearer (the ring is retained to the LONGEST
            # rule window, so that window's anchor lives near the old
            # end — a right-to-left scan there would walk everything)
            if cutoff - anchor[0] <= newest[0] - cutoff:
                for s in samples:
                    if s[0] > cutoff:
                        break
                    anchor = s
            else:
                for s in reversed(samples):
                    if s[0] <= cutoff:
                        anchor = s
                        break
        good = newest[1] - anchor[1]
        total = newest[2] - anchor[2]
        if total <= 0:
            return None, 0.0
        return max(1.0 - good / total, 0.0), total

    def budget_consumed(self, sli_name: str) -> Optional[float]:
        """Lifetime error-budget consumption for one SLI: bad fraction
        since the engine started, divided by the budget. 1.0 = the
        whole budget is gone; None = no events yet."""
        st = self.slis.get(sli_name)
        if st is None or not st.samples:
            return None
        _, good, total = st.samples[-1]
        if total <= 0:
            return None
        bad_frac = max(1.0 - good / total, 0.0)
        return bad_frac / (1.0 - st.sli.objective)

    # --------------------------------------------------------- evaluation
    def maybe_evaluate(self, now: Optional[float] = None) -> List[SLOAlert]:
        """Interval-gated :meth:`evaluate` — the engines call this once
        per serving iteration / sentinel fence."""
        if now is None:
            now = self._time()
        if (self._last_eval is not None
                and now - self._last_eval < self.eval_interval_s):
            return []
        return self.evaluate(now)

    def evaluate(self, now: Optional[float] = None) -> List[SLOAlert]:
        """Sample every SLI, evaluate every rule, emit alert
        transitions. Returns the transitions that happened THIS
        evaluation (also appended to :attr:`alerts`)."""
        if now is None:
            now = self._time()
        self._last_eval = now
        self.evaluations += 1
        for st in self.slis.values():
            good, total = self._cumulative(st)
            if not st.samples:
                # implicit zero baseline: before the engine existed
                # there were no events, so the first evaluation's
                # window covers everything observed so far
                st.samples.append((now, 0.0, 0.0))
            st.samples.append((now, good, total))
            st.prune(now, self._max_window)
        transitions: List[SLOAlert] = []
        rule_stats: Dict[str, dict] = {}
        for rule in self.rules:
            st = self.slis[rule.sli]
            budget = 1.0 - st.sli.objective
            bad_s, _ = self._window(st, now, rule.short_s)
            bad_l, total_l = self._window(st, now, rule.long_s)
            burn_s = (bad_s / budget) if bad_s is not None else 0.0
            burn_l = (bad_l / budget) if bad_l is not None else 0.0
            breached = (bad_s is not None and bad_l is not None
                        and burn_s >= rule.burn and burn_l >= rule.burn
                        and total_l >= rule.min_events)
            rule_stats[rule.name] = {
                "burn_short": round(burn_s, 4),
                "burn_long": round(burn_l, 4),
                "firing": breached}
            was = self._firing[rule.name]
            if breached == was:
                continue
            self._firing[rule.name] = breached
            alert = SLOAlert(
                rule=rule.name, sli=rule.sli, severity=rule.severity,
                kind="fired" if breached else "resolved", t=now,
                burn_short=round(burn_s, 4), burn_long=round(burn_l, 4),
                budget_consumed=round(
                    self.budget_consumed(rule.sli) or 0.0, 4))
            transitions.append(alert)
            self.alerts.append(alert)
            self._emit(alert)
        self._stream_eval(now, rule_stats)
        return transitions

    def _emit(self, alert: SLOAlert) -> None:
        fields = dataclasses.asdict(alert)
        # the record's "kind" is the JSONL discriminator ("event") —
        # the alert's own kind rides as "transition"
        fields["transition"] = fields.pop("kind")
        if alert.kind == "fired":
            self.registry.event("slo/alert_fired", **fields)
        else:
            self.registry.event("slo/alert_resolved", **fields)
        for cb in list(self._callbacks):
            try:
                cb(alert)
            except Exception:  # a broken subscriber must not stop serving
                pass           # — nor starve the subscribers after it
        if self.flight_recorder is not None and alert.kind == "fired" \
                and alert.severity == "page":
            self.flight_recorder.trigger(
                "slo_page", rule=alert.rule, sli=alert.sli, t=alert.t,
                burn_short=alert.burn_short, burn_long=alert.burn_long)

    def _stream_eval(self, now: float, rule_stats: Dict[str, dict]) -> None:
        """One ``{"kind": "slo_eval"}`` record per evaluation: the
        burn-rate timeline the report's slo section renders, and the
        flight recorder's last-N-evaluations ring entry."""
        rec = {
            "kind": "slo_eval", "t": now,
            "rules": rule_stats,
            "budget_consumed": {
                name: round(c, 4)
                for name in self.slis
                if (c := self.budget_consumed(name)) is not None},
        }
        if self.flight_recorder is not None:
            self.flight_recorder.note_alert(rec)
        sink = self.registry.sink
        if sink is not None:
            try:
                sink.write(rec)
            except Exception:
                pass

    # ------------------------------------------------------------ queries
    def firing(self) -> List[str]:
        """Rule names currently in the firing state."""
        return [name for name, on in self._firing.items() if on]

    def __repr__(self):
        return (f"SLOEngine(slis={sorted(self.slis)}, "
                f"rules={len(self.rules)}, evaluations={self.evaluations}, "
                f"firing={self.firing()})")
