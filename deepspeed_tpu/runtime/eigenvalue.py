"""Power-iteration curvature estimation (reference
``deepspeed/runtime/eigenvalue.py:149 Eigenvalue``): estimate the largest
Hessian eigenvalue per layer block to drive MoQ's adaptive quantization
schedule (layers with high curvature quantize later).

The reference runs torch autograd twice per iteration; here the
Hessian-vector product is ``jax.jvp`` over ``jax.grad`` — exact HVPs with
one compiled program, iterated with ``lax.fori_loop``.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Tuple

import jax
import jax.numpy as jnp


def hvp(loss_fn: Callable, params, vec):
    """Hessian-vector product at ``params`` along ``vec`` (same pytree)."""
    return jax.jvp(jax.grad(loss_fn), (params,), (vec,))[1]


class Eigenvalue:
    def __init__(self, verbose: bool = False, max_iter: int = 100,
                 tol: float = 1e-2, stability: float = 1e-6,
                 gas_boundary_resolution: int = 1,
                 layer_name: str = "blocks", layer_num: int = 0):
        self.max_iter = max_iter
        self.tol = tol
        self.stability = stability
        self.verbose = verbose
        # reference-config passthroughs (engine wiring)
        self.gas_boundary_resolution = gas_boundary_resolution
        self.layer_name = layer_name
        self.layer_num = layer_num

    def compute_eigenvalue(self, loss_fn: Callable, params, rng=None):
        """Largest |eigenvalue| of the Hessian of ``loss_fn`` at ``params``
        by power iteration (reference compute_eigenvalue)."""
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        leaves, treedef = jax.tree_util.tree_flatten(params)
        keys = jax.random.split(rng, len(leaves))
        v = treedef.unflatten([
            jax.random.normal(k, l.shape, jnp.float32)
            for k, l in zip(keys, leaves)])

        def norm(tree):
            return jnp.sqrt(sum(jnp.sum(x.astype(jnp.float32) ** 2)
                                for x in jax.tree_util.tree_leaves(tree)))

        def normalize(tree):
            n = norm(tree) + self.stability
            return jax.tree_util.tree_map(lambda x: x / n, tree), n

        def cond(carry):
            i, _, prev_ev, ev = carry
            rel = jnp.abs(ev - prev_ev) / jnp.maximum(jnp.abs(ev),
                                                      self.stability)
            return (i < self.max_iter) & ((i < 2) | (rel > self.tol))

        # carry = (iter, vector, prev_ev, ev); converge at |Δev|/|ev| < tol
        @jax.jit
        def run(v):
            def body(carry):
                i, v, _, ev = carry
                v, _ = normalize(v)
                hv = hvp(loss_fn, params, v)
                ev_new = sum(
                    jnp.sum(a.astype(jnp.float32) * b.astype(jnp.float32))
                    for a, b in zip(jax.tree_util.tree_leaves(v),
                                    jax.tree_util.tree_leaves(hv)))
                return i + 1, hv, ev, ev_new

            _, _, _, ev = jax.lax.while_loop(
                cond, body, (jnp.zeros((), jnp.int32), v,
                             jnp.zeros(()), jnp.zeros(())))
            return jnp.abs(ev)

        return float(jax.device_get(run(v)))

    def compute_layer_eigenvalues(self, loss_fn: Callable, params,
                                  layer_key: str = "blocks",
                                  rng=None) -> Dict[int, float]:
        """Per-layer eigenvalues for a scanned-blocks model: the Hessian is
        restricted to one layer's slice at a time (reference computes one
        eigenvalue per injected layer block)."""
        blocks = params[layer_key]
        num_layers = jax.tree_util.tree_leaves(blocks)[0].shape[0]
        out = {}
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        for i in range(num_layers):
            sub = jax.tree_util.tree_map(lambda x: x[i], blocks)

            def layer_loss(layer_params, i=i):
                patched = dict(params)
                patched[layer_key] = jax.tree_util.tree_map(
                    lambda full, one: full.at[i].set(one), blocks, layer_params)
                return loss_fn(patched)

            rng, sub_rng = jax.random.split(rng)
            out[i] = Eigenvalue(max_iter=self.max_iter,
                                tol=self.tol).compute_eigenvalue(
                layer_loss, sub, sub_rng)
        return out

    def post_process(self, eigenvalues: Dict[int, float]) -> Dict[int, float]:
        """Replace non-finite entries with the max (reference post_process:
        a failed layer inherits the most conservative schedule)."""
        vals = [v for v in eigenvalues.values() if jnp.isfinite(v)]
        mx = max(vals) if vals else 1.0
        return {k: (v if jnp.isfinite(v) else mx)
                for k, v in eigenvalues.items()}
