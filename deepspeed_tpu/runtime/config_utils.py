"""Typed-config helpers — analog of reference ``deepspeed/runtime/config_utils.py``
(DeepSpeedConfigModel and dict utilities), built on pydantic v1/v2 compat.
"""

from __future__ import annotations

from typing import Any, Dict

try:  # pydantic v2
    from pydantic import BaseModel, ConfigDict

    _PYDANTIC_V2 = True
except ImportError:  # pragma: no cover
    from pydantic import BaseModel  # type: ignore

    _PYDANTIC_V2 = False


class DeepSpeedConfigModel(BaseModel):
    """Base for all config sections: unknown keys warn instead of erroring
    (matching the reference's forward-compat behaviour)."""

    if _PYDANTIC_V2:
        model_config = ConfigDict(extra="allow", validate_assignment=True,
                                  populate_by_name=True, protected_namespaces=())
    else:  # pragma: no cover
        class Config:
            extra = "allow"
            validate_assignment = True
            allow_population_by_field_name = True

    def __init__(self, strict: bool = False, **data):
        # Drop keys explicitly set to "auto" unless the field declares support.
        super().__init__(**data)

    def dict_repr(self) -> Dict[str, Any]:
        if _PYDANTIC_V2:
            return self.model_dump()
        return self.dict()  # pragma: no cover


def get_scalar_param(param_dict: Dict, param_name: str, param_default_value):
    return param_dict.get(param_name, param_default_value)


def get_dict_param(param_dict: Dict, param_name: str, param_default_value):
    return param_dict.get(param_name, param_default_value)


def dict_raise_error_on_duplicate_keys(ordered_pairs):
    """json.load object_pairs_hook that rejects duplicate keys
    (reference config_utils.dict_raise_error_on_duplicate_keys)."""
    d = dict(ordered_pairs)
    if len(d) != len(ordered_pairs):
        counter = {}
        for k, _ in ordered_pairs:
            counter[k] = counter.get(k, 0) + 1
        keys = [k for k, v in counter.items() if v > 1]
        raise ValueError(f"Duplicate keys in DeepSpeed config: {keys}")
    return d


class ScientificNotationEncoder:
    pass  # placeholder for config printing parity; json handles floats fine
