"""Master config system.

TPU-native analog of reference ``deepspeed/runtime/config.py`` (DeepSpeedConfig,
config.py:674): one JSON/dict config parsed once into ~20 typed sub-configs and
threaded through every layer. Key names match the reference schema so existing
DeepSpeed JSON files load unchanged; TPU-only sections (``tensor_parallel``,
``sequence_parallel``, mesh overrides) extend it.

The batch-size triple is solved with the same arithmetic as the reference's
``_set_batch_related_parameters`` (config.py:904):
    train_batch_size == micro_batch_per_device * gradient_accumulation_steps * dp_world
"""

from __future__ import annotations

import copy
import json
import os
from typing import Any, Dict, Optional, Union

from deepspeed_tpu.comm.config import CommsLoggerConfig
from deepspeed_tpu.monitor.config import DeepSpeedMonitorConfig, get_monitor_config
from deepspeed_tpu.profiling.config import (
    DeepSpeedFlopsProfilerConfig,
    get_flops_profiler_config,
)
from deepspeed_tpu.runtime import constants as C
from deepspeed_tpu.runtime.config_utils import (
    DeepSpeedConfigModel,
    dict_raise_error_on_duplicate_keys,
    get_scalar_param,
)
from deepspeed_tpu.runtime.zero.config import DeepSpeedZeroConfig, ZeroStageEnum
from deepspeed_tpu.telemetry.config import TelemetryConfig, get_telemetry_config
from deepspeed_tpu.utils.logging import logger


class DeepSpeedConfigError(Exception):
    pass


class FP16Config(DeepSpeedConfigModel):
    enabled: bool = False
    auto_cast: bool = False
    loss_scale: float = 0.0
    initial_scale_power: int = 16
    loss_scale_window: int = 1000
    hysteresis: int = 2
    min_loss_scale: float = 1.0
    fp16_master_weights_and_grads: bool = False


class BF16Config(DeepSpeedConfigModel):
    enabled: bool = False


class OptimizerConfig(DeepSpeedConfigModel):
    type: Optional[str] = None
    params: Dict[str, Any] = {}
    legacy_fusion: bool = False


class SchedulerConfig(DeepSpeedConfigModel):
    type: Optional[str] = None
    params: Dict[str, Any] = {}


class ActivationCheckpointingConfig(DeepSpeedConfigModel):
    """Reference schema (runtime/activation_checkpointing/config.py) mapped to
    remat policies: ``partition_activations`` → save-nothing policy over the
    model axis, ``cpu_checkpointing`` → offload policy."""

    partition_activations: bool = False
    cpu_checkpointing: bool = False
    contiguous_memory_optimization: bool = False
    number_checkpoints: Optional[int] = None
    synchronize_checkpoint_boundary: bool = False
    profile: bool = False
    # TPU-native: named jax.checkpoint policy ("nothing", "dots", "dots_no_batch",
    # "everything", "offload_dots")
    policy: Optional[str] = None


class TensorParallelConfig(DeepSpeedConfigModel):
    tp_size: int = 1
    autotp: bool = False


class PipelineConfig(DeepSpeedConfigModel):
    stages: int = 1
    partition_method: str = "parameters"
    activation_checkpoint_interval: int = 0
    micro_batches: Optional[int] = None
    # "spmd": whole schedule compiled into one XLA program (default;
    #   GPipe-shaped backward via autodiff — remat bounds memory).
    # "host_1f1b": host-driven interpreter of the TrainSchedule instruction
    #   stream over per-stage jitted functions; activation memory bounded by
    #   num_pipe_buffers (pipeline depth), the reference's 1F1B profile
    #   (runtime/pipe/engine.py:1287 _exec_schedule analog).
    executor: str = "spmd"


class SequenceParallelConfig(DeepSpeedConfigModel):
    sp_size: int = 1
    mode: str = "ring"  # "ring" | "ulysses"


class ExpertParallelConfig(DeepSpeedConfigModel):
    ep_size: int = 1


class PLDConfig(DeepSpeedConfigModel):
    """Progressive layer drop (reference progressive_layer_drop section)."""

    enabled: bool = False
    theta: float = 0.5
    gamma: float = 0.001


class HybridEngineConfig(DeepSpeedConfigModel):
    """RLHF hybrid engine (reference deepspeed/runtime/config.py
    hybrid_engine section → DeepSpeedHybridEngine)."""

    enabled: bool = False
    max_out_tokens: int = 512
    inference_tp_size: int = 1
    release_inference_cache: bool = False
    pin_parameters: bool = True
    tp_gather_partition_size: int = 8


class ResilienceConfig(DeepSpeedConfigModel):
    """Training anomaly sentinel + auto-recovery (ISSUE 10).

    ``enabled`` turns on the sentinel (rolling robust z-score monitor over
    loss/grad-norm, read at the telemetry fences) and — when
    ``checkpoint_dir`` is set and the engine owns its dataloader — the
    PaLM-style rewind-and-skip recovery protocol. ``check_finite_grads``
    is independently usable: it adds a device-side skip-and-count guard on
    nonfinite grads to the bf16/fp32 step, mirroring the fp16
    dynamic-loss-scale overflow semantics (default: follows ``enabled``).
    """

    enabled: bool = False
    # None → follows `enabled`; True/False forces the guard on/off
    check_finite_grads: Optional[bool] = None
    # auto-recovery: where the engine saves/rewinds checkpoints; interval
    # in global steps (0 = caller manages saves; rewind still works off
    # whatever tags exist under checkpoint_dir)
    checkpoint_dir: Optional[str] = None
    checkpoint_interval: int = 0
    # sentinel read cadence in steps; 0 = ride the telemetry fence
    # (telemetry.sync_interval) when telemetry is on, else every step
    check_interval: int = 0
    # rolling robust z-score monitor
    window: int = 64
    min_history: int = 8
    spike_zscore: float = 8.0
    divergence_patience: int = 4
    # PaLM-style skip: batches between the rewind target and the anomaly
    # are skipped, plus an extra width (in steps) that escalates
    # base*factor^(k-1) across back-to-back rewinds, capped at max
    skip_width_base: int = 1
    skip_width_factor: int = 2
    skip_width_max: int = 64
    # rewind budget: ElasticAgent rolling-window semantics — only rewinds
    # inside the trailing window count; None window counts forever
    max_rewinds: int = 8
    rewind_window_s: Optional[float] = None
    # SDC audits, in global steps (0 = off)
    sdc_audit_interval: int = 0
    step_replay_interval: int = 0
    # "recover" (rewind+skip when possible, else raise) | "raise"
    on_anomaly: str = "recover"


class CheckpointConfig(DeepSpeedConfigModel):
    tag_validation: str = "Warn"
    load_universal: bool = False
    use_node_local_storage: bool = False
    parallel_write_pipeline: bool = False
    # background-thread persistence (reference Nebula async service analog)
    async_save: bool = False


class DataloaderConfig(DeepSpeedConfigModel):
    drop_last: bool = False


class AIOConfig(DeepSpeedConfigModel):
    """Host-swap engine knobs (the reference's aio section for ZeRO-Infinity)."""

    block_size: int = 1048576
    queue_depth: int = 8
    thread_count: int = 1
    single_submit: bool = False
    overlap_events: bool = True


def _read_config_dict(config: Union[str, dict]) -> dict:
    if isinstance(config, dict):
        return copy.deepcopy(config)
    if isinstance(config, str):
        if not os.path.exists(config):
            raise DeepSpeedConfigError(f"config path does not exist: {config}")
        with open(config) as f:
            return json.load(f, object_pairs_hook=dict_raise_error_on_duplicate_keys)
    raise DeepSpeedConfigError(f"unsupported config type {type(config)}")


def _deep_merge(base: dict, overrides: dict) -> None:
    for k, v in overrides.items():
        if isinstance(v, dict) and isinstance(base.get(k), dict):
            _deep_merge(base[k], v)
        else:
            base[k] = v


def _apply_autotuning_overrides(param_dict: dict) -> None:
    """Autotuning experiment contract: a child launched by the CLI autotuner
    (autotuning/cli.py) gets config overrides via DSTPU_AUTOTUNING_CONFIG
    (reference: the autotuner rewrites the ds_config per experiment,
    autotuning/autotuner.py)."""
    path = os.environ.get("DSTPU_AUTOTUNING_CONFIG")
    if not path:
        return
    with open(path) as f:
        overrides = json.load(f)
    _deep_merge(param_dict, overrides)
    # micro-batch overrides re-solve the batch triple: drop a stale total so
    # train_batch = micro * gas * dp is recomputed
    if "train_micro_batch_size_per_gpu" in overrides:
        param_dict.pop("train_batch_size", None)


class DeepSpeedConfig:
    """Parsed, validated config tree (reference DeepSpeedConfig, config.py:674)."""

    def __init__(self, config: Union[str, dict], mpu=None, world_size: Optional[int] = None):
        self._param_dict = _read_config_dict(config)
        _apply_autotuning_overrides(self._param_dict)
        d = self._param_dict

        # ---------------- parallel degrees (needed for batch arithmetic) ------
        self.tensor_parallel = TensorParallelConfig(**d.get(C.TENSOR_PARALLEL, {}))
        self.pipeline = PipelineConfig(**d.get(C.PIPELINE, {})) if isinstance(
            d.get(C.PIPELINE, {}), dict) else PipelineConfig()
        self.sequence_parallel = SequenceParallelConfig(**d.get(C.SEQUENCE_PARALLEL, {}))
        self.expert_parallel = ExpertParallelConfig(
            **({"ep_size": d[C.EXPERT_PARALLEL_SIZE]} if C.EXPERT_PARALLEL_SIZE in d else {}))

        if world_size is None:
            try:
                import jax

                world_size = jax.device_count()
            except Exception:
                world_size = 1
        self.world_size = world_size
        denom = (self.tensor_parallel.tp_size * self.pipeline.stages *
                 self.sequence_parallel.sp_size)
        self.data_parallel_size = max(world_size // max(denom, 1), 1)

        # ---------------- batch triple ---------------------------------------
        self.train_batch_size = d.get(C.TRAIN_BATCH_SIZE)
        self.train_micro_batch_size_per_gpu = d.get(C.TRAIN_MICRO_BATCH_SIZE_PER_GPU)
        self.gradient_accumulation_steps = d.get(C.GRADIENT_ACCUMULATION_STEPS)
        self._set_batch_related_parameters()

        # ---------------- precision ------------------------------------------
        self.fp16_config = FP16Config(**d.get(C.FP16, {}))
        bf16_dict = d.get(C.BFLOAT16, d.get(C.BFLOAT16_OLD, {}))
        self.bf16_config = BF16Config(**bf16_dict)
        self.fp16_enabled = self.fp16_config.enabled
        self.bfloat16_enabled = self.bf16_config.enabled
        if self.fp16_enabled and self.bfloat16_enabled:
            raise DeepSpeedConfigError("fp16 and bf16 cannot both be enabled")
        # grad-accumulation dtype (reference "data_types": {"grad_accum_dtype"}
        # — config.py get_data_types): fp32 (default) or bf16; bf16 halves
        # the accumulation buffer (the difference between fitting and
        # OOMing a 774M full step on one 16 GB chip)
        gad = d.get("data_types", {}).get("grad_accum_dtype")
        if gad not in (None, "fp32", "bf16"):
            raise DeepSpeedConfigError(
                f"data_types.grad_accum_dtype must be fp32 or bf16, got {gad!r}")
        self.grad_accum_dtype = gad or "fp32"
        self.gradient_clipping = float(d.get(C.GRADIENT_CLIPPING, C.GRADIENT_CLIPPING_DEFAULT))
        self.prescale_gradients = d.get(C.PRESCALE_GRADIENTS, C.PRESCALE_GRADIENTS_DEFAULT)
        self.gradient_predivide_factor = d.get(
            C.GRADIENT_PREDIVIDE_FACTOR, C.GRADIENT_PREDIVIDE_FACTOR_DEFAULT)

        # ---------------- optimizer / scheduler -------------------------------
        opt_dict = d.get(C.OPTIMIZER, {})
        self.optimizer = OptimizerConfig(**opt_dict) if opt_dict else None
        self.optimizer_name = self.optimizer.type.lower() if self.optimizer and \
            self.optimizer.type else None
        self.optimizer_params = self.optimizer.params if self.optimizer else {}
        sched_dict = d.get(C.SCHEDULER, {})
        self.scheduler = SchedulerConfig(**sched_dict) if sched_dict else None
        self.scheduler_name = self.scheduler.type if self.scheduler else None
        self.scheduler_params = self.scheduler.params if self.scheduler else {}

        # ---------------- zero ------------------------------------------------
        self.zero_config = DeepSpeedZeroConfig(**d.get("zero_optimization", {}))
        self.zero_optimization_stage = int(self.zero_config.stage)
        self.zero_enabled = self.zero_optimization_stage > 0
        self.zero_allow_untested_optimizer = d.get(
            C.ZERO_ALLOW_UNTESTED_OPTIMIZER, C.ZERO_ALLOW_UNTESTED_OPTIMIZER_DEFAULT)

        # ---------------- subsystems -----------------------------------------
        self.activation_checkpointing_config = ActivationCheckpointingConfig(
            **d.get(C.ACTIVATION_CHECKPOINTING, {}))
        self.monitor_config: DeepSpeedMonitorConfig = get_monitor_config(d)
        self.telemetry_config: TelemetryConfig = get_telemetry_config(d)
        self.flops_profiler_config: DeepSpeedFlopsProfilerConfig = get_flops_profiler_config(d)
        self.comms_logger_config = CommsLoggerConfig(**d.get("comms_logger", {}))
        self.checkpoint_config = CheckpointConfig(**d.get(C.CHECKPOINT, {}))
        self.resilience_config = ResilienceConfig(**d.get(C.RESILIENCE, {}))
        self.aio_config = AIOConfig(**d.get("aio", {}))
        self.hybrid_engine = HybridEngineConfig(**d.get("hybrid_engine", {}))
        self.pld_config = PLDConfig(**d.get("progressive_layer_drop", {}))
        # random-LTD token routing (reference config shape:
        # data_efficiency.data_routing.random_ltd — data_pipeline/config.py).
        # Reference gating is the INNER flag only (the reference's
        # get_random_ltd reads random_ltd.enabled directly); requiring the
        # outer data_efficiency/data_routing 'enabled' flags silently
        # disabled configs the reference would run — warn on the
        # contradiction instead of resolving it quietly.
        de = d.get("data_efficiency", {})
        dr = de.get("data_routing", {})
        rltd = dr.get("random_ltd", {})
        self.random_ltd_enabled = bool(rltd.get("enabled", False))
        if self.random_ltd_enabled and not (
                bool(de.get("enabled", True))
                and bool(dr.get("enabled", True))):
            logger.warning(
                "random_ltd.enabled is true but an outer data_efficiency/"
                "data_routing 'enabled' flag is false; matching reference "
                "semantics the inner flag governs — random-LTD stays "
                "ENABLED (drop the inner flag to disable it)")
        self.random_ltd_params = rltd
        # legacy curriculum learning (reference config.py
        # curriculum_enabled_legacy; engine.py:1653 injects curriculum_seqlen)
        cl = d.get("curriculum_learning", {})
        self.curriculum_enabled_legacy = bool(cl.get("enabled", False))
        self.curriculum_params_legacy = {k: v for k, v in cl.items()
                                         if k != "enabled"}
        self.dataloader_drop_last = d.get(C.DATALOADER_DROP_LAST, C.DATALOADER_DROP_LAST_DEFAULT)

        # ---------------- misc ------------------------------------------------
        self.steps_per_print = d.get(C.STEPS_PER_PRINT, C.STEPS_PER_PRINT_DEFAULT)
        # monitor cadence decoupled from print cadence; 0 (default) keeps the
        # legacy coupling (monitor writes fire with steps_per_print)
        self.monitor_interval = int(d.get(C.MONITOR_INTERVAL,
                                          C.MONITOR_INTERVAL_DEFAULT))
        self.wall_clock_breakdown = d.get(C.WALL_CLOCK_BREAKDOWN, C.WALL_CLOCK_BREAKDOWN_DEFAULT)
        self.memory_breakdown = d.get(C.MEMORY_BREAKDOWN, C.MEMORY_BREAKDOWN_DEFAULT)
        self.dump_state = d.get(C.DUMP_STATE, C.DUMP_STATE_DEFAULT)
        self.seed = d.get(C.SEED, C.SEED_DEFAULT)
        self.communication_data_type = d.get(
            C.COMMUNICATION_DATA_TYPE, C.COMMUNICATION_DATA_TYPE_DEFAULT)
        self.disable_allgather = d.get(C.DISABLE_ALLGATHER, C.DISABLE_ALLGATHER_DEFAULT)
        self.load_universal_checkpoint = d.get(
            C.LOAD_UNIVERSAL_CHECKPOINT, C.LOAD_UNIVERSAL_CHECKPOINT_DEFAULT)
        self.elasticity_enabled = bool(d.get(C.ELASTICITY, {}).get("enabled", False))

        # MoE section (layer-level config like the reference, plus global ep_size)
        self.moe_param_dict = d.get("moe", {})

        self._do_sanity_check()

    # --- reference config.py:904 _set_batch_related_parameters, same logic ----
    def _set_batch_related_parameters(self):
        train_batch = self.train_batch_size
        micro_batch = self.train_micro_batch_size_per_gpu
        grad_acc = self.gradient_accumulation_steps
        dp = self.data_parallel_size

        if train_batch is not None and micro_batch is not None and grad_acc is not None:
            pass
        elif train_batch is not None and micro_batch is not None:
            grad_acc = train_batch // micro_batch
            grad_acc //= dp
            self.gradient_accumulation_steps = grad_acc
        elif train_batch is not None and grad_acc is not None:
            micro_batch = train_batch // dp
            micro_batch //= grad_acc
            self.train_micro_batch_size_per_gpu = micro_batch
        elif micro_batch is not None and grad_acc is not None:
            self.train_batch_size = micro_batch * grad_acc * dp
        elif train_batch is not None:
            self.gradient_accumulation_steps = 1
            self.train_micro_batch_size_per_gpu = train_batch // dp
        elif micro_batch is not None:
            self.train_batch_size = micro_batch * dp
            self.gradient_accumulation_steps = 1
        else:
            raise DeepSpeedConfigError(
                "Either train_batch_size or train_micro_batch_size_per_gpu needs to be provided")

    def _batch_assertion(self):
        train_batch = self.train_batch_size
        micro_batch = self.train_micro_batch_size_per_gpu
        grad_acc = self.gradient_accumulation_steps
        assert train_batch > 0, f"train_batch_size: {train_batch} has to be greater than 0"
        assert micro_batch > 0, f"micro_batch: {micro_batch} has to be greater than 0"
        assert grad_acc > 0, f"gradient_accumulation_steps: {grad_acc} has to be greater than 0"
        assert train_batch == micro_batch * grad_acc * self.data_parallel_size, (
            f"Check batch related parameters. train_batch_size is not equal to "
            f"micro_batch_per_gpu * gradient_acc_step * world_size "
            f"{train_batch} != {micro_batch} * {grad_acc} * {self.data_parallel_size}")

    def _do_sanity_check(self):
        self._batch_assertion()
        if self.zero_optimization_stage > ZeroStageEnum.max_stage:
            raise DeepSpeedConfigError(
                f"max zero stage is {int(ZeroStageEnum.max_stage)}, got "
                f"{self.zero_optimization_stage}")

    def print_user_config(self):
        logger.info("  json = {}".format(
            json.dumps(self._param_dict, sort_keys=True, indent=4, default=str)))

    def print(self, name: str = "DeepSpeedConfig"):
        logger.info(f"{name}:")
        for k in sorted(vars(self)):
            if k.startswith("_"):
                continue
            logger.info(f"  {k} = {getattr(self, k)}")
        self.print_user_config()
