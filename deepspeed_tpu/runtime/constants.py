"""Config keys and defaults — analog of reference ``deepspeed/runtime/constants.py``.

Key names are kept byte-identical with the reference JSON schema so existing
DeepSpeed config files parse unchanged.
"""

#############################################
# Batch size
#############################################
TRAIN_BATCH_SIZE = "train_batch_size"
TRAIN_BATCH_SIZE_DEFAULT = None
TRAIN_MICRO_BATCH_SIZE_PER_GPU = "train_micro_batch_size_per_gpu"
TRAIN_MICRO_BATCH_SIZE_PER_GPU_DEFAULT = None
GRADIENT_ACCUMULATION_STEPS = "gradient_accumulation_steps"
GRADIENT_ACCUMULATION_STEPS_DEFAULT = None

#############################################
# Optimizer / scheduler
#############################################
OPTIMIZER = "optimizer"
OPTIMIZER_TYPE_DEFAULT = None
OPTIMIZER_PARAMS = "params"
TYPE = "type"
LEGACY_FUSION = "legacy_fusion"
SCHEDULER = "scheduler"
SCHEDULER_TYPE_DEFAULT = None
SCHEDULER_PARAMS = "params"
MAX_GRAD_NORM = "max_grad_norm"

ZERO_ALLOW_UNTESTED_OPTIMIZER = "zero_allow_untested_optimizer"
ZERO_ALLOW_UNTESTED_OPTIMIZER_DEFAULT = False
ZERO_FORCE_DS_CPU_OPTIMIZER = "zero_force_ds_cpu_optimizer"
ZERO_FORCE_DS_CPU_OPTIMIZER_DEFAULT = True

#############################################
# Precision
#############################################
FP16 = "fp16"
FP16_ENABLED = "enabled"
FP16_ENABLED_DEFAULT = False
FP16_LOSS_SCALE = "loss_scale"
FP16_LOSS_SCALE_DEFAULT = 0
FP16_INITIAL_SCALE_POWER = "initial_scale_power"
FP16_INITIAL_SCALE_POWER_DEFAULT = 16
FP16_LOSS_SCALE_WINDOW = "loss_scale_window"
FP16_LOSS_SCALE_WINDOW_DEFAULT = 1000
FP16_HYSTERESIS = "hysteresis"
FP16_HYSTERESIS_DEFAULT = 2
FP16_MIN_LOSS_SCALE = "min_loss_scale"
FP16_MIN_LOSS_SCALE_DEFAULT = 1
FP16_MASTER_WEIGHTS_AND_GRADS = "fp16_master_weights_and_grads"
FP16_MASTER_WEIGHTS_AND_GRADS_DEFAULT = False
FP16_AUTO_CAST = "auto_cast"
FP16_AUTO_CAST_DEFAULT = False

BFLOAT16 = "bf16"
BFLOAT16_OLD = "bfloat16"  # legacy key accepted by the reference too
BFLOAT16_ENABLED = "enabled"
BFLOAT16_ENABLED_DEFAULT = False

AMP = "amp"
AMP_ENABLED = "enabled"
AMP_ENABLED_DEFAULT = False

GRADIENT_CLIPPING = "gradient_clipping"
GRADIENT_CLIPPING_DEFAULT = 0.0

PRESCALE_GRADIENTS = "prescale_gradients"
PRESCALE_GRADIENTS_DEFAULT = False
GRADIENT_PREDIVIDE_FACTOR = "gradient_predivide_factor"
GRADIENT_PREDIVIDE_FACTOR_DEFAULT = 1.0

#############################################
# Logging / monitoring
#############################################
STEPS_PER_PRINT = "steps_per_print"
STEPS_PER_PRINT_DEFAULT = 10
# monitor cadence decoupled from print cadence (ISSUE 3 satellite): 0 =
# legacy behaviour (monitor writes ride steps_per_print)
MONITOR_INTERVAL = "monitor_interval"
MONITOR_INTERVAL_DEFAULT = 0
# training resilience section (ISSUE 10): anomaly sentinel + rewind-and-skip
# auto-recovery + SDC audits
RESILIENCE = "resilience"
WALL_CLOCK_BREAKDOWN = "wall_clock_breakdown"
WALL_CLOCK_BREAKDOWN_DEFAULT = False
DUMP_STATE = "dump_state"
DUMP_STATE_DEFAULT = False
MEMORY_BREAKDOWN = "memory_breakdown"
MEMORY_BREAKDOWN_DEFAULT = False

#############################################
# Misc runtime
#############################################
DISABLE_ALLGATHER = "disable_allgather"
DISABLE_ALLGATHER_DEFAULT = False
COMMUNICATION_DATA_TYPE = "communication_data_type"
COMMUNICATION_DATA_TYPE_DEFAULT = None
SPARSE_GRADIENTS = "sparse_gradients"
SPARSE_GRADIENTS_DEFAULT = False
GRADIENT_NOISE_SCALE = "gradient_noise_scale"

SEED = "seed"
SEED_DEFAULT = 1234

#############################################
# Parallelism (TPU-native extensions: the reference delegates TP to a user
# mpu object and has no sequence axis; here they are config-first)
#############################################
TENSOR_PARALLEL = "tensor_parallel"
TENSOR_PARALLEL_SIZE = "tp_size"
PIPELINE = "pipeline"
PIPELINE_STAGES = "stages"
SEQUENCE_PARALLEL = "sequence_parallel"
SEQUENCE_PARALLEL_SIZE = "sp_size"
SEQUENCE_PARALLEL_MODE = "mode"  # "ring" | "ulysses"
EXPERT_PARALLEL_SIZE = "ep_size"

#############################################
# Activation checkpointing
#############################################
ACTIVATION_CHECKPOINTING = "activation_checkpointing"

#############################################
# Data efficiency / types
#############################################
DATALOADER_DROP_LAST = "dataloader_drop_last"
DATALOADER_DROP_LAST_DEFAULT = False
DATA_EFFICIENCY = "data_efficiency"
DATA_TYPES = "data_types"

#############################################
# Checkpoint
#############################################
CHECKPOINT = "checkpoint"
LOAD_UNIVERSAL_CHECKPOINT = "load_universal_checkpoint"
LOAD_UNIVERSAL_CHECKPOINT_DEFAULT = False
USE_NODE_LOCAL_STORAGE_CHECKPOINT = "use_node_local_storage"
CHECKPOINT_PARALLEL_WRITE = "parallel_write"
CHECKPOINT_TAG_VALIDATION = "checkpoint_tag_validation"
CHECKPOINT_TAG_VALIDATION_DEFAULT = "Warn"
CHECKPOINT_TAG_VALIDATION_MODES = ["Warn", "Ignore", "Fail"]

#############################################
# Elasticity / compression / monitor keys live in their sub-packages
#############################################
ELASTICITY = "elasticity"
COMPRESSION_TRAINING = "compression_training"

ROUTE_TRAIN = "train"
ROUTE_EVAL = "eval"
ROUTE_PREDICT = "predict"
