"""MoQ — Mixture of Quantization (quantize-during-training).

Reference analog: ``deepspeed/runtime/quantize.py:180 Quantizer``: anneal
weight precision from ``quantize_bits.start`` down to ``quantize_bits.target``
over ``quantize_period`` steps (period doubling per transition), optionally
modulated by per-layer Hessian eigenvalues (high-curvature layers keep
precision longer).  The quantization itself is the STE fake-quant from
``compression/quantize.py`` — XLA fuses it into the surrounding matmuls.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from deepspeed_tpu.compression.quantize import fake_quantize_grouped


class Quantizer:
    def __init__(self, q_start_bits: int = 16, q_target_bits: int = 8,
                 q_period: int = 100, q_type: str = "symmetric",
                 q_rounding: str = "nearest", q_groups: int = 1,
                 use_quantizer_kernel: bool = False,
                 eigenvalue_enabled: bool = False,
                 layer_eigenvalues: Optional[Dict[int, float]] = None):
        self.q_start_bits = q_start_bits
        self.q_target_bits = q_target_bits
        self.q_period = max(q_period, 1)
        self.symmetric = q_type == "symmetric"
        self.q_rounding = q_rounding
        if q_rounding not in ("nearest", "stochastic"):
            raise ValueError(f"unknown q_rounding '{q_rounding}'")
        self.q_groups = q_groups
        self.eigenvalue_enabled = eigenvalue_enabled
        self.layer_eigenvalues = layer_eigenvalues or {}
        self.qsteps = 0
        self._rng = jax.random.PRNGKey(0)

    # ------------------------------------------------------------- schedule
    def current_bits(self, layer_id: int = 0) -> int:
        """Bit width at the current step: halve start→target, one transition
        per (possibly eigenvalue-scaled) period (reference compute_quantization
        period doubling)."""
        period = self.q_period
        if self.eigenvalue_enabled and self.layer_eigenvalues:
            # high-curvature layers keep precision longer
            mx = max(self.layer_eigenvalues.values()) or 1.0
            scale = 1.0 + self.layer_eigenvalues.get(layer_id, mx) / mx
            period = int(period * scale)
        bits = self.q_start_bits
        step, k = self.qsteps, 0
        while bits > self.q_target_bits and step >= period * (2 ** k):
            step -= period * (2 ** k)
            bits = max(bits // 2, self.q_target_bits)
            k += 1
        return bits

    def update_step(self, step: Optional[int] = None) -> None:
        self.qsteps = step if step is not None else self.qsteps + 1

    # ----------------------------------------------------------- quantize op
    def quantize(self, params, layer_axis_key: str = "blocks"):
        """Fake-quantize weight tensors at the scheduled precision
        (reference quantize() walking the param groups). 16 bits = off."""
        if self.q_rounding == "stochastic":
            self._rng, rng = jax.random.split(self._rng)
        else:
            rng = None

        def q_leaf(x, layer_id=0):
            bits = self.current_bits(layer_id)
            if bits >= 16 or x.ndim < 2:
                return x
            return fake_quantize_grouped(x, bits=bits, groups=self.q_groups,
                                         symmetric=self.symmetric,
                                         rounding=self.q_rounding, rng=rng)

        if isinstance(params, dict) and layer_axis_key in params and \
                self.eigenvalue_enabled and self.layer_eigenvalues:
            out = dict(params)
            blocks = params[layer_axis_key]
            num_layers = jax.tree_util.tree_leaves(blocks)[0].shape[0]

            def per_layer(x):
                if x.ndim < 3:
                    return x
                return jnp.stack([q_leaf(x[i], i) for i in range(num_layers)])

            out[layer_axis_key] = jax.tree_util.tree_map(per_layer, blocks)
            for k, v in out.items():
                if k != layer_axis_key:
                    out[k] = jax.tree_util.tree_map(q_leaf, v) \
                        if isinstance(v, dict) else q_leaf(v)
            return out
        return jax.tree_util.tree_map(q_leaf, params)
