"""Optimizer-state residency on fast storage (ZeRO-Infinity).

Reference analogs: ``OptimizerSwapper`` (runtime/swap_tensor/optimizer_utils.py)
and ``PipelinedOptimizerSwapper`` (runtime/swap_tensor/
pipelined_optimizer_swapper.py).  The optimizer's per-sub-group state
(fp32 master shard + Adam moments, as a dict of numpy arrays) lives on
storage; around each sub-group's CPU optimizer step the swapper:

    swap_in(group i+1)  [async prefetch]   ← overlapped with
    step on group i                         ← compute
    swap_out(group i-1) [async writeback]  ← overlapped

The pipelined variant drives that overlap; the base variant is strictly
synchronous (reference's non-pipelined mode).
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional

import numpy as np

from deepspeed_tpu.runtime.swap_tensor.async_swapper import AsyncTensorSwapper


class OptimizerSwapper:
    def __init__(self, swap_folder: str, aio_handle=None):
        self.swapper = AsyncTensorSwapper(os.path.join(swap_folder, "optimizer"),
                                          aio_handle=aio_handle)

    def _key(self, group: int, name: str) -> str:
        return f"group{group}__{name}"

    def swap_out_group(self, group: int, state: Dict[str, np.ndarray],
                       async_op: bool = False) -> None:
        for name, arr in state.items():
            self.swapper.swap_out(self._key(group, name), np.asarray(arr),
                                  async_op=True)
        if not async_op:
            self.swapper.synchronize()

    def swap_in_group(self, group: int, names: List[str],
                      async_op: bool = False) -> Optional[Dict[str, np.ndarray]]:
        for name in names:
            self.swapper.swap_in(self._key(group, name), async_op=True)
        if async_op:
            return None
        return self.wait_group(group, names)

    def wait_group(self, group: int, names: List[str]) -> Dict[str, np.ndarray]:
        return {name: self.swapper.wait_in(self._key(group, name))
                for name in names}

    def synchronize(self) -> None:
        self.swapper.synchronize()

    def contains_group(self, group: int, name: str) -> bool:
        return self.swapper.contains(self._key(group, name))


class PipelinedOptimizerSwapper(OptimizerSwapper):
    """Overlapped read/step/write loop over sub-groups (reference
    pipeline_read/pipeline_write config knobs)."""

    def run_step(self, groups: List[int], state_names: List[str], step_fn):
        """For each group g: state = resident(g); step_fn(g, state) mutates it
        in place; writeback overlaps the next group's step.

        ``step_fn(group, state_dict) -> None``
        """
        if not groups:
            return
        # prime: synchronous read of the first group
        self.swap_in_group(groups[0], state_names, async_op=True)
        resident = self.wait_group(groups[0], state_names)
        for i, g in enumerate(groups):
            nxt = groups[i + 1] if i + 1 < len(groups) else None
            if nxt is not None:
                self.swap_in_group(nxt, state_names, async_op=True)  # prefetch
            step_fn(g, resident)
            self.swap_out_group(g, resident, async_op=True)          # writeback
            if nxt is not None:
                resident = self.wait_group(nxt, state_names)
        self.synchronize()
