from deepspeed_tpu.runtime.swap_tensor.buffer_pool import SwapBufferPool
from deepspeed_tpu.runtime.swap_tensor.async_swapper import AsyncTensorSwapper
from deepspeed_tpu.runtime.swap_tensor.partitioned_param_swapper import (
    AsyncPartitionedParameterSwapper,
    PartitionedParamStatus,
)
from deepspeed_tpu.runtime.swap_tensor.optimizer_swapper import (
    OptimizerSwapper,
    PipelinedOptimizerSwapper,
)
