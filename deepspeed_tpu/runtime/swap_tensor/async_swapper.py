"""Async tensor swapping core.

Reference analog: ``AsyncTensorSwapper`` (runtime/swap_tensor/async_swapper.py)
— stream tensors out to fast storage without blocking the training loop, and
bring them back on demand.  Tensors here are numpy host arrays (the host side
of JAX arrays); each named tensor maps to one file under the swap folder and
swaps ride the native C++ AIO engine (csrc/aio/dstpu_aio.cpp).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Dict, Optional, Tuple

import numpy as np

from deepspeed_tpu.utils.logging import logger


@dataclass
class _Inflight:
    request_id: int
    buffer: np.ndarray
    write: bool


class AsyncTensorSwapper:
    def __init__(self, swap_folder: str, aio_handle=None, num_threads: int = 8,
                 block_size: int = 1 << 20):
        os.makedirs(swap_folder, exist_ok=True)
        self.swap_folder = swap_folder
        if aio_handle is None:
            from deepspeed_tpu.ops.aio import AsyncIOHandle

            aio_handle = AsyncIOHandle(block_size=block_size,
                                       num_threads=num_threads)
        self.aio = aio_handle
        self._inflight: Dict[str, _Inflight] = {}
        self._meta: Dict[str, Tuple[Tuple[int, ...], np.dtype]] = {}
        # reference AsyncTensorSwapper accounting
        self.num_elements_swapped = 0

    def _path(self, name: str) -> str:
        return os.path.join(self.swap_folder, name.replace("/", "__") + ".swp")

    def swap_out(self, name: str, array: np.ndarray, async_op: bool = True) -> None:
        """Write ``array`` to storage; the array must stay alive until
        synchronize() when async."""
        self.synchronize(name)  # a pending op on this name must not race us
        array = np.ascontiguousarray(array)
        self._meta[name] = (array.shape, array.dtype)
        rid = self.aio.async_pwrite(array, self._path(name))
        self._inflight[name] = _Inflight(rid, array, write=True)
        self.num_elements_swapped += array.size
        if not async_op:
            self.synchronize(name)

    def swap_in(self, name: str, async_op: bool = True,
                out: Optional[np.ndarray] = None) -> Optional[np.ndarray]:
        """Read the named tensor back. With async_op, returns None and the
        result is claimed via wait_in()."""
        assert name in self._meta, f"'{name}' was never swapped out"
        self.synchronize(name)  # complete any pending write before reading
        shape, dtype = self._meta[name]
        buf = out if out is not None else np.empty(shape, dtype)
        rid = self.aio.async_pread(buf, self._path(name))
        self._inflight[name] = _Inflight(rid, buf, write=False)
        if async_op:
            return None
        return self.wait_in(name)

    def wait_in(self, name: str) -> np.ndarray:
        fl = self._inflight.pop(name)
        assert not fl.write, f"wait_in('{name}') on a swap-out request"
        self.aio.wait(fl.request_id)
        return fl.buffer

    def synchronize(self, name: Optional[str] = None) -> None:
        """Complete one named request or all inflight IO."""
        if name is not None:
            fl = self._inflight.pop(name, None)
            if fl is not None:
                self.aio.wait(fl.request_id)
            return
        for n in list(self._inflight):
            self.synchronize(n)

    def contains(self, name: str) -> bool:
        return name in self._meta

    def meta(self, name: str) -> Tuple[Tuple[int, ...], np.dtype]:
        """(shape, dtype) of a swapped-out tensor."""
        assert name in self._meta, f"'{name}' was never swapped out"
        return self._meta[name]

    def release(self, name: str) -> None:
        self.synchronize(name)
        self._meta.pop(name, None)
        try:
            os.remove(self._path(name))
        except OSError:
            pass
